"""Chain reduction (helper2 parity) vs the oracle's reduction tree."""

import numpy as np
import pytest

from spgemm_tpu.chain import chain_product
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_chain
from spgemm_tpu.utils.semantics import chain_oracle


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7])
def test_chain_vs_oracle(n):
    rng = np.random.default_rng(40 + n)
    k = 2
    mats = random_chain(n, 4, k, 0.5, rng, "full")
    got = chain_product(mats)
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_m = BlockSparseMatrix.from_dict(mats[0].rows, mats[-1].cols, k, want)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


def test_chain_result_dims():
    rng = np.random.default_rng(50)
    from spgemm_tpu.utils.gen import random_block_sparse
    mats = [random_block_sparse(2, 3, 2, 1.0, rng),
            random_block_sparse(3, 4, 2, 1.0, rng),
            random_block_sparse(4, 5, 2, 1.0, rng)]
    got = chain_product(mats)
    assert got.rows == 2 * 2 and got.cols == 5 * 2


def test_single_matrix_chain():
    rng = np.random.default_rng(51)
    mats = random_chain(1, 3, 2, 0.5, rng)
    got = chain_product(mats)
    assert got == mats[0]


def _expected(mats, k):
    want = chain_oracle([m.to_dict() for m in mats], k)
    return BlockSparseMatrix.from_dict(mats[0].rows, mats[-1].cols, k, want)


# ------------------------------------------------ helper2 pairing-tree pin


def _helper2_tree(labels):
    """The reference helper2() reduction tree over opaque labels: adjacent
    pairs left to right, odd element carried (sparse_matrix_mult.cu:
    287-327).  The host oracle for the STRUCTURE of the reduction -- the
    arithmetic is non-associative, so this exact tree is load-bearing."""
    arr = list(labels)
    while len(arr) > 1:
        nxt = [(arr[i], arr[i + 1]) for i in range(0, len(arr) - 1, 2)]
        if len(arr) % 2 == 1:
            nxt.append(arr[-1])
        arr = nxt
    return arr[0]


class _Labeled:
    """Opaque chain element: multiplication is tree construction."""

    def __init__(self, label):
        self.label = label


@pytest.mark.parametrize("n", range(2, 10))
def test_chain_pairing_tree_pinned(n):
    """Regression pin for the plan/execute refactor: chain_product's
    pairing tree (incl. the odd-carry branch) must equal helper2's for
    N=2..9, and the multiplies must issue in left-to-right order.  A
    custom multiply takes the worker-less branch by design
    (chain._make_planner plans only for spgemm_device); the plan-ahead
    path's tree is value-pinned by test_chain_values_vs_oracle_n2_to_9
    below and dispatch-pinned by tests/test_plan.py."""
    issued = []

    def structural_multiply(a, b, **_kw):
        issued.append((a.label, b.label))
        return _Labeled((a.label, b.label))

    got = chain_product([_Labeled(i) for i in range(n)],
                        multiply=structural_multiply)
    assert got.label == _helper2_tree(range(n))
    # dispatch order: left-to-right within each halving pass
    replay = []
    arr = [i for i in range(n)]
    while len(arr) > 1:
        nxt = [(arr[i], arr[i + 1]) for i in range(0, len(arr) - 1, 2)]
        replay += nxt
        if len(arr) % 2 == 1:
            nxt.append(arr[-1])
        arr = nxt
    assert issued == replay


@pytest.mark.parametrize("n", range(2, 10))
def test_chain_values_vs_oracle_n2_to_9(n, monkeypatch):
    """Value-level pin of the same trees on adversarial (fold-order-
    sensitive) values, through the real engine with the plan-ahead
    pipeline on: any silent tree change shows as a bit mismatch."""
    monkeypatch.setenv("SPGEMM_TPU_PLAN_AHEAD", "2")
    rng = np.random.default_rng(200 + n)
    k = 2
    mats = random_chain(n, 3, k, 0.6, rng, "adversarial")
    got = chain_product(mats)
    want = _expected(mats, k)
    assert np.array_equal(got.coords, want.coords)
    assert np.array_equal(got.tiles, want.tiles)


class _DyingMultiply:
    """Succeeds for `ok` calls, then raises (simulates device/tunnel death)."""

    def __init__(self, ok):
        from spgemm_tpu.ops.spgemm import spgemm
        self.ok = ok
        self.calls = 0
        self.inner = spgemm

    def __call__(self, a, b, **kw):
        self.calls += 1
        if self.calls > self.ok:
            raise RuntimeError("injected device loss")
        return self.inner(a, b, **kw)


def test_failover_to_oracle_without_checkpoint():
    """Device dies mid-pass: failover restarts the pass on the host oracle
    from the host copies taken while the device was alive."""
    rng = np.random.default_rng(90)
    k = 2
    mats = random_chain(5, 4, k, 0.5, rng, "full")
    dying = _DyingMultiply(ok=2)  # pass 1 has 2 multiplies; die in pass 2
    got = chain_product(mats, multiply=dying, failover=True)
    want = _expected(mats, k)
    assert np.array_equal(got.coords, want.coords)
    assert np.array_equal(got.tiles, want.tiles)


def test_failover_resumes_from_checkpoint(tmp_path):
    rng = np.random.default_rng(91)
    k = 2
    mats = random_chain(4, 4, k, 0.5, rng, "adversarial")
    dying = _DyingMultiply(ok=2)
    got = chain_product(mats, multiply=dying, failover=True,
                        checkpoint_dir=str(tmp_path))
    want = _expected(mats, k)
    assert np.array_equal(got.coords, want.coords)
    assert np.array_equal(got.tiles, want.tiles)


def test_no_failover_raises():
    rng = np.random.default_rng(92)
    mats = random_chain(4, 4, 2, 0.5, rng, "small")
    with pytest.raises(RuntimeError, match="injected device loss"):
        chain_product(mats, multiply=_DyingMultiply(ok=1))
