"""Smoke tests for the driver-facing scripts: bench.py must always print one
valid JSON line (the round driver records it), and benchmarks/run.py must
produce parseable rows.  Tiny configs on the CPU backend."""

import json
import os
import subprocess
import sys

from conftest import run_repo_script as _run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_prints_one_json_line():
    # pin the knob: the child inherits os.environ, and an operator's
    # exported SPGEMM_TPU_ROUND_BATCH=0 A/B session must not flip the
    # round_batch assertion below
    rc = _run(["bench.py", "--chain", "3", "--block-dim", "12",
               "--bandwidth", "1", "--k", "8", "--iters", "1",
               "--device", "cpu"], SPGEMM_TPU_ROUND_BATCH="1")
    assert rc.returncode == 0, rc.stderr[-2000:]
    lines = [ln for ln in rc.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    row = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(row)
    assert row["unit"] == "s" and row["value"] > 0
    # tiny config matches no published scale: must NOT claim a baseline
    assert row["vs_baseline"] is None
    # launch-count observability (round-batched dispatch): the counter must
    # ride along in detail so a silent de-batching regression is visible in
    # every captured bench row
    assert row["detail"]["dispatches"] > 0
    assert row["detail"]["round_batch"] == 1


def test_bench_plan_phases_and_cache_counters_in_detail():
    """Bench JSON contract growth (planner pipeline): the plan/plan_wait
    phases and the plan-cache counters must ride in detail, and the
    last-stdout-line JSON contract must hold under the legacy serial path
    (SPGEMM_TPU_PLAN_AHEAD=0) too."""
    rc = _run(["bench.py", "--chain", "3", "--block-dim", "12",
               "--bandwidth", "1", "--k", "8", "--iters", "2",
               "--device", "cpu"], SPGEMM_TPU_PLAN_AHEAD="0")
    assert rc.returncode == 0, rc.stderr[-2000:]
    last = rc.stdout.strip().splitlines()[-1]
    row = json.loads(last)  # the LAST stdout line is the metric contract
    assert {"metric", "value", "unit", "vs_baseline"} <= set(row)
    detail = row["detail"]
    assert detail["plan_ahead"] == 0
    phases = detail["phases_s"]
    # serial path: dispatch blocked for the whole (inline) plan span
    assert "plan" in phases and "plan_wait" in phases
    assert phases["plan_wait"] >= 0 and phases["plan"] >= 0
    # iters=2 re-runs the identical chain: the second iteration's plans
    # all come from the structure-keyed cache, and the best-iteration
    # counters must show it
    assert detail["plan_cache_misses"] + detail["plan_cache_hits"] > 0
    assert detail["plan_cache_hits"] > 0


def test_bench_plan_ahead_pipeline_row():
    """The default plan-ahead path emits the same contract with the
    worker-planned spans (plan accumulated off the dispatch thread)."""
    rc = _run(["bench.py", "--chain", "4", "--block-dim", "12",
               "--bandwidth", "1", "--k", "8", "--iters", "1",
               "--device", "cpu"], SPGEMM_TPU_PLAN_AHEAD="2")
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads(rc.stdout.strip().splitlines()[-1])
    detail = row["detail"]
    assert detail["plan_ahead"] == 2
    assert "plan" in detail["phases_s"] and "plan_wait" in detail["phases_s"]


def test_planner_bench_repeat_structure_contract():
    """benchmarks/planner_bench.py --repeat-structure: one JSON line with
    the plan-cache hit measurement alongside the plan_ring_wall fields."""
    rc = _run([os.path.join("benchmarks", "planner_bench.py"),
               "--keys", "2000", "--repeat-structure"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads(rc.stdout.strip().splitlines()[-1])
    assert row["metric"] == "plan_ring_wall"
    detail = row["detail"]
    assert "plan_rounds_wall_s" in detail  # the pre-existing fields stay
    assert detail["plan_cache_hit_wall_s"] > 0
    assert detail["plan_cache_miss_wall_s"] >= detail["plan_cache_hit_wall_s"]
    assert detail["plan_cache"]["hits"] >= 1


def test_planner_bench_delta_contract():
    """benchmarks/planner_bench.py --delta: the same one-JSON-line
    contract, with a detail.delta block whose recomputed-row counts scale
    with the dirty fraction (tiny CPU config; the 20k-key acceptance run
    is manual)."""
    rc = _run([os.path.join("benchmarks", "planner_bench.py"),
               "--keys", "500", "--repeats", "2", "--delta",
               "--delta-k", "4"],
              SPGEMM_TPU_DELTA="")  # the mode manages the knob itself
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads(rc.stdout.strip().splitlines()[-1])
    assert row["metric"] == "plan_ring_wall"
    d = row["detail"]["delta"]
    assert d["keys"] == 500 and d["rows"] > 0
    fr = d["fractions"]
    assert [f["dirty_frac"] for f in fr] == [0.01, 0.10, 0.50]
    for f in fr:
        assert f["delta_wall_s"] > 0 and f["full_wall_s"] > 0
        assert f["speedup"] is not None
        assert 0 < f["rows_recomputed"] <= f["total_rows"]
    # recompute volume tracks the dirty fraction (sub-linear scaling's
    # audit trail), and the small fractions genuinely recompute a subset
    assert (fr[0]["rows_recomputed"] <= fr[1]["rows_recomputed"]
            <= fr[2]["rows_recomputed"])
    assert fr[0]["rows_recomputed"] < fr[0]["total_rows"]
    assert fr[1]["rows_recomputed"] < fr[1]["total_rows"]


def test_pool_bench_contract():
    """benchmarks/pool_bench.py (tiny config): one JSON line with both
    legs' makespans, the speedup, jobs/minute, and bit-exact parity in
    BOTH legs -- the device-pool acceptance bench's wire contract."""
    rc = _run([os.path.join("benchmarks", "pool_bench.py"),
               "--small", "1", "--chain", "3", "--small-dim", "5",
               "--large-dim", "8", "--k", "4", "--slices", "2"],
              timeout=540)
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads(rc.stdout.strip().splitlines()[-1])
    assert row["metric"] == "pool_batch_makespan"
    d = row["detail"]
    assert d["parity"] is True
    assert d["makespan_1slice_s"] > 0 and d["makespan_pool_s"] > 0
    assert d["speedup_vs_1slice"] is not None
    assert d["jobs"] == 2 and d["jobs_per_min_pool"] > 0
    # per-job placement detail rides along (slice names + queue waits)
    assert {j["slice"] for j in d["per_job_pool"]} <= {"s0w1", "s1w1"}


def test_bench_single_chain_no_crash():
    rc = _run(["bench.py", "--chain", "1", "--block-dim", "8",
               "--bandwidth", "1", "--k", "8", "--iters", "1",
               "--device", "cpu"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads([ln for ln in rc.stdout.splitlines()
                      if ln.startswith("{")][0])
    assert row["vs_baseline"] is None  # a 1-chain does zero multiplies


def test_benchmark_suite_webbase_row(tmp_path):
    rc = _run([os.path.join("benchmarks", "run.py"), "--config", "webbase-1M",
               "--device", "cpu", "--virtual-devices", "2"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads(rc.stdout.strip().splitlines()[-1])
    assert row["config"] == "webbase-1M"
    assert row["value_parity"] is True


def test_bench_warm_flag():
    rc = _run(["bench.py", "--chain", "2", "--block-dim", "8",
               "--bandwidth", "1", "--k", "8", "--device", "cpu", "--warm"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads([ln for ln in rc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert row["warmed"] is True and row["compile_pass_s"] > 0


def test_bench_emits_json_and_rc0_on_internal_failure():
    """The driver contract: rc must stay 0 and a JSON line must appear even
    when the run blows up mid-way (here: an invalid round size forces an
    engine error after backend init)."""
    rc = _run(["bench.py", "--chain", "2", "--block-dim", "8",
               "--bandwidth", "1", "--k", "8", "--device", "cpu",
               "--round-size", "-3"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    lines = [ln for ln in rc.stdout.splitlines() if ln.startswith("{")]
    assert lines, rc.stdout
    row = json.loads(lines[-1])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(row)
    # the failure branch must actually have fired (else this test is vacuous)
    assert row["metric"] == "chain_multiply_wall_clock_failed", row
    assert "error" in row["detail"]


def test_bench_outer_budget_kills_and_emits_json():
    """The self-wrapping outer process: when the inner bench exceeds the
    kill budget (the mid-run device-hang mode no in-process handler can
    escape), the outer SIGKILLs it and still emits the failure JSON with
    rc=0 -- the driver contract under every observed failure mode."""
    # budget 1 s: even interpreter start + jax import exceeds it, and the
    # medium-scale default workload takes minutes on CPU -- the kill path
    # fires deterministically regardless of host speed or warm caches
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "SPGEMM_TPU_BENCH_TIMEOUT": "1",
           "PYTHONPATH": REPO + ":" + os.environ.get("PYTHONPATH", "")}
    rc = subprocess.run(
        [sys.executable, "bench.py", "--device", "cpu"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads([ln for ln in rc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert row["metric"] == "chain_multiply_wall_clock_failed"
    assert "budget" in row["detail"]["error"]


def test_bench_json_contract_survives_probe_failure():
    """Driver-contract guard: when the backend PROBE fails (here the probe
    subprocess times out instantly -- the observed dead-TPU hang mode),
    bench.py must still exit 0 and end stdout with one valid JSON line,
    honestly tagged with the fallback reason and the clamped CPU workload."""
    rc = _run(["bench.py", "--chain", "2", "--block-dim", "8",
               "--bandwidth", "1", "--k", "4", "--iters", "1"],
              SPGEMM_TPU_PROBE_TIMEOUT="0.01")
    assert rc.returncode == 0, rc.stderr[-2000:]
    last = rc.stdout.strip().splitlines()[-1]
    row = json.loads(last)  # the LAST stdout line is the metric contract
    assert {"metric", "value", "unit", "vs_baseline"} <= set(row)
    assert row["value"] > 0
    assert "probe" in row["detail"]["fallback"]["reason"]


def test_suite_skip_flag():
    """--skip yields a placeholder row, runs nothing, exits 0."""
    rc = _run([os.path.join("benchmarks", "run.py"),
               "--config", "loader-scaling", "--skip", "loader-scaling",
               "--device", "cpu"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads(rc.stdout.strip().splitlines()[-1])
    assert row["config"] == "loader-scaling" and "skipped" in row


def test_write_table_merges_extras(tmp_path, monkeypatch):
    """A best-effort row from the evidence dir replaces the --skip
    placeholder of the same config (tpu_evidence.sh's isolation contract)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_run", os.path.join(REPO, "benchmarks", "run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    stale = {"config": "webbase-1Mrow", "error": "hung on first capture"}
    extra = {"config": "webbase-1Mrow", "backend": "pallas", "platform": "tpu",
             "wall_s": 0.9, "effective_gflops": 33.0,
             "value_parity_sampled": True, "parity_tiles_checked": 64}
    # appended file across captures: the NEWEST row per config must win
    (tmp_path / "extras.jsonl").write_text(
        json.dumps(stale) + "\n" + json.dumps(extra) + "\n")
    monkeypatch.setenv("SPGEMM_TPU_EVIDENCE_DIR", str(tmp_path))

    out = tmp_path / "RESULTS.md"
    mod.write_table([{"config": "webbase-1Mrow", "skipped": "via --skip"}],
                    path=str(out))
    text = out.read_text()
    assert "33.0" in text and "bit-exact (64 tiles sampled)" in text
    assert "skipped" not in text  # the placeholder was replaced, not kept

    # a freshly MEASURED row must never be overwritten by stale extras
    fresh = {"config": "webbase-1Mrow", "backend": "pallas", "platform": "tpu",
             "wall_s": 0.5, "effective_gflops": 60.0,
             "value_parity_sampled": True, "parity_tiles_checked": 64}
    mod.write_table([fresh], path=str(out))
    text = out.read_text()
    assert "60.0" in text and "33.0" not in text


def test_evidence_steps_validated_before_probe(tmp_path):
    """tpu_evidence.sh rejects unknown/malformed step subsets with exit 4
    (NOT 2 -- the watcher retries on 2 and would loop for hours on a
    misconfiguration) before touching any backend, and never writes into
    the output dir on the rejection path."""
    out = tmp_path / "ev"
    for bad in ("ffn,ooc", "headlines", "ffn bogus"):
        rc = subprocess.run(
            ["bash", os.path.join(REPO, "benchmarks", "tpu_evidence.sh"),
             str(out)],
            env={**os.environ, "SPGEMM_TPU_EVIDENCE_STEPS": bad},
            capture_output=True, text=True, timeout=60)
        assert rc.returncode == 4, (bad, rc.returncode, rc.stdout)
        assert "unknown step" in rc.stdout
        assert not out.exists()  # validation precedes mkdir


def test_suite_rc_nonzero_on_config_error(tmp_path):
    """A crashing config yields an error row AND a nonzero exit."""
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import benchmarks.run as R\n"
        "R._pin_platform('cpu')\n"
        "def boom(): raise RuntimeError('config exploded')\n"
        "R.CONFIGS = {'boom': boom}\n"
        "sys.exit(R.main())\n" % REPO
    )
    script = tmp_path / "suite_err.py"
    script.write_text(code)
    rc = _run([str(script)])
    assert rc.returncode != 0
    row = json.loads([ln for ln in rc.stdout.splitlines()
                      if ln.startswith("{")][-1])
    assert row["config"] == "boom" and "config exploded" in row["error"]
