"""Smoke tests for the driver-facing scripts: bench.py must always print one
valid JSON line (the round driver records it), and benchmarks/run.py must
produce parseable rows.  Tiny configs on the CPU backend."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=240):
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + ":" + os.environ.get("PYTHONPATH", "")}
    return subprocess.run([sys.executable, *args], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_bench_prints_one_json_line():
    rc = _run(["bench.py", "--chain", "3", "--block-dim", "12",
               "--bandwidth", "1", "--k", "8", "--iters", "1",
               "--device", "cpu"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    lines = [ln for ln in rc.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    row = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(row)
    assert row["unit"] == "s" and row["value"] > 0
    # tiny config matches no published scale: must NOT claim a baseline
    assert row["vs_baseline"] is None


def test_bench_single_chain_no_crash():
    rc = _run(["bench.py", "--chain", "1", "--block-dim", "8",
               "--bandwidth", "1", "--k", "8", "--iters", "1",
               "--device", "cpu"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads([ln for ln in rc.stdout.splitlines()
                      if ln.startswith("{")][0])
    assert row["vs_baseline"] is None  # a 1-chain does zero multiplies


def test_benchmark_suite_webbase_row(tmp_path):
    rc = _run([os.path.join("benchmarks", "run.py"), "--config", "webbase-1M",
               "--device", "cpu", "--virtual-devices", "2"])
    assert rc.returncode == 0, rc.stderr[-2000:]
    row = json.loads(rc.stdout.strip().splitlines()[-1])
    assert row["config"] == "webbase-1M"
    assert row["value_parity"] is True
