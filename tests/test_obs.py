"""L5 observability (spgemm_tpu/obs/): flight-recorder ring bounds, span
nesting/tags, the SPGEMM_TPU_OBS_TRACE kill switch, Prometheus text-format
0.0.4 contract (escaping included), Perfetto trace_event export, and the
jax-free-import guarantee (subprocess-pinned, mirroring the linter's)."""

import json
import subprocess
import sys
import threading

import pytest

from spgemm_tpu.obs import metrics, trace
from spgemm_tpu.utils.timers import PhaseTimers

REPO = __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__)))


@pytest.fixture(autouse=True)
def clean_recorder():
    trace.RECORDER.clear()
    yield
    trace.RECORDER.clear()


# --------------------------------------------------------- ring recorder --
def test_ring_is_bounded_and_counts_drops(monkeypatch):
    """The flight recorder must never grow unbounded in a resident
    daemon: past the cap the OLDEST spans are evicted, and the eviction
    is counted (silent loss would read as 'nothing happened')."""
    monkeypatch.setenv("SPGEMM_TPU_OBS_RING_CAP", "8")
    t = PhaseTimers()
    for i in range(50):
        t.record("plan", 0.001 * (i + 1))
    st = trace.RECORDER.stats()
    assert st["spans"] == 8 and st["capacity"] == 8
    assert st["emitted"] == 50 and st["dropped"] == 42
    spans = trace.RECORDER.snapshot()
    assert len(spans) == 8
    # newest retained: the last 8 record() durations
    assert [s["dur"] for s in spans] == \
        [pytest.approx(1e6 * 0.001 * (i + 1), rel=1e-6) for i in range(42, 50)]


def test_obs_trace_zero_disables_emission(monkeypatch):
    """The overhead A/B knob: with SPGEMM_TPU_OBS_TRACE=0 no span is
    emitted, while the timers keep accumulating (metrics survive)."""
    monkeypatch.setenv("SPGEMM_TPU_OBS_TRACE", "0")
    t = PhaseTimers()
    with t.phase("plan"):
        pass
    t.record("assembly", 0.5)
    t.incr("dispatches")
    assert trace.RECORDER.stats()["spans"] == 0
    assert trace.RECORDER.stats()["enabled"] is False
    assert t.snapshot()["assembly"] == 0.5
    assert t.counter_snapshot()["dispatches"] == 1


def test_span_nesting_parent_and_tags():
    """Parenting is lexical per thread; tags active on the emitting
    thread ride on every span."""
    t = PhaseTimers()
    with trace.RECORDER.tagged(job_id="job-9", trace_id="tr-1"):
        with t.phase("plan"):
            with t.phase("symbolic_join"):
                pass
    spans = {s["name"]: s for s in trace.RECORDER.snapshot()}
    plan, join = spans["plan"], spans["symbolic_join"]
    assert join["parent"] == plan["id"]
    assert plan["parent"] is None
    for s in (plan, join):
        assert s["tags"] == {"job_id": "job-9", "trace_id": "tr-1"}
        assert s["dur"] >= 0 and s["ph"] == "X"
    # child committed first but the parent link still resolves: ids are
    # assigned at OPEN time
    assert join["id"] > plan["id"]


def test_tags_nest_and_restore():
    with trace.RECORDER.tagged(job_id="a"):
        with trace.RECORDER.tagged(trace_id="b"):
            assert trace.RECORDER.current_tags() == {"job_id": "a",
                                                     "trace_id": "b"}
        assert trace.RECORDER.current_tags() == {"job_id": "a"}
    assert trace.RECORDER.current_tags() == {}


def test_instant_markers():
    trace.RECORDER.instant("serve_degrade", job_id="job-3")
    (s,) = trace.RECORDER.snapshot()
    assert s["ph"] == "i" and s["tags"]["job_id"] == "job-3"


# ------------------------------------------------------- Perfetto export --
def test_trace_events_are_valid_perfetto_json(tmp_path):
    """The export loads as a JSON array of trace_event objects: complete
    events carry ts+dur, thread metadata names every tid, args carry the
    span tags."""
    t = PhaseTimers()
    with trace.RECORDER.tagged(job_id="job-7"):
        with t.phase("numeric_dispatch"):
            pass
    path = trace.dump_json(str(tmp_path / "flight" / "x.trace.json"))
    events = json.loads(open(path, encoding="utf-8").read())
    assert isinstance(events, list) and events
    phs = {ev["ph"] for ev in events}
    assert phs <= {"X", "M", "i"}
    complete = [ev for ev in events if ev["ph"] == "X"]
    assert complete
    for ev in complete:
        assert {"name", "cat", "ts", "dur", "pid", "tid", "args"} <= set(ev)
        assert ev["ts"] >= 0 and ev["dur"] >= 0
    assert any(ev["ph"] == "M" and ev["name"] == "thread_name"
               for ev in events)
    dispatch = next(ev for ev in complete
                    if ev["name"] == "numeric_dispatch")
    assert dispatch["args"]["job_id"] == "job-7"


# ---------------------------------------------------- prometheus contract --
def test_render_escapes_help_and_label_values():
    text = metrics.render([
        ("spgemmd_jobs", {"state": 'we"ird\\st\nate'}, 3),
    ])
    # label escaping: backslash, quote, newline (format 0.0.4)
    assert 'state="we\\"ird\\\\st\\nate"' in text
    assert text.endswith("\n")
    # HELP text never carries a raw newline
    for line in text.splitlines():
        assert not line.startswith("# HELP") or "\n" not in line[7:]


def test_render_headers_types_and_ordering():
    text = metrics.render([
        ("spgemm_phase_seconds_total", {"phase": "plan"}, 1.5),
        ("spgemm_phase_seconds_total", {"phase": "assembly"}, 0.25),
        ("spgemmd_degraded", {}, 0),
    ])
    lines = text.splitlines()
    assert "# TYPE spgemm_phase_seconds_total counter" in lines
    assert "# TYPE spgemmd_degraded gauge" in lines
    assert 'spgemm_phase_seconds_total{phase="assembly"} 0.25' in lines
    assert 'spgemm_phase_seconds_total{phase="plan"} 1.5' in lines
    assert "spgemmd_degraded 0" in lines
    # one HELP/TYPE pair per family, immediately before its samples
    assert lines.index("# TYPE spgemm_phase_seconds_total counter") \
        == lines.index("# HELP spgemm_phase_seconds_total "
                       + metrics.escape_help(
                           metrics.REGISTRY[
                               "spgemm_phase_seconds_total"].doc)) + 1


def test_render_histogram_shape():
    text = metrics.render([
        ("spgemmd_job_wall_seconds", {},
         {"buckets": {0.1: 1, 1.0: 2, 10.0: 2, 60.0: 2, 600.0: 2,
                      3600.0: 2},
          "sum": 1.25, "count": 2}),
    ])
    lines = text.splitlines()
    assert "# TYPE spgemmd_job_wall_seconds histogram" in lines
    assert 'spgemmd_job_wall_seconds_bucket{le="0.1"} 1' in lines
    assert 'spgemmd_job_wall_seconds_bucket{le="+Inf"} 2' in lines
    assert "spgemmd_job_wall_seconds_sum 1.25" in lines
    assert "spgemmd_job_wall_seconds_count 2" in lines


def test_render_rejects_undeclared_family_and_wrong_labels():
    """The runtime half of the registry contract: an ad-hoc family name
    (or a label set that does not match the declaration) cannot ship."""
    with pytest.raises(ValueError, match="undeclared metric"):
        metrics.render([("spgemm_adhoc_total", {}, 1)])
    with pytest.raises(ValueError, match="labels"):
        metrics.render([("spgemmd_degraded", {"oops": "x"}, 1)])


def test_collect_engine_round_trips_through_render():
    t_names = ("plan", "numeric_dispatch")
    from spgemm_tpu.utils.timers import ENGINE

    for name in t_names:
        ENGINE.record(name, 0.125)
    ENGINE.incr("dispatches", 2)
    text = metrics.render(metrics.collect_engine())
    for name in t_names:
        assert f'spgemm_phase_seconds_total{{phase="{name}"}}' in text
    assert 'spgemm_engine_events_total{event="dispatches"}' in text
    assert "spgemm_trace_spans_emitted_total" in text


def test_metrics_table_covers_registry():
    table = metrics.metrics_table_md()
    for name in metrics.REGISTRY:
        assert f"`{name}`" in table
    for name in list(metrics.ENGINE_PHASES) + list(metrics.ENGINE_COUNTERS):
        assert f"`{name}`" in table


# --------------------------------------------------------- jax-free pins --
def test_obs_import_and_use_is_jax_free():
    """The scrape/dump path runs on client processes and watchdog threads
    that must never hang on a backend: importing + exercising the whole
    obs surface (spans, render, trace export) pulls no jax/jaxlib."""
    code = (
        "import sys\n"
        "from spgemm_tpu.obs import metrics, trace\n"
        "from spgemm_tpu.utils.timers import ENGINE\n"
        "with ENGINE.phase('plan'):\n"
        "    ENGINE.incr('dispatches')\n"
        "metrics.render(metrics.collect_engine())\n"
        "trace.to_trace_events()\n"
        "bad = [m for m in sys.modules\n"
        "       if m == 'jax' or m.startswith(('jax.', 'jaxlib'))]\n"
        "assert not bad, f'obs pulled in jax: {bad}'\n")
    rc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stderr[-2000:]


# ------------------------------------------------------ trace stitching --
def test_merge_trace_files_round_trips_two_subprocesses(tmp_path):
    """Satellite: ring dumps from two REAL processes stitch into one
    Perfetto array -- distinct labeled process tracks, the internal
    clock anchors consumed, and --trace filtering down to one trace
    context keeps both processes' contributions."""
    code = (
        "import sys\n"
        "from spgemm_tpu.obs import trace\n"
        "from spgemm_tpu.utils.timers import PhaseTimers\n"
        "t = PhaseTimers()\n"
        "with trace.RECORDER.tagged(trace_id=sys.argv[2]):\n"
        "    with t.phase('plan'):\n"
        "        pass\n"
        "with trace.RECORDER.tagged(trace_id='f' * 32):\n"
        "    t.record('assembly', 0.25)\n"
        "trace.dump_json(sys.argv[1], process_name=sys.argv[3])\n")
    tid = "ab" * 16
    paths = []
    for i in (1, 2):
        path = str(tmp_path / f"p{i}.trace.json")
        rc = subprocess.run(
            [sys.executable, "-c", code, path, tid, f"proc{i}"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert rc.returncode == 0, rc.stderr[-2000:]
        paths.append(path)
    merged = trace.merge_trace_files(paths)
    spans = [ev for ev in merged if ev["ph"] != "M"]
    pids = {ev["pid"] for ev in spans}
    assert len(pids) == 2
    proc_names = {ev["args"]["name"] for ev in merged
                  if ev["ph"] == "M" and ev["name"] == "process_name"}
    assert proc_names == {"proc1", "proc2"}
    assert not any(ev["name"] == trace.CLOCK_ORIGIN_META for ev in merged)
    # filter to one trace context: only its spans survive, and BOTH
    # processes' tracks are retained (the end-to-end flame view)
    only = trace.merge_trace_files(paths, trace_id=tid)
    fspans = [ev for ev in only if ev["ph"] != "M"]
    assert fspans and all(ev["args"]["trace_id"] == tid for ev in fspans)
    assert {ev["pid"] for ev in fspans} == pids
    assert {ev["name"] for ev in fspans} == {"plan"}


def test_merge_remaps_colliding_pids(tmp_path):
    """Two dumps from one process (same pid) must stitch as two DISTINCT
    process tracks, not interleave into one."""
    t = PhaseTimers()
    with t.phase("plan"):
        pass
    p1 = trace.dump_json(str(tmp_path / "a.trace.json"))
    p2 = trace.dump_json(str(tmp_path / "b.trace.json"))
    merged = trace.merge_trace_files([p1, p2])
    pids = {ev["pid"] for ev in merged}
    assert len(pids) == 2


def test_merge_aligns_timelines_on_wall_anchor(tmp_path):
    """Per-process span timestamps sit on per-process monotonic origins;
    the merge shifts every file onto the earliest wall-clock anchor's
    axis so cross-process ordering is correct in the viewer."""
    def dump(path, pid, origin_us, name):
        events = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": name}},
            {"name": trace.CLOCK_ORIGIN_META, "ph": "M", "pid": pid,
             "tid": 0, "args": {"wall_origin_us": origin_us}},
            {"name": name, "cat": "spgemm", "ph": "X", "ts": 5.0,
             "dur": 1.0, "pid": pid, "tid": 1, "args": {}},
        ]
        with open(path, "w", encoding="utf-8") as f:
            json.dump(events, f)
        return str(path)
    pa = dump(tmp_path / "a.json", 1, 1000.0, "early")
    pb = dump(tmp_path / "b.json", 2, 31000.0, "late")
    merged = trace.merge_trace_files([pa, pb])
    ts = {ev["name"]: ev["ts"] for ev in merged if ev["ph"] == "X"}
    assert ts["early"] == 5.0          # the earliest anchor is the axis
    assert ts["late"] == 30005.0       # shifted by the anchor delta
    # merged spans come out time-ordered on the shared axis
    spans = [ev for ev in merged if ev["ph"] == "X"]
    assert [ev["name"] for ev in spans] == ["early", "late"]


# ------------------------------------------------------- events --follow --
def test_follow_file_streams_and_survives_rotation(tmp_path):
    """Satellite: the --follow engine polls the rotating JSONL and a
    rotation boundary neither drops nor duplicates a record (seq-deduped,
    the old file's tail is drained from <path>.1)."""
    from spgemm_tpu.obs import events as obs_events

    path = str(tmp_path / "e.jsonl")

    def write(recs, p=path):
        with open(p, "a", encoding="utf-8") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    write([{"seq": i, "kind": "a"} for i in range(1, 4)])
    gen = obs_events.follow_file(path, last_seq=0, poll_s=0.01)
    assert [next(gen)["seq"] for _ in range(3)] == [1, 2, 3]
    # two records land in the old file, THEN it rotates and a new one
    # starts fresh: the follow must yield 4, 5 (from .1's tail) then 6
    write([{"seq": 4, "kind": "a"}, {"seq": 5, "kind": "a"}])
    __import__("os").replace(path, path + ".1")
    write([{"seq": 6, "kind": "a"}])
    assert [next(gen)["seq"] for _ in range(3)] == [4, 5, 6]


def test_follow_file_survives_daemon_restart_seq_reset(tmp_path):
    """A restarted daemon appends to the SAME file but resets its seq
    counter at 1: dedup is on (ts, seq), so a seq regression with a
    newer wall timestamp is a new generation, never a duplicate to
    swallow."""
    from spgemm_tpu.obs import events as obs_events

    path = str(tmp_path / "e.jsonl")
    with open(path, "w", encoding="utf-8") as f:
        for i in range(1, 4):
            f.write(json.dumps({"seq": i, "ts": 1000.0 + i}) + "\n")
    gen = obs_events.follow_file(path, last_seq=0, poll_s=0.01)
    assert [next(gen)["seq"] for _ in range(3)] == [1, 2, 3]
    # daemon restart: seq resets to 1, wall clock moved on
    with open(path, "a", encoding="utf-8") as f:
        for i in range(1, 3):
            f.write(json.dumps({"seq": i, "ts": 2000.0 + i}) + "\n")
    got = [next(gen) for _ in range(2)]
    assert [r["seq"] for r in got] == [1, 2]
    assert all(r["ts"] > 2000.0 for r in got)


def test_follow_file_detects_rotation_by_inode(tmp_path):
    """A burst can rotate AND grow the fresh file past the old read
    offset within one poll -- rotation must be detected by inode
    change, not just file shrinkage, or both gaps' records drop."""
    from spgemm_tpu.obs import events as obs_events

    path = str(tmp_path / "e.jsonl")

    def write(recs, p=path):
        with open(p, "a", encoding="utf-8") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")

    write([{"seq": 1, "kind": "a"}])
    gen = obs_events.follow_file(path, last_seq=0, poll_s=0.01)
    assert next(gen)["seq"] == 1
    # records 2-3 land, the file rotates, and the NEW file grows PAST
    # the follower's old offset before the next poll
    write([{"seq": 2, "kind": "a"}, {"seq": 3, "kind": "a"}])
    __import__("os").replace(path, path + ".1")
    write([{"seq": 4, "kind": "a", "pad": "x" * 200},
           {"seq": 5, "kind": "a"}])
    assert [next(gen)["seq"] for _ in range(4)] == [2, 3, 4, 5]


def test_follow_file_last_seq_skips_already_printed(tmp_path):
    from spgemm_tpu.obs import events as obs_events

    path = str(tmp_path / "e.jsonl")
    with open(path, "w", encoding="utf-8") as f:
        for i in range(1, 6):
            f.write(json.dumps({"seq": i}) + "\n")
    gen = obs_events.follow_file(path, last_seq=3, poll_s=0.01)
    assert next(gen)["seq"] == 4 and next(gen)["seq"] == 5


def test_read_records_leaves_partial_tail_for_next_poll(tmp_path):
    from spgemm_tpu.obs.events import _read_records

    path = str(tmp_path / "e.jsonl")
    with open(path, "w", encoding="utf-8") as f:
        f.write(json.dumps({"seq": 1}) + "\n")
        f.write('{"seq": 2')  # torn mid-write: no newline yet
    off, recs = _read_records(path, 0)
    assert [r["seq"] for r in recs] == [1]
    with open(path, "a", encoding="utf-8") as f:
        f.write(', "kind": "x"}\n')
    off2, recs2 = _read_records(path, off)
    assert [r["seq"] for r in recs2] == [2]
    assert off2 > off


# ------------------------------------------------- attribution threading --
def test_attribution_token_carries_scope_and_tags_to_worker():
    """The worker-thread contract (chain plan-ahead, OOC staging): a
    thread that adopts attribution() lands its accumulation in the
    spawning job's scope and its spans under the job's tags."""
    t = PhaseTimers()
    with trace.RECORDER.tagged(job_id="job-42"):
        scope = t.scope()
        token = t.attribution()

        def worker():
            with t.attributed(token):
                t.record("stage_prep", 0.5)
                t.incr("dispatches", 3)

        th = threading.Thread(target=worker)
        th.start()
        th.join()
        scope.close()
    assert scope.snapshot() == {"stage_prep": 0.5}
    assert scope.counter_snapshot() == {"dispatches": 3}
    span = next(s for s in trace.RECORDER.snapshot()
                if s["name"] == "stage_prep")
    assert span["tags"]["job_id"] == "job-42"
