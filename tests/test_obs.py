"""L5 observability (spgemm_tpu/obs/): flight-recorder ring bounds, span
nesting/tags, the SPGEMM_TPU_OBS_TRACE kill switch, Prometheus text-format
0.0.4 contract (escaping included), Perfetto trace_event export, and the
jax-free-import guarantee (subprocess-pinned, mirroring the linter's)."""

import json
import subprocess
import sys
import threading

import pytest

from spgemm_tpu.obs import metrics, trace
from spgemm_tpu.utils.timers import PhaseTimers

REPO = __import__("os").path.dirname(__import__("os").path.dirname(
    __import__("os").path.abspath(__file__)))


@pytest.fixture(autouse=True)
def clean_recorder():
    trace.RECORDER.clear()
    yield
    trace.RECORDER.clear()


# --------------------------------------------------------- ring recorder --
def test_ring_is_bounded_and_counts_drops(monkeypatch):
    """The flight recorder must never grow unbounded in a resident
    daemon: past the cap the OLDEST spans are evicted, and the eviction
    is counted (silent loss would read as 'nothing happened')."""
    monkeypatch.setenv("SPGEMM_TPU_OBS_RING_CAP", "8")
    t = PhaseTimers()
    for i in range(50):
        t.record("plan", 0.001 * (i + 1))
    st = trace.RECORDER.stats()
    assert st["spans"] == 8 and st["capacity"] == 8
    assert st["emitted"] == 50 and st["dropped"] == 42
    spans = trace.RECORDER.snapshot()
    assert len(spans) == 8
    # newest retained: the last 8 record() durations
    assert [s["dur"] for s in spans] == \
        [pytest.approx(1e6 * 0.001 * (i + 1), rel=1e-6) for i in range(42, 50)]


def test_obs_trace_zero_disables_emission(monkeypatch):
    """The overhead A/B knob: with SPGEMM_TPU_OBS_TRACE=0 no span is
    emitted, while the timers keep accumulating (metrics survive)."""
    monkeypatch.setenv("SPGEMM_TPU_OBS_TRACE", "0")
    t = PhaseTimers()
    with t.phase("plan"):
        pass
    t.record("assembly", 0.5)
    t.incr("dispatches")
    assert trace.RECORDER.stats()["spans"] == 0
    assert trace.RECORDER.stats()["enabled"] is False
    assert t.snapshot()["assembly"] == 0.5
    assert t.counter_snapshot()["dispatches"] == 1


def test_span_nesting_parent_and_tags():
    """Parenting is lexical per thread; tags active on the emitting
    thread ride on every span."""
    t = PhaseTimers()
    with trace.RECORDER.tagged(job_id="job-9", trace_id="tr-1"):
        with t.phase("plan"):
            with t.phase("symbolic_join"):
                pass
    spans = {s["name"]: s for s in trace.RECORDER.snapshot()}
    plan, join = spans["plan"], spans["symbolic_join"]
    assert join["parent"] == plan["id"]
    assert plan["parent"] is None
    for s in (plan, join):
        assert s["tags"] == {"job_id": "job-9", "trace_id": "tr-1"}
        assert s["dur"] >= 0 and s["ph"] == "X"
    # child committed first but the parent link still resolves: ids are
    # assigned at OPEN time
    assert join["id"] > plan["id"]


def test_tags_nest_and_restore():
    with trace.RECORDER.tagged(job_id="a"):
        with trace.RECORDER.tagged(trace_id="b"):
            assert trace.RECORDER.current_tags() == {"job_id": "a",
                                                     "trace_id": "b"}
        assert trace.RECORDER.current_tags() == {"job_id": "a"}
    assert trace.RECORDER.current_tags() == {}


def test_instant_markers():
    trace.RECORDER.instant("serve_degrade", job_id="job-3")
    (s,) = trace.RECORDER.snapshot()
    assert s["ph"] == "i" and s["tags"]["job_id"] == "job-3"


# ------------------------------------------------------- Perfetto export --
def test_trace_events_are_valid_perfetto_json(tmp_path):
    """The export loads as a JSON array of trace_event objects: complete
    events carry ts+dur, thread metadata names every tid, args carry the
    span tags."""
    t = PhaseTimers()
    with trace.RECORDER.tagged(job_id="job-7"):
        with t.phase("numeric_dispatch"):
            pass
    path = trace.dump_json(str(tmp_path / "flight" / "x.trace.json"))
    events = json.loads(open(path, encoding="utf-8").read())
    assert isinstance(events, list) and events
    phs = {ev["ph"] for ev in events}
    assert phs <= {"X", "M", "i"}
    complete = [ev for ev in events if ev["ph"] == "X"]
    assert complete
    for ev in complete:
        assert {"name", "cat", "ts", "dur", "pid", "tid", "args"} <= set(ev)
        assert ev["ts"] >= 0 and ev["dur"] >= 0
    assert any(ev["ph"] == "M" and ev["name"] == "thread_name"
               for ev in events)
    dispatch = next(ev for ev in complete
                    if ev["name"] == "numeric_dispatch")
    assert dispatch["args"]["job_id"] == "job-7"


# ---------------------------------------------------- prometheus contract --
def test_render_escapes_help_and_label_values():
    text = metrics.render([
        ("spgemmd_jobs", {"state": 'we"ird\\st\nate'}, 3),
    ])
    # label escaping: backslash, quote, newline (format 0.0.4)
    assert 'state="we\\"ird\\\\st\\nate"' in text
    assert text.endswith("\n")
    # HELP text never carries a raw newline
    for line in text.splitlines():
        assert not line.startswith("# HELP") or "\n" not in line[7:]


def test_render_headers_types_and_ordering():
    text = metrics.render([
        ("spgemm_phase_seconds_total", {"phase": "plan"}, 1.5),
        ("spgemm_phase_seconds_total", {"phase": "assembly"}, 0.25),
        ("spgemmd_degraded", {}, 0),
    ])
    lines = text.splitlines()
    assert "# TYPE spgemm_phase_seconds_total counter" in lines
    assert "# TYPE spgemmd_degraded gauge" in lines
    assert 'spgemm_phase_seconds_total{phase="assembly"} 0.25' in lines
    assert 'spgemm_phase_seconds_total{phase="plan"} 1.5' in lines
    assert "spgemmd_degraded 0" in lines
    # one HELP/TYPE pair per family, immediately before its samples
    assert lines.index("# TYPE spgemm_phase_seconds_total counter") \
        == lines.index("# HELP spgemm_phase_seconds_total "
                       + metrics.escape_help(
                           metrics.REGISTRY[
                               "spgemm_phase_seconds_total"].doc)) + 1


def test_render_histogram_shape():
    text = metrics.render([
        ("spgemmd_job_wall_seconds", {},
         {"buckets": {0.1: 1, 1.0: 2, 10.0: 2, 60.0: 2, 600.0: 2,
                      3600.0: 2},
          "sum": 1.25, "count": 2}),
    ])
    lines = text.splitlines()
    assert "# TYPE spgemmd_job_wall_seconds histogram" in lines
    assert 'spgemmd_job_wall_seconds_bucket{le="0.1"} 1' in lines
    assert 'spgemmd_job_wall_seconds_bucket{le="+Inf"} 2' in lines
    assert "spgemmd_job_wall_seconds_sum 1.25" in lines
    assert "spgemmd_job_wall_seconds_count 2" in lines


def test_render_rejects_undeclared_family_and_wrong_labels():
    """The runtime half of the registry contract: an ad-hoc family name
    (or a label set that does not match the declaration) cannot ship."""
    with pytest.raises(ValueError, match="undeclared metric"):
        metrics.render([("spgemm_adhoc_total", {}, 1)])
    with pytest.raises(ValueError, match="labels"):
        metrics.render([("spgemmd_degraded", {"oops": "x"}, 1)])


def test_collect_engine_round_trips_through_render():
    t_names = ("plan", "numeric_dispatch")
    from spgemm_tpu.utils.timers import ENGINE

    for name in t_names:
        ENGINE.record(name, 0.125)
    ENGINE.incr("dispatches", 2)
    text = metrics.render(metrics.collect_engine())
    for name in t_names:
        assert f'spgemm_phase_seconds_total{{phase="{name}"}}' in text
    assert 'spgemm_engine_events_total{event="dispatches"}' in text
    assert "spgemm_trace_spans_emitted_total" in text


def test_metrics_table_covers_registry():
    table = metrics.metrics_table_md()
    for name in metrics.REGISTRY:
        assert f"`{name}`" in table
    for name in list(metrics.ENGINE_PHASES) + list(metrics.ENGINE_COUNTERS):
        assert f"`{name}`" in table


# --------------------------------------------------------- jax-free pins --
def test_obs_import_and_use_is_jax_free():
    """The scrape/dump path runs on client processes and watchdog threads
    that must never hang on a backend: importing + exercising the whole
    obs surface (spans, render, trace export) pulls no jax/jaxlib."""
    code = (
        "import sys\n"
        "from spgemm_tpu.obs import metrics, trace\n"
        "from spgemm_tpu.utils.timers import ENGINE\n"
        "with ENGINE.phase('plan'):\n"
        "    ENGINE.incr('dispatches')\n"
        "metrics.render(metrics.collect_engine())\n"
        "trace.to_trace_events()\n"
        "bad = [m for m in sys.modules\n"
        "       if m == 'jax' or m.startswith(('jax.', 'jaxlib'))]\n"
        "assert not bad, f'obs pulled in jax: {bad}'\n")
    rc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stderr[-2000:]


# ------------------------------------------------- attribution threading --
def test_attribution_token_carries_scope_and_tags_to_worker():
    """The worker-thread contract (chain plan-ahead, OOC staging): a
    thread that adopts attribution() lands its accumulation in the
    spawning job's scope and its spans under the job's tags."""
    t = PhaseTimers()
    with trace.RECORDER.tagged(job_id="job-42"):
        scope = t.scope()
        token = t.attribution()

        def worker():
            with t.attributed(token):
                t.record("stage_prep", 0.5)
                t.incr("dispatches", 3)

        th = threading.Thread(target=worker)
        th.start()
        th.join()
        scope.close()
    assert scope.snapshot() == {"stage_prep": 0.5}
    assert scope.counter_snapshot() == {"dispatches": 3}
    span = next(s for s in trace.RECORDER.snapshot()
                if s["name"] == "stage_prep")
    assert span["tags"]["job_id"] == "job-42"
