"""Estimation-based planning (PR 8 tentpole): the sampled structure
estimator (ops/estimate), the deferred-exact plan route
(SpgemmPlan.ensure_exact), and the skew-aware ring mass balancing.

The standing contracts:
  * estimator on/off is a bit-identical whole-engine A/B on EVERY
    structure (estimation steers budgets and routing, never fold order);
  * confidence below SPGEMM_TPU_EST_CONFIDENCE always takes the exact-join
    fallback inline -- a deferred plan only ever exists behind a
    confident estimate;
  * an estimated plan-cache entry is promoted IN PLACE when the exact
    join lands, so later hits serve the exact plan;
  * the estimator is deterministic (no RNG -- same structure, same
    estimate) and host-pure (safe on plan-ahead worker threads).
"""

import numpy as np
import pytest

from spgemm_tpu.chain import chain_product
from spgemm_tpu.ops import estimate, plancache
from spgemm_tpu.ops.spgemm import execute, plan, spgemm
from spgemm_tpu.ops.symbolic import JoinResult, symbolic_join
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import (powerlaw_block_sparse, random_block_sparse,
                                  random_chain, random_values)
from spgemm_tpu.utils.semantics import chain_oracle, spgemm_oracle
from spgemm_tpu.utils.timers import ENGINE


def _oracle(a, b):
    return BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))


@pytest.fixture(autouse=True)
def _fresh_state():
    plancache.clear()
    estimate.clear()
    yield
    plancache.clear()
    estimate.clear()


# ------------------------------------------------- structure constructors


def _adversarial_skew():
    """Power-law row degrees (webbase-like) with wrap-corner values: the
    structure the confidence gate exists for."""
    rng = np.random.default_rng(81)
    a = powerlaw_block_sparse(32, 2, 3.0, rng, "adversarial")
    b = powerlaw_block_sparse(32, 2, 3.0, rng, "adversarial")
    return a, b


def _empty_operand():
    rng = np.random.default_rng(82)
    a = random_block_sparse(16, 16, 2, 0.4, rng, "adversarial")
    b = BlockSparseMatrix(rows=a.cols, cols=a.cols, k=2,
                          coords=np.zeros((0, 2), np.int64),
                          tiles=np.zeros((0, 2, 2), np.uint64))
    return a, b


def _single_key():
    coords = np.array([[0, 0]], np.int64)
    rng = np.random.default_rng(83)
    a = BlockSparseMatrix(rows=2, cols=2, k=2, coords=coords,
                          tiles=random_values((1, 2, 2), rng, "adversarial"))
    b = BlockSparseMatrix(rows=2, cols=2, k=2, coords=coords,
                          tiles=random_values((1, 2, 2), rng, "adversarial"))
    return a, b


def _uniform():
    """Near-constant row mass: the estimator's high-confidence regime."""
    rng = np.random.default_rng(84)
    a = random_block_sparse(32, 32, 2, 0.3, rng, "adversarial")
    b = random_block_sparse(32, 32, 2, 0.3, rng, "adversarial")
    return a, b


# ---------------------------------------------- (a) bit-identical on/off


@pytest.mark.parametrize("mk", [_adversarial_skew, _empty_operand,
                                _single_key, _uniform])
def test_estimator_on_off_bytes_identical(mk, monkeypatch):
    """The tentpole A/B: SPGEMM_TPU_PLAN_ESTIMATE=1 vs 0 on adversarial
    skew / empty-operand / single-key / uniform structures -- output
    BYTES identical, and both match the oracle."""
    a, b = mk()
    monkeypatch.setenv("SPGEMM_TPU_EST_SAMPLE_ROWS", "4")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "1")
    on = spgemm(a, b)
    plancache.clear()
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "0")
    off = spgemm(a, b)
    assert np.array_equal(on.coords, off.coords)
    assert on.tiles.tobytes() == off.tiles.tobytes()
    assert on == off == _oracle(a, b)


def test_estimator_chain_plan_ahead_bit_identical(monkeypatch):
    """The serving shape: a chain under the plan-ahead worker (which runs
    ensure_exact off the critical path) -- estimator on/off bit-identical
    and oracle-exact."""
    rng = np.random.default_rng(85)
    mats = random_chain(4, 18, 2, 0.4, rng, "adversarial")
    monkeypatch.setenv("SPGEMM_TPU_EST_SAMPLE_ROWS", "4")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_AHEAD", "2")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "1")
    on = chain_product(mats)
    plancache.clear()
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "0")
    off = chain_product(mats)
    want = chain_oracle([m.to_dict() for m in mats], 2)
    want_m = BlockSparseMatrix.from_dict(mats[0].rows, mats[-1].cols, 2, want)
    assert on.tiles.tobytes() == off.tiles.tobytes()
    assert on == off == want_m


# ------------------------------------------- (b) confidence gate fallback


def test_low_confidence_always_takes_exact_fallback(monkeypatch):
    """A threshold above any reachable confidence forces the inline
    exact-join fallback: the plan is never deferred, the route says
    'exact', and the fallback counters fire (never the hit counters)."""
    a, b = _uniform()
    monkeypatch.setenv("SPGEMM_TPU_EST_SAMPLE_ROWS", "4")
    monkeypatch.setenv("SPGEMM_TPU_EST_CONFIDENCE", "1.01")
    ENGINE.reset()
    p = plan(a, b, backend="xla", platform="cpu")
    assert p.plan_route == "exact" and not p.is_deferred
    assert p.join is not None and p.rounds is not None
    st = estimate.stats()
    assert st["fallbacks"] >= 1 and st["hits"] == 0
    counters = ENGINE.counter_snapshot()
    assert counters.get("est_fallbacks", 0) >= 1
    assert counters.get("est_hits", 0) == 0
    # the fallback is visible as a phase, and the result is still exact
    assert "join_fallback" in ENGINE.snapshot()
    assert execute(p, a, b).to_host() == _oracle(a, b)


def test_skewed_sample_confidence_below_uniform():
    """The gate's discriminator: a power-law structure earns strictly
    lower confidence than a near-uniform one at the same sample budget."""
    a_u, b_u = _uniform()
    a_s, _ = _adversarial_skew()
    est_u = estimate.maybe_estimate(a_u.coords, b_u.coords, sample_rows=8)
    est_s = estimate.maybe_estimate(a_s.coords, b_u.coords, sample_rows=8)
    assert est_u is not None and est_s is not None
    assert est_s.confidence < est_u.confidence
    assert est_s.skew > est_u.skew


# ------------------------------------- (c) estimated plans promote in place


def test_estimated_plan_promotes_in_cache(monkeypatch):
    """An estimated (deferred) plan caches under the structure
    fingerprint; forcing the exact join promotes the SAME object, so the
    next cache hit serves the exact plan with no second planner run."""
    a, b = _uniform()
    monkeypatch.setenv("SPGEMM_TPU_EST_SAMPLE_ROWS", "4")
    monkeypatch.setenv("SPGEMM_TPU_EST_CONFIDENCE", "0")
    ENGINE.reset()
    p1 = plan(a, b, backend="xla", platform="cpu")
    assert p1.plan_route == "estimated" and p1.is_deferred
    assert p1.rounds is None and p1.join is None
    assert p1.estimate is not None and p1.estimate.confidence >= 0
    assert ENGINE.counter_snapshot().get("est_hits", 0) == 1
    # executing forces ensure_exact: the cached entry is promoted in place
    got = execute(p1, a, b).to_host()
    assert not p1.is_deferred and p1.join is not None
    assert got == _oracle(a, b)
    p2 = plan(a, b, backend="xla", platform="cpu")
    assert p2 is p1 and not p2.is_deferred  # the promoted exact plan
    assert estimate.stats()["hits"] == 1    # no second estimator run
    # re-forcing is an idempotent no-op
    assert p2.ensure_exact() is p2


def test_deferred_plan_rounds_match_inline(monkeypatch):
    """ensure_exact() lands EXACTLY the rounds the inline path builds:
    same key partitions, same padded index arrays, byte for byte."""
    a, b = _uniform()
    monkeypatch.setenv("SPGEMM_TPU_EST_SAMPLE_ROWS", "4")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "1")
    deferred = plan(a, b, backend="xla", platform="cpu").ensure_exact()
    plancache.clear()
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "0")
    inline = plan(a, b, backend="xla", platform="cpu")
    assert np.array_equal(deferred.join.keys, inline.join.keys)
    assert len(deferred.rounds) == len(inline.rounds)
    for rd, ri in zip(deferred.rounds, inline.rounds):
        assert np.array_equal(rd.key_index, ri.key_index)
        assert rd.pa.tobytes() == ri.pa.tobytes()
        assert rd.pb.tobytes() == ri.pb.tobytes()


# --------------------------------------------------- estimator mechanics


def test_estimator_deterministic_and_scaled_sanely():
    """No RNG: identical estimates on repeated calls; scaled key/pair
    predictions land within a small factor of the exact join on a
    near-uniform structure."""
    a, b = _uniform()
    e1 = estimate.maybe_estimate(a.coords, b.coords, sample_rows=8)
    e2 = estimate.maybe_estimate(a.coords, b.coords, sample_rows=8)
    assert e1 is not e2
    assert e1.est_keys == e2.est_keys and e1.est_pairs == e2.est_pairs
    assert e1.confidence == e2.confidence
    join = symbolic_join(a.coords, b.coords)
    pairs = int(join.pair_ptr[-1])
    assert 0.5 * join.num_keys <= e1.est_keys <= 2.0 * join.num_keys
    assert 0.5 * pairs <= e1.est_pairs <= 2.0 * pairs


def test_estimator_skips_small_and_empty_populations():
    """Populations no bigger than the sample budget (and empty operands)
    return None -- the exact join is the right tool there."""
    a, b = _uniform()
    n_rows = len(np.unique(a.coords[:, 0]))
    assert estimate.maybe_estimate(a.coords, b.coords,
                                   sample_rows=n_rows) is None
    empty = np.zeros((0, 2), np.int64)
    assert estimate.maybe_estimate(empty, b.coords, sample_rows=4) is None
    assert estimate.maybe_estimate(a.coords, empty, sample_rows=4) is None


def test_fanouts_memoized_on_join_result():
    """The plan_rounds micro-fix: JoinResult.fanouts is computed once and
    reused (same array object on every access)."""
    a, b = _uniform()
    join = symbolic_join(a.coords, b.coords)
    assert join.fanouts is join.fanouts
    assert np.array_equal(join.fanouts, np.diff(join.pair_ptr))


# ------------------------------------------------ ring mass balancing


def _skewed_join(n_keys=64, deep=40):
    """A join whose first key carries `deep` pairs and the rest one each
    -- the equal-count split's worst case."""
    fan = np.ones(n_keys, np.int64)
    fan[0] = deep
    pair_ptr = np.concatenate(([0], np.cumsum(fan)))
    total = int(pair_ptr[-1])
    side = int(np.ceil(np.sqrt(n_keys)))
    keys = np.stack(np.divmod(np.arange(n_keys, dtype=np.int64), side),
                    axis=1)
    rng = np.random.default_rng(9)
    pair = rng.integers(0, 64, size=total).astype(np.int32)
    return JoinResult(keys=keys, pair_ptr=pair_ptr, pair_a=pair,
                      pair_b=pair.copy())


def test_plan_ring_mass_balanced_bounds():
    """Mass balancing assigns key slabs by cumulative pair mass: the
    per-device mass spread tightens vs the equal-key-count split, and the
    chunks still form a contiguous partition of the key space."""
    from spgemm_tpu.parallel.ring import plan_ring

    join = _skewed_join()
    n_dev = 4

    def dev_mass(chunks):
        fan = join.fanouts
        return [int(fan[c].sum()) for c in chunks]

    legacy, *_ = plan_ring(join, 64, n_dev, mass_balance=False)
    balanced, *_ = plan_ring(join, 64, n_dev, mass_balance=True)
    cat = np.concatenate([c for c in balanced])
    assert np.array_equal(cat, np.arange(join.num_keys))  # still a partition
    assert max(dev_mass(balanced)) < max(dev_mass(legacy))


def test_ring_schedule_memo_distinguishes_mass_balance(monkeypatch):
    """Review regression: the plan's memoized ring schedule keys on the
    resolved mass-balance flag -- an in-process knob A/B must never be
    served the other leg's schedule."""
    a, b = _uniform()
    p = plan(a, b, backend="xla", platform="cpu")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "1")
    s_on = p.ring_schedule(b.nnzb, 4)
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "0")
    s_off = p.ring_schedule(b.nnzb, 4)
    assert s_on is not s_off
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "1")
    assert p.ring_schedule(b.nnzb, 4) is s_on  # still memoized per leg


def test_ring_mass_balance_result_unchanged(monkeypatch):
    """The balance knob is pure load placement: ring results are
    identical (and oracle-exact) with it on and off."""
    from spgemm_tpu.parallel.ring import spgemm_ring

    rng = np.random.default_rng(86)
    a = powerlaw_block_sparse(24, 2, 3.0, rng, "small")
    b = powerlaw_block_sparse(24, 2, 3.0, rng, "small")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "1")
    on = spgemm_ring(a, b)
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "0")
    off = spgemm_ring(a, b)
    assert on.tiles.tobytes() == off.tiles.tobytes()
    assert on == off == _oracle(a, b)


# --------------------------------------- accumulator-route advisory (PR 17)


def test_predicted_route_reads_class_hist():
    """predicted_route: None estimate -> None; any sampled shape class at
    or past DENSE_MIN_CLASS -> 'dense'; else 'ladder'.  Pure histogram
    read -- no backend, no join."""
    from spgemm_tpu.ops.symbolic import DENSE_MIN_CLASS

    assert estimate.predicted_route(None) is None

    def _est(hist):
        return estimate.StructureEstimate(
            total_rows=100, sampled_rows=10, scale=10.0, est_keys=50.0,
            est_pairs=5000.0, est_max_fanout=8, class_hist=hist,
            confidence=1.0)

    assert estimate.predicted_route(_est({4: 40.0, 8: 6.0})) == "ladder"
    assert estimate.predicted_route(_est({})) == "ladder"
    assert estimate.predicted_route(
        _est({4: 40.0, DENSE_MIN_CLASS: 1.0})) == "dense"


def test_estimator_route_misprediction_is_telemetry_only(monkeypatch):
    """An estimator-routed plan whose evenly-spaced row sample misses the
    one hub row predicts 'ladder'; the real fanouts attach the dense twin
    anyway (the re-proof at plan_rounds runs off the exact join, never the
    prediction), the result stays byte-exact, and the drift lands ONLY as
    an accum_route_mismatch event."""
    from spgemm_tpu.obs import events as obs_events

    # 64 A tile-rows; row 5 is a 300-wide hub (output class 384, past
    # DENSE_MIN_CLASS), everything else fanout 4.  A 4-row evenly spaced
    # sample lands on rows {0, 21, 42, 63} (np.linspace over the sorted
    # row set) -- never the hub -- and the sampled rows' equal pair mass
    # keeps confidence at 1, so the estimate steers the plan.
    rng = np.random.default_rng(91)
    coords, base = [], 0
    for r in range(64):
        f = 300 if r == 5 else 4
        coords += [(r, base + j) for j in range(f)]
        base += f
    k = 2
    a_coords = np.array(coords, np.int64)
    b_coords = np.array([(m, 0) for m in range(base)], np.int64)
    a = BlockSparseMatrix(
        rows=64, cols=base, k=k, coords=a_coords,
        tiles=rng.integers(0, 1 << 64, size=(len(a_coords), k, k),
                           dtype=np.uint64))
    b = BlockSparseMatrix(
        rows=base, cols=1, k=k, coords=b_coords,
        tiles=rng.integers(0, 1 << 64, size=(len(b_coords), k, k),
                           dtype=np.uint64))
    monkeypatch.setenv("SPGEMM_TPU_ACCUM_ROUTE", "auto")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "1")
    monkeypatch.setenv("SPGEMM_TPU_EST_SAMPLE_ROWS", "4")
    obs_events.LOG.clear()
    p = plan(a, b)
    assert p.estimate is not None
    assert estimate.predicted_route(p.estimate) == "ladder"  # the miss
    rounds = p.ensure_exact().rounds
    assert any(r.route == "dense" or r.dense_alt is not None
               for r in rounds)  # the re-proof caught the hub
    drift = [e for e in obs_events.LOG.tail(200)
             if e["kind"] == "accum_route_mismatch"]
    assert drift and drift[-1]["predicted"] == "ladder" \
        and drift[-1]["real"] == "dense"
    est_leg = spgemm(a, b)
    plancache.clear()
    monkeypatch.setenv("SPGEMM_TPU_PLAN_ESTIMATE", "0")
    exact_leg = spgemm(a, b)
    assert est_leg.tiles.tobytes() == exact_leg.tiles.tobytes()
    assert est_leg == exact_leg == _oracle(a, b)


def test_dense_gate_cache_hit_skips_measurement(monkeypatch, tmp_path):
    """A persisted {ladder_s, dense_s} crossover entry routes the auto
    dense gate by dict lookup alone -- the kernel callables are never
    touched -- and the verdict follows the persisted ranking; the proof
    policy stays structural (DENSE_RATIO_GATE on the padded ratio)."""
    import json

    from spgemm_tpu.ops import crossover

    monkeypatch.setenv("SPGEMM_TPU_CROSSOVER_CACHE", str(tmp_path))
    key = "dense-v1:cpu:TestDev:k4:K256:P384"
    shape = dict(key=key, k=4, K=256, P=384, stream_len=2048)

    def _boom(*_a):
        raise AssertionError("kernel measurement ran on a cache hit")

    (tmp_path / "hybrid_crossover.json").write_text(
        json.dumps({key: {"ladder_s": 1.0, "dense_s": 0.1}}))
    crossover._CACHE.clear()  # drop the path-keyed memo: re-read disk
    assert crossover.dense_wins(_boom, _boom, policy="auto", **shape) is True

    (tmp_path / "hybrid_crossover.json").write_text(
        json.dumps({key: {"ladder_s": 0.1, "dense_s": 1.0}}))
    crossover._CACHE.clear()
    assert crossover.dense_wins(_boom, _boom, policy="auto", **shape) is False

    assert crossover.dense_wins(_boom, _boom, policy="proof",
                                padded_ratio=1.28, **shape) is True
    assert crossover.dense_wins(_boom, _boom, policy="proof",
                                padded_ratio=1.1, **shape) is False
