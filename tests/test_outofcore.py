"""Out-of-core SpGEMM: per-round host staging, bounded device residency.

The capability the reference gets from its host-staging design
(sparse_matrix_mult.cu:167-257: matrices in host RAM, the GPU holds one
<= 500-key round at a time): multiplies need not fit in device memory.
spgemm_outofcore must be bit-identical to the resident pipeline while only
ever uploading per-round sub-slabs.
"""

import numpy as np
import pytest

from spgemm_tpu.ops.spgemm import spgemm, spgemm_outofcore
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import banded_block_sparse, random_block_sparse
from spgemm_tpu.utils.semantics import spgemm_oracle


def _oracle(a, b):
    return BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))


_SEEDS = {("full", "xla"): 101, ("full", "pallas"): 102,
          ("adversarial", "xla"): 103, ("adversarial", "pallas"): 104}


@pytest.mark.parametrize("dist", ["full", "adversarial"])
@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_outofcore_matches_oracle(dist, backend):
    rng = np.random.default_rng(_SEEDS[dist, backend])
    a = random_block_sparse(8, 8, 4, 0.4, rng, dist)
    b = random_block_sparse(8, 8, 4, 0.4, rng, dist)
    got = spgemm_outofcore(a, b, backend=backend)
    assert got == _oracle(a, b)


def test_outofcore_matches_resident_banded():
    """Banded structure with real tile re-use inside rounds."""
    rng = np.random.default_rng(7)
    a = banded_block_sparse(24, 4, 3, rng, "full")
    b = banded_block_sparse(24, 4, 3, rng, "full")
    got = spgemm_outofcore(a, b)
    assert got == spgemm(a, b)


@pytest.mark.parametrize("depth", ["1", "4"])
def test_outofcore_depth_knob_bit_identical(depth, monkeypatch):
    """SPGEMM_TPU_OOC_DEPTH (1 = land-every-round minimal HBM, deeper =
    more landing/compute overlap) must not change a single bit; tiny
    round_size forces many rounds through the pipeline so the landing
    cadence genuinely differs between depths."""
    monkeypatch.setenv("SPGEMM_TPU_OOC_DEPTH", depth)
    rng = np.random.default_rng(13)
    a = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    b = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    got = spgemm_outofcore(a, b, round_size=3)
    assert got == _oracle(a, b)


def test_outofcore_tiny_rounds_force_multi_round_pipeline():
    """round_size=2 forces many rounds through the depth-2 pipeline and
    heavy sentinel padding; results must stay bit-identical."""
    rng = np.random.default_rng(11)
    a = random_block_sparse(10, 10, 2, 0.5, rng, "adversarial")
    b = random_block_sparse(10, 10, 2, 0.5, rng, "adversarial")
    got = spgemm_outofcore(a, b, round_size=2)
    assert got == _oracle(a, b)


def test_outofcore_mxu_backend_bounded_values():
    """MXU field mode is reference-bit-exact for bounded values; the
    out-of-core wrapper must compute the bounds itself (host matrices
    don't carry val_bound)."""
    rng = np.random.default_rng(13)
    a = random_block_sparse(6, 6, 4, 0.5, rng, "small")
    b = random_block_sparse(6, 6, 4, 0.5, rng, "small")
    got = spgemm_outofcore(a, b, backend="mxu")
    assert got == _oracle(a, b)


def test_outofcore_empty_result():
    a = BlockSparseMatrix(rows=8, cols=8, k=2,
                          coords=np.array([[0, 0]]),
                          tiles=np.ones((1, 2, 2), np.uint64))
    b = BlockSparseMatrix(rows=8, cols=8, k=2,
                          coords=np.array([[1, 1]]),
                          tiles=np.ones((1, 2, 2), np.uint64))
    got = spgemm_outofcore(a, b)  # A's col 0 never meets B's row 1
    assert got.nnzb == 0 and got.rows == 8 and got.cols == 8


@pytest.mark.parametrize("dist", ["small", "full"])
def test_outofcore_hybrid_dispatch(dist, caplog):
    """Hybrid out-of-core: small values prove every round onto the MXU
    path, full-range values fail the proof and run the exact kernel --
    both must match the oracle bit-for-bit, and the structured log must
    show the split actually happened (a silent degrade to exact-only
    dispatch would still pass a parity-only check)."""
    import logging
    import re

    rng = np.random.default_rng(17 + len(dist))
    a = random_block_sparse(6, 6, 4, 0.5, rng, dist)
    b = random_block_sparse(6, 6, 4, 0.5, rng, dist)
    with caplog.at_level(logging.INFO, logger="spgemm_tpu.spgemm"):
        got = spgemm_outofcore(a, b, backend="hybrid")
    assert got == _oracle(a, b)
    m = re.search(r"hybrid mxu=(\d+)/(\d+)", caplog.text)
    assert m, f"no hybrid dispatch tag in log: {caplog.text!r}"
    mxu, total = int(m.group(1)), int(m.group(2))
    if dist == "small":      # bounds < 2^16: every round proves onto the MXU
        assert mxu == total > 0
    else:                    # full-range u64: no round can prove exact
        assert mxu == 0 and total > 0


def test_outofcore_uploads_are_subslab_sized(monkeypatch):
    """The defining property: no upload may be as large as a whole operand
    slab.  Intercept the numeric round fn and check every slab argument it
    receives is strictly smaller than the operand it came from."""
    import spgemm_tpu.ops.spgemm as mod

    rng = np.random.default_rng(19)
    # block-diagonal-ish: each round references only a slice of the slabs
    a = banded_block_sparse(64, 2, 1, rng, "full")
    b = banded_block_sparse(64, 2, 1, rng, "full")

    seen = []
    real = mod._numeric_round

    def spy(ah, al, bh, bl, pa, pb):
        seen.append((ah.shape[0], bh.shape[0]))
        return real(ah, al, bh, bl, pa, pb)

    monkeypatch.setattr(mod, "_numeric_round", spy)
    got = spgemm_outofcore(a, b, backend="xla", round_size=16)
    # compare against the host oracle -- the resident spgemm would also run
    # through the spy and legitimately pass whole slabs
    assert got == _oracle(a, b)
    assert seen, "spy never saw a numeric round"
    max_a = max(s[0] for s in seen)
    max_b = max(s[1] for s in seen)
    assert max_a < a.nnzb and max_b < b.nnzb, (
        f"sub-slabs ({max_a}, {max_b}) not smaller than operands "
        f"({a.nnzb}, {b.nnzb})")
