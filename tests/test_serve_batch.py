"""Cross-job batched dispatch (serve/daemon._drain_batch_mates +
ops/spgemm.execute_batched): same-structure queued jobs fused into one
mega-launch per slice, bit-exact by construction -- tier-1 on the 8-vdev
CPU backend.

Covers the ISSUE-16 contract: batched results byte-identical to solo
runs, mixed-fingerprint queues never co-batch, the admission window
bounds added latency, per-job journal/SLO/trace records stay individual,
and DRR tenant fairness decides batch membership BEFORE formation (a
chatty tenant cannot fill a batch while another tenant waits).
"""

import threading
import time

import numpy as np
import pytest

from spgemm_tpu.ops import plancache
from spgemm_tpu.serve import client, placement
from spgemm_tpu.serve.daemon import Daemon, journal_parse_line
from spgemm_tpu.utils import io_text
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_chain
from spgemm_tpu.utils.semantics import chain_oracle
from spgemm_tpu.utils.timers import ENGINE


def _chain_folder(tmp_path, n=3, k=2, seed=7, name="chain_in"):
    """A reference-format input dir + the oracle's output bytes."""
    mats = random_chain(n, 4, k, 0.5, np.random.default_rng(seed), "full")
    folder = str(tmp_path / name)
    io_text.write_chain_dir(folder, mats, k)
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_bytes = io_text.format_matrix(BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k, want).prune_zeros())
    return folder, want_bytes


def _prime(folder, fingerprint="fp-test"):
    """Record the folder's structure in the plan-cache structure book --
    the served-before steady state where admission stamps the group key
    (a first contact always runs solo to record it)."""
    sig = placement.signature(folder)
    assert sig is not None
    plancache.note_chain_structure(sig, fingerprint)


@pytest.fixture(autouse=True)
def _fresh_structure_book():
    """The structure book is process-global (ops/plancache): without a
    per-test clear, one test's recorded fingerprints would hand a later
    test's admission a group key it never primed."""
    plancache.clear()
    yield
    plancache.clear()


@pytest.fixture
def batch_env(monkeypatch):
    """Arm batching: window open, K roomy, delta OFF (delta-eligible
    submits run solo by design, so the retention engine must be off for
    co-batching to form at all)."""
    monkeypatch.setenv("SPGEMM_TPU_SERVE_BATCH_WINDOW_S", "0.5")
    monkeypatch.setenv("SPGEMM_TPU_SERVE_BATCH_K", "8")
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "0")
    yield monkeypatch


@pytest.fixture
def make_daemon(tmp_path):
    """Daemon factory bound to a per-test socket; stops them on teardown."""
    daemons = []

    def _make(idx=0, **kw):
        d = Daemon(str(tmp_path / f"d{idx}.sock"), **kw)
        d.start()
        daemons.append(d)
        return d

    yield _make
    for d in daemons:
        d.stop()


def _submit_wait(d, folder, out_paths, tenant=None, timeout=120.0):
    """Submit one job per output path back-to-back, wait for all."""
    ids = [client.submit(folder, d.socket_path,
                         {"output": o}, tenant=tenant)["id"]
           for o in out_paths]
    return [client.wait(j, d.socket_path, timeout=timeout)["job"]
            for j in ids]


# ----------------------------------------------------- bit-exactness --
def test_batched_results_byte_identical_to_solo(tmp_path, batch_env,
                                                make_daemon):
    """The tentpole parity proof: co-batched jobs produce outputs
    byte-identical to the same submits through an unbatched daemon AND
    to the host oracle -- stacking along the round axis never changes
    any output row's fold order."""
    folder, want = _chain_folder(tmp_path)

    # solo leg: window 0 = the pre-batch daemon, the whole-feature A/B
    batch_env.setenv("SPGEMM_TPU_SERVE_BATCH_WINDOW_S", "0")
    d0 = make_daemon(0, journal=False)
    solo_outs = [str(tmp_path / f"solo{i}") for i in range(3)]
    for j in _submit_wait(d0, folder, solo_outs):
        assert j["state"] == "done", j["error"]
        assert j["batch"] is None
    d0.stop()

    # batched leg: window armed, structure primed (served-before state)
    batch_env.setenv("SPGEMM_TPU_SERVE_BATCH_WINDOW_S", "0.5")
    _prime(folder)
    before = ENGINE.counter_snapshot().get("serve_batches", 0)
    d1 = make_daemon(1, journal=False)
    batch_outs = [str(tmp_path / f"batch{i}") for i in range(3)]
    jobs = _submit_wait(d1, folder, batch_outs)
    for j in jobs:
        assert j["state"] == "done", j["error"]
    after = ENGINE.counter_snapshot().get("serve_batches", 0)
    assert after > before, "no fused batch formed"
    # at least one pair co-batched (back-to-back submits inside the
    # window); every co-batched job carries the shared batch id
    batched = [j for j in jobs if j["batch"] is not None]
    assert len(batched) >= 2
    assert len({j["batch"] for j in batched}) == 1

    for o in solo_outs + batch_outs:
        with open(o, "rb") as f:
            assert f.read() == want


# ------------------------------------------------- batch formation --
def test_mixed_fingerprints_never_cobatch(tmp_path, batch_env, make_daemon):
    """Only same-structure jobs fuse: a queue interleaving two
    fingerprints batches each group with its own kind, never across."""
    folder_a, _ = _chain_folder(tmp_path, seed=7, name="a")
    folder_b, _ = _chain_folder(tmp_path, seed=8, name="b")
    blocker, _ = _chain_folder(tmp_path, seed=9, name="blocker")
    _prime(folder_a, "fp-a")
    _prime(folder_b, "fp-b")
    # blocker stays UNprimed: no group key, runs solo immediately

    gate = threading.Event()
    solo_calls, batch_calls = [], []

    def runner(job, degraded=False):
        if job.folder == blocker:
            gate.wait(30)
        solo_calls.append(job.id)

    def batch_runner(jobs, degraded=False):
        batch_calls.append([j.id for j in jobs])

    d = make_daemon(runner=runner, batch_runner=batch_runner, journal=False)
    blk = client.submit(blocker, d.socket_path, {"output": "x"})["id"]
    # queue while the executor is busy: A1, B1, A2 -- FIFO order
    a1 = client.submit(folder_a, d.socket_path, {"output": "x"})["id"]
    b1 = client.submit(folder_b, d.socket_path, {"output": "x"})["id"]
    a2 = client.submit(folder_a, d.socket_path, {"output": "x"})["id"]
    gate.set()
    jobs = {j: client.wait(j, d.socket_path, timeout=60.0)["job"]
            for j in (blk, a1, b1, a2)}
    assert all(j["state"] == "done" for j in jobs.values())
    # A1+A2 fused past the interleaved B1; B1 ran solo
    assert [a1, a2] in batch_calls
    assert b1 in solo_calls
    assert not any(b1 in call for call in batch_calls)
    assert jobs[a1]["batch"] == jobs[a2]["batch"] is not None
    assert jobs[b1]["batch"] is None


def test_window_bounds_added_latency(tmp_path, batch_env, make_daemon):
    """The admission window is the only latency batching may add: a
    mate joining a batch waits at most window + the head's execute; a
    lone head waits exactly the window then runs solo."""
    folder, _ = _chain_folder(tmp_path)
    _prime(folder)
    window = 0.4
    batch_env.setenv("SPGEMM_TPU_SERVE_BATCH_WINDOW_S", str(window))

    d = make_daemon(runner=lambda job, degraded=False: None,
                    batch_runner=lambda jobs, degraded=False: None,
                    journal=False)
    # lone batchable head: waits the full window for mates, then solo
    t0 = time.time()
    [lone] = _submit_wait(d, folder, [str(tmp_path / "lone")])
    wall = time.time() - t0
    assert lone["state"] == "done"
    assert lone["batch"] is None
    assert wall < window + 10.0  # never unbounded
    # two back-to-back: the second co-batches, its queue wait bounded
    # by the window plus the head's execute wall
    jobs = _submit_wait(d, folder,
                        [str(tmp_path / "j0"), str(tmp_path / "j1")])
    assert all(j["state"] == "done" for j in jobs)
    assert jobs[0]["batch"] == jobs[1]["batch"] is not None
    head_exec = jobs[0]["detail"]["phases_s"].get("serve_execute", 0.0)
    mate_wait = jobs[1]["detail"]["phases_s"].get("serve_queue_wait")
    assert mate_wait is not None
    assert mate_wait <= window + head_exec + 5.0


# ---------------------------------------------- per-job observability --
def test_per_job_records_stay_individual(tmp_path, batch_env, make_daemon):
    """Fusing the dispatch must not fuse the records: every co-batched
    job keeps its own trace id, its own journal lifecycle, its own
    phase attribution, and its own SLO window entry."""
    folder, _ = _chain_folder(tmp_path)
    _prime(folder)
    d = make_daemon(runner=lambda job, degraded=False: None,
                    batch_runner=lambda jobs, degraded=False: None)
    outs = [str(tmp_path / f"o{i}") for i in range(3)]
    jobs = _submit_wait(d, folder, outs, tenant="acme")
    assert all(j["state"] == "done" for j in jobs)
    batched = [j for j in jobs if j["batch"] is not None]
    assert len(batched) >= 2

    # distinct client-minted trace ids survive the fused dispatch
    traces = {j["trace"] for j in jobs}
    assert len(traces) == len(jobs)
    # per-job phase attribution: each member's own scope saw the phases
    for j in batched:
        assert "serve_queue_wait" in j["detail"]["phases_s"]
        assert "serve_execute" in j["detail"]["phases_s"]
    # the journal carries each member's own lifecycle records
    with open(d.journal_path) as f:
        recs = [journal_parse_line(ln.strip()) for ln in f if ln.strip()]
    by_job = {}
    for rec in recs:
        if rec and rec.get("id"):
            by_job.setdefault(rec["id"], set()).add(rec.get("event"))
    for j in jobs:
        assert "submit" in by_job[j["id"]]
        assert "done" in by_job[j["id"]]
    # the SLO engine saw every member as its own terminal job
    slo = client.slo(d.socket_path)
    assert slo["tenants"]["acme"]["jobs"] == len(jobs)


def test_drr_fairness_decides_membership_before_formation(tmp_path,
                                                          batch_env,
                                                          make_daemon):
    """Tenant fairness is applied at drain time: with a chatty tenant's
    jobs queued ahead, the quiet tenant's same-structure job still lands
    in the FIRST batch (deficit-round-robin picks across tenants), not
    behind the chatty backlog."""
    folder, _ = _chain_folder(tmp_path)
    blocker, _ = _chain_folder(tmp_path, seed=9, name="blocker")
    _prime(folder)
    batch_env.setenv("SPGEMM_TPU_SERVE_BATCH_K", "4")

    gate = threading.Event()
    batch_calls = []

    def runner(job, degraded=False):
        if job.folder == blocker:
            gate.wait(30)

    def batch_runner(jobs, degraded=False):
        batch_calls.append([j.id for j in jobs])

    d = make_daemon(runner=runner, batch_runner=batch_runner, journal=False)
    blk = client.submit(blocker, d.socket_path, {"output": "x"})["id"]
    chatty = [client.submit(folder, d.socket_path, {"output": "x"},
                            tenant="chatty")["id"] for _ in range(5)]
    quiet = client.submit(folder, d.socket_path, {"output": "x"},
                          tenant="quiet")["id"]
    gate.set()
    for j in [blk] + chatty + [quiet]:
        assert client.wait(j, d.socket_path,
                           timeout=60.0)["job"]["state"] == "done"
    assert batch_calls, "no batch formed"
    # the first fused batch (K=4) includes the quiet tenant's job --
    # DRR ran before batch formation, so chatty couldn't fill it
    assert quiet in batch_calls[0]
    assert len(batch_calls[0]) <= 4


# ------------------------------------------- dense rounds run solo --
def test_dense_rounds_take_solo_fallback(monkeypatch):
    """ISSUE-17 interplay: the fused batched path only stacks the
    planner's 2-D ladder rounds along the job axis, so a forced-dense
    plan's 1-D pair streams trip the existing solo-fallback guard in
    ops/spgemm.execute_batched -- every job runs per-pair execute with
    identical bytes (never a crash, never a mis-stacked stream)."""
    from spgemm_tpu.ops.spgemm import execute, execute_batched, plan

    monkeypatch.setenv("SPGEMM_TPU_ACCUM_ROUTE", "dense")
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "0")  # count real dispatches
    plancache.clear()
    k, K, f = 2, 2, 40
    a_coords = np.array([(i, i * f + j) for i in range(K)
                         for j in range(f)], np.int64)
    b_coords = np.array([(m, 0) for m in range(K * f)], np.int64)

    def _pair(seed):
        r = np.random.default_rng(seed)
        a = BlockSparseMatrix(
            rows=K, cols=K * f, k=k, coords=a_coords,
            tiles=r.integers(0, 1 << 64, size=(len(a_coords), k, k),
                             dtype=np.uint64))
        b = BlockSparseMatrix(
            rows=K * f, cols=1, k=k, coords=b_coords,
            tiles=r.integers(0, 1 << 64, size=(len(b_coords), k, k),
                             dtype=np.uint64))
        return a, b

    pairs = [_pair(s) for s in (1, 2, 3)]
    p = plan(*pairs[0])
    rounds = p.ensure_exact().rounds
    assert any(rnd.pa.ndim != 2 for rnd in rounds)  # the guard's predicate
    solo = [execute(p, a, b) for a, b in pairs]
    ENGINE.reset()
    batched = execute_batched(p, list(pairs))
    counters = ENGINE.counter_snapshot()
    # solo fallback: one dispatch per (job, round), not one per round
    assert counters["dispatches"] == len(pairs) * len(rounds)
    assert counters.get("route_dense", 0) >= len(pairs)
    for s, g in zip(solo, batched):
        assert np.array_equal(s.coords, g.coords)
        assert np.asarray(s.hi).tobytes() == np.asarray(g.hi).tobytes()
        assert np.asarray(s.lo).tobytes() == np.asarray(g.lo).tobytes()
    plancache.clear()
