"""Pallas numeric kernel vs the XLA numeric phase and the oracle.

Runs in interpret mode on the CPU backend (SURVEY.md section 4: multi-chip /
kernel testing without a pod); the real-TPU compile path is exercised by
bench.py and the CLI on hardware.
"""

import numpy as np
import pytest

from spgemm_tpu.ops.spgemm import spgemm
from spgemm_tpu.utils.gen import random_block_sparse
from spgemm_tpu.utils.semantics import spgemm_oracle
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix


@pytest.mark.parametrize("dist", ["small", "full", "adversarial"])
@pytest.mark.parametrize("k", [2, 8])
def test_pallas_backend_vs_oracle(k, dist):
    rng = np.random.default_rng(2000 * k + len(dist))
    a = random_block_sparse(5, 5, k, 0.4, rng, dist)
    b = random_block_sparse(5, 5, k, 0.4, rng, dist)
    got = spgemm(a, b, backend="pallas")
    want = spgemm_oracle(a.to_dict(), b.to_dict(), k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, k, want)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


def test_pallas_multi_round_and_padding():
    rng = np.random.default_rng(77)
    a = random_block_sparse(9, 9, 4, 0.5, rng, "full")
    b = random_block_sparse(9, 9, 4, 0.5, rng, "full")
    got = spgemm(a, b, backend="pallas", round_size=4)
    want = spgemm(a, b, backend="xla")
    assert got == want


@pytest.mark.parametrize("algo", ["colbcast", "vecj"])
@pytest.mark.parametrize("pb", [2, 3, 8])  # clean multi-step, tail, PB == P
def test_pair_block_matches_unblocked(algo, pb):
    """Pair-axis blocking (PB pairs folded per grid step) must be
    bit-identical to the PB=1 kernel: sentinel padding of the pair axis
    contributes zero and the fold order stays pair-ascending.  P=8 with
    PB in {2, 3, 8} exercises the no-padding multi-step case, tail
    padding, and the full-collapse-to-one-step case."""
    import jax.numpy as jnp

    from spgemm_tpu.ops import u64
    from spgemm_tpu.ops.pallas_spgemm import numeric_round_pallas
    from spgemm_tpu.utils.gen import random_values

    rng = np.random.default_rng(31 * pb + len(algo))
    k, nnzb, K, P = 8, 9, 20, 8
    tiles = random_values((nnzb + 1, k, k), rng, "adversarial")
    tiles[-1] = 0
    hi, lo = map(jnp.asarray, u64.u64_to_hilo(tiles))
    pa = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    pb_idx = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    w = numeric_round_pallas(hi, lo, hi, lo, pa, pb_idx, interpret=True,
                             algo=algo)
    g = numeric_round_pallas(hi, lo, hi, lo, pa, pb_idx, interpret=True,
                             algo=algo, pair_block=pb)
    assert np.array_equal(np.asarray(w[0]), np.asarray(g[0]))
    assert np.array_equal(np.asarray(w[1]), np.asarray(g[1]))


@pytest.mark.parametrize("algo", ["colbcast", "vecj"])
def test_no_mod_matches_exact_in_proven_regime(algo):
    """u64.mac_nomod (28-op MAC) must be bit-identical to the exact kernel
    whenever the safe_exact_bound proof regime holds -- every product and
    partial sum < 2^64-1, so each mod_max is identity.  Hybrid dispatch
    routes proven rounds here when the speed gate keeps them on the VPU."""
    import jax.numpy as jnp

    from spgemm_tpu.ops import u64
    from spgemm_tpu.ops.mxu_spgemm import safe_exact_bound
    from spgemm_tpu.ops.pallas_spgemm import numeric_round_pallas
    from spgemm_tpu.utils.gen import random_values

    rng = np.random.default_rng(len(algo))
    k, nnzb, K, P = 8, 9, 12, 4
    bound = (1 << 24) - 1
    assert safe_exact_bound(bound, bound, P, k) is not None  # proven regime
    tiles = random_values((nnzb + 1, k, k), rng, "full") % np.uint64(bound + 1)
    tiles[-1] = 0
    hi, lo = map(jnp.asarray, u64.u64_to_hilo(tiles))
    pa = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    pb = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    w = numeric_round_pallas(hi, lo, hi, lo, pa, pb, interpret=True, algo=algo)
    g = numeric_round_pallas(hi, lo, hi, lo, pa, pb, interpret=True, algo=algo,
                             no_mod=True)
    assert np.array_equal(np.asarray(w[0]), np.asarray(g[0]))
    assert np.array_equal(np.asarray(w[1]), np.asarray(g[1]))

    # non-vacuity: outside the proven regime the variants genuinely diverge.
    # mod_max fires only on the exact value 2^64-1 (never on random data),
    # so construct it: (2^64-1) * 1 collapses to 0 under mulmod and stays
    # 2^64-1 under mul64_lo.
    t = np.zeros((3, k, k), np.uint64)
    t[0, 0, 0] = (1 << 64) - 1
    t[1, 0, 0] = 1
    chi, clo = map(jnp.asarray, u64.u64_to_hilo(t))
    one = jnp.zeros((1, 1), jnp.int32)
    wf = numeric_round_pallas(chi, clo, chi, clo, one, one + 1,
                              interpret=True, algo=algo)
    gf = numeric_round_pallas(chi, clo, chi, clo, one, one + 1,
                              interpret=True, algo=algo, no_mod=True)
    assert int(np.asarray(wf[0])[0, 0, 0]) == 0 == int(np.asarray(wf[1])[0, 0, 0])
    assert u64.hilo_to_u64(np.asarray(gf[0]), np.asarray(gf[1]))[0, 0, 0] \
        == np.uint64((1 << 64) - 1)


@pytest.mark.parametrize("dist", ["full", "adversarial"])
def test_vecj_algo_matches_colbcast(dist):
    """The vectorized-j kernel layout must be bit-identical to the unrolled
    column-broadcast layout (same fold order, different vector arrangement)."""
    import jax.numpy as jnp

    from spgemm_tpu.ops import u64
    from spgemm_tpu.ops.pallas_spgemm import numeric_round_pallas
    from spgemm_tpu.utils.gen import random_values

    rng = np.random.default_rng(len(dist))
    k, nnzb, K, P = 8, 9, 20, 7
    tiles = random_values((nnzb + 1, k, k), rng, dist)
    tiles[-1] = 0
    hi, lo = map(jnp.asarray, u64.u64_to_hilo(tiles))
    pa = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    pb = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    w = numeric_round_pallas(hi, lo, hi, lo, pa, pb, interpret=True,
                             algo="colbcast")
    g = numeric_round_pallas(hi, lo, hi, lo, pa, pb, interpret=True,
                             algo="vecj")
    assert np.array_equal(np.asarray(w[0]), np.asarray(g[0]))
    assert np.array_equal(np.asarray(w[1]), np.asarray(g[1]))
