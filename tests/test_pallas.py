"""Pallas numeric kernel vs the XLA numeric phase and the oracle.

Runs in interpret mode on the CPU backend (SURVEY.md section 4: multi-chip /
kernel testing without a pod); the real-TPU compile path is exercised by
bench.py and the CLI on hardware.
"""

import numpy as np
import pytest

from spgemm_tpu.ops.spgemm import spgemm
from spgemm_tpu.utils.gen import random_block_sparse
from spgemm_tpu.utils.semantics import spgemm_oracle
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix


@pytest.mark.parametrize("dist", ["small", "full", "adversarial"])
@pytest.mark.parametrize("k", [2, 8])
def test_pallas_backend_vs_oracle(k, dist):
    rng = np.random.default_rng(2000 * k + len(dist))
    a = random_block_sparse(5, 5, k, 0.4, rng, dist)
    b = random_block_sparse(5, 5, k, 0.4, rng, dist)
    got = spgemm(a, b, backend="pallas")
    want = spgemm_oracle(a.to_dict(), b.to_dict(), k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, k, want)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


def test_pallas_multi_round_and_padding():
    rng = np.random.default_rng(77)
    a = random_block_sparse(9, 9, 4, 0.5, rng, "full")
    b = random_block_sparse(9, 9, 4, 0.5, rng, "full")
    got = spgemm(a, b, backend="pallas", round_size=4)
    want = spgemm(a, b, backend="xla")
    assert got == want
