"""Fleet layer (PR 20 tentpole): the TCP front-end on spgemmd
(`SPGEMM_TPU_SERVE_ADDR` / `--addr`) and the spgemm-router federation
front door (`spgemm_tpu/fleet/`) -- all tier-1 on the CPU backend with
fake runners (the network/placement plane under test is jax-free).

The standing contracts:
  * the TCP listener speaks the SAME newline-JSON protocol as the unix
    socket -- version negotiation, line cap, malformed-line survival,
    and the structured error surface are transport-independent;
  * `SPGEMM_TPU_SERVE_ADDR` unset = no TCP listener at all (the
    whole-feature A/B: byte-identical pre-fleet daemon);
  * the router forwards `tenant` and the client-minted `trace` context
    untouched, answers under FLEET job ids with a `backend` field, and
    enforces placement over healthy backends only;
  * a backend that dies mid-job fails over ONCE to a healthy peer
    (idempotent re-submit) or the caller gets structured
    `backend-lost`/`no-backend` -- never a hang.
"""

import json
import socket

import numpy as np
import pytest

from spgemm_tpu.fleet.pricebook import PriceBook
from spgemm_tpu.fleet.router import Router, _label_scrape
from spgemm_tpu.serve import client, protocol
from spgemm_tpu.serve.daemon import Daemon
from spgemm_tpu.utils import io_text
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_chain
from spgemm_tpu.utils.semantics import chain_oracle


def _chain_folder(tmp_path, n=3, k=2, seed=7, name="chain_in"):
    mats = random_chain(n, 4, k, 0.5, np.random.default_rng(seed), "full")
    folder = str(tmp_path / name)
    io_text.write_chain_dir(folder, mats, k)
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_bytes = io_text.format_matrix(BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k, want).prune_zeros())
    return folder, want_bytes


@pytest.fixture
def make_daemon(tmp_path):
    """Daemon factory on a per-test socket (+ optional TCP front-end);
    stops them on teardown."""
    daemons = []

    def _make(idx=0, **kw):
        d = Daemon(str(tmp_path / f"d{idx}.sock"), **kw)
        d.start()
        daemons.append(d)
        return d

    yield _make
    for d in daemons:
        d.stop()


@pytest.fixture
def make_router(make_daemon):
    """(router, [daemons]) over N fake-runner daemons, all on TCP."""
    routers = []

    def _make(n=2, router_kw=None, **daemon_kw):
        daemon_kw.setdefault("runner", lambda job, degraded=False: None)
        ds = [make_daemon(idx=i, addr="tcp:127.0.0.1:0", **daemon_kw)
              for i in range(n)]
        r = Router(listen="tcp:127.0.0.1:0",
                   backends=[f"tcp:127.0.0.1:{d.tcp_port}" for d in ds],
                   poll_s=0.2, **(router_kw or {}))
        r.start()
        routers.append(r)
        return r, ds

    yield _make
    for r in routers:
        r.stop()


def _tcp_roundtrip(port: int, payload: bytes) -> dict:
    """One raw line out over TCP, one response line back."""
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=10.0) as s:
        try:
            s.sendall(payload)
        except BrokenPipeError:
            pass  # answer-and-close races the send, response readable
        for line in protocol.read_lines(s):
            return json.loads(line)
    raise AssertionError("no response line")


def _addr(obj) -> str:
    port = obj.tcp_port if isinstance(obj, Daemon) else obj.port
    return f"tcp:127.0.0.1:{port}"


# ------------------------------------------------------------ parse_addr --
def test_parse_addr_spellings():
    assert protocol.parse_addr("tcp:127.0.0.1:7463") == \
        ("tcp", "127.0.0.1", 7463)
    assert protocol.parse_addr("tcp:[::1]:80") == ("tcp", "::1", 80)
    assert protocol.parse_addr("tcp:host:0") == ("tcp", "host", 0)
    assert protocol.parse_addr("unix:/tmp/x.sock") == \
        ("unix", "/tmp/x.sock")
    assert protocol.parse_addr("/tmp/bare.sock") == \
        ("unix", "/tmp/bare.sock")
    assert protocol.format_addr(("tcp", "h", 1)) == "tcp:h:1"
    assert protocol.format_addr(("unix", "/p")) == "unix:/p"
    for bad in ("", "tcp:", "tcp:hostonly", "tcp::", "tcp:h:notaport",
                "tcp:h:70000", "unix:"):
        with pytest.raises(ValueError):
            protocol.parse_addr(bad)


# ------------------------------------------------------- TCP front-end --
def test_unset_addr_means_no_tcp_listener(make_daemon):
    """The whole-feature A/B: no SPGEMM_TPU_SERVE_ADDR, no --addr =
    exactly the pre-fleet unix-only daemon."""
    d = make_daemon(runner=lambda job, degraded=False: None)
    assert d.tcp_port is None and d._tcp_listener is None


def test_non_tcp_addr_fails_startup_loudly(tmp_path):
    with pytest.raises(ValueError, match="SPGEMM_TPU_SERVE_ADDR"):
        Daemon(str(tmp_path / "d.sock"), addr="unix:/elsewhere.sock",
               runner=lambda job, degraded=False: None)


def test_tcp_listener_serves_the_same_protocol(make_daemon):
    """stats over TCP == stats over the unix socket, same daemon."""
    d = make_daemon(addr="tcp:127.0.0.1:0",
                    runner=lambda job, degraded=False: None)
    assert isinstance(d.tcp_port, int) and d.tcp_port > 0
    over_tcp = client.stats(_addr(d))
    over_unix = client.stats(d.socket_path)
    assert over_tcp["daemon"] == over_unix["daemon"] == "spgemmd"
    assert over_tcp["socket"] == over_unix["socket"]


def test_malformed_tcp_line_gets_error_and_daemon_survives(make_daemon):
    d = make_daemon(addr="tcp:127.0.0.1:0",
                    runner=lambda job, degraded=False: None)
    resp = _tcp_roundtrip(d.tcp_port, b"this is not json\n")
    assert resp["ok"] is False
    assert resp["error"]["code"] == protocol.E_BAD_REQUEST
    # oversized line: answered structured, connection dropped, and the
    # daemon keeps serving the next connection
    resp = _tcp_roundtrip(d.tcp_port,
                          b"x" * (protocol.MAX_LINE_BYTES + 2))
    assert resp["ok"] is False
    assert resp["error"]["code"] == protocol.E_BAD_REQUEST
    assert client.stats(_addr(d))["daemon"] == "spgemmd"


def test_tcp_negotiation_old_client_direction(make_daemon):
    """Rolling upgrade, old-client-vs-new-daemon over TCP: a bare v1
    line is served; an impossible version is rejected naming what the
    daemon accepts (the downgrade handshake's raw material)."""
    d = make_daemon(addr="tcp:127.0.0.1:0",
                    runner=lambda job, degraded=False: None)
    resp = _tcp_roundtrip(d.tcp_port,
                          protocol.encode({"v": 1, "op": "stats"}))
    assert resp["ok"] is True and resp["daemon"] == "spgemmd"
    resp = _tcp_roundtrip(d.tcp_port,
                          protocol.encode({"v": 99, "op": "stats"}))
    assert resp["ok"] is False
    assert resp["error"]["code"] == protocol.E_BAD_REQUEST
    assert protocol.accepted_from_error(resp["error"]["message"]) == \
        protocol.ACCEPTED_VERSIONS


def test_tcp_negotiation_new_client_direction(tmp_path, make_daemon,
                                              monkeypatch):
    """Rolling upgrade, new-client-vs-old-daemon over TCP: the client's
    one-shot downgrade retry (strip + restamp) works unchanged through
    the TCP transport."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(addr="tcp:127.0.0.1:0",
                    runner=lambda job, degraded=False: None)
    monkeypatch.setattr(protocol, "ACCEPTED_VERSIONS", (1, 2))
    sent = []
    real_encode = protocol.encode
    monkeypatch.setattr(client.protocol, "encode",
                        lambda msg: sent.append(msg) or real_encode(msg))
    resp = client.submit(folder, _addr(d), tenant="alice")
    reqs = [m for m in sent if m.get("op") == "submit"]
    assert [m["v"] for m in reqs] == [3, 2]
    assert "trace" not in reqs[1] and reqs[1]["tenant"] == "alice"
    assert resp["ok"] and resp["id"]


def test_tcp_client_unavailable_is_structured(tmp_path):
    """No listener behind the port: the TCP client raises the same
    structured daemon-unavailable the unix path does, within budget."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here now
    with pytest.raises(client.ServeError) as ei:
        client.request({"op": "stats"}, f"tcp:127.0.0.1:{port}",
                       retry_total_s=0.2)
    assert ei.value.code == protocol.E_UNAVAILABLE


# ------------------------------------------------------------ pricebook --
def test_pricebook_merge_lookup_and_bounds(tmp_path):
    book = PriceBook(cap=2)
    folder, _ = _chain_folder(tmp_path)
    from spgemm_tpu.serve import placement
    sig = placement.signature(folder)
    assert book.lookup(folder) is None  # first contact
    assert book.merge({"book": {sig: 123.0, "other": 7}}) == 2
    assert book.lookup(folder) == 123.0
    # malformed gossip contributes nothing
    assert book.merge(None) == 0
    assert book.merge({"book": {1: "nan"}}) == 0
    # LRU cap: a third signature evicts the oldest untouched one
    assert book.merge({"book": {"third": 9.0}}) == 1
    assert book.stats()["book_entries"] == 2


# --------------------------------------------------------------- router --
def test_router_requires_backends():
    with pytest.raises(ValueError, match="at least one backend"):
        Router(listen="tcp:127.0.0.1:0", backends=[])
    with pytest.raises(ValueError, match="duplicate"):
        Router(listen="tcp:127.0.0.1:0",
               backends=["tcp:127.0.0.1:1", "tcp:127.0.0.1:1"])


def test_router_passes_tenant_and_trace_through(tmp_path, make_router):
    """The client-minted trace context and the tenant reach the backend
    byte-for-byte; the answer comes back under the FLEET id with the
    serving backend named."""
    folder, _ = _chain_folder(tmp_path)
    r, ds = make_router()
    trace = protocol.mint_trace()
    resp = client.submit(folder, _addr(r), tenant="alice", trace=trace)
    assert resp["id"].startswith("r")
    assert resp["backend"] in r._backends
    assert resp["trace"] == trace
    st = client.wait(resp["id"], _addr(r), timeout=30)
    job = st["job"]
    assert job["id"] == resp["id"]  # fleet id, not the backend's
    assert job["state"] == "done"
    assert job["tenant"] == "alice" and job["trace"] == trace
    assert st["backend"] == resp["backend"]


def test_router_rejects_bad_tenant_and_unknown_job(tmp_path, make_router):
    folder, _ = _chain_folder(tmp_path)
    r, _ = make_router()
    with pytest.raises(client.ServeError) as ei:
        client.submit(folder, _addr(r), tenant="bad tenant!")
    assert ei.value.code == protocol.E_BAD_REQUEST
    with pytest.raises(client.ServeError) as ei:
        client.status("r999", _addr(r))
    assert ei.value.code == protocol.E_UNKNOWN_JOB


def test_router_no_backend_when_all_dead(tmp_path):
    """Backends that never answered a poll are unplaceable: submit gets
    structured no-backend, never a hang."""
    folder, _ = _chain_folder(tmp_path)
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    r = Router(listen="tcp:127.0.0.1:0",
               backends=[f"tcp:127.0.0.1:{dead_port}"], poll_s=30.0)
    r.start()
    try:
        with pytest.raises(client.ServeError) as ei:
            client.submit(folder, _addr(r))
        assert ei.value.code == protocol.E_NO_BACKEND
    finally:
        r.stop()


def test_router_fails_over_to_survivor(tmp_path, make_router):
    """The backend holding a job dies; the next status through the
    router re-submits ONCE to the survivor and answers from there --
    and with no survivor, the caller gets structured backend-lost."""
    folder, _ = _chain_folder(tmp_path)
    r, ds = make_router(n=2)
    resp = client.submit(folder, _addr(r), tenant="alice")
    first = resp["backend"]
    victim = next(d for d in ds
                  if f"tcp:127.0.0.1:{d.tcp_port}" == first)
    survivor_name = next(n for n in r._backends if n != first)
    victim.stop()
    st = client.wait(resp["id"], _addr(r), timeout=30)
    assert st["job"]["state"] == "done"
    assert st["backend"] == survivor_name
    stats = client.stats(_addr(r))
    assert stats["jobs"]["failovers"] == 1
    assert stats["backends"][first]["up"] is False
    # one-shot: kill the survivor too and the SAME job now reports
    # backend-lost instead of a second silent re-submit
    next(d for d in ds if d is not victim).stop()
    with pytest.raises(client.ServeError) as ei:
        client.status(resp["id"], _addr(r))
    assert ei.value.code == protocol.E_BACKEND_LOST


def test_router_metrics_aggregation(tmp_path, make_router):
    """One scrape: router families per backend + every backend's own
    series relabeled with backend= (labels merged, not clobbered)."""
    folder, _ = _chain_folder(tmp_path)
    r, ds = make_router()
    client.submit(folder, _addr(r))
    text = client.metrics(_addr(r))
    for name in r._backends:
        assert f'spgemm_router_backend_up{{backend="{name}"}} 1' in text
    assert "spgemm_router_failovers_total 0" in text
    relabeled = [ln for ln in text.splitlines()
                 if 'backend="' in ln
                 and not ln.startswith("spgemm_router_")]
    assert relabeled, "no backend-relabeled passthrough series"


def test_label_scrape_injects_not_clobbers():
    out = _label_scrape('# HELP x y\na{b="c"} 1\nplain 2\n', 'be"1')
    assert out.splitlines() == [
        'a{backend="be\\"1",b="c"} 1', 'plain{backend="be\\"1"} 2']


def test_router_shutdown_op_stops(tmp_path, make_router):
    r, _ = make_router()
    resp = client.shutdown(_addr(r))
    assert resp["stopping"] is True
    assert r._stop.wait(5.0)
