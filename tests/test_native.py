"""Native C++ I/O vs the pure-Python path: identical parse, identical bytes."""

import os

import numpy as np
import pytest

from spgemm_tpu.utils import io_text, native
from spgemm_tpu.utils.gen import random_block_sparse


pytestmark = pytest.mark.skipif(native.get_lib() is None,
                                reason="native library unavailable (no g++?)")


def _py_read(path, k):
    os.environ["SPGEMM_TPU_NO_NATIVE"] = "1"
    try:
        return io_text.read_matrix(path, k)
    finally:
        del os.environ["SPGEMM_TPU_NO_NATIVE"]


def test_native_parse_matches_python(tmp_path):
    rng = np.random.default_rng(90)
    m = random_block_sparse(8, 8, 4, 0.4, rng, "full")
    path = str(tmp_path / "m")
    io_text.write_matrix(path, m)
    got = io_text.read_matrix(path, 4)       # native path
    want = _py_read(path, 4)                 # python path
    assert got == want == m


def test_native_write_bytes_identical(tmp_path):
    rng = np.random.default_rng(91)
    m = random_block_sparse(6, 6, 3, 0.5, rng, "adversarial")
    p_native = str(tmp_path / "native")
    assert native.write_matrix(p_native, m.rows, m.cols, m.k, m.coords, m.tiles)
    assert open(p_native, "rb").read() == io_text.format_matrix(m)


def test_native_empty_matrix(tmp_path):
    path = str(tmp_path / "m")
    (tmp_path / "m").write_text("8 8\n0\n")
    rows, cols, coords, tiles = native.parse_matrix(path, 4)
    assert (rows, cols) == (8, 8)
    assert coords.shape == (0, 2) and tiles.shape == (0, 4, 4)


def test_native_malformed_raises(tmp_path):
    path = tmp_path / "m"
    path.write_text("2 2\n1\n0 0\n1 2\n")  # truncated tile
    with pytest.raises(ValueError):
        native.parse_matrix(str(path), 2)
    path2 = tmp_path / "m2"
    path2.write_text("junk\n")
    with pytest.raises(ValueError):
        native.parse_matrix(str(path2), 2)


def test_native_missing_file():
    with pytest.raises(FileNotFoundError):
        native.parse_matrix("/does/not/exist", 2)


def test_native_u64_extremes(tmp_path):
    path = tmp_path / "m"
    path.write_text("2 2\n1\n0 0\n18446744073709551615 0\n1 18446744073709551614\n")
    rows, cols, coords, tiles = native.parse_matrix(str(path), 2)
    assert tiles[0, 0, 0] == np.uint64(18446744073709551615)
    assert tiles[0, 1, 1] == np.uint64(18446744073709551614)


# -- native full-parity fold (native/parityfold.cpp) -------------------------

def test_native_parity_fold_vs_oracle_adversarial():
    """The native uint64 wrap-then-mod fold must agree with the python-int
    oracle on full-range adversarial values (every key), and flag corrupted
    tiles with an exact count + first-bad index."""
    from spgemm_tpu.ops.symbolic import symbolic_join
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
    from spgemm_tpu.utils.semantics import spgemm_oracle

    rng = np.random.default_rng(92)
    a = random_block_sparse(12, 12, 4, 0.4, rng, "adversarial")
    b = random_block_sparse(12, 12, 4, 0.4, rng, "adversarial")
    join = symbolic_join(a.coords, b.coords)
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    assert np.array_equal(want.coords, join.keys)  # oracle key order == join

    res = native.parity_fold_check(a.tiles, b.tiles, join.pair_ptr,
                                   join.pair_a, join.pair_b, want.tiles)
    assert res == (0, -1)

    # corrupt two tiles -> exactly 2 bad keys, first index reported
    bad = want.tiles.copy()
    bad[3, 0, 0] ^= np.uint64(1)
    bad[7, 1, 2] ^= np.uint64(1)
    n_bad, first = native.parity_fold_check(
        a.tiles, b.tiles, join.pair_ptr, join.pair_a, join.pair_b, bad)
    assert n_bad == 2 and first == 3


def test_native_parity_fold_engine_output():
    """End-to-end: the engine's own output passes the native all-keys check
    (the at-scale parity statement of RESULTS.md, at test scale)."""
    from spgemm_tpu.ops.spgemm import spgemm
    from spgemm_tpu.ops.symbolic import symbolic_join

    rng = np.random.default_rng(93)
    a = random_block_sparse(16, 16, 4, 0.3, rng, "full")
    b = random_block_sparse(16, 16, 4, 0.3, rng, "full")
    got = spgemm(a, b)
    join = symbolic_join(a.coords, b.coords)
    res = native.parity_fold_check(a.tiles, b.tiles, join.pair_ptr,
                                   join.pair_a, join.pair_b, got.tiles)
    assert res == (0, -1)


# -- native symbolic join (native/symbolic.cpp) ------------------------------

def test_native_symbolic_join_matches_numpy(monkeypatch):
    """The C++ join must be bit-identical to the numpy fallback across
    structure families (uniform, banded, power-law, near-empty, empty)."""
    import spgemm_tpu.ops.symbolic as S
    from spgemm_tpu.utils.gen import (
        banded_block_sparse, powerlaw_block_sparse, random_block_sparse)

    if native.get_lib() is None:
        pytest.skip("native library unavailable")

    rng = np.random.default_rng(7)
    cases = [
        (random_block_sparse(48, 48, 8, 0.15, rng).coords,
         random_block_sparse(48, 48, 8, 0.15, rng).coords),
        (banded_block_sparse(64, 8, 3, rng).coords,
         banded_block_sparse(64, 8, 6, rng).coords),
        (powerlaw_block_sparse(64, 8, 3.0, rng).coords,
         powerlaw_block_sparse(64, 8, 3.0, rng).coords),
        (random_block_sparse(8, 8, 8, 0.02, rng).coords,
         random_block_sparse(8, 8, 8, 0.02, rng).coords),
        (np.zeros((0, 2), np.int64), random_block_sparse(8, 8, 8, 0.2, rng).coords),
        # disjoint structures: zero pairs
        (np.array([[0, 0]], np.int64), np.array([[5, 5]], np.int64)),
    ]
    for i, (ac, bc) in enumerate(cases):
        nat = S.symbolic_join(ac, bc)
        with monkeypatch.context() as m:
            m.setattr(native, "symbolic_join_native", lambda *a: None)
            py = S.symbolic_join(ac, bc)
        assert np.array_equal(nat.keys, py.keys), i
        assert np.array_equal(nat.pair_ptr, py.pair_ptr), i
        assert np.array_equal(nat.pair_a, py.pair_a), i
        assert np.array_equal(nat.pair_b, py.pair_b), i
