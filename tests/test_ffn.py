"""Block-sparse FFN (models/ffn): numerics vs dense, sharded-vs-single parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spgemm_tpu.models.ffn import (
    BlockSparseFFNConfig, bsmm_gather, bsmm_scatter, ffn_forward, init_params,
    loss_fn, make_sharded_train_step, shard_params)


CFG = BlockSparseFFNConfig(d_model=64, d_ff=128, k=8, block_density=0.5,
                           dtype="float32")


def _dense_w1(params, cfg):
    """Materialize W1 (d_model, d_ff) from its column-major block structure."""
    w = np.zeros((cfg.d_model, cfg.d_ff), np.float32)
    rows = np.asarray(params["w1"]["rows"])
    tiles = np.asarray(params["w1"]["tiles"], np.float32)
    for c in range(cfg.nb_ff):
        for ri, r in enumerate(rows[c]):
            w[r * cfg.k:(r + 1) * cfg.k, c * cfg.k:(c + 1) * cfg.k] = tiles[c, ri]
    return w


def _dense_w2(params, cfg):
    """Materialize W2 (d_ff, d_model) from its row-major block structure."""
    w = np.zeros((cfg.d_ff, cfg.d_model), np.float32)
    cols = np.asarray(params["w2"]["cols"])
    tiles = np.asarray(params["w2"]["tiles"], np.float32)
    for r in range(cfg.nb_ff):
        for ci, c in enumerate(cols[r]):
            # duplicate block-cols accumulate, matching segment_sum semantics
            w[r * cfg.k:(r + 1) * cfg.k, c * cfg.k:(c + 1) * cfg.k] += tiles[r, ci]
    return w


def test_bsmm_gather_vs_dense():
    params = init_params(CFG, jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (3, CFG.d_model), jnp.float32)
    xb = x.reshape(3, CFG.nb_model, CFG.k)
    got = bsmm_gather(xb, params["w1"]).reshape(3, CFG.d_ff)
    want = x @ _dense_w1(params, CFG)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_bsmm_scatter_vs_dense():
    params = init_params(CFG, jax.random.key(3))
    h = jax.random.normal(jax.random.key(4), (3, CFG.d_ff), jnp.float32)
    hb = h.reshape(3, CFG.nb_ff, CFG.k)
    got = bsmm_scatter(hb, params["w2"], CFG.nb_model).reshape(3, CFG.d_model)
    want = h @ _dense_w2(params, CFG)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-4)


def test_ffn_forward_vs_dense():
    params = init_params(CFG, jax.random.key(5))
    x = jax.random.normal(jax.random.key(6), (2, 4, CFG.d_model), jnp.float32)
    got = ffn_forward(params, x, CFG)
    flat = np.asarray(x, np.float32).reshape(8, CFG.d_model)
    h = np.asarray(jax.nn.gelu(jnp.asarray(flat @ _dense_w1(params, CFG))))
    want = (h @ _dense_w2(params, CFG)).reshape(2, 4, CFG.d_model)
    np.testing.assert_allclose(np.asarray(got, np.float32), want, rtol=1e-4, atol=1e-4)


@pytest.fixture
def mesh8():
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return jax.sharding.Mesh(devs, ("dp", "tp"))


def test_sharded_loss_matches_single_device(mesh8):
    cfg = BlockSparseFFNConfig(d_model=64, d_ff=8 * 32, k=8, block_density=0.5,
                               dtype="float32")
    assert cfg.nb_ff % 4 == 0
    params = init_params(cfg, jax.random.key(7))
    x = jax.random.normal(jax.random.key(8), (4, 8, cfg.d_model), jnp.float32)
    y = jax.random.normal(jax.random.key(9), (4, 8, cfg.d_model), jnp.float32)

    single = float(loss_fn(params, x, y, cfg))

    step = make_sharded_train_step(mesh8, cfg)
    sharded_params = shard_params(params, mesh8)
    from jax.sharding import NamedSharding, PartitionSpec as P
    data_sh = NamedSharding(mesh8, P("dp", "tp"))
    _, loss = step(jax.device_put(sharded_params),
                   jax.device_put(x, data_sh), jax.device_put(y, data_sh))
    assert abs(float(loss) - single) < 1e-4 * max(1.0, abs(single))


def test_sharded_training_reduces_loss(mesh8):
    cfg = BlockSparseFFNConfig(d_model=32, d_ff=8 * 16, k=4, block_density=0.5,
                               dtype="float32")
    params = shard_params(init_params(cfg, jax.random.key(10)), mesh8)
    step = make_sharded_train_step(mesh8, cfg, lr=0.1)
    from jax.sharding import NamedSharding, PartitionSpec as P
    data_sh = NamedSharding(mesh8, P("dp", "tp"))
    x = jax.device_put(
        jax.random.normal(jax.random.key(11), (4, 8, cfg.d_model), jnp.float32), data_sh)
    y = jax.device_put(
        jax.random.normal(jax.random.key(12), (4, 8, cfg.d_model), jnp.float32) * 0.1, data_sh)
    losses = []
    for _ in range(5):
        params, loss = step(params, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_pallas_forward_matches_einsum():
    """Both FFN matmuls as Pallas MXU kernels (interpret mode on CPU)."""
    from spgemm_tpu.models.ffn import ffn_forward_pallas, prepare_pallas_params
    cfg = BlockSparseFFNConfig(d_model=64, d_ff=128, k=8, block_density=0.5,
                               dtype="float32")
    params = init_params(cfg, jax.random.key(20))
    x = jax.random.normal(jax.random.key(21), (2, 4, cfg.d_model), jnp.float32)
    want = ffn_forward(params, x, cfg)
    pp = prepare_pallas_params(params, cfg)
    got = ffn_forward_pallas(pp, x, cfg, block_m=8, resident=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_pallas_forward_fused_gelu_matches_unfused():
    """fuse_gelu moves the activation into the kernel epilogue; numerics must
    match the unfused path (gelu applied to the same f32 accumulator -- in
    f32 configs the cast order is identical)."""
    from spgemm_tpu.models.ffn import ffn_forward_pallas, prepare_pallas_params
    cfg = BlockSparseFFNConfig(d_model=64, d_ff=128, k=8, block_density=0.5,
                               dtype="float32")
    params = init_params(cfg, jax.random.key(24))
    x = jax.random.normal(jax.random.key(25), (2, 4, cfg.d_model), jnp.float32)
    pp = prepare_pallas_params(params, cfg)
    want = ffn_forward_pallas(pp, x, cfg, block_m=8, resident=False)
    got = ffn_forward_pallas(pp, x, cfg, block_m=8, fuse_gelu=True,
                             resident=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
    want_ref = ffn_forward(params, x, cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want_ref),
                               rtol=1e-4, atol=1e-4)


def test_pallas_resident_matches_streaming():
    """The VMEM-resident x-panel kernel (bsmm_pallas_resident) must be
    bit-compatible with the streaming kernel -- same contraction per output
    column, only the DMA schedule differs."""
    from spgemm_tpu.models.ffn import ffn_forward_pallas, prepare_pallas_params
    from spgemm_tpu.ops.pallas_bsmm import bsmm_pallas, bsmm_pallas_resident
    cfg = BlockSparseFFNConfig(d_model=64, d_ff=128, k=8, block_density=0.5,
                               dtype="float32")
    params = init_params(cfg, jax.random.key(26))
    x2 = jax.random.normal(jax.random.key(27), (16, cfg.d_model), jnp.float32)
    w = params["w1"]
    got = bsmm_pallas_resident(x2, w["rows"], w["tiles"], block_m=8)
    want = bsmm_pallas(x2, w["rows"], w["tiles"], block_m=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    x3 = jax.random.normal(jax.random.key(28), (2, 4, cfg.d_model), jnp.float32)
    pp = prepare_pallas_params(params, cfg)
    full = ffn_forward_pallas(pp, x3, cfg, block_m=8, resident=True,
                              fuse_gelu=True)
    ref = ffn_forward(params, x3, cfg)
    np.testing.assert_allclose(np.asarray(full), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_pallas_forward_ragged_w2_fanin():
    """Column fan-in of W2 is ragged -> zero-tile padding must be exact."""
    from spgemm_tpu.models.ffn import ffn_forward_pallas, prepare_pallas_params
    cfg = BlockSparseFFNConfig(d_model=32, d_ff=64, k=8, block_density=0.3,
                               dtype="float32")
    params = init_params(cfg, jax.random.key(22))
    x = jax.random.normal(jax.random.key(23), (1, 3, cfg.d_model), jnp.float32)
    want = ffn_forward(params, x, cfg)
    got = ffn_forward_pallas(prepare_pallas_params(params, cfg), x, cfg,
                             block_m=8, resident=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
