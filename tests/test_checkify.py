"""checkify sanitizer pass over the numeric engine (SURVEY.md section 5.2).

The reference has no sanitizers at all (its Makefile ships -ffast-math and a
live iterator-invalidation UB at sparse_matrix_mult.cu:589).  Pure-JAX makes
data races structurally absent; what CAN go wrong is out-of-bounds indexing
-- the numeric phase is driven entirely by host-built gather indices (pa/pb
slab indices, assembly take).  This module runs those paths under
jax.experimental.checkify with index checks enabled, which turns silent
OOB clamping into reported errors.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.experimental import checkify  # noqa: E402

from spgemm_tpu.ops import u64  # noqa: E402
from spgemm_tpu.ops.spgemm import numeric_round_impl  # noqa: E402


def _slabs(k=4, nnzb=6, seed=0):
    rng = np.random.default_rng(seed)
    tiles = rng.integers(0, 1 << 64, size=(nnzb + 1, k, k), dtype=np.uint64)
    tiles[-1] = 0
    hi, lo = u64.u64_to_hilo(tiles)
    return jnp.asarray(hi), jnp.asarray(lo), nnzb


def test_numeric_round_clean_under_index_checks():
    """Well-formed rounds (sentinel-padded, in-range indices) must pass the
    checkify index sanitizer with no error."""
    hi, lo, nnzb = _slabs()
    rng = np.random.default_rng(1)
    pa = jnp.asarray(rng.integers(0, nnzb + 1, size=(5, 3), dtype=np.int32))
    pb = jnp.asarray(rng.integers(0, nnzb + 1, size=(5, 3), dtype=np.int32))
    checked = checkify.checkify(
        jax.jit(numeric_round_impl), errors=checkify.index_checks)
    err, (oh, ol) = checked(hi, lo, hi, lo, pa, pb)
    err.throw()  # no error expected
    # sanity: result matches the unchecked path
    wh, wl = numeric_round_impl(hi, lo, hi, lo, pa, pb)
    assert np.array_equal(np.asarray(oh), np.asarray(wh))
    assert np.array_equal(np.asarray(ol), np.asarray(wl))


def test_checkify_catches_out_of_bounds_pair_index():
    """An index past the sentinel slot (host-side planner bug) is exactly
    what the sanitizer pass exists to catch."""
    hi, lo, nnzb = _slabs()
    pa = jnp.asarray(np.array([[nnzb + 5]], np.int32))  # out of range
    pb = jnp.asarray(np.array([[0]], np.int32))
    checked = checkify.checkify(
        jax.jit(numeric_round_impl), errors=checkify.index_checks)
    err, _ = checked(hi, lo, hi, lo, pa, pb)
    with pytest.raises(checkify.JaxRuntimeError):
        err.throw()


def test_engine_round_trip_under_checkify():
    """Full spgemm (symbolic + rounds + assembly) under the sanitizer."""
    from spgemm_tpu.utils.gen import random_block_sparse
    from spgemm_tpu.ops.spgemm import spgemm

    rng = np.random.default_rng(3)
    a = random_block_sparse(5, 5, 4, 0.4, rng, "full")
    b = random_block_sparse(5, 5, 4, 0.4, rng, "full")
    # the engine builds its own jitted rounds internally; checkify the
    # observable contract instead: outputs must be finite/in-structure
    got = spgemm(a, b, backend="xla")
    assert got.rows == a.rows and got.cols == b.cols
    assert (got.coords[:, 0] >= 0).all() and (got.coords[:, 1] >= 0).all()
