"""Autotuner (spgemm_tpu/tune): trial planning, preemption, canary
rollout + revert backoff, warm tune-tier round-trip, estimator
adaptation, and the SPGEMM_TPU_TUNE=0 whole-feature A/B -- tier-1 on
the 8-vdev CPU backend."""

import os
import time

import numpy as np
import pytest

from spgemm_tpu.obs import profile as obs_profile
from spgemm_tpu.ops import warmstore
from spgemm_tpu.serve import placement
from spgemm_tpu.serve.daemon import Daemon
from spgemm_tpu.serve.queue import Job
from spgemm_tpu.tune import tuner as tune_mod
from spgemm_tpu.tune.tuner import (BACKOFF0_S, TUNER, TrialPreempted, Tuner,
                                   run_trial_leg, trial_vectors)
from spgemm_tpu.utils import io_text, knobs
from spgemm_tpu.utils.gen import random_chain
from spgemm_tpu.utils.timers import ENGINE


@pytest.fixture(autouse=True)
def _fresh_tune_state():
    """The tuner singleton, the process-global tuned overlay, the
    engine phase accumulators, AND the profiler's span-fed phase
    histograms survive across tests (a daemon pickup swaps the overlay;
    a trial leg accumulates the tune_trial phase a later scrape would
    render): reset every side so each test starts from the untuned
    engine."""
    TUNER.clear()
    TUNER.persist_with(None)
    knobs.clear_tuned()
    placement.clear()
    ENGINE.reset()
    obs_profile.clear()
    yield
    TUNER.clear()
    TUNER.persist_with(None)
    knobs.clear_tuned()
    placement.clear()
    ENGINE.reset()
    obs_profile.clear()


def _chain_folder(tmp_path, n=2, k=2, seed=7, name="tune_in"):
    mats = random_chain(n, 4, k, 0.5, np.random.default_rng(seed), "full")
    folder = str(tmp_path / name)
    io_text.write_chain_dir(folder, mats, k)
    return folder


def _drive_trials(t: Tuner, ck: str, folder: str, winner: dict,
                  base_s: float = 1.0, best_s: float = 0.5) -> None:
    """Walk the class through its whole trial plan with fabricated
    timings: the baseline leg costs base_s, `winner` costs best_s, every
    other candidate slightly worse than baseline.  Digests all match
    (the knobs under trial are bit-identical by construction)."""
    t.note_job(ck, "cpu")
    while True:
        leg = t.next_leg(lambda key: folder)
        if leg is None:
            break
        key, _fld, vec = leg
        secs = base_s if not vec else \
            (best_s if vec == winner else base_s * 1.01)
        t.record_leg(key, vec, secs, "digest-0")


# ------------------------------------------------------------- planning --
def test_trial_vectors_shape():
    legs = trial_vectors("cpu")
    assert legs[0] == {}  # baseline first, always
    names = {k for leg in legs for k in leg}
    # CPU pools never deviate the MXU pair width or the ring overlap:
    # the CPU 'mxu' lowering is an XLA oracle and single-host CPU runs
    # never take the ring, so those legs would time pure noise
    assert names == {"SPGEMM_TPU_ACCUM_ROUTE", "SPGEMM_TPU_ROUND_BATCH"}
    tpu_names = {k for leg in trial_vectors("tpu") for k in leg}
    assert "SPGEMM_TPU_MXU_R" in tpu_names
    assert "SPGEMM_TPU_RING_OVERLAP" in tpu_names
    # every leg is a one-knob deviation (coordinate search, never the
    # cross product)
    assert all(len(leg) <= 1 for leg in trial_vectors("tpu"))


def test_promotion_needs_min_win():
    t = Tuner()
    _drive_trials(t, "ck@cpu", "/nonexistent-ok", winner={}, base_s=1.0)
    st = t.stats()["classes"][0]
    # no candidate beat the baseline: the class settles untuned
    assert st["state"] == "settled" and st["knobs"] == {}
    assert t.overlay_for("ck@cpu") == {}


def test_promotion_and_canary_lifecycle():
    t = Tuner()
    winner = {"SPGEMM_TPU_ACCUM_ROUTE": "dense"}
    _drive_trials(t, "ck@cpu", "/nonexistent-ok", winner=winner)
    st = t.stats()["classes"][0]
    assert st["state"] == "canary" and st["knobs"] == winner
    assert st["win"] == pytest.approx(2.0)
    # canary/live overlays apply; the gate is consumed exactly once
    assert t.overlay_for("ck@cpu") == winner
    assert t.consume_canary("ck@cpu") is True
    assert t.consume_canary("ck@cpu") is False
    t.note_terminal("ck@cpu", ok=True)
    assert t.stats()["classes"][0]["state"] == "live"
    assert t.overlay_for("ck@cpu") == winner


def test_canary_failure_reverts_and_backs_off():
    t = Tuner()
    winner = {"SPGEMM_TPU_ACCUM_ROUTE": "dense"}
    _drive_trials(t, "ck@cpu", "/nonexistent-ok", winner=winner)
    assert t.consume_canary("ck@cpu") is True
    t.note_terminal("ck@cpu", ok=False)
    st = t.stats()["classes"][0]
    assert st["state"] == "reverted"
    assert st["backoff_s"] == BACKOFF0_S
    assert t.overlay_for("ck@cpu") == {}  # the override is gone
    assert t.stats()["reverts"] == 1
    # still parked: no trial leg before the backoff horizon
    assert t.next_leg(lambda key: "/x") is None
    # expire the backoff and fail the canary again: the backoff doubles
    with t._lock:
        t._classes["ck@cpu"].retry_at = time.monotonic() - 1
    _drive_trials(t, "ck@cpu", "/nonexistent-ok", winner=winner)
    assert t.consume_canary("ck@cpu") is True
    t.note_terminal("ck@cpu", ok=False)
    assert t.stats()["classes"][0]["backoff_s"] == 2 * BACKOFF0_S


def test_parity_mismatch_parks_the_class():
    t = Tuner()
    t.note_job("ck@cpu", "cpu")
    leg = t.next_leg(lambda key: "/x")
    assert leg[2] == {}
    t.record_leg("ck@cpu", {}, 1.0, "digest-base")
    key, _f, vec = t.next_leg(lambda key: "/x")
    t.record_leg(key, vec, 0.1, "digest-DIFFERENT")
    st = t.stats()["classes"][0]
    # a candidate that changed the bits is an engine bug: never promote
    # on top of it, park the class in backoff
    assert st["state"] == "reverted" and st["knobs"] == {}
    assert t.stats()["reverts"] == 1


# ----------------------------------------------------------- preemption --
def test_preempted_leg_is_discarded_and_retried():
    t = Tuner()
    t.note_job("ck@cpu", "cpu")

    def preempting_run(folder):
        raise TrialPreempted(folder)

    assert run_trial_leg(preempting_run, lambda key: "/x", tuner=t) is True
    # the leg was discarded, not recorded: the class still owes the same
    # baseline leg, and the overlay is restored
    assert knobs.tuned_overlay() == {}
    assert t.next_leg(lambda key: "/x")[2] == {}
    # and a later quiet window simply re-runs it
    assert run_trial_leg(lambda folder: "d0", lambda key: "/x",
                         tuner=t) is True
    assert t.next_leg(lambda key: "/x")[2] != {}  # baseline landed


def test_trial_failpoint_aborts_leg_without_side_effects(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "tune.trial:1")
    t = Tuner()
    t.note_job("ck@cpu", "cpu")
    ran = []
    assert run_trial_leg(lambda folder: ran.append(folder) or "d0",
                         lambda key: "/x", tuner=t) is True
    # the armed failpoint aborted BEFORE the leg ran anything: no
    # measurement recorded, overlay restored, class unharmed
    assert ran == []
    assert knobs.tuned_overlay() == {}
    assert t.next_leg(lambda key: "/x")[2] == {}
    assert t.stats()["classes"][0]["state"] == "trialing"


def test_daemon_beat_preempts_within_one_heartbeat(tmp_path):
    """The daemon's trial runner yields the device the moment a real job
    is queued: the heartbeat planted between multiplies (and fired once
    before the chain even loads) raises TrialPreempted -- a queued job
    never waits past one multiply boundary on a trial."""
    d = Daemon(str(tmp_path / "t.sock"), journal=False)  # never started
    sl = d.slices[0]
    run = d._tune_run_fn(sl, sl.gen)
    folder = _chain_folder(tmp_path)
    # idle queue: the leg completes and the digest is deterministic
    # (the tuner's parity contract relies on it)
    assert run(folder) == run(folder)
    # a queued job preempts at the FIRST beat, before any multiply
    d.queue.submit(Job("job-t1", folder, str(tmp_path / "out"), {}))
    t0 = time.perf_counter()
    with pytest.raises(TrialPreempted):
        run(folder)
    assert time.perf_counter() - t0 < 1.0


def test_maybe_tune_never_runs_while_pool_busy(tmp_path, monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_TUNE_TRIAL_S", "0.01")
    d = Daemon(str(tmp_path / "t.sock"), journal=False)
    sl = d.slices[0]
    folder = _chain_folder(tmp_path)
    TUNER.note_job("ck@cpu", "cpu")
    placement.note_class("ck@cpu", folder)
    # a busy slice (a real job mid-execute) blocks the trial lane
    sl.current = Job("job-b", folder, str(tmp_path / "o"), {})
    before = TUNER.stats()["trials"]
    d._maybe_tune(sl, sl.gen)
    assert TUNER.stats()["trials"] == before
    sl.current = None
    d._maybe_tune(sl, sl.gen)
    assert TUNER.stats()["trials"] == before + 1


# ------------------------------------------------------ warm store tier --
def test_override_roundtrips_warm_store_across_restart(monkeypatch,
                                                       tmp_path):
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    t = Tuner()
    t.persist_with(warmstore.save_tune)
    winner = {"SPGEMM_TPU_ACCUM_ROUTE": "dense"}
    _drive_trials(t, "ck@cpu", "/x", winner=winner)
    t.consume_canary("ck@cpu")
    t.note_terminal("ck@cpu", ok=True)  # live -> persisted
    assert any(n.startswith("tune-") for n in os.listdir(tmp_path))
    # "restart": a fresh tuner adopts the persisted override verbatim
    warmstore.reset()
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    t2 = Tuner()
    assert t2.load(warmstore.load_tunes()) == 1
    assert t2.overlay_for("ck@cpu") == winner
    assert t2.stats()["classes"][0]["state"] == "live"


def test_canary_record_reauditions_after_restart(monkeypatch, tmp_path):
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    t = Tuner()
    t.persist_with(warmstore.save_tune)
    winner = {"SPGEMM_TPU_ACCUM_ROUTE": "dense"}
    _drive_trials(t, "ck@cpu", "/x", winner=winner)  # canary, unsettled
    warmstore.reset()
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    t2 = Tuner()
    assert t2.load(warmstore.load_tunes()) == 1
    # a daemon that died mid-audition re-runs the canary gate: the
    # override applies, and the first job consumes a fresh canary
    assert t2.stats()["classes"][0]["state"] == "canary"
    assert t2.consume_canary("ck@cpu") is True


def test_knob_vector_skewed_override_is_counted_cold_fallback(monkeypatch,
                                                              tmp_path):
    """A tune record persisted under a different BASE jit-static vector
    (hand-copied dir, changed deployment env) must be refused by the
    envelope check -- counted, never adopted."""
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    t = Tuner()
    t.persist_with(warmstore.save_tune)
    _drive_trials(t, "ck@cpu", "/x",
                  winner={"SPGEMM_TPU_ACCUM_ROUTE": "dense"})
    assert any(n.startswith("tune-") for n in os.listdir(tmp_path))
    warmstore.reset()
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    monkeypatch.setenv("SPGEMM_TPU_MXU_R", "16")  # base vector changed
    assert warmstore.load_tunes() == {}
    assert warmstore.stats()["corrupt"] >= 1


def test_clear_tunes_leaves_plans(monkeypatch, tmp_path):
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    t = Tuner()
    t.persist_with(warmstore.save_tune)
    _drive_trials(t, "ck@cpu", "/x",
                  winner={"SPGEMM_TPU_ACCUM_ROUTE": "dense"})
    (tmp_path / "plan-deadbeef.npz").write_bytes(b"not-a-real-plan")
    warmstore.reset()
    removed = warmstore.clear_tunes(str(tmp_path))
    assert removed == 1
    names = os.listdir(tmp_path)
    assert not any(n.startswith("tune-") for n in names)
    assert "plan-deadbeef.npz" in names  # the plan tier is untouched


# ------------------------------------------------- estimator adaptation --
def test_est_adaptation_tight_class_shrinks_sample_budget():
    t = Tuner()
    t.note_job("ck@cpu", "cpu")
    for _ in range(tune_mod.EST_MIN_JOBS):
        t.note_est_accuracy("ck@cpu", 0.01)
    ov = t.overlay_for("ck@cpu")
    assert ov["SPGEMM_TPU_EST_SAMPLE_ROWS"] == "24"  # default 48 halved
    # repeated tight windows keep halving down to the floor, never below
    for _ in range(10 * tune_mod.EST_MIN_JOBS):
        t.note_est_accuracy("ck@cpu", 0.01)
    floor = max(tune_mod.EST_ROWS_FLOOR, 1)
    assert int(t.overlay_for("ck@cpu")["SPGEMM_TPU_EST_SAMPLE_ROWS"]) \
        >= floor


def test_est_adaptation_misfiring_class_raises_confidence():
    t = Tuner()
    t.note_job("ck@cpu", "cpu")
    for _ in range(tune_mod.EST_MIN_JOBS):
        t.note_est_accuracy("ck@cpu", 0.9)
    ov = t.overlay_for("ck@cpu")
    assert float(ov["SPGEMM_TPU_EST_CONFIDENCE"]) == pytest.approx(0.7)
    # capped at 1.0 however often the class misfires
    for _ in range(10 * tune_mod.EST_MIN_JOBS):
        t.note_est_accuracy("ck@cpu", 0.9)
    assert float(t.overlay_for("ck@cpu")["SPGEMM_TPU_EST_CONFIDENCE"]) \
        <= 1.0


# ------------------------------------------------------------ TUNE=0 A/B --
def test_tune_off_is_inert_everywhere(monkeypatch, tmp_path):
    monkeypatch.setenv("SPGEMM_TPU_TUNE", "0")
    t = Tuner()
    t.note_job("ck@cpu", "cpu")  # gated: no class is even created
    assert t.stats()["classes"] == []
    assert t.overlay_for("ck@cpu") == {}
    assert t.consume_canary("ck@cpu") is False
    assert run_trial_leg(lambda folder: "d0", lambda key: "/x",
                         tuner=t) is False
    d = Daemon(str(tmp_path / "t.sock"), journal=False)
    d.start()
    try:
        scrape = d._op_metrics()["text"]
        assert "spgemm_tune" not in scrape
        assert "tune_trial" not in scrape and "tune_apply" not in scrape
        assert d._op_stats()["tune"]["enabled"] is False
    finally:
        d.stop()


def test_tune_enabled_idle_daemon_scrape_unchanged(tmp_path):
    """Tuning ON but never contacted: the scrape must stay byte-free of
    every tune family (count-0 gating -- the surface only grows once a
    class exists)."""
    d = Daemon(str(tmp_path / "t.sock"), journal=False)
    d.start()
    try:
        scrape = d._op_metrics()["text"]
        assert "spgemm_tune" not in scrape
    finally:
        d.stop()


# ----------------------------------------------------- daemon trial lane --
def test_daemon_idle_trials_settle_a_seeded_class(tmp_path, monkeypatch):
    """End-to-end trial lane on the live daemon: a seeded class's legs
    run on idle ticks (real chain_product on the CPU backend) and the
    class leaves the trialing state on its own -- every leg bit-exact
    (a parity mismatch would park it as reverted and fail the state
    assertion below)."""
    monkeypatch.setenv("SPGEMM_TPU_TUNE_TRIAL_S", "0.01")
    folder = _chain_folder(tmp_path)
    d = Daemon(str(tmp_path / "t.sock"), journal=False)
    d.start()
    try:
        TUNER.note_job("ck@cpu", "cpu")
        placement.note_class("ck@cpu", folder)
        deadline = time.time() + 60
        while time.time() < deadline:
            rows = TUNER.stats()["classes"]
            if rows and rows[0]["state"] in ("settled", "canary"):
                break
            time.sleep(0.05)
        rows = TUNER.stats()["classes"]
        assert rows and rows[0]["state"] in ("settled", "canary"), rows
        assert TUNER.stats()["trials"] >= len(trial_vectors("cpu"))
        # the scrape now carries the tune families iff an override exists
        stats = d._op_stats()
        assert stats["tune"]["classes"]
    finally:
        d.stop()
