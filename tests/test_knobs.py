"""Central knob registry (spgemm_tpu/utils/knobs.py): typed validated
accessors, live snapshot, the `spgemm_tpu.cli knobs` subcommand, and the
generated-docs helpers the DOC lint rule consumes."""

import json

import pytest

from spgemm_tpu.cli import run
from spgemm_tpu.utils import knobs


def test_defaults_when_unset(monkeypatch):
    for name in knobs.REGISTRY:
        monkeypatch.delenv(name, raising=False)
    assert knobs.get("SPGEMM_TPU_VPU_ALGO") == "colbcast"
    assert knobs.get("SPGEMM_TPU_VPU_PB") == 1
    assert knobs.get("SPGEMM_TPU_ROUND_BATCH") is True
    assert knobs.get("SPGEMM_TPU_DCN_CHUNK_MB") == 64.0
    assert knobs.get("SPGEMM_TPU_HYBRID_GATE") is None       # platform-dep
    assert knobs.get("SPGEMM_TPU_DCN_HEARTBEAT_S") is None   # jax default
    assert knobs.get("SPGEMM_TPU_NO_NATIVE") is False        # flag
    assert knobs.source("SPGEMM_TPU_VPU_ALGO") == "default"


def test_env_values_parse_typed(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_VPU_PB", "4")
    monkeypatch.setenv("SPGEMM_TPU_DCN_CHUNK_MB", "0.5")
    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", "0")
    monkeypatch.setenv("SPGEMM_TPU_NO_NATIVE", "1")
    assert knobs.get("SPGEMM_TPU_VPU_PB") == 4
    assert knobs.get("SPGEMM_TPU_DCN_CHUNK_MB") == 0.5
    assert knobs.get("SPGEMM_TPU_RING_OVERLAP") is False
    assert knobs.get("SPGEMM_TPU_NO_NATIVE") is True
    assert knobs.source("SPGEMM_TPU_VPU_PB") == "env"


def test_whitespace_and_empty_fall_back_to_default(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_DCN_CHUNK_MB", "  ")
    assert knobs.get("SPGEMM_TPU_DCN_CHUNK_MB") == 64.0
    assert knobs.source("SPGEMM_TPU_DCN_CHUNK_MB") == "default"
    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", " 0 ")  # stripped
    assert knobs.get("SPGEMM_TPU_RING_OVERLAP") is False


@pytest.mark.parametrize("name,bad", [
    ("SPGEMM_TPU_ROUND_BATCH", "yes"),
    ("SPGEMM_TPU_RING_OVERLAP", "2"),
    ("SPGEMM_TPU_VPU_ALGO", "bogus"),
    ("SPGEMM_TPU_VPU_PB", "zero"),
    ("SPGEMM_TPU_VPU_PB", "0"),
    ("SPGEMM_TPU_OOC_DEPTH", "0"),
    ("SPGEMM_TPU_DCN_CHUNK_MB", "-1"),
    ("SPGEMM_TPU_DCN_CHUNK_MB", "lots"),
    ("SPGEMM_TPU_HYBRID_GATE", "maybe"),
    ("SPGEMM_TPU_SERVE_TENANT_INFLIGHT", "0"),
    ("SPGEMM_TPU_SERVE_TENANT_INFLIGHT", "many"),
])
def test_invalid_values_raise_naming_the_knob(monkeypatch, name, bad):
    """The round-5 contract ('a documented knob that crashes later' trap):
    invalid values raise immediately and the message names the knob."""
    monkeypatch.setenv(name, bad)
    with pytest.raises(ValueError, match=name):
        knobs.get(name)


def test_unregistered_name_is_a_keyerror():
    with pytest.raises(KeyError):
        knobs.get("SPGEMM_TPU_NOT_A_KNOB")


def test_snapshot_covers_registry(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_MXU_R", "16")
    rows = {r["name"]: r for r in knobs.snapshot()}
    assert set(rows) == set(knobs.REGISTRY)
    assert rows["SPGEMM_TPU_MXU_R"]["value"] == "16"
    assert rows["SPGEMM_TPU_MXU_R"]["source"] == "env"
    assert rows["SPGEMM_TPU_MXU_R"]["default"] == "8"
    assert rows["SPGEMM_TPU_VPU_ALGO"]["jit_static"] is True


def test_cli_knobs_subcommand(capsys, monkeypatch):
    """`spgemm_tpu.cli knobs`: every knob listed with value + source."""
    monkeypatch.setenv("SPGEMM_TPU_OOC_DEPTH", "3")
    assert run(["knobs"]) == 0
    out = capsys.readouterr().out
    for name in knobs.REGISTRY:
        assert name in out
    assert "(env, default 2)" in out  # the overridden OOC depth row


def test_cli_knobs_subcommand_json(capsys, monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", "0")
    assert run(["knobs", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    rows = {r["name"]: r for r in report["knobs"]}
    assert set(rows) == set(knobs.REGISTRY)
    row = rows["SPGEMM_TPU_RING_OVERLAP"]
    assert row["value"] == "0" and row["source"] == "env"
    # plan-cache live stats ride next to the knob rows, so the whole-engine
    # A/B (SPGEMM_TPU_PLAN_AHEAD=0|2) is inspectable without a bench run
    cache = report["plan_cache"]
    assert {"hits", "misses", "entries", "capacity", "enabled"} <= set(cache)
    assert cache["capacity"] == 32  # the registry default


def test_cli_knobs_json_reports_cache_activity(capsys):
    """The stats are LIVE: in-process cache traffic shows up in the same
    listing a harness would read."""
    from spgemm_tpu.ops import plancache

    plancache.clear()
    key = plancache.fingerprint(
        __import__("numpy").zeros((2, 2), "int64"),
        __import__("numpy").ones((2, 2), "int64"), meta=("t",))
    assert plancache.lookup(key) is None  # one miss
    plancache.store(key, object())
    assert plancache.lookup(key) is not None  # one hit
    assert run(["knobs", "--json"]) == 0
    cache = json.loads(capsys.readouterr().out)["plan_cache"]
    assert cache["hits"] == 1 and cache["misses"] == 1
    assert cache["entries"] == 1
    plancache.clear()


def test_snapshot_survives_invalid_values(monkeypatch):
    """Auditing a MISCONFIGURED session is the listing's whole point: an
    invalid env value becomes a per-row error, never an aborted listing
    (get() at the consuming call site stays strict)."""
    monkeypatch.setenv("SPGEMM_TPU_VPU_PB", "bad")
    rows = {r["name"]: r for r in knobs.snapshot()}
    assert set(rows) == set(knobs.REGISTRY)  # every knob still listed
    row = rows["SPGEMM_TPU_VPU_PB"]
    assert row["value"].startswith("INVALID")
    assert "SPGEMM_TPU_VPU_PB" in row["error"]
    assert "error" not in rows["SPGEMM_TPU_MXU_R"]


def test_cli_knobs_survives_invalid_values(capsys, monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", "maybe")
    assert run(["knobs"]) == 0
    out = capsys.readouterr().out
    assert "INVALID" in out and "SPGEMM_TPU_RING_OVERLAP must be" in out
    assert "SPGEMM_TPU_FORCE_1MROW" in out  # later rows still printed


def test_cli_knobs_folder_keeps_old_meaning(tmp_path, monkeypatch, capsys):
    """A pre-existing input directory named `knobs` must still run the
    chain product -- the subcommand only fires when no such dir exists."""
    import numpy as np

    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.gen import random_chain

    rng = np.random.default_rng(7)
    mats = random_chain(2, 4, 2, 0.5, rng, "small")
    io_text.write_chain_dir(str(tmp_path / "knobs"), mats, 2)
    monkeypatch.chdir(tmp_path)
    assert run(["knobs"]) == 0
    assert "time taken " in capsys.readouterr().out  # the chain ran
    assert (tmp_path / "matrix").exists()


def test_cli_knobs_scratch_dir_does_not_swallow_subcommand(
        tmp_path, monkeypatch, capsys):
    """Only an INPUT dir (with the reference `size` file) disambiguates to
    the matrix driver; an unrelated knobs/ scratch dir must not."""
    (tmp_path / "knobs").mkdir()  # no `size` file inside
    monkeypatch.chdir(tmp_path)
    assert run(["knobs"]) == 0
    assert "SPGEMM_TPU_VPU_ALGO" in capsys.readouterr().out


def test_knob_table_lists_every_knob():
    table = knobs.knob_table_md()
    for name in knobs.REGISTRY:
        assert f"`{name}`" in table


def test_consumers_read_through_registry(monkeypatch):
    """Spot-check the migrated call sites: the registry value actually
    drives the engine predicates (not a stale copy of the old parsing)."""
    from spgemm_tpu.ops.spgemm import round_batch_enabled
    from spgemm_tpu.parallel.ring import overlap_enabled

    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "0")
    assert round_batch_enabled() is False
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "1")
    assert round_batch_enabled() is True
    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", "0")
    assert overlap_enabled() is False


def test_pin_unless_exported(monkeypatch):
    """The one harness-pin idiom (cli.run / bench.py / benchmarks/run.py):
    an exported value always wins; otherwise the pin lands and restore()
    removes it cleanly (and is safe to call twice)."""
    monkeypatch.delenv("SPGEMM_TPU_DELTA", raising=False)
    restore = knobs.pin_unless_exported("SPGEMM_TPU_DELTA", "0")
    assert knobs.get("SPGEMM_TPU_DELTA") is False
    assert knobs.source("SPGEMM_TPU_DELTA") == "env"
    restore()
    restore()  # idempotent
    assert knobs.source("SPGEMM_TPU_DELTA") == "default"
    assert knobs.get("SPGEMM_TPU_DELTA") is True
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    noop = knobs.pin_unless_exported("SPGEMM_TPU_DELTA", "0")
    assert knobs.get("SPGEMM_TPU_DELTA") is True  # exported value wins
    noop()
    assert knobs.get("SPGEMM_TPU_DELTA") is True
