"""SLO engine (obs/slo.py): fixed-bucket quantile digests, rolling-window
age-out, multi-window burn-rate transitions (slo_burn / slo_burn_clear
events), accounting-only mode, the tenant-cardinality bound, scrape-family
rendering, and the SPGEMM_TPU_OBS_TRACE inertness contract."""

import pytest

from spgemm_tpu.obs import events, metrics, slo


@pytest.fixture
def engine():
    return slo.SloEngine()


@pytest.fixture(autouse=True)
def clean_event_log():
    events.LOG.clear()
    yield
    events.LOG.clear()


def _arm(monkeypatch, target="1", error_pct="10", window="120"):
    monkeypatch.setenv("SPGEMM_TPU_SLO_TARGET_S", target)
    monkeypatch.setenv("SPGEMM_TPU_SLO_ERROR_PCT", error_pct)
    monkeypatch.setenv("SPGEMM_TPU_SLO_WINDOW_S", window)


def _burn_kinds():
    return [r["kind"] for r in events.LOG.tail(100)
            if r["kind"].startswith("slo_burn")]


# ------------------------------------------------------------ quantiles --
def test_quantiles_from_fixed_bucket_digest(engine):
    """p50/p95/p99 come from the digest's bucket bounds, never a sample
    list: a bimodal 50/50 mix reports the low mode's bound at p50 and
    the high mode's at p95/p99."""
    for i in range(50):
        engine.observe("t", "s0", 0.02, 0.0, False, now=1000.0 + i)
    for i in range(50):
        engine.observe("t", "s0", 3.0, 0.0, False, now=1050.0 + i)
    row = engine.report(now=1100.0)["tenants"]["t"]
    assert row["jobs"] == 100 and row["errors"] == 0
    lat = row["latency_s"]
    assert lat["p50"] == 0.025   # first bound covering the low mode
    assert lat["p95"] == 5.0     # first bound covering the high mode
    assert lat["p99"] == 5.0
    assert row["error_ratio"] == 0.0


def test_queue_wait_share_and_slice_merge(engine):
    """Per-tenant accounts merge the tenant's slices (digests add);
    queue-wait share is queued / (queued + execute) seconds."""
    engine.observe("t", "s0", 0.9, 0.1, False, now=10.0)
    engine.observe("t", "s1", 0.9, 0.1, False, now=11.0)
    rep = engine.report(now=12.0)
    row = rep["tenants"]["t"]
    assert row["jobs"] == 2
    assert row["queue_wait_share"] == pytest.approx(0.1)
    # both (tenant, slice) windows exist for burn accounting
    assert {(b["tenant"], b["slice"]) for b in rep["burn"]} == \
        {("t", "s0"), ("t", "s1")}


def test_window_ages_out_records(engine, monkeypatch):
    _arm(monkeypatch, window="100")
    engine.observe("t", "s0", 0.1, 0.0, False, now=0.0)
    engine.observe("t", "s0", 0.1, 0.0, False, now=99.0)
    assert engine.report(now=99.5)["tenants"]["t"]["jobs"] == 2
    # past the window the old record ages out; past both, the tenant
    # row disappears (no in-window records)
    assert engine.report(now=150.0)["tenants"]["t"]["jobs"] == 1
    assert "t" not in engine.report(now=500.0)["tenants"]


# ------------------------------------------------------------ burn rate --
def test_burn_activates_and_emits_event_with_trace(engine, monkeypatch):
    """The acceptance shape: bad fraction over budget in BOTH windows
    flips the burn state once and emits one slo_burn event carrying the
    newest bad record's trace context."""
    _arm(monkeypatch, error_pct="10", window="120")
    for i in range(8):
        engine.observe("t", "s0", 0.1, 0.0, False, now=1000.0 + i)
    engine.observe("t", "s0", 0.1, 0.0, True, trace_id="aa" * 16,
                   now=1008.0)
    engine.observe("t", "s0", 0.1, 0.0, True, trace_id="bb" * 16,
                   now=1009.0)
    rep = engine.report(now=1010.0)
    (burn,) = rep["burn"]
    assert burn["active"] is True
    assert burn["trace_id"] == "bb" * 16   # the NEWEST bad record
    assert burn["bad"] == 2 and burn["jobs"] == 10
    # bad_frac 0.2 over a 0.1 budget = burn 2.0 in both windows
    assert burn["slow_burn"] == pytest.approx(2.0)
    assert burn["fast_burn"] == pytest.approx(2.0)
    recs = [r for r in events.LOG.tail(100) if r["kind"] == "slo_burn"]
    assert len(recs) == 1   # a transition, not one event per record
    assert recs[0]["tenant"] == "t" and recs[0]["slice"] == "s0"
    # the event fired at the record that CROSSED the budget (the first
    # bad job: 1/9 > 10%), carrying that record's trace; the live burn
    # detail above tracks the newest bad record as the window rolls
    assert recs[0]["trace_id"] == "aa" * 16
    assert rep["burn_active"] == 1


def test_burn_clears_when_bad_records_age_out(engine, monkeypatch):
    _arm(monkeypatch, error_pct="10", window="100")
    engine.observe("t", "s0", 0.1, 0.0, True, trace_id="aa" * 16,
                   now=1000.0)
    assert engine.report(now=1001.0)["burn"][0]["active"] is True
    # the bad record ages out of the window: the burn clears and the
    # clear is an event (alert lifecycle, not a sticky flag)
    assert engine.report(now=1200.0)["burn"][0]["active"] is False
    assert _burn_kinds() == ["slo_burn", "slo_burn_clear"]


def test_fast_window_gates_stale_burns(engine, monkeypatch):
    """The multi-window AND: old bad events alone (outside the fast
    window) must not page -- the budget is burning only if it is
    burning NOW too."""
    _arm(monkeypatch, error_pct="10", window="120")  # fast window: 10 s
    engine.observe("t", "s0", 0.1, 0.0, True, now=1000.0)
    engine.observe("t", "s0", 0.1, 0.0, True, now=1001.0)
    for i in range(3):
        # recent good records: the fast window sees only these
        engine.observe("t", "s0", 0.1, 0.0, False, now=1100.0 + i)
    (burn,) = engine.report(now=1103.0)["burn"]
    assert burn["active"] is False
    assert burn["slow_burn"] >= 1.0 and burn["fast_burn"] == 0.0
    # the bad-only spike at t=1000 burned (both windows agreed then);
    # once the fast window runs clean the burn must CLEAR even though
    # the slow window is still over budget
    assert _burn_kinds()[-1] == "slo_burn_clear"


def test_latency_target_makes_slow_jobs_bad(engine, monkeypatch):
    """A job slower than SPGEMM_TPU_SLO_TARGET_S burns budget without
    any error flag -- the latency objective IS an objective."""
    _arm(monkeypatch, target="1", error_pct="10", window="120")
    engine.observe("t", "s0", 5.0, 0.0, False, trace_id="cc" * 16,
                   now=1000.0)
    (burn,) = engine.report(now=1001.0)["burn"]
    assert burn["active"] is True and burn["trace_id"] == "cc" * 16


def test_unset_objectives_mean_accounting_only(engine, monkeypatch):
    monkeypatch.delenv("SPGEMM_TPU_SLO_TARGET_S", raising=False)
    for i in range(5):
        engine.observe("t", "s0", 30.0, 0.0, True, now=1000.0 + i)
    rep = engine.report(now=1005.0)
    assert rep["objectives"]["enabled"] is False
    # the accounting still renders...
    assert rep["tenants"]["t"]["error_ratio"] == 1.0
    # ...but nothing ever burns and no alert event fires
    assert all(not b["active"] for b in rep["burn"])
    assert _burn_kinds() == []


# ------------------------------------------------------ cardinality bound --
def test_tenant_eviction_is_topk_by_recency_and_counted(engine,
                                                        monkeypatch):
    monkeypatch.setattr(slo, "TENANT_RETAIN", 3)
    for i in range(6):
        engine.observe(f"t{i}", "s0", 0.1, 0.0, False, now=1000.0 + i)
    rep = engine.report(now=1010.0)
    assert set(rep["tenants"]) == {"t3", "t4", "t5"}  # newest keep
    assert rep["tenants_evicted"] == 3
    # a re-seen tenant is recency-bumped, not re-evicted
    engine.observe("t3", "s0", 0.1, 0.0, False, now=1011.0)
    engine.observe("t9", "s0", 0.1, 0.0, False, now=1012.0)
    rep = engine.report(now=1013.0)
    assert "t3" in rep["tenants"] and "t4" not in rep["tenants"]
    # the scrape stays bounded with it
    labels = {lbl["tenant"] for fam, lbl, _v in engine.samples(now=1013.0)
              if fam == "spgemm_slo_error_ratio"}
    assert len(labels) <= 3


def test_evicting_a_burning_tenant_clears_its_alert(engine, monkeypatch):
    """An alert consumer pairs slo_burn with slo_burn_clear: eviction of
    a tenant whose window is actively burning must close the lifecycle,
    never leave a phantom open alert."""
    monkeypatch.setattr(slo, "TENANT_RETAIN", 2)
    _arm(monkeypatch, error_pct="10", window="120")
    engine.observe("a", "s0", 0.1, 0.0, True, trace_id="aa" * 16,
                   now=1000.0)  # tenant a burns
    assert _burn_kinds() == ["slo_burn"]
    engine.observe("b", "s0", 0.1, 0.0, False, now=1001.0)
    engine.observe("c", "s0", 0.1, 0.0, False, now=1002.0)  # evicts a
    assert _burn_kinds() == ["slo_burn", "slo_burn_clear"]
    recs = [r for r in events.LOG.tail(100)
            if r["kind"] == "slo_burn_clear"]
    assert recs[0]["tenant"] == "a"
    assert recs[0]["reason"] == "tenant-evicted"
    assert engine.report(now=1003.0)["tenants_evicted"] == 1


def test_record_ring_is_bounded(engine, monkeypatch):
    monkeypatch.setattr(slo, "RECORD_RETAIN", 16)
    for i in range(100):
        engine.observe("t", "s0", 0.1, 0.0, False, now=1000.0 + i * 1e-3)
    assert engine.report(now=1001.0)["tenants"]["t"]["jobs"] == 16


# ------------------------------------------------------------- rendering --
def test_samples_render_through_the_registry(engine, monkeypatch):
    _arm(monkeypatch)
    engine.observe("t", "s0", 0.1, 0.05, True, now=1000.0)
    text = metrics.render(engine.samples(now=1001.0))
    assert 'spgemm_slo_latency_seconds{quantile="0.5",tenant="t"}' in text
    assert 'spgemm_slo_error_ratio{tenant="t"} 1' in text
    assert 'spgemm_slo_queue_wait_share{tenant="t"}' in text
    assert 'spgemm_slo_burn_active{slice="s0",tenant="t"} 1' in text
    assert "spgemm_slo_tenants_evicted_total 0" in text


# ------------------------------------------------------------- inertness --
def test_master_knob_zero_makes_engine_inert(engine, monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_OBS_TRACE", "0")
    _arm(monkeypatch)
    engine.observe("t", "s0", 99.0, 0.0, True, now=1000.0)
    rep = engine.report(now=1001.0)
    assert rep["records"] == 0 and rep["tenants"] == {}
    assert rep["burn"] == [] and _burn_kinds() == []
