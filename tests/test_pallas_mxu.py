"""Pallas-grid MXU limb kernel (ops/pallas_mxu.py) vs the XLA limb oracle.

The XLA formulation (ops/mxu_spgemm.py) is property-tested against the
numpy/oracle semantics in tests/test_mxu.py; here the Pallas kernel is
cross-checked bit-for-bit against it, in interpret mode (CPU CI).

The split pinned by test_fold_outside_kernel_matches_combine is
load-bearing: composing the carry-normalize + pack stages after the piece
sums INSIDE one Mosaic kernel miscompiles on the current toolchain (bisected
empirically on hardware -- each stage is bit-exact in isolation, the fused
graph is not), so numeric_round_mxu_pallas ends the kernel at the carry-free
piece sums and folds outside.  If fold_piece_sums is ever moved back into
the kernel, re-run the hardware parity smoke (bench.py detail.tpu_parity or
benchmarks/run.py cage12 --backend mxu) before trusting it.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from spgemm_tpu.ops import u64  # noqa: E402
from spgemm_tpu.ops.mxu_spgemm import N_LIMBS, _combine_mod_m, numeric_round_mxu  # noqa: E402
from spgemm_tpu.ops.pallas_mxu import (  # noqa: E402
    _piece_sums, fold_piece_sums, numeric_round_mxu_pallas)


def test_fold_outside_kernel_matches_combine():
    """piece-sums + outside fold == the proven XLA diagonal fold."""
    rng = np.random.default_rng(0)
    k = 8
    # realistic int32 magnitudes: limb products summed over up to P*k terms
    S = rng.integers(0, 127 * 127 * 1024, size=(N_LIMBS * k, N_LIMBS * k),
                     dtype=np.int64).astype(np.int32)
    limbs = _piece_sums(jnp.asarray(S), k)
    got_h, got_l = fold_piece_sums(limbs)
    want_h, want_l = _combine_mod_m(jnp.asarray(S)[None], k)
    assert np.array_equal(np.asarray(got_h), np.asarray(want_h)[0])
    assert np.array_equal(np.asarray(got_l), np.asarray(want_l)[0])


@pytest.mark.parametrize("k,K,P", [(2, 3, 1), (4, 5, 7), (8, 9, 16), (8, 2, 3)])
def test_kernel_matches_xla_mxu(k, K, P):
    rng = np.random.default_rng(100 * k + K + P)
    nnzb = 11
    tiles = rng.integers(0, 1 << 64, size=(nnzb + 1, k, k), dtype=np.uint64)
    tiles[-1] = 0  # sentinel zero tile
    hi, lo = u64.u64_to_hilo(tiles)
    hi, lo = jnp.asarray(hi), jnp.asarray(lo)
    # pair lists with sentinel padding mixed in
    pa = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    pb = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))

    want_h, want_l = numeric_round_mxu(hi, lo, hi, lo, pa, pb)
    got_h, got_l = numeric_round_mxu_pallas(hi, lo, hi, lo, pa, pb,
                                            interpret=True)
    assert np.array_equal(np.asarray(want_h), np.asarray(got_h))
    assert np.array_equal(np.asarray(want_l), np.asarray(got_l))


def test_kernel_all_sentinel_rows_are_zero():
    """A key whose pair list is entirely padding must produce the zero tile
    (field mode: 0 * x == 0, 0 + 0 == 0)."""
    k, nnzb = 4, 3
    rng = np.random.default_rng(7)
    tiles = rng.integers(0, 1 << 64, size=(nnzb + 1, k, k), dtype=np.uint64)
    tiles[-1] = 0
    hi, lo = u64.u64_to_hilo(tiles)
    hi, lo = jnp.asarray(hi), jnp.asarray(lo)
    sent = np.int32(nnzb)
    pa = jnp.asarray(np.array([[sent, sent], [0, 1]], np.int32))
    pb = jnp.asarray(np.array([[sent, sent], [1, 2]], np.int32))
    got_h, got_l = numeric_round_mxu_pallas(hi, lo, hi, lo, pa, pb,
                                            interpret=True)
    assert not np.asarray(got_h)[0].any()
    assert not np.asarray(got_l)[0].any()
    want_h, want_l = numeric_round_mxu(hi, lo, hi, lo, pa, pb)
    assert np.array_equal(np.asarray(want_h), np.asarray(got_h))
    assert np.array_equal(np.asarray(want_l), np.asarray(got_l))


def test_pair_padding_to_block_multiple():
    """P not a multiple of the pair-block width R exercises the wrapper's
    sentinel padding of the pair axis."""
    k, nnzb, K, P = 8, 9, 4, 11  # R = 8 -> P padded to 16
    rng = np.random.default_rng(3)
    tiles = rng.integers(0, 1 << 64, size=(nnzb + 1, k, k), dtype=np.uint64)
    tiles[-1] = 0
    hi, lo = u64.u64_to_hilo(tiles)
    hi, lo = jnp.asarray(hi), jnp.asarray(lo)
    pa = jnp.asarray(rng.integers(0, nnzb, size=(K, P), dtype=np.int32))
    pb = jnp.asarray(rng.integers(0, nnzb, size=(K, P), dtype=np.int32))
    want_h, want_l = numeric_round_mxu(hi, lo, hi, lo, pa, pb)
    got_h, got_l = numeric_round_mxu_pallas(hi, lo, hi, lo, pa, pb,
                                            interpret=True)
    assert np.array_equal(np.asarray(want_h), np.asarray(got_h))
    assert np.array_equal(np.asarray(want_l), np.asarray(got_l))


@pytest.mark.parametrize("pair_width", [1, 3, 16, 128])
def test_pair_width_ladder_bit_identical(pair_width):
    """Any requested R (clamped to the bf16-exactness cap 1024/k) must be
    bit-identical to the default -- field mode is associative, so the
    R-grouping of the int32 accumulation is semantics-free."""
    k, nnzb, K, P = 8, 9, 4, 21
    rng = np.random.default_rng(pair_width)
    tiles = rng.integers(0, 1 << 64, size=(nnzb + 1, k, k), dtype=np.uint64)
    tiles[-1] = 0
    hi, lo = u64.u64_to_hilo(tiles)
    hi, lo = jnp.asarray(hi), jnp.asarray(lo)
    pa = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    pb = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    want_h, want_l = numeric_round_mxu_pallas(hi, lo, hi, lo, pa, pb,
                                              interpret=True)
    got_h, got_l = numeric_round_mxu_pallas(hi, lo, hi, lo, pa, pb,
                                            interpret=True,
                                            pair_width=pair_width)
    assert np.array_equal(np.asarray(want_h), np.asarray(got_h))
    assert np.array_equal(np.asarray(want_l), np.asarray(got_l))


@pytest.mark.parametrize("limbs,pair_width", [(10, None), (3, None), (3, 16)])
def test_raw_epilogue_bit_identical(limbs, pair_width):
    """raw_epilogue=True (no in-kernel piece sums; batched XLA epilogue)
    must be bit-identical to the in-kernel epilogue at any limb grid and
    pair width -- same weights, same carry-free bound, different venue."""
    k, nnzb, K, P = 8, 9, 5, 13
    rng = np.random.default_rng(limbs + (pair_width or 0))
    bound = (1 << (7 * limbs)) - 1 if limbs < 10 else (1 << 64) - 1
    tiles = (rng.integers(0, 1 << 64, size=(nnzb + 1, k, k), dtype=np.uint64)
             % np.uint64(bound))
    tiles[-1] = 0
    hi, lo = u64.u64_to_hilo(tiles)
    hi, lo = jnp.asarray(hi), jnp.asarray(lo)
    pa = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    pb = jnp.asarray(rng.integers(0, nnzb + 1, size=(K, P), dtype=np.int32))
    kw = {"a_limbs": limbs, "b_limbs": limbs, "pair_width": pair_width}
    want_h, want_l = numeric_round_mxu_pallas(hi, lo, hi, lo, pa, pb,
                                              interpret=True, **kw)
    got_h, got_l = numeric_round_mxu_pallas(hi, lo, hi, lo, pa, pb,
                                            interpret=True,
                                            raw_epilogue=True, **kw)
    assert np.array_equal(np.asarray(want_h), np.asarray(got_h))
    assert np.array_equal(np.asarray(want_l), np.asarray(got_l))


@pytest.mark.parametrize("bits_a,bits_b", [(32, 32), (14, 64), (7, 7), (50, 21)])
def test_adaptive_limb_counts(bits_a, bits_b):
    """Bounded operands with shrunk limb grids must match the full 10x10."""
    from spgemm_tpu.ops.pallas_mxu import limbs_for_bound

    k, nnzb, K, P = 4, 7, 3, 5
    rng = np.random.default_rng(bits_a * 100 + bits_b)
    a_t = rng.integers(0, 1 << bits_a, size=(nnzb + 1, k, k), dtype=np.uint64)
    b_t = rng.integers(0, 1 << bits_b, size=(nnzb + 1, k, k), dtype=np.uint64)
    a_t[-1] = 0
    b_t[-1] = 0
    ah, al = map(jnp.asarray, u64.u64_to_hilo(a_t))
    bh, bl = map(jnp.asarray, u64.u64_to_hilo(b_t))
    pa = jnp.asarray(rng.integers(0, nnzb, size=(K, P), dtype=np.int32))
    pb = jnp.asarray(rng.integers(0, nnzb, size=(K, P), dtype=np.int32))

    La = limbs_for_bound((1 << bits_a) - 1)
    Lb = limbs_for_bound((1 << bits_b) - 1)
    assert La == -(-bits_a // 7) or bits_a >= 64
    want = numeric_round_mxu(ah, al, bh, bl, pa, pb)
    got = numeric_round_mxu_pallas(ah, al, bh, bl, pa, pb, interpret=True,
                                   a_limbs=La, b_limbs=Lb)
    assert np.array_equal(np.asarray(want[0]), np.asarray(got[0]))
    assert np.array_equal(np.asarray(want[1]), np.asarray(got[1]))


def test_limbs_for_bound():
    from spgemm_tpu.ops.pallas_mxu import limbs_for_bound

    assert limbs_for_bound(None) == 10
    assert limbs_for_bound((1 << 64) - 2) == 10
    assert limbs_for_bound((1 << 32) - 1) == 5
    assert limbs_for_bound(127) == 1
    assert limbs_for_bound(128) == 2
    assert limbs_for_bound(0) == 1
