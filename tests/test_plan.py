"""Planner pipeline (PR 4 tentpole): the plan/execute split of
ops/spgemm.spgemm_device, the structure-keyed plan cache (ops/plancache),
and chain.py's bounded plan-ahead worker.

The standing contracts:
  * plan() + execute() == the legacy inline path, bit-for-bit, on every
    backend (planning is deterministic; dispatch order is unchanged);
  * SPGEMM_TPU_PLAN_AHEAD=0 and >0 produce identical bits AND identical
    dispatch counts on a chain;
  * a cache hit returns the SAME plan object and skips the join entirely;
  * planning is host-pure when backend/platform are passed resolved (the
    BKD worker-thread contract).
"""

import numpy as np
import pytest

from spgemm_tpu.chain import chain_product
from spgemm_tpu.ops import plancache
from spgemm_tpu.ops.spgemm import execute, plan, spgemm, spgemm_device
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_block_sparse, random_chain
from spgemm_tpu.utils.semantics import chain_oracle, spgemm_oracle
from spgemm_tpu.utils.timers import ENGINE


def _oracle(a, b):
    return BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))


@pytest.fixture(autouse=True)
def _fresh_cache():
    plancache.clear()
    yield
    plancache.clear()


# ------------------------------------------------------- plan/execute split


@pytest.mark.parametrize("backend", ["xla", "hybrid"])
def test_plan_execute_matches_inline_and_oracle(backend, monkeypatch):
    """Explicit plan() + execute() == spgemm() == the oracle on
    adversarial (fold-order-sensitive) values."""
    rng = np.random.default_rng(101 + len(backend))
    a = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    b = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    p = plan(a, b, backend=backend, platform="cpu")
    got = execute(p, a, b).to_host()
    inline = spgemm(a, b, backend=backend)
    assert got == inline == _oracle(a, b)


def test_plan_is_reusable_across_same_structure_operands():
    """A plan is structure-keyed: the SAME plan drives operands with
    different VALUES (the serving scenario) bit-exactly."""
    rng = np.random.default_rng(103)
    a1 = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    b1 = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    a2 = BlockSparseMatrix(rows=a1.rows, cols=a1.cols, k=a1.k,
                           coords=a1.coords,
                           tiles=a1.tiles[::-1].copy())  # same structure
    p = plan(a1, b1, backend="xla", platform="cpu")
    assert execute(p, a2, b1).to_host() == _oracle(a2, b1)


def test_execute_rejects_mismatched_operands():
    """Sentinels are baked into pa/pb: a structurally different operand
    pair must be refused, never silently mis-gathered."""
    rng = np.random.default_rng(104)
    a = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    b = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    c = random_block_sparse(6, 6, 2, 0.9, rng, "full")
    p = plan(a, b, backend="xla", platform="cpu")
    assert c.nnzb != b.nnzb
    with pytest.raises(ValueError, match="nnzb"):
        execute(p, a, c)
    k4 = random_block_sparse(6, 6, 4, 0.5, rng, "full")
    with pytest.raises(ValueError, match="k="):
        execute(p, k4, k4)
    # the dangerous case (code-review repro): SAME nnzb, different coords
    # -- the pa/pb gathers stay in-bounds and would silently produce a
    # wrong product, so the coords guard must fire
    shifted = b.coords.copy()
    shifted[-1, 1] += 1  # still lex-sorted: last coord's col bumped
    b_shifted = BlockSparseMatrix(rows=b.rows, cols=b.cols + b.k, k=b.k,
                                  coords=shifted, tiles=b.tiles)
    assert b_shifted.nnzb == b.nnzb
    with pytest.raises(ValueError, match="coords"):
        execute(p, a, b_shifted)


def test_plan_host_purity_marker_and_duck_typing():
    """Planner worker threads call _plan_host with resolved backend/
    platform: the body carries the @host_only marker (BKD-scanned) and
    needs only coords/nnzb/k/val_bound -- no device, no tiles."""
    from types import SimpleNamespace

    from spgemm_tpu.ops.spgemm import _plan_host

    assert getattr(_plan_host, "__spgemm_host_only__", False)
    coords = np.array([[0, 0], [0, 1], [1, 0]], np.int64)
    m = SimpleNamespace(coords=coords, nnzb=3, k=2, val_bound=0)
    p = plan(m, m, backend="xla", platform="cpu")
    assert p.join.num_keys > 0 and p.backend == "xla"


def test_empty_join_plans_and_executes():
    rng = np.random.default_rng(105)
    a = random_block_sparse(4, 4, 2, 0.4, rng, "full")
    # B's rows never meet A's cols: disjoint block structure, empty join
    b = BlockSparseMatrix(rows=a.rows, cols=a.cols, k=2,
                          coords=np.zeros((0, 2), np.int64),
                          tiles=np.zeros((0, 2, 2), np.uint64))
    p = plan(a, b, backend="xla", platform="cpu")
    assert p.join.num_keys == 0 and p.rounds == []
    assert execute(p, a, b).nnzb == 0


# ------------------------------------------------------------- plan cache


def test_plan_cache_hits_same_structure(monkeypatch):
    """Second plan of the same structure is the SAME object, with the
    join/round phases skipped (hit counter, no second miss)."""
    rng = np.random.default_rng(111)
    a = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    b = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    ENGINE.reset()
    p1 = plan(a, b, backend="xla", platform="cpu")
    p2 = plan(a, b, backend="xla", platform="cpu")
    assert p2 is p1
    counters = ENGINE.counter_snapshot()
    assert counters["plan_cache_misses"] == 1
    assert counters["plan_cache_hits"] == 1
    stats = plancache.stats()
    assert stats["hits"] == 1 and stats["misses"] == 1


def test_plan_cache_distinguishes_structure_and_knobs(monkeypatch):
    """Different coords, a different jit-static knob vector, or a flipped
    ROUND_BATCH must all be cache MISSES -- a stale plan under any of
    those is a wrong plan."""
    rng = np.random.default_rng(112)
    a = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    b = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    c = random_block_sparse(8, 8, 2, 0.8, rng, "full")
    p1 = plan(a, b, backend="xla", platform="cpu")
    assert plan(a, c, backend="xla", platform="cpu") is not p1
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "0")
    p_legacy = plan(a, b, backend="xla", platform="cpu")
    assert p_legacy is not p1 and p_legacy.batch is False
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "1")
    monkeypatch.setenv("SPGEMM_TPU_MXU_R", "16")  # jit-static knob
    assert plan(a, b, backend="xla", platform="cpu") is not p1
    monkeypatch.delenv("SPGEMM_TPU_MXU_R")
    assert plan(a, b, backend="xla", platform="cpu") is p1  # back to hit


def test_plan_cache_lru_eviction(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_PLAN_CACHE_CAP", "1")
    rng = np.random.default_rng(113)
    a = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    b = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    c = random_block_sparse(6, 6, 2, 0.9, rng, "full")
    p1 = plan(a, b, backend="xla", platform="cpu")
    plan(a, c, backend="xla", platform="cpu")  # evicts p1 at cap 1
    assert plancache.stats()["entries"] == 1
    assert plan(a, b, backend="xla", platform="cpu") is not p1  # re-planned
    assert plancache.stats()["hits"] == 0


def test_plan_cache_disabled_never_stores(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_PLAN_CACHE", "0")
    rng = np.random.default_rng(114)
    a = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    p1 = plan(a, a, backend="xla", platform="cpu")
    p2 = plan(a, a, backend="xla", platform="cpu")
    assert p1 is not p2 and p1.fingerprint is None
    assert plancache.stats()["entries"] == 0


def test_spgemm_device_second_run_hits_cache():
    """The end-to-end serving path: a repeated multiply re-uses the plan
    (hits > 0) and stays bit-exact."""
    rng = np.random.default_rng(115)
    a = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    b = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    first = spgemm(a, b)
    ENGINE.reset()
    second = spgemm(a, b)
    assert second == first == _oracle(a, b)
    assert ENGINE.counter_snapshot()["plan_cache_hits"] >= 1
    # the hit path's plan span is recorded (near-zero) and dispatch still
    # had to wait on it -- both phases must exist for the bench contract
    snap = ENGINE.snapshot()
    assert "plan" in snap and "plan_wait" in snap


# ------------------------------------------------- chain plan-ahead worker


@pytest.mark.parametrize("n", [4, 5, 6])
def test_chain_plan_ahead_bit_identical_and_same_dispatch(n, monkeypatch):
    """The tentpole A/B: PLAN_AHEAD=2 vs 0 on an adversarial chain --
    identical bits, identical dispatch counts (planning is deterministic
    and dispatch order unchanged), and the pipeline actually overlapped
    (plan_wait recorded alongside plan)."""
    from spgemm_tpu.ops import delta

    rng = np.random.default_rng(120 + n)
    mats = random_chain(n, 4, 2, 0.6, rng, "adversarial")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_AHEAD", "0")
    plancache.clear()
    ENGINE.reset()
    serial = chain_product(mats)
    serial_dispatches = ENGINE.counter_snapshot()["dispatches"]
    monkeypatch.setenv("SPGEMM_TPU_PLAN_AHEAD", "2")
    plancache.clear()
    delta.clear()  # the piped leg must re-EXECUTE, not serve retained rows
    ENGINE.reset()
    piped = chain_product(mats)
    snap = ENGINE.snapshot()
    assert ENGINE.counter_snapshot()["dispatches"] == serial_dispatches
    assert "plan" in snap and "plan_wait" in snap
    want = chain_oracle([m.to_dict() for m in mats], 2)
    want_m = BlockSparseMatrix.from_dict(mats[0].rows, mats[-1].cols, 2, want)
    assert piped == serial == want_m


def test_chain_planner_failure_fails_over_to_oracle(monkeypatch):
    """A planner-worker exception surfaces on the consumer like a device
    loss: without failover it raises, with failover the pass restarts on
    the host oracle."""
    import spgemm_tpu.ops.spgemm as spgemm_mod

    rng = np.random.default_rng(130)
    mats = random_chain(5, 4, 2, 0.5, rng, "full")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_AHEAD", "2")
    calls = []
    real = spgemm_mod.plan

    def dying_plan(a, b, **kw):
        calls.append(1)
        if len(calls) > 1:
            raise RuntimeError("injected planner death")
        return real(a, b, **kw)

    monkeypatch.setattr(spgemm_mod, "plan", dying_plan)
    with pytest.raises(RuntimeError, match="injected planner death"):
        chain_product(mats)
    calls.clear()
    got = chain_product(mats, failover=True)
    want = chain_oracle([m.to_dict() for m in mats], 2)
    want_m = BlockSparseMatrix.from_dict(mats[0].rows, mats[-1].cols, 2, want)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


def test_plan_ahead_knob_validation(monkeypatch):
    rng = np.random.default_rng(131)
    mats = random_chain(2, 3, 2, 0.5, rng, "full")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_AHEAD", "-1")
    with pytest.raises(ValueError, match="SPGEMM_TPU_PLAN_AHEAD"):
        chain_product(mats)
    monkeypatch.setenv("SPGEMM_TPU_PLAN_AHEAD", "lots")
    with pytest.raises(ValueError, match="SPGEMM_TPU_PLAN_AHEAD"):
        chain_product(mats)


# ------------------------------------- sharded strategies consume the plan


def test_rowshard_consumes_prebuilt_plan():
    rng = np.random.default_rng(140)
    a = random_block_sparse(8, 8, 2, 0.5, rng, "adversarial")
    b = random_block_sparse(8, 8, 2, 0.5, rng, "adversarial")
    from spgemm_tpu.parallel.rowshard import spgemm_sharded

    p = plan(a, b, backend="xla", platform="cpu")
    got = spgemm_sharded(a, b, plan=p)
    assert got == spgemm_sharded(a, b) == _oracle(a, b)
    # the hook is memoized: a second consumer reuses the same schedule
    assert p.rowshard_rounds(None) is p.rowshard_rounds(None)
    with pytest.raises(ValueError, match="nnzb"):
        c = random_block_sparse(8, 8, 2, 0.9, rng, "full")
        spgemm_sharded(c, b, plan=p)


def test_ring_consumes_prebuilt_plan():
    rng = np.random.default_rng(141)
    # bounded values: ring arithmetic is field mode, reference-exact here
    a = random_block_sparse(8, 8, 2, 0.5, rng, "small")
    b = random_block_sparse(8, 8, 2, 0.5, rng, "small")
    from spgemm_tpu.parallel.ring import spgemm_ring

    p = plan(a, b, backend="xla", platform="cpu")
    got = spgemm_ring(a, b, plan=p)
    assert got == spgemm_ring(a, b) == _oracle(a, b)
    n_dev = len(__import__("jax").devices())
    assert p.ring_schedule(b.nnzb, n_dev) is p.ring_schedule(b.nnzb, n_dev)
