"""Single-chip SpGEMM (symbolic + XLA numeric) vs the numpy oracle."""

import numpy as np
import pytest

from spgemm_tpu.ops.spgemm import spgemm
from spgemm_tpu.ops.symbolic import plan_rounds, symbolic_join
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_block_sparse
from spgemm_tpu.utils.semantics import spgemm_oracle


def assert_matches_oracle(a: BlockSparseMatrix, b: BlockSparseMatrix, **kw):
    got = spgemm(a, b, **kw)
    want = spgemm_oracle(a.to_dict(), b.to_dict(), a.k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, a.k, want)
    assert got.nnzb == want_m.nnzb, (got.coords, want_m.coords)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


@pytest.mark.parametrize("dist", ["small", "full", "adversarial"])
@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_random_vs_oracle(k, dist):
    # deterministic seed (str hash() is salted per process)
    rng = np.random.default_rng(1000 * k + len(dist))
    a = random_block_sparse(6, 6, k, 0.4, rng, dist)
    b = random_block_sparse(6, 6, k, 0.4, rng, dist)
    assert_matches_oracle(a, b)


@pytest.mark.parametrize("k", [64, 128])
def test_beyond_reference_tile_cap_vs_oracle(k):
    """k > 32 exact parity -- a capability the reference physically cannot
    have: its CUDA launch uses one thread per tile element (block(k,k),
    sparse_matrix_mult.cu kernel launch region), capping k at 32 by the
    1024-thread block limit (SURVEY.md section 3.3).  The u64 engine here is
    shape-polymorphic in k; pin exact wrap-then-mod parity at k=64/128."""
    rng = np.random.default_rng(6400 + k)
    a = random_block_sparse(3, 3, k, 0.7, rng, "adversarial")
    b = random_block_sparse(3, 3, k, 0.7, rng, "adversarial")
    assert_matches_oracle(a, b, backend="xla")


def test_beyond_reference_tile_cap_pallas_k64():
    """The Pallas VPU kernel at k=64 (interpret mode): same exact parity.
    G auto-clamps to 512/k = 8 lanes-wide groups; fold order is unchanged."""
    rng = np.random.default_rng(640)
    a = random_block_sparse(2, 2, 64, 1.0, rng, "full")
    b = random_block_sparse(2, 2, 64, 1.0, rng, "full")
    assert_matches_oracle(a, b, backend="pallas")


def test_rectangular():
    rng = np.random.default_rng(30)
    a = random_block_sparse(3, 7, 4, 0.5, rng, "full")
    b = random_block_sparse(7, 2, 4, 0.5, rng, "full")
    assert_matches_oracle(a, b)


def test_no_structural_match():
    """A's cols never meet B's rows -> empty result with correct dims."""
    a = BlockSparseMatrix.from_blocks(4, 4, 2, [(0, 0)],
                                      np.ones((1, 2, 2), np.uint64))
    b = BlockSparseMatrix.from_blocks(4, 4, 2, [(1, 1)],
                                      np.ones((1, 2, 2), np.uint64))
    c = spgemm(a, b)
    assert c.nnzb == 0 and c.rows == 4 and c.cols == 4


def test_zero_product_tiles_kept():
    """All-zero output tiles are NOT pruned by spgemm (only at final write)."""
    k = 2
    a = BlockSparseMatrix.from_blocks(2, 2, k, [(0, 0)],
                                      np.zeros((1, k, k), np.uint64))
    b = BlockSparseMatrix.from_blocks(2, 2, k, [(0, 0)],
                                      np.ones((1, k, k), np.uint64))
    c = spgemm(a, b)
    assert c.nnzb == 1
    assert np.all(c.tiles == 0)


def test_small_round_size_multiple_rounds():
    rng = np.random.default_rng(31)
    a = random_block_sparse(10, 10, 2, 0.4, rng, "full")
    b = random_block_sparse(10, 10, 2, 0.4, rng, "full")
    assert_matches_oracle(a, b, round_size=4)


def test_symbolic_join_pair_order():
    """Pair lists must be j-ascending (reference map order, SURVEY 2.9)."""
    a_coords = np.array([(0, 0), (0, 1), (0, 3)], dtype=np.int64)
    b_coords = np.array([(0, 5), (1, 5), (3, 5)], dtype=np.int64)
    join = symbolic_join(a_coords, b_coords)
    assert join.num_keys == 1
    assert tuple(join.keys[0]) == (0, 5)
    # pairs in ascending inner-coordinate order: j = 0, 1, 3
    inner = a_coords[join.pair_a, 1]
    assert list(inner) == [0, 1, 3]


def test_plan_rounds_shapes_and_sentinels():
    a_coords = np.array([(0, 0), (0, 1), (1, 0)], dtype=np.int64)
    b_coords = np.array([(0, 0), (1, 0)], dtype=np.int64)
    join = symbolic_join(a_coords, b_coords)
    rounds = plan_rounds(join, a_sentinel=3, b_sentinel=2, round_size=512)
    covered = np.concatenate([r.key_index for r in rounds])
    assert sorted(covered.tolist()) == list(range(join.num_keys))
    for r in rounds:
        assert r.pa.shape == r.pb.shape
        assert _is_shape_class(r.pa.shape[1])


def _is_shape_class(x: int) -> bool:
    """Member of the pow2 + 3/4-pow2 ladder {1, 2, 3, 4, 6, 8, 12, 16, ...}."""
    if x & (x - 1) == 0:
        return True
    return x % 3 == 0 and ((x // 3) & (x // 3 - 1)) == 0


def test_plan_rounds_34_pow2_classes():
    # bandwidth-1 banded: interior output keys have fanout 3, which must land
    # in the 3-slot class (not pad to 4), and the scattered pair lists must
    # match the join exactly
    n = 16
    coords = np.array([(r, c) for r in range(n)
                       for c in range(max(0, r - 1), min(n, r + 2))], np.int64)
    join = symbolic_join(coords, coords)
    assert 3 in np.diff(join.pair_ptr)
    rounds = plan_rounds(join, a_sentinel=len(coords), b_sentinel=len(coords))
    widths = {r.pa.shape[1] for r in rounds}
    assert 3 in widths and 4 not in widths
    # reassemble per-key pair lists from rounds and compare against the join
    for r in rounds:
        for row, ki in enumerate(r.key_index):
            s, e = join.pair_ptr[ki], join.pair_ptr[ki + 1]
            got_a = r.pa[row][: e - s]
            got_b = r.pb[row][: e - s]
            assert list(got_a) == list(join.pair_a[s:e])
            assert list(got_b) == list(join.pair_b[s:e])
            assert all(v == len(coords) for v in r.pa[row][e - s:])  # sentinel tail


def _force_numpy_join(monkeypatch):
    """Disable the native join so the numpy branch under test actually runs
    (the native .so is auto-built on any machine with g++, so without this
    the regression below would silently test the C++ path instead)."""
    from spgemm_tpu.utils import native
    monkeypatch.setattr(native, "symbolic_join_native", lambda *a: None)


@pytest.mark.parametrize("force_numpy", [True, False])
def test_symbolic_join_huge_coords_no_int64_wrap(monkeypatch, force_numpy):
    """Regression (round-1 ADVICE): the fused sort key must not wrap.

    max_row * span here is exactly 2^63 -- an int64 fused key goes negative
    and sorts the largest output key FIRST; the uint64 key (matching
    native/symbolic.cpp) keeps the lexicographic order.  Runs both the
    numpy branch (forced) and whatever symbolic_join dispatches to.
    """
    if force_numpy:
        _force_numpy_join(monkeypatch)
    big_r = 1 << 32
    big_c = (1 << 31) - 1  # span = 2^31
    a_coords = np.array([(0, 0), (big_r, 0)], dtype=np.int64)
    b_coords = np.array([(0, 5), (0, big_c)], dtype=np.int64)
    join = symbolic_join(a_coords, b_coords)
    expect = [(0, 5), (0, big_c), (big_r, 5), (big_r, big_c)]
    assert [tuple(k) for k in join.keys] == expect
    assert list(np.diff(join.pair_ptr)) == [1, 1, 1, 1]


def test_symbolic_join_beyond_uint64_lexsort_fallback(monkeypatch):
    """Even uint64 fusing would wrap here ((max_row+1)*span > 2^64): the
    numpy path must take the stable-lexsort branch and the native path must
    not be consulted (it would wrap silently)."""
    from spgemm_tpu.utils import native

    def _fail(*a):
        raise AssertionError("native join consulted beyond its safe range")

    monkeypatch.setattr(native, "symbolic_join_native", _fail)
    big_r = 1 << 40
    big_c = (1 << 31) - 1
    a_coords = np.array([(0, 0), (big_r, 0)], dtype=np.int64)
    b_coords = np.array([(0, 5), (0, big_c)], dtype=np.int64)
    join = symbolic_join(a_coords, b_coords)
    expect = [(0, 5), (0, big_c), (big_r, 5), (big_r, big_c)]
    assert [tuple(k) for k in join.keys] == expect
    # stability across the lexsort branch: shared key, j-ascending pairs
    # (span stays > 2^24 so (max_row+1)*span > 2^64 keeps this branch)
    big_c2 = (1 << 30) + 7
    a2 = np.array([(big_r, 0), (big_r, 1)], dtype=np.int64)
    b2 = np.array([(0, big_c2), (1, big_c2)], dtype=np.int64)
    j2 = symbolic_join(a2, b2)
    assert j2.num_keys == 1
    assert list(a2[j2.pair_a, 1]) == [0, 1]
