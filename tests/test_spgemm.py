"""Single-chip SpGEMM (symbolic + XLA numeric) vs the numpy oracle."""

import numpy as np
import pytest

from spgemm_tpu.ops.spgemm import spgemm
from spgemm_tpu.ops.symbolic import plan_rounds, symbolic_join
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_block_sparse
from spgemm_tpu.utils.semantics import spgemm_oracle


def assert_matches_oracle(a: BlockSparseMatrix, b: BlockSparseMatrix, **kw):
    got = spgemm(a, b, **kw)
    want = spgemm_oracle(a.to_dict(), b.to_dict(), a.k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, a.k, want)
    assert got.nnzb == want_m.nnzb, (got.coords, want_m.coords)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


@pytest.mark.parametrize("dist", ["small", "full", "adversarial"])
@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_random_vs_oracle(k, dist):
    # deterministic seed (str hash() is salted per process)
    rng = np.random.default_rng(1000 * k + len(dist))
    a = random_block_sparse(6, 6, k, 0.4, rng, dist)
    b = random_block_sparse(6, 6, k, 0.4, rng, dist)
    assert_matches_oracle(a, b)


@pytest.mark.parametrize("k", [64, 128])
def test_beyond_reference_tile_cap_vs_oracle(k):
    """k > 32 exact parity -- a capability the reference physically cannot
    have: its CUDA launch uses one thread per tile element (block(k,k),
    sparse_matrix_mult.cu kernel launch region), capping k at 32 by the
    1024-thread block limit (SURVEY.md section 3.3).  The u64 engine here is
    shape-polymorphic in k; pin exact wrap-then-mod parity at k=64/128."""
    rng = np.random.default_rng(6400 + k)
    a = random_block_sparse(3, 3, k, 0.7, rng, "adversarial")
    b = random_block_sparse(3, 3, k, 0.7, rng, "adversarial")
    assert_matches_oracle(a, b, backend="xla")


def test_beyond_reference_tile_cap_pallas_k64():
    """The Pallas VPU kernel at k=64 (interpret mode): same exact parity.
    G auto-clamps to 512/k = 8 lanes-wide groups; fold order is unchanged."""
    rng = np.random.default_rng(640)
    a = random_block_sparse(2, 2, 64, 1.0, rng, "full")
    b = random_block_sparse(2, 2, 64, 1.0, rng, "full")
    assert_matches_oracle(a, b, backend="pallas")


def test_rectangular():
    rng = np.random.default_rng(30)
    a = random_block_sparse(3, 7, 4, 0.5, rng, "full")
    b = random_block_sparse(7, 2, 4, 0.5, rng, "full")
    assert_matches_oracle(a, b)


def test_no_structural_match():
    """A's cols never meet B's rows -> empty result with correct dims."""
    a = BlockSparseMatrix.from_blocks(4, 4, 2, [(0, 0)],
                                      np.ones((1, 2, 2), np.uint64))
    b = BlockSparseMatrix.from_blocks(4, 4, 2, [(1, 1)],
                                      np.ones((1, 2, 2), np.uint64))
    c = spgemm(a, b)
    assert c.nnzb == 0 and c.rows == 4 and c.cols == 4


def test_zero_product_tiles_kept():
    """All-zero output tiles are NOT pruned by spgemm (only at final write)."""
    k = 2
    a = BlockSparseMatrix.from_blocks(2, 2, k, [(0, 0)],
                                      np.zeros((1, k, k), np.uint64))
    b = BlockSparseMatrix.from_blocks(2, 2, k, [(0, 0)],
                                      np.ones((1, k, k), np.uint64))
    c = spgemm(a, b)
    assert c.nnzb == 1
    assert np.all(c.tiles == 0)


def test_small_round_size_multiple_rounds():
    rng = np.random.default_rng(31)
    a = random_block_sparse(10, 10, 2, 0.4, rng, "full")
    b = random_block_sparse(10, 10, 2, 0.4, rng, "full")
    assert_matches_oracle(a, b, round_size=4)


def test_symbolic_join_pair_order():
    """Pair lists must be j-ascending (reference map order, SURVEY 2.9)."""
    a_coords = np.array([(0, 0), (0, 1), (0, 3)], dtype=np.int64)
    b_coords = np.array([(0, 5), (1, 5), (3, 5)], dtype=np.int64)
    join = symbolic_join(a_coords, b_coords)
    assert join.num_keys == 1
    assert tuple(join.keys[0]) == (0, 5)
    # pairs in ascending inner-coordinate order: j = 0, 1, 3
    inner = a_coords[join.pair_a, 1]
    assert list(inner) == [0, 1, 3]


def test_plan_rounds_shapes_and_sentinels():
    a_coords = np.array([(0, 0), (0, 1), (1, 0)], dtype=np.int64)
    b_coords = np.array([(0, 0), (1, 0)], dtype=np.int64)
    join = symbolic_join(a_coords, b_coords)
    rounds = plan_rounds(join, a_sentinel=3, b_sentinel=2, round_size=512)
    covered = np.concatenate([r.key_index for r in rounds])
    assert sorted(covered.tolist()) == list(range(join.num_keys))
    for r in rounds:
        assert r.pa.shape == r.pb.shape
        assert _is_shape_class(r.pa.shape[1])


def _is_shape_class(x: int) -> bool:
    """Member of the pow2 + 3/4-pow2 ladder {1, 2, 3, 4, 6, 8, 12, 16, ...}."""
    if x & (x - 1) == 0:
        return True
    return x % 3 == 0 and ((x // 3) & (x // 3 - 1)) == 0


def test_plan_rounds_34_pow2_classes():
    # bandwidth-1 banded: interior output keys have fanout 3, which must land
    # in the 3-slot class (not pad to 4), and the scattered pair lists must
    # match the join exactly
    n = 16
    coords = np.array([(r, c) for r in range(n)
                       for c in range(max(0, r - 1), min(n, r + 2))], np.int64)
    join = symbolic_join(coords, coords)
    assert 3 in np.diff(join.pair_ptr)
    rounds = plan_rounds(join, a_sentinel=len(coords), b_sentinel=len(coords))
    widths = {r.pa.shape[1] for r in rounds}
    assert 3 in widths and 4 not in widths
    # reassemble per-key pair lists from rounds and compare against the join
    for r in rounds:
        for row, ki in enumerate(r.key_index):
            s, e = join.pair_ptr[ki], join.pair_ptr[ki + 1]
            got_a = r.pa[row][: e - s]
            got_b = r.pb[row][: e - s]
            assert list(got_a) == list(join.pair_a[s:e])
            assert list(got_b) == list(join.pair_b[s:e])
            assert all(v == len(coords) for v in r.pa[row][e - s:])  # sentinel tail


def _force_numpy_join(monkeypatch):
    """Disable the native join so the numpy branch under test actually runs
    (the native .so is auto-built on any machine with g++, so without this
    the regression below would silently test the C++ path instead)."""
    from spgemm_tpu.utils import native
    monkeypatch.setattr(native, "symbolic_join_native", lambda *a: None)


@pytest.mark.parametrize("force_numpy", [True, False])
def test_symbolic_join_huge_coords_no_int64_wrap(monkeypatch, force_numpy):
    """Regression (round-1 ADVICE): the fused sort key must not wrap.

    max_row * span here is exactly 2^63 -- an int64 fused key goes negative
    and sorts the largest output key FIRST; the uint64 key (matching
    native/symbolic.cpp) keeps the lexicographic order.  Runs both the
    numpy branch (forced) and whatever symbolic_join dispatches to.
    """
    if force_numpy:
        _force_numpy_join(monkeypatch)
    big_r = 1 << 32
    big_c = (1 << 31) - 1  # span = 2^31
    a_coords = np.array([(0, 0), (big_r, 0)], dtype=np.int64)
    b_coords = np.array([(0, 5), (0, big_c)], dtype=np.int64)
    join = symbolic_join(a_coords, b_coords)
    expect = [(0, 5), (0, big_c), (big_r, 5), (big_r, big_c)]
    assert [tuple(k) for k in join.keys] == expect
    assert list(np.diff(join.pair_ptr)) == [1, 1, 1, 1]


def test_symbolic_join_beyond_uint64_lexsort_fallback(monkeypatch):
    """Even uint64 fusing would wrap here ((max_row+1)*span > 2^64): the
    numpy path must take the stable-lexsort branch and the native path must
    not be consulted (it would wrap silently)."""
    from spgemm_tpu.utils import native

    def _fail(*a):
        raise AssertionError("native join consulted beyond its safe range")

    monkeypatch.setattr(native, "symbolic_join_native", _fail)
    big_r = 1 << 40
    big_c = (1 << 31) - 1
    a_coords = np.array([(0, 0), (big_r, 0)], dtype=np.int64)
    b_coords = np.array([(0, 5), (0, big_c)], dtype=np.int64)
    join = symbolic_join(a_coords, b_coords)
    expect = [(0, 5), (0, big_c), (big_r, 5), (big_r, big_c)]
    assert [tuple(k) for k in join.keys] == expect
    # stability across the lexsort branch: shared key, j-ascending pairs
    # (span stays > 2^24 so (max_row+1)*span > 2^64 keeps this branch)
    big_c2 = (1 << 30) + 7
    a2 = np.array([(big_r, 0), (big_r, 1)], dtype=np.int64)
    b2 = np.array([(0, big_c2), (1, big_c2)], dtype=np.int64)
    j2 = symbolic_join(a2, b2)
    assert j2.num_keys == 1
    assert list(a2[j2.pair_a, 1]) == [0, 1]


# ---------------------------------------------------------------------------
# Accumulator routes (SPGEMM_TPU_ACCUM_ROUTE): the dense segmented-stream
# fold and the padded ladder must produce byte-identical planes on every
# structure (same per-key j-ascending fold order, different layout only),
# and the auto gate must actually take the dense route on a deep class.


def _hub_pair(k=4, keys=2, fanout=300, seed=170):
    """`keys` hub output rows of the given fanout.  fanout 300 lands in
    shape class 384 -- a 1.28x padded-MAC ratio, past the structural
    dense gate (crossover.DENSE_RATIO_GATE) and past DENSE_MIN_CLASS."""
    rng = np.random.default_rng(seed)
    a_coords = np.array([(i, i * fanout + j) for i in range(keys)
                         for j in range(fanout)], np.int64)
    b_coords = np.array([(m, 0) for m in range(keys * fanout)], np.int64)
    a = BlockSparseMatrix(
        rows=keys, cols=keys * fanout, k=k, coords=a_coords,
        tiles=rng.integers(0, 1 << 64, size=(len(a_coords), k, k),
                           dtype=np.uint64))
    b = BlockSparseMatrix(
        rows=keys * fanout, cols=1, k=k, coords=b_coords,
        tiles=rng.integers(0, 1 << 64, size=(len(b_coords), k, k),
                           dtype=np.uint64))
    return a, b


def _skew_pair(k=2, seed=7):
    from spgemm_tpu.utils.gen import powerlaw_block_sparse
    rng = np.random.default_rng(seed)
    return (powerlaw_block_sparse(32, k, 3.0, rng, "adversarial"),
            powerlaw_block_sparse(32, k, 3.0, rng, "adversarial"))


def _shallow_pair(k=4, seed=3):
    """Every fanout class below DENSE_MIN_CLASS: auto attaches no twin."""
    rng = np.random.default_rng(seed)
    return (random_block_sparse(6, 6, k, 0.4, rng, "adversarial"),
            random_block_sparse(6, 6, k, 0.4, rng, "adversarial"))


def _empty_pair(k=4, seed=9):
    """Structurally empty product (A's cols never meet B's rows)."""
    rng = np.random.default_rng(seed)
    a = BlockSparseMatrix(
        rows=2, cols=4, k=k, coords=np.array([(0, 0), (1, 1)], np.int64),
        tiles=rng.integers(0, 1 << 64, size=(2, k, k), dtype=np.uint64))
    b = BlockSparseMatrix(
        rows=4, cols=2, k=k, coords=np.array([(2, 0), (3, 1)], np.int64),
        tiles=rng.integers(0, 1 << 64, size=(2, k, k), dtype=np.uint64))
    return a, b


@pytest.mark.parametrize("make_pair", [_hub_pair, _skew_pair,
                                       _shallow_pair, _empty_pair],
                         ids=["hub", "skew", "shallow", "empty"])
def test_accum_route_bytes_identical(monkeypatch, make_pair):
    """auto | dense | ladder: identical bytes on every structure (the PR's
    bit-exactness contract) and all equal to the oracle.  The knob is
    jit-static, so each leg plans from a cleared cache."""
    from spgemm_tpu.ops import plancache

    a, b = make_pair()
    want = spgemm_oracle(a.to_dict(), b.to_dict(), a.k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, a.k, want)
    legs = {}
    for route in ("ladder", "dense", "auto"):
        monkeypatch.setenv("SPGEMM_TPU_ACCUM_ROUTE", route)
        plancache.clear()
        legs[route] = spgemm(a, b)
    plancache.clear()
    for route, got in legs.items():
        assert np.array_equal(got.coords, want_m.coords), route
        assert got.tiles.tobytes() == want_m.tiles.tobytes(), route
    assert legs["dense"].tiles.tobytes() == legs["ladder"].tiles.tobytes()
    assert legs["auto"].tiles.tobytes() == legs["ladder"].tiles.tobytes()


def test_dense_round_stream_invariants():
    """route='dense' plan_rounds: one 1-D pair stream per fanout class,
    padded to a multiple of 8 (_stream_pad), seg mapping real slots to
    their output row and pad slots to the scratch row n_rows, and the
    stream walking each key's pairs j-ascending (the fold order)."""
    a, b = _hub_pair(keys=3, fanout=300)
    join = symbolic_join(a.coords, b.coords)
    rounds = plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=b.nnzb,
                         route="dense")
    assert rounds, "hub structure must produce at least one round"
    covered = []
    for rnd in rounds:
        assert rnd.route == "dense"
        assert rnd.pa.ndim == rnd.pb.ndim == rnd.seg.ndim == 1
        L = rnd.pa.shape[0]
        assert L == rnd.pb.shape[0] == rnd.seg.shape[0]
        assert L % 8 == 0
        # n_rows is the ladder twin's K_pad: >= the real key count, and
        # out_rows reports it so assembly sees identical shapes per route
        assert rnd.out_rows == rnd.n_rows >= len(rnd.key_index)
        real = rnd.real_pairs
        assert 0 < real <= L
        assert np.all(rnd.seg[:real] < len(rnd.key_index))
        assert np.all(rnd.seg[real:] == rnd.n_rows)  # scratch row
        assert np.all(rnd.pa[real:] == a.nnzb)       # sentinel pad
        assert np.all(rnd.pb[real:] == b.nnzb)
        assert rnd.padded_mac_ratio() == L / real
        # reassemble each key's pair list from the stream: contiguous,
        # j-ascending, exactly the join's list (fold order untouched)
        for row, ki in enumerate(rnd.key_index):
            s, e = join.pair_ptr[ki], join.pair_ptr[ki + 1]
            mask = rnd.seg[:real] == row
            assert list(rnd.pa[:real][mask]) == list(join.pair_a[s:e])
            assert list(rnd.pb[:real][mask]) == list(join.pair_b[s:e])
        covered.extend(rnd.key_index)
    assert sorted(covered) == list(range(join.num_keys))


def test_ladder_route_is_pre_dense_plan(monkeypatch):
    """SPGEMM_TPU_ACCUM_ROUTE=ladder restores the exact pre-dense engine:
    every round keeps the 2-D pair grid, no dense twin is attached, no
    dense dispatch fires, and dispatch counts match the plan's rounds."""
    from spgemm_tpu.ops import plancache
    from spgemm_tpu.ops.spgemm import plan as build_plan
    from spgemm_tpu.utils.timers import ENGINE

    monkeypatch.setenv("SPGEMM_TPU_ACCUM_ROUTE", "ladder")
    plancache.clear()
    a, b = _hub_pair()
    p = build_plan(a, b)
    rounds = p.ensure_exact().rounds
    assert all(r.route == "ladder" and r.pa.ndim == 2 for r in rounds)
    assert all(r.seg is None and r.dense_alt is None for r in rounds)
    ENGINE.reset()
    spgemm(a, b)
    counters = ENGINE.counter_snapshot()
    assert counters.get("route_dense", 0) == 0
    assert counters["dispatches"] == len(rounds)
    plancache.clear()


def test_auto_gate_takes_dense_on_deep_class(monkeypatch):
    """auto on CPU runs the structural proof gate (crossover policy
    'proof'): the hub class's 1.28x padded ratio clears DENSE_RATIO_GATE,
    so the round must dispatch dense (route_dense fires) with bytes equal
    to the forced-ladder leg -- the gate changes wall clock, never bits."""
    from spgemm_tpu.ops import plancache
    from spgemm_tpu.ops.spgemm import plan as build_plan
    from spgemm_tpu.utils.timers import ENGINE

    a, b = _hub_pair()
    monkeypatch.setenv("SPGEMM_TPU_ACCUM_ROUTE", "auto")
    plancache.clear()
    p = build_plan(a, b)
    rounds = p.ensure_exact().rounds
    deep = [r for r in rounds if r.dense_alt is not None]
    assert deep, "class 384 must carry a dense twin under auto"
    for r in deep:
        assert r.route == "ladder" and r.dense_alt.route == "dense"
        # the twin folds the same real pairs into the same padded row span
        assert r.dense_alt.real_pairs == round(r.pa.size
                                               / r.padded_mac_ratio())
        assert r.dense_alt.n_rows == r.pa.shape[0]
        assert r.dense_alt.padded_mac_ratio() < r.padded_mac_ratio()
    ENGINE.reset()
    auto_out = spgemm(a, b)
    assert ENGINE.counter_snapshot().get("route_dense", 0) >= 1
    monkeypatch.setenv("SPGEMM_TPU_ACCUM_ROUTE", "ladder")
    plancache.clear()
    ladder_out = spgemm(a, b)
    assert auto_out.tiles.tobytes() == ladder_out.tiles.tobytes()
    plancache.clear()
