"""Ring overlap knob (SPGEMM_TPU_RING_OVERLAP) on the 8-virtual-device mesh.

The double-buffered step body (hop for slab t+1 issued before the fold over
slab t) must be BIT-IDENTICAL to the legacy fold-then-hop body: the knob only
moves the ppermute issue point, never the fold order.  These tests pin that
contract -- the regression guard for the round-7 comm/compute overlap layer
(tests/test_parallel.py covers ring-vs-oracle correctness; this file covers
the A/B knob itself plus its observability side channel).
"""

import numpy as np
import pytest

from spgemm_tpu.parallel.ring import overlap_enabled, spgemm_ring
from spgemm_tpu.utils.gen import powerlaw_block_sparse, random_block_sparse
from spgemm_tpu.utils.timers import ENGINE


def _ring(monkeypatch, overlap: str, a, b):
    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", overlap)
    return spgemm_ring(a, b)


@pytest.mark.parametrize("dist", ["small", "full", "adversarial"])
def test_overlap_bit_identical(monkeypatch, dist):
    """overlap=0 and overlap=1 agree bit-for-bit on bounded, full-range, and
    adversarial values (the b32 and full-width field MACs both ride under
    the knob)."""
    rng = np.random.default_rng(700)
    k = 4
    a = random_block_sparse(9, 9, k, 0.4, rng, dist)
    b = random_block_sparse(9, 9, k, 0.4, rng, dist)
    got0 = _ring(monkeypatch, "0", a, b)
    got1 = _ring(monkeypatch, "1", a, b)
    assert np.array_equal(got0.coords, got1.coords)
    assert np.array_equal(got0.tiles, got1.tiles)


def test_overlap_bit_identical_powerlaw(monkeypatch):
    """The webbase-like power-law structure (skewed fanout -> deep rank
    lists) through both bodies on the full 8-device mesh."""
    rng = np.random.default_rng(701)
    a = powerlaw_block_sparse(48, 8, 3.0, rng, "small")
    b = powerlaw_block_sparse(48, 8, 3.0, rng, "small")
    got0 = _ring(monkeypatch, "0", a, b)
    got1 = _ring(monkeypatch, "1", a, b)
    assert got0 == got1


@pytest.mark.parametrize("overlap", ["0", "1"])
def test_deep_cell_tail_matches_oracle(monkeypatch, overlap):
    """A (1 x J) row times (J x 1) column concentrates J/n_dev pairs in one
    (key, slab) cell -- past RANK_UNROLL_MAX, so the dense tail block must
    carry the spill.  J=80 on the 8-device mesh = 10 pairs/cell (tail depth
    2); values bounded, so ring == the reference oracle exactly."""
    from spgemm_tpu.parallel.ring import RANK_UNROLL_MAX
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
    from spgemm_tpu.utils.semantics import spgemm_oracle

    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", overlap)
    rng = np.random.default_rng(704)
    k, J = 2, 80
    assert J // 8 > RANK_UNROLL_MAX - 8 + 1  # stays deep if the cap moves up
    a = BlockSparseMatrix(
        rows=k, cols=J * k, k=k,
        coords=np.stack([np.zeros(J, np.int64),
                         np.arange(J, dtype=np.int64)], axis=1),
        tiles=rng.integers(0, 1 << 20, size=(J, k, k), dtype=np.uint64))
    b = BlockSparseMatrix(
        rows=J * k, cols=k, k=k,
        coords=np.stack([np.arange(J, dtype=np.int64),
                         np.zeros(J, np.int64)], axis=1),
        tiles=rng.integers(0, 1 << 20, size=(J, k, k), dtype=np.uint64))
    got = spgemm_ring(a, b)
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, k, spgemm_oracle(a.to_dict(), b.to_dict(), k))
    assert got == want


def test_overlap_default_on(monkeypatch):
    monkeypatch.delenv("SPGEMM_TPU_RING_OVERLAP", raising=False)
    assert overlap_enabled() is True
    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", "0")
    assert overlap_enabled() is False


def test_overlap_knob_validated(monkeypatch):
    """An invalid knob value must raise immediately, naming the knob --
    never silently run some default (the round-5 'documented knob that
    crashes later' trap)."""
    monkeypatch.setenv("SPGEMM_TPU_RING_OVERLAP", "yes")
    with pytest.raises(ValueError, match="SPGEMM_TPU_RING_OVERLAP"):
        overlap_enabled()
    rng = np.random.default_rng(702)
    a = random_block_sparse(4, 4, 2, 0.5, rng, "small")
    b = random_block_sparse(4, 4, 2, 0.5, rng, "small")
    with pytest.raises(ValueError, match="SPGEMM_TPU_RING_OVERLAP"):
        spgemm_ring(a, b)


def test_ring_phases_recorded(monkeypatch):
    """Observability contract: a ring multiply must land ring_plan /
    ring_hop / ring_fold spans and the ring_steps counter in the ENGINE
    registry (bench.py's detail.phases_s and the CLI --profile report read
    exactly these)."""
    monkeypatch.delenv("SPGEMM_TPU_RING_OVERLAP", raising=False)
    rng = np.random.default_rng(703)
    a = random_block_sparse(6, 6, 2, 0.5, rng, "small")
    b = random_block_sparse(6, 6, 2, 0.5, rng, "small")
    ENGINE.reset()
    try:
        spgemm_ring(a, b)
        snap = ENGINE.snapshot()
        counters = ENGINE.counter_snapshot()
    finally:
        ENGINE.reset()
    for phase in ("ring_plan", "ring_hop", "ring_fold"):
        assert phase in snap and snap[phase] >= 0, snap
    assert counters.get("ring_steps", 0) >= 1, counters
