"""Limb arithmetic (ops/u64) vs exact python ints, including every wrap corner."""

import numpy as np
import pytest

from spgemm_tpu.ops import u64
from spgemm_tpu.utils.gen import ADVERSARIAL_VALUES
from spgemm_tpu.utils.semantics import MAX_INT, scalar_mac

import jax.numpy as jnp


def _pairs(rng, n=2048):
    a = rng.integers(0, 1 << 64, size=n, dtype=np.uint64)
    b = rng.integers(0, 1 << 64, size=n, dtype=np.uint64)
    # splice in the full adversarial cross product
    adv = ADVERSARIAL_VALUES
    aa, bb = np.meshgrid(adv, adv)
    a = np.concatenate([a, aa.ravel()])
    b = np.concatenate([b, bb.ravel()])
    return a, b


def test_hilo_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.integers(0, 1 << 64, size=1000, dtype=np.uint64)
    hi, lo = u64.u64_to_hilo(x)
    assert hi.dtype == np.uint32 and lo.dtype == np.uint32
    assert np.array_equal(u64.hilo_to_u64(hi, lo), x)


def test_mul32_wide_exact():
    rng = np.random.default_rng(1)
    a = rng.integers(0, 1 << 32, size=4096, dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=4096, dtype=np.uint32)
    edges = np.array([0, 1, 0xFFFF, 0x10000, 0xFFFFFFFF, 0x80000000], dtype=np.uint32)
    ea, eb = np.meshgrid(edges, edges)
    a, b = np.concatenate([a, ea.ravel()]), np.concatenate([b, eb.ravel()])
    hi, lo = u64.mul32_wide(jnp.asarray(a), jnp.asarray(b))
    got = u64.hilo_to_u64(np.asarray(hi), np.asarray(lo))
    want = a.astype(np.uint64) * b.astype(np.uint64)  # exact: fits in u64
    assert np.array_equal(got, want)


def test_mul64_lo_matches_wrapping_product():
    rng = np.random.default_rng(2)
    a, b = _pairs(rng)
    ah, al = u64.u64_to_hilo(a)
    bh, bl = u64.u64_to_hilo(b)
    hi, lo = u64.mul64_lo(jnp.asarray(ah), jnp.asarray(al), jnp.asarray(bh), jnp.asarray(bl))
    got = u64.hilo_to_u64(np.asarray(hi), np.asarray(lo))
    want = np.array([(int(x) * int(y)) & MAX_INT for x, y in zip(a, b)], dtype=np.uint64)
    assert np.array_equal(got, want)


def test_addmod_and_mulmod_vs_python():
    rng = np.random.default_rng(3)
    a, b = _pairs(rng)
    ah, al = u64.u64_to_hilo(a)
    bh, bl = u64.u64_to_hilo(b)
    ja, jb = (jnp.asarray(ah), jnp.asarray(al)), (jnp.asarray(bh), jnp.asarray(bl))

    mh, ml = u64.mulmod(*ja, *jb)
    got_mul = u64.hilo_to_u64(np.asarray(mh), np.asarray(ml))
    want_mul = np.array([scalar_mac(0, int(x), int(y)) for x, y in zip(a, b)],
                        dtype=np.uint64)
    assert np.array_equal(got_mul, want_mul)

    sh, sl = u64.addmod(*ja, *jb)
    got_add = u64.hilo_to_u64(np.asarray(sh), np.asarray(sl))

    def ref_add(x, y):
        s = (int(x) + int(y)) & MAX_INT
        return 0 if s == MAX_INT else s

    want_add = np.array([ref_add(x, y) for x, y in zip(a, b)], dtype=np.uint64)
    assert np.array_equal(got_add, want_add)


def test_mac_sequence_order_dependence():
    """The non-associativity quirk itself: folding must match scalar_mac order."""
    rng = np.random.default_rng(4)
    vals_a = rng.integers(0, 1 << 64, size=64, dtype=np.uint64)
    vals_b = rng.integers(0, 1 << 64, size=64, dtype=np.uint64)

    acc_int = 0
    for x, y in zip(vals_a, vals_b):
        acc_int = scalar_mac(acc_int, int(x), int(y))

    acc_h = jnp.zeros((), jnp.uint32)
    acc_l = jnp.zeros((), jnp.uint32)
    for x, y in zip(vals_a, vals_b):
        ah, al = u64.u64_to_hilo(np.uint64(x))
        bh, bl = u64.u64_to_hilo(np.uint64(y))
        acc_h, acc_l = u64.mac(acc_h, acc_l,
                               jnp.uint32(ah), jnp.uint32(al),
                               jnp.uint32(bh), jnp.uint32(bl))
    got = int(u64.hilo_to_u64(np.asarray(acc_h), np.asarray(acc_l)))
    assert got == acc_int


@pytest.mark.parametrize("a,b", [(MAX_INT, MAX_INT), (MAX_INT, 1), (1 << 63, 2),
                                 (MAX_INT - 1, MAX_INT - 1), (0, MAX_INT)])
def test_known_corners(a, b):
    ah, al = u64.u64_to_hilo(np.uint64(a))
    bh, bl = u64.u64_to_hilo(np.uint64(b))
    mh, ml = u64.mulmod(jnp.uint32(ah), jnp.uint32(al), jnp.uint32(bh), jnp.uint32(bl))
    got = int(u64.hilo_to_u64(np.asarray(mh), np.asarray(ml)))
    assert got == scalar_mac(0, a, b)
