"""MatrixMarket converter (utils/mtx)."""

import numpy as np

from spgemm_tpu.utils.mtx import elements_to_blocks, main, mtx_to_block_matrix, read_mtx


MTX_GENERAL = """%%MatrixMarket matrix coordinate real general
% a comment
4 4 5
1 1 1.5
2 1 2.0
3 3 0.25
4 4 7.0
1 4 3.0
"""

MTX_SYM = """%%MatrixMarket matrix coordinate real symmetric
3 3 3
1 1 5.0
2 1 1.0
3 3 2.0
"""

MTX_PATTERN = """%%MatrixMarket matrix coordinate pattern general
2 2 2
1 1
2 2
"""


def test_read_general(tmp_path):
    p = tmp_path / "a.mtx"
    p.write_text(MTX_GENERAL)
    rows, cols, r, c, v = read_mtx(str(p), value_map="pattern")
    assert (rows, cols) == (4, 4)
    assert len(r) == 5
    assert np.all(v == 1)


def test_read_symmetric_mirrors(tmp_path):
    p = tmp_path / "s.mtx"
    p.write_text(MTX_SYM)
    rows, cols, r, c, v = read_mtx(str(p), value_map="pattern")
    have = set(zip(r.tolist(), c.tolist()))
    assert have == {(0, 0), (1, 0), (0, 1), (2, 2)}


def test_value_map_scale(tmp_path):
    p = tmp_path / "a.mtx"
    p.write_text(MTX_GENERAL)
    rows, cols, r, c, v = read_mtx(str(p), value_map="scale", scale=4.0)
    by_coord = dict(zip(zip(r.tolist(), c.tolist()), v.tolist()))
    assert by_coord[(0, 0)] == 6      # 1.5 * 4
    assert by_coord[(2, 2)] == 1      # 0.25 * 4
    assert by_coord[(3, 3)] == 28


def test_elements_to_blocks_tiling():
    r = np.array([0, 1, 3, 2])
    c = np.array([0, 1, 3, 0])
    v = np.array([10, 20, 30, 40], np.uint64)
    m = elements_to_blocks(4, 4, r, c, v, k=2)
    assert m.nnzb == 3
    d = m.to_dict()
    assert set(d.keys()) == {(0, 0), (1, 0), (1, 1)}
    assert d[(0, 0)][0, 0] == 10 and d[(0, 0)][1, 1] == 20
    assert d[(1, 0)][0, 0] == 40
    assert d[(1, 1)][1, 1] == 30


def test_pattern_mtx(tmp_path):
    p = tmp_path / "p.mtx"
    p.write_text(MTX_PATTERN)
    m = mtx_to_block_matrix(str(p), k=2)
    assert m.nnzb == 1
    assert m.tiles[0, 0, 0] == 1 and m.tiles[0, 1, 1] == 1


def test_real_mtx_cross_parser_and_end_to_end_cli(tmp_path):
    """Committed REAL MatrixMarket file (tests/data/gr_12_12.mtx: the 5-point
    grid Laplacian, symmetric real coordinate format with comment lines --
    provenance in tests/data/README.md) driven through the whole stack:

      1. cross-parser check: our read_mtx vs scipy.io.mmread must agree
         element-for-element after symmetric mirroring + the 'scale' map;
      2. convert_to_dir -> reference text directory;
      3. CLI chain product (A @ A) on that directory;
      4. full bit-exact parity of every output tile vs the python oracle.
    """
    import os

    import pytest

    scipy_io = pytest.importorskip(
        "scipy.io", reason="cross-parser check needs scipy")

    from conftest import run_repo_script
    from spgemm_tpu.utils import io_text, semantics
    from spgemm_tpu.utils.mtx import convert_to_dir

    mtx = os.path.join(os.path.dirname(__file__), "data", "gr_12_12.mtx")

    # 1. independent parser agreement (scipy mirrors symmetric storage too)
    rows, cols, r, c, v = read_mtx(mtx, value_map="scale", scale=2.0)
    s = scipy_io.mmread(mtx).tocoo()
    assert (rows, cols) == s.shape
    ours = dict(zip(zip(r.tolist(), c.tolist()), v.tolist()))
    theirs = {(int(rr), int(cc)): int(round(abs(vv * 2.0)))
              for rr, cc, vv in zip(s.row, s.col, s.data)}
    assert ours == theirs

    # 2-4. convert, run the CLI on [A, A], verify every tile vs the oracle
    chain_dir = tmp_path / "chain"
    convert_to_dir([mtx, mtx], str(chain_dir), k=4,
                   value_map="scale", scale=2.0)
    out = tmp_path / "matrix"
    rc = run_repo_script(
        ["-m", "spgemm_tpu.cli", str(chain_dir),
         "--device", "cpu", "--output", str(out)], timeout=300)
    assert rc.returncode == 0, rc.stderr[-2000:]

    a = io_text.read_chain(str(chain_dir), 0, 1, 4)
    want = semantics.spgemm_oracle(a[0].to_dict(), a[1].to_dict(), 4)
    got = io_text.read_matrix(str(out), 4).to_dict()
    want_nz = {key: t for key, t in want.items() if np.any(t)}
    assert set(got) == set(want_nz)
    for key, tile in want_nz.items():
        assert np.array_equal(got[key], tile), key


def test_cli_convert_roundtrip(tmp_path):
    p = tmp_path / "a.mtx"
    p.write_text(MTX_GENERAL)
    out = tmp_path / "dir"
    assert main([str(p), str(p), str(out), "--k", "2"]) == 0
    from spgemm_tpu.utils import io_text
    n, k = io_text.read_size(str(out))
    assert (n, k) == (2, 2)
    mats = io_text.read_chain(str(out), 0, 1, 2)
    assert mats[0] == mats[1]
    assert mats[0].nnzb > 0
