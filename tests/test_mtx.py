"""MatrixMarket converter (utils/mtx)."""

import numpy as np

from spgemm_tpu.utils.mtx import elements_to_blocks, main, mtx_to_block_matrix, read_mtx


MTX_GENERAL = """%%MatrixMarket matrix coordinate real general
% a comment
4 4 5
1 1 1.5
2 1 2.0
3 3 0.25
4 4 7.0
1 4 3.0
"""

MTX_SYM = """%%MatrixMarket matrix coordinate real symmetric
3 3 3
1 1 5.0
2 1 1.0
3 3 2.0
"""

MTX_PATTERN = """%%MatrixMarket matrix coordinate pattern general
2 2 2
1 1
2 2
"""


def test_read_general(tmp_path):
    p = tmp_path / "a.mtx"
    p.write_text(MTX_GENERAL)
    rows, cols, r, c, v = read_mtx(str(p), value_map="pattern")
    assert (rows, cols) == (4, 4)
    assert len(r) == 5
    assert np.all(v == 1)


def test_read_symmetric_mirrors(tmp_path):
    p = tmp_path / "s.mtx"
    p.write_text(MTX_SYM)
    rows, cols, r, c, v = read_mtx(str(p), value_map="pattern")
    have = set(zip(r.tolist(), c.tolist()))
    assert have == {(0, 0), (1, 0), (0, 1), (2, 2)}


def test_value_map_scale(tmp_path):
    p = tmp_path / "a.mtx"
    p.write_text(MTX_GENERAL)
    rows, cols, r, c, v = read_mtx(str(p), value_map="scale", scale=4.0)
    by_coord = dict(zip(zip(r.tolist(), c.tolist()), v.tolist()))
    assert by_coord[(0, 0)] == 6      # 1.5 * 4
    assert by_coord[(2, 2)] == 1      # 0.25 * 4
    assert by_coord[(3, 3)] == 28


def test_elements_to_blocks_tiling():
    r = np.array([0, 1, 3, 2])
    c = np.array([0, 1, 3, 0])
    v = np.array([10, 20, 30, 40], np.uint64)
    m = elements_to_blocks(4, 4, r, c, v, k=2)
    assert m.nnzb == 3
    d = m.to_dict()
    assert set(d.keys()) == {(0, 0), (1, 0), (1, 1)}
    assert d[(0, 0)][0, 0] == 10 and d[(0, 0)][1, 1] == 20
    assert d[(1, 0)][0, 0] == 40
    assert d[(1, 1)][1, 1] == 30


def test_pattern_mtx(tmp_path):
    p = tmp_path / "p.mtx"
    p.write_text(MTX_PATTERN)
    m = mtx_to_block_matrix(str(p), k=2)
    assert m.nnzb == 1
    assert m.tiles[0, 0, 0] == 1 and m.tiles[0, 1, 1] == 1


def test_cli_convert_roundtrip(tmp_path):
    p = tmp_path / "a.mtx"
    p.write_text(MTX_GENERAL)
    out = tmp_path / "dir"
    assert main([str(p), str(p), str(out), "--k", "2"]) == 0
    from spgemm_tpu.utils import io_text
    n, k = io_text.read_size(str(out))
    assert (n, k) == (2, 2)
    mats = io_text.read_chain(str(out), 0, 1, 2)
    assert mats[0] == mats[1]
    assert mats[0].nnzb > 0
