"""Device-pool scheduler (PR 12): slice specs (parallel/mesh.slice_pool),
per-tenant fair queuing + in-flight caps (serve/queue), estimator-priced
placement + work stealing (serve/placement, Daemon._accepts), per-slice
watchdog degrade, the client wait backoff, and the protocol v2 tenant
field -- tier-1 on the 8-vdev CPU backend (injected runners everywhere
the engine itself is not the subject)."""

import json
import socket
import threading
import time

import numpy as np
import pytest

from spgemm_tpu.parallel import mesh
from spgemm_tpu.serve import client, placement, protocol
from spgemm_tpu.serve.daemon import Daemon
from spgemm_tpu.serve.queue import (Job, JobQueue, TenantCapExceeded)
from spgemm_tpu.utils import io_text
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_chain
from spgemm_tpu.utils.semantics import chain_oracle


def _chain_folder(tmp_path, n=3, k=2, seed=7, name="chain_in"):
    """A reference-format input dir + the oracle's output bytes."""
    mats = random_chain(n, 4, k, 0.5, np.random.default_rng(seed), "full")
    folder = str(tmp_path / name)
    io_text.write_chain_dir(folder, mats, k)
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_bytes = io_text.format_matrix(BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k, want).prune_zeros())
    return folder, want_bytes


@pytest.fixture
def make_daemon(tmp_path):
    """Daemon factory bound to a per-test socket; stops them on teardown."""
    daemons = []

    def _make(idx=0, **kw):
        d = Daemon(str(tmp_path / f"d{idx}.sock"), **kw)
        d.start()
        daemons.append(d)
        return d

    yield _make
    for d in daemons:
        d.stop()


def _wait_until(pred, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ------------------------------------------------------------ slice spec --
def test_slice_spec_terms_and_device_assignment():
    """The `1x4+4` idiom: one 4-device slice (devices 0-3) plus four
    singles (4-7), in declaration order."""
    pool = mesh.slice_pool("1x4+4", 8)
    assert [s.width for s in pool] == [4, 1, 1, 1, 1]
    assert pool[0].device_ids == (0, 1, 2, 3)
    assert [s.device_ids for s in pool[1:]] == [(4,), (5,), (6,), (7,)]
    # no '*': the narrowest width class is the default placement
    assert [s.default for s in pool] == [False, True, True, True, True]
    # names are stable and carry the width
    assert pool[0].name == "s0w4" and pool[1].name == "s1w1"


def test_slice_spec_star_marks_default():
    pool = mesh.slice_pool("1x4*+4", 8)
    assert [s.default for s in pool] == [True, False, False, False, False]


def test_slice_spec_single_is_one_single_device_slice():
    pool = mesh.slice_pool("1", None)  # no device count needed
    assert len(pool) == 1 and pool[0].device_ids == (0,)


def test_slice_spec_auto_builds_singles_plus_full_mesh():
    pool = mesh.slice_pool("auto", 4)
    assert [s.width for s in pool] == [1, 1, 1, 1, 4]
    assert pool[-1].device_ids == (0, 1, 2, 3)
    assert all(s.default for s in pool[:4]) and not pool[-1].default
    assert pool[-1].overlaps(pool[0])


@pytest.mark.parametrize("spec", ["", "bogus", "0x2", "2x0", "4x"])
def test_slice_spec_garbage_raises_naming_the_spec(spec):
    with pytest.raises(mesh.SliceSpecError):
        mesh.parse_slice_spec(spec, 8)


def test_slice_spec_overcommit_and_auto_need_devices():
    with pytest.raises(mesh.SliceSpecError, match="12 devices"):
        mesh.parse_slice_spec("1x4+8", 8)
    with pytest.raises(mesh.SliceSpecError, match="device count"):
        mesh.parse_slice_spec("auto", None)
    # explicit specs are trusted when the count is unknown
    assert mesh.parse_slice_spec("1x4+8", None)


# ---------------------------------------------------------- fair queuing --
def test_tenant_round_robin_no_starvation():
    """The satellite contract: a chatty tenant's burst never starves a
    quiet tenant's single job past one round -- it is served on the very
    next pop after its submit."""
    q = JobQueue(cap=16)
    chatty = [Job(f"a{i}", "f", "o", {}, tenant="chatty")
              for i in range(4)]
    for j in chatty:
        q.submit(j)
    quiet = Job("b0", "f", "o", {}, tenant="quiet")
    q.submit(quiet)
    order = [q.next(0.01).id for _ in range(5)]
    assert order[0] == "a0"           # chatty was first in
    assert "b0" in order[:2]          # quiet lands within its round
    assert order.count("b0") == 1
    # within a tenant, strict FIFO
    assert [i for i in order if i.startswith("a")] == \
        ["a0", "a1", "a2", "a3"]


def test_tenant_absent_maps_to_default_and_rides_snapshot():
    j = Job("j1", "f", "o", {})
    assert j.tenant == protocol.DEFAULT_TENANT
    snap = j.snapshot()
    assert snap["tenant"] == protocol.DEFAULT_TENANT
    assert snap["slice"] is None and snap["placement"] is None


def test_tenant_inflight_cap_is_structured_and_releases():
    q = JobQueue(cap=16, tenant_inflight=2)
    a, b = (Job(f"j{i}", "f", "o", {}, tenant="t") for i in (1, 2))
    q.submit(a)
    q.submit(b)
    with pytest.raises(TenantCapExceeded) as ei:
        q.submit(Job("j3", "f", "o", {}, tenant="t"))
    assert ei.value.tenant == "t" and ei.value.cap == 2
    # another tenant is not capped by t's flight
    q.submit(Job("other", "f", "o", {}, tenant="u"))
    # a terminal release frees the slot (queued jobs count as in flight
    # until released)
    a2 = q.next(0.01)
    a2.start()
    a2.finish("done")
    q.release(a2)
    q.submit(Job("j4", "f", "o", {}, tenant="t"))  # fits again
    assert q.tenants()["t"]["inflight"] == 2


def test_release_of_never_admitted_job_frees_no_slot():
    """The journal-replay rejection path finishes (and releases) a job
    whose submit RAISED: that release must not decrement an in-flight
    slot an admitted job owns, or the tenant cap silently widens."""
    q = JobQueue(cap=16, tenant_inflight=2)
    for i in (1, 2):
        q.submit(Job(f"j{i}", "f", "o", {}, tenant="t"))
    rej = Job("j3", "f", "o", {}, tenant="t")
    with pytest.raises(TenantCapExceeded):
        q.submit(rej)
    rej.finish("failed", error={"code": "tenant-cap", "message": "x"})
    q.release(rej)  # what _observe_terminal does on the replay path
    assert q.tenants()["t"]["inflight"] == 2  # slots intact
    with pytest.raises(TenantCapExceeded):
        q.submit(Job("j4", "f", "o", {}, tenant="t"))


def test_tenant_cap_rejection_is_a_wire_error_not_a_hang(tmp_path,
                                                         make_daemon):
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        release.wait(30)

    d = make_daemon(runner=runner, tenant_inflight=1)
    try:
        client.submit(folder, d.socket_path, tenant="chatty")
        with pytest.raises(client.ServeError) as ei:
            client.submit(folder, d.socket_path, tenant="chatty")
        assert ei.value.code == protocol.E_TENANT_CAP
        # a different tenant is admitted; stats reports both tenants
        client.submit(folder, d.socket_path, tenant="quiet")
        st = client.stats(d.socket_path)
        assert "chatty" in st["tenants"]
        assert st["tenant_inflight_cap"] == 1
    finally:
        release.set()


def test_bad_tenant_name_is_bad_request(tmp_path, make_daemon):
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    for bad in ("", "has space", "x" * 65, 7):
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(10.0)
            s.connect(d.socket_path)
            s.sendall(protocol.encode({"v": protocol.PROTOCOL_VERSION,
                                       "op": "submit", "folder": folder,
                                       "tenant": bad}))
            resp = json.loads(next(protocol.read_lines(s)))
        assert resp["ok"] is False
        assert resp["error"]["code"] == protocol.E_BAD_REQUEST


def test_protocol_v1_requests_still_served(make_daemon):
    """The version bump is backward compatible: a v1 client (no tenant
    field) keeps working against the v2 daemon."""
    d = make_daemon(runner=lambda job, degraded=False: None)
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(10.0)
        s.connect(d.socket_path)
        s.sendall(protocol.encode({"v": 1, "op": "stats"}))
        resp = json.loads(next(protocol.read_lines(s)))
    assert resp["ok"] is True and resp["daemon"] == "spgemmd"


def test_version_for_is_the_capability_table():
    """ONE negotiation rule (protocol.version_for over FIELD_MIN_VERSION,
    itself derived from the per-op REQUEST_FIELDS tables) replaces
    per-field stamping -- driven from the registry, so every op's full
    request round-trips at every accepted version with no hand-listed
    field cases to forget."""
    for op in protocol.OPS:
        fields = protocol.REQUEST_FIELDS[op]
        full = {"op": op, **{name: f"x-{name}" for name in fields}}
        # the minimum carrying version is the max field min-version
        want = max([1, *fields.values()])
        assert protocol.version_for(full) == want, op
        # stripping at each accepted version keeps exactly the fields
        # that version carries -- and never touches the envelope
        for v in protocol.ACCEPTED_VERSIONS:
            stripped = protocol.strip_for_version(full, v)
            assert stripped["op"] == op
            kept = {name for name in fields if name in stripped}
            assert kept == {name for name, mv in fields.items()
                            if mv <= v}, (op, v)
            # a stripped request is carryable at the version it was
            # stripped for
            assert protocol.version_for(stripped) <= v
    # every op's bare request is v1 (first-contact compatibility)
    for op in protocol.OPS:
        assert protocol.version_for({"op": op}) == 1
    # the daemon's version-mismatch wording parses back to its versions
    assert protocol.accepted_from_error(
        "protocol version mismatch: daemon speaks v2 (accepts v1/v2), "
        "request carries v=3") == (1, 2)
    assert protocol.accepted_from_error("something else") == ()
    # ANCHORED: a bad-request that merely ECHOES client data containing
    # the accepts wording (e.g. a trace of literally `accepts v1/v2`)
    # must not read as a version mismatch -- a spoofed match would
    # silently strip-and-retry a field the daemon explicitly rejected
    assert protocol.accepted_from_error(
        "trace must be 32 lowercase hex chars (a 128-bit trace "
        "context), got 'accepts v1/v2'") == ()


def test_registry_min_versions_span_the_protocol():
    """The registry declares at least one field at every version up to
    PROTOCOL_VERSION (otherwise the version constant has drifted past
    the tables) -- response-side-only versions count (v4 adds only the
    fleet router's `backend`/`backends` answer fields, so clients never
    stamp it) -- and FIELD_MIN_VERSION is exactly the post-v1 slice of
    the request tables."""
    all_versions = {v for table in (protocol.REQUEST_FIELDS,
                                    protocol.RESPONSE_FIELDS)
                    for fields in table.values()
                    for v in fields.values()}
    assert set(range(2, protocol.PROTOCOL_VERSION + 1)) <= all_versions
    derived = {name: v for fields in protocol.REQUEST_FIELDS.values()
               for name, v in fields.items() if v > 1}
    assert protocol.FIELD_MIN_VERSION == derived


def test_client_stamps_lowest_version_for_fields(tmp_path, make_daemon,
                                                 monkeypatch):
    """The upgraded client stamps v1 on a featureless request and the
    capability-table version exactly when a versioned field rides along
    (submit always carries the client-minted v3 trace context)."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    sent = []
    real_encode = protocol.encode
    # protocol.encode is shared with the in-process daemon's responses:
    # keep only REQUEST messages (they carry an op)
    monkeypatch.setattr(client.protocol, "encode",
                        lambda msg: sent.append(msg) or real_encode(msg))
    client.stats(d.socket_path)
    client.submit(folder, d.socket_path)
    reqs = [m for m in sent if "op" in m]
    assert [m["v"] for m in reqs] == [1, 3]
    assert protocol.valid_trace(reqs[-1]["trace"])


def test_client_downgrades_against_older_daemon(tmp_path, make_daemon,
                                                monkeypatch):
    """Rolling upgrade, new-client-vs-old-daemon direction: the older
    daemon's version-mismatch answer names what it accepts, and the
    client retries ONCE at the best mutually-spoken version with the
    too-new fields stripped -- the daemon then supplies the fallback
    (it mints the trace the stripped request no longer carries)."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    # simulate a v2-era daemon: its strict version gate rejects v3
    monkeypatch.setattr(protocol, "ACCEPTED_VERSIONS", (1, 2))
    sent = []
    real_encode = protocol.encode
    monkeypatch.setattr(client.protocol, "encode",
                        lambda msg: sent.append(msg) or real_encode(msg))
    resp = client.submit(folder, d.socket_path, tenant="alice")
    reqs = [m for m in sent if m.get("op") == "submit"]
    assert [m["v"] for m in reqs] == [3, 2]
    assert "trace" not in reqs[1] and reqs[1]["tenant"] == "alice"
    assert resp["ok"] and resp["id"]
    # a genuinely bad request surfaces after the one downgrade retry
    # (v3 -> version gate -> v2 -> folder check), never a retry loop
    with pytest.raises(client.ServeError) as ei:
        client.submit(str(tmp_path / "missing"), d.socket_path)
    assert ei.value.code == protocol.E_BAD_REQUEST
    assert "chain input" in ei.value.message
    assert len([m for m in sent if m.get("op") == "submit"]) == 4


def test_accept_claims_slice_under_the_queue_lock(tmp_path, make_daemon):
    """Overlapping-slice mutual exclusion is decided at the ACCEPT, not
    at the executor's later bookkeeping: a predicate that returns True
    claims sl.current immediately, so an overlapping slice probing
    _devices_held in the same dispatch round can never double-book the
    device."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None,
                    slices="auto", n_devices=2)
    d._stop.set()  # freeze the executors; we drive the predicate by hand
    for sl in d.slices:
        sl.thread.join(timeout=5.0)
        sl.current = None
    single, full = d.slices[0], d.slices[2]
    from spgemm_tpu.serve.queue import Job as _Job
    j1 = _Job("c1", folder, "o", {})
    j2 = _Job("c2", folder, "o", {})
    j2.placement = {"class": "large"}  # prefers the full-mesh slice
    assert d._accepts(single, j1) is True
    assert single.current is j1  # claimed at accept time
    # the full-mesh slice shares device 0 with the claimed single: it
    # must refuse j2 in the same round, not dispatch concurrently
    assert d._accepts(full, j2) is False


def test_lone_wide_slice_pins_all_its_devices(tmp_path, make_daemon):
    """`--slices 1x4` (one wide slice, nothing else) must shard over its
    devices, never silently shrink to the single-device legacy path."""
    folder, _ = _chain_folder(tmp_path)
    seen = {}

    def runner(job, degraded=False):
        seen["device_ids"] = job.device_ids

    d = make_daemon(runner=runner, slices="1x4", n_devices=4)
    j = client.submit(folder, d.socket_path)
    resp = client.wait(j["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "done"
    assert seen["device_ids"] == (0, 1, 2, 3)


def test_one_degraded_slice_keeps_daemon_reason_null(tmp_path,
                                                     make_daemon):
    """The pre-pool alerting contract: daemon-level degrade_reason is set
    if-and-only-if the daemon-level degraded flag is -- a healthy pool
    with one bad slice reports the reason per-slice only."""
    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    first = threading.Event()

    def runner(job, degraded=False):
        if not first.is_set() and not degraded:
            first.set()
            unwedge.wait(60)

    d = make_daemon(runner=runner, slices="2", n_devices=2,
                    job_timeout_s=0.3, wedge_grace_s=0.2,
                    probe=lambda: "timeout")
    try:
        j = client.submit(folder, d.socket_path)
        client.wait(j["id"], d.socket_path, timeout=30)
        _wait_until(lambda: any(s.degraded for s in d.slices),
                    msg="wedged slice degrades")
        st = client.stats(d.socket_path)
        assert st["degraded"] is False
        assert st["degrade_reason"] is None          # daemon-level: null
        bad = next(s for s in st["slices"] if s["degraded"])
        assert bad["degrade_reason"]                 # slice-level: set
    finally:
        unwedge.set()


# ------------------------------------------------------------- placement --
def test_placement_route_classes(tmp_path, monkeypatch):
    placement.clear()
    folder, _ = _chain_folder(tmp_path, name="routed")
    # first contact, small input: the spec's default slice
    assert placement.route(folder)["class"] == "default"
    # priced: below the webbase threshold -> small, above -> large
    placement.note_mass(folder, 10.0)
    assert placement.route(folder) == {
        "class": "small", "source": "estimate", "mass": 10.0}
    placement.note_mass(folder, placement.LARGE_MASS_PAIRS * 2)
    assert placement.route(folder)["class"] == "large"
    # a content change invalidates the stat-signature key: re-priced
    time.sleep(0.01)
    (tmp_path / "routed" / "matrix1").write_text(
        (tmp_path / "routed" / "matrix1").read_text() + " ")
    assert placement.route(folder)["class"] == "default"
    # first contact, webbase-class bytes: wide without an estimate
    monkeypatch.setattr(placement, "LARGE_INPUT_BYTES", 1)
    got = placement.route(folder)
    assert got["class"] == "large" and got["source"] == "bytes"
    st = placement.stats()
    assert st["book_entries"] >= 1 and st["routed"]["large"] >= 2


def test_estimate_chain_mass_prices_first_pass_pairs():
    from spgemm_tpu.ops import estimate

    a = np.array([[0, 0], [0, 1], [1, 0]], np.int64)
    b = np.array([[0, 0], [1, 1]], np.int64)
    # exact tiny join: rows of a join b's row index -> 3 pairs
    assert estimate.pair_mass(a, b) == 3.0
    # helper2 first pass: (0,1) only for a 3-chain
    assert estimate.chain_mass([a, b, a]) == 3.0
    assert estimate.chain_mass([a]) == 0.0


# ----------------------------------------------------- pool dispatching --
def test_two_slices_run_jobs_concurrently(tmp_path, make_daemon):
    folder, _ = _chain_folder(tmp_path)
    started, release = [], threading.Event()

    def runner(job, degraded=False):
        started.append(job.id)
        release.wait(30)

    d = make_daemon(runner=runner, slices="2", n_devices=2)
    try:
        for _ in range(2):
            client.submit(folder, d.socket_path)
        # a single-executor daemon can never have two jobs in flight
        _wait_until(lambda: len(started) == 2,
                    msg="two jobs running concurrently")
        st = client.stats(d.socket_path)
        assert sum(1 for s in st["slices"] if s["busy"]) == 2
    finally:
        release.set()


def test_single_slice_default_is_legacy_executor(tmp_path, make_daemon):
    """SPGEMM_TPU_SERVE_SLICES=1 (the default) is the whole-pool A/B:
    one slice, and jobs run with default (uncommitted) device placement
    exactly like the pre-pool daemon."""
    folder, _ = _chain_folder(tmp_path)
    seen = {}

    def runner(job, degraded=False):
        seen["device_ids"] = job.device_ids
        seen["slice"] = job.slice

    d = make_daemon(runner=runner)
    assert len(d.slices) == 1 and d.slices[0].width == 1
    j = client.submit(folder, d.socket_path)
    resp = client.wait(j["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "done"
    assert seen["device_ids"] is None        # legacy default placement
    assert seen["slice"] == d.slices[0].name


def test_work_stealing_when_preferred_slice_busy(tmp_path, make_daemon):
    """An idle off-class slice takes the job when every preferred slice
    is busy: `1x2+1` has one wide + one (default) narrow slice, so the
    second default-class job is stolen by the wide slice instead of
    queueing behind the narrow one."""
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        release.wait(30)

    d = make_daemon(runner=runner, slices="1x2+1", n_devices=3)
    try:
        narrow = next(s.name for s in d.slices if s.width == 1)
        wide = next(s.name for s in d.slices if s.width == 2)
        j1 = client.submit(folder, d.socket_path)
        _wait_until(lambda: any(s.current for s in d.slices),
                    msg="first job picked up")
        j2 = client.submit(folder, d.socket_path)
        _wait_until(lambda: sum(1 for s in d.slices if s.current) == 2,
                    msg="second job stolen by the idle slice")
        snap1 = client.status(j1["id"], d.socket_path)["job"]
        snap2 = client.status(j2["id"], d.socket_path)["job"]
        assert snap1["slice"] == narrow and not snap1["stolen"]
        assert snap2["slice"] == wide and snap2["stolen"]
        st = client.stats(d.socket_path)
        assert next(s for s in st["slices"]
                    if s["name"] == wide)["steals"] == 1
    finally:
        release.set()


def test_overlapping_slices_are_mutually_exclusive(tmp_path, make_daemon):
    """`auto`'s full-mesh slice shares devices with the singles: it must
    not dispatch while a device-owning single is busy."""
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        release.wait(30)

    d = make_daemon(runner=runner, slices="auto", n_devices=2)
    try:
        for _ in range(3):
            client.submit(folder, d.socket_path)
        _wait_until(lambda: sum(1 for s in d.slices if s.current) == 2,
                    msg="both singles busy")
        time.sleep(0.6)  # give the full-mesh slice every chance to err
        full = next(s for s in d.slices if s.width == 2)
        assert full.current is None  # its devices are held by the singles
        assert client.stats(d.socket_path)["jobs"]["queued"] == 1
    finally:
        release.set()


# -------------------------------------------------- per-slice degrade ----
def test_one_wedged_slice_degrades_alone(tmp_path, make_daemon):
    """The acceptance contract: one wedged slice degrades (CPU failover)
    and is excluded from placement while the rest keep serving; stats and
    the Prometheus per-slice series expose it."""
    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    first = threading.Event()

    def runner(job, degraded=False):
        if not first.is_set() and not degraded:
            first.set()
            unwedge.wait(60)  # hung backend call: no beats, no return

    d = make_daemon(runner=runner, slices="2", n_devices=2,
                    job_timeout_s=0.3, wedge_grace_s=0.2,
                    probe=lambda: "timeout")
    try:
        j1 = client.submit(folder, d.socket_path)
        resp = client.wait(j1["id"], d.socket_path, timeout=30)
        assert resp["job"]["state"] == "failed"
        assert resp["job"]["error"]["code"] == protocol.E_JOB_TIMEOUT
        _wait_until(lambda: any(s.degraded for s in d.slices),
                    msg="wedged slice degrades")
        # the POOL is not degraded: one healthy slice remains
        assert d.degraded is False
        # and it keeps serving new jobs on the device path
        j2 = client.submit(folder, d.socket_path, {"timeout_s": 0})
        resp2 = client.wait(j2["id"], d.socket_path, timeout=30)
        assert resp2["job"]["state"] == "done"
        assert resp2["job"]["detail"]["degraded"] is False
        st = client.stats(d.socket_path)
        assert st["degraded"] is False
        assert st["slices_degraded"] == 1
        bad = next(s for s in st["slices"] if s["degraded"])
        assert bad["degrade_reason"]
        # the scrape surface carries the per-slice series
        text = client.metrics(d.socket_path)
        assert f'spgemm_slice_degraded{{slice="{bad["name"]}"}} 1' in text
        assert "spgemm_slice_busy{" in text
        assert "spgemm_slice_jobs_total{" in text
    finally:
        unwedge.set()


def test_all_slices_degraded_still_serves_and_flags_daemon(tmp_path,
                                                           make_daemon):
    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    hangs = []

    def runner(job, degraded=False):
        if not degraded and len(hangs) < 2:
            hangs.append(job.id)
            unwedge.wait(60)

    d = make_daemon(runner=runner, slices="2", n_devices=2,
                    job_timeout_s=0.3, wedge_grace_s=0.2,
                    probe=lambda: "timeout")
    try:
        for _ in range(2):
            j = client.submit(folder, d.socket_path)
            client.wait(j["id"], d.socket_path, timeout=30)
        _wait_until(lambda: all(s.degraded for s in d.slices),
                    msg="both slices degrade")
        assert d.degraded is True  # the whole pool is down
        # degraded slices still serve, host-only
        j = client.submit(folder, d.socket_path, {"timeout_s": 0})
        resp = client.wait(j["id"], d.socket_path, timeout=30)
        assert resp["job"]["state"] == "done"
        assert resp["job"]["detail"]["degraded"] is True
    finally:
        unwedge.set()


# ------------------------------------------------------- client backoff --
def test_client_wait_backs_off_between_slices(tmp_path, make_daemon,
                                              monkeypatch):
    """The satellite regression: a slow job must not make the waiter
    hammer the accept loop -- reconnects between expired wait slices are
    exponentially spaced (capped), so the request count stays near
    logarithmic in the wait, not linear."""
    folder, _ = _chain_folder(tmp_path)

    def runner(job, degraded=False):
        time.sleep(1.2)

    d = make_daemon(runner=runner)
    monkeypatch.setattr(client, "WAIT_SLICE_S", 0.05)
    calls = []
    real_request = client.request

    def counting_request(msg, *a, **kw):
        if msg.get("op") == "wait":
            calls.append(time.time())
        return real_request(msg, *a, **kw)

    monkeypatch.setattr(client, "request", counting_request)
    j = client.submit(folder, d.socket_path)
    resp = client.wait(j["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "done"
    # 1.2 s of waiting at 0.05 s slices would be ~24 reconnects without
    # backoff; the doubling schedule needs well under half that
    assert 2 <= len(calls) <= 12
    gaps = [b - a for a, b in zip(calls, calls[1:])]
    assert max(gaps) > 0.15  # the backoff actually grew past the slice


# ------------------------------------------------- end-to-end trace ------
def test_submit_trace_context_threads_through(tmp_path, make_daemon):
    """The client-minted 128-bit trace context rides the submit, the
    status snapshot, and every span the job emits -- replacing the
    job-id-as-trace_id aliasing (the id is one daemon's namespace, the
    trace crosses processes)."""
    from spgemm_tpu.obs import trace as obs_trace
    obs_trace.RECORDER.clear()  # job ids repeat across in-process daemons
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    want = protocol.mint_trace()
    resp = client.submit(folder, d.socket_path, trace=want)
    assert resp["trace"] == want
    final = client.wait(resp["id"], d.socket_path, timeout=30)
    assert final["job"]["state"] == "done"
    assert final["job"]["trace"] == want
    spans = [ev for ev in client.trace(d.socket_path)
             if (ev.get("args") or {}).get("job_id") == resp["id"]]
    assert spans
    assert all(ev["args"]["trace_id"] == want for ev in spans)


def test_submit_without_trace_gets_daemon_minted_one(tmp_path,
                                                     make_daemon):
    """v1/v2 submits (no trace field) fall back to a daemon-minted
    context -- the trace is never absent, never the job id."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(10.0)
        s.connect(d.socket_path)
        s.sendall(protocol.encode({"v": 2, "op": "submit",
                                   "folder": folder, "tenant": "legacy"}))
        resp = json.loads(next(protocol.read_lines(s)))
    assert resp["ok"] is True
    assert protocol.valid_trace(resp["trace"])
    assert resp["trace"] != resp["id"]


def test_submit_malformed_trace_is_bad_request(tmp_path, make_daemon):
    """A client that tried to thread a trace must hear it failed, not
    silently get a re-mint."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    for bad in ("short", "G" * 32, "AB" * 16, 7):
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(10.0)
            s.connect(d.socket_path)
            s.sendall(protocol.encode({"v": 3, "op": "submit",
                                       "folder": folder, "trace": bad}))
            resp = json.loads(next(protocol.read_lines(s)))
        assert resp["ok"] is False
        assert resp["error"]["code"] == protocol.E_BAD_REQUEST


def test_journal_replay_restores_trace_context(tmp_path):
    """A restarted daemon re-queues a journaled job under its ORIGINAL
    trace context -- the stitched trace survives the restart."""
    from spgemm_tpu.serve.daemon import Daemon, journal_frame
    sock = str(tmp_path / "dj.sock")
    trace_id = protocol.mint_trace()
    rec = {"event": "submit", "id": "job-7", "folder": str(tmp_path),
           "output": str(tmp_path / "o"), "options": {},
           "timeout_s": 0.0, "tenant": "t", "trace": trace_id}
    with open(sock + ".journal", "w", encoding="utf-8") as f:
        f.write(journal_frame(rec))
    done = threading.Event()
    seen = {}

    def runner(job, degraded=False):
        seen["trace"] = job.trace_id
        done.set()

    d = Daemon(sock, runner=runner)
    d.start()
    try:
        assert done.wait(10), "replayed job never ran"
        assert seen["trace"] == trace_id
    finally:
        d.stop()


def test_pool_trace_dump_carries_per_slice_tracks(tmp_path, make_daemon):
    """Satellite: a 2-slice daemon's Perfetto export names each slice
    executor's thread (thread_name metadata tracks) and the two slices'
    job span sets are DISJOINT -- concurrent jobs never bleed spans
    across slices."""
    from spgemm_tpu.obs import trace as obs_trace
    obs_trace.RECORDER.clear()  # job ids repeat across in-process daemons
    folder, _ = _chain_folder(tmp_path)
    started, release = [], threading.Event()

    def runner(job, degraded=False):
        started.append(job.id)
        release.wait(30)

    d = make_daemon(runner=runner, slices="2", n_devices=2)
    try:
        ids = [client.submit(folder, d.socket_path)["id"]
               for _ in range(2)]
        _wait_until(lambda: len(started) == 2,
                    msg="both jobs running on their slices")
    finally:
        release.set()
    for jid in ids:
        resp = client.wait(jid, d.socket_path, timeout=30)
        assert resp["job"]["state"] == "done"
    events = client.trace(d.socket_path)
    thread_names = {ev["args"]["name"] for ev in events
                    if ev.get("ph") == "M"
                    and ev["name"] == "thread_name"}
    assert any("spgemmd-executor-s0w1" in n for n in thread_names)
    assert any("spgemmd-executor-s1w1" in n for n in thread_names)
    assert any(ev.get("ph") == "M" and ev["name"] == "process_name"
               for ev in events)
    by_slice: dict = {}
    for ev in events:
        args = ev.get("args") or {}
        if args.get("slice") and args.get("job_id"):
            by_slice.setdefault(args["slice"], set()).add(args["job_id"])
    assert set(by_slice) == {"s0w1", "s1w1"}
    jobs_a, jobs_b = by_slice["s0w1"], by_slice["s1w1"]
    assert jobs_a and jobs_b and jobs_a.isdisjoint(jobs_b)
    assert jobs_a | jobs_b == set(ids)


def test_tenant_label_cardinality_capped_on_scrape(tmp_path, make_daemon,
                                                   monkeypatch):
    """Satellite: a tenant-id-per-request client cannot grow the scrape
    without bound -- past the top-K-by-recency cap the remaining
    tenants' queue depths aggregate into one `other` row."""
    from spgemm_tpu.obs import slo as obs_slo
    monkeypatch.setattr(obs_slo, "TENANT_RETAIN", 3)
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        release.wait(30)

    d = make_daemon(runner=runner)
    try:
        for i in range(6):
            client.submit(folder, d.socket_path, tenant=f"t{i}")
        _wait_until(lambda: any(s.current for s in d.slices),
                    msg="first job picked up")
        text = client.metrics(d.socket_path)
        rows = [line for line in text.splitlines()
                if line.startswith("spgemmd_tenant_queue_depth{")]
        assert len(rows) <= 4  # top 3 by recency + the `other` aggregate
        assert any('tenant="other"' in line for line in rows)
        # nothing is dropped, only aggregated: depths still sum to the
        # queued total (6 submitted, 1 running)
        total = sum(float(line.rsplit(" ", 1)[1]) for line in rows)
        assert total == 5.0
        # the newest tenants keep their own labels
        assert any('tenant="t5"' in line for line in rows)
    finally:
        release.set()


# ------------------------------------------------ real-engine pool proof --
def test_pool_serves_real_engine_bit_exact_across_slices(tmp_path,
                                                         make_daemon):
    """Two real chain jobs through a 2-slice pool: both bit-exact vs the
    oracle, each on its own slice with committed device placement --
    slice width and placement steer wall, never bits."""
    fa, wa = _chain_folder(tmp_path, seed=31, name="pool_a")
    fb, wb = _chain_folder(tmp_path, seed=32, name="pool_b")
    d = make_daemon(slices="2", n_devices=2)  # default runner: real engine
    outs = {}
    for folder in (fa, fb):
        out = folder + ".out"
        j = client.submit(folder, d.socket_path, {"output": out})
        outs[folder] = (j["id"], out)
    slices_used = set()
    for folder, want in ((fa, wa), (fb, wb)):
        jid, out = outs[folder]
        resp = client.wait(jid, d.socket_path, timeout=300)
        assert resp["job"]["state"] == "done", resp["job"]["error"]
        assert open(out, "rb").read() == want
        slices_used.add(resp["job"]["slice"])
        # pool jobs carry committed per-slice placement
        assert resp["job"]["detail"]["slice"] in ("s0w1", "s1w1")
    assert len(slices_used) == 2
