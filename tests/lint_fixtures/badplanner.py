"""spgemm-lint BKD fixture: backend touches inside a @host_only helper.

Planner/worker-thread code (chain.py plan-ahead, OOC staging helpers) is
marked with utils/backend_probe.host_only and must never touch a backend:
a dead TPU hangs inside backend init, and a hang on a worker thread wedges
the whole pipeline with no exception to fail over on.  The BKD rule scans
the WHOLE decorated body, not just import time.  Never imported.
"""

import jax
import jax.numpy as jnp

from spgemm_tpu.utils.backend_probe import host_only


@host_only
def bad_planner_helper(join):
    platform = jax.devices()[0].platform  # seeded BKD: backend touch on a
    #                                       planner thread
    pa = jnp.asarray(join)  # seeded BKD: array materialization initializes
    #                         the backend just as surely
    return platform, pa


@host_only
def good_planner_helper(coords, backend, platform):
    # resolved identity passed in as data, pure-host work only: legal
    return (len(coords), backend, platform)


def legal_unmarked_lazy(join):
    # unmarked function body: BKD stays an import-time rule here (the CLI
    # and engine touch backends lazily from the main thread by design)
    return jax.devices()[0].platform  # legal lazy touch
