"""Seeded THR violations: guarded-by lock discipline, one per shape.

Accesses of an annotated attribute (instance `self.X` or module global)
outside a `with <lock>:` block are findings; __init__, *_locked methods,
Condition aliases, and reasoned thr-ok escapes are the legal shapes.
NOT part of the package -- linted by tests/test_lint.py only.
"""

import threading

_G: dict = {}  # spgemm-lint: guarded-by(_GLOCK)
_GLOCK = threading.Lock()


def global_bad():
    _G["x"] = 1  # THR: module-global write without the lock


def global_good():
    with _GLOCK:
        _G["x"] = 2  # legal: lock held


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._avail = threading.Condition(self._lock)
        self._jobs: list = []  # spgemm-lint: guarded-by(_lock)
        self._jobs.append(0)   # legal: __init__ precedes publication

    def bad_read(self):
        return len(self._jobs)  # THR: no lock held

    def good_read(self):
        with self._lock:
            return len(self._jobs)  # legal

    def good_via_condition(self):
        with self._avail:
            return self._jobs.pop()  # legal: Condition aliases _lock

    def bad_nested_def(self):
        with self._lock:
            def cb():
                # THR: a callback runs later, usually on another thread --
                # the enclosing `with` does not protect it
                return list(self._jobs)
            return cb

    def drain_locked(self):
        return self._jobs.pop()  # legal: *_locked = caller holds the lock

    def escaped_read(self):
        # spgemm-lint: thr-ok(seeded: benign lock-free len probe, logging only)
        return len(self._jobs)  # legal: escaped with a reason
