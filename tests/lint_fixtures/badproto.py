"""PRO fixture: serve wire-contract registry discipline.

Seeded violations: undeclared request/response field literals (with and
without op context), an unknown op in a message literal, a hardcoded
protocol version, undeclared error codes at raise and compare sites, and
an undeclared protocol.E_* constant.  Legal shapes alongside: declared
fields for the op in play, the envelope fields, declared codes through
the E_* constants, and wire dicts bound to unconventional names (out of
PRO scope by design -- the rule audits the `msg`/`resp` convention).
NOT part of the package -- linted by tests/test_lint.py only.
"""

from spgemm_tpu.serve import protocol


def _op_status(msg):
    job = msg.get("id")  # legal: declared status request field
    flavor = msg.get("flavor")  # PRO: undeclared request field for status
    if job is None:
        return protocol.error(protocol.E_BAD_REQUEST, "no id")  # legal
    return protocol.ok(job=job, verbose=flavor)  # PRO: undeclared `verbose`


def build_submit(folder):
    # legal: declared submit fields + envelope
    good = {"op": "submit", "folder": folder, "options": {}}
    # PRO: undeclared request field `priority` for op submit
    bad = {"op": "submit", "folder": folder, "priority": 9}
    # PRO x2: unknown op + (independently) a hardcoded version stamp
    worse = {"op": "frobnicate", "v": 3}
    return good, bad, worse


def poll(resp):
    if not resp.get("ok"):  # legal: envelope field
        # PRO: undeclared error code at a raise site
        raise protocol.ProtocolError("went-sideways", "poll failed")
    state = resp["job"]  # legal: declared response field (status/wait)
    queue = resp.get("backlog")  # PRO: undeclared response field
    return state, queue


def classify(err):
    # PRO: undeclared error code on a code-flavored compare
    if err.get("code") == "transient-blip":
        return "retry"
    # legal: declared codes (literal and via tuple)
    if err.get("code") in ("queue-full", "tenant-cap"):
        return "backoff"
    return "fail"


def misspelled():
    return protocol.E_NOPE  # PRO: undeclared error-code constant


def legal_constants():
    return (protocol.E_UNKNOWN_JOB, protocol.E_SHUTTING_DOWN)


def out_of_scope(record):
    # legal: `record` is not a conventional wire-dict name, so its keys
    # are not auditable wire fields (and must not false-positive)
    return record.get("whatever"), record["anything"]
