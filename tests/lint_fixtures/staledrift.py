"""DRF fixture: the quiet call-site half of a drift scenario.

This module references exactly one declared knob and emits exactly one
declared event kind.  On its own (the fixture-dir run) it yields ZERO
findings: every DRF sub-audit self-gates on its registry module being in
the linted unit set.  tests/test_lint.py builds a tmp tree placing real
registry-module copies at matching suffixes next to this file, making
every OTHER registry entry unreferenced -- the drift findings then anchor
at the registry declaration lines, and the entries referenced here must
NOT be flagged.
NOT part of the package -- linted by tests/test_lint.py only.
"""

from spgemm_tpu.obs import events
from spgemm_tpu.utils import knobs


def referenced_surface():
    cap = knobs.get("SPGEMM_TPU_PLAN_CACHE")  # keeps this knob drift-free
    events.emit("job_start", cap=cap)  # keeps this kind drift-free
    return cap
