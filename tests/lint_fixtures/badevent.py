"""EVT fixture: structured-event-kind registry discipline.

Seeded violations: an undeclared kind through the module alias, an
undeclared kind through the LOG singleton, and a computed (non-literal)
kind through the bare imported emit.  Legal shapes alongside: declared
kinds through each receiver spelling, and a locally-defined emit helper
(not the obs/events log, so out of EVT scope by design).
NOT part of the package -- linted by tests/test_lint.py only.
"""

from spgemm_tpu.obs import events as obs_events
from spgemm_tpu.obs.events import LOG, emit


def bad_module_alias(job_id):
    # EVT: undeclared kind via the module alias
    obs_events.emit("job_vanished", job=job_id)


def bad_log_singleton():
    # EVT: undeclared kind via the LOG singleton
    LOG.emit("daemon_hiccup")


def bad_dynamic(kind):
    emit(kind, detail="x")  # EVT: computed kind via the bare import


def legal_declared(job_id):
    obs_events.emit("job_submit", job=job_id)  # legal: declared kind
    LOG.emit("watchdog_reap", job=job_id)  # legal: declared kind
    emit("job_done", job=job_id)  # legal: declared kind


def legal_local_helper():
    def local_emit(kind):  # legal: not the obs/events log
        return kind

    return local_emit("anything_goes")
