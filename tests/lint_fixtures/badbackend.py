"""spgemm-lint BKD fixture: seeded module-import-time backend touches
(a dead TPU hangs inside backend init -- only utils/backend_probe may
touch a backend, and only lazily).  Never imported."""

import jax
import jax.numpy as jnp

PLATFORM = jax.devices()[0].platform  # seeded BKD: runs at import

_ZERO = jnp.zeros((8, 8), jnp.uint32)  # seeded BKD: materializing an array
                                       # at import initializes the backend


def bad_default(devs=jax.local_devices()):  # seeded BKD: default evaluates
    return devs                             # at import time


def legal_lazy_probe():
    return jax.devices()[0].platform  # inside a function body: legal


DTYPE = jnp.uint32  # attribute access, no call: legal


if __name__ == "__main__":
    print(jax.devices())  # script driver block, never runs on import: legal
