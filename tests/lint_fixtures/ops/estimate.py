"""spgemm-lint FLD fixture: ops/estimate.py is in the numeric-lint scope.

The estimator's predictions steer budgets and routing on the numeric path,
and its sizing sums carry fld-proof escapes in the real module -- a
`jnp.sum` smuggled into an estimator helper without one must be a finding.
Never imported."""

import jax.numpy as jnp


def smuggled_mass_total(row_mass):
    return jnp.sum(row_mass)  # seeded FLD: unordered reduction
