"""spgemm-lint FLD fixture: seeded unordered reductions.

The `ops/spgemm.py` path suffix puts this file in the linter's numeric-
module scope -- fixtures exercise exactly the production path-based
scoping.  NEVER imported (tests parse it via lint_file); the code only
needs to be syntactically valid.
"""

import functools

import jax
import jax.numpy as jnp


def bad_jnp_sum(tiles):
    return jnp.sum(tiles, axis=0)  # seeded FLD: unordered reduction


def bad_psum(partial_tile):
    return jax.lax.psum(partial_tile, "ring")  # seeded FLD


def bad_segment_sum(flat, segs, n):
    return jax.ops.segment_sum(flat, segs, num_segments=n)  # seeded FLD


def bad_functools_reduce(tiles):
    return functools.reduce(lambda a, b: a + b, set(tiles))  # seeded FLD


def bad_method_sum(acc):
    return acc.sum(axis=-1)  # seeded FLD: method spelling


def escaped_proven_sum(tiles):
    # spgemm-lint: fld-proof(fixture: safe_exact_bound holds, sum == fold)
    return jnp.sum(tiles, axis=0)  # escaped: must NOT be a finding


def legal_builtin_sum(values):
    return sum(list(values))  # builtin left fold is ordered: legal
