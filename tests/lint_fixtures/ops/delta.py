"""spgemm-lint FLD fixture: ops/delta.py is in the numeric-lint scope.

The delta subsystem decides which output rows re-fold (its reachability
masks gate the numeric path), so an unordered reduction smuggled into a
delta helper must be a finding.  Never imported."""

import jax.numpy as jnp


def smuggled_dirty_total(pair_dirty):
    return jnp.sum(pair_dirty)  # seeded FLD: unordered reduction
