"""spgemm-lint KNB fixture: seeded raw SPGEMM_TPU_* environment reads
(must go through spgemm_tpu/utils/knobs.py).  Never imported."""

import os
from os import environ


def bad_environ_get():
    return os.environ.get("SPGEMM_TPU_SEEDED_A", "1")  # seeded KNB


def bad_getenv():
    return os.getenv("SPGEMM_TPU_SEEDED_B")  # seeded KNB


def bad_subscript():
    return environ["SPGEMM_TPU_SEEDED_C"]  # seeded KNB


def bad_planner_knob_reads():
    # the planner-pipeline knobs are registry knobs like any other: raw
    # reads of them are KNB findings (registered in utils/knobs.py, read
    # via knobs.get in chain.py / ops/plancache.py)
    ahead = os.environ.get("SPGEMM_TPU_PLAN_AHEAD", "2")  # seeded KNB
    cap = os.getenv("SPGEMM_TPU_PLAN_CACHE_CAP")  # seeded KNB
    return ahead, cap


def bad_serve_knob_reads():
    # the spgemmd serving knobs are registry knobs like any other: raw
    # reads are KNB findings (registered in utils/knobs.py, read via
    # knobs.get in serve/daemon.py / serve/queue.py / serve/protocol.py)
    sock = os.environ.get("SPGEMM_TPU_SERVE_SOCKET")  # seeded KNB
    cap = os.getenv("SPGEMM_TPU_SERVE_QUEUE_CAP", "64")  # seeded KNB
    deadline = environ["SPGEMM_TPU_SERVE_JOB_TIMEOUT"]  # seeded KNB
    grace = os.getenv("SPGEMM_TPU_SERVE_WEDGE_GRACE_S", "60")  # seeded KNB
    return sock, cap, deadline, grace


def bad_estimator_knob_reads():
    # the sampled-estimator knobs are registry knobs like any other: raw
    # reads are KNB findings (registered in utils/knobs.py, read via
    # knobs.get in ops/estimate.py)
    on = os.environ.get("SPGEMM_TPU_PLAN_ESTIMATE", "1")  # seeded KNB
    rows = os.getenv("SPGEMM_TPU_EST_SAMPLE_ROWS")  # seeded KNB
    conf = environ["SPGEMM_TPU_EST_CONFIDENCE"]  # seeded KNB
    return on, rows, conf


def bad_delta_knob_reads():
    # the delta-recompute knobs are registry knobs like any other: raw
    # reads are KNB findings (registered in utils/knobs.py, read via
    # knobs.get in ops/delta.py)
    on = os.getenv("SPGEMM_TPU_DELTA", "1")  # seeded KNB
    cap = os.environ.get("SPGEMM_TPU_DELTA_RETAIN")  # seeded KNB
    return on, cap


def legal_non_knob_reads():
    # non-SPGEMM_TPU names are not knobs: raw access stays legal
    return os.environ.get("JAX_PLATFORMS", ""), os.getenv("HOME")


def legal_knob_write():
    # WRITES stay legal: A/B harnesses and tests drive knob values this
    # way for code that then reads them through the registry
    os.environ["SPGEMM_TPU_SEEDED_A"] = "0"
    del environ["SPGEMM_TPU_SEEDED_C"]


def bad_obs_knob_reads():
    # the observability/event-log knobs are registry knobs like any
    # other: raw reads are KNB findings (registered in utils/knobs.py,
    # read via knobs.get in obs/events.py / obs/trace.py)
    ev = os.environ.get("SPGEMM_TPU_OBS_EVENTS", "1")  # seeded KNB
    cap = os.getenv("SPGEMM_TPU_OBS_EVENTS_MAX_KB")  # seeded KNB
    return ev, cap


def bad_batch_knob_reads():
    # the cross-job batching knobs are registry knobs like any other:
    # raw reads are KNB findings (registered in utils/knobs.py, read
    # via knobs.get in serve/daemon.py)
    k = os.environ.get("SPGEMM_TPU_SERVE_BATCH_K", "8")  # seeded KNB
    win = os.getenv("SPGEMM_TPU_SERVE_BATCH_WINDOW_S")  # seeded KNB
    return k, win


def bad_warm_knob_reads():
    # the warm-start persistence knobs are registry knobs like any
    # other: raw reads are KNB findings (registered in utils/knobs.py,
    # read via knobs.get in ops/warmstore.py)
    on = os.environ.get("SPGEMM_TPU_WARM", "1")  # seeded KNB
    d = os.getenv("SPGEMM_TPU_WARM_DIR")  # seeded KNB
    mb = environ["SPGEMM_TPU_WARM_MAX_MB"]  # seeded KNB
    return on, d, mb


def bad_accum_route_knob_read():
    # the accumulator-route knob is a registry knob like any other: a
    # raw read is a KNB finding (registered in utils/knobs.py, read via
    # knobs.get in ops/symbolic.py)
    return os.environ.get("SPGEMM_TPU_ACCUM_ROUTE", "auto")  # seeded KNB


def bad_fleet_knob_reads():
    # the fleet-layer knobs (TCP front-end + router) are registry knobs
    # like any other: raw reads are KNB findings (registered in
    # utils/knobs.py, read via knobs.get in serve/protocol.py and
    # fleet/router.py)
    addr = os.environ.get("SPGEMM_TPU_SERVE_ADDR")  # seeded KNB
    fleet = os.getenv("SPGEMM_TPU_ROUTER_BACKENDS", "")  # seeded KNB
    poll = environ["SPGEMM_TPU_ROUTER_POLL_S"]  # seeded KNB
    return addr, fleet, poll
