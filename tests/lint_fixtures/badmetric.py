"""MET fixture: ENGINE metric-name registry discipline.

Seeded violations: an undeclared phase name, an undeclared counter name,
and a computed (non-literal) name.  Legal shapes alongside: declared
names, and an ad-hoc PhaseTimers instance (not the ENGINE registry, so
out of MET scope by design).
"""

from spgemm_tpu.utils.timers import ENGINE as timers


def bad_phase(x):
    with timers.phase("made_up_phase"):  # MET: undeclared phase name
        return x


def bad_counter():
    timers.incr("made_up_counter")  # MET: undeclared counter name


def bad_dynamic(name):
    timers.record(name, 0.5)  # MET: computed metric name


def legal_declared(x):
    with timers.phase("plan"):  # legal: declared phase
        timers.incr("dispatches")  # legal: declared counter
        return x


def legal_local_instance():
    from spgemm_tpu.utils.timers import PhaseTimers

    t = PhaseTimers()
    with t.phase("driver-local"):  # legal: not the ENGINE registry
        pass


def bad_profile_layer_names():
    # the deep-profiling layer's series ride the same registries: a
    # near-miss of the new `compiles` counter (the family name, not the
    # declared counter name) and an ad-hoc compile phase are findings
    timers.incr("spgemm_compiles_total")  # MET: undeclared profile counter
    with timers.phase("compile_wait"):  # MET: undeclared profile phase
        pass


def bad_warm_layer_names():
    # the warm-start layer's series ride the same registries: a
    # singular near-miss of the declared counter and an ad-hoc load
    # phase are findings
    timers.incr("warm_hit")  # MET: undeclared warm counter
    with timers.phase("warm_loading"):  # MET: undeclared warm phase
        pass


def legal_warm_names(x):
    with timers.phase("warm_load"):  # legal: declared warm phase
        timers.incr("warm_hits")  # legal: declared warm counter
        return x


def bad_batch_layer_names():
    # the cross-job batching layer's series ride the same registries: a
    # singular near-miss of the declared serve_batches counter is a
    # finding
    timers.incr("serve_batch")  # MET: undeclared batch counter


def legal_batch_names():
    timers.incr("serve_batches")  # legal: declared batch counter
    timers.incr("serve_batched_jobs")  # legal: declared batch counter


def bad_dense_route_names():
    # the dense accumulator route's series ride the same registries: a
    # truncated near-miss of the declared counter and an ad-hoc fold
    # phase are findings
    timers.incr("route_den")  # MET: undeclared dense counter
    with timers.phase("dense_folding"):  # MET: undeclared dense phase
        pass


def legal_dense_route_names(x):
    with timers.phase("dense_fold"):  # legal: declared dense phase
        timers.incr("route_dense")  # legal: declared dense counter
        return x
