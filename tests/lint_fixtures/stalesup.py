"""Seeded stale suppressions: escape-hatch comments whose underlying
finding no longer exists -- each one must be reported as a SUP finding
(like an unused noqa) and inventoried with stale=true in --json.
NOT part of the package -- linted by tests/test_lint.py only.
"""


def sized(x):
    # spgemm-lint: fld-proof(seeded-stale: nothing to suppress below)
    return len(x)


def guarded():
    # spgemm-lint: thr-ok(seeded-stale: no THR finding here)
    return 1


def handled():
    try:
        return sized([])
    # spgemm-lint: exc-ok(seeded-stale: the handler below is narrow)
    except ValueError:
        return 0


def ordered():
    # spgemm-lint: lck-ok(seeded-stale: no lock-order edge anywhere here)
    return 2


def unblocked():
    # spgemm-lint: blk-ok(seeded-stale: nothing blocking below)
    return 3


def unshared():
    # spgemm-lint: tsi-ok(seeded-stale: no thread-shared write here)
    return 4


def undrifted():
    # spgemm-lint: drf-ok(seeded-stale: no registry declaration here)
    return 5
