"""Seeded LCK violations: an inverted lock-acquisition order (the
two-witness deadlock cycle) and a non-reentrant re-acquisition through a
call edge.  NOT part of the package -- linted by tests/test_lint.py only.
"""

import threading

_A = threading.Lock()
_B = threading.Lock()
_R = threading.RLock()


def a_then_b():
    with _A:
        with _B:  # LCK: acquires B while holding A (one half of the cycle)
            pass


def b_then_a():
    with _B:
        with _A:  # LCK: acquires A while holding B (the inversion)
            pass


def reenters():
    with _A:
        helper()  # LCK: helper re-acquires _A -- self-deadlock


def helper():
    with _A:  # legal alone: no lock held on entry from a clean caller
        pass


def legal_nested_same_order():
    with _A:
        with _B:  # same A->B order as a_then_b: an edge, not a new cycle
            pass


def legal_rlock_reentry():
    with _R:
        rlock_helper()  # legal: RLock re-entry is its documented use-case


def rlock_helper():
    with _R:  # no self-edge finding -- reentrant by construction
        pass
