"""FPT fixture: failpoint-name registry discipline.

Seeded violations: an undeclared point name, a computed (non-literal)
name, and the same through the bare-function import spelling.  Legal
shapes alongside: declared names through both import spellings.
"""

from spgemm_tpu.utils import failpoints
from spgemm_tpu.utils.failpoints import check as fp_check


def bad_undeclared():
    failpoints.check("made.up.point")  # FPT: undeclared failpoint name


def bad_dynamic(name):
    failpoints.check(name)  # FPT: computed failpoint name


def bad_bare_import():
    fp_check("also.made.up")  # FPT: undeclared via the bare import


def legal_declared():
    if failpoints.check("warm.load"):  # legal: declared (corrupt kind)
        return True
    fp_check("serve.journal")  # legal: declared via the bare import
    return False
