"""Non-numeric helper module for the interprocedural FLD fixture: the
reductions live HERE (legal in this module's own scope) and taint the
numeric caller across the module boundary."""

import jax.numpy as jnp

import hostdeep


def hidden_sum(x):
    return jnp.sum(x)  # the hidden reduction (legal here, taints callers)


def outer(x):
    return hostdeep.inner(x)  # second hop toward hostdeep's reduction


def sized(x):
    # spgemm-lint: fld-proof(seeded: source-proved sum keeps callers untainted)
    return jnp.sum(x)
