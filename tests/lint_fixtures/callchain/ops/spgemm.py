"""Seeded interprocedural FLD: the "hide the jnp.sum in utils/" hole.

This file sits on a numeric-path suffix (ops/spgemm.py), so calls into
non-numeric helpers that transitively perform an unordered reduction are
call-site findings -- one hop (hosthelper.hidden_sum) and two hops
(hosthelper.outer -> hostdeep.inner).  A call-site fld-proof escape and a
source-proved helper are the legal shapes.  NOT part of the package --
linted by tests/test_lint.py only.
"""

import hosthelper
from hosthelper import hidden_sum


def one_hop(x):
    return hidden_sum(x)  # FLD: reduction one call-hop away


def two_hops(x):
    return hosthelper.outer(x)  # FLD: reduction two call-hops away


def escaped_site(x):
    # spgemm-lint: fld-proof(seeded: call-site escape suppresses the taint)
    return hidden_sum(x)


def proved_at_source(x):
    return hosthelper.sized(x)  # legal: the helper proves its sum at source
