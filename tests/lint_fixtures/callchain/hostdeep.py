"""Deepest module of the interprocedural FLD fixture: the two-hop
reduction target (numeric caller -> hosthelper.outer -> inner)."""

import jax.numpy as jnp


def inner(x):
    return jnp.sum(x)
