"""Seeded TSI violations: an instance attribute written from two thread
roots without a guarded-by annotation, a loop-spawned single target
(multi-instance: one root, many threads), and a nested-def target spawned
from two sites -- plus the legal shapes (annotated state, __init__
writes, single-root writes, a reasoned tsi-ok escape on a single-writer
handoff slot).  NOT part of the package -- linted by tests/test_lint.py
only.
"""

import threading

_SHARED = 0


def spawn_workers():
    def worker():
        global _SHARED
        _SHARED = 1  # TSI: nested-def root, two spawn sites

    threading.Thread(target=worker, daemon=True).start()
    threading.Thread(target=worker, daemon=True).start()


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.guarded = 0  # spgemm-lint: guarded-by(_lock)
        self.done = 0     # legal here: __init__ happens-before publication
        self.beat = 0.0
        self.solo = 0
        threading.Thread(target=self._loop_a, daemon=True).start()
        threading.Thread(target=self._loop_b, daemon=True).start()

    def _loop_a(self):
        self.done += 1  # TSI: two-root write without guarded-by
        # spgemm-lint: tsi-ok(seeded: single-writer beat slot, the reader tolerates staleness by design)
        self.beat = 1.0
        with self._lock:
            self.guarded += 1  # legal: annotated (THR owns it)
        self._helper()

    def _loop_b(self):
        self.done += 1  # the second root's write of the same attr
        # spgemm-lint: tsi-ok(seeded: single-writer beat slot, the reader tolerates staleness by design)
        self.beat = 2.0

    def _helper(self):
        self.solo = 1  # legal: reached from one root only


class ConnServer:
    def __init__(self):
        self.hits = 0
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while True:
            threading.Thread(target=self._handle, daemon=True).start()

    def _handle(self):
        self.hits += 1  # TSI: multi-instance root (loop-spawned target)
