"""Seeded BLK violations: blocking operations under a registered lock --
direct, transitive through a call edge, and via a typed resource
(Queue.get) -- plus the legal shapes (no lock held; the condition's own
wait; a reasoned blk-ok escape).  NOT part of the package -- linted by
tests/test_lint.py only.
"""

import queue
import threading
import time

_LOCK = threading.Lock()
_COND = threading.Condition(_LOCK)
_Q = queue.Queue()


def direct():
    with _LOCK:
        time.sleep(0.1)  # BLK: sleeping while holding _LOCK


def transitive():
    with _LOCK:
        helper()  # BLK: reaches subprocess.run while _LOCK is held


def helper():
    import subprocess
    subprocess.run(["true"])  # legal alone: no lock held here


def typed_queue():
    with _LOCK:
        return _Q.get()  # BLK: Queue.get blocks while _LOCK is held


def legal_no_lock():
    time.sleep(0.1)  # legal: nothing held


def legal_condition_wait():
    with _COND:
        _COND.wait(0.1)  # legal: wait releases the condition's own lock


def escaped():
    with _LOCK:
        # spgemm-lint: blk-ok(seeded: bounded poll with the lock deliberately held, reviewable reason)
        time.sleep(0.0)
