"""Seeded EXC violations: exception-contract discipline, one per shape.

A broad `except Exception` needs the repo's `# noqa: BLE001 -- <reason>`
justification on its line; a bare `except:` / `except BaseException` must
end its handler in `raise` (the JobAbandoned-must-pierce contract), or be
escaped with a reasoned exc-ok.  NOT part of the package -- linted by
tests/test_lint.py only.
"""


def work():
    raise ValueError("seeded")


def cleanup():
    pass


def naked_broad():
    try:
        work()
    except Exception:  # EXC: broad catch with no BLE001 justification
        return None


def justified_broad():
    try:
        work()
    except Exception:  # noqa: BLE001 -- seeded: failover contract citation
        return None


def bare_no_reraise():
    try:
        work()
    except:  # EXC: bare except that swallows (no trailing raise)
        cleanup()


def bare_reraise():
    try:
        work()
    except:  # legal for EXC: the handler provably re-raises
        cleanup()
        raise


def base_no_reraise():
    try:
        work()
    except BaseException:  # EXC: would swallow JobAbandoned-style signals
        cleanup()


def base_escaped():
    try:
        work()
    # spgemm-lint: exc-ok(seeded: the swallow IS this fixture's contract)
    except BaseException:
        cleanup()
