"""Chaos layer (PR 13): the failpoint registry (utils/failpoints), the
crash-safe CRC-framed journal + torn-tail replay, self-healing slice
recovery (re-probe + canary gate + backoff), the client connect retry,
the graceful drain, and the warm-dir flock probe race -- tier-1, injected
runners/probes everywhere the engine itself is not the subject."""

import os
import threading
import time

import numpy as np
import pytest

from spgemm_tpu.serve import client, protocol
from spgemm_tpu.serve.daemon import (Daemon, journal_frame,
                                     journal_parse_line)
from spgemm_tpu.utils import failpoints, io_text
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_chain
from spgemm_tpu.utils.semantics import chain_oracle


def _chain_folder(tmp_path, n=3, k=2, seed=7, name="chain_in"):
    mats = random_chain(n, 4, k, 0.5, np.random.default_rng(seed), "full")
    folder = str(tmp_path / name)
    io_text.write_chain_dir(folder, mats, k)
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_bytes = io_text.format_matrix(BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k, want).prune_zeros())
    return folder, want_bytes


@pytest.fixture
def make_daemon(tmp_path):
    daemons = []

    def _make(idx=0, **kw):
        d = Daemon(str(tmp_path / f"d{idx}.sock"), **kw)
        d.start()
        daemons.append(d)
        return d

    yield _make
    for d in daemons:
        d.stop()


@pytest.fixture(autouse=True)
def _clean_failpoints(monkeypatch):
    """Every test starts unarmed with zeroed trigger counters."""
    monkeypatch.delenv("SPGEMM_TPU_FAILPOINTS", raising=False)
    failpoints.clear()
    yield
    failpoints.clear()


def _wait_until(pred, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# -------------------------------------------------- failpoint registry --
def test_failpoints_unarmed_are_inert():
    for name in failpoints.REGISTRY:
        assert failpoints.check(name) is False
    assert failpoints.triggered() == {}


def test_failpoints_unregistered_name_raises():
    with pytest.raises(KeyError):
        failpoints.check("not.a.point")


def test_failpoints_spec_parsing_is_strict(monkeypatch):
    for bad in ("bogus.name", "plan.build:nope", "plan.build:0.5:0",
                "plan.build:2", "plan.build:1:1:1"):
        monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", bad)
        failpoints.clear()
        with pytest.raises(ValueError, match="SPGEMM_TPU_FAILPOINTS"):
            failpoints.check("plan.build")


def test_failpoints_malformed_spec_raises_on_every_check(monkeypatch):
    """A malformed spec must raise on EVERY check, not just the first:
    one swallowed ValueError (an executor's broad job-error except) must
    never leave the bad spec cached as 'armed nothing' -- the chaos run
    would pass without injecting anything."""
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "plan.build:bogus")
    failpoints.clear()
    for _ in range(3):
        with pytest.raises(ValueError, match="SPGEMM_TPU_FAILPOINTS"):
            failpoints.check("plan.build")
    # and fixing the env (not just clearing it) re-arms without clear()
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "warm.load:1:1")
    assert failpoints.check("warm.load") is True


def test_failpoints_kinds_and_count_budget(monkeypatch):
    # corrupt: check() returns True, site takes its own path; count caps
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "warm.load:1:2")
    assert [failpoints.check("warm.load") for _ in range(4)] == \
        [True, True, False, False]
    assert failpoints.triggered() == {"warm.load": 2}
    # raise: the registered exception, carrying the point name
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "plan.build")
    with pytest.raises(failpoints.FailpointTriggered) as ei:
        failpoints.check("plan.build")
    assert ei.value.point == "plan.build"
    # other points stay inert under a spec that does not name them
    assert failpoints.check("delta.diff") is False


def test_failpoints_prob_sequence_is_seeded(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "delta.diff:0.5")
    seq1 = [failpoints.check("delta.diff") for _ in range(16)]
    failpoints.clear()
    seq2 = [failpoints.check("delta.diff") for _ in range(16)]
    assert seq1 == seq2  # same spec => same trigger sequence
    assert True in seq1 and False in seq1


def test_failpoints_hang_releases_on_disarm(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "serve.executor")
    t = threading.Thread(
        target=lambda: failpoints.check("serve.executor"), daemon=True)
    t.start()
    time.sleep(0.2)
    assert t.is_alive()  # hanging, the wedge signature
    monkeypatch.delenv("SPGEMM_TPU_FAILPOINTS")
    t.join(5.0)
    assert not t.is_alive()  # released by disarming


def test_failpoints_triggers_reach_metrics_and_events(monkeypatch):
    from spgemm_tpu.obs import events as obs_events
    from spgemm_tpu.obs import metrics as obs_metrics

    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "warm.load:1:1")
    assert failpoints.check("warm.load") is True
    samples = obs_metrics.collect_engine()
    assert ("spgemm_failpoints_triggered_total", {"point": "warm.load"},
            1) in samples
    kinds = [r for r in obs_events.LOG.tail(50)
             if r.get("kind") == "failpoint_trigger"]
    assert kinds and kinds[-1]["point"] == "warm.load"
    # and the renderer accepts the family (declared, labeled)
    text = obs_metrics.render(
        [("spgemm_failpoints_triggered_total", {"point": "warm.load"}, 1)])
    assert 'spgemm_failpoints_triggered_total{point="warm.load"} 1' in text


# ------------------------------------------------- journal crash safety --
def test_journal_frame_roundtrip_and_torn_lines():
    ev = {"event": "submit", "id": "job-1", "folder": "/x"}
    line = journal_frame(ev)
    assert line.endswith("\n")
    assert journal_parse_line(line.strip()) == ev
    # a torn prefix of the frame fails the length/CRC check
    for cut in (5, len(line) // 2, len(line) - 3):
        assert journal_parse_line(line[:cut].strip()) is None
    # a bit-flipped payload fails the CRC
    bad = line.strip().replace("job-1", "job-2")
    assert journal_parse_line(bad) is None
    # legacy bare-JSON records (pre-framing journals) still parse
    assert journal_parse_line('{"event":"done","id":"j"}') == \
        {"event": "done", "id": "j"}
    assert journal_parse_line('{"event":"done"') is None


def test_journal_replay_truncates_at_torn_record_and_counts(tmp_path,
                                                            make_daemon):
    """Replay tolerates a mid-write kill: everything before the first
    bad record replays, the tear is counted (stats + metrics), never a
    crash -- and records past the tear are dropped (unattributable)."""
    folder, _ = _chain_folder(tmp_path)
    sock = str(tmp_path / "torn.sock")
    ran = []
    with open(sock + ".journal", "w", encoding="utf-8") as f:
        f.write(journal_frame({"event": "submit", "id": "job-1",
                               "folder": folder, "output": folder + "/o1",
                               "options": {}}))
        good = journal_frame({"event": "submit", "id": "job-2",
                              "folder": folder, "output": folder + "/o2",
                              "options": {}})
        f.write(good[:len(good) // 2])  # the SIGKILL-mid-append tail
    d = Daemon(sock, runner=lambda job, degraded=False: ran.append(job.id))
    d.start()
    try:
        _wait_until(lambda: "job-1" in ran, msg="replayed job runs")
        st = d._journal_stats()
        assert st["torn"] == 1
        assert "job-2" not in ran  # past the tear: dropped, not garbled
        resp = d._op_metrics()
        assert "spgemmd_journal_torn_total 1" in resp["text"]
    finally:
        d.stop()


def test_journal_failpoint_writes_torn_record(tmp_path, monkeypatch):
    """The serve.journal corrupt failpoint writes exactly the torn frame
    the replay path must truncate at."""
    folder, _ = _chain_folder(tmp_path)
    sock = str(tmp_path / "fp.sock")
    d = Daemon(sock, runner=lambda job, degraded=False: None)
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "serve.journal:1:1")
    d._journal_append({"event": "submit", "id": "job-x", "folder": folder,
                       "output": "o", "options": {}})
    monkeypatch.delenv("SPGEMM_TPU_FAILPOINTS")
    live, torn = d._journal_live_records()
    assert live == [] and torn == 1


# --------------------------------------------- self-healing recovery --
def test_wedge_heal_lifecycle_one_slice_keeps_serving(tmp_path,
                                                      make_daemon):
    """The satellite acceptance test: wedge -> reap -> degrade on one
    slice (the other keeps serving) -> heartbeat resumes -> un-wedge
    (the abandoned executor aborts via JobAbandoned, never corrupting
    the successor) -> recovery re-probe reinstates the slice behind the
    canary gate -> the canary job completes and the slice graduates."""
    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    first = threading.Event()
    ran = []

    def runner(job, degraded=False):
        if not first.is_set() and not degraded:
            first.set()
            unwedge.wait(60)  # hung backend call: no beats, no return
            job.touch()       # heartbeat resumes after the un-wedge
            return
        ran.append((job.id, job.slice, degraded))

    d = make_daemon(runner=runner, slices="2", n_devices=2,
                    job_timeout_s=0.3, wedge_grace_s=0.2,
                    probe=lambda: "cpu", recover_s=0.1)
    j1 = client.submit(folder, d.socket_path)
    r1 = client.wait(j1["id"], d.socket_path, timeout=30)
    assert r1["job"]["state"] == "failed"
    assert r1["job"]["error"]["code"] == protocol.E_JOB_TIMEOUT
    _wait_until(lambda: any(s.degraded for s in d.slices),
                msg="wedged slice degrades")
    # the pool keeps serving while one slice is down
    j2 = client.submit(folder, d.socket_path)
    assert client.wait(j2["id"], d.socket_path,
                       timeout=30)["job"]["state"] == "done"
    # recovery: the live probe reinstates the slice (canary armed)
    _wait_until(lambda: not any(s.degraded for s in d.slices),
                msg="degraded slice reinstated")
    st = client.stats(d.socket_path)
    healed = [s for s in st["slices"] if s["recoveries"] >= 1]
    assert len(healed) == 1
    assert healed[0]["recovered_at"] is not None
    assert healed[0]["canary"] is True
    # un-wedge: the abandoned executor resumes, beats, and aborts
    unwedge.set()
    # drive jobs until the healed slice serves its canary and graduates
    deadline = time.time() + 20
    while time.time() < deadline:
        j = client.submit(folder, d.socket_path)
        client.wait(j["id"], d.socket_path, timeout=30)
        st = client.stats(d.socket_path)
        row = next(s for s in st["slices"] if s["recoveries"] >= 1)
        if not row["canary"]:
            break
        time.sleep(0.05)
    assert not row["canary"], "canary never settled"
    assert not row["degraded"]
    # healthy-pool bookkeeping: daemon-level flag/reason stayed null
    assert st["degraded"] is False and st["degrade_reason"] is None
    resp = d._op_metrics()
    assert 'spgemm_slice_recoveries_total{slice="%s"} 1' % row["name"] \
        in resp["text"]


def test_recovery_disabled_by_default(tmp_path, make_daemon):
    """recover_s=0 (the knob default) is the pre-recovery behavior: a
    degraded slice stays degraded."""
    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    first = threading.Event()

    def runner(job, degraded=False):
        if not first.is_set() and not degraded:
            first.set()
            unwedge.wait(60)

    d = make_daemon(runner=runner, slices="2", n_devices=2,
                    job_timeout_s=0.3, wedge_grace_s=0.2,
                    probe=lambda: "cpu")
    try:
        j = client.submit(folder, d.socket_path)
        client.wait(j["id"], d.socket_path, timeout=30)
        _wait_until(lambda: any(s.degraded for s in d.slices),
                    msg="wedged slice degrades")
        time.sleep(0.5)  # several would-be recovery cadences
        assert any(s.degraded for s in d.slices)
        assert all(s.recoveries == 0 for s in d.slices)
    finally:
        unwedge.set()


def test_canary_failure_redegrades_and_doubles_backoff(tmp_path,
                                                       make_daemon):
    """A slice that probes live but wedges its canary job re-degrades,
    and the recovery backoff doubles -- the lying device waits longer
    before its next audition.  A 1-slice pool pins the canary job to
    the reinstated slice (in a wider pool another healthy slice could
    pick it up and the sequence would race)."""
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        if not degraded:
            release.wait(60)  # every healthy pickup wedges

    d = make_daemon(runner=runner, job_timeout_s=0.4, wedge_grace_s=0.2,
                    probe=lambda: "cpu", recover_s=0.2)
    sl = d.slices[0]
    try:
        j1 = client.submit(folder, d.socket_path)
        client.wait(j1["id"], d.socket_path, timeout=30)
        _wait_until(lambda: sl.degraded, msg="first wedge degrades")
        _wait_until(lambda: sl.recoveries >= 1 and not sl.degraded,
                    timeout=20, msg="recovery reinstates the slice")
        # the canary job wedges the reinstated slice again
        j2 = client.submit(folder, d.socket_path)
        client.wait(j2["id"], d.socket_path, timeout=30)
        _wait_until(lambda: sl.degraded, timeout=20,
                    msg="failed canary re-degrades")
        with d._lock:
            assert sl.canary is False
            assert sl.recover_backoff >= 0.4  # doubled from the 0.2 base
    finally:
        release.set()


def test_canary_gate_consumed_at_pickup_spares_the_next_job(
        tmp_path, make_daemon):
    """The gate tightens exactly ONE pickup: with a second job already
    queued, the executor claims it before the watchdog's settle tick
    observes the canary's outcome -- an unconsumed gate would tighten
    (and spuriously reap) that job too on a healthy recovered slice."""
    from spgemm_tpu.serve.queue import TERMINAL, JobAbandoned

    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    first = threading.Event()

    def runner(job, degraded=False):
        if degraded:
            return
        if not first.is_set():
            first.set()
            unwedge.wait(60)  # the wedge trigger
            return
        # healthy post-reinstatement jobs: slow-but-alive well past the
        # 0.4 s tightened (wedge-grace) deadline, beating throughout
        deadline = time.time() + 1.2
        while time.time() < deadline:
            time.sleep(0.05)
            job.touch()
            if job.state in TERMINAL:
                raise JobAbandoned(job.id)

    d = make_daemon(runner=runner, job_timeout_s=0.0, wedge_grace_s=0.4,
                    probe=lambda: "cpu", recover_s=0.1)
    sl = d.slices[0]
    try:
        j1 = client.submit(folder, d.socket_path, {"timeout_s": 0.3})
        client.wait(j1["id"], d.socket_path, timeout=30)
        _wait_until(lambda: sl.degraded, msg="wedge degrades")
        unwedge.set()  # straggler aborts before the gate arms
        _wait_until(lambda: sl.recoveries >= 1 and not sl.degraded,
                    timeout=20, msg="recovery reinstates")
        # both queued before the canary runs: j3's pickup follows j2's
        # abort immediately, ahead of any watchdog settle tick
        j2 = client.submit(folder, d.socket_path)
        j3 = client.submit(folder, d.socket_path)
        r2 = client.wait(j2["id"], d.socket_path, timeout=30)
        r3 = client.wait(j3["id"], d.socket_path, timeout=60)
        assert r2["job"]["state"] == "failed"  # the audition, reaped
        assert r2["job"]["error"]["code"] == protocol.E_JOB_TIMEOUT
        assert r3["job"]["state"] == "done"  # untightened, unreaped
        _wait_until(lambda: not sl.canary and sl.canary_job is None,
                    msg="gate fully settles")
    finally:
        unwedge.set()


def test_canary_settles_when_reaped_job_outlived_slow_not_wedged(
        tmp_path, make_daemon):
    """A canary job reaped under its tightened deadline whose executor
    MOVES ON (heartbeats, aborts via JobAbandoned -- the slow-not-wedged
    signature) settles the gate: moving on proves the device executes.
    Without this, a deadline-less deployment would reap every long job
    on a healthy recovered slice forever."""
    from spgemm_tpu.serve.queue import TERMINAL, JobAbandoned

    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    phase = {"n": 0}

    def runner(job, degraded=False):
        if degraded:
            return
        phase["n"] += 1
        if phase["n"] == 1:
            unwedge.wait(60)  # wedge: no beats, no return
            return
        # canary: SLOW but alive -- beats like chain_product and aborts
        # at the next boundary once the watchdog reaped it (2 s: well
        # past the 0.4 s tightened deadline, short enough that job 3
        # finishes fast)
        deadline = time.time() + 2
        while time.time() < deadline:
            time.sleep(0.05)
            job.touch()
            if job.state in TERMINAL:
                raise JobAbandoned(job.id)

    # job_timeout_s=0: deadline-less deployment; only the canary gate's
    # wedge-grace tightening gives job 2 a deadline at all
    d = make_daemon(runner=runner, job_timeout_s=0.0, wedge_grace_s=0.4,
                    probe=lambda: "cpu", recover_s=0.1)
    sl = d.slices[0]
    try:
        j1 = client.submit(folder, d.socket_path,
                           {"timeout_s": 0.3})  # the wedge trigger
        client.wait(j1["id"], d.socket_path, timeout=30)
        _wait_until(lambda: sl.degraded, msg="wedge degrades")
        _wait_until(lambda: sl.recoveries >= 1 and not sl.degraded,
                    timeout=20, msg="recovery reinstates")
        j2 = client.submit(folder, d.socket_path)  # no deadline of its own
        r2 = client.wait(j2["id"], d.socket_path, timeout=30)
        assert r2["job"]["state"] == "failed"  # reaped under the gate
        assert r2["job"]["error"]["code"] == protocol.E_JOB_TIMEOUT
        # the executor outlives the reap (beats, aborts, moves on): the
        # gate settles instead of dooming every later long job
        _wait_until(lambda: not sl.canary, timeout=20,
                    msg="canary settles on slow-not-wedged")
        assert not sl.degraded
        # and a later deadline-less job runs unreaped to completion
        j3 = client.submit(folder, d.socket_path)
        r3 = client.wait(j3["id"], d.socket_path, timeout=60)
        assert r3["job"]["state"] == "done"
    finally:
        unwedge.set()


def test_redegrade_of_degraded_slice_keeps_backoff(tmp_path, make_daemon):
    """Re-degrading an ALREADY-degraded slice (its CPU-failover executor
    died or wedged) must keep the accumulated exponential backoff:
    resetting to the base cadence would resume auditioning a known-dead
    device as if the failed probes never happened."""
    d = make_daemon(recover_s=30.0, probe=lambda: "dead")
    sl = d.slices[0]
    d._degrade_slice(sl, "first degrade")
    with d._lock:
        assert sl.recover_backoff == 30.0  # fresh degrade: base cadence
        sl.recover_backoff = 120.0  # as accumulated by failed probes
    d._degrade_slice(sl, "degraded executor died")
    with d._lock:
        assert sl.recover_backoff == 120.0  # kept, not reset to base


def test_stats_reports_armed_and_triggered_failpoints(
        tmp_path, make_daemon, monkeypatch):
    """The chaos surface is inspectable on a live daemon: stats carries
    the armed points under the current spec and the trigger totals."""
    monkeypatch.setenv("SPGEMM_TPU_FAILPOINTS", "warm.load:0.5:3")
    d = make_daemon()
    st = client.stats(d.socket_path)
    assert st["failpoints"]["armed"]["warm.load"] == {
        "kind": "corrupt", "prob": 0.5, "remaining": 3}
    assert st["failpoints"]["triggered"] == {}


def test_accepts_refuses_live_claim_allows_terminal_overwrite(
        tmp_path, make_daemon):
    """The reinstatement race's mutual exclusion, pinned at the claim
    point: a LIVE claim on the slice (a retired executor still running
    its last job) refuses the successor's claim and is never clobbered
    -- deadline reaping and wedge attribution keep their target, and two
    jobs can never dispatch on one slice's devices -- while a TERMINAL
    leftover claim (a wedged executor's abandoned slot) must be
    overwritable or the degraded replacement never serves again."""
    from spgemm_tpu.serve.queue import Job

    d = make_daemon(runner=lambda job, degraded=False: None)
    sl = d.slices[0]
    held = Job("held", "f", "o", {})
    held.start()  # live: running
    sl.current = held
    j = Job("nxt", "f", "o", {})
    assert d._accepts(sl, j) is False
    assert sl.current is held  # the live claim was not clobbered
    held.finish("failed", error={"code": "x", "message": "reaped"})
    assert d._accepts(sl, j) is True  # wedged leftover: overwrite
    assert sl.current is j
    sl.current = None


def test_reinstatement_mid_job_serializes_with_straggler(tmp_path,
                                                         make_daemon):
    """End-to-end reinstatement race: _spawn_executor replaces an
    executor MID-JOB (the recovery probe retires a live, actively
    dispatching generation).  The successor must not claim the next job
    until the straggler's job is terminal -- one job per slice at a
    time, sl.current owned by the in-flight job throughout -- and both
    jobs must complete once the straggler finishes."""
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()
    ran = []

    def runner(job, degraded=False):
        ran.append(job.id)
        if len(ran) == 1:
            release.wait(30)  # the straggler's job, in flight

    d = make_daemon(runner=runner)
    sl = d.slices[0]
    try:
        j1 = client.submit(folder, d.socket_path)
        _wait_until(lambda: sl.current is not None
                    and sl.current.id == j1["id"],
                    msg="straggler picks up job 1")
        # the reinstatement: retire the live generation mid-job
        d._spawn_executor(sl, degraded=False)
        j2 = client.submit(folder, d.socket_path)
        time.sleep(0.6)  # several successor poll cycles
        cur = sl.current
        assert cur is not None and cur.id == j1["id"], \
            "successor clobbered the straggler's live claim"
        assert d.queue.get(j2["id"]).state == "queued"
        assert ran == [j1["id"]]
    finally:
        release.set()
    r1 = client.wait(j1["id"], d.socket_path, timeout=30)
    r2 = client.wait(j2["id"], d.socket_path, timeout=30)
    assert r1["job"]["state"] == "done"
    assert r2["job"]["state"] == "done"
    assert ran == [j1["id"], j2["id"]]


# --------------------------------------------------- client retry --
def test_client_connect_retry_bounds_and_structured_error(tmp_path):
    path = str(tmp_path / "nobody.sock")
    t0 = time.time()
    with pytest.raises(client.ServeError) as ei:
        client.request({"op": "stats"}, path, retry_total_s=0.4)
    assert ei.value.code == protocol.E_UNAVAILABLE
    assert 0.3 <= time.time() - t0 < 5.0  # bounded total wait
    # retry_total_s=0: exactly one attempt, still the structured error
    t0 = time.time()
    with pytest.raises(client.ServeError) as ei:
        client.request({"op": "stats"}, path, retry_total_s=0)
    assert ei.value.code == protocol.E_UNAVAILABLE
    assert time.time() - t0 < 0.2


def test_client_connect_retry_rides_out_daemon_restart(tmp_path):
    """The rollout window: a submit launched while no daemon is bound
    yet succeeds once the daemon comes up within the retry budget."""
    folder, _ = _chain_folder(tmp_path)
    sock = str(tmp_path / "late.sock")
    d = Daemon(sock, runner=lambda job, degraded=False: None)

    def _late_start():
        time.sleep(0.4)
        d.start()

    t = threading.Thread(target=_late_start, daemon=True)
    t.start()
    try:
        resp = client.submit(folder, sock)  # default retry window: 5 s
        assert resp["ok"] and resp["id"]
    finally:
        t.join()
        d.stop()


# --------------------------------------------------- graceful drain --
def test_stop_drains_then_reaps_with_structured_error(tmp_path,
                                                      monkeypatch):
    """stop() (the SIGTERM/shutdown path) waits DRAIN_GRACE_S for
    in-flight jobs, then reaps stragglers with a structured
    shutting-down error -- never a hang, never a silent loss."""
    monkeypatch.setattr(Daemon, "DRAIN_GRACE_S", 0.3)
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        release.wait(60)

    d = Daemon(str(tmp_path / "drain.sock"), runner=runner)
    d.start()
    try:
        j = client.submit(folder, d.socket_path)
        _wait_until(lambda: d.queue.get(j["id"]).state == "running",
                    msg="job running")
        t0 = time.time()
        d.stop()
        assert time.time() - t0 < 8.0  # drained, did not hang
        job = d.queue.get(j["id"])
        assert job.state == "failed"
        assert job.error["code"] == protocol.E_SHUTTING_DOWN
        # a drain reap is routine rollout fallout, not executor death:
        # its own outcome label keeps "abandoned" alerts meaningful
        assert d._terminal_totals["drained"] == 1
        assert d._terminal_totals["abandoned"] == 0
        assert not os.path.exists(d.socket_path)
    finally:
        release.set()


def test_stop_lets_fast_jobs_finish_inside_the_grace(tmp_path,
                                                     monkeypatch):
    monkeypatch.setattr(Daemon, "DRAIN_GRACE_S", 5.0)
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        release.wait(30)

    d = Daemon(str(tmp_path / "drain2.sock"), runner=runner)
    d.start()
    j = client.submit(folder, d.socket_path)
    _wait_until(lambda: d.queue.get(j["id"]).state == "running",
                msg="job running")
    threading.Timer(0.2, release.set).start()
    d.stop()
    assert d.queue.get(j["id"]).state == "done"  # finished, not reaped


# ------------------------------------------- warm flock probe race --
def test_warm_stat_probe_never_cold_starts_a_daemon(tmp_path):
    """The `cli warm --stat` flock probe (warmstore.scan) holds the dir
    lock for microseconds; a daemon's configure() landing inside that
    window must win via its ~250 ms retry, never run cold for its whole
    lifetime.  The recovery re-probe path never touches the warm dir
    (the probe is a subprocess matmul; the replacement executor reuses
    the already-bound store), so this window is the only flock race."""
    from spgemm_tpu.ops import warmstore

    warm = str(tmp_path / "w.warm")
    os.makedirs(warm)
    stop = threading.Event()

    def prober():
        while not stop.is_set():
            warmstore.scan(warm)  # takes + drops the flock each call

    t = threading.Thread(target=prober, daemon=True)
    t.start()
    try:
        time.sleep(0.05)  # prober definitely spinning
        assert warmstore.configure(warm) is True
        assert warmstore.active()
        # and the probe against the now-live owner reports locked
        # without stealing it
        info = warmstore.scan(warm)
        assert info["locked"] is True
        assert warmstore.active()
    finally:
        stop.set()
        t.join(5.0)
        warmstore.release()
