"""Text format reader/writer: roundtrip, byte-exactness, std::map semantics."""

import os

import numpy as np
import pytest

from spgemm_tpu.utils import io_text
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_block_sparse


def test_golden_bytes_exact_format(tmp_path):
    """Writer must match the reference's byte format (sparse_matrix_mult.cu:595-608):
    'R C\\n', 'blocks\\n', per tile 'r c\\n' + k space-joined rows, no trailing space."""
    m = BlockSparseMatrix.from_blocks(
        4, 4, 2,
        coords=[(2, 0), (0, 2)],  # unsorted on purpose: writer emits sorted order
        tiles=np.array([[[1, 2], [3, 4]],
                        [[18446744073709551615, 0], [7, 8]]], dtype=np.uint64),
    )
    golden = (b"4 4\n2\n"
              b"0 2\n18446744073709551615 0\n7 8\n"
              b"2 0\n1 2\n3 4\n")
    assert io_text.format_matrix(m) == golden
    path = tmp_path / "matrix"
    io_text.write_matrix(str(path), m)
    assert path.read_bytes() == golden


def test_golden_chain_end_to_end_cli(tmp_path):
    """COMMITTED golden fixture (SURVEY.md section 4 'golden files'): a tiny
    adversarial-valued chain directory in the reference text format plus the
    expected ./matrix bytes, derived from the python-int oracle when the
    fixture was created -- NOT from the engine.  Pins the full pipeline
    (reader -> chain engine -> pruning -> writer) byte-for-byte across time;
    a reader+writer bug pair that cancels in round-trip tests cannot cancel
    here."""
    from conftest import run_repo_script

    data = os.path.join(os.path.dirname(__file__), "data")
    out = tmp_path / "matrix"
    rc = run_repo_script(
        ["-m", "spgemm_tpu.cli", os.path.join(data, "golden_chain"),
         "--device", "cpu", "--output", str(out)], timeout=300)
    assert rc.returncode == 0, rc.stderr[-2000:]
    with open(os.path.join(data, "golden_chain_expected_matrix"), "rb") as f:
        want = f.read()
    assert out.read_bytes() == want


def test_golden_wrap_chain_end_to_end_cli(tmp_path):
    """Adversarial committed fixture (tests/data/README.md): a hand-built
    chain forcing all three section-2.9 collapses -- product u64 wrap
    (2^32*2^32), product==MAX, and accumulator u64 wrap (2^63+2^63) -- the
    last of which zeroes a whole output tile so the final prune drops it.
    Under clean mod-(2^64-1) arithmetic the output differs in values AND in
    block count, so any 'cleanup' of the wrap-then-mod fold order
    (sparse_matrix_mult.cu:48,59-61) turns this red.  Generator with the
    derivation: tests/data/gen_golden_wrap.py."""
    from conftest import run_repo_script

    data = os.path.join(os.path.dirname(__file__), "data")
    out = tmp_path / "matrix"
    rc = run_repo_script(
        ["-m", "spgemm_tpu.cli", os.path.join(data, "golden_wrap"),
         "--device", "cpu", "--output", str(out)], timeout=300)
    assert rc.returncode == 0, rc.stderr[-2000:]
    with open(os.path.join(data, "golden_wrap_expected_matrix"), "rb") as f:
        want = f.read()
    assert out.read_bytes() == want

    # Non-vacuity, re-asserted at test time (not only in the generator):
    # clean field-mode semantics on the same chain keeps the pruned tile.
    from spgemm_tpu.utils import semantics
    mats = [m.to_dict() for m in
            io_text.read_chain(os.path.join(data, "golden_wrap"), 0, 2, 4)]
    f1 = semantics.field_spgemm_oracle(mats[0], mats[1], 4)
    fld = semantics.field_spgemm_oracle(f1, mats[2], 4)
    assert np.any(fld[(1, 1)]), "field-mode must keep the tile ref-mode prunes"
    assert b"\n1 1\n" not in want


def test_reader_roundtrip(tmp_path):
    rng = np.random.default_rng(20)
    m = random_block_sparse(8, 8, 4, 0.3, rng, "full")
    path = tmp_path / "matrix1"
    io_text.write_matrix(str(path), m)
    m2 = io_text.read_matrix(str(path), 4)
    assert m2 == m


def test_reader_whitespace_insensitive(tmp_path):
    """istream >> semantics: any whitespace separates tokens."""
    text = "2 2\n1\n0    0\n1 2\n3\t4\n"
    path = tmp_path / "m"
    path.write_text(text)
    m = io_text.read_matrix(str(path), 2)
    assert m.rows == 2 and m.cols == 2 and m.nnzb == 1
    assert np.array_equal(m.tiles[0], np.array([[1, 2], [3, 4]], dtype=np.uint64))


def test_duplicate_coords_last_wins(tmp_path):
    """std::map operator[] overwrite (sparse_matrix_mult.cu:383)."""
    text = "2 2\n2\n0 0\n1 1\n1 1\n0 0\n9 9\n9 9\n"
    path = tmp_path / "m"
    path.write_text(text)
    m = io_text.read_matrix(str(path), 2)
    assert m.nnzb == 1
    assert np.array_equal(m.tiles[0], np.full((2, 2), 9, dtype=np.uint64))


def test_chain_dir_roundtrip(tmp_path):
    rng = np.random.default_rng(21)
    mats = [random_block_sparse(4, 4, 2, 0.5, rng) for _ in range(3)]
    folder = str(tmp_path / "chain")
    io_text.write_chain_dir(folder, mats, 2)
    n, k = io_text.read_size(folder)
    assert (n, k) == (3, 2)
    loaded = io_text.read_chain(folder, 0, n - 1, k)
    for a, b in zip(loaded, mats):
        assert a == b


def test_empty_matrix(tmp_path):
    path = tmp_path / "m"
    path.write_text("8 8\n0\n")
    m = io_text.read_matrix(str(path), 4)
    assert m.nnzb == 0
    io_text.write_matrix(str(tmp_path / "out"), m)
    assert (tmp_path / "out").read_bytes() == b"8 8\n0\n"


def test_missing_file_raises_filenotfound(tmp_path, monkeypatch):
    """Both parser paths (native rc=-1, python open) must raise
    FileNotFoundError for a missing file -- the reference prints an error
    and exits (sparse_matrix_mult.cu:346-349)."""
    with pytest.raises(FileNotFoundError):
        io_text.read_matrix(str(tmp_path / "nope"), 2)
    monkeypatch.setenv("SPGEMM_TPU_NO_NATIVE", "1")
    with pytest.raises(FileNotFoundError):
        io_text.read_matrix(str(tmp_path / "nope"), 2)
    with pytest.raises(FileNotFoundError):
        io_text.read_size(str(tmp_path))


@pytest.mark.parametrize("text,why", [
    ("", "empty file"),
    ("2 2\n", "header only, no block count"),
    ("2 2\n1\n0 0\n1 2\n3\n", "truncated tile data"),
    ("2 2\n2\n0 0\n1 2\n3 4\n", "block count larger than data"),
])
def test_malformed_matrix_raises_valueerror(tmp_path, monkeypatch, text, why):
    """Malformed inputs must raise ValueError on BOTH parser paths (the
    native tokenizer and the numpy fallback must agree on rejection)."""
    path = tmp_path / "m"
    path.write_text(text)
    with pytest.raises(ValueError):
        io_text.read_matrix(str(path), 2)
    monkeypatch.setenv("SPGEMM_TPU_NO_NATIVE", "1")
    with pytest.raises(ValueError):
        io_text.read_matrix(str(path), 2)


def test_malformed_size_file(tmp_path):
    (tmp_path / "size").write_text("3\n")
    with pytest.raises(ValueError):
        io_text.read_size(str(tmp_path))


def test_prune_zeros():
    tiles = np.zeros((3, 2, 2), dtype=np.uint64)
    tiles[1, 0, 1] = 5
    m = BlockSparseMatrix.from_blocks(4, 4, 2, [(0, 0), (0, 1), (1, 1)], tiles)
    p = m.prune_zeros()
    assert p.nnzb == 1
    assert tuple(p.coords[0]) == (0, 1)
