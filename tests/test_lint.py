"""spgemm-lint: the repo self-lints clean (tier-1 gate), and each seeded
fixture violation (FLD incl. the interprocedural pass / KNB / BKD / THR /
LCK / BLK / TSI / EXC / SUP / DOC) is caught with the correct rule ID --
both in-process and through the `python -m spgemm_tpu.analysis --json` /
`--sarif` reports that CI consumes -- plus the v3 contracts: the
content-hash result cache (warm runs hit, edits invalidate, output stays
byte-identical), SARIF `suppressions` objects on escaped findings, and
the generated ARCHITECTURE.md thread-inventory table."""

import json
import os
import subprocess
import sys

from conftest import run_repo_script as _run
from spgemm_tpu.analysis import (check_claude_md, core, docrules, lint_file,
                                 lint_repo)

REPO = core.repo_root()
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
FIXTURE_CLAUDE = os.path.join(FIXTURES, "CLAUDE.md")


def _fixture_lines(name: str, needle: str) -> list[int]:
    """1-indexed lines of a fixture whose text contains needle."""
    src = open(os.path.join(FIXTURES, name)).read()
    return [i for i, ln in enumerate(src.splitlines(), 1) if needle in ln]


# ------------------------------------------------------- self-lint gate --
def test_repo_self_lints_clean():
    """The tier-1 contract: zero findings on the migrated repo -- package
    AST rules AND the doc drift checks (CLAUDE.md knob table, CLI help)."""
    findings = lint_repo()
    assert findings == [], "\n".join(
        f"{f.file}:{f.line}: [{f.rule}] {f.message}" for f in findings)


def test_default_scope_covers_driver_scripts():
    """bench.py / benchmarks / the graft entry read engine knobs too --
    the default walk must keep them under the KNB/BKD contract."""
    names = {os.path.basename(p) for p in core.default_paths()}
    assert {"spgemm_tpu", "bench.py", "benchmarks",
            "__graft_entry__.py"} <= names


# ------------------------------------------------------------- FLD rule --
def test_fld_fixture_each_violation_caught():
    findings = lint_file(os.path.join(FIXTURES, "ops", "spgemm.py"))
    fld = [f for f in findings if f.rule == "FLD"]
    # jnp.sum, lax.psum, segment_sum, functools.reduce, method .sum()
    assert len(fld) == 5
    assert [f for f in findings if f.rule != "FLD"] == []
    assert all(f.file.endswith("ops/spgemm.py") and f.line > 0 for f in fld)


def test_fld_escape_hatch_suppresses_with_reason():
    src = open(os.path.join(FIXTURES, "ops", "spgemm.py")).read()
    escaped_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                        if "escaped: must NOT" in ln)
    findings = lint_file(os.path.join(FIXTURES, "ops", "spgemm.py"))
    assert escaped_line not in [f.line for f in findings]


def test_fld_escape_requires_reason(tmp_path):
    """A bare fld-proof() is not an escape: the reason is the citation."""
    p = tmp_path / "ops" / "u64.py"  # numeric-path suffix
    p.parent.mkdir()
    p.write_text("import jax.numpy as jnp\n"
                 "def f(x):\n"
                 "    # spgemm-lint: fld-proof()\n"
                 "    return jnp.sum(x)\n")
    assert [f.rule for f in lint_file(str(p))] == ["FLD"]


def test_fld_scope_is_path_based(tmp_path):
    """The same reductions in a non-numeric module are not findings."""
    p = tmp_path / "hostutil.py"
    p.write_text("import jax.numpy as jnp\n"
                 "def f(x):\n"
                 "    return jnp.sum(x)\n")
    assert lint_file(str(p)) == []
    assert [f.rule for f in lint_file(str(p), numeric=True)] == ["FLD"]


def test_fld_delta_module_in_numeric_scope():
    """ops/delta.py (incremental recompute) is in the numeric-lint scope:
    its reachability masks gate which output rows re-fold, so a smuggled
    unordered reduction is a finding -- and the LIVE module self-lints
    clean."""
    assert core.is_numeric_module("spgemm_tpu/ops/delta.py")
    findings = lint_file(os.path.join(FIXTURES, "ops", "delta.py"))
    assert [f.rule for f in findings] == ["FLD"]
    assert "jnp.sum" in findings[0].message
    live = lint_file(os.path.join(REPO, "spgemm_tpu", "ops", "delta.py"))
    assert live == [], "\n".join(
        f"{f.file}:{f.line}: [{f.rule}] {f.message}" for f in live)


def test_fld_estimator_module_in_numeric_scope():
    """ops/estimate.py (the sampled planner estimator) is in the
    numeric-lint scope: a jnp.sum smuggled into an estimator helper is a
    finding -- and the LIVE module self-lints clean (its sizing sums carry
    reasoned fld-proof escapes)."""
    assert core.is_numeric_module("spgemm_tpu/ops/estimate.py")
    findings = lint_file(os.path.join(FIXTURES, "ops", "estimate.py"))
    assert [f.rule for f in findings] == ["FLD"]
    assert "jnp.sum" in findings[0].message
    live = lint_file(os.path.join(REPO, "spgemm_tpu", "ops", "estimate.py"))
    assert live == [], "\n".join(
        f"{f.file}:{f.line}: [{f.rule}] {f.message}" for f in live)


# ------------------------------------------------------------- KNB rule --
def test_knb_fixture_each_violation_caught():
    """Every READ spelling is a finding (the three classic ones plus the
    seeded planner-, serve-, and estimator-knob reads); the write/del in
    the same fixture (how harnesses and tests drive knob values) must NOT
    be."""
    findings = lint_file(os.path.join(FIXTURES, "badknob.py"))
    assert [f.rule for f in findings] == ["KNB"] * 25
    msgs = " ".join(f.message for f in findings)
    for seeded in ("SPGEMM_TPU_SEEDED_A", "SPGEMM_TPU_SEEDED_B",
                   "SPGEMM_TPU_SEEDED_C", "SPGEMM_TPU_PLAN_AHEAD",
                   "SPGEMM_TPU_PLAN_CACHE_CAP", "SPGEMM_TPU_SERVE_SOCKET",
                   "SPGEMM_TPU_SERVE_QUEUE_CAP",
                   "SPGEMM_TPU_SERVE_JOB_TIMEOUT",
                   "SPGEMM_TPU_SERVE_WEDGE_GRACE_S",
                   "SPGEMM_TPU_PLAN_ESTIMATE",
                   "SPGEMM_TPU_EST_SAMPLE_ROWS",
                   "SPGEMM_TPU_EST_CONFIDENCE",
                   "SPGEMM_TPU_DELTA", "SPGEMM_TPU_DELTA_RETAIN",
                   "SPGEMM_TPU_OBS_EVENTS",
                   "SPGEMM_TPU_OBS_EVENTS_MAX_KB",
                   "SPGEMM_TPU_WARM", "SPGEMM_TPU_WARM_DIR",
                   "SPGEMM_TPU_WARM_MAX_MB",
                   "SPGEMM_TPU_SERVE_BATCH_K",
                   "SPGEMM_TPU_SERVE_BATCH_WINDOW_S",
                   "SPGEMM_TPU_ACCUM_ROUTE",
                   "SPGEMM_TPU_SERVE_ADDR",
                   "SPGEMM_TPU_ROUTER_BACKENDS",
                   "SPGEMM_TPU_ROUTER_POLL_S"):
        assert seeded in msgs  # the finding names the offending knob


def test_knb_registry_module_is_exempt():
    """knobs.py itself reads the environment -- the one blessed reader."""
    findings = lint_file(os.path.join(REPO, "spgemm_tpu", "utils",
                                      "knobs.py"))
    assert [f for f in findings if f.rule == "KNB"] == []


# ------------------------------------------------------------- BKD rule --
def test_bkd_fixture_each_violation_caught():
    findings = lint_file(os.path.join(FIXTURES, "badbackend.py"))
    # jax.devices() at module scope, jnp.zeros() at module scope (array
    # materialization initializes the backend), jax.local_devices() in a
    # default-argument expression
    assert [f.rule for f in findings] == ["BKD"] * 3
    flagged = [f.line for f in findings]
    src = open(os.path.join(FIXTURES, "badbackend.py")).read()
    lazy_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                     if "legal" in ln and "jax.devices" in ln)
    main_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                     if "script driver" in ln)
    assert lazy_line not in flagged and main_line not in flagged


def test_bkd_probe_module_is_exempt():
    findings = lint_file(os.path.join(REPO, "spgemm_tpu", "utils",
                                      "backend_probe.py"))
    assert [f for f in findings if f.rule == "BKD"] == []


def test_bkd_host_only_body_is_scanned():
    """@host_only (utils/backend_probe) marks planner/worker-thread code:
    its WHOLE body is in BKD scope -- a backend touch there hangs a thread
    the pipeline is blocked on -- while unmarked function bodies keep the
    import-time-only rule."""
    findings = lint_file(os.path.join(FIXTURES, "badplanner.py"))
    assert [f.rule for f in findings] == ["BKD"] * 2
    msgs = " ".join(f.message for f in findings)
    assert "host_only" in msgs and "jax.devices" in msgs
    src = open(os.path.join(FIXTURES, "badplanner.py")).read()
    flagged = [f.line for f in findings]
    legal = next(i for i, ln in enumerate(src.splitlines(), 1)
                 if "legal" in ln and "jax.devices" in ln)
    assert legal not in flagged  # unmarked lazy touch stays legal


def test_bkd_host_only_dotted_decorator(tmp_path):
    """The dotted spelling `@backend_probe.host_only` is recognized too,
    and a passing helper (pure numpy) yields no finding."""
    p = tmp_path / "planhelp.py"
    p.write_text("from spgemm_tpu.utils import backend_probe\n"
                 "import numpy as np\n"
                 "import jax\n"
                 "@backend_probe.host_only\n"
                 "def bad(x):\n"
                 "    return jax.device_put(x)\n"
                 "@backend_probe.host_only\n"
                 "def good(x):\n"
                 "    return np.asarray(x).sum()\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["BKD"]
    assert "jax.device_put" in findings[0].message


def test_host_only_marker_on_planner_entrypoints():
    """The engine's planner bodies really carry the marker the rule keys
    on (the runtime attribute host_only sets)."""
    from spgemm_tpu.chain import _PlanAheadWorker
    from spgemm_tpu.ops.spgemm import _plan_host

    assert getattr(_plan_host, "__spgemm_host_only__", False)
    assert getattr(_PlanAheadWorker._work, "__spgemm_host_only__", False)


# ------------------------------------------------------------- MET rule --
def test_met_fixture_each_violation_caught():
    """Undeclared phase/counter names and a computed name are findings;
    declared names and ad-hoc PhaseTimers instances stay legal."""
    findings = lint_file(os.path.join(FIXTURES, "badmetric.py"))
    met = [f for f in findings if f.rule == "MET"]
    assert len(met) == 10 and findings == met
    flagged = [f.line for f in met]
    for needle in ("MET: undeclared phase name",
                   "MET: undeclared counter name",
                   "MET: computed metric name",
                   "MET: undeclared profile counter",
                   "MET: undeclared profile phase",
                   "MET: undeclared warm counter",
                   "MET: undeclared warm phase",
                   "MET: undeclared batch counter",
                   "MET: undeclared dense counter",
                   "MET: undeclared dense phase"):
        assert _fixture_lines("badmetric.py", needle)[0] in flagged
    msgs = " ".join(f.message for f in met)
    assert "made_up_phase" in msgs and "made_up_counter" in msgs
    # the deep-profiling near-misses: the FAMILY name is not the declared
    # counter name, and an ad-hoc compile phase does not exist
    assert "spgemm_compiles_total" in msgs and "compile_wait" in msgs
    # the warm-start near-misses: the singular of the declared counter
    # and an ad-hoc load phase
    assert "warm_hit" in msgs and "warm_loading" in msgs
    # the dense-route near-misses: the truncated counter name and an
    # ad-hoc fold phase
    assert "route_den" in msgs and "dense_folding" in msgs
    assert "ENGINE_PHASES" in msgs and "ENGINE_COUNTERS" in msgs
    for needle in ("legal: declared phase", "legal: declared counter",
                   "legal: not the ENGINE registry",
                   "legal: declared warm phase",
                   "legal: declared warm counter",
                   "legal: declared batch counter",
                   "legal: declared dense phase",
                   "legal: declared dense counter"):
        assert _fixture_lines("badmetric.py", needle)[0] not in flagged


def test_met_alias_spellings_resolve(tmp_path):
    """Both repo spellings -- `from ...timers import ENGINE` and the
    `import ... as t` + `t.ENGINE` form -- resolve to the registry, and
    the keyword spelling `name=` is in scope too (both mint the
    series)."""
    p = tmp_path / "h.py"
    p.write_text("from spgemm_tpu.utils.timers import ENGINE\n"
                 "import spgemm_tpu.utils.timers as t\n"
                 "from spgemm_tpu.utils import timers\n"
                 "def f(i):\n"
                 "    ENGINE.incr('nope_a')\n"
                 "    t.ENGINE.incr('nope_b')\n"
                 "    timers.ENGINE.incr('nope_c')\n"
                 "    ENGINE.incr(name='nope_kw')\n"
                 "    ENGINE.incr(name=f'dyn_{i}')\n"
                 "    ENGINE.incr('dispatches')\n"
                 "    ENGINE.incr(name='dispatches')\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["MET"] * 5
    assert [f.line for f in findings] == [5, 6, 7, 8, 9]


def test_met_registry_covers_live_call_sites():
    """Every ENGINE phase/counter name the package actually uses is
    declared (the repo self-lint enforces this; spot-check the registry
    side so a deleted declaration cannot slip through unnoticed)."""
    from spgemm_tpu.obs.metrics import ENGINE_COUNTERS, ENGINE_PHASES

    for name in ("plan", "plan_wait", "numeric_dispatch", "assembly",
                 "ring_fold", "dcn_exchange", "serve_execute",
                 "serve_queue_wait", "estimate", "join_fallback",
                 "delta_diff", "delta_splice", "warm_load", "warm_flush"):
        assert name in ENGINE_PHASES
    for name in ("dispatches", "plan_cache_hits", "plan_cache_misses",
                 "plan_cache_evictions", "ring_steps", "serve_reaps",
                 "serve_degrades", "est_hits", "est_fallbacks",
                 "delta_rows_recomputed", "delta_rows_total",
                 "delta_full_fallbacks", "compiles", "warm_hits",
                 "warm_misses", "warm_corrupt"):
        assert name in ENGINE_COUNTERS


# ------------------------------------------------------------- FPT rule --
def test_fpt_fixture_each_violation_caught():
    """Undeclared failpoint names (module and bare-import spellings) and
    a computed name are findings; declared names stay legal."""
    findings = lint_file(os.path.join(FIXTURES, "badfailpoint.py"))
    fpt = [f for f in findings if f.rule == "FPT"]
    assert len(fpt) == 3 and findings == fpt
    flagged = [f.line for f in fpt]
    for needle in ("FPT: undeclared failpoint name",
                   "FPT: computed failpoint name",
                   "FPT: undeclared via the bare import"):
        assert _fixture_lines("badfailpoint.py", needle)[0] in flagged
    msgs = " ".join(f.message for f in fpt)
    assert "made.up.point" in msgs and "also.made.up" in msgs
    assert "utils/failpoints.py" in msgs
    for needle in ("legal: declared (corrupt kind)",
                   "legal: declared via the bare import"):
        assert _fixture_lines("badfailpoint.py", needle)[0] not in flagged


def test_fpt_stale_registry_entry_is_a_finding(tmp_path):
    """The reverse direction: a registry entry no check() site names is
    flagged AT THE REGISTRY -- and only when the registry module itself
    is in the linted unit set (fixture runs over partial trees must not
    call every entry stale)."""
    import shutil

    from spgemm_tpu.analysis.core import lint_report
    from spgemm_tpu.utils.failpoints import REGISTRY

    # a partial tree WITHOUT the registry module: quiet
    site = tmp_path / "site.py"
    site.write_text("from spgemm_tpu.utils import failpoints\n"
                    "def f():\n"
                    "    failpoints.check('warm.load')\n")
    findings, _ = lint_report([str(site)], doc=False)
    assert [f for f in findings if f.rule == "FPT"] == []

    # the registry module + one site: every OTHER entry is stale
    pkg = tmp_path / "utils"
    pkg.mkdir()
    shutil.copy(os.path.join(REPO, "spgemm_tpu", "utils",
                             "failpoints.py"),
                str(pkg / "failpoints.py"))
    findings, _ = lint_report([str(site), str(pkg)], doc=False)
    stale = [f for f in findings if f.rule == "FPT"
             and "stale failpoint registry entry" in f.message]
    assert len(stale) == len(REGISTRY) - 1  # all but the checked one
    assert all(f.file.endswith("failpoints.py") for f in stale)
    assert not any("'warm.load'" in f.message for f in stale)


def test_fpt_registry_covers_live_call_sites():
    """Every failpoint the chaos harness documents is declared (the repo
    self-lint enforces site coverage; spot-check the registry side)."""
    from spgemm_tpu.utils.failpoints import REGISTRY

    for name in ("plan.build", "plan.ensure_exact", "kernel.dispatch",
                 "delta.diff", "delta.splice", "warm.load", "warm.flush",
                 "serve.journal", "serve.accept", "serve.readline",
                 "serve.executor", "serve.heartbeat"):
        assert name in REGISTRY
    assert all(fp.kind in ("raise", "hang", "corrupt", "delay")
               for fp in REGISTRY.values())


# ------------------------------------------------------------- PRO rule --
def test_pro_fixture_each_violation_caught():
    """Undeclared request/response fields (with and without op context),
    an unknown op, a hardcoded version stamp, undeclared error codes at
    raise and compare sites, and an undeclared E_* constant are
    findings; declared fields/codes and unconventional receiver names
    stay legal."""
    findings = lint_file(os.path.join(FIXTURES, "badproto.py"))
    pro = [f for f in findings if f.rule == "PRO"]
    assert len(pro) == 9 and findings == pro
    flagged = [f.line for f in pro]
    for needle in ("PRO: undeclared request field for status",
                   "PRO: undeclared request field `priority`",
                   "PRO: undeclared error code at a raise site",
                   "PRO: undeclared response field",
                   "PRO: undeclared error code on a code-flavored",
                   "PRO: undeclared error-code constant"):
        # seed comments sit on the finding's line or the line above
        # (dict-literal findings anchor on the literal's first line)
        line = _fixture_lines("badproto.py", needle)[0]
        assert line in flagged or line + 1 in flagged, needle
    # the worse-dict line carries BOTH the unknown-op and the
    # hardcoded-version findings
    (worse_line,) = _fixture_lines("badproto.py", '{"op": "frobnicate"')
    assert flagged.count(worse_line) == 2
    msgs = " ".join(f.message for f in pro)
    assert "flavor" in msgs and "verbose" in msgs and "priority" in msgs
    assert "frobnicate" in msgs and "version_for" in msgs
    assert "went-sideways" in msgs and "transient-blip" in msgs
    assert "E_NOPE" in msgs and "REQUEST_FIELDS" in msgs
    for needle in ("legal: declared status request field",
                   "legal: declared submit fields + envelope",
                   "legal: envelope field",
                   "legal: declared response field"):
        assert _fixture_lines("badproto.py", needle)[0] not in flagged
    out_of_scope = _fixture_lines("badproto.py", "return record.get")[0]
    assert out_of_scope not in flagged


def test_pro_registry_coherence_audit(tmp_path):
    """The package-level PRO direction audits the LIVE registry
    (request/response op symmetry, min versions in range, one version
    per field name, post-v1 fields in FIELD_MIN_VERSION, E_* constants
    matching ERROR_CODES both ways), anchored at the registry module's
    declaration lines -- and gates on protocol.py itself being in the
    unit set, so partial trees stay quiet."""
    from spgemm_tpu.analysis.core import lint_report

    src = open(os.path.join(REPO, "spgemm_tpu", "serve",
                            "protocol.py")).read()
    # the real registry at the real suffix: coherent, zero PRO findings
    pkg = tmp_path / "serve"
    pkg.mkdir()
    (pkg / "protocol.py").write_text(src)
    findings, _ = lint_report([str(pkg)], doc=False)
    assert [f for f in findings if f.rule == "PRO"] == []
    # a wrong-suffix copy never gates the audit on
    (tmp_path / "notprotocol.py").write_text(src)
    findings, _ = lint_report([str(tmp_path / "notprotocol.py")],
                              doc=False)
    assert [f for f in findings if f.rule == "PRO"] == []


def test_pro_registry_audit_catches_incoherence(tmp_path, monkeypatch):
    """Seed the live tables with every incoherence class and watch the
    audit flag each: a request-only op, an out-of-range min version, a
    field name carrying two versions across ops, and a post-v1 field
    missing from FIELD_MIN_VERSION (the rolling-upgrade hazard)."""
    from spgemm_tpu.analysis.core import lint_report
    from spgemm_tpu.serve import protocol

    bad_requests = dict(protocol.REQUEST_FIELDS)
    bad_requests["phantom"] = {"thing": 9}       # no response half; v9
    bad_requests["status"] = {"id": 2}           # 'id' is 1 elsewhere
    bad_requests["wait"] = {"id": 1, "timeout": 1, "rush": 3}  # no FMV
    monkeypatch.setattr(protocol, "REQUEST_FIELDS", bad_requests)
    pkg = tmp_path / "serve"
    pkg.mkdir()
    (pkg / "protocol.py").write_text(open(os.path.join(
        REPO, "spgemm_tpu", "serve", "protocol.py")).read())
    findings, _ = lint_report([str(pkg)], doc=False)
    msgs = " ".join(f.message for f in findings if f.rule == "PRO")
    assert "'phantom'" in msgs and "only one of" in msgs
    assert "outside 1..PROTOCOL_VERSION" in msgs
    assert "two min versions" in msgs
    assert "rolling-upgrade hazard" in msgs and "'rush'" in msgs


def test_pro_guard_deletion_on_daemon_copy(tmp_path):
    """Guard-deletion spot-check: the pristine daemon lints PRO-clean,
    and a typo'd response kwarg on a copy goes red -- deleting or
    misspelling a wire field cannot land silently."""
    src = open(os.path.join(REPO, "spgemm_tpu", "serve",
                            "daemon.py")).read()
    p = tmp_path / "daemon.py"
    p.write_text(src)
    clean = [f for f in lint_file(str(p)) if f.rule in ("PRO", "EVT")]
    assert clean == []
    needle = "state=job.state, queued="
    assert needle in src  # the _op_submit protocol.ok kwargs
    p.write_text(src.replace(needle, "state=job.state, qeued=", 1))
    broken = [f for f in lint_file(str(p)) if f.rule == "PRO"]
    assert broken and "qeued" in broken[0].message


# ------------------------------------------------------------- EVT rule --
def test_evt_fixture_each_violation_caught():
    """Undeclared kinds through the module alias and the LOG singleton,
    and a computed kind through the bare import, are findings; declared
    kinds and local emit helpers stay legal."""
    findings = lint_file(os.path.join(FIXTURES, "badevent.py"))
    evt = [f for f in findings if f.rule == "EVT"]
    assert len(evt) == 3 and findings == evt
    flagged = [f.line for f in evt]
    for needle in ("EVT: undeclared kind via the module alias",
                   "EVT: undeclared kind via the LOG singleton",
                   "EVT: computed kind via the bare import"):
        line = _fixture_lines("badevent.py", needle)[0]
        assert line in flagged or line + 1 in flagged, needle
    msgs = " ".join(f.message for f in evt)
    assert "job_vanished" in msgs and "daemon_hiccup" in msgs
    assert "EVENT_KINDS" in msgs
    for needle in ("legal: declared kind",
                   "legal: not the obs/events log"):
        for line in _fixture_lines("badevent.py", needle):
            assert line not in flagged


def test_evt_guard_deletion_on_daemon_copy(tmp_path):
    """Guard-deletion spot-check, event side: renaming an emitted kind
    on a daemon copy goes red against EVENT_KINDS."""
    src = open(os.path.join(REPO, "spgemm_tpu", "serve",
                            "daemon.py")).read()
    assert '"job_submit"' in src
    p = tmp_path / "daemon.py"
    p.write_text(src.replace('"job_submit"', '"job_submitted"', 1))
    broken = [f for f in lint_file(str(p)) if f.rule == "EVT"]
    assert broken and "job_submitted" in broken[0].message


def test_evt_registry_covers_live_kinds():
    """Every lifecycle kind the daemon and engine actually emit is
    declared (the repo self-lint enforces the site direction;
    spot-check the registry side)."""
    from spgemm_tpu.obs.events import EVENT_KINDS

    for kind in ("daemon_start", "job_submit", "job_done", "job_failed",
                 "watchdog_reap", "watchdog_wedge", "est_fallback",
                 "delta_fallback", "warm_load", "compile", "slo_burn",
                 "slo_burn_clear", "failpoint_trigger"):
        assert kind in EVENT_KINDS
        assert EVENT_KINDS[kind]  # every kind carries its doc


# ------------------------------------------------------------- DRF rule --
def test_drf_quiet_without_registry_modules():
    """The drift audit self-gates on each registry module being in the
    linted unit set: the fixture site alone yields nothing."""
    findings = lint_file(os.path.join(FIXTURES, "staledrift.py"))
    assert findings == []
    from spgemm_tpu.analysis.core import lint_report

    findings, _ = lint_report(
        [os.path.join(FIXTURES, "staledrift.py")], doc=False)
    assert findings == []


def test_drf_stale_registry_entries_flagged_at_declarations(tmp_path):
    """Registry copies at the real suffixes + the one-reference fixture
    site: every UNreferenced knob and event kind is a DRF finding at
    its declaration line; the referenced ones are not; the drf-ok
    escape on the shell-side knob suppresses (inventoried, not
    stale)."""
    import shutil

    from spgemm_tpu.analysis.core import lint_run

    for sub, name in (("utils", "knobs.py"), ("obs", "events.py")):
        d = tmp_path / sub
        d.mkdir(exist_ok=True)
        shutil.copy(os.path.join(REPO, "spgemm_tpu", sub, name),
                    str(d / name))
    site = tmp_path / "site.py"
    site.write_text(
        open(os.path.join(FIXTURES, "staledrift.py")).read())
    report = lint_run([str(tmp_path)], doc=False)
    drf = [f for f in report.findings if f.rule == "DRF"]
    assert drf, "expected drift findings against the registry copies"
    assert all(f.file.endswith(("knobs.py", "events.py")) for f in drf)
    msgs = " ".join(f.message for f in drf)
    # unreferenced entries flagged...
    assert "SPGEMM_TPU_MXU_R" in msgs
    assert "job_done" in msgs
    # ...referenced ones not, and the escaped shell-side knob rides the
    # suppression inventory instead of the findings
    assert "SPGEMM_TPU_PLAN_CACHE " not in msgs
    assert "'job_start'" not in msgs
    assert "SPGEMM_TPU_EVIDENCE_STEPS" not in msgs
    esc = [s for s in report.suppressions
           if s.rule == "DRF" and "EVIDENCE_STEPS" in s.reason
           or s.rule == "DRF" and "shell-side" in s.reason]
    assert esc and not any(s.stale for s in esc)
    # findings anchor at the declaration lines (the quoted name)
    knobs_src = open(os.path.join(REPO, "spgemm_tpu", "utils",
                                  "knobs.py")).read().splitlines()
    for f in drf:
        if f.file.endswith("knobs.py"):
            assert '"SPGEMM_TPU_' in knobs_src[f.line - 1]


def test_drf_signature_covers_new_registries():
    """Editing serve/protocol.py or obs/events.py changes the analysis
    signature, so every cached per-file PRO/EVT result is invalidated
    on the next run (the same contract MET/FPT already have)."""
    before = core._analysis_signature()
    path = os.path.join(REPO, "spgemm_tpu", "serve", "protocol.py")
    original = open(path, "rb").read()
    try:
        with open(path, "ab") as f:
            f.write(b"\n# signature-probe\n")
        assert core._analysis_signature() != before
    finally:
        with open(path, "wb") as f:
            f.write(original)
    assert core._analysis_signature() == before


# ------------------------------------------------------------- DOC rule --
def test_doc_fixture_drift_caught():
    findings = check_claude_md(FIXTURE_CLAUDE)
    assert [f.rule for f in findings] == ["DOC"]
    assert "drifted" in findings[0].message


def test_doc_current_table_passes_and_tamper_fails(tmp_path):
    good = tmp_path / "CLAUDE.md"
    good.write_text("# doc\n\n" + docrules.render_knob_block() + "\n")
    assert check_claude_md(str(good)) == []
    tampered = good.read_text().replace("SPGEMM_TPU_VPU_ALGO", "SPGEMM_TPU_GONE")
    good.write_text(tampered)
    assert [f.rule for f in check_claude_md(str(good))] == ["DOC"]
    good.write_text("# no markers at all\n")
    findings = check_claude_md(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "markers missing" in findings[0].message


def test_doc_cli_help_covers_every_knob():
    assert docrules.check_cli_help() == []


def test_doc_metrics_table_current_and_tamper_fails(tmp_path):
    """The ARCHITECTURE.md metrics table is held to the obs/metrics.py
    registry exactly like the knob table is to knobs.py."""
    good = tmp_path / "ARCHITECTURE.md"
    good.write_text("# arch\n\n" + docrules.render_metrics_block() + "\n")
    assert docrules.check_architecture_md(str(good)) == []
    tampered = good.read_text().replace("spgemm_phase_seconds_total",
                                        "spgemm_gone_total")
    good.write_text(tampered)
    findings = docrules.check_architecture_md(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "drifted" in findings[0].message
    good.write_text("# no markers at all\n")
    findings = docrules.check_architecture_md(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "markers missing" in findings[0].message


def test_write_metrics_table_regenerates(tmp_path):
    """`--write-metrics-table` rewrites the marked block in place, after
    which the DOC check passes."""
    arch = tmp_path / "ARCHITECTURE.md"
    arch.write_text("# doc\n" + docrules.METRICS_TABLE_BEGIN + "\nstale\n"
                    + docrules.METRICS_TABLE_END + "\ntail\n")
    rc = _run(["-m", "spgemm_tpu.analysis", "--write-metrics-table",
               "--architecture-md", str(arch)])
    assert rc.returncode == 0, rc.stderr[-2000:]
    assert docrules.check_architecture_md(str(arch)) == []
    assert arch.read_text().startswith("# doc\n")
    assert arch.read_text().endswith("\ntail\n")


def test_doc_thread_inventory_current_and_tamper_fails(tmp_path):
    """The generated ARCHITECTURE.md thread-inventory table is held to
    the concurrency pass's output exactly like the knob and metrics
    tables are to their registries."""
    good = tmp_path / "ARCHITECTURE.md"
    good.write_text("# arch\n\n" + docrules.render_thread_block() + "\n")
    assert docrules.check_thread_inventory(str(good)) == []
    tampered = good.read_text().replace("Daemon._watchdog_loop",
                                        "Daemon._gone_loop")
    assert tampered != good.read_text()
    good.write_text(tampered)
    findings = docrules.check_thread_inventory(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "drifted" in findings[0].message
    good.write_text("# no markers at all\n")
    findings = docrules.check_thread_inventory(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "markers missing" in findings[0].message


def test_write_thread_inventory_regenerates(tmp_path):
    """`--write-thread-inventory` rewrites the marked block in place,
    after which the DOC check passes."""
    arch = tmp_path / "ARCHITECTURE.md"
    arch.write_text("# doc\n" + docrules.THREAD_TABLE_BEGIN + "\nstale\n"
                    + docrules.THREAD_TABLE_END + "\ntail\n")
    rc = _run(["-m", "spgemm_tpu.analysis", "--write-thread-inventory",
               "--architecture-md", str(arch)])
    assert rc.returncode == 0, rc.stderr[-2000:]
    assert docrules.check_thread_inventory(str(arch)) == []
    assert arch.read_text().startswith("# doc\n")
    assert arch.read_text().endswith("\ntail\n")


def test_thread_inventory_covers_live_daemon_roots():
    """Spot-check the generated rows: the resident daemon's thread
    population (PRs 12-13) resolves as roots -- executors, watchdog,
    accept loop, recovery probe, the event-log writer, the plan-ahead
    worker -- so the table the docs commit actually inventories the
    threads the concurrency pass reasons about."""
    md = docrules.thread_inventory_md()
    for root in ("serve.daemon.Daemon._executor_loop",
                 "serve.daemon.Daemon._watchdog_loop",
                 "serve.daemon.Daemon._accept_loop",
                 "serve.daemon.Daemon._recover_probe",
                 "obs.events.EventLog._writer_loop",
                 "chain._PlanAheadWorker._work",
                 # nested-def targets resolve as roots in their own
                 # right: the degrade probe and the OOC pipeline workers
                 "serve.daemon.Daemon._degrade_slice._run_probe",
                 "ops.spgemm.spgemm_outofcore._lander",
                 "ops.spgemm.spgemm_outofcore._stager"):
        assert f"`{root}`" in md
    # the executor root's row names the locks it may transitively hold
    executor_row = next(ln for ln in md.splitlines()
                        if "Daemon._executor_loop" in ln)
    assert "serve.daemon.Daemon._lock" in executor_row
    assert "ops.warmstore._LOCK" in executor_row


# ----------------------------------------------------------- PARSE rule --
def test_syntax_error_gets_its_own_rule_id(tmp_path):
    """A broken file means NO rule ran on it: its finding must not be
    attributed to a rule family in the JSON counts."""
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["PARSE"]
    assert "does not parse" in findings[0].message


# ------------------------------------------------------------- THR rule --
def test_thr_fixture_each_violation_caught():
    """Unguarded accesses of guarded-by-annotated state: a module-global
    write, an instance read, and a nested-def access (callbacks run later,
    usually on another thread -- the enclosing `with` does not protect
    them)."""
    findings = lint_file(os.path.join(FIXTURES, "badthread.py"))
    thr = [f for f in findings if f.rule == "THR"]
    assert len(thr) == 3 and findings == thr
    flagged = [f.line for f in thr]
    for needle in ("module-global write without the lock", "THR: no lock",
                   "return list(self._jobs)"):
        assert _fixture_lines("badthread.py", needle)[0] in flagged
    # the legal shapes stay clean: lock held, Condition alias, __init__,
    # *_locked convention, reasoned thr-ok escape
    for needle in ("legal: lock held", "legal: Condition aliases",
                   "legal: __init__", "caller holds the lock",
                   "escaped with a reason"):
        assert _fixture_lines("badthread.py", needle)[0] not in flagged


def test_thr_finding_names_attribute_and_lock():
    findings = lint_file(os.path.join(FIXTURES, "badthread.py"))
    msgs = " ".join(f.message for f in findings)
    assert "guarded-by(_lock)" in msgs and "guarded-by(_GLOCK)" in msgs
    assert "self._jobs" in msgs and "_G" in msgs


def test_thr_guard_deletion_turns_lint_red(tmp_path):
    """The acceptance spot-check, on FIXTURE COPIES of the live serving
    modules: deleting any one `with` lock guard in serve/queue.py or
    serve/daemon.py must produce a THR finding (the annotations actually
    bind)."""
    cases = [
        ("serve/queue.py",
         "        with self._lock:\n            return {",
         "        if True:\n            return {"),           # Job.snapshot
        ("serve/daemon.py",
         "        with self._lock:\n            degraded = self.degraded\n"
         "            degrade_reason = self.degrade_reason",
         "        if True:\n            degraded = self.degraded\n"
         "            degrade_reason = self.degrade_reason"),  # _op_stats
    ]
    for rel, guarded, unguarded in cases:
        src = open(os.path.join(REPO, "spgemm_tpu", rel)).read()
        assert lint_file(os.path.join(REPO, "spgemm_tpu", rel)) == []
        mutated = src.replace(guarded, unguarded)
        assert mutated != src, f"guard pattern drifted in {rel}"
        p = tmp_path / os.path.basename(rel)
        p.write_text(mutated)
        thr = [f for f in lint_file(str(p)) if f.rule == "THR"]
        assert thr, f"deleting a lock guard in {rel} must turn lint red"


# ------------------------------------------------------------- LCK rule --
def test_lck_fixture_each_violation_caught():
    """The seeded lock-order fixture: the A->B vs B->A inversion is a
    cycle finding carrying BOTH witness chains, and the call-edge
    re-acquisition is the non-reentrant self-deadlock finding; the
    same-order nest stays a legal edge."""
    findings = core.lint_paths([os.path.join(FIXTURES, "badlockorder.py")],
                               doc=False)
    assert [f.rule for f in findings] == ["LCK", "LCK"]
    by_line = {f.line: f.message for f in findings}
    cycle_line = _fixture_lines("badlockorder.py", "one half of the cycle")[0]
    self_line = _fixture_lines("badlockorder.py", "self-deadlock")[0]
    assert set(by_line) == {cycle_line, self_line}
    legal = _fixture_lines("badlockorder.py", "an edge, not a new cycle")[0]
    assert legal not in by_line
    cycle = by_line[cycle_line]
    assert "lock-order cycle" in cycle
    assert "a_then_b" in cycle and "b_then_a" in cycle  # both witnesses
    assert "._A`" in cycle and "._B`" in cycle
    self_edge = by_line[self_line]
    assert "re-acquired while already held" in self_edge
    assert "reenters -> helper" in self_edge  # the witness chain
    assert "non-reentrant" in self_edge
    # RLock re-entry through a call edge is its documented use-case --
    # never a self-edge finding
    rlock = _fixture_lines("badlockorder.py", "RLock re-entry")[0]
    assert rlock not in by_line


def test_lck_multi_item_with_inversion_caught(tmp_path):
    """Review regression: `with A, B:` acquires left-to-right exactly
    like nested withs -- the single-statement spelling of one half of an
    AB/BA inversion must still close the cycle."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_A = threading.Lock()\n"
        "_B = threading.Lock()\n"
        "def f():\n"
        "    with _A, _B:\n"
        "        pass\n"
        "def g():\n"
        "    with _B:\n"
        "        with _A:\n"
        "            pass\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["LCK"]
    assert "lock-order cycle" in findings[0].message


def test_lck_conditionally_defined_module_lock_registers(tmp_path):
    """Review regression: a lock assigned inside a module-level try/if
    block still executes at module scope -- it must register (hazards
    on it checked), while function-local assignments must not leak into
    the module registry."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "try:\n"
        "    _L = threading.Lock()\n"
        "except ImportError:\n"
        "    _L = None\n"
        "def reenters():\n"
        "    with _L:\n"
        "        helper()\n"
        "def helper():\n"
        "    with _L:\n"
        "        pass\n"
        "def local_only():\n"
        "    _M = threading.Lock()\n"   # a local, not a module lock
        "    with _M:\n"
        "        pass\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["LCK"]
    assert "re-acquired while already held" in findings[0].message


def test_lck_escaped_anchor_does_not_vouch_for_other_sites(tmp_path):
    """Review regression: an lck-ok on one re-acquisition site argues
    THAT site's unreachability only -- the same hazard spelled at
    another site still turns lint red (the live finding moves to the
    first unescaped site; the escape stays used, not stale)."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_L = threading.Lock()\n"
        "def escaped_path():\n"
        "    with _L:\n"
        "        # spgemm-lint: lck-ok(seeded: this branch is gated unreachable)\n"
        "        helper()\n"
        "def other_path():\n"
        "    with _L:\n"
        "        helper()\n"
        "def helper():\n"
        "    with _L:\n"
        "        pass\n")
    findings, suppressions = core.lint_report([str(p)], doc=False)
    lck = [f for f in findings if f.rule == "LCK"]
    assert len(lck) == 1 and lck[0].line == 9  # the unescaped site
    assert findings == lck  # in particular: no stale-escape SUP
    assert len(suppressions) == 1 and not suppressions[0].stale


def test_lck_direct_self_recursion_caught(tmp_path):
    """Review regression: `with self._lock: self.step(...)` recursing
    into ITSELF is the one-edge re-acquisition deadlock -- the self
    call edge must not be dropped."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def step(self, n):\n"
        "        with self._lock:\n"
        "            if n:\n"
        "                self.step(n - 1)\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["LCK"]
    assert "re-acquired while already held" in findings[0].message


def test_lck_rlock_still_participates_in_order_cycles(tmp_path):
    """The RLock self-edge exemption must not blind the cycle detector:
    an RLock acquired in opposite orders against a plain Lock deadlocks
    exactly like two Locks -- still a finding."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_L = threading.Lock()\n"
        "_R = threading.RLock()\n"
        "def l_then_r():\n"
        "    with _L:\n"
        "        with _R:\n"
        "            pass\n"
        "def r_then_l():\n"
        "    with _R:\n"
        "        with _L:\n"
        "            pass\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["LCK"]
    assert "lock-order cycle" in findings[0].message


# ------------------------------------------------------------- BLK rule --
def test_blk_fixture_each_violation_caught():
    """The seeded blocking-under-lock fixture: direct sleep, transitive
    subprocess.run through a call edge, and the typed Queue.get are
    findings; no-lock blocking, the condition's own wait, and the
    reasoned blk-ok escape stay legal."""
    findings, suppressions = core.lint_report(
        [os.path.join(FIXTURES, "badblocking.py")], doc=False)
    assert [f.rule for f in findings] == ["BLK"] * 3
    flagged = [f.line for f in findings]
    for needle in ("BLK: sleeping while holding",
                   "BLK: reaches subprocess.run",
                   "BLK: Queue.get blocks"):
        assert _fixture_lines("badblocking.py", needle)[0] in flagged
    for needle in ("legal: nothing held", "legal: wait releases",
                   "time.sleep(0.0)"):
        assert _fixture_lines("badblocking.py", needle)[0] not in flagged
    by_line = {f.line: f.message for f in findings}
    trans = by_line[_fixture_lines("badblocking.py",
                                   "BLK: reaches subprocess.run")[0]]
    # the witness chain down to the blocking call, with its file:line
    assert "transitive -> helper -> `subprocess.run`" in trans
    assert "badblocking.py:" in trans
    # the escape is inventoried, in use (source escape on the sleep)
    blk = [s for s in suppressions if s.rule == "BLK"]
    assert len(blk) == 1 and not blk[0].stale


def test_blk_cond_wait_through_helper_discharges_own_lock(tmp_path):
    """Review regression: a Condition.wait hoisted into a helper still
    releases the condition's own lock -- the canonical cond-var pattern
    must not be flagged through the call edge; a SECOND held lock
    staying held across the wait still is."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "        self._other = threading.Lock()\n"
        "    def ok(self):\n"
        "        with self._cond:\n"
        "            self._wait_helper()\n"
        "    def bad(self):\n"
        "        with self._other:\n"
        "            with self._cond:\n"
        "                self._wait_helper()\n"
        "    def _wait_helper(self):\n"
        "        self._cond.wait()\n")
    findings = core.lint_paths([str(p)], doc=False)
    blk = [f for f in findings if f.rule == "BLK"]
    assert len(blk) == 1  # only the _other-held route
    assert "_other" in blk[0].message and "_cond.wait" in blk[0].message


def test_blk_cond_wait_does_not_shadow_later_blocking_op(tmp_path):
    """Review regression: the per-function block summary keeps one
    witness PER released lock -- a Condition.wait in a helper must not
    hide a plain sleep behind the same call edge when the caller's held
    lock is the one the wait releases."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "import time\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "    def caller(self):\n"
        "        with self._cv:\n"
        "            self._helper()\n"
        "    def _helper(self):\n"
        "        self._cv.wait()\n"
        "        time.sleep(1)\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["BLK"]
    assert "time.sleep" in findings[0].message


def test_blk_lock_shadow_param_not_module_lock(tmp_path):
    """Review regression: a parameter/local shadowing a registered
    lock's name is NOT the module lock -- blocking under it must not be
    misattributed (which would also fabricate LCK order edges)."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "import time\n"
        "_LOCK = threading.Lock()\n"
        "def f(_LOCK):\n"
        "    with _LOCK:\n"
        "        time.sleep(0.1)\n")
    assert core.lint_paths([str(p)], doc=False) == []


def test_tsi_for_and_with_as_targets_recorded(tmp_path):
    """Review regression: `for self.cur in ...:` and
    `with open() as self.fh:` write the attribute like any assignment
    -- two-root spellings of either must fire."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class W:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._w1).start()\n"
        "        threading.Thread(target=self._w2).start()\n"
        "    def _w1(self):\n"
        "        for self.cur in range(3):\n"
        "            pass\n"
        "        with open('/dev/null') as self.fh:\n"
        "            pass\n"
        "    def _w2(self):\n"
        "        for self.cur in range(3):\n"
        "            pass\n"
        "        with open('/dev/null') as self.fh:\n"
        "            pass\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["TSI", "TSI"]
    msgs = " ".join(f.message for f in findings)
    assert ".cur`" in msgs and ".fh`" in msgs


def test_blk_from_import_spelling_caught(tmp_path):
    """Review regression: `from time import sleep` / import aliases
    resolve to the canonical blocking spelling -- an import-style
    refactor must not disarm the rule."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "from time import sleep\n"
        "import subprocess as sp\n"
        "_LOCK = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK:\n"
        "        sleep(1)\n"
        "def g():\n"
        "    with _LOCK:\n"
        "        sp.run(['true'])\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["BLK", "BLK"]
    assert [f.line for f in findings] == [7, 10]


def test_tsi_call_binding_target_does_not_root_the_callee(tmp_path):
    """Review regression: in `t = pick(worker_a, worker_b);
    Thread(target=t)` the candidates are the ARGUMENTS -- `pick` runs
    synchronously on the spawning thread and must not become a root
    (its writes would inflate root counts and pollute the inventory)."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_G = 0\n"
        "def pick(a, b):\n"
        "    global _G\n"
        "    _G = 1\n"           # synchronous write: no reaching root
        "    return a\n"
        "def spawn():\n"
        "    t = pick(worker_a, worker_b)\n"
        "    threading.Thread(target=t).start()\n"
        "def worker_a():\n"
        "    global _G\n"
        "    _G = 2\n"           # ONE root writes _G: no finding
        "def worker_b():\n"
        "    pass\n")
    assert core.lint_paths([str(p)], doc=False) == []


def test_blk_through_nested_def_called_under_lock(tmp_path):
    """A nested def invoked SYNCHRONOUSLY while the lock is held blocks
    under the lock like any helper: the intra-module nested-label call
    edge carries the witness chain (nested defs are separate records,
    not folds, since the thread-root rework)."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "import time\n"
        "_LOCK = threading.Lock()\n"
        "def outer():\n"
        "    def slow():\n"
        "        time.sleep(0.1)\n"
        "    with _LOCK:\n"
        "        slow()\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["BLK"]
    assert "outer -> outer.slow -> `time.sleep`" in findings[0].message


def test_tsi_thread_spawned_in_loop_else_not_multi_instance(tmp_path):
    """Review regression: a for/while `else` block runs exactly once,
    after the loop -- a thread spawned there is single-instance and its
    private writes stay legal."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_G = 0\n"
        "def spawn(items):\n"
        "    for it in items:\n"
        "        pass\n"
        "    else:\n"
        "        threading.Thread(target=worker).start()\n"
        "def worker():\n"
        "    global _G\n"
        "    _G = 1\n")
    assert core.lint_paths([str(p)], doc=False) == []


def test_tsi_tuple_unpacking_write_caught(tmp_path):
    """Review regression: `self.a, self.b = ...` writes both attributes
    -- the unpacking spelling must not reopen the hole the rule
    closes."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class W:\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._w1).start()\n"
        "        threading.Thread(target=self._w2).start()\n"
        "    def _w1(self):\n"
        "        self.a, self.b = 1, 2\n"
        "    def _w2(self):\n"
        "        self.a, self.b = 3, 4\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["TSI", "TSI"]
    msgs = " ".join(f.message for f in findings)
    assert ".a`" in msgs and ".b`" in msgs


def test_tsi_nonanchor_escape_suppression_carried(tmp_path):
    """Review regression: a tsi-ok on a NON-anchor write line suppresses
    the finding -- and the (finding, reason) pair still reaches the
    report's suppressed surface (SARIF must audit the escape, not watch
    the finding vanish)."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_G = 0\n"
        "def spawn():\n"
        "    threading.Thread(target=w1).start()\n"
        "    threading.Thread(target=w2).start()\n"
        "def w1():\n"
        "    global _G\n"
        "    _G = 1\n"
        "def w2():\n"
        "    global _G\n"
        "    # spgemm-lint: tsi-ok(seeded: non-anchor escape)\n"
        "    _G = 2\n")
    report = core.lint_run([str(p)], doc=False)
    assert report.findings == []
    pairs = [(f, reason) for f, reason in report.suppressed
             if f.rule == "TSI"]
    assert len(pairs) == 1
    assert "non-anchor escape" in pairs[0][1]
    # the escape is inventoried in use, not stale
    tsi = [s for s in report.suppressions if s.rule == "TSI"]
    assert len(tsi) == 1 and not tsi[0].stale


def test_blk_sibling_nested_def_call_resolves(tmp_path):
    """Review regression: a nested def calling its SIBLING nested def
    resolves by ascending through enclosing function scopes -- a
    blocking op behind that hop while the lock is held is still a
    finding (the OOC stager/lander helper shape)."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "import time\n"
        "_LOCK = threading.Lock()\n"
        "def outer():\n"
        "    def a():\n"
        "        b()\n"
        "    def b():\n"
        "        time.sleep(1)\n"
        "    with _LOCK:\n"
        "        a()\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["BLK"]
    assert "outer -> outer.a -> outer.b -> `time.sleep`" \
        in findings[0].message


def test_nested_name_never_resolves_to_sibling_method(tmp_path):
    """The ascent stops at function scopes: a bare call inside a method
    must not resolve to a sibling METHOD of the class (Python name
    resolution would not either)."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "import time\n"
        "_LOCK = threading.Lock()\n"
        "class W:\n"
        "    def m(self):\n"
        "        with _LOCK:\n"
        "            sleeper()\n"      # NOT W.sleeper: no finding
        "    def sleeper(self):\n"
        "        time.sleep(1)\n")
    assert core.lint_paths([str(p)], doc=False) == []


def test_blk_escape_on_unreached_op_goes_stale(tmp_path):
    """Review regression: a blk-ok on a blocking op that is never
    reached with a lock held (e.g. the hazard was fixed by hoisting but
    the escape was forgotten) suppresses nothing -- SUP must report it
    stale, not let the dead justification outlive the code."""
    p = tmp_path / "h.py"
    p.write_text(
        "import time\n"
        "def poll():\n"
        "    # spgemm-lint: blk-ok(left behind after the hoist)\n"
        "    time.sleep(0.1)\n")
    findings, suppressions = core.lint_report([str(p)], doc=False)
    assert [f.rule for f in findings] == ["SUP"]
    assert len(suppressions) == 1 and suppressions[0].stale


def test_blk_source_escapes_on_lock_held_routes_stay_used(tmp_path):
    """The counterpart: source blk-oks whose ops ARE reached under a
    lock are in use -- including a SECOND escaped route behind the first
    (the failpoints delay+hang shape), which a single-witness summary
    would miss."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "import time\n"
        "_LOCK = threading.Lock()\n"
        "def entry():\n"
        "    with _LOCK:\n"
        "        helper()\n"
        "def helper():\n"
        "    # spgemm-lint: blk-ok(seeded: bounded injected delay)\n"
        "    time.sleep(0.1)\n"
        "    deeper()\n"
        "def deeper():\n"
        "    # spgemm-lint: blk-ok(seeded: the second escaped route)\n"
        "    time.sleep(0.2)\n")
    report = core.lint_run([str(p)], doc=False)
    assert report.findings == []  # both routes escaped at source
    assert len(report.suppressions) == 2
    assert not any(s.stale for s in report.suppressions)
    # the transitively-suppressed call-site finding still reaches the
    # SARIF suppressions surface, reason attached from the source escape
    pairs = [(f, r) for f, r in report.suppressed if f.rule == "BLK"]
    assert len(pairs) == 1
    assert "bounded injected delay" in pairs[0][1]


def test_tsi_threading_local_writes_exempt(tmp_path):
    """threading.local() is per-thread by construction: writes through
    a registered local (the flight recorder's span stack) are not
    shared state."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class R:\n"
        "    def __init__(self):\n"
        "        self._tls = threading.local()\n"
        "        threading.Thread(target=self._w1).start()\n"
        "        threading.Thread(target=self._w2).start()\n"
        "    def _w1(self):\n"
        "        self._tls.stack = [1]\n"
        "    def _w2(self):\n"
        "        self._tls.stack = [2]\n")
    assert core.lint_paths([str(p)], doc=False) == []


def test_tsi_module_singleton_attr_write_caught(tmp_path):
    """Review regression: `STATE.flag = ...` mutates the module-level
    singleton exactly like `STATE['k'] = ...` -- attribute spelling must
    not be invisible to TSI."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class Holder:\n"
        "    pass\n"
        "STATE = Holder()\n"
        "def spawn():\n"
        "    threading.Thread(target=w1).start()\n"
        "    threading.Thread(target=w2).start()\n"
        "def w1():\n"
        "    STATE.flag = True\n"
        "def w2():\n"
        "    STATE.flag = False\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["TSI"]
    assert "STATE" in findings[0].message


def test_cache_malformed_entry_falls_back_cold(tmp_path):
    """A structurally malformed (but valid-JSON) cache entry is a
    counted invalidation and a cold re-run, never a crash -- the
    best-effort contract."""
    d = tmp_path / "c"
    d.mkdir()
    (d / "cache.json").write_text(json.dumps({"files": {
        "a.py": "not-a-dict",
        "b.py": {"sha": "s", "version": core._analysis_signature()},
    }}))
    cache = core.LintCache(str(d))
    assert cache.get("a.py", "s") is None   # non-dict entry
    assert cache.get("b.py", "s") is None   # missing findings/raw keys
    assert cache.invalidations == 2 and cache.hits == 0


def test_tsi_nested_def_thread_in_init_not_exempt(tmp_path):
    """The review regression verbatim: a closure defined in __init__ and
    passed to Thread(target=...) runs AFTER publication -- its writes
    must not inherit __init__'s happens-before exemption, and with a
    second root writing the same attr the race is a finding."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        def warm():\n"
        "            self.state = 'warm'\n"
        "        threading.Thread(target=warm).start()\n"
        "        threading.Thread(target=self._serve).start()\n"
        "    def _serve(self):\n"
        "        self.state = 'serving'\n")
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["TSI"]
    assert "state" in findings[0].message
    assert "__init__.warm" in findings[0].message


# ------------------------------------------------------------- TSI rule --
def test_tsi_fixture_each_violation_caught():
    """The seeded thread-shared fixture: an attribute written from both
    Thread targets, a module global written by a nested-def root spawned
    from two sites, and an attribute written by a loop-spawned
    multi-instance root are findings; annotated state, __init__ writes,
    single-root writes, and the reasoned tsi-ok handoff slot stay
    legal."""
    findings, suppressions = core.lint_report(
        [os.path.join(FIXTURES, "badshared.py")], doc=False)
    assert [f.rule for f in findings] == ["TSI"] * 3
    by_line = {f.line: f.message for f in findings}
    nested = _fixture_lines("badshared.py", "nested-def root")[0]
    first = _fixture_lines("badshared.py", "TSI: two-root write")[0]
    second = _fixture_lines("badshared.py", "the second root's write")[0]
    multi = _fixture_lines("badshared.py", "multi-instance root")[0]
    assert set(by_line) == {nested, first, multi}
    two_root = by_line[first]
    assert "2 thread roots" in two_root
    assert "Worker._loop_a" in two_root and "Worker._loop_b" in two_root
    assert f"badshared.py:{first}" in two_root  # every write site named
    assert f"badshared.py:{second}" in two_root
    # the nested-def target resolves as a root in its own right, and its
    # two spawn sites make it multi-instance by themselves
    assert "spawn_workers.worker" in by_line[nested]
    assert "multi-instance" in by_line[nested]
    # one loop-spawned target = many threads: one root suffices
    assert "ConnServer._handle" in by_line[multi]
    assert "multi-instance" in by_line[multi]
    for needle in ("legal: annotated", "happens-before publication",
                   "legal: reached from one root", "self.beat = 1.0",
                   "self.beat = 2.0"):
        assert _fixture_lines("badshared.py", needle)[0] not in by_line
    # both tsi-ok escapes on the beat slot are inventoried, in use
    tsi = [s for s in suppressions if s.rule == "TSI"]
    assert len(tsi) == 2 and not any(s.stale for s in tsi)


def test_tsi_single_spawn_single_root_stays_quiet(tmp_path):
    """The precision boundary: ONE thread spawned once on one target
    writing its own private state is not a race -- no finding (the
    multi-instance weighting fires only on loop spawns and multi-site
    spawns)."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        threading.Thread(target=self._work).start()\n"
        "    def _work(self):\n"
        "        self.n = 1\n")
    assert core.lint_paths([str(p)], doc=False) == []


def test_tsi_loop_variable_target_not_multi_instance(tmp_path):
    """The daemon's for-over-(target, name)-tuples start() spelling
    spawns each bound function ONCE: a loop whose iteration rebinds the
    target must not mark those roots multi-instance (each root's private
    writes stay legal); two DISTINCT roots writing one attr still
    fire."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "class W:\n"
        "    def start(self):\n"
        "        for target in (self._a, self._b):\n"
        "            threading.Thread(target=target).start()\n"
        "    def _a(self):\n"
        "        self.a_private = 1\n"   # one root: legal
        "        self.shared = 1\n"      # two roots: finding
        "    def _b(self):\n"
        "        self.b_private = 1\n"   # one root: legal
        "        self.shared = 2\n")     # the second root's write
    findings = core.lint_paths([str(p)], doc=False)
    assert [f.rule for f in findings] == ["TSI"]
    assert "shared" in findings[0].message
    assert "multi-instance" not in findings[0].message


# ------------------------------- v3 guard-deletion spot-checks (live copies) --
def test_lck_escape_deletion_turns_lint_red(tmp_path):
    """Acceptance spot-check on a FIXTURE COPY of serve/daemon.py: the
    one live lock-order hazard (the recovery probe re-entering
    _spawn_executor under self._lock) is held green only by its reasoned
    lck-ok escape -- deleting the escape (equivalently, reordering the
    call away from it) must produce the LCK self-deadlock finding, so
    the analysis provably binds to the live module."""
    src = open(os.path.join(REPO, "spgemm_tpu", "serve",
                            "daemon.py")).read()
    p = tmp_path / "daemon.py"
    p.write_text(src)
    assert core.lint_paths([str(p)], doc=False) == []
    kept = [ln for ln in src.splitlines()
            if "spgemm-lint: lck-ok(" not in ln]
    assert len(kept) == len(src.splitlines()) - 1, \
        "the lck-ok escape drifted in serve/daemon.py"
    p.write_text("\n".join(kept) + "\n")
    lck = [f for f in core.lint_paths([str(p)], doc=False)
           if f.rule == "LCK"]
    assert lck, "deleting the lck-ok escape must turn lint red"
    assert "re-acquired while already held" in lck[0].message
    assert "_spawn_executor" in lck[0].message


def test_blk_sleep_under_lock_turns_lint_red(tmp_path):
    """Acceptance spot-check on a FIXTURE COPY of ops/warmstore.py: the
    copy lints clean (its real flock/sleep sites carry reasoned blk-ok
    escapes), and adding one time.sleep inside a `with _LOCK:` block
    must produce a BLK finding."""
    src = open(os.path.join(REPO, "spgemm_tpu", "ops",
                            "warmstore.py")).read()
    p = tmp_path / "warmstore.py"
    p.write_text(src)
    assert core.lint_paths([str(p)], doc=False) == []
    guarded = ("def directory() -> str | None:\n"
               "    with _LOCK:\n        return _DIR")
    mutated = src.replace(
        guarded, "def directory() -> str | None:\n    with _LOCK:\n"
                 "        time.sleep(0.2)\n        return _DIR")
    assert mutated != src, "anchor drifted in ops/warmstore.py"
    p.write_text(mutated)
    blk = [f for f in core.lint_paths([str(p)], doc=False)
           if f.rule == "BLK"]
    assert blk, "a sleep under _LOCK in warmstore must turn lint red"
    assert "time.sleep" in blk[0].message and "_LOCK" in blk[0].message


def test_tsi_guard_strip_turns_lint_red(tmp_path):
    """Acceptance spot-check on a FIXTURE COPY of serve/daemon.py:
    stripping the guarded-by annotation from Daemon.degraded -- written
    from the watchdog and recovery-probe thread roots -- must produce a
    TSI finding (the THR opt-in hole stays closed on the live module)."""
    src = open(os.path.join(REPO, "spgemm_tpu", "serve",
                            "daemon.py")).read()
    annotated = ("self.degraded = False                    "
                 "# spgemm-lint: guarded-by(_lock)")
    mutated = src.replace(annotated, "self.degraded = False")
    assert mutated != src, "annotation anchor drifted in serve/daemon.py"
    p = tmp_path / "daemon.py"
    p.write_text(mutated)
    tsi = [f for f in core.lint_paths([str(p)], doc=False)
           if f.rule == "TSI"]
    assert tsi, \
        "stripping guarded-by from a two-root attribute must turn lint red"
    assert "degraded" in tsi[0].message
    assert "thread roots" in tsi[0].message
    assert "guarded-by" in tsi[0].message


# ------------------------------------------------------------- EXC rule --
def test_exc_fixture_each_violation_caught():
    """Naked broad catch, swallowing bare except, swallowing BaseException
    -- and the legal shapes: BLE001-with-reason, re-raising handler,
    reasoned exc-ok escape."""
    findings = lint_file(os.path.join(FIXTURES, "badexcept.py"))
    exc = [f for f in findings if f.rule == "EXC"]
    assert len(exc) == 3 and findings == exc
    flagged = [f.line for f in exc]
    for needle in ("no BLE001 justification", "bare except that swallows",
                   "would swallow JobAbandoned"):
        assert _fixture_lines("badexcept.py", needle)[0] in flagged
    legal = (_fixture_lines("badexcept.py", "noqa: BLE001")
             + _fixture_lines("badexcept.py", "re-raises"))
    assert legal and not set(legal) & set(flagged)


def test_exc_ble_reason_must_be_nonempty(tmp_path):
    """A bare `# noqa: BLE001` (no `-- reason`) does not justify the broad
    catch -- the reason is the reviewable citation."""
    p = tmp_path / "h.py"
    p.write_text("def f():\n"
                 "    try:\n"
                 "        pass\n"
                 "    except Exception:  # noqa: BLE001\n"
                 "        pass\n")
    assert [f.rule for f in lint_file(str(p))] == ["EXC"]


def test_exc_base_reraise_must_be_terminal(tmp_path):
    """A conditional re-raise does not satisfy the provably-re-raise
    contract: the handler body must END in `raise`."""
    p = tmp_path / "h.py"
    p.write_text("def f(flag):\n"
                 "    try:\n"
                 "        pass\n"
                 "    except BaseException:\n"
                 "        if flag:\n"
                 "            raise\n"
                 "        return None\n")
    assert [f.rule for f in lint_file(str(p))] == ["EXC"]


# ------------------------------------------- interprocedural FLD (taint) --
def test_interprocedural_fld_one_hop_outside_numeric():
    """The acceptance case: a numeric module calling a helper in a
    NON-numeric module whose body performs the unordered reduction is
    flagged at the call site, one and two hops deep, with the witness
    chain down to the reduction's file:line in the message."""
    findings = core.lint_paths([os.path.join(FIXTURES, "callchain")],
                               doc=False)
    fld = [f for f in findings if f.rule == "FLD"]
    assert len(fld) == 2 and findings == fld
    assert all(f.file.endswith("callchain/ops/spgemm.py") for f in fld)
    by_msg = {f.line: f.message for f in fld}
    src = open(os.path.join(FIXTURES, "callchain", "ops",
                            "spgemm.py")).read()
    one = next(i for i, ln in enumerate(src.splitlines(), 1)
               if "one call-hop" in ln)
    two = next(i for i, ln in enumerate(src.splitlines(), 1)
               if "two call-hops" in ln)
    assert set(by_msg) == {one, two}
    assert "hidden_sum -> `jnp.sum`" in by_msg[one]
    assert "hosthelper.py:" in by_msg[one]
    assert "outer -> inner -> `jnp.sum`" in by_msg[two]
    assert "hostdeep.py:" in by_msg[two]
    # the call-site escape and the source-proved helper stay clean
    escaped = next(i for i, ln in enumerate(src.splitlines(), 1)
                   if "call-site escape" in ln)
    proved = next(i for i, ln in enumerate(src.splitlines(), 1)
                  if "proves its sum at source" in ln)
    assert not {escaped, escaped + 1, proved} & set(by_msg)


def test_interprocedural_fld_same_module_helper_still_flagged(tmp_path):
    """Module-scoped evasion INSIDE numeric code never existed (check_fld
    sees the whole module); the taint pass must not double-report it."""
    p = tmp_path / "ops" / "spgemm.py"
    p.parent.mkdir()
    p.write_text("import jax.numpy as jnp\n"
                 "def helper(x):\n"
                 "    return jnp.sum(x)\n"
                 "def entry(x):\n"
                 "    return helper(x)\n")
    findings = core.lint_paths([str(tmp_path)], doc=False)
    # exactly one finding: the direct reduction (per-module FLD); the
    # same-module call edge is not re-reported by the taint pass
    assert [f.rule for f in findings] == ["FLD"]
    assert findings[0].line == 3


def test_interprocedural_fld_import_alias_resolves(tmp_path):
    """`import helpers as h; h.f(...)` resolves through the alias."""
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "u64.py").write_text(
        "import myhelpers as h\n"
        "def entry(x):\n"
        "    return h.hidden(x)\n")
    (tmp_path / "myhelpers.py").write_text(
        "import jax.numpy as jnp\n"
        "def hidden(x):\n"
        "    return jnp.sum(x)\n")
    findings = core.lint_paths([str(tmp_path)], doc=False)
    assert [f.rule for f in findings] == ["FLD"]
    assert findings[0].file.endswith("ops/u64.py") and findings[0].line == 3


# --------------------------------------------------- suppression audit --
def test_stale_suppressions_reported():
    """An escape comment on a line that no longer produces the underlying
    finding is itself a finding (SUP), for every escape family -- the
    three v2 spellings AND the v3 concurrency ones (lck-ok / blk-ok /
    tsi-ok), all in the one inventory."""
    findings, suppressions = core.lint_report(
        [os.path.join(FIXTURES, "stalesup.py")], doc=False)
    assert [f.rule for f in findings] == ["SUP"] * 7
    assert {s.rule for s in suppressions} == {"FLD", "THR", "EXC",
                                              "LCK", "BLK", "TSI", "DRF"}
    assert all(s.stale for s in suppressions)
    assert all("seeded-stale" in s.reason for s in suppressions)
    assert [f.line for f in findings] == [s.line for s in sorted(
        suppressions, key=lambda s: s.line)]


def test_fld_proof_on_clean_numeric_line_is_stale(tmp_path):
    """The acceptance case verbatim: a fld-proof(...) comment on a clean
    line IN A NUMERIC MODULE is reported as stale."""
    p = tmp_path / "ops" / "u64.py"
    p.parent.mkdir()
    p.write_text("def f(x):\n"
                 "    # spgemm-lint: fld-proof(left over from a refactor)\n"
                 "    return x + 1\n")
    findings, suppressions = core.lint_report([str(p)], doc=False)
    assert [f.rule for f in findings] == ["SUP"]
    assert "suppresses nothing" in findings[0].message
    assert len(suppressions) == 1 and suppressions[0].stale


def test_used_suppressions_inventoried_not_stale():
    """Escapes that DO suppress something appear in the inventory with
    stale=false and produce no SUP finding -- incl. interprocedural
    call-site escapes and taint-suppressing source escapes."""
    findings, suppressions = core.lint_report(
        [os.path.join(FIXTURES, "callchain")], doc=False)
    assert [f.rule for f in findings] == ["FLD", "FLD"]
    assert len(suppressions) == 2  # call-site escape + source escape
    assert not any(s.stale for s in suppressions)


# ------------------------------------------------- JSON report contract --
def test_json_report_fixture_run():
    """The machine-readable report: every rule family present with the
    correct rule ID, (file, line, rule, message) per finding, the full
    suppression inventory, the cache block, exit 1."""
    rc = _run(["-m", "spgemm_tpu.analysis", "--json", "--no-cache",
               FIXTURES, "--claude-md", FIXTURE_CLAUDE])
    assert rc.returncode == 1, rc.stderr[-2000:]
    report = json.loads(rc.stdout)
    assert report["clean"] is False
    # badknob: 3 classic + 2 planner-knob + 4 serve-knob + 3
    # estimator-knob + 2 delta-knob + 2 obs-events-knob + 3 warm-knob
    # + 2 batch-knob
    # reads; badbackend: 3 import-time touches; badplanner: 2
    # @host_only-body touches; FLD: 5 per-module + 2 interprocedural
    # (callchain) + 1 ops/estimate + 1 ops/delta numeric-scope;
    # badthread/badexcept: 3 each; badlockorder: cycle + self-edge;
    # badblocking: direct + transitive + typed-queue; badshared:
    # two-root write + nested-def two-site root + loop-spawned
    # multi-instance root; stalesup: one stale escape per family (7);
    # badmetric: undeclared phase + undeclared counter + computed name
    # + 2 deep-profiling + 2 warm-layer + 1 batch-layer + 2 dense-route
    # near-misses; badfailpoint: 2
    # undeclared + 1 computed (the stale-registry direction stays quiet
    # -- the registry module is not in the fixture unit set);
    # badproto: 2 undeclared-for-op fields + 1 undeclared submit dict
    # key + unknown op + hardcoded version + 2 undeclared codes +
    # 1 undeclared union-context response field + 1 undeclared E_*
    # constant; badevent: 2 undeclared kinds + 1 computed kind;
    # DRF stays quiet like FPT's registry direction (no registry module
    # in the fixture unit set -- staledrift.py alone yields nothing)
    assert report["counts"] == {"FLD": 9, "KNB": 25, "BKD": 5, "THR": 3,
                                "LCK": 2, "BLK": 3, "TSI": 3,
                                "EXC": 3, "MET": 10, "FPT": 3,
                                "PRO": 9, "EVT": 3, "DRF": 0, "DOC": 1,
                                "SUP": 7, "PARSE": 0}
    assert set(report["counts"]) == set(core.RULES)
    for f in report["findings"]:
        assert set(f) == {"file", "line", "rule", "message"}
        assert f["rule"] in core.RULES
        assert isinstance(f["line"], int) and f["line"] >= 1
    # the suppression inventory: every escape comment in the run, with
    # the seven stalesup.py seeds marked stale
    sup = report["suppressions"]
    assert all(set(s) == {"file", "line", "rule", "reason", "stale"}
               for s in sup)
    assert sum(s["stale"] for s in sup) == 7
    assert all(s["file"].endswith("stalesup.py")
               for s in sup if s["stale"])
    # 7 stale + thr-ok + exc-ok + 3 fld escapes + blk-ok (badblocking)
    # + 2 tsi-ok (badshared) in use
    assert len(sup) == 15
    # --no-cache: the cache block reports disabled, nothing else
    assert report["cache"] == {"enabled": False}


def test_json_report_clean_repo_run_cold_then_warm(tmp_path):
    """`make lint` contract, cold AND warm: the default run exits 0 with
    a clean report (and never needs a backend -- the linter is jax-free
    by design), the repo's own escape inventory rides along all in use
    -- including the reasoned lck-ok/blk-ok escapes the concurrency pass
    surfaced -- and a second run on the unchanged tree is served from
    the content-hash cache: hits > 0, zero misses, byte-identical
    output.  Timing-assertion-free by design: the hit/miss figures, not
    the wall clock, are the contract."""
    args = ["-m", "spgemm_tpu.analysis", "--json",
            "--cache-dir", str(tmp_path / "cache")]
    rc = _run(args)
    assert rc.returncode == 0, rc.stdout + rc.stderr[-2000:]
    cold = json.loads(rc.stdout)
    assert cold["clean"] is True and cold["findings"] == []
    assert not any(s["stale"] for s in cold["suppressions"])
    rules_in_use = {s["rule"] for s in cold["suppressions"]}
    assert {"LCK", "BLK"} <= rules_in_use
    cache = cold["cache"]
    assert cache["enabled"] is True
    assert cache["hits"] == 0 and cache["invalidations"] == 0
    assert cache["misses"] > 0  # a fresh cache dir: every unit is cold
    rc2 = _run(args)
    assert rc2.returncode == 0, rc2.stdout + rc2.stderr[-2000:]
    warm = json.loads(rc2.stdout)
    assert warm["cache"]["hits"] == cache["misses"]
    assert warm["cache"]["misses"] == 0
    assert warm["cache"]["invalidations"] == 0
    for key in ("findings", "counts", "suppressions", "clean"):
        assert warm[key] == cold[key]


# ---------------------------------------------- content-hash result cache --
def test_cache_warm_fixture_run_byte_identical(tmp_path):
    """The fixture tree (a run WITH findings) twice through one cache
    dir: the cold run misses every unit, the warm run hits every unit
    and re-runs none -- with byte-identical findings, counts, and
    suppressions either way (cached per-file results feed the
    whole-program passes exactly like live ones)."""
    args = ["-m", "spgemm_tpu.analysis", "--json",
            "--cache-dir", str(tmp_path / "cache"), FIXTURES,
            "--claude-md", FIXTURE_CLAUDE]
    cold = json.loads(_run(args).stdout)
    warm = json.loads(_run(args).stdout)
    assert cold["cache"]["hits"] == 0 and cold["cache"]["misses"] > 0
    assert warm["cache"]["hits"] == cold["cache"]["misses"]
    assert warm["cache"]["misses"] == 0
    assert warm["cache"]["invalidations"] == 0
    for key in ("findings", "counts", "suppressions", "clean"):
        assert warm[key] == cold[key]


def test_cache_invalidates_on_edit(tmp_path):
    """Editing one file invalidates exactly that entry (counted as an
    invalidation, not a miss); untouched files still hit."""
    tree = tmp_path / "tree"
    tree.mkdir()
    (tree / "a.py").write_text("def f():\n    return 1\n")
    (tree / "b.py").write_text("def g():\n    return 2\n")
    cdir = str(tmp_path / "cache")
    args = ["-m", "spgemm_tpu.analysis", "--json", "--cache-dir", cdir,
            str(tree)]
    r1 = json.loads(_run(args).stdout)
    assert r1["cache"] == {"enabled": True, "dir": cdir, "hits": 0,
                           "misses": 2, "invalidations": 0}
    (tree / "a.py").write_text("def f():\n    return 3\n")
    r2 = json.loads(_run(args).stdout)
    assert r2["cache"]["hits"] == 1
    assert r2["cache"]["invalidations"] == 1
    assert r2["cache"]["misses"] == 0


def test_cache_signature_covers_rule_registries():
    """The cached per-file rules validate against obs/metrics.py (MET),
    utils/failpoints.py (FPT), serve/protocol.py (PRO), and
    obs/events.py (EVT): all four must feed the linter-version
    signature, or a registry edit would replay stale cached results
    while the call sites' files are untouched."""
    assert set(core._SIGNATURE_EXTRAS) == {"obs/metrics.py",
                                           "utils/failpoints.py",
                                           "serve/protocol.py",
                                           "obs/events.py"}
    for rel in core._SIGNATURE_EXTRAS:
        assert os.path.exists(os.path.join(REPO, "spgemm_tpu", rel))


def test_cache_prunes_dead_entries(tmp_path):
    """Entries for files renamed or deleted out of the scope are dropped
    on prune (default-scope runs call it), so cache.json cannot grow
    without bound."""
    d = str(tmp_path / "c")
    cache = core.LintCache(d)
    cache.put("a.py", "sha", [], set(), [])
    cache.put("gone.py", "sha", [], set(), [])
    cache.save()
    c2 = core.LintCache(d)
    c2.prune({"a.py"})
    c2.save()
    c3 = core.LintCache(d)
    assert c3.get("a.py", "sha") is not None
    assert c3.get("gone.py", "sha") is None and c3.misses == 1


def test_cache_keyed_on_analysis_package_content():
    """The linter-version half of the key is the analysis package's own
    content hash: ANY rule change invalidates every entry -- there is no
    version constant to forget to bump."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        cache = core.LintCache(d)
        cache.put("x.py", "sha-of-x", [], set(), [])
        cache.save()
        fresh = core.LintCache(d)
        assert fresh.get("x.py", "sha-of-x") is not None
        assert fresh.hits == 1
        skewed = core.LintCache(d)
        skewed.signature = "not-the-analysis-package-hash"
        assert skewed.get("x.py", "sha-of-x") is None
        assert skewed.invalidations == 1
        # and a content change on the file side invalidates too
        assert fresh.get("x.py", "different-bytes") is None
        assert fresh.invalidations == 1


# ------------------------------------------------------ SARIF emission --
def test_sarif_output_schema_shape(tmp_path):
    """`--sarif F` (make lint-sarif) writes a SARIF 2.1.0 log: version +
    $schema, one run, the full rule registry as tool.driver.rules, one
    result per finding with ruleId/message/physicalLocation."""
    out = tmp_path / "lint.sarif"
    rc = _run(["-m", "spgemm_tpu.analysis", "--sarif", str(out),
               os.path.join(FIXTURES, "badthread.py"),
               os.path.join(FIXTURES, "badexcept.py")])
    assert rc.returncode == 1
    log = json.loads(out.read_text())
    assert log["version"] == "2.1.0"
    assert log["$schema"].endswith("sarif-2.1.0.json")
    assert len(log["runs"]) == 1
    run = log["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "spgemm-lint"
    assert [r["id"] for r in driver["rules"]] == list(core.RULES)
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    # 3 THR + 3 EXC active, plus the fixtures' two escaped findings
    # (thr-ok + exc-ok) carried as results with SARIF suppressions
    assert len(run["results"]) == 8
    for res in run["results"]:
        assert res["ruleId"] in core.RULES
        assert res["level"] == "error"
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1
    active = [r for r in run["results"] if not r["suppressions"]]
    escaped = [r for r in run["results"] if r["suppressions"]]
    assert len(active) == 6 and len(escaped) == 2
    # an active finding carries the explicit empty array (SARIF's "not
    # suppressed", distinct from "suppression state unknown")
    assert all(r["suppressions"] == [] for r in active)
    for res in escaped:
        (sup,) = res["suppressions"]
        assert sup["kind"] == "inSource"
        assert sup["justification"]  # the escape reason, auditable
    assert {r["ruleId"] for r in escaped} == {"THR", "EXC"}


def test_sarif_clean_run_empty_results(tmp_path):
    out = tmp_path / "lint.sarif"
    rc = _run(["-m", "spgemm_tpu.analysis", "--sarif", str(out),
               os.path.join(REPO, "spgemm_tpu", "utils", "timers.py")])
    assert rc.returncode == 0
    log = json.loads(out.read_text())
    assert log["runs"][0]["results"] == []


# -------------------------------------------- environment independence --
def test_analysis_import_is_jax_free():
    """The linter must never hang on a dead TPU: importing the analysis
    package AND running the full default self-lint (incl. the DOC checks,
    which import the CLI) pulls in no jax/jaxlib module."""
    code = (
        "import sys\n"
        "import spgemm_tpu.analysis\n"
        "from spgemm_tpu.analysis import callgraph, core, excrules, "
        "lockrules, sarif, thrrules\n"
        "core.lint_repo()\n"
        "bad = [m for m in sys.modules\n"
        "       if m == 'jax' or m.startswith(('jax.', 'jaxlib'))]\n"
        "assert not bad, f'linter pulled in jax: {bad}'\n")
    rc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stderr[-2000:]


def test_linter_reads_no_engine_env(monkeypatch):
    """Lint results are environment-independent (CI-cacheable): a full
    default run reads zero SPGEMM_TPU_* variables -- the knob table and
    CLI epilog render from registry metadata, not live values."""
    real = os.environ
    reads: list[str] = []

    class Tracker:
        def get(self, key, default=None):
            reads.append(key)
            return real.get(key, default)

        def __getitem__(self, key):
            reads.append(key)
            return real[key]

        def __contains__(self, key):
            reads.append(key)
            return key in real

        def __setitem__(self, key, value):  # pytest writes its own vars
            real[key] = value

        def __delitem__(self, key):
            del real[key]

        def __iter__(self):
            return iter(dict(real))

        def keys(self):
            return real.keys()

        def items(self):
            return real.items()

        def copy(self):
            return real.copy()

    monkeypatch.setattr(os, "environ", Tracker())
    findings = core.lint_paths(core.default_paths(),
                               claude_md=os.path.join(REPO, "CLAUDE.md"))
    assert findings == []
    engine_reads = [k for k in reads if k.startswith("SPGEMM_TPU_")]
    assert engine_reads == [], engine_reads


def test_analysis_help_covers_every_rule_id():
    """The DOC half for the linter's own help: the epilog (generated from
    core.RULES) names every rule id."""
    assert docrules.check_analysis_help() == []
    from spgemm_tpu.analysis.__main__ import build_parser
    help_text = build_parser().format_help()
    for rule in core.RULES:
        assert rule in help_text


# ------------------------------------------- review-hardening regressions --
def test_interprocedural_fld_taint_survives_call_cycle(tmp_path):
    """Regression: memoizing the in-progress None used to cut cycles
    finalized an ancestor as clean when its only route to a reduction ran
    through the cycle -- the call site a -> b -> d -> jnp.sum was silently
    missed whenever b's back-edge to a was visited first."""
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "u64.py").write_text(
        "import helpa\n"
        "def entry(x):\n"
        "    return helpa.a_fn(x)\n")
    (tmp_path / "helpa.py").write_text(
        "import helpb\n"
        "def a_fn(x):\n"
        "    return helpb.b_fn(x)\n")
    (tmp_path / "helpb.py").write_text(
        "import helpa\n"
        "import helpd\n"
        "def b_fn(x):\n"
        "    helpa.a_fn(x)\n"          # cycle edge, visited first
        "    return helpd.d_fn(x)\n")  # the route to the reduction
    (tmp_path / "helpd.py").write_text(
        "import jax.numpy as jnp\n"
        "def d_fn(x):\n"
        "    return jnp.sum(x)\n")
    findings = core.lint_paths([str(tmp_path)], doc=False)
    assert [f.rule for f in findings] == ["FLD"]
    assert findings[0].file.endswith("ops/u64.py")
    assert "a_fn -> b_fn -> d_fn -> `jnp.sum`" in findings[0].message


def test_thr_local_shadow_of_guarded_global_not_flagged(tmp_path):
    """Regression: a plain local that shadows a guarded module global is
    the LOCAL on every use (no `global` declaration), so THR must not
    fire on it -- while `global X` rebinding stays checked, including
    from a nested def closing over the shadowing scope."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_CACHE = {}  # spgemm-lint: guarded-by(_LOCK)\n"
        "_LOCK = threading.Lock()\n"
        "def local_shadow():\n"
        "    _CACHE = {}\n"          # a plain local, not the global
        "    _CACHE['x'] = 1\n"      # must NOT be a finding
        "    def inner():\n"
        "        return _CACHE\n"    # closure over the local: clean too
        "    return inner\n"
        "def global_rebind():\n"
        "    global _CACHE\n"
        "    _CACHE = {}\n"          # THE global, unguarded: finding
        "def global_read():\n"
        "    return len(_CACHE)\n")  # the global, unguarded: finding
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["THR", "THR"]
    assert [f.line for f in findings] == [12, 14]


def test_exc_ble_reason_on_wrapped_handler_clause(tmp_path):
    """Regression: a handler whose caught-type tuple wraps across lines
    carries its justification on the clause's LAST line -- it must count
    (a reformat of a justified handler must not break lint)."""
    p = tmp_path / "h.py"
    p.write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except (ValueError,\n"
        "            Exception):  # noqa: BLE001 -- seeded: wrapped clause\n"
        "        pass\n")
    assert lint_file(str(p)) == []


def test_thr_parameter_shadow_of_guarded_global_not_flagged(tmp_path):
    """Regression: a function PARAMETER named like a guarded module global
    is the local on every use -- THR must not fire on it."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_COUNT = 0  # spgemm-lint: guarded-by(_LOCK)\n"
        "_LOCK = threading.Lock()\n"
        "def param_shadow(_COUNT):\n"
        "    return _COUNT + 1\n"       # the parameter, not the global
        "def star_shadow(*_COUNT, **kw):\n"
        "    return len(_COUNT)\n"      # vararg parameter: local too
        "def real_read():\n"
        "    return _COUNT\n")          # THE global, unguarded: finding
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["THR"]
    assert findings[0].line == 9


def test_thr_init_not_exempt_for_module_globals(tmp_path):
    """Regression: __init__'s exemption holds only for the instance's own
    attributes (construction happens-before publication); a module global
    is already published to every thread while __init__ runs, so an
    unguarded write there is a real lost-update race -- a finding."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_COUNT = 0  # spgemm-lint: guarded-by(_LOCK)\n"
        "_LOCK = threading.Lock()\n"
        "class Reg:\n"
        "    def __init__(self):\n"
        "        global _COUNT\n"
        "        _COUNT += 1\n"         # global in a ctor: still a finding
        "        self.n = _COUNT\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["THR", "THR"]
    assert [f.line for f in findings] == [7, 8]


def test_fld_proof_two_lines_above_interprocedural_finding_is_stale(tmp_path):
    """Regression: an fld-proof escape TWO lines above a tainted call
    suppresses nothing (escapes bind to their line and the one below) --
    the finding must still fire AND the escape must be reported stale,
    not vouched for by a widened used-window."""
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "u64.py").write_text(
        "import farhelp\n"
        "def entry(x):\n"
        "    # spgemm-lint: fld-proof(too far away to bind)\n"
        "    y = x\n"
        "    return farhelp.hidden(y)\n")
    (tmp_path / "farhelp.py").write_text(
        "import jax.numpy as jnp\n"
        "def hidden(x):\n"
        "    return jnp.sum(x)\n")
    findings, suppressions = core.lint_report([str(tmp_path)], doc=False)
    assert sorted(f.rule for f in findings) == ["FLD", "SUP"]
    assert len(suppressions) == 1 and suppressions[0].stale
