"""spgemm-lint: the repo self-lints clean (tier-1 gate), and each seeded
fixture violation (FLD/KNB/BKD/DOC) is caught with the correct rule ID --
both in-process and through the `python -m spgemm_tpu.analysis --json`
report that CI consumes."""

import json
import os

from conftest import run_repo_script as _run
from spgemm_tpu.analysis import (check_claude_md, core, docrules, lint_file,
                                 lint_repo)

REPO = core.repo_root()
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
FIXTURE_CLAUDE = os.path.join(FIXTURES, "CLAUDE.md")


# ------------------------------------------------------- self-lint gate --
def test_repo_self_lints_clean():
    """The tier-1 contract: zero findings on the migrated repo -- package
    AST rules AND the doc drift checks (CLAUDE.md knob table, CLI help)."""
    findings = lint_repo()
    assert findings == [], "\n".join(
        f"{f.file}:{f.line}: [{f.rule}] {f.message}" for f in findings)


def test_default_scope_covers_driver_scripts():
    """bench.py / benchmarks / the graft entry read engine knobs too --
    the default walk must keep them under the KNB/BKD contract."""
    names = {os.path.basename(p) for p in core.default_paths()}
    assert {"spgemm_tpu", "bench.py", "benchmarks",
            "__graft_entry__.py"} <= names


# ------------------------------------------------------------- FLD rule --
def test_fld_fixture_each_violation_caught():
    findings = lint_file(os.path.join(FIXTURES, "ops", "spgemm.py"))
    fld = [f for f in findings if f.rule == "FLD"]
    # jnp.sum, lax.psum, segment_sum, functools.reduce, method .sum()
    assert len(fld) == 5
    assert [f for f in findings if f.rule != "FLD"] == []
    assert all(f.file.endswith("ops/spgemm.py") and f.line > 0 for f in fld)


def test_fld_escape_hatch_suppresses_with_reason():
    src = open(os.path.join(FIXTURES, "ops", "spgemm.py")).read()
    escaped_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                        if "escaped: must NOT" in ln)
    findings = lint_file(os.path.join(FIXTURES, "ops", "spgemm.py"))
    assert escaped_line not in [f.line for f in findings]


def test_fld_escape_requires_reason(tmp_path):
    """A bare fld-proof() is not an escape: the reason is the citation."""
    p = tmp_path / "ops" / "u64.py"  # numeric-path suffix
    p.parent.mkdir()
    p.write_text("import jax.numpy as jnp\n"
                 "def f(x):\n"
                 "    # spgemm-lint: fld-proof()\n"
                 "    return jnp.sum(x)\n")
    assert [f.rule for f in lint_file(str(p))] == ["FLD"]


def test_fld_scope_is_path_based(tmp_path):
    """The same reductions in a non-numeric module are not findings."""
    p = tmp_path / "hostutil.py"
    p.write_text("import jax.numpy as jnp\n"
                 "def f(x):\n"
                 "    return jnp.sum(x)\n")
    assert lint_file(str(p)) == []
    assert [f.rule for f in lint_file(str(p), numeric=True)] == ["FLD"]


# ------------------------------------------------------------- KNB rule --
def test_knb_fixture_each_violation_caught():
    """Every READ spelling is a finding (the three classic ones plus the
    seeded planner- and serve-knob reads); the write/del in the same
    fixture (how harnesses and tests drive knob values) must NOT be."""
    findings = lint_file(os.path.join(FIXTURES, "badknob.py"))
    assert [f.rule for f in findings] == ["KNB"] * 9
    msgs = " ".join(f.message for f in findings)
    for seeded in ("SPGEMM_TPU_SEEDED_A", "SPGEMM_TPU_SEEDED_B",
                   "SPGEMM_TPU_SEEDED_C", "SPGEMM_TPU_PLAN_AHEAD",
                   "SPGEMM_TPU_PLAN_CACHE_CAP", "SPGEMM_TPU_SERVE_SOCKET",
                   "SPGEMM_TPU_SERVE_QUEUE_CAP",
                   "SPGEMM_TPU_SERVE_JOB_TIMEOUT",
                   "SPGEMM_TPU_SERVE_WEDGE_GRACE_S"):
        assert seeded in msgs  # the finding names the offending knob


def test_knb_registry_module_is_exempt():
    """knobs.py itself reads the environment -- the one blessed reader."""
    findings = lint_file(os.path.join(REPO, "spgemm_tpu", "utils",
                                      "knobs.py"))
    assert [f for f in findings if f.rule == "KNB"] == []


# ------------------------------------------------------------- BKD rule --
def test_bkd_fixture_each_violation_caught():
    findings = lint_file(os.path.join(FIXTURES, "badbackend.py"))
    # jax.devices() at module scope, jnp.zeros() at module scope (array
    # materialization initializes the backend), jax.local_devices() in a
    # default-argument expression
    assert [f.rule for f in findings] == ["BKD"] * 3
    flagged = [f.line for f in findings]
    src = open(os.path.join(FIXTURES, "badbackend.py")).read()
    lazy_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                     if "legal" in ln and "jax.devices" in ln)
    main_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                     if "script driver" in ln)
    assert lazy_line not in flagged and main_line not in flagged


def test_bkd_probe_module_is_exempt():
    findings = lint_file(os.path.join(REPO, "spgemm_tpu", "utils",
                                      "backend_probe.py"))
    assert [f for f in findings if f.rule == "BKD"] == []


def test_bkd_host_only_body_is_scanned():
    """@host_only (utils/backend_probe) marks planner/worker-thread code:
    its WHOLE body is in BKD scope -- a backend touch there hangs a thread
    the pipeline is blocked on -- while unmarked function bodies keep the
    import-time-only rule."""
    findings = lint_file(os.path.join(FIXTURES, "badplanner.py"))
    assert [f.rule for f in findings] == ["BKD"] * 2
    msgs = " ".join(f.message for f in findings)
    assert "host_only" in msgs and "jax.devices" in msgs
    src = open(os.path.join(FIXTURES, "badplanner.py")).read()
    flagged = [f.line for f in findings]
    legal = next(i for i, ln in enumerate(src.splitlines(), 1)
                 if "legal" in ln and "jax.devices" in ln)
    assert legal not in flagged  # unmarked lazy touch stays legal


def test_bkd_host_only_dotted_decorator(tmp_path):
    """The dotted spelling `@backend_probe.host_only` is recognized too,
    and a passing helper (pure numpy) yields no finding."""
    p = tmp_path / "planhelp.py"
    p.write_text("from spgemm_tpu.utils import backend_probe\n"
                 "import numpy as np\n"
                 "import jax\n"
                 "@backend_probe.host_only\n"
                 "def bad(x):\n"
                 "    return jax.device_put(x)\n"
                 "@backend_probe.host_only\n"
                 "def good(x):\n"
                 "    return np.asarray(x).sum()\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["BKD"]
    assert "jax.device_put" in findings[0].message


def test_host_only_marker_on_planner_entrypoints():
    """The engine's planner bodies really carry the marker the rule keys
    on (the runtime attribute host_only sets)."""
    from spgemm_tpu.chain import _PlanAheadWorker
    from spgemm_tpu.ops.spgemm import _plan_host

    assert getattr(_plan_host, "__spgemm_host_only__", False)
    assert getattr(_PlanAheadWorker._work, "__spgemm_host_only__", False)


# ------------------------------------------------------------- DOC rule --
def test_doc_fixture_drift_caught():
    findings = check_claude_md(FIXTURE_CLAUDE)
    assert [f.rule for f in findings] == ["DOC"]
    assert "drifted" in findings[0].message


def test_doc_current_table_passes_and_tamper_fails(tmp_path):
    good = tmp_path / "CLAUDE.md"
    good.write_text("# doc\n\n" + docrules.render_knob_block() + "\n")
    assert check_claude_md(str(good)) == []
    tampered = good.read_text().replace("SPGEMM_TPU_VPU_ALGO", "SPGEMM_TPU_GONE")
    good.write_text(tampered)
    assert [f.rule for f in check_claude_md(str(good))] == ["DOC"]
    good.write_text("# no markers at all\n")
    findings = check_claude_md(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "markers missing" in findings[0].message


def test_doc_cli_help_covers_every_knob():
    assert docrules.check_cli_help() == []


# ----------------------------------------------------------- PARSE rule --
def test_syntax_error_gets_its_own_rule_id(tmp_path):
    """A broken file means NO rule ran on it: its finding must not be
    attributed to a rule family in the JSON counts."""
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["PARSE"]
    assert "does not parse" in findings[0].message


# ------------------------------------------------- JSON report contract --
def test_json_report_fixture_run():
    """The machine-readable report: every rule family present with the
    correct rule ID, (file, line, rule, message) per finding, exit 1."""
    rc = _run(["-m", "spgemm_tpu.analysis", "--json", FIXTURES,
               "--claude-md", FIXTURE_CLAUDE])
    assert rc.returncode == 1, rc.stderr[-2000:]
    report = json.loads(rc.stdout)
    assert report["clean"] is False
    # badknob: 3 classic + 2 planner-knob + 4 serve-knob reads;
    # badbackend: 3 import-time touches; badplanner: 2 @host_only-body
    # touches
    assert report["counts"] == {"FLD": 5, "KNB": 9, "BKD": 5, "DOC": 1,
                                "PARSE": 0}
    for f in report["findings"]:
        assert set(f) == {"file", "line", "rule", "message"}
        assert f["rule"] in ("FLD", "KNB", "BKD", "DOC")
        assert isinstance(f["line"], int) and f["line"] >= 1


def test_json_report_clean_repo_run():
    """`make lint` contract: the default run exits 0 with a clean report
    (and never needs a backend -- the linter is jax-free by design)."""
    rc = _run(["-m", "spgemm_tpu.analysis", "--json"])
    assert rc.returncode == 0, rc.stdout + rc.stderr[-2000:]
    report = json.loads(rc.stdout)
    assert report["clean"] is True and report["findings"] == []
