"""spgemm-lint: the repo self-lints clean (tier-1 gate), and each seeded
fixture violation (FLD incl. the interprocedural pass / KNB / BKD / THR /
EXC / SUP / DOC) is caught with the correct rule ID -- both in-process and
through the `python -m spgemm_tpu.analysis --json` / `--sarif` reports
that CI consumes."""

import json
import os
import subprocess
import sys

from conftest import run_repo_script as _run
from spgemm_tpu.analysis import (check_claude_md, core, docrules, lint_file,
                                 lint_repo)

REPO = core.repo_root()
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")
FIXTURE_CLAUDE = os.path.join(FIXTURES, "CLAUDE.md")


def _fixture_lines(name: str, needle: str) -> list[int]:
    """1-indexed lines of a fixture whose text contains needle."""
    src = open(os.path.join(FIXTURES, name)).read()
    return [i for i, ln in enumerate(src.splitlines(), 1) if needle in ln]


# ------------------------------------------------------- self-lint gate --
def test_repo_self_lints_clean():
    """The tier-1 contract: zero findings on the migrated repo -- package
    AST rules AND the doc drift checks (CLAUDE.md knob table, CLI help)."""
    findings = lint_repo()
    assert findings == [], "\n".join(
        f"{f.file}:{f.line}: [{f.rule}] {f.message}" for f in findings)


def test_default_scope_covers_driver_scripts():
    """bench.py / benchmarks / the graft entry read engine knobs too --
    the default walk must keep them under the KNB/BKD contract."""
    names = {os.path.basename(p) for p in core.default_paths()}
    assert {"spgemm_tpu", "bench.py", "benchmarks",
            "__graft_entry__.py"} <= names


# ------------------------------------------------------------- FLD rule --
def test_fld_fixture_each_violation_caught():
    findings = lint_file(os.path.join(FIXTURES, "ops", "spgemm.py"))
    fld = [f for f in findings if f.rule == "FLD"]
    # jnp.sum, lax.psum, segment_sum, functools.reduce, method .sum()
    assert len(fld) == 5
    assert [f for f in findings if f.rule != "FLD"] == []
    assert all(f.file.endswith("ops/spgemm.py") and f.line > 0 for f in fld)


def test_fld_escape_hatch_suppresses_with_reason():
    src = open(os.path.join(FIXTURES, "ops", "spgemm.py")).read()
    escaped_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                        if "escaped: must NOT" in ln)
    findings = lint_file(os.path.join(FIXTURES, "ops", "spgemm.py"))
    assert escaped_line not in [f.line for f in findings]


def test_fld_escape_requires_reason(tmp_path):
    """A bare fld-proof() is not an escape: the reason is the citation."""
    p = tmp_path / "ops" / "u64.py"  # numeric-path suffix
    p.parent.mkdir()
    p.write_text("import jax.numpy as jnp\n"
                 "def f(x):\n"
                 "    # spgemm-lint: fld-proof()\n"
                 "    return jnp.sum(x)\n")
    assert [f.rule for f in lint_file(str(p))] == ["FLD"]


def test_fld_scope_is_path_based(tmp_path):
    """The same reductions in a non-numeric module are not findings."""
    p = tmp_path / "hostutil.py"
    p.write_text("import jax.numpy as jnp\n"
                 "def f(x):\n"
                 "    return jnp.sum(x)\n")
    assert lint_file(str(p)) == []
    assert [f.rule for f in lint_file(str(p), numeric=True)] == ["FLD"]


def test_fld_delta_module_in_numeric_scope():
    """ops/delta.py (incremental recompute) is in the numeric-lint scope:
    its reachability masks gate which output rows re-fold, so a smuggled
    unordered reduction is a finding -- and the LIVE module self-lints
    clean."""
    assert core.is_numeric_module("spgemm_tpu/ops/delta.py")
    findings = lint_file(os.path.join(FIXTURES, "ops", "delta.py"))
    assert [f.rule for f in findings] == ["FLD"]
    assert "jnp.sum" in findings[0].message
    live = lint_file(os.path.join(REPO, "spgemm_tpu", "ops", "delta.py"))
    assert live == [], "\n".join(
        f"{f.file}:{f.line}: [{f.rule}] {f.message}" for f in live)


def test_fld_estimator_module_in_numeric_scope():
    """ops/estimate.py (the sampled planner estimator) is in the
    numeric-lint scope: a jnp.sum smuggled into an estimator helper is a
    finding -- and the LIVE module self-lints clean (its sizing sums carry
    reasoned fld-proof escapes)."""
    assert core.is_numeric_module("spgemm_tpu/ops/estimate.py")
    findings = lint_file(os.path.join(FIXTURES, "ops", "estimate.py"))
    assert [f.rule for f in findings] == ["FLD"]
    assert "jnp.sum" in findings[0].message
    live = lint_file(os.path.join(REPO, "spgemm_tpu", "ops", "estimate.py"))
    assert live == [], "\n".join(
        f"{f.file}:{f.line}: [{f.rule}] {f.message}" for f in live)


# ------------------------------------------------------------- KNB rule --
def test_knb_fixture_each_violation_caught():
    """Every READ spelling is a finding (the three classic ones plus the
    seeded planner-, serve-, and estimator-knob reads); the write/del in
    the same fixture (how harnesses and tests drive knob values) must NOT
    be."""
    findings = lint_file(os.path.join(FIXTURES, "badknob.py"))
    assert [f.rule for f in findings] == ["KNB"] * 19
    msgs = " ".join(f.message for f in findings)
    for seeded in ("SPGEMM_TPU_SEEDED_A", "SPGEMM_TPU_SEEDED_B",
                   "SPGEMM_TPU_SEEDED_C", "SPGEMM_TPU_PLAN_AHEAD",
                   "SPGEMM_TPU_PLAN_CACHE_CAP", "SPGEMM_TPU_SERVE_SOCKET",
                   "SPGEMM_TPU_SERVE_QUEUE_CAP",
                   "SPGEMM_TPU_SERVE_JOB_TIMEOUT",
                   "SPGEMM_TPU_SERVE_WEDGE_GRACE_S",
                   "SPGEMM_TPU_PLAN_ESTIMATE",
                   "SPGEMM_TPU_EST_SAMPLE_ROWS",
                   "SPGEMM_TPU_EST_CONFIDENCE",
                   "SPGEMM_TPU_DELTA", "SPGEMM_TPU_DELTA_RETAIN",
                   "SPGEMM_TPU_OBS_EVENTS",
                   "SPGEMM_TPU_OBS_EVENTS_MAX_KB",
                   "SPGEMM_TPU_WARM", "SPGEMM_TPU_WARM_DIR",
                   "SPGEMM_TPU_WARM_MAX_MB"):
        assert seeded in msgs  # the finding names the offending knob


def test_knb_registry_module_is_exempt():
    """knobs.py itself reads the environment -- the one blessed reader."""
    findings = lint_file(os.path.join(REPO, "spgemm_tpu", "utils",
                                      "knobs.py"))
    assert [f for f in findings if f.rule == "KNB"] == []


# ------------------------------------------------------------- BKD rule --
def test_bkd_fixture_each_violation_caught():
    findings = lint_file(os.path.join(FIXTURES, "badbackend.py"))
    # jax.devices() at module scope, jnp.zeros() at module scope (array
    # materialization initializes the backend), jax.local_devices() in a
    # default-argument expression
    assert [f.rule for f in findings] == ["BKD"] * 3
    flagged = [f.line for f in findings]
    src = open(os.path.join(FIXTURES, "badbackend.py")).read()
    lazy_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                     if "legal" in ln and "jax.devices" in ln)
    main_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                     if "script driver" in ln)
    assert lazy_line not in flagged and main_line not in flagged


def test_bkd_probe_module_is_exempt():
    findings = lint_file(os.path.join(REPO, "spgemm_tpu", "utils",
                                      "backend_probe.py"))
    assert [f for f in findings if f.rule == "BKD"] == []


def test_bkd_host_only_body_is_scanned():
    """@host_only (utils/backend_probe) marks planner/worker-thread code:
    its WHOLE body is in BKD scope -- a backend touch there hangs a thread
    the pipeline is blocked on -- while unmarked function bodies keep the
    import-time-only rule."""
    findings = lint_file(os.path.join(FIXTURES, "badplanner.py"))
    assert [f.rule for f in findings] == ["BKD"] * 2
    msgs = " ".join(f.message for f in findings)
    assert "host_only" in msgs and "jax.devices" in msgs
    src = open(os.path.join(FIXTURES, "badplanner.py")).read()
    flagged = [f.line for f in findings]
    legal = next(i for i, ln in enumerate(src.splitlines(), 1)
                 if "legal" in ln and "jax.devices" in ln)
    assert legal not in flagged  # unmarked lazy touch stays legal


def test_bkd_host_only_dotted_decorator(tmp_path):
    """The dotted spelling `@backend_probe.host_only` is recognized too,
    and a passing helper (pure numpy) yields no finding."""
    p = tmp_path / "planhelp.py"
    p.write_text("from spgemm_tpu.utils import backend_probe\n"
                 "import numpy as np\n"
                 "import jax\n"
                 "@backend_probe.host_only\n"
                 "def bad(x):\n"
                 "    return jax.device_put(x)\n"
                 "@backend_probe.host_only\n"
                 "def good(x):\n"
                 "    return np.asarray(x).sum()\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["BKD"]
    assert "jax.device_put" in findings[0].message


def test_host_only_marker_on_planner_entrypoints():
    """The engine's planner bodies really carry the marker the rule keys
    on (the runtime attribute host_only sets)."""
    from spgemm_tpu.chain import _PlanAheadWorker
    from spgemm_tpu.ops.spgemm import _plan_host

    assert getattr(_plan_host, "__spgemm_host_only__", False)
    assert getattr(_PlanAheadWorker._work, "__spgemm_host_only__", False)


# ------------------------------------------------------------- MET rule --
def test_met_fixture_each_violation_caught():
    """Undeclared phase/counter names and a computed name are findings;
    declared names and ad-hoc PhaseTimers instances stay legal."""
    findings = lint_file(os.path.join(FIXTURES, "badmetric.py"))
    met = [f for f in findings if f.rule == "MET"]
    assert len(met) == 7 and findings == met
    flagged = [f.line for f in met]
    for needle in ("MET: undeclared phase name",
                   "MET: undeclared counter name",
                   "MET: computed metric name",
                   "MET: undeclared profile counter",
                   "MET: undeclared profile phase",
                   "MET: undeclared warm counter",
                   "MET: undeclared warm phase"):
        assert _fixture_lines("badmetric.py", needle)[0] in flagged
    msgs = " ".join(f.message for f in met)
    assert "made_up_phase" in msgs and "made_up_counter" in msgs
    # the deep-profiling near-misses: the FAMILY name is not the declared
    # counter name, and an ad-hoc compile phase does not exist
    assert "spgemm_compiles_total" in msgs and "compile_wait" in msgs
    # the warm-start near-misses: the singular of the declared counter
    # and an ad-hoc load phase
    assert "warm_hit" in msgs and "warm_loading" in msgs
    assert "ENGINE_PHASES" in msgs and "ENGINE_COUNTERS" in msgs
    for needle in ("legal: declared phase", "legal: declared counter",
                   "legal: not the ENGINE registry",
                   "legal: declared warm phase",
                   "legal: declared warm counter"):
        assert _fixture_lines("badmetric.py", needle)[0] not in flagged


def test_met_alias_spellings_resolve(tmp_path):
    """Both repo spellings -- `from ...timers import ENGINE` and the
    `import ... as t` + `t.ENGINE` form -- resolve to the registry, and
    the keyword spelling `name=` is in scope too (both mint the
    series)."""
    p = tmp_path / "h.py"
    p.write_text("from spgemm_tpu.utils.timers import ENGINE\n"
                 "import spgemm_tpu.utils.timers as t\n"
                 "from spgemm_tpu.utils import timers\n"
                 "def f(i):\n"
                 "    ENGINE.incr('nope_a')\n"
                 "    t.ENGINE.incr('nope_b')\n"
                 "    timers.ENGINE.incr('nope_c')\n"
                 "    ENGINE.incr(name='nope_kw')\n"
                 "    ENGINE.incr(name=f'dyn_{i}')\n"
                 "    ENGINE.incr('dispatches')\n"
                 "    ENGINE.incr(name='dispatches')\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["MET"] * 5
    assert [f.line for f in findings] == [5, 6, 7, 8, 9]


def test_met_registry_covers_live_call_sites():
    """Every ENGINE phase/counter name the package actually uses is
    declared (the repo self-lint enforces this; spot-check the registry
    side so a deleted declaration cannot slip through unnoticed)."""
    from spgemm_tpu.obs.metrics import ENGINE_COUNTERS, ENGINE_PHASES

    for name in ("plan", "plan_wait", "numeric_dispatch", "assembly",
                 "ring_fold", "dcn_exchange", "serve_execute",
                 "serve_queue_wait", "estimate", "join_fallback",
                 "delta_diff", "delta_splice", "warm_load", "warm_flush"):
        assert name in ENGINE_PHASES
    for name in ("dispatches", "plan_cache_hits", "plan_cache_misses",
                 "plan_cache_evictions", "ring_steps", "serve_reaps",
                 "serve_degrades", "est_hits", "est_fallbacks",
                 "delta_rows_recomputed", "delta_rows_total",
                 "delta_full_fallbacks", "compiles", "warm_hits",
                 "warm_misses", "warm_corrupt"):
        assert name in ENGINE_COUNTERS


# ------------------------------------------------------------- FPT rule --
def test_fpt_fixture_each_violation_caught():
    """Undeclared failpoint names (module and bare-import spellings) and
    a computed name are findings; declared names stay legal."""
    findings = lint_file(os.path.join(FIXTURES, "badfailpoint.py"))
    fpt = [f for f in findings if f.rule == "FPT"]
    assert len(fpt) == 3 and findings == fpt
    flagged = [f.line for f in fpt]
    for needle in ("FPT: undeclared failpoint name",
                   "FPT: computed failpoint name",
                   "FPT: undeclared via the bare import"):
        assert _fixture_lines("badfailpoint.py", needle)[0] in flagged
    msgs = " ".join(f.message for f in fpt)
    assert "made.up.point" in msgs and "also.made.up" in msgs
    assert "utils/failpoints.py" in msgs
    for needle in ("legal: declared (corrupt kind)",
                   "legal: declared via the bare import"):
        assert _fixture_lines("badfailpoint.py", needle)[0] not in flagged


def test_fpt_stale_registry_entry_is_a_finding(tmp_path):
    """The reverse direction: a registry entry no check() site names is
    flagged AT THE REGISTRY -- and only when the registry module itself
    is in the linted unit set (fixture runs over partial trees must not
    call every entry stale)."""
    import shutil

    from spgemm_tpu.analysis.core import lint_report
    from spgemm_tpu.utils.failpoints import REGISTRY

    # a partial tree WITHOUT the registry module: quiet
    site = tmp_path / "site.py"
    site.write_text("from spgemm_tpu.utils import failpoints\n"
                    "def f():\n"
                    "    failpoints.check('warm.load')\n")
    findings, _ = lint_report([str(site)], doc=False)
    assert [f for f in findings if f.rule == "FPT"] == []

    # the registry module + one site: every OTHER entry is stale
    pkg = tmp_path / "utils"
    pkg.mkdir()
    shutil.copy(os.path.join(REPO, "spgemm_tpu", "utils",
                             "failpoints.py"),
                str(pkg / "failpoints.py"))
    findings, _ = lint_report([str(site), str(pkg)], doc=False)
    stale = [f for f in findings if f.rule == "FPT"
             and "stale failpoint registry entry" in f.message]
    assert len(stale) == len(REGISTRY) - 1  # all but the checked one
    assert all(f.file.endswith("failpoints.py") for f in stale)
    assert not any("'warm.load'" in f.message for f in stale)


def test_fpt_registry_covers_live_call_sites():
    """Every failpoint the chaos harness documents is declared (the repo
    self-lint enforces site coverage; spot-check the registry side)."""
    from spgemm_tpu.utils.failpoints import REGISTRY

    for name in ("plan.build", "plan.ensure_exact", "kernel.dispatch",
                 "delta.diff", "delta.splice", "warm.load", "warm.flush",
                 "serve.journal", "serve.accept", "serve.readline",
                 "serve.executor", "serve.heartbeat"):
        assert name in REGISTRY
    assert all(fp.kind in ("raise", "hang", "corrupt", "delay")
               for fp in REGISTRY.values())


# ------------------------------------------------------------- DOC rule --
def test_doc_fixture_drift_caught():
    findings = check_claude_md(FIXTURE_CLAUDE)
    assert [f.rule for f in findings] == ["DOC"]
    assert "drifted" in findings[0].message


def test_doc_current_table_passes_and_tamper_fails(tmp_path):
    good = tmp_path / "CLAUDE.md"
    good.write_text("# doc\n\n" + docrules.render_knob_block() + "\n")
    assert check_claude_md(str(good)) == []
    tampered = good.read_text().replace("SPGEMM_TPU_VPU_ALGO", "SPGEMM_TPU_GONE")
    good.write_text(tampered)
    assert [f.rule for f in check_claude_md(str(good))] == ["DOC"]
    good.write_text("# no markers at all\n")
    findings = check_claude_md(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "markers missing" in findings[0].message


def test_doc_cli_help_covers_every_knob():
    assert docrules.check_cli_help() == []


def test_doc_metrics_table_current_and_tamper_fails(tmp_path):
    """The ARCHITECTURE.md metrics table is held to the obs/metrics.py
    registry exactly like the knob table is to knobs.py."""
    good = tmp_path / "ARCHITECTURE.md"
    good.write_text("# arch\n\n" + docrules.render_metrics_block() + "\n")
    assert docrules.check_architecture_md(str(good)) == []
    tampered = good.read_text().replace("spgemm_phase_seconds_total",
                                        "spgemm_gone_total")
    good.write_text(tampered)
    findings = docrules.check_architecture_md(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "drifted" in findings[0].message
    good.write_text("# no markers at all\n")
    findings = docrules.check_architecture_md(str(good))
    assert [f.rule for f in findings] == ["DOC"]
    assert "markers missing" in findings[0].message


def test_write_metrics_table_regenerates(tmp_path):
    """`--write-metrics-table` rewrites the marked block in place, after
    which the DOC check passes."""
    arch = tmp_path / "ARCHITECTURE.md"
    arch.write_text("# doc\n" + docrules.METRICS_TABLE_BEGIN + "\nstale\n"
                    + docrules.METRICS_TABLE_END + "\ntail\n")
    rc = _run(["-m", "spgemm_tpu.analysis", "--write-metrics-table",
               "--architecture-md", str(arch)])
    assert rc.returncode == 0, rc.stderr[-2000:]
    assert docrules.check_architecture_md(str(arch)) == []
    assert arch.read_text().startswith("# doc\n")
    assert arch.read_text().endswith("\ntail\n")


# ----------------------------------------------------------- PARSE rule --
def test_syntax_error_gets_its_own_rule_id(tmp_path):
    """A broken file means NO rule ran on it: its finding must not be
    attributed to a rule family in the JSON counts."""
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["PARSE"]
    assert "does not parse" in findings[0].message


# ------------------------------------------------------------- THR rule --
def test_thr_fixture_each_violation_caught():
    """Unguarded accesses of guarded-by-annotated state: a module-global
    write, an instance read, and a nested-def access (callbacks run later,
    usually on another thread -- the enclosing `with` does not protect
    them)."""
    findings = lint_file(os.path.join(FIXTURES, "badthread.py"))
    thr = [f for f in findings if f.rule == "THR"]
    assert len(thr) == 3 and findings == thr
    flagged = [f.line for f in thr]
    for needle in ("module-global write without the lock", "THR: no lock",
                   "return list(self._jobs)"):
        assert _fixture_lines("badthread.py", needle)[0] in flagged
    # the legal shapes stay clean: lock held, Condition alias, __init__,
    # *_locked convention, reasoned thr-ok escape
    for needle in ("legal: lock held", "legal: Condition aliases",
                   "legal: __init__", "caller holds the lock",
                   "escaped with a reason"):
        assert _fixture_lines("badthread.py", needle)[0] not in flagged


def test_thr_finding_names_attribute_and_lock():
    findings = lint_file(os.path.join(FIXTURES, "badthread.py"))
    msgs = " ".join(f.message for f in findings)
    assert "guarded-by(_lock)" in msgs and "guarded-by(_GLOCK)" in msgs
    assert "self._jobs" in msgs and "_G" in msgs


def test_thr_guard_deletion_turns_lint_red(tmp_path):
    """The acceptance spot-check, on FIXTURE COPIES of the live serving
    modules: deleting any one `with` lock guard in serve/queue.py or
    serve/daemon.py must produce a THR finding (the annotations actually
    bind)."""
    cases = [
        ("serve/queue.py",
         "        with self._lock:\n            return {",
         "        if True:\n            return {"),           # Job.snapshot
        ("serve/daemon.py",
         "        with self._lock:\n            degraded = self.degraded\n"
         "            degrade_reason = self.degrade_reason",
         "        if True:\n            degraded = self.degraded\n"
         "            degrade_reason = self.degrade_reason"),  # _op_stats
    ]
    for rel, guarded, unguarded in cases:
        src = open(os.path.join(REPO, "spgemm_tpu", rel)).read()
        assert lint_file(os.path.join(REPO, "spgemm_tpu", rel)) == []
        mutated = src.replace(guarded, unguarded)
        assert mutated != src, f"guard pattern drifted in {rel}"
        p = tmp_path / os.path.basename(rel)
        p.write_text(mutated)
        thr = [f for f in lint_file(str(p)) if f.rule == "THR"]
        assert thr, f"deleting a lock guard in {rel} must turn lint red"


# ------------------------------------------------------------- EXC rule --
def test_exc_fixture_each_violation_caught():
    """Naked broad catch, swallowing bare except, swallowing BaseException
    -- and the legal shapes: BLE001-with-reason, re-raising handler,
    reasoned exc-ok escape."""
    findings = lint_file(os.path.join(FIXTURES, "badexcept.py"))
    exc = [f for f in findings if f.rule == "EXC"]
    assert len(exc) == 3 and findings == exc
    flagged = [f.line for f in exc]
    for needle in ("no BLE001 justification", "bare except that swallows",
                   "would swallow JobAbandoned"):
        assert _fixture_lines("badexcept.py", needle)[0] in flagged
    legal = (_fixture_lines("badexcept.py", "noqa: BLE001")
             + _fixture_lines("badexcept.py", "re-raises"))
    assert legal and not set(legal) & set(flagged)


def test_exc_ble_reason_must_be_nonempty(tmp_path):
    """A bare `# noqa: BLE001` (no `-- reason`) does not justify the broad
    catch -- the reason is the reviewable citation."""
    p = tmp_path / "h.py"
    p.write_text("def f():\n"
                 "    try:\n"
                 "        pass\n"
                 "    except Exception:  # noqa: BLE001\n"
                 "        pass\n")
    assert [f.rule for f in lint_file(str(p))] == ["EXC"]


def test_exc_base_reraise_must_be_terminal(tmp_path):
    """A conditional re-raise does not satisfy the provably-re-raise
    contract: the handler body must END in `raise`."""
    p = tmp_path / "h.py"
    p.write_text("def f(flag):\n"
                 "    try:\n"
                 "        pass\n"
                 "    except BaseException:\n"
                 "        if flag:\n"
                 "            raise\n"
                 "        return None\n")
    assert [f.rule for f in lint_file(str(p))] == ["EXC"]


# ------------------------------------------- interprocedural FLD (taint) --
def test_interprocedural_fld_one_hop_outside_numeric():
    """The acceptance case: a numeric module calling a helper in a
    NON-numeric module whose body performs the unordered reduction is
    flagged at the call site, one and two hops deep, with the witness
    chain down to the reduction's file:line in the message."""
    findings = core.lint_paths([os.path.join(FIXTURES, "callchain")],
                               doc=False)
    fld = [f for f in findings if f.rule == "FLD"]
    assert len(fld) == 2 and findings == fld
    assert all(f.file.endswith("callchain/ops/spgemm.py") for f in fld)
    by_msg = {f.line: f.message for f in fld}
    src = open(os.path.join(FIXTURES, "callchain", "ops",
                            "spgemm.py")).read()
    one = next(i for i, ln in enumerate(src.splitlines(), 1)
               if "one call-hop" in ln)
    two = next(i for i, ln in enumerate(src.splitlines(), 1)
               if "two call-hops" in ln)
    assert set(by_msg) == {one, two}
    assert "hidden_sum -> `jnp.sum`" in by_msg[one]
    assert "hosthelper.py:" in by_msg[one]
    assert "outer -> inner -> `jnp.sum`" in by_msg[two]
    assert "hostdeep.py:" in by_msg[two]
    # the call-site escape and the source-proved helper stay clean
    escaped = next(i for i, ln in enumerate(src.splitlines(), 1)
                   if "call-site escape" in ln)
    proved = next(i for i, ln in enumerate(src.splitlines(), 1)
                  if "proves its sum at source" in ln)
    assert not {escaped, escaped + 1, proved} & set(by_msg)


def test_interprocedural_fld_same_module_helper_still_flagged(tmp_path):
    """Module-scoped evasion INSIDE numeric code never existed (check_fld
    sees the whole module); the taint pass must not double-report it."""
    p = tmp_path / "ops" / "spgemm.py"
    p.parent.mkdir()
    p.write_text("import jax.numpy as jnp\n"
                 "def helper(x):\n"
                 "    return jnp.sum(x)\n"
                 "def entry(x):\n"
                 "    return helper(x)\n")
    findings = core.lint_paths([str(tmp_path)], doc=False)
    # exactly one finding: the direct reduction (per-module FLD); the
    # same-module call edge is not re-reported by the taint pass
    assert [f.rule for f in findings] == ["FLD"]
    assert findings[0].line == 3


def test_interprocedural_fld_import_alias_resolves(tmp_path):
    """`import helpers as h; h.f(...)` resolves through the alias."""
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "u64.py").write_text(
        "import myhelpers as h\n"
        "def entry(x):\n"
        "    return h.hidden(x)\n")
    (tmp_path / "myhelpers.py").write_text(
        "import jax.numpy as jnp\n"
        "def hidden(x):\n"
        "    return jnp.sum(x)\n")
    findings = core.lint_paths([str(tmp_path)], doc=False)
    assert [f.rule for f in findings] == ["FLD"]
    assert findings[0].file.endswith("ops/u64.py") and findings[0].line == 3


# --------------------------------------------------- suppression audit --
def test_stale_suppressions_reported():
    """An escape comment on a line that no longer produces the underlying
    finding is itself a finding (SUP), for every escape family."""
    findings, suppressions = core.lint_report(
        [os.path.join(FIXTURES, "stalesup.py")], doc=False)
    assert [f.rule for f in findings] == ["SUP"] * 3
    assert {s.rule for s in suppressions} == {"FLD", "THR", "EXC"}
    assert all(s.stale for s in suppressions)
    assert all("seeded-stale" in s.reason for s in suppressions)
    assert [f.line for f in findings] == [s.line for s in sorted(
        suppressions, key=lambda s: s.line)]


def test_fld_proof_on_clean_numeric_line_is_stale(tmp_path):
    """The acceptance case verbatim: a fld-proof(...) comment on a clean
    line IN A NUMERIC MODULE is reported as stale."""
    p = tmp_path / "ops" / "u64.py"
    p.parent.mkdir()
    p.write_text("def f(x):\n"
                 "    # spgemm-lint: fld-proof(left over from a refactor)\n"
                 "    return x + 1\n")
    findings, suppressions = core.lint_report([str(p)], doc=False)
    assert [f.rule for f in findings] == ["SUP"]
    assert "suppresses nothing" in findings[0].message
    assert len(suppressions) == 1 and suppressions[0].stale


def test_used_suppressions_inventoried_not_stale():
    """Escapes that DO suppress something appear in the inventory with
    stale=false and produce no SUP finding -- incl. interprocedural
    call-site escapes and taint-suppressing source escapes."""
    findings, suppressions = core.lint_report(
        [os.path.join(FIXTURES, "callchain")], doc=False)
    assert [f.rule for f in findings] == ["FLD", "FLD"]
    assert len(suppressions) == 2  # call-site escape + source escape
    assert not any(s.stale for s in suppressions)


# ------------------------------------------------- JSON report contract --
def test_json_report_fixture_run():
    """The machine-readable report: every rule family present with the
    correct rule ID, (file, line, rule, message) per finding, the full
    suppression inventory, exit 1."""
    rc = _run(["-m", "spgemm_tpu.analysis", "--json", FIXTURES,
               "--claude-md", FIXTURE_CLAUDE])
    assert rc.returncode == 1, rc.stderr[-2000:]
    report = json.loads(rc.stdout)
    assert report["clean"] is False
    # badknob: 3 classic + 2 planner-knob + 4 serve-knob + 3
    # estimator-knob + 2 delta-knob + 2 obs-events-knob + 3 warm-knob
    # reads; badbackend: 3 import-time touches; badplanner: 2
    # @host_only-body touches; FLD: 5 per-module + 2 interprocedural
    # (callchain) + 1 ops/estimate + 1 ops/delta numeric-scope;
    # badthread/badexcept/stalesup: 3 each; badmetric: undeclared phase
    # + undeclared counter + computed name + 2 deep-profiling + 2
    # warm-layer near-misses; badfailpoint: 2 undeclared + 1 computed
    # (the stale-registry direction stays quiet -- the registry module
    # is not in the fixture unit set)
    assert report["counts"] == {"FLD": 9, "KNB": 19, "BKD": 5, "THR": 3,
                                "EXC": 3, "MET": 7, "FPT": 3, "DOC": 1,
                                "SUP": 3, "PARSE": 0}
    assert set(report["counts"]) == set(core.RULES)
    for f in report["findings"]:
        assert set(f) == {"file", "line", "rule", "message"}
        assert f["rule"] in core.RULES
        assert isinstance(f["line"], int) and f["line"] >= 1
    # the suppression inventory: every escape comment in the run, with
    # the three stalesup.py seeds marked stale
    sup = report["suppressions"]
    assert all(set(s) == {"file", "line", "rule", "reason", "stale"}
               for s in sup)
    assert sum(s["stale"] for s in sup) == 3
    assert all(s["file"].endswith("stalesup.py")
               for s in sup if s["stale"])
    assert len(sup) == 8  # 3 stale + thr-ok + exc-ok + fld escapes in use


def test_json_report_clean_repo_run():
    """`make lint` contract: the default run exits 0 with a clean report
    (and never needs a backend -- the linter is jax-free by design).  The
    repo's own escape inventory rides along, all in use."""
    rc = _run(["-m", "spgemm_tpu.analysis", "--json"])
    assert rc.returncode == 0, rc.stdout + rc.stderr[-2000:]
    report = json.loads(rc.stdout)
    assert report["clean"] is True and report["findings"] == []
    assert not any(s["stale"] for s in report["suppressions"])


# ------------------------------------------------------ SARIF emission --
def test_sarif_output_schema_shape(tmp_path):
    """`--sarif F` (make lint-sarif) writes a SARIF 2.1.0 log: version +
    $schema, one run, the full rule registry as tool.driver.rules, one
    result per finding with ruleId/message/physicalLocation."""
    out = tmp_path / "lint.sarif"
    rc = _run(["-m", "spgemm_tpu.analysis", "--sarif", str(out),
               os.path.join(FIXTURES, "badthread.py"),
               os.path.join(FIXTURES, "badexcept.py")])
    assert rc.returncode == 1
    log = json.loads(out.read_text())
    assert log["version"] == "2.1.0"
    assert log["$schema"].endswith("sarif-2.1.0.json")
    assert len(log["runs"]) == 1
    run = log["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "spgemm-lint"
    assert [r["id"] for r in driver["rules"]] == list(core.RULES)
    assert all(r["shortDescription"]["text"] for r in driver["rules"])
    assert len(run["results"]) == 6  # 3 THR + 3 EXC
    for res in run["results"]:
        assert res["ruleId"] in core.RULES
        assert res["level"] == "error"
        assert res["message"]["text"]
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1


def test_sarif_clean_run_empty_results(tmp_path):
    out = tmp_path / "lint.sarif"
    rc = _run(["-m", "spgemm_tpu.analysis", "--sarif", str(out),
               os.path.join(REPO, "spgemm_tpu", "utils", "timers.py")])
    assert rc.returncode == 0
    log = json.loads(out.read_text())
    assert log["runs"][0]["results"] == []


# -------------------------------------------- environment independence --
def test_analysis_import_is_jax_free():
    """The linter must never hang on a dead TPU: importing the analysis
    package AND running the full default self-lint (incl. the DOC checks,
    which import the CLI) pulls in no jax/jaxlib module."""
    code = (
        "import sys\n"
        "import spgemm_tpu.analysis\n"
        "from spgemm_tpu.analysis import callgraph, core, excrules, "
        "sarif, thrrules\n"
        "core.lint_repo()\n"
        "bad = [m for m in sys.modules\n"
        "       if m == 'jax' or m.startswith(('jax.', 'jaxlib'))]\n"
        "assert not bad, f'linter pulled in jax: {bad}'\n")
    rc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stderr[-2000:]


def test_linter_reads_no_engine_env(monkeypatch):
    """Lint results are environment-independent (CI-cacheable): a full
    default run reads zero SPGEMM_TPU_* variables -- the knob table and
    CLI epilog render from registry metadata, not live values."""
    real = os.environ
    reads: list[str] = []

    class Tracker:
        def get(self, key, default=None):
            reads.append(key)
            return real.get(key, default)

        def __getitem__(self, key):
            reads.append(key)
            return real[key]

        def __contains__(self, key):
            reads.append(key)
            return key in real

        def __setitem__(self, key, value):  # pytest writes its own vars
            real[key] = value

        def __delitem__(self, key):
            del real[key]

        def __iter__(self):
            return iter(dict(real))

        def keys(self):
            return real.keys()

        def items(self):
            return real.items()

        def copy(self):
            return real.copy()

    monkeypatch.setattr(os, "environ", Tracker())
    findings = core.lint_paths(core.default_paths(),
                               claude_md=os.path.join(REPO, "CLAUDE.md"))
    assert findings == []
    engine_reads = [k for k in reads if k.startswith("SPGEMM_TPU_")]
    assert engine_reads == [], engine_reads


def test_analysis_help_covers_every_rule_id():
    """The DOC half for the linter's own help: the epilog (generated from
    core.RULES) names every rule id."""
    assert docrules.check_analysis_help() == []
    from spgemm_tpu.analysis.__main__ import build_parser
    help_text = build_parser().format_help()
    for rule in core.RULES:
        assert rule in help_text


# ------------------------------------------- review-hardening regressions --
def test_interprocedural_fld_taint_survives_call_cycle(tmp_path):
    """Regression: memoizing the in-progress None used to cut cycles
    finalized an ancestor as clean when its only route to a reduction ran
    through the cycle -- the call site a -> b -> d -> jnp.sum was silently
    missed whenever b's back-edge to a was visited first."""
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "u64.py").write_text(
        "import helpa\n"
        "def entry(x):\n"
        "    return helpa.a_fn(x)\n")
    (tmp_path / "helpa.py").write_text(
        "import helpb\n"
        "def a_fn(x):\n"
        "    return helpb.b_fn(x)\n")
    (tmp_path / "helpb.py").write_text(
        "import helpa\n"
        "import helpd\n"
        "def b_fn(x):\n"
        "    helpa.a_fn(x)\n"          # cycle edge, visited first
        "    return helpd.d_fn(x)\n")  # the route to the reduction
    (tmp_path / "helpd.py").write_text(
        "import jax.numpy as jnp\n"
        "def d_fn(x):\n"
        "    return jnp.sum(x)\n")
    findings = core.lint_paths([str(tmp_path)], doc=False)
    assert [f.rule for f in findings] == ["FLD"]
    assert findings[0].file.endswith("ops/u64.py")
    assert "a_fn -> b_fn -> d_fn -> `jnp.sum`" in findings[0].message


def test_thr_local_shadow_of_guarded_global_not_flagged(tmp_path):
    """Regression: a plain local that shadows a guarded module global is
    the LOCAL on every use (no `global` declaration), so THR must not
    fire on it -- while `global X` rebinding stays checked, including
    from a nested def closing over the shadowing scope."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_CACHE = {}  # spgemm-lint: guarded-by(_LOCK)\n"
        "_LOCK = threading.Lock()\n"
        "def local_shadow():\n"
        "    _CACHE = {}\n"          # a plain local, not the global
        "    _CACHE['x'] = 1\n"      # must NOT be a finding
        "    def inner():\n"
        "        return _CACHE\n"    # closure over the local: clean too
        "    return inner\n"
        "def global_rebind():\n"
        "    global _CACHE\n"
        "    _CACHE = {}\n"          # THE global, unguarded: finding
        "def global_read():\n"
        "    return len(_CACHE)\n")  # the global, unguarded: finding
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["THR", "THR"]
    assert [f.line for f in findings] == [12, 14]


def test_exc_ble_reason_on_wrapped_handler_clause(tmp_path):
    """Regression: a handler whose caught-type tuple wraps across lines
    carries its justification on the clause's LAST line -- it must count
    (a reformat of a justified handler must not break lint)."""
    p = tmp_path / "h.py"
    p.write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except (ValueError,\n"
        "            Exception):  # noqa: BLE001 -- seeded: wrapped clause\n"
        "        pass\n")
    assert lint_file(str(p)) == []


def test_thr_parameter_shadow_of_guarded_global_not_flagged(tmp_path):
    """Regression: a function PARAMETER named like a guarded module global
    is the local on every use -- THR must not fire on it."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_COUNT = 0  # spgemm-lint: guarded-by(_LOCK)\n"
        "_LOCK = threading.Lock()\n"
        "def param_shadow(_COUNT):\n"
        "    return _COUNT + 1\n"       # the parameter, not the global
        "def star_shadow(*_COUNT, **kw):\n"
        "    return len(_COUNT)\n"      # vararg parameter: local too
        "def real_read():\n"
        "    return _COUNT\n")          # THE global, unguarded: finding
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["THR"]
    assert findings[0].line == 9


def test_thr_init_not_exempt_for_module_globals(tmp_path):
    """Regression: __init__'s exemption holds only for the instance's own
    attributes (construction happens-before publication); a module global
    is already published to every thread while __init__ runs, so an
    unguarded write there is a real lost-update race -- a finding."""
    p = tmp_path / "h.py"
    p.write_text(
        "import threading\n"
        "_COUNT = 0  # spgemm-lint: guarded-by(_LOCK)\n"
        "_LOCK = threading.Lock()\n"
        "class Reg:\n"
        "    def __init__(self):\n"
        "        global _COUNT\n"
        "        _COUNT += 1\n"         # global in a ctor: still a finding
        "        self.n = _COUNT\n")
    findings = lint_file(str(p))
    assert [f.rule for f in findings] == ["THR", "THR"]
    assert [f.line for f in findings] == [7, 8]


def test_fld_proof_two_lines_above_interprocedural_finding_is_stale(tmp_path):
    """Regression: an fld-proof escape TWO lines above a tainted call
    suppresses nothing (escapes bind to their line and the one below) --
    the finding must still fire AND the escape must be reported stale,
    not vouched for by a widened used-window."""
    (tmp_path / "ops").mkdir()
    (tmp_path / "ops" / "u64.py").write_text(
        "import farhelp\n"
        "def entry(x):\n"
        "    # spgemm-lint: fld-proof(too far away to bind)\n"
        "    y = x\n"
        "    return farhelp.hidden(y)\n")
    (tmp_path / "farhelp.py").write_text(
        "import jax.numpy as jnp\n"
        "def hidden(x):\n"
        "    return jnp.sum(x)\n")
    findings, suppressions = core.lint_report([str(tmp_path)], doc=False)
    assert sorted(f.rule for f in findings) == ["FLD", "SUP"]
    assert len(suppressions) == 1 and suppressions[0].stale
