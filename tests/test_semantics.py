"""numpy oracle (utils/semantics) vs the dead-simple python-int reference."""

import numpy as np

from spgemm_tpu.utils import semantics as sem
from spgemm_tpu.utils.gen import random_values


def test_mulmod_addmod_np_vs_scalar():
    rng = np.random.default_rng(10)
    a = random_values(512, rng, "full")
    b = random_values(512, rng, "full")
    got = sem.mulmod_np(a, b)
    want = np.array([sem.scalar_mac(0, int(x), int(y)) for x, y in zip(a, b)],
                    dtype=np.uint64)
    assert np.array_equal(got, want)


def test_tile_pair_mac_np_vs_scalar_tile():
    rng = np.random.default_rng(11)
    k = 4
    for dist in ("full", "small", "adversarial"):
        a_tile = random_values((k, k), rng, dist)
        b_tile = random_values((k, k), rng, dist)
        acc0 = random_values((k, k), rng, dist)
        got = sem.tile_pair_mac_np(acc0.copy(), a_tile, b_tile)
        want = np.array(sem.scalar_tile_matmul(acc0, a_tile, b_tile), dtype=np.uint64)
        assert np.array_equal(got, want), dist


def test_spgemm_oracle_small_dense_identity():
    k = 2
    ident = {(0, 0): np.eye(k, dtype=np.uint64), (1, 1): np.eye(k, dtype=np.uint64)}
    rng = np.random.default_rng(12)
    m = {(0, 0): random_values((k, k), rng, "small"),
         (0, 1): random_values((k, k), rng, "small"),
         (1, 0): random_values((k, k), rng, "small")}
    out = sem.spgemm_oracle(ident, m, k)
    assert set(out.keys()) == set(m.keys())
    for key in m:
        assert np.array_equal(out[key], m[key])


def test_spgemm_oracle_pair_order_is_j_ascending():
    """Construct a case where wrong pair order changes the result."""
    k = 1
    big = np.array([[0xFFFFFFFFFFFFFFFE]], dtype=np.uint64)
    one = np.array([[1]], dtype=np.uint64)
    # output (0,0) accumulates j=0 then j=1: order affects the wrap quirk
    a = {(0, 0): big, (0, 1): one}
    b = {(0, 0): big, (1, 0): big}
    out = sem.spgemm_oracle(a, b, k)
    # manual fold in j-ascending order
    acc = sem.scalar_mac(0, int(big[0, 0]), int(big[0, 0]))
    acc = sem.scalar_mac(acc, 1, int(big[0, 0]))
    assert int(out[(0, 0)][0, 0]) == acc


def test_chain_oracle_odd_carry():
    rng = np.random.default_rng(13)
    k = 2
    mats = []
    for _ in range(5):
        mats.append({(0, 0): random_values((k, k), rng, "full")})
    got = sem.chain_oracle(mats, k)
    # helper2 pairing for 5: ((M0 M1)(M2 M3)) then ((P0 P1) M4) -> ((P01) M4)
    p0 = sem.spgemm_oracle(mats[0], mats[1], k)
    p1 = sem.spgemm_oracle(mats[2], mats[3], k)
    q0 = sem.spgemm_oracle(p0, p1, k)
    want = sem.spgemm_oracle(q0, mats[4], k)
    assert np.array_equal(got[(0, 0)], want[(0, 0)])
