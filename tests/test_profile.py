"""L5 deep profiling (spgemm_tpu/obs/profile.py + obs/events.py):
compile/cost/memory accounting, prediction accountability, the
structured event log's rotation bound, and the whole layer's inertness
under SPGEMM_TPU_OBS_TRACE=0 (the satellite-mandated degradation
coverage: memory_stats absent/raising never crashes and omits the
gauges; the event log honors its byte cap; disabled means flat)."""

import json
import os

import numpy as np
import pytest

from spgemm_tpu.obs import events, metrics, profile, trace
from spgemm_tpu.utils.gen import random_block_sparse


@pytest.fixture(autouse=True)
def clean_accounts():
    profile.clear()
    events.LOG.clear()
    trace.RECORDER.clear()
    yield
    profile.clear()
    events.LOG.clear()
    trace.RECORDER.clear()


def _spgemm_once(seed=0, k=4, dim=6):
    from spgemm_tpu.ops.spgemm import spgemm

    rng = np.random.default_rng(seed)
    a = random_block_sparse(dim, dim, k, 0.4, rng, "small")
    b = random_block_sparse(dim, dim, k, 0.4, rng, "small")
    return a, b, spgemm(a, b, backend="xla")


# ------------------------------------------------- compile accounting --
def test_compile_accounting_records_nonzero_cost():
    """One CPU multiply lands compile records for the numeric round with
    compile wall, cost-model FLOPs, and the jit-static knob vector --
    the acceptance shape `cli profile --json` reports."""
    _spgemm_once(seed=1)
    rep = profile.report()
    sites = rep["compile_sites"]
    assert "numeric_round" in sites
    agg = sites["numeric_round"]
    assert agg["count"] >= 1
    assert agg["flops_total"] > 0
    assert agg["seconds"]["count"] == agg["count"]
    assert agg["seconds"]["sum"] > 0
    recs = [r for r in rep["compiles"] if r["site"] == "numeric_round"]
    assert recs and recs[0]["flops"] > 0
    assert "SPGEMM_TPU_VPU_ALGO" in recs[0]["static_knobs"]
    # memory_analysis works on CPU: argument/output bytes are real
    assert recs[0]["argument_bytes"] > 0
    # a repeat of the same shapes compiles nothing new
    n_before = sum(a["count"] for a in sites.values())
    _spgemm_once(seed=1)
    n_after = sum(a["count"]
                  for a in profile.report()["compile_sites"].values())
    assert n_after == n_before


def test_profiled_jit_bit_identical_to_plain_jit():
    """The AOT-accounted dispatch path returns the same bits as the
    plain jit call (the oracle parity of the wrapped engine is pinned
    elsewhere; this pins the wrapper itself)."""
    import jax
    import jax.numpy as jnp

    def f(x, y):
        return x * 2 + y

    plain = jax.jit(f)
    wrapped = profile.ProfiledJit("test_site", jax.jit(f))
    x = jnp.arange(12, dtype=jnp.uint32).reshape(3, 4)
    y = jnp.ones((3, 4), jnp.uint32)
    assert (np.asarray(wrapped(x, y)) == np.asarray(plain(x, y))).all()
    assert profile.compile_stats()["test_site"]["count"] == 1
    # second call: cached executable, no new record
    wrapped(x, y)
    assert profile.compile_stats()["test_site"]["count"] == 1
    # new shape: one more record
    wrapped(x[:2], y[:2])
    assert profile.compile_stats()["test_site"]["count"] == 2


def test_profiled_jit_degrades_on_unloweable_fn():
    """A callable without the AOT surface is dispatched untouched --
    accounting must never break dispatch."""
    calls = []

    def plain(x):
        calls.append(x)
        return x + 1

    wrapped = profile.ProfiledJit("broken_site", plain)
    assert wrapped(1) == 2 and calls == [1]
    assert "broken_site" not in profile.compile_stats()


# ------------------------------------------------- memory watermarks --
def test_memory_absent_on_cpu_omits_gauges_never_crashes():
    """The CPU backend's memory_stats() returns None: the engine's
    sampling must record nothing, report unavailable, and the scrape
    must omit the HBM gauges (not render zeros)."""
    _spgemm_once(seed=2)
    mem = profile.memory_stats()
    assert mem["available"] is False and mem["samples"] == 0
    profile.memory_job_begin("job-x")  # no-op while unavailable
    assert profile.memory_job_peak("job-x") is None
    assert profile.memory_job_peak(None) is None
    text = metrics.render(metrics.collect_engine())
    assert "spgemm_hbm_bytes_in_use" not in text
    assert "spgemm_hbm_peak_bytes" not in text
    # the sample counter still renders (0 = backend never reported)
    assert "spgemm_hbm_samples_total 0" in text


def test_memory_observation_feeds_watermarks_and_job_window():
    """A backend that DOES report feeds the gauges, the process peak,
    and the per-job window -- keyed by the emitting thread's span
    job_id tag, so a wedged predecessor's late sample lands in ITS
    window, never the current job's (exercised with pushed readings --
    the jax-side sampler is a thin try/except around memory_stats)."""
    profile.observe_memory({"bytes_in_use": 100, "peak_bytes_in_use": 120})
    profile.memory_job_begin("job-b")
    with trace.RECORDER.tagged(job_id="job-b"):
        profile.observe_memory({"bytes_in_use": 500,
                                "peak_bytes_in_use": 600})
        profile.observe_memory({"bytes_in_use": 300})
    mem = profile.memory_stats()
    assert mem["available"] is True and mem["samples"] == 3
    assert mem["bytes_in_use"] == 300
    assert mem["peak_bytes"] == 600
    assert profile.memory_job_peak("job-b") == 500  # window opened at 100
    # cross-job attribution: a late sample tagged with the OLD job's id
    # (a wedged executor unwedging) must not move the new job's window
    with trace.RECORDER.tagged(job_id="job-a"):
        profile.observe_memory({"bytes_in_use": 9000})
    assert profile.memory_job_peak("job-b") == 500
    assert profile.memory_job_peak("job-a") == 9000
    text = metrics.render(metrics.collect_engine())
    assert "spgemm_hbm_bytes_in_use 9000" in text
    assert "spgemm_hbm_peak_bytes 9000" in text
    # malformed / None readings are ignored, never a crash
    profile.observe_memory(None)
    profile.observe_memory({"weird": 1})
    assert profile.memory_stats()["samples"] == 4


# -------------------------------------------- prediction accountability --
def test_estimator_accuracy_scored_when_exact_join_lands(monkeypatch):
    """An estimator-routed plan is scored against the exact join at
    ensure_exact time: one observation per estimate, per quantity."""
    from spgemm_tpu.ops import plancache
    from spgemm_tpu.ops.spgemm import plan as plan_spgemm

    monkeypatch.setenv("SPGEMM_TPU_EST_SAMPLE_ROWS", "8")
    plancache.clear()
    rng = np.random.default_rng(3)
    a = random_block_sparse(24, 24, 4, 0.3, rng, "small")
    b = random_block_sparse(24, 24, 4, 0.3, rng, "small")
    p = plan_spgemm(a, b, backend="xla", platform="cpu")
    assert p.plan_route == "estimated"
    assert profile.est_stats()["count"] == 0  # join not landed yet
    p.ensure_exact()
    est = profile.est_stats()
    assert est["count"] == 1
    assert set(est["rel_error"]) == {"keys", "pairs", "fanout"}
    for hist in est["rel_error"].values():
        assert hist["count"] == 1
    text = metrics.render(metrics.collect_engine())
    assert 'spgemm_est_rel_error_count{quantity="keys"} 1' in text
    # a REJECTED estimate (low confidence -> inline join_fallback) never
    # steered the plan and must not bias the drift-alert series
    monkeypatch.setenv("SPGEMM_TPU_EST_CONFIDENCE", "2")  # force fallback
    plancache.clear()
    p2 = plan_spgemm(a, b, backend="xla", platform="cpu")
    assert p2.plan_route == "exact" and p2.estimate is not None
    assert profile.est_stats()["count"] == 1  # unchanged


def test_delta_accountability_and_fallback_reasons(monkeypatch):
    """Delta multiplies observe their predicted-dirty fraction (a full
    fallback observes 1.0, an unchanged repeat 0.0) with the fallback
    reason counted in delta.stats() and the event log, and executed ==
    predicted always (mispredictions stay 0 by construction)."""
    from spgemm_tpu.ops import delta
    from spgemm_tpu.ops.spgemm import spgemm_device

    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    delta.clear()
    a, b, _ = _spgemm_once(seed=4)
    # first contact was a fallback (reason no_entry, fraction 1.0);
    # second submit of identical operands is a delta hit with an empty
    # diff (fraction 0.0)
    spgemm_device(a, b)
    dlt = profile.delta_stats()
    assert dlt["count"] >= 2
    assert dlt["mispredictions"] == 0
    frac = dlt["dirty_fraction"]
    assert frac["buckets"][0.0] >= 1  # the empty-diff repeat
    assert frac["count"] > frac["buckets"][0.9]  # the 1.0 fallback
    assert delta.stats()["fallback_reasons"].get("no_entry", 0) >= 1
    kinds = [r["kind"] for r in events.LOG.tail(100)]
    assert "delta_fallback" in kinds
    text = metrics.render(metrics.collect_engine())
    assert "spgemm_delta_dirty_fraction_count" in text
    assert "spgemm_delta_mispredictions_total 0" in text


# ------------------------------------------------------- phase histogram --
def test_phase_histogram_fed_from_spans():
    from spgemm_tpu.utils.timers import PhaseTimers

    t = PhaseTimers()
    t.record("plan", 0.005)
    t.record("plan", 2.0)
    hist = profile.phase_stats()["plan"]
    assert hist["count"] == 2
    assert hist["buckets"][0.01] == 1  # the 5 ms entry
    text = metrics.render(metrics.collect_engine())
    assert 'spgemm_phase_seconds_count{phase="plan"} 2' in text


def test_phase_histogram_admits_only_declared_names():
    """Ad-hoc PhaseTimers instances (the run-once CLI's local driver
    phases) flow through the recorder but are outside the MET registry:
    they must not mint undeclared label values on the declared-only
    spgemm_phase_seconds family."""
    from spgemm_tpu.utils.timers import PhaseTimers

    t = PhaseTimers()
    t.record("driver-local-load", 0.5)  # undeclared: span only
    t.record("assembly", 0.5)           # declared
    assert set(profile.phase_stats()) == {"assembly"}


# ------------------------------------------------------------ event log --
def test_event_log_rotation_honors_cap(tmp_path, monkeypatch):
    """The on-disk JSONL rotates at SPGEMM_TPU_OBS_EVENTS_MAX_KB: the
    live file stays under ~cap, one .1 generation holds the overflow --
    bounded disk under a resident daemon."""
    monkeypatch.setenv("SPGEMM_TPU_OBS_EVENTS_MAX_KB", "1")  # 1 KiB
    path = str(tmp_path / "d.events.jsonl")
    events.LOG.configure(path)
    payload = "x" * 100
    for i in range(64):
        events.emit("test_event", i=i, payload=payload)
    assert events.LOG.flush(timeout=10)  # the writer thread owns the file
    st = events.LOG.stats()
    assert st["rotations"] >= 1
    assert os.path.exists(path) and os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 1024 + 200  # cap + one record slack
    assert os.path.getsize(path + ".1") <= 1024 + 200
    # every line of the live file is valid JSON with seq/ts/kind
    with open(path, encoding="utf-8") as f:
        for line in f:
            rec = json.loads(line)
            assert {"seq", "ts", "mono_us", "kind"} <= set(rec)
    # the in-process ring is bounded too
    assert st["ring"] <= events.EventLog.RING_RETAIN


def test_event_log_carries_trace_tags():
    """Auto-correlation: an event emitted inside a tagged job context
    carries the job/trace ids without the call site passing them."""
    with trace.RECORDER.tagged(job_id="job-5", trace_id="tr-5"):
        events.emit("test_event", detail="hello")
    (rec,) = events.LOG.tail(1)
    assert rec["job_id"] == "job-5" and rec["trace_id"] == "tr-5"
    assert rec["detail"] == "hello" and rec["kind"] == "test_event"


def test_event_log_disabled_by_its_knob(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_OBS_EVENTS", "0")
    events.emit("test_event")
    assert events.LOG.stats()["emitted"] == 0
    assert events.LOG.tail(10) == []


def test_event_write_errors_counted_not_raised(tmp_path):
    """A dead file sink loses log lines, never the emitter (the daemon
    must survive a full disk); emit() itself does no file I/O -- the
    failure lands on the writer thread and is counted."""
    events.LOG.configure(str(tmp_path / "no_such_dir" / "e.jsonl"))
    events.emit("test_event")
    events.LOG.flush(timeout=10)
    st = events.LOG.stats()
    assert st["write_errors"] == 1 and st["emitted"] == 1
    assert events.LOG.tail(1)[0]["kind"] == "test_event"  # ring still fed


def test_event_sink_recovers_after_file_vanishes(tmp_path, monkeypatch):
    """An operator cleaner removing the live JSONL mid-run must not
    wedge the sink: the failed rotation resyncs the tracked size and
    the next append recreates the file."""
    monkeypatch.setenv("SPGEMM_TPU_OBS_EVENTS_MAX_KB", "1")
    path = str(tmp_path / "v.events.jsonl")
    events.LOG.configure(path)
    payload = "x" * 200
    for i in range(4):  # ~900 tracked bytes, just under the 1 KiB cap
        events.emit("test_event", i=i, payload=payload)
    assert events.LOG.flush(timeout=10)
    os.remove(path)
    for i in range(8):  # the first over-cap line hits the dead rotation
        events.emit("test_event", i=i, payload=payload)
    assert events.LOG.flush(timeout=10)
    st = events.LOG.stats()
    assert os.path.exists(path), "sink never recovered the file"
    assert os.path.getsize(path) > 0
    # at most the one line riding the failed rotation was lost
    assert st["write_errors"] <= 1


def test_event_rotation_accounting_is_byte_accurate(tmp_path, monkeypatch):
    """Non-ASCII payloads (paths, repr'd exceptions) are budgeted in
    utf-8 BYTES, not str characters -- the on-disk file must not exceed
    the documented cap by the multibyte inflation factor."""
    monkeypatch.setenv("SPGEMM_TPU_OBS_EVENTS_MAX_KB", "1")
    path = str(tmp_path / "u.events.jsonl")
    events.LOG.configure(path)
    payload = "é" * 120  # 2 bytes each in utf-8
    for i in range(32):
        events.emit("test_event", i=i, payload=payload)
    assert events.LOG.flush(timeout=10)
    assert os.path.getsize(path) <= 1024 + 600  # cap + one record slack
    assert events.LOG.stats()["rotations"] >= 1


# -------------------------------------------------- master-knob inertness --
def test_profile_layer_inert_under_obs_trace_zero(monkeypatch):
    """SPGEMM_TPU_OBS_TRACE=0 makes the WHOLE deep-profiling layer
    inert: no compile records, no memory/accuracy/phase observations --
    and the engine still computes bit-identically."""
    monkeypatch.setenv("SPGEMM_TPU_OBS_TRACE", "0")
    a, b, got = _spgemm_once(seed=5)
    profile.observe_memory({"bytes_in_use": 100})
    profile.observe_estimate(1, 1, 1, 2, 2, 2)
    profile.observe_delta(1, 1, 2)
    rep = profile.report()
    assert rep["enabled"] is False
    assert rep["compiles"] == [] and rep["compile_sites"] == {}
    assert rep["memory"]["samples"] == 0
    assert rep["estimator"]["count"] == 0
    assert rep["delta"]["count"] == 0
    assert profile.phase_stats() == {}
    # parity: the disabled layer changed no bits
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
    from spgemm_tpu.utils.semantics import spgemm_oracle

    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    assert got == want


# ------------------------------------------------------ report plumbing --
def test_report_and_summary_are_json_serializable():
    _spgemm_once(seed=6)
    events.emit("test_event")
    json.dumps(profile.report())
    json.dumps(profile.summary())
    assert profile.summary()["compiles"] >= 1
