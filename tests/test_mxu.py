"""MXU field-mode numeric phase (ops/mxu_spgemm.py) and the hybrid backend.

Field-mode ground truth is python-int arithmetic mod (2^64 - 1); reference-
mode ground truth is utils/semantics.spgemm_oracle.  The hybrid backend must
be bit-exact against the REFERENCE oracle whenever it claims safety.
"""

import jax.numpy as jnp
import re

import numpy as np
import pytest

from spgemm_tpu.ops import u64
from spgemm_tpu.ops.mxu_spgemm import (
    limbs7, numeric_round_mxu, safe_exact_bound)
from spgemm_tpu.ops.spgemm import spgemm, spgemm_device
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import ADVERSARIAL_VALUES, random_block_sparse
from spgemm_tpu.utils.semantics import field_spgemm_oracle, spgemm_oracle

M = (1 << 64) - 1


def field_oracle(a: BlockSparseMatrix, b: BlockSparseMatrix) -> dict:
    """Clean mod-(2^64-1) SpGEMM oracle (shared python-int implementation)."""
    return field_spgemm_oracle(a.to_dict(), b.to_dict(), a.k)


def test_limbs7_roundtrip():
    rng = np.random.default_rng(0)
    vals = np.concatenate([
        rng.integers(0, 1 << 64, size=200, dtype=np.uint64),
        ADVERSARIAL_VALUES])
    hi, lo = u64.u64_to_hilo(vals)
    planes = limbs7(jnp.asarray(hi), jnp.asarray(lo))
    got = np.zeros(len(vals), dtype=object)
    for l, plane in enumerate(planes):
        got = got + (np.asarray(plane).astype(object) << (7 * l))
    assert all(int(g) == int(v) for g, v in zip(got, vals))


def test_numeric_round_mxu_adversarial():
    """Single-tile folds over adversarial values vs the python-int field oracle."""
    rng = np.random.default_rng(1)
    k = 8
    n_tiles, P = 6, 3
    idx = rng.integers(0, len(ADVERSARIAL_VALUES), size=(n_tiles, k, k))
    tiles = ADVERSARIAL_VALUES[idx]
    slab = np.concatenate([tiles, np.zeros((1, k, k), np.uint64)])
    hi, lo = u64.u64_to_hilo(slab)
    pa = rng.integers(0, n_tiles, size=(4, P)).astype(np.int32)
    pb = rng.integers(0, n_tiles, size=(4, P)).astype(np.int32)
    # pad one row with sentinels to cover the zero-contribution path
    pa[-1, 1:] = n_tiles
    pb[-1, 1:] = n_tiles

    oh, ol = numeric_round_mxu(jnp.asarray(hi), jnp.asarray(lo),
                               jnp.asarray(hi), jnp.asarray(lo),
                               jnp.asarray(pa), jnp.asarray(pb))
    got = u64.hilo_to_u64(np.asarray(oh), np.asarray(ol))

    for key in range(pa.shape[0]):
        want = [[0] * k for _ in range(k)]
        for p in range(P):
            at = slab[pa[key, p]]
            bt = slab[pb[key, p]]
            for i in range(k):
                for n_ in range(k):
                    s = want[i][n_]
                    for j in range(k):
                        s = (s + int(at[i, j]) * int(bt[j, n_])) % M
                    want[i][n_] = s
        assert np.array_equal(got[key], np.array(want, dtype=np.uint64)), key


def test_spgemm_mxu_vs_field_oracle():
    rng = np.random.default_rng(2)
    a = random_block_sparse(6, 6, 8, 0.4, rng, "full")
    b = random_block_sparse(6, 6, 8, 0.4, rng, "full")
    c = spgemm(a, b, backend="mxu")
    want = field_oracle(a, b)
    assert set(map(tuple, c.coords.tolist())) == set(want.keys())
    cd = c.to_dict()
    for key, tile in want.items():
        assert np.array_equal(cd[key], tile), key


def test_hybrid_small_values_bit_exact_and_uses_mxu(caplog):
    import logging
    rng = np.random.default_rng(3)
    a = random_block_sparse(8, 8, 8, 0.5, rng, "small")
    b = random_block_sparse(8, 8, 8, 0.5, rng, "small")
    with caplog.at_level(logging.INFO, logger="spgemm_tpu.spgemm"):
        c = spgemm(a, b, backend="hybrid")
    m = re.search(r"spgemm\[hybrid mxu=(\d+)/(\d+)\]", caplog.text)
    assert m and m.group(1) == m.group(2) != "0"  # every round ran the proof
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    assert c == want  # bit-exact REFERENCE semantics via the MXU path


def test_hybrid_full_values_falls_back_to_exact(caplog):
    import logging
    rng = np.random.default_rng(4)
    a = random_block_sparse(6, 6, 8, 0.4, rng, "full")
    b = random_block_sparse(6, 6, 8, 0.4, rng, "full")
    with caplog.at_level(logging.INFO, logger="spgemm_tpu.spgemm"):
        c = spgemm(a, b, backend="hybrid")
    m = re.search(r"spgemm\[hybrid mxu=(\d+)/(\d+)\]", caplog.text)
    assert m and m.group(1) == "0"  # no round provable at full range
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    assert c == want


def test_hybrid_chain_bound_propagation():
    """Level-1 multiplies of a small-valued chain may ride the MXU; the
    propagated bound must force exact mode once safety is unprovable, and the
    end result must equal the reference chain oracle bit-for-bit."""
    from spgemm_tpu.chain import chain_product
    from spgemm_tpu.utils.semantics import chain_oracle

    rng = np.random.default_rng(5)
    mats = [random_block_sparse(6, 6, 8, 0.5, rng, "small") for _ in range(4)]
    got = chain_product(mats, backend="hybrid")
    want = BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, 8,
        chain_oracle([m.to_dict() for m in mats], 8))
    assert got == want


def test_hybrid_perf_gate_routes_to_measured_winner(tmp_path, monkeypatch,
                                                    caplog):
    """Under SPGEMM_TPU_HYBRID_GATE=auto a provably-safe round consults the
    measured crossover (ops/crossover.py): it must run the exact kernel
    when that measures faster, the MXU kernel when that wins -- and produce
    the reference-bit-exact result either way (VERDICT r3 #4: 'hybrid'
    never slower than the exact backend).  (Delta recompute pinned OFF:
    the repeated same-value multiply below must RE-DISPATCH so its
    routing log line exists -- the zero-diff shortcut is test_delta's
    subject.)"""
    import logging

    from spgemm_tpu.ops import crossover

    monkeypatch.setenv("SPGEMM_TPU_DELTA", "0")

    rng = np.random.default_rng(9)
    a = random_block_sparse(8, 8, 8, 0.5, rng, "small")
    b = random_block_sparse(8, 8, 8, 0.5, rng, "small")
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    monkeypatch.setenv("SPGEMM_TPU_HYBRID_GATE", "auto")

    for exact_s, mxu_s, expect_mxu in [(0.1, 0.2, False), (0.2, 0.1, True)]:
        cache_dir = tmp_path / f"e{exact_s}"
        monkeypatch.setenv("SPGEMM_TPU_CROSSOVER_CACHE", str(cache_dir))
        monkeypatch.setattr(crossover, "_CACHE", {})  # fresh in-process cache
        times = iter([exact_s, mxu_s] * 64)  # exact measured first, per key
        monkeypatch.setattr(crossover, "_time_call",
                            lambda fn, args, repeats=2: next(times))
        caplog.clear()
        with caplog.at_level(logging.INFO, logger="spgemm_tpu.spgemm"):
            c = spgemm(a, b, backend="hybrid")
        m = re.search(r"spgemm\[hybrid mxu=(\d+)/(\d+)\]", caplog.text)
        assert m, caplog.text
        n_mxu, n_rounds = int(m.group(1)), int(m.group(2))
        assert n_rounds > 0
        assert n_mxu == (n_rounds if expect_mxu else 0), (n_mxu, n_rounds)
        assert c == want  # bit-exact regardless of routing
        # the proven output bound must propagate whenever the PROOF held --
        # even when the speed gate routed every round to the exact kernel
        # (identical bits), so downstream chain multiplies stay provable
        from spgemm_tpu.ops.device import DeviceBlockMatrix
        dc = spgemm_device(DeviceBlockMatrix.from_host(a),
                           DeviceBlockMatrix.from_host(b), backend="hybrid")
        assert dc.val_bound < (1 << 64) - 2, (expect_mxu, dc.val_bound)
        # the decision is persisted: a fresh in-process cache re-reads it
        monkeypatch.setattr(crossover, "_CACHE", {})
        monkeypatch.setattr(
            crossover, "_time_call",
            lambda *a, **k: pytest.fail("re-measured despite disk cache"))
        caplog.clear()
        with caplog.at_level(logging.INFO, logger="spgemm_tpu.spgemm"):
            c2 = spgemm(a, b, backend="hybrid")
        m2 = re.search(r"spgemm\[hybrid mxu=(\d+)/(\d+)\]", caplog.text)
        assert m2 and int(m2.group(1)) == n_mxu
        assert c2 == want


def test_hybrid_proven_route_dispatches_nomod_pallas(tmp_path, monkeypatch,
                                                     caplog):
    """The exact_name == 'pallas' branch of _hybrid_setup (the one that
    actually dispatches the 28-op nomod kernel) is TPU-only in production;
    force it on CPU via resolve_backend + interpret-mode Pallas so the
    partial plumbing through choose_numeric is exercised in CI, end to end
    through the engine, with reference-bit-exact output."""
    import logging

    from spgemm_tpu.ops import crossover
    from spgemm_tpu.ops import spgemm as spgemm_mod

    rng = np.random.default_rng(11)
    a = random_block_sparse(6, 6, 4, 0.5, rng, "small")
    b = random_block_sparse(6, 6, 4, 0.5, rng, "small")
    monkeypatch.setenv("SPGEMM_TPU_HYBRID_GATE", "auto")
    monkeypatch.setenv("SPGEMM_TPU_CROSSOVER_CACHE", str(tmp_path))
    monkeypatch.setattr(crossover, "_CACHE", {})
    # exact backend resolves to the Pallas kernel (interpret mode on CPU);
    # an explicit backend name must still pass through untouched
    monkeypatch.setattr(spgemm_mod, "resolve_backend",
                        lambda be, platform=None:
                        "pallas" if be is None else be)
    times = iter([0.1, 0.2] * 64)  # exact (nomod) measures faster -> VPU
    monkeypatch.setattr(crossover, "_time_call",
                        lambda fn, args, repeats=2: next(times))
    with caplog.at_level(logging.INFO, logger="spgemm_tpu.spgemm"):
        c = spgemm(a, b, backend="hybrid")
    m = re.search(r"spgemm\[hybrid mxu=(\d+)/(\d+)\]", caplog.text)
    assert m and int(m.group(1)) == 0 and int(m.group(2)) > 0, caplog.text
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    assert c == want  # proven rounds ran the nomod pallas kernel, bit-exact


def test_safe_exact_bound():
    assert safe_exact_bound(0, 0, 4, 32) == 0
    assert safe_exact_bound(1, 1, 4, 32) == 128  # boolean adjacency
    # (2^32-1)^2 < 2^64-1: a single max-u32 product is still provably safe
    assert safe_exact_bound((1 << 32) - 1, (1 << 32) - 1, 1, 1) is not None
    assert safe_exact_bound(1 << 33, 1 << 33, 1, 1) is None
    assert safe_exact_bound((1 << 32) - 1, (1 << 32) - 1, 1, 2) is None
    small = (1 << 16) - 1
    out = safe_exact_bound(small, small, 9, 32)
    assert out is not None and out < (1 << 64) - 1


def test_pxk_cap_raises():
    k = 32
    hi = jnp.zeros((2, k, k), jnp.uint32)
    pa = jnp.zeros((1, 8192), jnp.int32)
    with pytest.raises(ValueError, match="int32-exact bound"):
        numeric_round_mxu(hi, hi, hi, hi, pa, pa)


def test_hybrid_mixed_fanout_per_round_dispatch(caplog):
    """A single huge-fanout key must no longer force every round off the
    MXU: rounds whose fanout class proves safe run field mode, the heavy
    round runs exact -- and the mixed result is still reference-bit-exact."""
    import logging

    rng = np.random.default_rng(5)
    k = 4
    a = random_block_sparse(12, 12, k, 0.25, rng, "small")
    b = random_block_sparse(12, 12, k, 0.25, rng, "small")
    # every tile gets value bound 2^30-1 (with_blocks below rebuilds tiles),
    # chosen so the per-fanout proof passes only for small fanout classes;
    # a dense A-row against a dense B-column adds fanout-12 keys that fail it
    big = np.uint64((1 << 30) - 1)
    dense_a = np.array([(0, j) for j in range(12)], np.int64)
    dense_b = np.array([(j, 0) for j in range(12)], np.int64)
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix as BSM
    def with_blocks(m, extra):
        coords = np.unique(np.concatenate([m.coords, extra]), axis=0)
        tiles = np.full((len(coords), k, k), big, np.uint64)
        return BSM.from_blocks(m.rows, m.cols, k, coords, tiles)
    a2, b2 = with_blocks(a, dense_a), with_blocks(b, dense_b)
    # proof math: bound=2^30-1, k=4 -> bound^2*k*fanout < 2^64-1 iff
    # fanout <= 4; the fanout-12 dense rounds must go exact, the small-
    # fanout rounds stay mxu
    with caplog.at_level(logging.INFO, logger="spgemm_tpu.spgemm"):
        c = spgemm(a2, b2, backend="hybrid")
    m = re.search(r"spgemm\[hybrid mxu=(\d+)/(\d+)\]", caplog.text)
    assert m, caplog.text
    n_mxu, n_rounds = int(m.group(1)), int(m.group(2))
    assert 0 < n_mxu < n_rounds, (n_mxu, n_rounds)  # genuinely mixed
    want = BlockSparseMatrix.from_dict(
        a2.rows, b2.cols, k, spgemm_oracle(a2.to_dict(), b2.to_dict(), k))
    assert c == want  # bit-exact reference semantics from the mixed dispatch


def test_time_call_reads_device_output(monkeypatch):
    """ADVICE r4 (medium): on this environment's TPU tunnel,
    block_until_ready acks at enqueue, so _time_call must fetch a scalar
    from every output leaf inside the timed region (kernel_sweep._digest
    pattern) or the crossover cache records dispatch latency as kernel
    time.  Pin that the digest touches each leaf of the timed call."""
    import jax.numpy as jnp

    from spgemm_tpu.ops import crossover

    fetched = []
    real_digest = crossover._digest
    monkeypatch.setattr(crossover, "_digest",
                        lambda out: fetched.append(real_digest(out)))

    def fn(x):
        return x + 1, x * 2

    dt = crossover._time_call(fn, (jnp.arange(4, dtype=jnp.uint32),))
    assert dt >= 0.0
    # warmup + 2 timed repeats, each through the digest
    assert len(fetched) == 3
    # and the digest really folds both leaves: (0+1) ^ (0*2) = 1
    assert fetched[0] == 1


def test_crossover_cache_keyed_by_path(tmp_path, monkeypatch):
    """ADVICE r4 (low): switching SPGEMM_TPU_CROSSOVER_CACHE mid-process
    must not leak entries between the old and new cache files."""
    import json

    from spgemm_tpu.ops import crossover

    monkeypatch.setattr(crossover, "_CACHE", {})
    dir_a, dir_b = tmp_path / "a", tmp_path / "b"

    monkeypatch.setenv("SPGEMM_TPU_CROSSOVER_CACHE", str(dir_a))
    crossover._load()["k1"] = {"exact_s": 1.0, "mxu_s": 2.0}
    crossover._save()

    monkeypatch.setenv("SPGEMM_TPU_CROSSOVER_CACHE", str(dir_b))
    assert "k1" not in crossover._load()  # no leak from dir_a
    crossover._load()["k2"] = {"exact_s": 3.0, "mxu_s": 1.0}
    crossover._save()

    with open(dir_a / "hybrid_crossover.json") as f:
        on_a = json.load(f)
    with open(dir_b / "hybrid_crossover.json") as f:
        on_b = json.load(f)
    assert set(on_a) == {"k1"} and set(on_b) == {"k2"}
    # and dir_a's in-memory view still serves its own entries
    monkeypatch.setenv("SPGEMM_TPU_CROSSOVER_CACHE", str(dir_a))
    assert "k1" in crossover._load() and "k2" not in crossover._load()
