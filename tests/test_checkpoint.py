"""Chain checkpoint/resume (utils/checkpoint + chain_product integration)."""

import os

import numpy as np

from spgemm_tpu.chain import chain_product
from spgemm_tpu.utils import checkpoint
from spgemm_tpu.utils.gen import random_chain


def test_save_load_roundtrip(tmp_path):
    rng = np.random.default_rng(400)
    mats = random_chain(3, 4, 2, 0.5, rng, "full")
    path = checkpoint.save_pass(str(tmp_path), 2, mats)
    assert os.path.exists(path)
    idx, loaded = checkpoint.latest_pass(str(tmp_path))
    assert idx == 2
    assert loaded == mats


def test_latest_pass_picks_newest(tmp_path):
    rng = np.random.default_rng(401)
    checkpoint.save_pass(str(tmp_path), 1, random_chain(2, 3, 2, 0.5, rng))
    mats3 = random_chain(1, 3, 2, 0.5, rng)
    checkpoint.save_pass(str(tmp_path), 3, mats3)
    idx, loaded = checkpoint.latest_pass(str(tmp_path))
    assert idx == 3 and loaded == mats3


def test_latest_pass_empty(tmp_path):
    assert checkpoint.latest_pass(str(tmp_path / "nope")) is None
    assert checkpoint.latest_pass(str(tmp_path)) is None


def test_latest_pass_falls_back_past_truncated_newest(tmp_path, caplog):
    """A corrupt/truncated newest pass_N.npz must not kill the resume: the
    loader falls back to the next-newest COMPLETE pass with a warning."""
    rng = np.random.default_rng(404)
    mats2 = random_chain(2, 3, 2, 0.5, rng, "full")
    checkpoint.save_pass(str(tmp_path), 2, mats2)
    path3 = checkpoint.save_pass(str(tmp_path), 3,
                                 random_chain(1, 3, 2, 0.5, rng, "full"))
    with open(path3, "r+b") as f:  # tear the newest file mid-archive
        f.truncate(os.path.getsize(path3) // 2)
    with caplog.at_level("WARNING", logger="spgemm_tpu.checkpoint"):
        idx, loaded = checkpoint.latest_pass(str(tmp_path))
    assert idx == 2 and loaded == mats2
    assert any("pass_3.npz" in r.getMessage() for r in caplog.records)


def test_latest_pass_all_corrupt_returns_none(tmp_path):
    (tmp_path / "pass_1.npz").write_bytes(b"not an npz at all")
    (tmp_path / "pass_2.npz").write_bytes(b"")
    assert checkpoint.latest_pass(str(tmp_path)) is None


def test_chain_resume_survives_truncated_newest(tmp_path):
    """End-to-end: chain_product resumes from the newest COMPLETE pass
    when the newest file is torn."""
    rng = np.random.default_rng(405)
    mats = random_chain(5, 4, 2, 0.5, rng, "full")
    want = chain_product(mats)
    arr = [chain_product(mats[i : i + 2]) for i in range(0, 4, 2)] + [mats[4]]
    ckdir = str(tmp_path / "ck")
    checkpoint.save_pass(ckdir, 1, arr)
    bad = checkpoint.save_pass(ckdir, 2, arr)  # pose as a newer, torn pass
    with open(bad, "r+b") as f:
        f.truncate(16)
    garbage = random_chain(5, 4, 2, 0.5, np.random.default_rng(998))
    assert chain_product(garbage, checkpoint_dir=ckdir) == want


def test_chain_with_checkpointing_matches_plain(tmp_path):
    rng = np.random.default_rng(402)
    mats = random_chain(5, 4, 2, 0.5, rng, "full")
    plain = chain_product(mats)
    ckpt = chain_product(mats, checkpoint_dir=str(tmp_path / "ck"))
    assert ckpt == plain
    # passes for n=5: 5 -> 3 -> 2 -> 1 (three snapshots)
    names = sorted(os.listdir(tmp_path / "ck"))
    assert names == ["pass_1.npz", "pass_2.npz", "pass_3.npz"]


def test_chain_resume_from_partial(tmp_path):
    """Kill after pass 1, restart -- result identical, passes 2..3 recomputed."""
    rng = np.random.default_rng(403)
    mats = random_chain(5, 4, 2, 0.5, rng, "full")
    want = chain_product(mats)

    # simulate the first pass only
    arr = [chain_product(mats[i : i + 2]) for i in range(0, 4, 2)] + [mats[4]]
    ckdir = str(tmp_path / "ck")
    checkpoint.save_pass(ckdir, 1, arr)

    # resume: input matrices are deliberately garbage to prove the resume path
    # is what produced the result
    garbage = random_chain(5, 4, 2, 0.5, np.random.default_rng(999))
    got = chain_product(garbage, checkpoint_dir=ckdir)
    assert got == want
