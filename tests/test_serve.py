"""spgemmd (serve/): protocol edge cases, admission control, watchdog
degrade paths, the warm-plan-cache serving proof, per-job timer scoping,
and journal-based restart resume -- all tier-1 on the 8-vdev CPU backend.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from spgemm_tpu.serve import client, protocol
from spgemm_tpu.serve.daemon import Daemon, journal_parse_line
from spgemm_tpu.serve.queue import (TERMINAL, Job, JobAbandoned, JobQueue,
                                    QueueFull)
from spgemm_tpu.utils import io_text
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_chain
from spgemm_tpu.utils.semantics import chain_oracle
from spgemm_tpu.utils.timers import PhaseTimers


def _chain_folder(tmp_path, n=3, k=2, seed=7, name="chain_in"):
    """A reference-format input dir + the oracle's output bytes."""
    mats = random_chain(n, 4, k, 0.5, np.random.default_rng(seed), "full")
    folder = str(tmp_path / name)
    io_text.write_chain_dir(folder, mats, k)
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_bytes = io_text.format_matrix(BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k, want).prune_zeros())
    return folder, want_bytes


@pytest.fixture
def make_daemon(tmp_path):
    """Daemon factory bound to a per-test socket; stops them on teardown."""
    daemons = []

    def _make(idx=0, **kw):
        d = Daemon(str(tmp_path / f"d{idx}.sock"), **kw)
        d.start()
        daemons.append(d)
        return d

    yield _make
    for d in daemons:
        d.stop()


def _raw_roundtrip(sock_path, payload: bytes) -> dict:
    """One raw line out, one response line back (no client validation)."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(10.0)
        s.connect(sock_path)
        try:
            s.sendall(payload)
        except BrokenPipeError:
            # the daemon may answer-and-close (busy reply, oversized-line
            # drop) before our bytes land; the response is still readable
            pass
        for line in protocol.read_lines(s):
            return json.loads(line)
    raise AssertionError("no response line")


def _wait_until(pred, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ------------------------------------------------------------- protocol --
def test_malformed_line_gets_error_and_daemon_survives(make_daemon):
    d = make_daemon(runner=lambda job, degraded=False: None)
    resp = _raw_roundtrip(d.socket_path, b"this is not json\n")
    assert resp["ok"] is False
    assert resp["error"]["code"] == protocol.E_BAD_REQUEST
    # same daemon, next request: still serving
    st = client.stats(d.socket_path)
    assert st["ok"] is True and st["daemon"] == "spgemmd"


def test_protocol_version_and_op_validation(make_daemon):
    d = make_daemon(runner=lambda job, degraded=False: None)
    resp = _raw_roundtrip(
        d.socket_path, json.dumps({"v": 99, "op": "stats"}).encode() + b"\n")
    assert resp["error"]["code"] == protocol.E_BAD_REQUEST
    assert "version" in resp["error"]["message"]
    resp = _raw_roundtrip(
        d.socket_path,
        json.dumps({"v": protocol.PROTOCOL_VERSION,
                    "op": "frobnicate"}).encode() + b"\n")
    assert resp["error"]["code"] == protocol.E_BAD_REQUEST
    assert "frobnicate" in resp["error"]["message"]


def test_submit_validation(tmp_path, make_daemon):
    d = make_daemon(runner=lambda job, degraded=False: None)
    # not a chain dir (no `size` file)
    with pytest.raises(client.ServeError) as ei:
        client.submit(str(tmp_path / "nowhere"), d.socket_path)
    assert ei.value.code == protocol.E_BAD_REQUEST
    # unknown option names are rejected, and named
    folder, _ = _chain_folder(tmp_path)
    with pytest.raises(client.ServeError) as ei:
        client.submit(folder, d.socket_path, {"round_sise": 4})
    assert ei.value.code == protocol.E_BAD_REQUEST
    assert "round_sise" in ei.value.message
    # unknown job id
    with pytest.raises(client.ServeError) as ei:
        client.status("job-999", d.socket_path)
    assert ei.value.code == protocol.E_UNKNOWN_JOB


def test_oversized_line_bounded_with_bad_request(make_daemon):
    """A newline-free byte stream past MAX_LINE_BYTES gets a structured
    bad-request and the connection dropped -- never an unbounded buffer in
    the device owner -- and the daemon keeps serving."""
    d = make_daemon(runner=lambda job, degraded=False: None)
    resp = _raw_roundtrip(d.socket_path,
                          b"x" * (protocol.MAX_LINE_BYTES + 2))
    assert resp["ok"] is False
    assert resp["error"]["code"] == protocol.E_BAD_REQUEST
    assert "exceeds" in resp["error"]["message"]
    assert client.stats(d.socket_path)["ok"] is True


def test_non_numeric_timeouts_are_bad_request(tmp_path, make_daemon):
    """timeout_s in submit options / timeout on wait that can't float()
    answer bad-request naming the value, not internal-error."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    with pytest.raises(client.ServeError) as ei:
        client.submit(folder, d.socket_path, {"timeout_s": "5s"})
    assert ei.value.code == protocol.E_BAD_REQUEST
    assert "5s" in ei.value.message
    j = client.submit(folder, d.socket_path)
    resp = _raw_roundtrip(
        d.socket_path,
        json.dumps({"v": protocol.PROTOCOL_VERSION, "op": "wait",
                    "id": j["id"], "timeout": "soon"}).encode() + b"\n")
    assert resp["error"]["code"] == protocol.E_BAD_REQUEST


def test_shutdown_op(make_daemon):
    d = make_daemon(runner=lambda job, degraded=False: None)
    resp = client.shutdown(d.socket_path)
    assert resp["stopping"] is True
    assert d._stop.is_set()


# ------------------------------------------------------- admission ctrl --
def test_queue_cap_overflow_returns_structured_rejection(tmp_path,
                                                         make_daemon):
    folder, _ = _chain_folder(tmp_path)
    gate = threading.Event()

    def runner(job, degraded=False):
        gate.wait(30)

    d = make_daemon(runner=runner, queue_cap=1)
    try:
        j1 = client.submit(folder, d.socket_path)
        _wait_until(lambda: d.queue.get(j1["id"]).state == "running",
                    msg="job-1 running")
        j2 = client.submit(folder, d.socket_path)  # fills the single slot
        assert j2["state"] == "queued"
        with pytest.raises(client.ServeError) as ei:
            client.submit(folder, d.socket_path)
        assert ei.value.code == protocol.E_QUEUE_FULL
        assert "SPGEMM_TPU_SERVE_QUEUE_CAP" in ei.value.message
    finally:
        gate.set()
    for j in (j1, j2):
        resp = client.wait(j["id"], d.socket_path, timeout=30)
        assert resp["job"]["state"] == "done"


def test_queue_fifo_and_counts():
    q = JobQueue(cap=2)
    a, b = Job("a", "f", "o", {}), Job("b", "f", "o", {})
    assert q.submit(a) == 1 and q.submit(b) == 2
    with pytest.raises(QueueFull):
        q.submit(Job("c", "f", "o", {}))
    assert q.next(0.01) is a and q.next(0.01) is b  # FIFO order
    assert q.next(0.01) is None
    a.start()
    a.finish("done")
    assert not a.finish("failed")  # terminal transitions are first-write-wins
    assert a.state == "done"
    assert q.counts() == {"queued": 1, "running": 0, "done": 1,
                          "failed": 0, "depth": 0}


# ------------------------------------------------ watchdog degrade paths --
def test_job_timeout_reaped_and_wedged_executor_degrades(tmp_path,
                                                         make_daemon):
    """A job past SPGEMM_TPU_SERVE_JOB_TIMEOUT is reaped with a structured
    job-timeout error; the executor still stuck on it counts as wedged,
    the daemon degrades to the CPU path and serves the next job."""
    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    calls = []

    def runner(job, degraded=False):
        calls.append((job.id, degraded))
        if not degraded:
            unwedge.wait(60)  # a hung backend call: never raises

    d = make_daemon(runner=runner, job_timeout_s=0.3, wedge_grace_s=0.2,
                    probe=lambda: "timeout")
    try:
        j1 = client.submit(folder, d.socket_path)
        resp = client.wait(j1["id"], d.socket_path, timeout=30)
        assert resp["job"]["state"] == "failed"
        assert resp["job"]["error"]["code"] == protocol.E_JOB_TIMEOUT
        _wait_until(lambda: d.degraded, msg="degrade after wedge grace")
        # the replacement executor serves the next job on the CPU path
        j2 = client.submit(folder, d.socket_path)
        resp = client.wait(j2["id"], d.socket_path, timeout=30)
        assert resp["job"]["state"] == "done"
        assert resp["job"]["detail"]["degraded"] is True
        assert (j2["id"], True) in calls
        st = client.stats(d.socket_path)
        assert st["degraded"] is True
        assert "wedged" in st["degrade_reason"]
        assert st["backend_probe"] == "timeout"
    finally:
        unwedge.set()


def test_heartbeating_executor_is_slow_not_wedged(tmp_path, make_daemon):
    """A reaped job whose executor keeps HEARTBEATING (chain progress:
    touch() after every multiply) is slow, not wedged -- the daemon must
    not degrade, and once the runner returns it serves on, healthy."""
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        # overruns a 0.2s deadline by far, but beats every 0.05s -- a
        # legitimately long chain, not a hung backend call
        deadline = time.time() + 2.0
        while time.time() < deadline and not release.is_set():
            job.touch()
            time.sleep(0.05)

    # grace 0.6 s vs 0.05 s beats: an order of magnitude of margin, so a
    # shared-host scheduling stall of the runner thread cannot flake a
    # heartbeating executor into a wedge verdict (observed at 0.3 s)
    d = make_daemon(runner=runner, job_timeout_s=0.2, wedge_grace_s=0.6,
                    probe=lambda: "should-never-run")
    try:
        j1 = client.submit(folder, d.socket_path)
        resp = client.wait(j1["id"], d.socket_path, timeout=30)
        assert resp["job"]["state"] == "failed"  # the deadline still binds
        assert resp["job"]["error"]["code"] == protocol.E_JOB_TIMEOUT
        time.sleep(1.0)  # several grace windows of heartbeating overrun
        assert d.degraded is False
    finally:
        release.set()
    # the same executor finishes the overrun job's runner and serves on
    j2 = client.submit(folder, d.socket_path, {"timeout_s": 0})
    resp = client.wait(j2["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "done"
    assert d.degraded is False


def test_submit_timeout_zero_overrides_daemon_default(tmp_path,
                                                      make_daemon):
    """timeout_s=0 in submit options means NO deadline (the knob's own
    semantics), even when the daemon carries a default -- only an absent
    option falls back."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None,
                    job_timeout_s=7.5)
    j0 = client.submit(folder, d.socket_path, {"timeout_s": 0})
    j1 = client.submit(folder, d.socket_path)
    assert d.queue.get(j0["id"]).timeout_s == 0.0   # explicit opt-out
    assert d.queue.get(j1["id"]).timeout_s == 7.5   # absent -> default


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_executor_death_fails_job_and_daemon_degrades(tmp_path,
                                                      make_daemon):
    """Kill the worker mid-job (BaseException escapes the per-job catch):
    the job fails with a structured error, the daemon degrades and still
    serves the next job, stats reports degraded."""
    folder, _ = _chain_folder(tmp_path)
    calls = []

    def runner(job, degraded=False):
        calls.append((job.id, degraded))
        if not degraded:
            raise KeyboardInterrupt  # kills the executor thread outright

    d = make_daemon(runner=runner, probe=lambda: "error")
    j1 = client.submit(folder, d.socket_path)
    resp = client.wait(j1["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "failed"
    assert resp["job"]["error"]["code"] == protocol.E_EXECUTOR_DIED
    j2 = client.submit(folder, d.socket_path)
    resp = client.wait(j2["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "done"
    assert (j2["id"], True) in calls
    st = client.stats(d.socket_path)
    assert st["degraded"] is True and "died" in st["degrade_reason"]


# ------------------------------------- the serving proof (real engine) --
def test_second_identical_submit_hits_warm_plan_cache(tmp_path,
                                                      make_daemon):
    """The tentpole acceptance: two submits of the same input through the
    real engine -- both bit-exact vs the oracle, and the second job's
    status detail proves the plan cache stayed warm across jobs."""
    from spgemm_tpu.ops import plancache

    folder, want_bytes = _chain_folder(tmp_path, n=3, k=2)
    plancache.clear()
    d = make_daemon()  # default runner: the real chain engine
    details = []
    for i in (1, 2):
        out = str(tmp_path / f"matrix.{i}")
        j = client.submit(folder, d.socket_path, {"output": out})
        resp = client.wait(j["id"], d.socket_path, timeout=120)
        assert resp["job"]["state"] == "done", resp["job"]["error"]
        assert open(out, "rb").read() == want_bytes
        details.append(resp["job"]["detail"])
    assert details[0]["plan_cache_misses"] >= 1  # cold first job
    assert details[1]["plan_cache_hits"] >= 1    # warm second job
    assert details[1]["degraded"] is False
    # the identical second submit is also the delta path's zero-diff
    # case: every output row carries over from the retained results
    assert details[0]["delta_rows"] == details[0]["total_rows"] > 0
    assert details[1]["delta_rows"] == 0
    assert details[1]["total_rows"] == details[0]["total_rows"]


def test_job_detail_phases_are_scoped_per_job(tmp_path, make_daemon,
                                              monkeypatch):
    """utils/timers accumulates process-wide; the daemon's PhaseScope diff
    must give each job its OWN phases and counters -- two sequential jobs
    of the same shape report (near-)equal dispatch counts, not cumulative
    ones, and the second job shows zero fresh planner misses.  (Delta
    recompute is pinned OFF: it would legitimately answer job 2 from the
    retained result with zero dispatches, which is tests/test_delta.py's
    subject, not this scoping contract's.)"""
    from spgemm_tpu.ops import plancache

    monkeypatch.setenv("SPGEMM_TPU_DELTA", "0")
    folder, _ = _chain_folder(tmp_path, n=3, k=2, seed=11, name="scoped_in")
    plancache.clear()
    d = make_daemon()
    details = []
    for i in (1, 2):
        out = str(tmp_path / f"m{i}")
        j = client.submit(folder, d.socket_path, {"output": out})
        resp = client.wait(j["id"], d.socket_path, timeout=120)
        assert resp["job"]["state"] == "done", resp["job"]["error"]
        details.append(resp["job"]["detail"])
    # identical work -> identical per-job dispatch counts; an unscoped
    # registry would report job2 = job1 + job2
    assert details[0]["dispatches"] == details[1]["dispatches"] > 0
    # job 1's planner misses must not bleed into job 2's detail
    assert details[0]["plan_cache_misses"] >= 1
    assert details[1]["plan_cache_misses"] == 0
    assert "plan" in details[0]["phases_s"]


def test_phase_scope_diffs_only_whats_new():
    """Unit contract of utils/timers.PhaseScope: pre-scope accumulation is
    invisible, post-scope accumulation is exact."""
    t = PhaseTimers()
    t.record("a", 1.0)
    t.incr("c", 2)
    s = t.scope()
    assert s.snapshot() == {} and s.counter_snapshot() == {}
    t.record("a", 0.5)
    t.record("b", 0.25)
    t.incr("c")
    assert s.snapshot() == {"a": 0.5, "b": 0.25}
    assert s.counter_snapshot() == {"c": 1}


def test_reaped_job_never_writes_its_output(tmp_path):
    """An abandoned wedged executor can unwedge long after its job was
    reaped and resubmitted: its chain must abort at the next multiply
    boundary (JobAbandoned rides the heartbeat) and the stale result must
    not clobber the output path a successor may own by now."""
    from spgemm_tpu.serve.daemon import run_chain_job

    folder, _ = _chain_folder(tmp_path)
    out = str(tmp_path / "stale_out")
    job = Job("job-x", folder, out, {})
    job.start()
    job.finish("failed", error={"code": protocol.E_JOB_TIMEOUT,
                                "message": "reaped"})
    with pytest.raises(JobAbandoned):  # the late-unwedging runner path
        run_chain_job(job, degraded=True)
    assert not os.path.exists(out)


def test_abandoned_chain_pierces_the_failover_catch(tmp_path):
    """JobAbandoned is a BaseException ON PURPOSE: chain_product's
    failover wrapper catches Exception (device loss) and must not mistake
    an abort for a failure to retry on the host oracle -- the abort must
    reach the executor loop, not restart the pass."""
    from spgemm_tpu.serve.daemon import run_chain_job

    folder, _ = _chain_folder(tmp_path, n=4)
    out = str(tmp_path / "stale_out2")
    job = Job("job-y", folder, out, {"failover": True})
    job.start()
    job.finish("failed", error={"code": protocol.E_JOB_TIMEOUT,
                                "message": "reaped"})
    with pytest.raises(JobAbandoned):
        run_chain_job(job)  # failover=True: Exception would be swallowed
    assert not os.path.exists(out)
    assert not issubclass(JobAbandoned, Exception)  # pierces catch-alls


# ------------------------------------------------------- journal resume --
def test_journal_submit_record_precedes_terminal_event(tmp_path,
                                                       make_daemon):
    """The submit record is journaled BEFORE the job is enqueued: even an
    instantly-finishing job's done event lands after it, so replay never
    resurrects finished work (events replay in file order)."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    j = client.submit(folder, d.socket_path)
    client.wait(j["id"], d.socket_path, timeout=30)
    events = [journal_parse_line(ln.strip())["event"] for ln in
              open(d.journal_path, encoding="utf-8")]
    assert events == ["submit", "done"]



def test_restart_requeues_unfinished_jobs_from_journal(tmp_path,
                                                       make_daemon):
    """A daemon restart re-queues journaled jobs that never reached a
    terminal state, keeps their ids, resumes their chains from the
    checkpoint dir wired through submit, and continues the id sequence."""
    folder, want_bytes = _chain_folder(tmp_path, n=5, k=2, seed=13)
    ckdir = str(tmp_path / "ck")
    out = str(tmp_path / "matrix.resume")
    sock = str(tmp_path / "dj.sock")

    # daemon 1: accept the submit but never run it (no threads started --
    # the journal record is what a crash leaves behind)
    d1 = Daemon(sock, runner=lambda job, degraded=False: None)
    resp = d1._op_submit({"op": "submit", "folder": folder,
                          "options": {"output": out,
                                      "checkpoint_dir": ckdir}})
    assert resp["ok"] and resp["id"] == "job-1"
    assert os.path.exists(d1.journal_path)

    # daemon 2 on the same socket: replay -> re-queue -> run for real
    d2 = Daemon(sock)
    d2.start()
    try:
        resp = client.wait("job-1", sock, timeout=120)
        assert resp["job"]["state"] == "done", resp["job"]["error"]
        assert open(out, "rb").read() == want_bytes
        # checkpoint_dir was wired through: per-pass snapshots exist, so a
        # NEXT restart would resume mid-chain instead of recomputing
        assert any(f.startswith("pass_") for f in os.listdir(ckdir))
        # id sequence continues after the replayed job
        j = client.submit(folder, sock, {"output": out + ".2"})
        assert j["id"] == "job-2"
        client.wait(j["id"], sock, timeout=120)
        # terminal events landed in the journal: a further restart would
        # re-queue nothing
        events = [journal_parse_line(ln.strip()) for ln in
                  open(d2.journal_path, encoding="utf-8")]
        done = {e["id"] for e in events if e["event"] == "done"}
        assert {"job-1", "job-2"} <= done
    finally:
        d2.stop()


def test_journal_compacts_at_runtime(tmp_path, make_daemon, monkeypatch):
    """A resident daemon must not grow its journal for its lifetime:
    every JOURNAL_COMPACT_EVERY terminal events the file is rewritten to
    only the still-live submit records."""
    monkeypatch.setattr(Daemon, "JOURNAL_COMPACT_EVERY", 4)
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    for _ in range(6):
        j = client.submit(folder, d.socket_path)
        client.wait(j["id"], d.socket_path, timeout=30)
    # terminal event #4 compacted submit/done pairs 1-4 away; only jobs
    # 5 and 6 (submitted after the compaction) remain on disk
    events = [journal_parse_line(ln.strip()) for ln in
              open(d.journal_path, encoding="utf-8")]
    assert len(events) == 4
    assert {e["id"] for e in events} == {"job-5", "job-6"}
    # every surviving submit has its terminal event: a restart from this
    # journal re-queues nothing
    done = {e["id"] for e in events if e["event"] == "done"}
    assert {e["id"] for e in events if e["event"] == "submit"} == done


# ------------------------------------------------ review-fix regressions --
def test_wedge_grace_comes_from_the_knob_registry(tmp_path, monkeypatch):
    """The slow-vs-wedged window is a deployment property (it must exceed
    the longest single multiply): a registry knob with a wide default,
    never a hardcoded second."""
    monkeypatch.setenv("SPGEMM_TPU_SERVE_WEDGE_GRACE_S", "7.5")
    assert Daemon(str(tmp_path / "g1.sock"))._wedge_grace_s == 7.5
    monkeypatch.delenv("SPGEMM_TPU_SERVE_WEDGE_GRACE_S")
    assert Daemon(str(tmp_path / "g2.sock"))._wedge_grace_s == 60.0
    d = Daemon(str(tmp_path / "g3.sock"), wedge_grace_s=0.2)
    assert d._wedge_grace_s == 0.2  # explicit override (tests) still wins


def test_reaped_slow_job_aborts_and_executor_serves_on(tmp_path,
                                                       make_daemon):
    """A reaped job's chain aborts at the next heartbeat: the SAME
    executor moves on to live work -- no degrade, no zombie computing a
    failed job's chain to completion."""
    folder, _ = _chain_folder(tmp_path)

    def runner(job, degraded=False):
        if job.id != "job-1":
            return
        while True:  # job-1: slow multiplies that beat, never a hang
            time.sleep(0.02)
            job.touch()
            if job.state in TERMINAL:
                raise JobAbandoned(job.id)

    d = make_daemon(runner=runner, job_timeout_s=0.2, wedge_grace_s=10.0,
                    probe=lambda: "should-never-run")
    j1 = client.submit(folder, d.socket_path)
    resp = client.wait(j1["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "failed"
    assert resp["job"]["error"]["code"] == protocol.E_JOB_TIMEOUT
    j2 = client.submit(folder, d.socket_path, {"timeout_s": 0})
    resp = client.wait(j2["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "done"
    assert d.degraded is False
    assert d.slices[0].gen == 1  # still the original executor thread


def test_reaped_job_keeps_its_phase_detail(tmp_path, make_daemon):
    """A watchdog-reaped job must not lose its per-job phases/counters:
    the one job an operator most needs to diagnose (it hit its deadline)
    still reports what it was doing."""
    from spgemm_tpu.utils.timers import ENGINE

    folder, _ = _chain_folder(tmp_path)
    wedged = threading.Event()

    def runner(job, degraded=False):
        ENGINE.record("numeric_dispatch", 0.125)
        ENGINE.incr("dispatches", 7)
        wedged.wait(30)  # hung backend call: no beats, no return

    d = make_daemon(runner=runner, job_timeout_s=0.2, wedge_grace_s=60.0,
                    probe=lambda: "x")
    try:
        j = client.submit(folder, d.socket_path)
        resp = client.wait(j["id"], d.socket_path, timeout=30)
        assert resp["job"]["state"] == "failed"
        assert resp["job"]["error"]["code"] == protocol.E_JOB_TIMEOUT
        det = resp["job"]["detail"]
        assert det["dispatches"] == 7
        assert det["phases_s"]["numeric_dispatch"] == 0.125
        assert det["degraded"] is False
    finally:
        wedged.set()


def test_bad_option_values_rejected_at_admission(tmp_path, make_daemon):
    """Option VALUES get the same early bad-request as option names: a
    bad round_size/backend must never become a late opaque job-error."""
    folder, _ = _chain_folder(tmp_path)
    d = make_daemon(runner=lambda job, degraded=False: None)
    for opts, fragment in (({"round_size": "abc"}, "round_size"),
                           ({"round_size": 0}, "round_size"),
                           ({"backend": "cuda"}, "cuda"),
                           # negative would silently mean "no deadline"
                           ({"timeout_s": -5}, "timeout_s")):
        with pytest.raises(client.ServeError) as ei:
            client.submit(folder, d.socket_path, opts)
        assert ei.value.code == protocol.E_BAD_REQUEST
        assert fragment in ei.value.message
    j = client.submit(folder, d.socket_path, {"round_size": 4,
                                              "backend": "xla"})
    assert client.wait(j["id"], d.socket_path,
                       timeout=30)["job"]["state"] == "done"


def test_relative_paths_resolve_client_side(tmp_path, make_daemon,
                                            monkeypatch):
    """The daemon's cwd is not the submitter's: a relative folder/output/
    checkpoint_dir must be resolved against the CLIENT's cwd before it
    goes on the wire, or the daemon checks (and writes!) the wrong
    tree."""
    _chain_folder(tmp_path)  # creates tmp_path/chain_in

    def runner(job, degraded=False):
        assert os.path.isabs(job.folder) and os.path.isabs(job.output)
        assert os.path.isabs(job.options["checkpoint_dir"])
        with open(job.output, "w", encoding="utf-8") as f:
            f.write("ok")

    d = make_daemon(runner=runner)  # daemon cwd: wherever pytest runs
    monkeypatch.chdir(tmp_path)     # client cwd: elsewhere
    j = client.submit("chain_in", d.socket_path,
                      {"output": "rel_out", "checkpoint_dir": "rel_ck"})
    resp = client.wait(j["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "done", resp["job"]["error"]
    assert (tmp_path / "rel_out").read_text() == "ok"


def test_server_side_wait_is_sliced(tmp_path, make_daemon, monkeypatch):
    """One server-side wait is clamped to MAX_WAIT_SLICE_S (a running
    snapshot is answered past it), so an abandoned waiter can never pin a
    connection slot until a deadline-less job terminates; client.wait
    polls in slices and still sees the terminal state."""
    monkeypatch.setattr(Daemon, "MAX_WAIT_SLICE_S", 0.2)
    monkeypatch.setattr(client, "WAIT_SLICE_S", 0.2)
    folder, _ = _chain_folder(tmp_path)
    release = threading.Event()

    def runner(job, degraded=False):
        release.wait(30)

    d = make_daemon(runner=runner)
    j = client.submit(folder, d.socket_path)
    # a raw wait with timeout null returns a RUNNING snapshot within the
    # slice instead of blocking the connection until the job ends
    t0 = time.time()
    resp = _raw_roundtrip(
        d.socket_path,
        protocol.encode({"v": protocol.PROTOCOL_VERSION, "op": "wait",
                         "id": j["id"], "timeout": None}))
    assert time.time() - t0 < 5.0
    assert resp["ok"] and resp["job"]["state"] in ("queued", "running")
    # the polling client still blocks through multiple slices to terminal
    waiter = {}

    def do_wait():
        waiter["resp"] = client.wait(j["id"], d.socket_path, timeout=30)

    t = threading.Thread(target=do_wait)
    t.start()
    time.sleep(0.6)  # several slices elapse while the job still runs
    release.set()
    t.join(timeout=30)
    assert waiter["resp"]["job"]["state"] == "done"


def test_terminal_jobs_evicted_beyond_retention(monkeypatch):
    """The job index must not grow for the daemon's lifetime: terminal
    jobs beyond RETAIN_TERMINAL are evicted (oldest first) at the next
    admission; live jobs are never touched."""
    monkeypatch.setattr(JobQueue, "RETAIN_TERMINAL", 2)
    q = JobQueue(cap=10)
    jobs = [Job(f"j{i}", "f", "o", {}) for i in range(5)]
    for j in jobs[:4]:
        q.submit(j)
        assert q.next(0.01) is j
        j.start()
        j.finish("done")
    q.submit(jobs[4])
    assert q.get("j0") is None and q.get("j1") is None  # evicted
    assert q.get("j2") is jobs[2] and q.get("j3") is jobs[3]  # retained
    assert q.get("j4") is jobs[4]  # live


def test_connection_bound_answers_busy(make_daemon, monkeypatch):
    """Past MAX_CONNS concurrent connections the daemon answers a
    structured busy error and closes -- a connect() loop exhausts the
    bound, not the device owner's threads -- and released connections
    free slots for live service."""
    monkeypatch.setattr(Daemon, "MAX_CONNS", 2)
    d = make_daemon(runner=lambda job, degraded=False: None)
    held = []
    try:
        for _ in range(2):
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(d.socket_path)
            held.append(s)
        _wait_until(lambda: d._conn_count == 2, msg="2 conns admitted")
        resp = _raw_roundtrip(
            d.socket_path,
            protocol.encode({"v": protocol.PROTOCOL_VERSION,
                             "op": "stats"}))
        assert resp["ok"] is False
        assert resp["error"]["code"] == protocol.E_BUSY
    finally:
        for s in held:
            s.close()
    _wait_until(lambda: d._conn_count == 0, msg="conns released")
    assert client.stats(d.socket_path)["ok"] is True


# ------------------------------------------- THR lock-discipline fixes --
def test_overdue_holds_the_job_lock():
    """Regression for the THR finding spgemm-lint v2 surfaced: overdue()
    read state/started_at lock-free while start()/finish() wrote them
    under _lock (a torn read could pair a stale state with a fresh
    started_at).  Pin the fix: overdue() participates in the job lock --
    it blocks while another thread holds it -- and stays consistent
    across a terminal transition."""
    job = Job("job-thr", "f", "o", {}, timeout_s=0.001)
    job.start()
    time.sleep(0.01)
    assert job.overdue()
    job._lock.acquire()
    got = []
    t = threading.Thread(target=lambda: got.append(job.overdue()),
                         daemon=True)
    t.start()
    t.join(timeout=0.3)
    try:
        assert t.is_alive(), "overdue() must wait for the job lock"
    finally:
        job._lock.release()
    t.join(timeout=5.0)
    assert got == [True]
    job.finish("failed", error={"code": "x", "message": "m"})
    assert not job.overdue()  # terminal: never overdue again


def test_stats_degrade_snapshot_holds_the_daemon_lock(tmp_path):
    """Regression for the THR finding on Daemon degrade state: _op_stats
    (and the executor's degraded read, and _spawn_executor's write) used
    degraded/degrade_reason/_probe_outcome lock-free against the
    watchdog's locked writes in _degrade.  Pin the fix the same way:
    the stats snapshot participates in the daemon lock."""
    d = Daemon(str(tmp_path / "d.sock"), journal=False)  # not started:
    # _op_stats needs no serving threads, so no teardown either
    d._lock.acquire()
    got = []
    t = threading.Thread(target=lambda: got.append(d._op_stats()),
                         daemon=True)
    t.start()
    t.join(timeout=0.3)
    try:
        assert t.is_alive(), "_op_stats must wait for the daemon lock"
    finally:
        d._lock.release()
    t.join(timeout=5.0)
    assert got and got[0]["ok"] is True
    assert got[0]["degraded"] is False and got[0]["degrade_reason"] is None


# --------------------------------------------------- L5 observability --
def test_concurrent_phase_scopes_are_disjoint():
    """The PR-7 PhaseScope fix, pinned with real threads: two scopes open
    CONCURRENTLY over one PhaseTimers (the watchdog-reaped job's wedged
    executor + the replacement executor's next job) must each see exactly
    their own thread's accumulation -- the old baseline-and-diff
    implementation reported both threads' overlap into both scopes."""
    t = PhaseTimers()
    start = threading.Barrier(2)
    scopes = {}

    def job(name, seconds, n):
        scope = t.scope()          # opened on THIS thread
        scopes[name] = scope
        start.wait(timeout=10)     # maximize overlap
        for _ in range(5):
            t.record(name, seconds)
            t.incr("dispatches", n)
        scope.close()

    threads = [threading.Thread(target=job, args=("ring_fold", 0.25, 1)),
               threading.Thread(target=job, args=("assembly", 0.5, 10))]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert scopes["ring_fold"].snapshot() == {"ring_fold": 1.25}
    assert scopes["ring_fold"].counter_snapshot() == {"dispatches": 5}
    assert scopes["assembly"].snapshot() == {"assembly": 2.5}
    assert scopes["assembly"].counter_snapshot() == {"dispatches": 50}
    # the process-wide registry still saw everything
    assert t.counter_snapshot()["dispatches"] == 55


def test_closed_scope_stops_collecting():
    t = PhaseTimers()
    with t.scope() as s:
        t.record("plan", 1.0)
    t.record("plan", 9.0)  # after close: not this scope's
    assert s.snapshot() == {"plan": 1.0}


def test_metrics_op_serves_prometheus_and_series_move(tmp_path,
                                                     make_daemon):
    """The scrapeable surface: text-format 0.0.4 with daemon gauges, and
    the per-phase + terminal-outcome series move across a job."""
    from spgemm_tpu.serve.obs_smoke import parse_prometheus
    from spgemm_tpu.utils.timers import ENGINE

    folder, _ = _chain_folder(tmp_path)

    def runner(job, degraded=False):
        ENGINE.record("numeric_dispatch", 0.125)
        ENGINE.incr("dispatches", 3)

    d = make_daemon(runner=runner)
    resp = client.request({"op": "metrics"}, d.socket_path)
    assert resp["ok"] is True
    assert resp["content_type"].startswith("text/plain; version=0.0.4")
    before = parse_prometheus(resp["text"])
    assert before["spgemmd_queue_depth"] == 0
    assert before["spgemmd_degraded"] == 0
    assert before["spgemmd_uptime_seconds"] >= 0
    assert before['spgemmd_jobs_terminal_total{outcome="done"}'] == 0

    j = client.submit(folder, d.socket_path)
    assert client.wait(j["id"], d.socket_path,
                       timeout=30)["job"]["state"] == "done"
    after = parse_prometheus(client.metrics(d.socket_path))
    series = 'spgemm_phase_seconds_total{phase="numeric_dispatch"}'
    assert after.get(series, 0) >= before.get(series, 0) + 0.125
    assert after['spgemmd_jobs_terminal_total{outcome="done"}'] == 1
    assert after['spgemmd_jobs{state="done"}'] == 1
    assert after["spgemmd_job_wall_seconds_count"] == 1
    assert after['spgemmd_job_wall_seconds_bucket{le="+Inf"}'] == 1


def test_trace_op_returns_tagged_trace_events(tmp_path, make_daemon):
    """The `trace` op serializes the flight recorder as trace_event JSON;
    a job's spans carry its job_id (executor tagging)."""
    from spgemm_tpu.obs import trace as obs_trace

    # the ring is process-wide and earlier daemons also named jobs
    # "job-1": start from a clean timeline
    obs_trace.RECORDER.clear()
    folder, _ = _chain_folder(tmp_path)

    def runner(job, degraded=False):
        from spgemm_tpu.utils.timers import ENGINE

        with ENGINE.phase("numeric_dispatch"):
            pass

    d = make_daemon(runner=runner)
    j = client.submit(folder, d.socket_path)
    assert client.wait(j["id"], d.socket_path,
                       timeout=30)["job"]["state"] == "done"
    events = client.trace(d.socket_path)
    assert isinstance(events, list) and events
    mine = [ev for ev in events
            if ev.get("args", {}).get("job_id") == j["id"]]
    names = {ev["name"] for ev in mine}
    assert "serve_execute" in names and "numeric_dispatch" in names
    # lexical parenting: the dispatch span nests under serve_execute
    exec_span = next(ev for ev in mine if ev["name"] == "serve_execute")
    disp_span = next(ev for ev in mine if ev["name"] == "numeric_dispatch")
    assert disp_span["args"]["parent"] == exec_span["args"]["span_id"]


def test_degrade_auto_dumps_flight_trace(tmp_path, make_daemon):
    """The postmortem contract: a watchdog reap and the following
    wedge-degrade auto-snapshot the recorder next to the journal as
    valid Perfetto trace_event JSON -- evidence survives the wedge."""
    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()

    def runner(job, degraded=False):
        if not degraded:
            unwedge.wait(60)  # hung backend call: no beats, no return

    d = make_daemon(runner=runner, job_timeout_s=0.3, wedge_grace_s=0.2,
                    probe=lambda: "timeout")
    try:
        j1 = client.submit(folder, d.socket_path)
        resp = client.wait(j1["id"], d.socket_path, timeout=30)
        assert resp["job"]["state"] == "failed"
        _wait_until(lambda: d.degraded, msg="degrade after wedge grace")
        reap_dump = os.path.join(d.flight_dir, f"{j1['id']}.trace.json")
        wedge_dump = os.path.join(d.flight_dir,
                                  f"{j1['id']}.wedged.trace.json")
        degrade_dump = os.path.join(d.flight_dir, "degrade.trace.json")
        for path in (reap_dump, wedge_dump, degrade_dump):
            _wait_until(lambda p=path: os.path.exists(p),
                        msg=f"flight dump {path}")
            events = json.load(open(path, encoding="utf-8"))
            assert isinstance(events, list) and events
            assert all("ph" in ev and "name" in ev for ev in events)
        # the reap/degrade transitions left instant markers in the ring
        names = {ev["name"] for ev in
                 json.load(open(degrade_dump, encoding="utf-8"))}
        assert "serve_reap" in names
        # stats points an operator at the evidence
        st = client.stats(d.socket_path)
        assert st["flight_dir"] == d.flight_dir
        assert st["jobs_terminal"]["timeout"] == 1
    finally:
        unwedge.set()


def test_stats_reports_journal_and_terminal_totals(tmp_path, make_daemon):
    """The scraper's healthy-vs-recovered discriminators: uptime, journal
    size/compaction count, and daemon-lifetime per-outcome totals (the
    bounded queue index alone cannot provide them)."""
    folder, _ = _chain_folder(tmp_path)
    boom = []

    def runner(job, degraded=False):
        if boom:
            raise RuntimeError("synthetic job failure")

    d = make_daemon(runner=runner)
    j = client.submit(folder, d.socket_path)
    assert client.wait(j["id"], d.socket_path,
                       timeout=30)["job"]["state"] == "done"
    boom.append(True)
    j = client.submit(folder, d.socket_path)
    assert client.wait(j["id"], d.socket_path,
                       timeout=30)["job"]["state"] == "failed"
    st = client.stats(d.socket_path)
    assert st["uptime_s"] >= 0
    assert st["jobs_terminal"] == {"done": 1, "error": 1, "timeout": 0,
                                   "abandoned": 0, "drained": 0}
    journal = st["journal"]
    assert journal["enabled"] is True
    assert journal["path"] == d.journal_path
    assert journal["bytes"] > 0          # submit/done records on disk
    assert journal["compactions"] >= 0
    assert st["trace"]["capacity"] >= 1  # recorder health rides along


def test_wedged_job_phases_never_bleed_into_replacement(tmp_path,
                                                        make_daemon):
    """The end-to-end disjointness proof: a wedged executor that keeps
    accumulating AFTER its job was reaped (and after the replacement
    executor started the next job) contaminates neither the replacement
    job's detail nor loses its own."""
    from spgemm_tpu.utils.timers import ENGINE

    folder, _ = _chain_folder(tmp_path)
    unwedge = threading.Event()
    job2_running = threading.Event()

    def runner(job, degraded=False):
        if job.id == "job-1" and not degraded:
            ENGINE.record("ring_fold", 0.125)   # before the wedge
            unwedge.wait(30)                    # wedged...
            ENGINE.record("ring_fold", 100.0)   # ...unwedges much later
            return
        job2_running.set()
        ENGINE.record("assembly", 0.25)
        unwedge.wait(30)  # keep job 2 running while job 1 unwedges

    d = make_daemon(runner=runner, job_timeout_s=0.3, wedge_grace_s=0.2,
                    probe=lambda: "timeout")
    j1 = client.submit(folder, d.socket_path)
    resp = client.wait(j1["id"], d.socket_path, timeout=30)
    assert resp["job"]["state"] == "failed"
    _wait_until(lambda: d.degraded, msg="degrade after wedge grace")
    j2 = client.submit(folder, d.socket_path, {"timeout_s": 0})
    _wait_until(job2_running.is_set, msg="replacement executor on job 2")
    unwedge.set()  # job 1's wedged thread wakes UNDER job 2
    resp2 = client.wait(j2["id"], d.socket_path, timeout=30)
    assert resp2["job"]["state"] == "done"
    det2 = resp2["job"]["detail"]
    # job 2 must not see the wedged thread's late 100 s of ring_fold
    assert "ring_fold" not in det2["phases_s"]
    assert det2["phases_s"]["assembly"] == 0.25
    # and job 1's reap-time detail kept its own pre-wedge phase
    det1 = client.status(j1["id"], d.socket_path)["job"]["detail"]
    assert det1["phases_s"]["ring_fold"] == 0.125


def test_flight_dump_dir_is_bounded(tmp_path):
    """The flight dir is a client-growable resource like every other:
    past FLIGHT_RETAIN dumps the oldest are pruned, never unbounded disk
    on the device owner."""
    d = Daemon(str(tmp_path / "d.sock"), journal=False)  # not started
    d.FLIGHT_RETAIN = 5
    for i in range(12):
        assert d._flight_dump(f"job-{i}") is not None
    kept = set(os.listdir(d.flight_dir))
    assert kept == {f"job-{i}.trace.json" for i in range(7, 12)}
