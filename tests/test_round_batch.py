"""Round-batched dispatch: mega-launch planning, bit-exact parity vs the
per-round path, and the launch-count regression guard.

SPGEMM_TPU_ROUND_BATCH=1 (the default) merges each fanout class's keys into
one launch and assembles through a precomputed inverse permutation; =0 is
the legacy one-launch-per-round loop.  Both must produce identical bits on
every backend -- the arithmetic is non-associative (SURVEY.md section 2.9),
so these tests run adversarial values where any fold-order change shows.
"""

import logging

import numpy as np
import pytest

from spgemm_tpu.ops.spgemm import (_proof_fanout_cap, round_batch_enabled,
                                   spgemm, spgemm_outofcore)
from spgemm_tpu.ops.symbolic import (_shape_class, assembly_permutation,
                                     plan_rounds, symbolic_join)
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import banded_block_sparse, random_block_sparse
from spgemm_tpu.utils.semantics import spgemm_oracle
from spgemm_tpu.utils.timers import ENGINE


def _oracle(a, b):
    return BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))


def _is_ladder(x: int) -> bool:
    """Member of the pow2 + 3/4-pow2 ladder {1, 2, 3, 4, 6, 8, 12, ...}."""
    if x & (x - 1) == 0:
        return True
    return x % 3 == 0 and ((x // 3) & (x // 3 - 1)) == 0


# ---------------------------------------------------------------- planner


def test_plan_rounds_batch_one_round_per_class():
    """Batched planning: each fanout class collapses to ONE mega-round
    (ladder-padded key axis), covering every key exactly once."""
    rng = np.random.default_rng(21)
    a = banded_block_sparse(64, 2, 2, rng, "full")
    join = symbolic_join(a.coords, a.coords)
    base = plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=a.nnzb,
                       round_size=16)
    batched = plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=a.nnzb,
                          round_size=None, batch=True)
    classes = {r.pa.shape[1] for r in base}
    assert len(batched) == len(classes) < len(base)
    covered = np.concatenate([r.key_index for r in batched])
    assert sorted(covered.tolist()) == list(range(join.num_keys))
    for r in batched:
        assert _is_ladder(r.pa.shape[0]) and _is_ladder(r.pa.shape[1])
        # pair lists must match the join exactly, sentinel-padded tails
        for row, ki in enumerate(r.key_index):
            s, e = join.pair_ptr[ki], join.pair_ptr[ki + 1]
            assert list(r.pa[row][: e - s]) == list(join.pair_a[s:e])
            assert all(v == a.nnzb for v in r.pa[row][e - s:])


def test_plan_rounds_batch_respects_entry_budget_and_round_size():
    rng = np.random.default_rng(22)
    a = banded_block_sparse(96, 2, 1, rng, "full")
    join = symbolic_join(a.coords, a.coords)
    # tiny entry budget: chunks of class P are capped at ~64 // P keys
    small = plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=a.nnzb,
                        round_size=None, batch=True, batch_entries=64)
    assert all(r.pa.shape[0] * r.pa.shape[1] <= 64 for r in small)
    # an explicit round_size still caps the key axis in batch mode
    capped = plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=a.nnzb,
                         round_size=8, batch=True)
    assert all(r.pa.shape[0] <= 8 for r in capped)


def test_smem_derived_chunk_cap_clamps_to_pow2():
    """ROADMAP round-7 flag: at P <= 512 the Pallas kernels ship (P, K)
    index arrays with the key axis in LANES, and Mosaic lane-pads K to
    the next 128 multiple.  An SMEM-derived batch chunk cap landing on
    the 3/4 ladder (K=192 here, from a 200-key budget) therefore shipped
    a 256-wide array -- a silent 33% overshoot of the max_entries budget
    it was solved from.  Batch mode must clamp SMEM-derived caps to the
    pow2 floor so the lane-padded footprint stays within budget."""
    from spgemm_tpu.ops.symbolic import JoinResult

    P, n_keys, max_entries = 8, 200, 1600  # _smem_key_cap -> 1600/8 = 200
    join = JoinResult(
        keys=np.stack([np.zeros(n_keys, np.int64),
                       np.arange(n_keys, dtype=np.int64)], axis=1),
        pair_ptr=np.arange(n_keys + 1, dtype=np.int64) * P,
        pair_a=np.zeros(n_keys * P, np.int32),
        pair_b=np.zeros(n_keys * P, np.int32),
    )
    rounds = plan_rounds(join, a_sentinel=4, b_sentinel=4, round_size=None,
                         max_entries=max_entries, batch=True)
    covered = np.concatenate([r.key_index for r in rounds])
    assert sorted(covered.tolist()) == list(range(n_keys))
    for r in rounds:
        K_pad, P_r = r.pa.shape
        lane_padded_k = -(-K_pad // 128) * 128
        pad8_p = -(-P_r // 8) * 8
        assert pad8_p * lane_padded_k <= max_entries, (
            f"round ships a {pad8_p} x {lane_padded_k}-entry index array "
            f"after Mosaic padding -- past the {max_entries} SMEM budget")
    # the finer 3/4 ladder must survive where the cap is NOT SMEM-derived
    # (gather-entry budgets bound materialization, nothing lane-pads them)
    gather = plan_rounds(join, a_sentinel=4, b_sentinel=4, round_size=None,
                         batch=True, batch_entries=192 * P)
    assert max(r.pa.shape[0] for r in gather) == 192
    # below pad8(P) * 128 entries NO key-chunk width fits (Mosaic lane-pads
    # K to >= 128): the planner must refuse loudly, never under-budget
    with pytest.raises(ValueError, match="lane-pad"):
        plan_rounds(join, a_sentinel=4, b_sentinel=4, round_size=None,
                    max_entries=800, batch=True)


def test_plan_rounds_split_fanout_partitions_classes():
    """split_fanout must partition a class's keys at the proof threshold:
    rounds on each side carry max_fanout <=/> the split."""
    # fanouts 5 and 6 share shape class 6; split at 5 must separate them
    coords = [(0, j) for j in range(5)] + [(1, j) for j in range(6)]
    a_coords = np.array(coords, np.int64)
    b_coords = np.array([(j, 0) for j in range(6)], np.int64)
    join = symbolic_join(a_coords, b_coords)
    assert sorted(join.fanouts.tolist()) == [5, 6]
    rounds = plan_rounds(join, a_sentinel=len(a_coords),
                         b_sentinel=len(b_coords), round_size=None,
                         batch=True, split_fanout=5)
    assert len(rounds) == 2
    assert sorted(r.max_fanout for r in rounds) == [5, 6]
    assert all(r.pa.shape[1] == 6 for r in rounds)
    # without the split, one mega-round carries both
    merged = plan_rounds(join, a_sentinel=len(a_coords),
                         b_sentinel=len(b_coords), round_size=None,
                         batch=True)
    assert len(merged) == 1 and merged[0].max_fanout == 6


def test_assembly_permutation_maps_keys_and_sentinel():
    rng = np.random.default_rng(23)
    a = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    join = symbolic_join(a.coords, a.coords)
    rounds = plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=a.nnzb,
                         round_size=None, batch=True)
    inv = assembly_permutation(rounds, join.num_keys)
    total = sum(r.pa.shape[0] for r in rounds)
    assert inv.shape == (join.num_keys + 1,)
    assert inv[-1] == total  # sentinel slot -> appended zero row
    # each key maps into its round's (offset + position) row, all distinct
    assert len(set(inv[:-1].tolist())) == join.num_keys
    off = 0
    for r in rounds:
        got = inv[r.key_index]
        assert list(got) == list(off + np.arange(len(r.key_index)))
        off += r.pa.shape[0]


def test_proof_fanout_cap_matches_safe_exact_bound():
    from spgemm_tpu.ops.mxu_spgemm import safe_exact_bound

    for a_b, b_b, k in [(1, 1, 4), ((1 << 30) - 3, (1 << 30) + 5, 4),
                        ((1 << 32) - 1, (1 << 32) - 1, 32),
                        ((1 << 20), (1 << 20), 8)]:
        cap = _proof_fanout_cap(a_b, b_b, k)
        if cap is None:
            continue  # every fanout proves; nothing to check at a boundary
        if cap >= 1:  # cap 0 = nothing proves (safe_exact_bound floors f at 1)
            assert safe_exact_bound(a_b, b_b, cap, k) is not None
        assert safe_exact_bound(a_b, b_b, cap + 1, k) is None


# ------------------------------------------------------ engine bit parity


@pytest.mark.parametrize("backend", ["xla", "pallas", "hybrid"])
def test_batched_vs_per_round_bit_identical(backend, monkeypatch):
    """The tentpole contract: ROUND_BATCH=1 and =0 produce the same bits on
    every backend, on adversarial (fold-order-sensitive) values."""
    rng = np.random.default_rng(31 + len(backend))
    a = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    b = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "0")
    legacy = spgemm(a, b, backend=backend)
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "1")
    batched = spgemm(a, b, backend=backend)
    assert batched == legacy == _oracle(a, b)


def test_golden_fold_order_duplicate_heavy_classes(monkeypatch):
    """Golden case: every output key shares ONE fanout class (duplicate-
    heavy), values adversarial, so the whole multiply collapses into a
    single mega-launch whose per-key fold order must still match the
    reference exactly."""
    k = 2
    n = 24
    # dense band: every interior key has the same fanout -> one fat class
    a = banded_block_sparse(n, k, 2, np.random.default_rng(41), "adversarial")
    b = banded_block_sparse(n, k, 2, np.random.default_rng(42), "adversarial")
    join = symbolic_join(a.coords, b.coords)
    classes, counts = np.unique(
        [_shape_class(int(f)) for f in join.fanouts], return_counts=True)
    assert counts.max() > n  # genuinely duplicate-heavy
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "1")
    ENGINE.reset()
    got = spgemm(a, b, backend="xla")
    assert ENGINE.counter_snapshot()["dispatches"] == len(classes)
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "0")
    legacy = spgemm(a, b, backend="xla")
    assert got == legacy == _oracle(a, b)


@pytest.mark.parametrize("depth", ["2", "3"])
def test_outofcore_staging_worker_bit_identical(depth, monkeypatch):
    """OOC depth >= 2 now stages on a worker thread (3-stage pipeline);
    results must stay bit-identical to depth 1 and the oracle, and the
    stage_prep phase must actually have run off the main dispatch span."""
    rng = np.random.default_rng(51)
    a = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    b = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    monkeypatch.setenv("SPGEMM_TPU_OOC_DEPTH", depth)
    ENGINE.reset()
    got = spgemm_outofcore(a, b, round_size=3)
    assert "stage_prep" in ENGINE.snapshot()
    assert ENGINE.counter_snapshot()["dispatches"] > 1
    monkeypatch.setenv("SPGEMM_TPU_OOC_DEPTH", "1")
    sync = spgemm_outofcore(a, b, round_size=3)
    assert got == sync == _oracle(a, b)


def test_outofcore_staging_worker_propagates_prep_errors(monkeypatch):
    """A staging-thread failure must surface on the caller, not hang the
    pipeline or leak workers."""
    import spgemm_tpu.ops.spgemm as mod

    rng = np.random.default_rng(52)
    a = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    b = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    monkeypatch.setenv("SPGEMM_TPU_OOC_DEPTH", "2")
    calls = []
    orig = np.unique

    def boom(*args, **kw):
        calls.append(1)
        if len(calls) > 4:
            raise RuntimeError("staged failure")
        return orig(*args, **kw)

    monkeypatch.setattr(mod.np, "unique", boom)
    with pytest.raises(RuntimeError, match="staged failure"):
        spgemm_outofcore(a, b, round_size=2)


# ------------------------------------------------- launch-count regression


def test_dispatch_count_scales_with_classes_not_keys(monkeypatch):
    """The regression guard for silent de-batching: a multiply whose legacy
    plan needs many rounds must dispatch <= #shape-classes x #kernel-choices
    launches under ROUND_BATCH=1."""
    rng = np.random.default_rng(61)
    a = banded_block_sparse(700, 2, 1, rng, "full")
    b = banded_block_sparse(700, 2, 1, rng, "full")
    join = symbolic_join(a.coords, b.coords)
    n_classes = len({_shape_class(int(f)) for f in join.fanouts})
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "1")
    ENGINE.reset()
    got = spgemm(a, b, backend="xla")
    batched_dispatches = ENGINE.counter_snapshot()["dispatches"]
    assert batched_dispatches <= n_classes * 1  # one kernel choice (xla)
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "0")
    ENGINE.reset()
    legacy = spgemm(a, b, backend="xla")
    legacy_dispatches = ENGINE.counter_snapshot()["dispatches"]
    assert legacy_dispatches > batched_dispatches  # the A/B genuinely differs
    assert got == legacy


def test_hybrid_dispatch_count_bounded_by_partitions(monkeypatch, caplog):
    """Hybrid + batching: <= 2 launches per class (proven/unproven
    partition), and the structured log still reports the split."""
    import re

    rng = np.random.default_rng(62)
    a = random_block_sparse(8, 8, 4, 0.6, rng, "small")
    b = random_block_sparse(8, 8, 4, 0.6, rng, "small")
    join = symbolic_join(a.coords, b.coords)
    n_classes = len({_shape_class(int(f)) for f in join.fanouts})
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "1")
    ENGINE.reset()
    with caplog.at_level(logging.INFO, logger="spgemm_tpu.spgemm"):
        got = spgemm(a, b, backend="hybrid")
    assert got == _oracle(a, b)
    assert ENGINE.counter_snapshot()["dispatches"] <= n_classes * 2
    assert re.search(r"hybrid mxu=(\d+)/(\d+)", caplog.text)


# -------------------------------------------------------- knob validation


def test_round_batch_env_validation(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "yes")
    with pytest.raises(ValueError, match="SPGEMM_TPU_ROUND_BATCH"):
        round_batch_enabled()
    monkeypatch.setenv("SPGEMM_TPU_ROUND_BATCH", "0")
    assert round_batch_enabled() is False
    monkeypatch.delenv("SPGEMM_TPU_ROUND_BATCH")
    assert round_batch_enabled() is True


def test_vpu_knob_validation_rejects_broken_tpu_combos():
    """VERDICT round-5 "What's weak" #2: the advertised knobs crash on TPU
    hardware with a bare JaxRuntimeError -- the engine must reject them at
    entry with the knob named."""
    from spgemm_tpu.ops.pallas_spgemm import validate_vpu_config

    # fine everywhere
    validate_vpu_config("colbcast", 1, platform="tpu")
    # fine in interpret mode (parity tests run these)
    validate_vpu_config("vecj", 4, platform="cpu", interpret=True)
    validate_vpu_config("vecj", 2, platform="tpu", interpret=True)
    with pytest.raises(ValueError, match="SPGEMM_TPU_VPU_ALGO"):
        validate_vpu_config("vecj", 1, platform="tpu")
    with pytest.raises(ValueError, match="SPGEMM_TPU_VPU_PB"):
        validate_vpu_config("colbcast", 4, platform="tpu")
    with pytest.raises(ValueError, match="SPGEMM_TPU_VPU_ALGO"):
        validate_vpu_config("nope", 1, platform="cpu", interpret=True)
    with pytest.raises(ValueError, match="SPGEMM_TPU_VPU_PB"):
        validate_vpu_config("colbcast", 0, platform="cpu", interpret=True)


def test_engine_rejects_bad_vpu_env(monkeypatch):
    """_select_numeric must validate the env knobs before any kernel call."""
    rng = np.random.default_rng(63)
    a = random_block_sparse(4, 4, 2, 0.5, rng, "full")
    b = random_block_sparse(4, 4, 2, 0.5, rng, "full")
    monkeypatch.setenv("SPGEMM_TPU_VPU_ALGO", "bogus")
    with pytest.raises(ValueError, match="SPGEMM_TPU_VPU_ALGO"):
        spgemm(a, b, backend="pallas")
    monkeypatch.delenv("SPGEMM_TPU_VPU_ALGO")
    monkeypatch.setenv("SPGEMM_TPU_VPU_PB", "zero")
    with pytest.raises(ValueError, match="SPGEMM_TPU_VPU_PB"):
        spgemm(a, b, backend="pallas")
    monkeypatch.setenv("SPGEMM_TPU_VPU_PB", "0")
    with pytest.raises(ValueError, match="SPGEMM_TPU_VPU_PB"):
        spgemm(a, b, backend="pallas")


# -------------------------------------------- stacked (R, K, P) kernel API


def test_kernels_accept_stacked_round_axis():
    """Every numeric kernel accepts a stacked (R, K, P) batch and returns
    per-round slices bit-identical to separate calls."""
    import jax.numpy as jnp

    from spgemm_tpu.ops.mxu_spgemm import numeric_round_mxu
    from spgemm_tpu.ops.pallas_spgemm import numeric_round_pallas
    from spgemm_tpu.ops.spgemm import numeric_round_impl, pack_tiles

    rng = np.random.default_rng(71)
    m = random_block_sparse(6, 6, 2, 0.8, rng, "adversarial")
    hi, lo = pack_tiles(m)
    pa = rng.integers(0, m.nnzb + 1, size=(3, 4, 2)).astype(np.int32)
    pb = rng.integers(0, m.nnzb + 1, size=(3, 4, 2)).astype(np.int32)
    kernels = [
        lambda *args: numeric_round_impl(*args),
        lambda *args: numeric_round_pallas(*args, interpret=True),
    ]
    for fn in kernels:
        sh, sl = fn(hi, lo, hi, lo, jnp.asarray(pa), jnp.asarray(pb))
        assert sh.shape == (3, 4, 2, 2)
        for r in range(3):
            oh, ol = fn(hi, lo, hi, lo, jnp.asarray(pa[r]), jnp.asarray(pb[r]))
            assert (np.asarray(sh[r]) == np.asarray(oh)).all()
            assert (np.asarray(sl[r]) == np.asarray(ol)).all()
    # field-mode kernel: same check, small values so residues are plain sums
    m2 = random_block_sparse(6, 6, 2, 0.8, rng, "small")
    hi2, lo2 = pack_tiles(m2)
    sh, sl = numeric_round_mxu(hi2, lo2, hi2, lo2,
                               jnp.asarray(pa), jnp.asarray(pb))
    for r in range(3):
        oh, ol = numeric_round_mxu(hi2, lo2, hi2, lo2,
                                   jnp.asarray(pa[r]), jnp.asarray(pb[r]))
        assert (np.asarray(sh[r]) == np.asarray(oh)).all()
        assert (np.asarray(sl[r]) == np.asarray(ol)).all()
