"""Generator for the golden_wrap adversarial fixture (provenance record).

Run from the repo root:  python tests/data/gen_golden_wrap.py

Produces tests/data/golden_wrap/{size,matrix1,matrix2,matrix3} and
tests/data/golden_wrap_expected_matrix.  The expected bytes come from the
SCALAR python-int oracle (utils/semantics.scalar_tile_matmul) -- arbitrary
precision, no numpy, no engine code -- cross-checked here against the
vectorized numpy oracle before anything is written.

The chain is hand-constructed so that the reference's wrap-then-mod fold
order (SURVEY.md section 2.9; sparse_matrix_mult.cu:48,59-61) is load-bearing
in the expected output.  Three distinct collapses are forced:

  1. product u64 wrap:   2^32 * 2^32 = 2^64 wraps to 0, then %MAX keeps 0
     (clean mod-(2^64-1) arithmetic would give 1);
  2. product == MAX:     MAX * 1 -> p' = 0 (same in both semantics --
     included so the %MAX equality branch is exercised, not just the wrap);
  3. accumulator u64 wrap: 2^63 + 2^63 = 2^64 wraps to 0 (clean: 1).

Collapse 3 is additionally arranged to zero an ENTIRE output tile, so the
final zero-tile prune (sparse_matrix_mult.cu:577-592) removes it: under
clean semantics that tile would be all-ones and kept, making the expected
file differ STRUCTURALLY (block count), not just in values.  Any "cleanup"
of the non-associative fold order turns the golden test red.

matrix3 is a block identity, so the wrap-born values of pass 1 must survive
an exact second chain pass (and the helper2 odd-carry pairing) unchanged.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from spgemm_tpu.utils import io_text, semantics
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix

K = 4
MAX = semantics.MAX_INT
P63 = 1 << 63
P32 = 1 << 32


def _tile(rows):
    return np.array(rows, dtype=np.uint64)


def build_chain():
    z = [0] * K
    # --- M1: 2x2 block grid (8x8 elements) -------------------------------
    # Block (0,0) row 0 is the three-collapse row: against M2(0,0) col 0
    #   j=0: 2^63*1, j=1: 2^63*1  -> acc wraps 2^64 -> 0     (collapse 3)
    #   j=2: 2^32*2^32 = 2^64     -> p wraps to 0            (collapse 1)
    #   j=3: MAX*1                -> p' = 0                  (collapse 2)
    # reference C(0,0)[0,0] from this pair: 0; clean arithmetic: 2.
    m1 = {
        (0, 0): _tile([[P63, P63, P32, MAX],
                       [1, 0, 0, 0],
                       [0, 2, 0, 0],
                       z]),
        # second pair into output key (0,0): plain small values, checks the
        # j-ascending multi-pair fold lands AFTER the (0,0) pair.
        (0, 1): _tile([[3, 0, 0, 0], z, z, z]),
        # feeds output tile (1,1): every element 2^63+2^63 -> wraps to an
        # ALL-ZERO tile (pruned at write); clean semantics: all-ones (kept).
        (1, 1): _tile([[P63, P63, 0, 0]] * K),
    }
    # --- M2 ---------------------------------------------------------------
    m2 = {
        (0, 0): _tile([[1, 7, 0, 0],
                       [1, 0, 0, 0],
                       [P32, 0, 0, 0],
                       [1, 0, 0, 0]]),
        (1, 0): _tile([[5, 0, 0, 0], z, z, z]),
        (1, 1): _tile([[1, 1, 1, 1],
                       [1, 1, 1, 1],
                       z, z]),
    }
    # --- M3: block identity (the wrapped values must survive a 2nd pass) --
    eye = np.eye(K, dtype=np.uint64)
    m3 = {(0, 0): eye, (1, 1): eye}
    return [m1, m2, m3]


def scalar_chain(mats):
    """Chain product with helper2 pairing, entirely in python ints."""
    arr = [{c: [[int(v) for v in row] for row in t] for c, t in m.items()}
           for m in mats]
    while len(arr) > 1:
        nxt = []
        for i in range(0, len(arr) - 1, 2):
            a, b = arr[i], arr[i + 1]
            b_rows = {}
            for (br, bc) in sorted(b):
                b_rows.setdefault(br, []).append(bc)
            out = {}
            for (ar, ac) in sorted(a):
                for bc in b_rows.get(ac, ()):
                    acc = out.setdefault((ar, bc), [[0] * K for _ in range(K)])
                    out[(ar, bc)] = semantics.scalar_tile_matmul(
                        acc, a[(ar, ac)], b[(ac, bc)])
            nxt.append(out)
        if len(arr) % 2 == 1:
            nxt.append(arr[-1])
        arr = nxt
    return arr[0]


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    mats = build_chain()

    want = scalar_chain(mats)
    # cross-check scalar vs vectorized-numpy oracle before writing anything
    vec = semantics.chain_oracle(
        [{c: t.copy() for c, t in m.items()} for m in mats], K)
    assert set(vec) == set(want)
    for key in want:
        assert np.array_equal(vec[key],
                              np.array(want[key], dtype=np.uint64)), key

    # assert the fixture is actually adversarial: clean field semantics must
    # differ in VALUES and in post-prune STRUCTURE
    f1 = semantics.field_spgemm_oracle(mats[0], mats[1], K)
    f = semantics.field_spgemm_oracle(f1, mats[2], K)
    ref_nonzero = {c for c, t in want.items()
                   if any(v for row in t for v in row)}
    field_nonzero = {c for c, t in f.items() if np.any(t)}
    # [0,0]: pair 1 folds to 0 via all three collapses, pair 2 adds 3*5=15;
    # clean semantics: pair 1 gives 2, so 17.  Pin both exactly.
    assert want[(0, 0)][0][0] == 15, want[(0, 0)][0][0]
    assert int(f[(0, 0)][0, 0]) == 17, f[(0, 0)][0, 0]
    assert (1, 1) not in ref_nonzero and (1, 1) in field_nonzero, \
        "zero-tile prune must differ between semantics"

    out_dir = os.path.join(here, "golden_wrap")
    ms = [BlockSparseMatrix.from_dict(8, 8, K, m) for m in mats]
    io_text.write_chain_dir(out_dir, ms, K)
    result = BlockSparseMatrix.from_dict(8, 8, K, {
        c: np.array(t, dtype=np.uint64) for c, t in want.items()
    }).prune_zeros()
    with open(os.path.join(here, "golden_wrap_expected_matrix"), "wb") as fh:
        fh.write(io_text.format_matrix(result))
    print("wrote", out_dir, "and expected matrix:",
          result.nnzb, "blocks after prune")


if __name__ == "__main__":
    main()
