"""2-process DCN chain distribution (jax.distributed on CPU, subprocesses).

The in-process suite runs everything else on one process; this test actually
spawns two JAX processes with a coordinator, exercising the padded DCN
all-gather and the replicated combine -- the reference's multi-node MPI path
(SURVEY.md section 4: 'multi-node behavior was only ever exercised on a real
cluster'; here it runs in CI)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _gloo_transport_race(outs) -> bool:
    """The known CPU-gloo TCP race: under host load a rank can abort with
    `gloo::EnforceNotMet ... op.preamble.length <= op.nbytes` inside an
    all-gather (mismatched in-flight ops on one TCP pair), taking its
    peers down with heartbeat/PartnerLost collateral.  An infra artifact
    of the CPU transport, not an engine bug -- the spawn is retried ONCE
    on exactly this signature (a systematic engine failure keeps failing
    on the retry and still fails the test)."""
    return any("gloo::EnforceNotMet" in out and "preamble" in out
               for out in outs)


@pytest.mark.parametrize("num_procs,n_mats", [
    (2, 5),   # the original 2-host split
    (4, 7),   # P=4, every rank active (4-way padded DCN all-gather)
    (4, 3),   # P=4, N < P: ranks 1-3 idle -- the q==0 degenerate branch
              # (reference: sparse_matrix_mult.cu:612-666 region) over DCN
])
def test_multi_process_chain(tmp_path, num_procs, n_mats):
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    env = {**os.environ}
    env.pop("JAX_PLATFORMS", None)  # worker pins cpu via jax.config

    for attempt in range(2):
        port = _free_port()
        coord = f"127.0.0.1:{port}"
        procs = [
            subprocess.Popen(
                [sys.executable, worker, coord, str(num_procs), str(r),
                 str(tmp_path), str(n_mats)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
            for r in range(num_procs)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=150)
                outs.append(out.decode())
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("multihost workers timed out")
        if (attempt == 0 and any(p.returncode != 0 for p in procs)
                and _gloo_transport_race(outs)):
            continue  # one retry for the CPU-gloo transport race only
        break
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]

    # compare against the single-process partitioned result (P semantics)
    from spgemm_tpu.parallel.chainpart import chain_product_partitioned
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.gen import random_chain

    k = 2
    mats = random_chain(n_mats, 4, k, 0.5, np.random.default_rng(777), "full")
    want = chain_product_partitioned(mats, num_procs)
    got = io_text.read_matrix(str(tmp_path / "out"), k)
    assert got == want


def test_skewed_partials_chunked_exchange(tmp_path):
    """Skewed partials (rank 0's is ~86x rank 1's) through the chunked DCN
    exchange with a chunk budget SMALLER than the big partial: the combined
    result must be byte-identical to the legacy padded path, and the logged
    peak-exchange buffer must respect P x SPGEMM_TPU_DCN_CHUNK_MB -- the
    bounded-memory contract the padded path (O(P x max_nnzb)) never had.
    Two real JAX processes run ONLY the partial exchange, both flavors in
    one session (rank 0: 600 tiles, rank 1: 7)."""
    import re

    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    # k=4 tile = 2 coord words + 32 plane words = 136 B; 0.01 MiB holds 77
    # tiles, so the 600-tile partial needs 8 chunk rounds
    env = {**os.environ, "SPGEMM_TPU_DCN_CHUNK_MB": "0.01"}
    env.pop("JAX_PLATFORMS", None)

    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, "2", str(r),
             str(tmp_path), "600", "exchange"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=150)
            outs.append(out.decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("exchange workers timed out")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]

    # the memory-guard ledger line, logged BEFORE the first payload
    # collective, must respect the advertised P x chunk bound
    out = outs[0]
    ledger = re.search(
        r"dcn exchange: (\d+) ranks, max partial (\d+) tiles -> (\d+) chunk "
        r"rounds of <=(\d+) tiles; peak exchange buffer ([\d.]+) MiB "
        r"\(bound: P x SPGEMM_TPU_DCN_CHUNK_MB = ([\d.]+) MiB\)", out)
    assert ledger, f"missing exchange ledger line in:\n{out[-2000:]}"
    p, max_nnzb, n_chunks, chunk_tiles, peak_mb, bound_mb = ledger.groups()
    assert int(max_nnzb) == 600
    assert int(chunk_tiles) < 600, "chunk budget must be below the big partial"
    assert int(n_chunks) > 1, "skew must force a multi-round exchange"
    assert float(peak_mb) <= float(bound_mb), \
        "logged peak exceeds the advertised P x chunk bound"
    assert float(bound_mb) == float(p) * 0.01
    # the guard-railed legacy path announces itself loudly
    assert "LEGACY PADDED" in out

    # A/B: both flavors must combine to the exact same per-rank partials
    chunked = dict(np.load(tmp_path / "exchange_chunked.npz"))
    padded = dict(np.load(tmp_path / "exchange_padded.npz"))
    assert sorted(chunked) == sorted(padded)
    assert len(chunked) == 4  # coords+tiles for each of the 2 ranks
    for name in chunked:
        assert np.array_equal(chunked[name], padded[name]), name


def test_partner_loss_fails_fast(tmp_path):
    """Fault injection for the DCN failure contract (multihost.py docstring):
    worker P-1 dies hard right before the partial-product exchange.  The
    survivor must (a) exit non-zero well before the test timeout -- the
    reference would block forever in MPI_Recv (sparse_matrix_mult.cu:508-552)
    -- (b) surface the loss loudly (the distributed service's error poller
    terminating the process, or PartnerLostError if the collective raises
    first), and (c) write no output file."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    env = {**os.environ}
    env.pop("JAX_PLATFORMS", None)

    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, "2", str(r),
             str(tmp_path), "5", "die"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out.decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("survivor hung after partner loss (contract: fail fast)")

    assert procs[1].returncode == 17, outs[1][-500:]   # the injected death
    assert procs[0].returncode not in (0, None), outs[0][-2000:]
    assert ("PartnerLostError" in outs[0]
            or "JAX distributed service detected fatal errors" in outs[0]
            or "unhealthy" in outs[0]), outs[0][-2000:]
    assert not (tmp_path / "out").exists(), "no output after partner loss"
