"""2-process DCN chain distribution (jax.distributed on CPU, subprocesses).

The in-process suite runs everything else on one process; this test actually
spawns two JAX processes with a coordinator, exercising the padded DCN
all-gather and the replicated combine -- the reference's multi-node MPI path
(SURVEY.md section 4: 'multi-node behavior was only ever exercised on a real
cluster'; here it runs in CI)."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_chain(tmp_path):
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    env = {**os.environ}
    env.pop("JAX_PLATFORMS", None)  # worker pins cpu via jax.config

    procs = [
        subprocess.Popen(
            [sys.executable, worker, coord, "2", str(r), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=150)
            outs.append(out.decode())
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]

    # compare against the single-process partitioned result (P=2 semantics)
    from spgemm_tpu.parallel.chainpart import chain_product_partitioned
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.gen import random_chain

    k = 2
    mats = random_chain(5, 4, k, 0.5, np.random.default_rng(777), "full")
    want = chain_product_partitioned(mats, 2)
    got = io_text.read_matrix(str(tmp_path / "out"), k)
    assert got == want
