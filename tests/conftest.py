"""Test env: force the CPU backend with 8 virtual devices so every multi-chip
code path (shard_map / psum / ppermute) runs single-process, per SURVEY.md
section 4 ("multi-chip without a pod").

In this environment jax is already imported at interpreter start (the axon TPU
plugin's sitecustomize), so setting JAX_PLATFORMS here is too late; instead we
update jax.config before any backend is initialized, which conftest load time
guarantees."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

assert not jax._src.xla_bridge._backends, (
    "a jax backend initialized before conftest -- platform pinning failed")
jax.config.update("jax_platforms", "cpu")

# persistent XLA compilation cache, sharing bench.py's dir: the tier-1
# suite is compile-dominated on CPU, and every re-run (a CI retry, the
# round driver's verify) re-compiled hundreds of identical executables
# from scratch -- serving them from disk roughly halves the
# compile-heavy files' wall (test_ring: 20 s cold -> 9.6 s warm).
# Correctness is XLA's own content-hash cache contract, and the compile
# ACCOUNTING tests still hold: ProfiledJit's AOT lower().compile()
# records land (with cost analyses) whether the backend compiled or
# loaded.  The warm-start layer (ops/warmstore) wires the same cache
# under spgemmd -- this is that tentpole applied to the dev loop.
jax.config.update("jax_compilation_cache_dir",
                  os.path.expanduser("~/.cache/jax_bench"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_delta_store():
    """The delta-recompute store (ops/delta) retains previous results
    keyed by structure fingerprint, process-wide: without a per-test
    clear, a test re-running a structure another test already multiplied
    would be answered from the retained result (content digests are
    value-exact, so results stay CORRECT -- but dispatch-count and
    phase assertions would observe the delta path instead of the engine
    under test).  The warm store (ops/warmstore) is the same hazard one
    level down -- an in-process Daemon.start() binds the process-wide
    store to its socket-adjacent dir, and a later test's plan/delta
    lookups would otherwise be answered from THAT test's disk entries --
    so it unbinds per test too (reset releases the flock; on-disk files
    are the owning test's tmp dir and die with it)."""
    from spgemm_tpu.ops import delta, warmstore

    delta.clear()
    warmstore.reset()
    yield


def run_repo_script(args, timeout=240, **env_overrides):
    """Subprocess runner shared by tests that drive repo entry points
    (bench.py, benchmarks/run.py, the CLI): repo root on PYTHONPATH (no
    empty entries -- an empty PYTHONPATH element puts the subprocess cwd
    on sys.path), JAX_PLATFORMS=cpu for the child's own pinning paths."""
    import subprocess

    extra = [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.pathsep.join([REPO] + extra),
           **env_overrides}
    return subprocess.run([sys.executable, *args], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)
