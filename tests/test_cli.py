"""End-to-end CLI: reference contract (folder in, ./matrix out, 'time taken')."""

import os

import numpy as np
import pytest

from spgemm_tpu.cli import run
from spgemm_tpu.utils import io_text
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_chain
from spgemm_tpu.utils.semantics import chain_oracle


def _expected_bytes(mats, k):
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_m = BlockSparseMatrix.from_dict(mats[0].rows, mats[-1].cols, k, want)
    return io_text.format_matrix(want_m.prune_zeros())


@pytest.mark.parametrize("n,dist", [(3, "full"), (5, "small"), (4, "adversarial")])
def test_cli_end_to_end(tmp_path, capsys, n, dist):
    rng = np.random.default_rng(60 + n)
    k = 2
    mats = random_chain(n, 4, k, 0.5, rng, dist)
    folder = str(tmp_path / "in")
    io_text.write_chain_dir(folder, mats, k)
    out = str(tmp_path / "matrix")

    rc = run([folder, "--output", out])
    assert rc == 0
    assert open(out, "rb").read() == _expected_bytes(mats, k)
    captured = capsys.readouterr().out
    assert "time taken " in captured  # :679 parity line
    assert "multiplying 0 1" in captured  # :301 progress line, unconditional


@pytest.mark.parametrize("n", [4, 5])  # even + odd-carry reduction trees
def test_cli_stream_mode(tmp_path, capsys, monkeypatch, n):
    """--stream (host-resident partials, bounded HBM) is bit-identical to the
    default device-resident chain AND actually routes every multiply through
    the host-to-host spgemm (a wiring regression would be invisible to a
    parity-only check, since both paths produce identical bytes)."""
    import spgemm_tpu.ops.spgemm as spgemm_mod

    calls = []
    real = spgemm_mod.spgemm

    def counting(a, b, **kw):
        calls.append(1)
        return real(a, b, **kw)

    monkeypatch.setattr(spgemm_mod, "spgemm", counting)

    rng = np.random.default_rng(80 + n)
    k = 2
    mats = random_chain(n, 4, k, 0.5, rng, "adversarial")
    folder = str(tmp_path / "in")
    io_text.write_chain_dir(folder, mats, k)
    out = str(tmp_path / "matrix")

    rc = run([folder, "--output", out, "--stream"])
    assert rc == 0
    assert open(out, "rb").read() == _expected_bytes(mats, k)
    assert len(calls) == n - 1  # one host-to-host multiply per reduction edge


def test_cli_out_of_core(tmp_path, capsys):
    """--out-of-core (per-round staging) matches the reference bytes."""
    rng = np.random.default_rng(90)
    k = 2
    mats = random_chain(4, 4, k, 0.5, rng, "adversarial")
    folder = str(tmp_path / "in")
    io_text.write_chain_dir(folder, mats, k)
    out = str(tmp_path / "matrix")

    rc = run([folder, "--output", out, "--out-of-core"])
    assert rc == 0
    assert open(out, "rb").read() == _expected_bytes(mats, k)


def test_cli_serve_subcommands_dispatch(tmp_path, capsys):
    """`submit`/`status` dispatch to the spgemmd client handlers (fail
    fast with rc 1 when no daemon listens -- never an argparse crash or a
    hang)."""
    dead = str(tmp_path / "none.sock")
    assert run(["status", "--socket", dead]) == 1
    assert run(["submit", str(tmp_path), "--socket", dead]) == 1
    err = capsys.readouterr().err
    assert "status failed" in err and "submit failed" in err


def test_cli_serve_named_input_dir_keeps_folder_meaning(tmp_path,
                                                        monkeypatch,
                                                        capsys):
    """Like `knobs`: an INPUT directory named `serve` (it has a `size`
    file) keeps the reference-contract meaning instead of being swallowed
    by the subcommand."""
    rng = np.random.default_rng(71)
    k = 2
    mats = random_chain(2, 3, k, 0.6, rng, "small")
    folder = str(tmp_path / "serve")
    io_text.write_chain_dir(folder, mats, k)
    monkeypatch.chdir(tmp_path)
    out = str(tmp_path / "out")
    assert run(["serve", "--output", out]) == 0
    assert open(out, "rb").read() == _expected_bytes(mats, k)


def test_cli_default_output_cwd(tmp_path, monkeypatch, capsys):
    """The reference writes to ./matrix in the cwd (sparse_matrix_mult.cu:595)."""
    rng = np.random.default_rng(70)
    k = 2
    mats = random_chain(2, 3, k, 0.6, rng, "small")
    folder = str(tmp_path / "in")
    io_text.write_chain_dir(folder, mats, k)
    monkeypatch.chdir(tmp_path)
    assert run([folder]) == 0
    assert os.path.exists(tmp_path / "matrix")
    assert (tmp_path / "matrix").read_bytes() == _expected_bytes(mats, k)
