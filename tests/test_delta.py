"""Delta SpGEMM (PR 9 tentpole, ops/delta): row-granular incremental
recompute for evolving inputs.

The standing contracts:
  * delta on/off is a bit-identical whole-engine A/B: untouched output
    rows keep their exact bytes, dirty rows re-fold in full
    (SPGEMM_TPU_DELTA=0|1);
  * the empty diff executes NOTHING (zero dispatches) and the all-dirty
    diff degenerates to the full path -- both byte-exact;
  * recompute volume tracks the dirty fraction (the delta_rows_* ENGINE
    counters are the audit trail);
  * every ambiguity -- first contact, store eviction, provenance
    mismatch -- is a counted full fallback, never a wrong answer;
  * dirtiness propagates through a chain analytically (the producer's
    tag), so pass >= 1 partials need neither host tiles nor hashing.
"""

import numpy as np
import pytest

from spgemm_tpu.chain import chain_product
from spgemm_tpu.ops import delta, plancache
from spgemm_tpu.ops.spgemm import plan, spgemm, spgemm_device, subplan
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_block_sparse, random_chain
from spgemm_tpu.utils.semantics import chain_oracle, spgemm_oracle
from spgemm_tpu.utils.timers import ENGINE


def _oracle(a, b):
    return BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))


def _mutate_rows(m: BlockSparseMatrix, rows) -> BlockSparseMatrix:
    """Same structure, new VALUES in the given tile-rows (every tile of
    those rows gets one element bumped)."""
    tiles = m.tiles.copy()
    mask = np.isin(m.coords[:, 0], np.asarray(list(rows), np.int64))
    tiles[mask, 0, 0] += np.uint64(1)
    return BlockSparseMatrix(rows=m.rows, cols=m.cols, k=m.k,
                             coords=m.coords, tiles=tiles)


@pytest.fixture(autouse=True)
def _fresh_caches():
    plancache.clear()
    delta.clear()
    yield
    plancache.clear()
    delta.clear()


# ------------------------------------------------------------ row digests


def test_row_digests_change_exactly_on_mutated_rows():
    rng = np.random.default_rng(201)
    a = random_block_sparse(8, 8, 2, 0.6, rng, "full")
    rows = np.unique(a.coords[:, 0])
    dirty = rows[:2]
    a2 = _mutate_rows(a, dirty)
    ids1, d1 = delta.row_digests(a.coords, a.tiles)
    ids2, d2 = delta.row_digests(a2.coords, a2.tiles)
    assert np.array_equal(ids1, ids2)
    changed = ids1[d1 != d2]
    assert np.array_equal(np.sort(changed), np.sort(dirty))


def test_row_digests_empty_operand():
    ids, digs = delta.row_digests(np.zeros((0, 2), np.int64),
                                  np.zeros((0, 2, 2), np.uint64))
    assert len(ids) == 0 and len(digs) == 0


# ----------------------------------------------------- sub-plan machinery


def test_subplan_rows_match_full_execution():
    """A row-sliced sub-plan's keys compute byte-identically to the same
    keys of the full plan (the splice's correctness core)."""
    from spgemm_tpu.ops.spgemm import execute

    rng = np.random.default_rng(202)
    a = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    b = random_block_sparse(8, 8, 4, 0.5, rng, "adversarial")
    p = plan(a, b, backend="xla", platform="cpu")
    full = execute(p, a, b)
    keep = p.join.keys[:, 0] % 2 == 0  # every even output tile-row
    assert 0 < int(np.count_nonzero(keep)) < p.join.num_keys
    sub_p, kept = subplan(p, keep)
    sub = execute(sub_p, a, b)
    assert np.array_equal(sub_p.join.keys, p.join.keys[kept])
    np.testing.assert_array_equal(np.asarray(sub.hi[: len(kept)]),
                                  np.asarray(full.hi)[kept])
    np.testing.assert_array_equal(np.asarray(sub.lo[: len(kept)]),
                                  np.asarray(full.lo)[kept])


# -------------------------------------------------- single-multiply delta


def test_delta_bit_exact_vs_full_on_partial_mutation(monkeypatch):
    """The tentpole A/B on adversarial (fold-order-sensitive) values: a
    mutated re-submit through the delta path is byte-identical to the
    full recompute and the oracle, recomputed fewer rows than total --
    for an A-side dirty row (reaches only its own output row) AND then a
    B-side dirty row (reaches every output row whose pair lists touch
    it, the direction that actually fans out)."""
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    rng = np.random.default_rng(203)
    a = random_block_sparse(8, 8, 4, 0.6, rng, "adversarial")
    b = random_block_sparse(8, 8, 4, 0.6, rng, "adversarial")
    first = spgemm(a, b, backend="xla")
    assert first == _oracle(a, b)
    a2 = _mutate_rows(a, np.unique(a.coords[:, 0])[:1])
    ENGINE.reset()
    got = spgemm(a2, b, backend="xla")
    counters = ENGINE.counter_snapshot()
    assert 0 < counters["delta_rows_recomputed"] \
        < counters["delta_rows_total"]
    assert counters.get("delta_full_fallbacks", 0) == 0
    # B-side mutation against the refreshed entry (a2 retained now)
    b2 = _mutate_rows(b, np.unique(b.coords[:, 0])[:1])
    got_b = spgemm(a2, b2, backend="xla")
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "0")
    want = spgemm(a2, b, backend="xla")
    assert got == want == _oracle(a2, b)
    assert got_b == spgemm(a2, b2, backend="xla") == _oracle(a2, b2)


def test_empty_diff_executes_nothing(monkeypatch):
    """Zero dirty rows -> zero recompute: the retained result is the
    answer and no numeric launch happens."""
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    rng = np.random.default_rng(205)
    a = random_block_sparse(8, 8, 2, 0.5, rng, "adversarial")
    b = random_block_sparse(8, 8, 2, 0.5, rng, "adversarial")
    first = spgemm(a, b, backend="xla")
    ENGINE.reset()
    second = spgemm(a, b, backend="xla")
    counters = ENGINE.counter_snapshot()
    assert counters.get("dispatches", 0) == 0
    assert counters["delta_rows_recomputed"] == 0
    assert counters["delta_rows_total"] > 0
    assert second == first == _oracle(a, b)


def test_all_dirty_degenerates_to_full_path(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    rng = np.random.default_rng(206)
    a = random_block_sparse(6, 6, 2, 0.7, rng, "adversarial")
    b = random_block_sparse(6, 6, 2, 0.7, rng, "adversarial")
    spgemm(a, b, backend="xla")
    a2 = _mutate_rows(a, np.unique(a.coords[:, 0]))  # every row dirty
    ENGINE.reset()
    got = spgemm(a2, b, backend="xla")
    counters = ENGINE.counter_snapshot()
    assert counters["delta_rows_recomputed"] == counters["delta_rows_total"]
    assert counters.get("delta_full_fallbacks", 0) == 0  # a diff, not a miss
    assert got == _oracle(a2, b)


def test_delta_disabled_is_legacy(monkeypatch):
    """SPGEMM_TPU_DELTA=0: no retention, no tags, identical dispatch
    counts on a repeat -- the legacy engine exactly."""
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "0")
    rng = np.random.default_rng(207)
    a = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    b = random_block_sparse(8, 8, 2, 0.5, rng, "full")
    ENGINE.reset()
    first = spgemm(a, b, backend="xla")
    d1 = ENGINE.counter_snapshot()["dispatches"]
    ENGINE.reset()
    second = spgemm(a, b, backend="xla")
    counters = ENGINE.counter_snapshot()
    assert counters["dispatches"] == d1 > 0
    assert "delta_rows_total" not in counters
    assert delta.stats()["entries"] == 0
    assert second == first


def test_store_eviction_is_counted_full_fallback(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    monkeypatch.setenv("SPGEMM_TPU_DELTA_RETAIN", "1")
    rng = np.random.default_rng(208)
    a = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    b = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    c = random_block_sparse(6, 6, 2, 0.9, rng, "full")
    spgemm(a, b, backend="xla")      # entry 1
    spgemm(a, c, backend="xla")      # entry 2 evicts entry 1 at cap 1
    st = delta.stats()
    assert st["entries"] == 1 and st["evictions"] == 1
    ENGINE.reset()
    got = spgemm(a, b, backend="xla")  # evicted: full fallback, correct
    assert ENGINE.counter_snapshot()["delta_full_fallbacks"] == 1
    assert got == _oracle(a, b)


def test_plan_cache_off_bypasses_delta(monkeypatch):
    """No fingerprint -> no delta keying: the engine runs the plain full
    path and retains nothing."""
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_CACHE", "0")
    rng = np.random.default_rng(209)
    a = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    assert spgemm(a, a, backend="xla") == _oracle(a, a)
    assert delta.stats()["entries"] == 0


# -------------------------------------------------------- chain propagation


@pytest.mark.parametrize("ahead", ["0", "2"])
def test_chain_delta_propagates_and_stays_bit_exact(monkeypatch, ahead):
    """A re-submitted chain with one mutated leaf re-folds only reached
    rows at EVERY pass (pass >= 1 partials propagate dirtiness via the
    producer tag -- no host tiles needed) and matches the mutated chain's
    oracle byte-for-byte, under both plan-ahead modes."""
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    monkeypatch.setenv("SPGEMM_TPU_PLAN_AHEAD", ahead)
    rng = np.random.default_rng(210)
    mats = random_chain(4, 6, 2, 0.5, rng, "adversarial")
    chain_product(mats)  # submit 1: first contact everywhere
    mats2 = list(mats)
    mats2[0] = _mutate_rows(mats[0], np.unique(mats[0].coords[:, 0])[:1])
    ENGINE.reset()
    got = chain_product(mats2)  # submit 2: the delta path, all passes
    counters = ENGINE.counter_snapshot()
    assert counters.get("delta_full_fallbacks", 0) == 0
    assert 0 < counters["delta_rows_recomputed"] \
        < counters["delta_rows_total"]
    want = chain_oracle([m.to_dict() for m in mats2], 2)
    want_m = BlockSparseMatrix.from_dict(mats2[0].rows, mats2[-1].cols, 2,
                                         want)
    assert got == want_m


def test_chain_identical_resubmit_recomputes_nothing(monkeypatch):
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    rng = np.random.default_rng(211)
    mats = random_chain(4, 4, 2, 0.5, rng, "full")
    first = chain_product(mats)
    ENGINE.reset()
    second = chain_product(mats)
    counters = ENGINE.counter_snapshot()
    assert counters.get("dispatches", 0) == 0
    assert counters["delta_rows_recomputed"] == 0
    assert second == first


def test_tag_lineage_gap_falls_back_full(monkeypatch):
    """A consumer whose stored producer version is neither the tag's
    prev_version nor its version (a run the entry missed) must take the
    counted full fallback, never a stale splice."""
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    rng = np.random.default_rng(212)
    a = random_block_sparse(6, 6, 2, 0.6, rng, "full")
    b = random_block_sparse(6, 6, 2, 0.6, rng, "full")
    da = spgemm_device(a, b)            # producer: entry v1, tag v1
    c = random_block_sparse(6, 6, 2, 0.6, rng, "full")
    spgemm_device(da, c)                # consumer stores ("tag", key, 1)
    # two producer re-runs the consumer never sees: v1 -> v2 -> v3
    a2 = _mutate_rows(a, np.unique(a.coords[:, 0])[:1])
    da2 = spgemm_device(a2, b)
    a3 = _mutate_rows(a2, np.unique(a.coords[:, 0])[1:2])
    da3 = spgemm_device(a3, b)
    ENGINE.reset()
    got = spgemm_device(da3, c)         # stored v1, tag prev=2: gap
    assert ENGINE.counter_snapshot()["delta_full_fallbacks"] == 1
    assert got.to_host() == _oracle(da3.to_host(), c)


# ------------------------------------------------------- stats + surfaces


def test_delta_stats_and_knobs_listing(monkeypatch, capsys):
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    rng = np.random.default_rng(213)
    a = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    spgemm(a, a, backend="xla")
    spgemm(a, a, backend="xla")
    st = delta.stats()
    assert st["full_fallbacks"] == 1 and st["hits"] == 1
    assert st["entries"] == 1 and st["enabled"] is True
    assert st["rows_total"] >= st["rows_recomputed"] > 0
    from spgemm_tpu.cli import run_knobs

    assert run_knobs([]) == 0
    out = capsys.readouterr().out
    assert "delta:" in out and "full_fallbacks=1" in out
    import json

    assert run_knobs(["--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["delta"]["hits"] == 1
    assert payload["plan_cache"]["evictions"] == 0


def test_plan_cache_eviction_counter(monkeypatch):
    """The plan cache's LRU pops are no longer invisible: stats() and the
    ENGINE counter both move on an eviction."""
    monkeypatch.setenv("SPGEMM_TPU_PLAN_CACHE_CAP", "1")
    rng = np.random.default_rng(214)
    a = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    b = random_block_sparse(6, 6, 2, 0.5, rng, "full")
    c = random_block_sparse(6, 6, 2, 0.9, rng, "full")
    ENGINE.reset()
    plan(a, b, backend="xla", platform="cpu")
    assert plancache.stats()["evictions"] == 0
    plan(a, c, backend="xla", platform="cpu")  # evicts at cap 1
    assert plancache.stats()["evictions"] == 1
    assert ENGINE.counter_snapshot()["plan_cache_evictions"] == 1
