"""Subprocess worker for the multi-process DCN tests (tests/test_multihost.py).

Usage: python _multihost_worker.py <coordinator> <num_procs> <proc_id> <dir> <n_mats> [die|exchange]
Builds a deterministic chain, partitions it by process, runs the multi-host
reduction, and (process 0) writes the result matrix file into <dir>/out.
With the optional 'die' flag, the LAST process exits hard right before the
DCN exchange -- the partner-loss fault injection for
test_partner_loss_fails_fast (survivors must fail fast, never hang).
With 'exchange', each rank builds a SKEWED synthetic partial directly (rank 0
holds <n_mats> tiles, every other rank 7) and runs only the partial-product
exchange -- the chunked-vs-padded A/B harness for
test_skewed_partials_chunked_exchange (process 0 dumps every gathered partial
to <dir>/exchange_out.npz; SPGEMM_TPU_DCN_CHUNK_MB comes in via the env).
"""

import logging
import os
import sys


def main():
    coordinator, num_procs, proc_id, workdir, n_mats = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        int(sys.argv[5]))
    mode = sys.argv[6] if len(sys.argv) > 6 else ""
    die = mode == "die"

    import jax
    from jax._src import xla_bridge

    assert not xla_bridge._backends
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    from spgemm_tpu.utils import jaxcompat

    # version-skew shim: heartbeat_timeout_seconds postdates the pinned
    # 0.4.x toolchain (partner-loss detection then uses the runtime default)
    jaxcompat.distributed_initialize(coordinator_address=coordinator,
                                     num_processes=num_procs,
                                     process_id=proc_id,
                                     heartbeat_timeout_seconds=5)

    if die and proc_id == num_procs - 1:
        # simulate host death at the DCN boundary: cluster formed, partial
        # owed, then gone without a goodbye (no MPI_Finalize analog runs)
        print(f"proc {proc_id} dying deliberately", flush=True)
        os._exit(17)

    import numpy as np

    from spgemm_tpu.parallel import multihost
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.gen import random_chain

    if mode == "exchange":
        # surface multihost's dcn-exchange ledger line on stdout: the test
        # asserts the logged peak bound against the knob
        logging.basicConfig(level=logging.INFO, stream=sys.stdout,
                            format="%(name)s %(message)s")
        from spgemm_tpu.utils.blockcsr import BlockSparseMatrix

        k = 4
        side = 64
        nnzb = n_mats if proc_id == 0 else 7  # one rank dwarfs the others
        rng = np.random.default_rng(1000 + proc_id)
        idx = rng.choice(side * side, size=nnzb, replace=False)
        idx.sort()
        coords = np.stack(np.divmod(idx, side), axis=1).astype(np.int64)
        tiles = rng.integers(0, 1 << 64, size=(nnzb, k, k), dtype=np.uint64)
        partial = BlockSparseMatrix(rows=side, cols=side, k=k,
                                    coords=coords, tiles=tiles)
        # chunked exchange first (SPGEMM_TPU_DCN_CHUNK_MB from the test's
        # env), then the legacy padded path in the SAME session -- one
        # cluster bring-up, two exchange flavors to A/B
        chunked = multihost._allgather_partials(partial, k)
        os.environ["SPGEMM_TPU_DCN_CHUNK_MB"] = "0"
        padded = multihost._allgather_partials(partial, k)
        if proc_id == 0:
            for name, parts in (("chunked", chunked), ("padded", padded)):
                np.savez(os.path.join(workdir, f"exchange_{name}.npz"),
                         **{f"coords{i}": p.coords for i, p in enumerate(parts)},
                         **{f"tiles{i}": p.tiles for i, p in enumerate(parts)})
        print(f"proc {proc_id} done", flush=True)
        return

    k = 2
    mats = random_chain(n_mats, 4, k, 0.5, np.random.default_rng(777), "full")
    result = multihost.run_distributed(
        "unused", k, len(mats), loader=lambda s, e: mats[s : e + 1])
    if jax.process_index() == 0:
        io_text.write_matrix(f"{workdir}/out", result)
    print(f"proc {proc_id} done", flush=True)


if __name__ == "__main__":
    main()
