"""Subprocess worker for the multi-process DCN tests (tests/test_multihost.py).

Usage: python _multihost_worker.py <coordinator> <num_procs> <proc_id> <dir> <n_mats>
Builds a deterministic chain, partitions it by process, runs the multi-host
reduction, and (process 0) writes the result matrix file into <dir>/out.
"""

import sys


def main():
    coordinator, num_procs, proc_id, workdir, n_mats = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        int(sys.argv[5]))

    import jax
    from jax._src import xla_bridge

    assert not xla_bridge._backends
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_procs, process_id=proc_id)

    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    import numpy as np

    from spgemm_tpu.parallel import multihost
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.gen import random_chain

    k = 2
    mats = random_chain(n_mats, 4, k, 0.5, np.random.default_rng(777), "full")
    result = multihost.run_distributed(
        "unused", k, len(mats), loader=lambda s, e: mats[s : e + 1])
    if jax.process_index() == 0:
        io_text.write_matrix(f"{workdir}/out", result)
    print(f"proc {proc_id} done", flush=True)


if __name__ == "__main__":
    main()
