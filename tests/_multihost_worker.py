"""Subprocess worker for the multi-process DCN tests (tests/test_multihost.py).

Usage: python _multihost_worker.py <coordinator> <num_procs> <proc_id> <dir> <n_mats> [die]
Builds a deterministic chain, partitions it by process, runs the multi-host
reduction, and (process 0) writes the result matrix file into <dir>/out.
With the optional 'die' flag, the LAST process exits hard right before the
DCN exchange -- the partner-loss fault injection for
test_partner_loss_fails_fast (survivors must fail fast, never hang).
"""

import os
import sys


def main():
    coordinator, num_procs, proc_id, workdir, n_mats = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        int(sys.argv[5]))
    die = len(sys.argv) > 6 and sys.argv[6] == "die"

    import jax
    from jax._src import xla_bridge

    assert not xla_bridge._backends
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    from spgemm_tpu.utils import jaxcompat

    # version-skew shim: heartbeat_timeout_seconds postdates the pinned
    # 0.4.x toolchain (partner-loss detection then uses the runtime default)
    jaxcompat.distributed_initialize(coordinator_address=coordinator,
                                     num_processes=num_procs,
                                     process_id=proc_id,
                                     heartbeat_timeout_seconds=5)

    if die and proc_id == num_procs - 1:
        # simulate host death at the DCN boundary: cluster formed, partial
        # owed, then gone without a goodbye (no MPI_Finalize analog runs)
        print(f"proc {proc_id} dying deliberately", flush=True)
        os._exit(17)

    import numpy as np

    from spgemm_tpu.parallel import multihost
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.gen import random_chain

    k = 2
    mats = random_chain(n_mats, 4, k, 0.5, np.random.default_rng(777), "full")
    result = multihost.run_distributed(
        "unused", k, len(mats), loader=lambda s, e: mats[s : e + 1])
    if jax.process_index() == 0:
        io_text.write_matrix(f"{workdir}/out", result)
    print(f"proc {proc_id} done", flush=True)


if __name__ == "__main__":
    main()
