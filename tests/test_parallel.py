"""Distribution layer on the 8-virtual-device CPU mesh (SURVEY.md section 4)."""

import jax
import numpy as np
import pytest

from spgemm_tpu.chain import chain_product
from spgemm_tpu.ops import u64
from spgemm_tpu.parallel.chainpart import chain_product_partitioned, partition_chain
from spgemm_tpu.parallel.innershard import spgemm_inner
from spgemm_tpu.parallel.mesh import default_mesh
from spgemm_tpu.parallel.rowshard import spgemm_sharded
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_block_sparse, random_chain
from spgemm_tpu.utils.semantics import (MAX_INT, field_spgemm_oracle,
                                        spgemm_oracle)

import jax.numpy as jnp


def test_virtual_mesh_present():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual CPU devices"


# -- rowshard: bit-exact output-space sharding ------------------------------

@pytest.mark.parametrize("dist", ["full", "adversarial"])
def test_rowshard_vs_oracle_bit_exact(dist):
    rng = np.random.default_rng(300 + len(dist))
    k = 4
    a = random_block_sparse(7, 7, k, 0.4, rng, dist)
    b = random_block_sparse(7, 7, k, 0.4, rng, dist)
    got = spgemm_sharded(a, b)
    want = spgemm_oracle(a.to_dict(), b.to_dict(), k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, k, want)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


def test_rowshard_small_key_count():
    """Fewer output keys than devices: padding must not corrupt results."""
    rng = np.random.default_rng(310)
    k = 2
    a = random_block_sparse(2, 2, k, 1.0, rng, "full")
    b = random_block_sparse(2, 2, k, 1.0, rng, "full")
    from spgemm_tpu.ops.spgemm import spgemm
    assert spgemm_sharded(a, b) == spgemm(a, b)


# -- field-mode arithmetic --------------------------------------------------

def test_field_ops_vs_python_int():
    rng = np.random.default_rng(320)
    a = rng.integers(0, 1 << 64, size=512, dtype=np.uint64)
    b = rng.integers(0, 1 << 64, size=512, dtype=np.uint64)
    corners = np.array([0, 1, MAX_INT, MAX_INT - 1, 1 << 32, 1 << 63],
                       dtype=np.uint64)
    ca, cb = np.meshgrid(corners, corners)
    a, b = np.concatenate([a, ca.ravel()]), np.concatenate([b, cb.ravel()])
    ah, al = u64.u64_to_hilo(a)
    bh, bl = u64.u64_to_hilo(b)
    ja = (jnp.asarray(ah), jnp.asarray(al))
    jb = (jnp.asarray(bh), jnp.asarray(bl))

    sh, sl = u64.addmod_field(*ja, *jb)
    got_add = u64.hilo_to_u64(np.asarray(sh), np.asarray(sl))
    want_add = np.array([(int(x) + int(y)) % MAX_INT for x, y in zip(a, b)],
                        dtype=np.uint64)
    assert np.array_equal(got_add, want_add)

    mh, ml = u64.mulmod_field(*ja, *jb)
    got_mul = u64.hilo_to_u64(np.asarray(mh), np.asarray(ml))
    want_mul = np.array([(int(x) * int(y)) % MAX_INT for x, y in zip(a, b)],
                        dtype=np.uint64)
    assert np.array_equal(got_mul, want_mul)


def test_mac_field_b32_matches_mac_field_below_2_32():
    """The proven bounded-field MAC (u64.mac_field_b32, ~6x fewer ops) must
    agree with mac_field for every operand pair below 2^32, across the
    accumulator's FULL residue range -- including acc values that make the
    accumulate step wrap and fold (the part b32 does not shortcut)."""
    rng = np.random.default_rng(350)
    n = 512
    a = rng.integers(0, 1 << 32, size=n, dtype=np.uint64)
    b = rng.integers(0, 1 << 32, size=n, dtype=np.uint64)
    acc = rng.integers(0, MAX_INT, size=n, dtype=np.uint64)
    # corners: max operands against accs at the fold boundaries
    corners_ab = np.array([0, 1, (1 << 32) - 1], dtype=np.uint64)
    corners_acc = np.array([0, MAX_INT - 1, MAX_INT - 2, 1 << 63],
                           dtype=np.uint64)
    ca, cacc = np.meshgrid(corners_ab, corners_acc)
    a = np.concatenate([a, ca.ravel(), np.full(cacc.size, (1 << 32) - 1,
                                               np.uint64)])
    b = np.concatenate([b, np.full(ca.size, (1 << 32) - 1, np.uint64),
                        ca.ravel()])
    acc = np.concatenate([acc, cacc.ravel(), cacc.ravel()])

    ah, al = map(jnp.asarray, u64.u64_to_hilo(a))
    bh, bl = map(jnp.asarray, u64.u64_to_hilo(b))
    ch, cl = map(jnp.asarray, u64.u64_to_hilo(acc))
    wh, wl = u64.mac_field(ch, cl, ah, al, bh, bl)
    gh, gl = u64.mac_field_b32(ch, cl, al, bl)
    assert np.array_equal(np.asarray(gh), np.asarray(wh))
    assert np.array_equal(np.asarray(gl), np.asarray(wl))


def test_innershard_matches_reference_on_small_values():
    """Below 2^32 nothing wraps, so field mode == reference mode exactly."""
    rng = np.random.default_rng(330)
    k = 4
    a = random_block_sparse(6, 6, k, 0.5, rng, "small")
    b = random_block_sparse(6, 6, k, 0.5, rng, "small")
    got = spgemm_inner(a, b)
    want = spgemm_oracle(a.to_dict(), b.to_dict(), k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, k, want)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


def test_innershard_field_semantics_on_full_values():
    """On arbitrary u64 data, innershard computes the clean mod-(2^64-1) product."""
    rng = np.random.default_rng(340)
    k = 2
    a = random_block_sparse(4, 4, k, 0.6, rng, "full")
    b = random_block_sparse(4, 4, k, 0.6, rng, "full")
    got = spgemm_inner(a, b)
    want = field_spgemm_oracle(a.to_dict(), b.to_dict(), k)
    for i, (r, c) in enumerate(got.coords):
        tile = np.array(want[(int(r), int(c))], dtype=np.uint64)
        assert np.array_equal(got.tiles[i], tile)


@pytest.mark.parametrize("strategy", ["inner", "ring"])
def test_field_mode_wrap_contract(strategy):
    """CONTRACT (parallel/innershard.py docstring): above 2^32 the field-mode
    strategies return the clean mod-(2^64-1) residue of the true integer
    product -- NOT the reference's wrap-then-mod value.  Pin both halves on
    adversarial corner values: (a) the residue is exactly the python-int
    field oracle, (b) this input really is in the deviation regime (the two
    oracles disagree), so the contract is exercised, not vacuous."""
    from spgemm_tpu.parallel.ring import spgemm_ring
    rng = np.random.default_rng(345)
    k = 4
    a = random_block_sparse(6, 6, k, 0.5, rng, "adversarial")
    b = random_block_sparse(6, 6, k, 0.5, rng, "adversarial")
    fn = {"inner": spgemm_inner, "ring": spgemm_ring}[strategy]
    got = fn(a, b)
    want = field_spgemm_oracle(a.to_dict(), b.to_dict(), k)
    for i, (r, c) in enumerate(got.coords):
        tile = want[(int(r), int(c))]
        assert np.array_equal(got.tiles[i], tile), (
            f"{strategy} deviates from the clean-residue contract at "
            f"key ({r},{c})")
    # (b) deviation regime check: reference wrap-then-mod differs somewhere
    ref = spgemm_oracle(a.to_dict(), b.to_dict(), k)
    assert any(
        not np.array_equal(want[key], ref_tile)
        for key, ref_tile in ref.items()
    ), "input never triggered a wrap -- contract test is vacuous"


# -- chain partition (MPI semantics) ----------------------------------------

def test_partition_chain_reference_arithmetic():
    # N=10, P=3: q=3 -> [0,2],[3,5],[6,9] (last rank takes remainder)
    assert partition_chain(10, 3) == [(0, 2), (3, 5), (6, 9)]
    # N < P: only rank 0 works
    assert partition_chain(2, 4) == [(0, 1), None, None, None]
    assert partition_chain(8, 1) == [(0, 7)]


@pytest.mark.parametrize("n,p", [(7, 3), (8, 2), (3, 8), (5, 5)])
def test_chain_partitioned_matches_manual(n, p):
    rng = np.random.default_rng(350 + n * 10 + p)
    k = 2
    mats = random_chain(n, 3, k, 0.6, rng, "full")
    got = chain_product_partitioned(mats, p)
    parts = [pt for pt in partition_chain(n, p) if pt is not None]
    partials = [chain_product(mats[s : e + 1]) for s, e in parts]
    want = partials[0] if len(partials) == 1 else chain_product(partials)
    assert got == want


def test_mesh_helper():
    m = default_mesh(4)
    assert m.devices.size == 4


# -- ring SpGEMM (B rotation over ICI) --------------------------------------

def test_ring_matches_reference_on_small_values():
    """Below 2^32 field mode == reference mode, so ring == oracle exactly."""
    from spgemm_tpu.parallel.ring import spgemm_ring
    rng = np.random.default_rng(360)
    k = 4
    a = random_block_sparse(8, 8, k, 0.4, rng, "small")
    b = random_block_sparse(8, 8, k, 0.4, rng, "small")
    got = spgemm_ring(a, b)
    want = spgemm_oracle(a.to_dict(), b.to_dict(), k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, k, want)
    assert np.array_equal(got.coords, want_m.coords)
    assert np.array_equal(got.tiles, want_m.tiles)


def test_ring_matches_innershard_on_full_values():
    """Both are field-mode: identical results on arbitrary u64 data."""
    from spgemm_tpu.parallel.ring import spgemm_ring
    rng = np.random.default_rng(361)
    k = 2
    a = random_block_sparse(6, 6, k, 0.5, rng, "full")
    b = random_block_sparse(6, 6, k, 0.5, rng, "full")
    assert spgemm_ring(a, b) == spgemm_inner(a, b)


def test_ring_fewer_keys_than_devices():
    from spgemm_tpu.parallel.ring import spgemm_ring
    rng = np.random.default_rng(362)
    k = 2
    a = random_block_sparse(2, 2, k, 1.0, rng, "small")
    b = random_block_sparse(2, 2, k, 1.0, rng, "small")
    want = spgemm_oracle(a.to_dict(), b.to_dict(), k)
    want_m = BlockSparseMatrix.from_dict(a.rows, b.cols, k, want)
    assert spgemm_ring(a, b) == want_m


def test_plan_ring_packing_matches_naive_oracle():
    """Pin the vectorized RANK-COMPACTED planner cell by cell: rank list r
    must hold, for every (device, slab), exactly the device's keys with
    >= r+1 pairs in that slab -- each carrying that cell's r-th pair in the
    original j-ascending order -- with unique acc rows per rank, sentinel
    padding elsewhere, and nothing else (the dense (cell, p_max) pair axis
    was the round-6 4.2x padded-MAC waste this layout removed).  Pairs
    beyond RANK_UNROLL_MAX must land in the dense TAIL block, in order."""
    from spgemm_tpu.ops.symbolic import JoinResult
    from spgemm_tpu.parallel.ring import RANK_UNROLL_MAX, plan_ring

    rng = np.random.default_rng(363)
    n_keys, nnzb_b, n_dev = 37, 53, 8
    fanouts = rng.integers(0, 7, size=n_keys)
    fat = int(fanouts.argmax())
    fanouts[fat] = RANK_UNROLL_MAX + 4  # deep key: must spill into the tail
    pair_ptr = np.concatenate(([0], np.cumsum(fanouts))).astype(np.int64)
    total = int(pair_ptr[-1])
    side = 7
    keys = np.stack(np.divmod(np.arange(n_keys, dtype=np.int64), side), axis=1)
    pair_a = rng.integers(0, nnzb_b, size=total).astype(np.int32)
    pair_b = rng.integers(0, nnzb_b, size=total).astype(np.int32)
    # concentrate the fat key's pairs in slab 0's B range so ONE cell is
    # deeper than the rank-unroll cap
    pair_b[pair_ptr[fat]: pair_ptr[fat + 1]] = \
        rng.integers(0, nnzb_b // n_dev, size=fanouts[fat]).astype(np.int32)
    join = JoinResult(keys=keys, pair_ptr=pair_ptr,
                      pair_a=pair_a, pair_b=pair_b)

    key_chunks, slab_bounds, ranks, tail, s_max, k_max = \
        plan_ring(join, nnzb_b, n_dev)
    assert k_max == max(len(c) for c in key_chunks)
    assert len(ranks) <= RANK_UNROLL_MAX and tail is not None
    slab_of_pair = np.searchsorted(slab_bounds, pair_b, side="right") - 1
    max_fanout_per_cell = 0
    for d, chunk in enumerate(key_chunks):
        for s in range(n_dev):
            for row, ki in enumerate(chunk):
                lo, hi = pair_ptr[ki], pair_ptr[ki + 1]
                sel = slab_of_pair[lo:hi] == s
                want_a = pair_a[lo:hi][sel]  # original j-ascending order
                want_b = pair_b[lo:hi][sel] - slab_bounds[s]
                max_fanout_per_cell = max(max_fanout_per_cell, len(want_a))
                for r in range(len(ranks)):
                    row_idx, pa_r, pb_r = ranks[r]
                    slots = np.flatnonzero(row_idx[d, s] == row)
                    if r < len(want_a):  # cell owes its r-th pair to rank r
                        assert len(slots) == 1, \
                            "acc row must appear exactly once per rank"
                        assert pa_r[d, s, slots[0]] == want_a[r]
                        assert pb_r[d, s, slots[0]] == want_b[r]
                    else:
                        assert len(slots) == 0, \
                            "rank list holds a cell with no rank-r pair"
                # pairs past the unroll cap: the cell's tail slot holds
                # them contiguously, in order, sentinel-padded
                row_t, pa_t, pb_t = tail
                slots = np.flatnonzero(row_t[d, s] == row)
                spill_a = want_a[RANK_UNROLL_MAX:]
                spill_b = want_b[RANK_UNROLL_MAX:]
                if len(spill_a):
                    assert len(slots) == 1, "deep cell missing a tail slot"
                    got_a, got_b = pa_t[d, s, slots[0]], pb_t[d, s, slots[0]]
                    assert np.array_equal(got_a[: len(spill_a)], spill_a)
                    assert np.array_equal(got_b[: len(spill_b)], spill_b)
                    assert np.all(got_a[len(spill_a):] == -1)
                    assert np.all(got_b[len(spill_b):] == s_max)
                else:
                    assert len(slots) == 0, "shallow cell occupies the tail"
    # the schedule depth is exactly the deepest cell
    assert max_fanout_per_cell > RANK_UNROLL_MAX
    assert tail[1].shape[-1] == max_fanout_per_cell - RANK_UNROLL_MAX
    # padding sentinels on all dummy rows, in every rank and the tail
    for row_idx, pa_r, pb_r in ranks:
        assert np.all(pa_r[row_idx == k_max] == -1)
        assert np.all(pb_r[row_idx == k_max] == s_max)
    row_t, pa_t, pb_t = tail
    assert np.all(pa_t[row_t == k_max] == -1)


def test_chain_product_on_devices_matches_partitioned():
    """Device-parallel chain DP must be bit-identical to the single-device
    mpirun-semantics replica at the same P (and to the oracle)."""
    import jax

    from spgemm_tpu.parallel.chainpart import (
        chain_product_on_devices, chain_product_partitioned)
    from spgemm_tpu.utils.gen import random_chain
    from spgemm_tpu.utils.semantics import chain_oracle

    devs = jax.devices()[:4]
    rng = np.random.default_rng(123)
    k = 2
    mats = random_chain(9, 4, k, 0.5, rng, "adversarial")
    got = chain_product_on_devices(mats, devices=devs)
    want_semantic = chain_product_partitioned(mats, len(devs))
    assert got == want_semantic
    # and the P-rank reduction tree itself is what the reference computes
    want_m = BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k,
        chain_oracle([chain_oracle([m.to_dict() for m in mats[s:e + 1]], k)
                      for s, e in [(0, 1), (2, 3), (4, 5), (6, 8)]], k))
    assert got == want_m


def test_chain_product_on_devices_degenerate_n_lt_p():
    import jax

    from spgemm_tpu.parallel.chainpart import chain_product_on_devices
    from spgemm_tpu.utils.gen import random_chain
    from spgemm_tpu.utils.semantics import chain_oracle

    rng = np.random.default_rng(124)
    k = 2
    mats = random_chain(3, 3, k, 0.6, rng, "full")
    got = chain_product_on_devices(mats, devices=jax.devices()[:8])
    want = BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k,
        chain_oracle([m.to_dict() for m in mats], k))
    assert got == want


def test_chain_product_on_devices_explicit_num_parts():
    """Parity requires matching the reference's P: num_parts decouples P
    from the device count (ranks cycle over devices)."""
    from spgemm_tpu.parallel.chainpart import (
        chain_product_on_devices, chain_product_partitioned)
    from spgemm_tpu.utils.gen import random_chain

    rng = np.random.default_rng(125)
    mats = random_chain(7, 4, 2, 0.5, rng, "full")
    got = chain_product_on_devices(mats, devices=jax.devices()[:2],
                                   num_parts=3)
    want = chain_product_partitioned(mats, 3)
    assert got == want
