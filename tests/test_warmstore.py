"""Persistent warm start (PR 11 tentpole, ops/warmstore).

The standing contracts:
  * a persisted plan replays byte-identically: the codec round-trips the
    exact join, every padded round, and the assembly permutation;
  * the warm tier is invisible to correctness: warm on/off is a
    bit-identical whole-engine A/B (persistence short-circuits planning
    and retention, never fold order);
  * a restarted process's first same-structure contact is a warm hit
    (plan) and a clean delta (retained result), not a full fallback;
  * EVERY doubt -- truncated file, schema skew, jit-static knob vector
    mismatch, foreign identity, a dir locked by a live process -- is a
    counted cold fallback, never a crash and never wrong bits (the
    utils/checkpoint.latest_pass discipline);
  * the on-disk store is bounded (SPGEMM_TPU_WARM_MAX_MB, oldest
    pruned).
"""

import os

import numpy as np
import pytest

from spgemm_tpu.ops import delta, plancache, warmstore
from spgemm_tpu.ops.spgemm import plan as plan_spgemm
from spgemm_tpu.ops.symbolic import (PLAN_CODEC_VERSION, plan_from_arrays,
                                     plan_to_arrays)
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.gen import random_block_sparse
from spgemm_tpu.utils.semantics import spgemm_oracle


@pytest.fixture(autouse=True)
def _fresh_stores():
    warmstore.reset()
    plancache.clear()
    delta.clear()
    yield
    warmstore.reset()
    plancache.clear()
    delta.clear()


class _Structure:
    """coords/nnzb/k/val_bound stand-in: all ops/spgemm.plan reads."""

    def __init__(self, n_rows: int, per_row: int, seed: int, k: int = 8):
        rng = np.random.default_rng(seed)
        rows = np.repeat(np.arange(n_rows, dtype=np.int64), per_row)
        cols = rng.integers(0, n_rows, size=len(rows), dtype=np.int64)
        self.coords = np.unique(np.stack([rows, cols], axis=1), axis=0)
        self.nnzb = len(self.coords)
        self.k = k
        self.val_bound = 0


def _host_plan(seed: int = 0, n_rows: int = 20):
    a = _Structure(n_rows, 3, seed)
    b = _Structure(n_rows, 3, seed + 1)
    p = plan_spgemm(a, b, backend="xla", platform="cpu")
    p.ensure_exact()
    return p


def _assert_plans_equal(p1, p2):
    assert p1.fingerprint == p2.fingerprint
    assert (p1.backend, p1.platform, p1.k) == (p2.backend, p2.platform,
                                               p2.k)
    assert (p1.a_nnzb, p1.b_nnzb, p1.batch) == (p2.a_nnzb, p2.b_nnzb,
                                                p2.batch)
    assert p1.round_size == p2.round_size
    assert p1.split_fanout == p2.split_fanout
    assert np.array_equal(p1.join.keys, p2.join.keys)
    assert np.array_equal(p1.join.pair_ptr, p2.join.pair_ptr)
    assert np.array_equal(p1.join.pair_a, p2.join.pair_a)
    assert np.array_equal(p1.join.pair_b, p2.join.pair_b)
    assert len(p1.rounds) == len(p2.rounds)
    for r1, r2 in zip(p1.rounds, p2.rounds):
        assert np.array_equal(r1.key_index, r2.key_index)
        assert np.array_equal(r1.pa, r2.pa)
        assert np.array_equal(r1.pb, r2.pb)
        assert r1.max_fanout == r2.max_fanout
    assert (p1.take is None) == (p2.take is None)
    if p1.take is not None:
        assert np.array_equal(p1.take, p2.take)
    assert np.array_equal(p1._a_coords, p2._a_coords)
    assert np.array_equal(p1._b_coords, p2._b_coords)


# ------------------------------------------------------------------ codec


def test_plan_codec_roundtrip():
    p = _host_plan()
    arrays = plan_to_arrays(p)
    assert arrays is not None
    assert int(arrays["codec"]) == PLAN_CODEC_VERSION
    _assert_plans_equal(p, plan_from_arrays(arrays,
                                            fingerprint=p.fingerprint))


def test_plan_codec_refuses_version_skew():
    arrays = plan_to_arrays(_host_plan())
    arrays["codec"] = np.int64(PLAN_CODEC_VERSION + 1)
    with pytest.raises(ValueError, match="version skew"):
        plan_from_arrays(arrays)


def test_deferred_plan_is_not_encodable():
    """An estimator-routed plan whose exact join has not landed has
    nothing worth persisting -- the codec must refuse, not half-write."""
    p = _host_plan()
    p._exact_builder = lambda plan: None  # re-arm deferral artificially
    assert plan_to_arrays(p) is None


# -------------------------------------------------------- warm plan tier


def test_warm_plan_survives_process_cache_clear(monkeypatch, tmp_path):
    """plancache.clear() simulates process death: the second plan() must
    be served from disk (warm hit), byte-identical to the original."""
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    p1 = _host_plan(seed=11)
    warmstore.flush()
    assert warmstore.stats()["plans"] == 1
    plancache.clear()
    p2 = _host_plan(seed=11)
    st = warmstore.stats()
    assert st["plan_hits"] == 1 and st["corrupt"] == 0
    _assert_plans_equal(p1, p2)
    # and the warm-loaded object is now the in-process L1 entry
    p3 = _host_plan(seed=11)
    assert p3 is p2


def test_warm_off_is_exactly_cold(monkeypatch, tmp_path):
    """SPGEMM_TPU_WARM=0 with a populated dir sitting right there must
    touch nothing -- the whole-engine A/B contract."""
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    _host_plan(seed=12)
    warmstore.flush()
    warmstore.reset()
    monkeypatch.setenv("SPGEMM_TPU_WARM", "0")
    plancache.clear()
    _host_plan(seed=12)
    st = warmstore.stats()
    assert not st["active"]
    assert st["plan_hits"] == 0 and st["plan_misses"] == 0


# ------------------------------------- corruption / skew / lock fallbacks


def _seed_one_plan(tmp_path):
    p = _host_plan(seed=13)
    warmstore.flush()
    files = [n for n in os.listdir(tmp_path) if n.startswith("plan-")]
    assert len(files) == 1
    return p, os.path.join(str(tmp_path), files[0])


def test_truncated_entry_is_counted_cold_fallback(monkeypatch, tmp_path):
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    p, path = _seed_one_plan(tmp_path)
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[: len(raw) // 3])  # torn write
    plancache.clear()
    p2 = _host_plan(seed=13)  # must re-plan cold, not crash
    st = warmstore.stats()
    assert st["corrupt"] == 1 and st["plan_hits"] == 0
    _assert_plans_equal(p, p2)  # the cold re-plan is the same plan


def test_schema_skew_is_counted_cold_fallback(monkeypatch, tmp_path):
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    p, path = _seed_one_plan(tmp_path)
    with np.load(path, allow_pickle=False) as z:
        payload = {name: z[name] for name in z.files}
    payload["schema"] = np.int64(warmstore.SCHEMA_VERSION + 1)
    with open(path, "wb") as f:
        np.savez_compressed(f, **payload)
    plancache.clear()
    _host_plan(seed=13)
    st = warmstore.stats()
    assert st["corrupt"] == 1 and st["plan_hits"] == 0


def test_knob_vector_mismatch_is_counted_cold_fallback(monkeypatch,
                                                       tmp_path):
    """A hand-copied warm dir from a different jit-static config: the
    fingerprint normally diverges too, but the stored vector is the
    defense in depth -- tamper the file onto the current fingerprint and
    the envelope check must still refuse it."""
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    p, path = _seed_one_plan(tmp_path)
    with np.load(path, allow_pickle=False) as z:
        payload = {name: z[name] for name in z.files}
    payload["knobs"] = np.array("(('SPGEMM_TPU_MXU_R', '999'),)")
    with open(path, "wb") as f:
        np.savez_compressed(f, **payload)
    plancache.clear()
    _host_plan(seed=13)
    st = warmstore.stats()
    assert st["corrupt"] == 1 and st["plan_hits"] == 0


def test_locked_dir_runs_cold_not_crashed(monkeypatch, tmp_path):
    """Two concurrent daemons pointed at one warm dir: the loser of the
    flock must run cold (counted, evented), never corrupt the winner."""
    import fcntl

    lock_path = os.path.join(str(tmp_path), "lock")
    holder = open(lock_path, "a+")
    fcntl.flock(holder.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    try:
        monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
        assert warmstore.configure() is False
        assert not warmstore.active()
        assert "locked" in (warmstore.disabled_reason() or "")
        # the engine path stays fully functional, just cold
        _host_plan(seed=14)
        st = warmstore.stats()
        assert st["plans"] == 0 and st["plan_hits"] == 0
    finally:
        holder.close()
    # holder gone: a reconfigure wins the lock and persistence resumes
    warmstore.reset()
    assert warmstore.configure() is True
    assert warmstore.active()


def test_winner_holds_the_flock(monkeypatch, tmp_path):
    """The configured store actually owns the dir: a second flock
    attempt (another process's configure) must fail while it lives."""
    import fcntl

    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    assert warmstore.configure() is True
    probe = open(os.path.join(str(tmp_path), "lock"), "a+")
    try:
        with pytest.raises(OSError):
            fcntl.flock(probe.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    finally:
        probe.close()


# ------------------------------------------------------------ size budget


def test_budget_prunes_oldest_entries(monkeypatch, tmp_path):
    """The prune is a file-level policy (oldest npz first, xla/ and the
    lock excluded) -- drive it with entry-shaped files of known size and
    age, plus one real freshest plan that must survive."""
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    monkeypatch.setenv("SPGEMM_TPU_WARM_MAX_MB", "1")
    rng = np.random.default_rng(0)
    names = [f"plan-{'%02d' % i * 20}.npz" for i in range(5)]
    for i, name in enumerate(names):  # 5 x 300 KB, oldest first
        path = os.path.join(str(tmp_path), name)
        open(path, "wb").write(rng.bytes(300 << 10))
        os.utime(path, (1_000_000 + i, 1_000_000 + i))
    p = _host_plan(seed=100)
    warmstore.save_plan(p)  # the freshest entry: newest mtime
    assert warmstore.stats()["bytes"] > 1 << 20  # over budget pre-prune
    warmstore.flush()
    st = warmstore.stats()
    assert st["pruned"] >= 1
    assert st["bytes"] <= 1 << 20
    survivors = set(os.listdir(tmp_path))
    assert f"plan-{p.fingerprint}.npz" in survivors  # newest kept
    assert names[0] not in survivors                 # oldest went first
    assert "lock" in survivors                       # never pruned


# ----------------------------------------------------------- delta entries


def test_delta_entry_roundtrip_host_only(monkeypatch, tmp_path):
    """save_delta/load_delta round-trip both provenance kinds and the
    result planes, without touching a device (warmstore is jax-free)."""
    from types import SimpleNamespace

    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    rng = np.random.default_rng(0)
    res = SimpleNamespace(
        rows=16, cols=16, k=4,
        coords=np.array([[0, 0], [1, 1]], np.int64),
        hi=rng.integers(0, 1 << 32, (3, 4, 4)).astype(np.uint32),
        lo=rng.integers(0, 1 << 32, (3, 4, 4)).astype(np.uint32),
        val_bound=None)
    digs = np.array([b"x" * 32, b"y" * 32], dtype="S32")
    entry = delta.DeltaEntry(
        key="fp|dev[0]x[0]", version=7,
        a_src=("digest", np.array([0, 1], np.int64), digs),
        b_src=("tag", "otherkey", 3), result=res, out_rows=2)
    assert warmstore.save_delta(entry.key, entry)
    raw = warmstore.load_delta(entry.key)
    assert raw is not None
    assert raw["version"] == 7 and raw["out_rows"] == 2
    kind, rows, got_digs = raw["a_src"]
    assert kind == "digest"
    assert np.array_equal(rows, entry.a_src[1])
    assert np.array_equal(got_digs, digs)
    assert raw["b_src"] == ("tag", "otherkey", 3)
    got = raw["result"]
    assert (got["rows"], got["cols"], got["k"]) == (16, 16, 4)
    assert got["val_bound"] is None
    assert np.array_equal(got["hi"], res.hi)
    assert np.array_equal(got["lo"], res.lo)
    assert np.array_equal(got["coords"], res.coords)
    # a different key never aliases (miss, not a foreign entry)
    assert warmstore.load_delta("fp|dev[1]x[1]") is None


def test_seed_entry_fences_the_version_counter():
    """A rehydrated entry's version must fence the global source: the
    next handed-out version is strictly greater, so restored lineages
    can never alias fresh ones."""
    from types import SimpleNamespace

    entry = delta.DeltaEntry(key="k", version=1000, a_src=("opaque",),
                             b_src=("opaque",),
                             result=SimpleNamespace(), out_rows=0)
    delta.seed_entry(entry)
    assert delta.lookup("k") is entry
    assert delta._next_version() > 1000


def test_configure_fences_versions_over_all_disk_entries(monkeypatch,
                                                         tmp_path):
    """Bind-time version fence (review hardening): a fresh process must
    never re-issue a version some surviving on-disk tag REFERENCES --
    even when the referenced producer's own entry was pruned or corrupt
    -- or a rehydrated consumer would read a fresh producer tag as
    already-consumed and splice stale rows.  The fence runs at
    configure(), before any multiply can mint a version."""
    from types import SimpleNamespace

    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    res = SimpleNamespace(
        rows=4, cols=4, k=2, coords=np.zeros((0, 2), np.int64),
        hi=np.zeros((1, 2, 2), np.uint32),
        lo=np.zeros((1, 2, 2), np.uint32), val_bound=0)
    digs = np.zeros(0, dtype="S32")
    entry = delta.DeltaEntry(
        key="consumer", version=500,
        a_src=("tag", "producer", 499),  # references a PRUNED producer
        b_src=("digest", np.zeros(0, np.int64), digs),
        result=res, out_rows=0)
    assert warmstore.configure() is True
    assert warmstore.save_delta(entry.key, entry)
    # process death: in-memory state gone (the monotonic counter resets
    # with the process), disk survives
    warmstore.reset()
    delta.clear()
    monkeypatch.setattr(delta, "_VERSION", 0)
    assert warmstore.configure() is True  # the fence runs here
    assert delta._next_version() > 500


def test_warm_restart_is_clean_delta_end_to_end(monkeypatch, tmp_path):
    """The acceptance path in-process: execute, flush, simulate process
    death (clear every in-memory store), execute again -- the second run
    must be a delta hit with ZERO recomputed rows (the digests prove the
    operands unchanged), bit-exact vs the oracle."""
    from spgemm_tpu.ops.spgemm import spgemm_device

    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    rng = np.random.default_rng(42)
    a = random_block_sparse(10, 8, 2, 0.5, rng, "full")
    b = random_block_sparse(10, 8, 2, 0.5, rng, "full")
    spgemm_device(a, b).block_until_ready()
    warmstore.flush()
    st = warmstore.stats()
    assert st["plans"] == 1 and st["deltas"] == 1
    # process death: every in-memory store gone, disk survives
    plancache.clear()
    delta.clear()
    warmstore.reset()
    got = spgemm_device(a, b).to_host()
    dst = delta.stats()
    assert dst["hits"] == 1 and dst["full_fallbacks"] == 0, dst
    assert dst["rows_recomputed"] == 0 and dst["rows_total"] > 0
    wst = warmstore.stats()
    assert wst["plan_hits"] == 1 and wst["delta_hits"] == 1
    want = spgemm_oracle(a.to_dict(), b.to_dict(), a.k)
    got_d = got.to_dict()
    assert set(got_d) == set(want)
    for key in want:
        assert np.array_equal(got_d[key], want[key])


def test_warm_restart_mutated_input_recomputes_dirty_rows(monkeypatch,
                                                          tmp_path):
    """Restart + a VALUE mutation: the rehydrated entry's digests find
    the dirty row, only its reach re-folds, and the splice against the
    re-uploaded retained planes is bit-exact."""
    from spgemm_tpu.ops.spgemm import spgemm_device

    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    monkeypatch.setenv("SPGEMM_TPU_DELTA", "1")
    rng = np.random.default_rng(43)
    a = random_block_sparse(12, 8, 2, 0.5, rng, "full")
    b = random_block_sparse(12, 8, 2, 0.5, rng, "full")
    spgemm_device(a, b).block_until_ready()
    warmstore.flush()
    plancache.clear()
    delta.clear()
    warmstore.reset()
    tiles = a.tiles.copy()
    tiles[0, 0, 0] += np.uint64(1)  # one tile-row goes dirty
    a2 = BlockSparseMatrix(rows=a.rows, cols=a.cols, k=a.k,
                           coords=a.coords, tiles=tiles)
    got = spgemm_device(a2, b).to_host()
    dst = delta.stats()
    assert dst["hits"] == 1 and dst["full_fallbacks"] == 0, dst
    assert 0 < dst["rows_recomputed"] < dst["rows_total"]
    want = spgemm_oracle(a2.to_dict(), b.to_dict(), a.k)
    got_d = got.to_dict()
    assert set(got_d) == set(want)
    for key in want:
        assert np.array_equal(got_d[key], want[key])


# ------------------------------------------------- plancache scope stats


def test_plancache_stats_scope_deltas():
    """stats(since=baseline) reports the scope's own hit/miss/eviction
    deltas -- the per-job detail fix (a second job must not inherit the
    first's process-lifetime totals)."""
    a, b = _Structure(16, 3, 1), _Structure(16, 3, 2)
    plan_spgemm(a, b, backend="xla", platform="cpu")  # job 1: one miss
    base = plancache.baseline()
    plan_spgemm(a, b, backend="xla", platform="cpu")  # job 2: one hit
    scoped = plancache.stats(since=base)
    assert scoped["hits"] == 1 and scoped["misses"] == 0
    lifetime = plancache.stats()
    assert lifetime["misses"] >= 1  # totals still available unscoped


# --------------------------------------------------------------- CLI glue


def test_cli_warm_stat_and_clear(monkeypatch, tmp_path, capsys):
    from spgemm_tpu import cli

    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    _host_plan(seed=21)
    warmstore.flush()
    warmstore.reset()  # drop our flock so --clear may take it
    assert cli.run(["warm", "--stat", "--json"]) == 0
    import json

    info = json.loads(capsys.readouterr().out)
    assert info["plans"] == 1 and info["bytes"] > 0
    assert not info["locked"]
    assert cli.run(["warm", "--clear"]) == 0
    assert "cleared 1" in capsys.readouterr().out
    assert warmstore.scan(str(tmp_path))["plans"] == 0


def test_cli_warm_clear_refuses_live_dir(monkeypatch, tmp_path):
    """--clear against a dir a LIVE process holds (a foreign flock --
    flock is per open-file-description, so a raw second handle models
    another process) must refuse and leave the entries intact."""
    import fcntl

    from spgemm_tpu import cli

    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", str(tmp_path))
    _host_plan(seed=22)
    warmstore.flush()
    warmstore.reset()  # our own handle gone; a "daemon" takes the dir
    holder = open(os.path.join(str(tmp_path), "lock"), "a+")
    fcntl.flock(holder.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    try:
        assert cli.run(["warm", "--clear"]) == 1  # refused, files intact
        assert warmstore.scan(str(tmp_path))["plans"] == 1
    finally:
        holder.close()


# ------------------------------------------------- fleet warm seeding


def test_warm_clone_serves_first_contact(monkeypatch, tmp_path):
    """`warm --clone` fleet seeding: a dir cloned from a peer must serve
    the destination's FIRST same-structure contact from disk
    (warm_hits >= 1, byte-identical plan) -- and a skewed or unreadable
    source entry is a counted skip, never a crash or a bad copy."""
    src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", src)
    p1 = _host_plan(seed=31)
    warmstore.flush()
    warmstore.reset()  # the peer is done; its flock is gone
    # poison the source with skew + junk the clone must skip
    np.savez(os.path.join(src, "plan-deadbeef.npz"),
             schema=np.int64(999), kind=np.array("plan"))
    with open(os.path.join(src, "plan-junk.npz"), "wb") as f:
        f.write(b"not an npz")
    result = warmstore.clone(src, dst)
    assert result["copied"] == 1
    assert result["skip_reasons"] == {"schema-skew": 1, "unreadable": 1}
    # idempotent: a re-clone keeps the existing local entry
    again = warmstore.clone(src, dst)
    assert again["copied"] == 0
    assert again["skip_reasons"].get("exists") == 1
    # the seeded dir serves the destination's first contact warm
    plancache.clear()
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", dst)
    p2 = _host_plan(seed=31)
    st = warmstore.stats()
    assert st["plan_hits"] >= 1 and st["corrupt"] == 0
    _assert_plans_equal(p1, p2)


def test_cli_warm_clone_and_live_dst_refusal(monkeypatch, tmp_path,
                                             capsys):
    """The CLI spelling (`warm --clone SRC --dir DST`) clones, and a
    destination held by a live process refuses exactly like --clear."""
    import fcntl

    from spgemm_tpu import cli

    src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
    monkeypatch.setenv("SPGEMM_TPU_WARM_DIR", src)
    _host_plan(seed=32)
    warmstore.flush()
    warmstore.reset()
    assert cli.run(["warm", "--clone", src, "--dir", dst]) == 0
    assert "cloned 1 entries" in capsys.readouterr().out
    assert warmstore.scan(dst)["plans"] == 1
    # a "daemon" holds the destination: seeding must refuse
    os.makedirs(os.path.join(dst), exist_ok=True)
    holder = open(os.path.join(dst, "lock"), "a+")
    fcntl.flock(holder.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    try:
        assert cli.run(["warm", "--clone", src, "--dir", dst]) == 1
        assert "in use by a live process" in capsys.readouterr().err
    finally:
        holder.close()
    # self-clone is a refusal, not a silent no-op
    assert cli.run(["warm", "--clone", src, "--dir", src]) == 1
