"""Property-based tests (SURVEY.md section 4: "property tests (hypothesis)
random block-sparse chains vs the oracle").

Strategies generate adversarial uint64 values (0, 1, 2^32 boundaries,
2^64-1 -- the wrap-then-mod quirk's trigger set, SURVEY.md section 2.9)
alongside uniform randoms, random block structures including empty and
duplicate-free coordinate sets, and short chains.  Each property pins a
layer of the engine against an independent implementation:

  * u64 limb arithmetic vs python ints (arbitrary 64-bit operands);
  * symbolic_join vs a dict-based brute-force join (arbitrary structures);
  * single SpGEMM and full chain_product vs the python-int oracle;
  * text format round-trip identity.

Example counts are kept small: each engine call jit-compiles on first use
and the suite must stay CI-fast; the adversarial example pool is seeded
into every run via the `examples` heuristics below.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis (not baked into "
                         "every toolchain image)")
from hypothesis import given, settings, strategies as st  # noqa: E402

from spgemm_tpu.ops import u64
from spgemm_tpu.ops.symbolic import symbolic_join
from spgemm_tpu.ops.spgemm import spgemm
from spgemm_tpu.chain import chain_product
from spgemm_tpu.utils import io_text
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.semantics import chain_oracle, scalar_mac, spgemm_oracle

MAX = (1 << 64) - 1
# the §2.9 trigger set: values whose products/sums straddle 2^32/2^64 wraps
EDGE = [0, 1, 2, (1 << 32) - 1, 1 << 32, (1 << 32) + 1,
        (1 << 63) - 1, 1 << 63, MAX - 2, MAX - 1, MAX]

u64_values = st.one_of(st.sampled_from(EDGE),
                       st.integers(min_value=0, max_value=MAX))


@st.composite
def block_matrices(draw, max_dim=4, k=2, dim=None):
    """A BlockSparseMatrix with arbitrary (deduplicated) structure and
    edge-heavy values.  dim fixes the block dimension (multiplication-
    compatible chains share one dim, like utils/gen.random_chain)."""
    if dim is None:
        dim = draw(st.integers(min_value=1, max_value=max_dim))
    coords = draw(st.lists(
        st.tuples(st.integers(0, dim - 1), st.integers(0, dim - 1)),
        min_size=0, max_size=dim * dim, unique=True))
    tiles = np.array(
        [[[draw(u64_values) for _ in range(k)] for _ in range(k)]
         for _ in coords], dtype=np.uint64).reshape(len(coords), k, k)
    return BlockSparseMatrix.from_blocks(
        rows=dim * k, cols=dim * k, k=k,
        coords=np.array(sorted(coords), np.int64).reshape(-1, 2),
        tiles=tiles if len(coords) else np.zeros((0, k, k), np.uint64))


@st.composite
def matrix_pairs(draw, max_dim=4, k=2):
    """A multiplication-compatible (square, shared-dim) matrix pair."""
    dim = draw(st.integers(min_value=1, max_value=max_dim))
    return (draw(block_matrices(k=k, dim=dim)),
            draw(block_matrices(k=k, dim=dim)))


@st.composite
def matrix_chains(draw, max_dim=3, k=2):
    """A multiplication-compatible chain of 2-4 matrices."""
    dim = draw(st.integers(min_value=1, max_value=max_dim))
    n = draw(st.integers(min_value=2, max_value=4))
    return [draw(block_matrices(k=k, dim=dim)) for _ in range(n)]


@settings(max_examples=200, deadline=None)
@given(a=u64_values, b=u64_values, acc=u64_values)
def test_u64_mac_matches_python_ints(a, b, acc):
    """One contraction step (acc = addmod(acc, mulmod(a, b))) of the limb
    arithmetic vs exact python ints -- the §2.9 wrap-then-mod sequence."""
    ah, al = u64.u64_to_hilo(np.array([a], np.uint64))
    bh, bl = u64.u64_to_hilo(np.array([b], np.uint64))
    ch, cl = u64.u64_to_hilo(np.array([acc], np.uint64))
    rh, rl = u64.mac(ch, cl, ah, al, bh, bl)
    got = int(u64.hilo_to_u64(np.asarray(rh), np.asarray(rl))[0])
    assert got == scalar_mac(acc, a, b)  # the one reference-fold definition


@settings(max_examples=200, deadline=None)
@given(a=u64_values, b=u64_values)
def test_u64_field_mulmod_is_true_residue(a, b):
    """Field mode must be the mathematically-correct mod-(2^64-1) residue
    for ALL operands (it is the associative arithmetic the cross-device
    reductions rely on)."""
    ah, al = u64.u64_to_hilo(np.array([a], np.uint64))
    bh, bl = u64.u64_to_hilo(np.array([b], np.uint64))
    rh, rl = u64.mulmod_field(ah, al, bh, bl)
    got = int(u64.hilo_to_u64(np.asarray(rh), np.asarray(rl))[0])
    assert got == (a * b) % MAX  # true residue, canonical rep in [0, MAX-1]


@settings(max_examples=200, deadline=None)
@given(a=st.integers(0, (1 << 30) - 1), b=st.integers(0, (1 << 30) - 1),
       acc=st.integers(0, (1 << 62) - 1))
def test_u64_mac_nomod_matches_mac_in_proven_regime(a, b, acc):
    """mac_nomod (the 28-op proven-regime MAC hybrid dispatch uses) must
    equal mac whenever product and sum stay below 2^64-1 -- here
    a*b < 2^60 and acc + a*b < 2^63, comfortably inside the
    safe_exact_bound envelope."""
    ah, al = u64.u64_to_hilo(np.array([a], np.uint64))
    bh, bl = u64.u64_to_hilo(np.array([b], np.uint64))
    ch, cl = u64.u64_to_hilo(np.array([acc], np.uint64))
    wh, wl = u64.mac(ch, cl, ah, al, bh, bl)
    gh, gl = u64.mac_nomod(ch, cl, ah, al, bh, bl)
    assert int(u64.hilo_to_u64(np.asarray(wh), np.asarray(wl))[0]) \
        == int(u64.hilo_to_u64(np.asarray(gh), np.asarray(gl))[0]) \
        == scalar_mac(acc, a, b)


@settings(max_examples=25, deadline=None)
@given(ab=matrix_pairs(), n_dev=st.integers(1, 8))
def test_plan_ring_covers_join_exactly(ab, n_dev):
    """Every join pair appears in the ring schedule exactly once, in its
    key's row, in the slab owning its B tile -- for arbitrary structures,
    device counts, and the empty-join edge."""
    from spgemm_tpu.parallel.ring import plan_ring

    a, b = ab
    join = symbolic_join(a.coords, b.coords)
    if join.num_keys == 0:
        return
    key_chunks, slab_bounds, ranks, tail, s_max, k_max = \
        plan_ring(join, b.nnzb, n_dev)
    seen = []
    for row_idx, pa_all, pb_all in ranks:
        for d, chunk in enumerate(key_chunks):
            for s in range(n_dev):
                for slot, row in enumerate(row_idx[d, s]):
                    if row == k_max:  # padding cell: only sentinels
                        assert pa_all[d, s, slot] == -1
                        continue
                    ki = chunk[row]  # compacted cell -> this device's key
                    pa_v, pb_v = pa_all[d, s, slot], pb_all[d, s, slot]
                    assert pa_v >= 0, "occupied row holds a sentinel pair"
                    gb = pb_v + slab_bounds[s]
                    assert slab_bounds[s] <= gb < slab_bounds[s + 1]
                    seen.append((int(ki), int(pa_v), int(gb)))
    if tail is not None:  # deep cells' spilled pairs count too
        row_idx, pa_all, pb_all = tail
        for d, chunk in enumerate(key_chunks):
            for s in range(n_dev):
                for slot, row in enumerate(row_idx[d, s]):
                    if row == k_max:
                        assert np.all(pa_all[d, s, slot] == -1)
                        continue
                    ki = chunk[row]
                    for pa_v, pb_v in zip(pa_all[d, s, slot],
                                          pb_all[d, s, slot]):
                        if pa_v < 0:
                            continue
                        gb = pb_v + slab_bounds[s]
                        assert slab_bounds[s] <= gb < slab_bounds[s + 1]
                        seen.append((int(ki), int(pa_v), int(gb)))
    want = []
    for ki in range(join.num_keys):
        lo, hi = join.pair_ptr[ki], join.pair_ptr[ki + 1]
        want += [(ki, int(pa_v), int(pb_v))
                 for pa_v, pb_v in zip(join.pair_a[lo:hi], join.pair_b[lo:hi])]
    assert sorted(seen) == sorted(want)


@settings(max_examples=50, deadline=None)
@given(ab=matrix_pairs())
def test_symbolic_join_vs_bruteforce(ab):
    """Join structure + per-key pair lists vs a dict brute force."""
    a, b = ab
    join = symbolic_join(a.coords, b.coords)
    brute: dict = {}
    for ia, (r, j) in enumerate(a.coords):
        for ib, (jb, c) in enumerate(b.coords):
            if j == jb:
                brute.setdefault((int(r), int(c)), []).append((ia, ib))
    assert sorted(brute.keys()) == [tuple(x) for x in join.keys.tolist()]
    for ki, key in enumerate(join.keys.tolist()):
        lo, hi = join.pair_ptr[ki], join.pair_ptr[ki + 1]
        got_pairs = list(zip(join.pair_a[lo:hi].tolist(),
                             join.pair_b[lo:hi].tolist()))
        # j-ascending order == sorted by (a slab index, b slab index) here
        # because coords are lex-sorted
        assert got_pairs == sorted(brute[tuple(key)])


@settings(max_examples=15, deadline=None)
@given(ab=matrix_pairs())
def test_spgemm_vs_oracle(ab):
    a, b = ab
    got = spgemm(a, b, backend="xla")
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    assert got == want


@settings(max_examples=8, deadline=None)
@given(mats=matrix_chains())
def test_chain_vs_oracle(mats):
    got = chain_product(mats, backend="xla")
    want = BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, mats[0].k,
        chain_oracle([m.to_dict() for m in mats], mats[0].k))
    assert got == want


@settings(max_examples=10, deadline=None)
@given(ab=matrix_pairs(), data=st.data())
def test_delta_recompute_byte_identical(ab, data):
    """Delta SpGEMM (ops/delta) vs full recompute across RANDOM dirty
    tile-row sets, including the empty diff (zero dirty rows -> zero
    recompute) and the all-dirty edge (degenerates to the full path):
    the delta path's bytes must equal the full path's for every drawn
    mutation, on edge-heavy values."""
    import os

    from spgemm_tpu.ops import delta, plancache
    from spgemm_tpu.utils.timers import ENGINE

    a, b = ab
    rows = np.unique(a.coords[:, 0]).tolist() if a.nnzb else []
    dirty = data.draw(st.lists(st.sampled_from(rows), unique=True,
                               max_size=len(rows))) if rows else []
    tiles = a.tiles.copy()
    if dirty:
        mask = np.isin(a.coords[:, 0], np.array(dirty, np.int64))
        tiles[mask, 0, 0] += np.uint64(1)  # wraps at 2^64: still a change
    a2 = BlockSparseMatrix(rows=a.rows, cols=a.cols, k=a.k,
                           coords=a.coords, tiles=tiles)
    prev = os.environ.get("SPGEMM_TPU_DELTA")
    delta.clear()
    plancache.clear()
    try:
        os.environ["SPGEMM_TPU_DELTA"] = "1"
        spgemm(a, b, backend="xla")       # seeds the retained entry
        ENGINE.reset()
        got = spgemm(a2, b, backend="xla")  # the delta path
        counters = ENGINE.counter_snapshot()
        if not dirty:
            assert counters.get("delta_rows_recomputed", 0) == 0
        os.environ["SPGEMM_TPU_DELTA"] = "0"
        want = spgemm(a2, b, backend="xla")  # the full path
    finally:
        if prev is None:
            os.environ.pop("SPGEMM_TPU_DELTA", None)
        else:
            os.environ["SPGEMM_TPU_DELTA"] = prev
        delta.clear()
    assert got == want
    oracle = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a2.to_dict(), b.to_dict(), a.k))
    assert want == oracle


@settings(max_examples=25, deadline=None)
@given(m=block_matrices())
def test_text_format_roundtrip(m, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("prop") / "m")
    io_text.write_matrix(path, m)
    back = io_text.read_matrix(path, m.k)
    assert back == m
