"""SLO engine (L5): rolling service-level objectives over the serving
surface -- the layer that turns recorded telemetry into judgment.

Every terminal job the daemon commits feeds one record here (tenant,
slice, wall seconds, queue wait, error flag, trace context).  Records
land in bounded per-(tenant, slice) rolling windows (a ring of at most
``RECORD_RETAIN`` records each, aged out past ``SPGEMM_TPU_SLO_WINDOW_S``
-- never an unbounded sample list), from which the engine computes, per
tenant:

  * streaming latency quantiles (p50/p95/p99) via a fixed-bucket digest
    (``LATENCY_BUCKETS``; digests merge across a tenant's slices by
    adding counts, so per-tenant quantiles cost nothing extra);
  * the error ratio (failed / total jobs in the window);
  * the queue-wait share (queued seconds / total latency seconds --
    "is the tenant slow because the pool is busy or because jobs are?").

Declared objectives drive multi-window burn-rate evaluation (the Google
SRE workbook shape): ``SPGEMM_TPU_SLO_TARGET_S`` makes any job slower
than the target (or failed) a *bad* event, ``SPGEMM_TPU_SLO_ERROR_PCT``
is the budget (the bad fraction the window may spend), and a window
whose bad fraction exceeds the budget in BOTH the fast (window/12) and
slow (full window) views is *burning* -- the two-window AND is what
keeps one slow job from paging and a real regression from hiding.  A
burn transition emits a structured ``slo_burn`` event carrying the
newest bad job's trace context (so the alert resolves to one openable
stitched trace, ``cli trace-dump --merge``), flips the
``spgemm_slo_burn_active{tenant=,slice=}`` gauge, and clears with an
``slo_burn_clear`` when the window recovers.  Objectives unset
(``SPGEMM_TPU_SLO_TARGET_S`` absent) = accounting-only: quantile/error
series still render, burn evaluation never runs.

Tenant cardinality is bounded at the source: at most ``TENANT_RETAIN``
distinct tenants hold windows (top-K by recency); an evicted tenant's
windows are dropped and counted (``spgemm_slo_tenants_evicted_total``),
so a tenant-id-per-request client cannot grow the engine or the scrape
without bound.  The daemon applies the same cap to its
``spgemmd_tenant_queue_depth`` series (top-K + one ``other`` aggregate).

jax-free by construction like the rest of ``obs/``; keyed off the L5
master knob (``SPGEMM_TPU_OBS_TRACE=0`` = the whole engine inert --
``observe`` returns before touching any state).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from spgemm_tpu.utils import knobs

# reported quantiles (Prometheus summary-style `quantile` label values)
QUANTILES = (0.5, 0.95, 0.99)

# fixed latency digest bucket upper bounds, seconds: a quantile is the
# first bound whose cumulative count covers the rank (coarse on purpose
# -- the digest is O(len) per window, never a sample list)
LATENCY_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.25, 1.0, 5.0, 30.0,
                   120.0, 600.0, 3600.0)

# the fast burn window is this fraction of the full objective window
# (SRE-workbook multi-window: 1h slow + 5m fast at the default 3600 s)
FAST_WINDOW_DIV = 12

# a window burns when bad_fraction/budget reaches this in BOTH windows
BURN_THRESHOLD = 1.0

# distinct tenants holding windows (top-K by recency; evictions counted)
# -- also the daemon's scrape-label cap for per-tenant series
TENANT_RETAIN = 32

# per-(tenant, slice) window ring bound (records, before age-out)
RECORD_RETAIN = 512


def enabled() -> bool:
    """The L5 master knob (SPGEMM_TPU_OBS_TRACE): the SLO engine records
    and judges only while the observability stack is on -- one A/B flag
    prices the whole layer, overhead-free at 0."""
    return knobs.get("SPGEMM_TPU_OBS_TRACE")


def objectives() -> dict:
    """The declared objectives, read per call like every knob: target
    latency (None = accounting-only, no burn evaluation), error budget
    percent, and the rolling window seconds."""
    target = knobs.get("SPGEMM_TPU_SLO_TARGET_S")
    return {
        "target_s": target,
        "error_pct": knobs.get("SPGEMM_TPU_SLO_ERROR_PCT"),
        "window_s": knobs.get("SPGEMM_TPU_SLO_WINDOW_S"),
        "enabled": target is not None,
    }


class _Window:
    """One (tenant, slice) rolling window: bounded record ring + the
    live burn state.  Mutated only under the engine's lock."""

    __slots__ = ("records", "burn_active", "burn")

    def __init__(self):
        # (ts, wall_s, queue_wait_s, bad, error, trace_id) tuples,
        # oldest first; bounded by RECORD_RETAIN and aged past window_s
        self.records: deque = deque()
        self.burn_active = False
        self.burn: dict | None = None  # newest evaluation detail


def _quantile(digest: list[int], count: int, maximum: float,
              q: float) -> float:
    """The q-quantile from cumulative fixed-bucket counts: the first
    bucket bound whose cumulative count covers rank q*count (the
    observed maximum for the overflow bucket)."""
    if count <= 0:
        return 0.0
    rank = q * count
    for i, le in enumerate(LATENCY_BUCKETS):
        if digest[i] >= rank:
            return le
    return maximum


class SloEngine:
    """The process-wide SLO accountant: spgemmd feeds one record per
    committed terminal job (``observe``), scrapes/CLIs read
    ``samples``/``report``.  All state is engine-lock-guarded; burn
    transition events are emitted OUTSIDE the lock (the event log has
    its own lock and the two must never nest)."""

    def __init__(self):
        self._lock = threading.Lock()
        # (tenant, slice) -> _Window
        self._windows: "OrderedDict[tuple, _Window]" = OrderedDict()  # spgemm-lint: guarded-by(_lock)
        self._tenants: "OrderedDict[str, float]" = OrderedDict()  # spgemm-lint: guarded-by(_lock)
        self._evicted = 0   # spgemm-lint: guarded-by(_lock)
        self._records = 0   # spgemm-lint: guarded-by(_lock)

    # ------------------------------------------------------------ ingest --
    def observe(self, tenant: str, slice_name: str, wall_s: float,
                queue_wait_s: float, error: bool,
                trace_id: str | None = None,
                now: float | None = None) -> None:
        """One terminal job record.  Ages/evicts, then re-evaluates the
        window's burn state; a transition emits slo_burn/slo_burn_clear
        after the lock releases."""
        if not enabled():
            return
        obj = objectives()
        now = time.time() if now is None else now
        bad = bool(error) or (obj["target_s"] is not None
                              and wall_s > obj["target_s"])
        transitions: list[tuple[str, dict]] = []
        with self._lock:
            key = (tenant, slice_name)
            w = self._windows.get(key)
            if w is None:
                w = self._windows[key] = _Window()
            w.records.append((now, float(wall_s), float(queue_wait_s),
                              bad, bool(error), trace_id))
            while len(w.records) > RECORD_RETAIN:
                w.records.popleft()
            self._records += 1
            self._tenants[tenant] = now
            self._tenants.move_to_end(tenant)
            while len(self._tenants) > TENANT_RETAIN:
                old, _ = self._tenants.popitem(last=False)
                for k in [k for k in self._windows if k[0] == old]:
                    # an evicted window that was BURNING must close its
                    # alert lifecycle: a consumer pairing slo_burn with
                    # slo_burn_clear would otherwise hold a phantom open
                    # alert forever while the gauge series just vanishes
                    if self._windows[k].burn_active:
                        transitions.append(("slo_burn_clear", {
                            "tenant": k[0], "slice": k[1],
                            "reason": "tenant-evicted"}))
                    del self._windows[k]
                self._evicted += 1
            transitions += self._evaluate_locked(key, w, obj, now)
        self._emit(transitions)

    # -------------------------------------------------------- evaluation --
    def _evaluate_locked(self, key: tuple, w: _Window, obj: dict,
                         now: float) -> list[tuple[str, dict]]:
        """Multi-window burn-rate for one window (caller holds _lock);
        returns the transition events to emit after the lock releases.
        Ages out records past the objective window as a side effect."""
        window = obj["window_s"]
        while w.records and now - w.records[0][0] > window:
            w.records.popleft()
        if not obj["enabled"]:
            transitions = []
            if w.burn_active:
                transitions.append(("slo_burn_clear", {
                    "tenant": key[0], "slice": key[1],
                    "reason": "objectives-unset"}))
            w.burn_active = False
            w.burn = None
            return transitions
        fast_window = window / FAST_WINDOW_DIV
        # the budget floor keeps the burn ratio finite at a 0% budget
        # (any bad event then burns "infinitely" fast)
        budget = max(obj["error_pct"] / 100.0, 1e-9)
        slow_n = slow_bad = fast_n = fast_bad = 0
        newest_bad_trace = None
        for ts, _wall, _qw, bad, _err, trace_id in w.records:
            slow_n += 1
            slow_bad += bad
            if now - ts <= fast_window:
                fast_n += 1
                fast_bad += bad
            if bad and trace_id:
                newest_bad_trace = trace_id
        slow_burn = (slow_bad / slow_n) / budget if slow_n else 0.0
        fast_burn = (fast_bad / fast_n) / budget if fast_n else 0.0
        active = (slow_bad > 0 and slow_burn >= BURN_THRESHOLD
                  and fast_burn >= BURN_THRESHOLD)
        was = w.burn_active
        w.burn_active = active
        w.burn = {"fast_burn": round(fast_burn, 4),
                  "slow_burn": round(slow_burn, 4),
                  "bad": slow_bad, "jobs": slow_n,
                  "trace_id": newest_bad_trace}
        if active and not was:
            return [("slo_burn", {
                "tenant": key[0], "slice": key[1],
                "fast_burn": round(fast_burn, 4),
                "slow_burn": round(slow_burn, 4),
                "bad": slow_bad, "jobs": slow_n,
                "trace_id": newest_bad_trace,
                "target_s": obj["target_s"],
                "error_pct": obj["error_pct"],
                "window_s": window})]
        if was and not active:
            return [("slo_burn_clear", {"tenant": key[0],
                                        "slice": key[1]})]
        return []

    @staticmethod
    def _emit(transitions: list[tuple[str, dict]]) -> None:
        from spgemm_tpu.obs import events  # noqa: PLC0415 -- events imports trace, trace feeds profile; keep slo leaf-light

        # the transition list only ever carries the two burn kinds;
        # re-spell them literally so the EVT registry rule can audit
        # the emit sites (a computed kind is unauditable by design)
        for kind, fields in transitions:
            if kind == "slo_burn":
                events.emit("slo_burn", **fields)
            else:
                events.emit("slo_burn_clear", **fields)

    def _reevaluate_all_locked(self, now: float) -> list[tuple[str, dict]]:
        """Slide every window to `now` (a burn with no new records must
        still clear when its bad records age out)."""
        obj = objectives()
        transitions: list[tuple[str, dict]] = []
        for key, w in self._windows.items():
            transitions += self._evaluate_locked(key, w, obj, now)
        return transitions

    # --------------------------------------------------------- inspection --
    def report(self, now: float | None = None) -> dict:
        """The `cli slo [--json]` / stats payload: objectives, per-tenant
        window accounts (quantiles merged over the tenant's slices,
        error ratio, queue-wait share), per-window burn state, and the
        cardinality-bound eviction count."""
        obj = objectives()
        now = time.time() if now is None else now
        with self._lock:
            transitions = self._reevaluate_all_locked(now)
            tenants: dict[str, dict] = {}
            burns: list[dict] = []
            for (tenant, slice_name), w in self._windows.items():
                agg = tenants.get(tenant)
                if agg is None:
                    agg = tenants[tenant] = {
                        "digest": [0] * len(LATENCY_BUCKETS), "max": 0.0,
                        "jobs": 0, "errors": 0, "wall_s": 0.0,
                        "queue_wait_s": 0.0}
                for _ts, wall, qw, _bad, err, _tr in w.records:
                    agg["jobs"] += 1
                    agg["errors"] += err
                    agg["wall_s"] += wall
                    agg["queue_wait_s"] += qw
                    agg["max"] = max(agg["max"], wall)
                    for i, le in enumerate(LATENCY_BUCKETS):
                        if wall <= le:
                            agg["digest"][i] += 1
                burns.append({"tenant": tenant, "slice": slice_name,
                              "active": w.burn_active,
                              **(w.burn or {})})
            evicted = self._evicted
            records = self._records
        self._emit(transitions)
        rows = {}
        for tenant, agg in sorted(tenants.items()):
            if not agg["jobs"]:
                continue
            total_s = agg["wall_s"] + agg["queue_wait_s"]
            rows[tenant] = {
                "jobs": agg["jobs"],
                "errors": agg["errors"],
                "error_ratio": round(agg["errors"] / agg["jobs"], 6),
                "queue_wait_share": round(
                    agg["queue_wait_s"] / total_s, 6) if total_s else 0.0,
                "latency_s": {f"p{int(q * 100)}": _quantile(
                    agg["digest"], agg["jobs"], agg["max"], q)
                    for q in QUANTILES},
            }
        return {"enabled": enabled(), "objectives": obj, "tenants": rows,
                "burn": burns,
                "burn_active": sum(1 for b in burns if b["active"]),
                "tenants_evicted": evicted, "records": records}

    def samples(self, now: float | None = None) -> list[tuple]:
        """Metric samples for the daemon scrape (families declared in
        obs/metrics.py): per-tenant quantile/error/queue-share gauges,
        per-(tenant, slice) burn gauges, the eviction counter.  Tenant
        label cardinality is bounded by TENANT_RETAIN at the source."""
        rep = self.report(now)
        samples: list[tuple] = []
        for tenant, row in rep["tenants"].items():
            for q in QUANTILES:
                samples.append(("spgemm_slo_latency_seconds",
                                {"tenant": tenant, "quantile": f"{q:g}"},
                                row["latency_s"][f"p{int(q * 100)}"]))
            samples.append(("spgemm_slo_error_ratio", {"tenant": tenant},
                            row["error_ratio"]))
            samples.append(("spgemm_slo_queue_wait_share",
                            {"tenant": tenant},
                            row["queue_wait_share"]))
        for b in rep["burn"]:
            samples.append(("spgemm_slo_burn_active",
                            {"tenant": b["tenant"], "slice": b["slice"]},
                            int(b["active"])))
        samples.append(("spgemm_slo_tenants_evicted_total", {},
                        rep["tenants_evicted"]))
        return samples

    def clear(self) -> None:
        """Drop every window and zero the counters (tests, harnesses)."""
        with self._lock:
            self._windows.clear()
            self._tenants.clear()
            self._evicted = 0
            self._records = 0


# The process-wide engine: spgemmd feeds it from the terminal-event path
# and serves the `slo` op / scrape families from it.
SLO = SloEngine()
