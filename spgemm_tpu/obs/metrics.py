"""Metrics registry + Prometheus text-format 0.0.4 renderer.

knobs.py-style single source of truth: every scrapeable metric family is
declared here ONCE (name, type counter|gauge|histogram, labels, consuming
module, help text), and every ENGINE phase/counter name the package may
pass to `ENGINE.phase/record/incr` is declared here too.  From the
registry are generated:

  * the renderer's validation -- `render()` raises on an undeclared
    family name, so an ad-hoc metric cannot ship silently;
  * the ARCHITECTURE.md metrics table (`metrics_table_md`; the linter's
    DOC rule diffs the generated text against the committed block, and
    `python -m spgemm_tpu.analysis --write-metrics-table` regenerates it);
  * the MET lint rule's name set (`analysis/metrules.py`): an
    `ENGINE.incr("...")`/`record`/`phase` whose name literal is not
    declared below is a lint finding -- no ad-hoc series names.

jax-free by design: imported by the linter, the client CLI, and spgemmd's
scrape path, none of which may touch a backend.
"""

from __future__ import annotations

from dataclasses import dataclass

# Engine PHASE names (wall-seconds accumulators): the only names the
# package may pass to ENGINE.phase(...) / ENGINE.record(...).  Each
# becomes one spgemm_phase_* label value and one span name in the flight
# recorder.
ENGINE_PHASES: dict[str, str] = {
    "plan": "full symbolic plan (join + rounds + assembly permutation)",
    "plan_wait": "how long dispatch actually blocked on planning",
    "estimate": "sampled structure estimation (ops/estimate.py)",
    "join_fallback": "exact join built inline on low estimator confidence",
    "symbolic_join": "host symbolic join over operand structures",
    "plan_rounds": "round bucketing + assembly permutation",
    "numeric_dispatch": "numeric kernel launches (host dispatch span)",
    "dense_fold": "dense accumulator route: index-ordered segmented "
                  "stream fold (SPGEMM_TPU_ACCUM_ROUTE)",
    "assembly": "on-device result assembly / OOC host landing",
    "stage_prep": "OOC staging worker: host gather/pack of one round",
    "ring_plan": "ring schedule planning",
    "ring_hop": "one-hop ring wire probe",
    "ring_fold": "per-slab ring fold",
    "dcn_exchange": "multihost partial exchange over DCN",
    "delta_diff": "delta path: row digests + content diff + join "
                  "reachability (ops/delta)",
    "delta_splice": "delta path: recomputed-row splice into the retained "
                    "previous result",
    "serve_queue_wait": "spgemmd: submit-to-execution queue wait",
    "serve_execute": "spgemmd: one job's executor span",
    "warm_load": "warm-start store: one on-disk plan/delta entry "
                 "deserialize attempt (ops/warmstore)",
    "warm_flush": "warm-start store: persist in-memory plan/delta "
                  "entries + budget prune (spgemmd terminal events, "
                  "shutdown)",
    "tune_trial": "autotuner: one timed trial leg (one knob vector of "
                  "the per-class enumeration) run on an idle slice "
                  "(spgemm_tpu/tune)",
    "tune_apply": "autotuner: activating a class's tuned override at "
                  "job pickup + persisting a fresh winner into the "
                  "warm store's tune tier",
}

# Engine event COUNTER names: the only names the package may pass to
# ENGINE.incr(...).  Each becomes one spgemm_engine_events_total label
# value.
ENGINE_COUNTERS: dict[str, str] = {
    "dispatches": "numeric kernel launches",
    "route_dense": "rounds dispatched on the dense accumulator route "
                   "(forced by SPGEMM_TPU_ACCUM_ROUTE=dense or won by "
                   "the auto gate, ops/crossover.dense_wins)",
    "ring_steps": "ring rotation steps executed",
    "dcn_chunks": "bounded DCN exchange chunks shipped",
    "plan_cache_hits": "structure-keyed plan cache hits",
    "plan_cache_misses": "structure-keyed plan cache misses",
    "plan_cache_evictions": "structure-keyed plan cache LRU evictions "
                            "(capacity pressure -- invisible before "
                            "delta retention made it matter)",
    "delta_rows_recomputed": "output tile-rows re-folded by "
                             "delta-enabled multiplies (a full "
                             "fallback counts every row)",
    "delta_rows_total": "total output tile-rows seen by delta-enabled "
                        "multiplies (the recompute ratio's denominator)",
    "delta_full_fallbacks": "delta-enabled multiplies that took the full "
                            "path (first contact, provenance mismatch, "
                            "store eviction)",
    "est_hits": "estimator-routed plans (exact join deferred off the "
                "critical path)",
    "est_fallbacks": "estimator fallbacks to the inline exact join "
                     "(confidence below SPGEMM_TPU_EST_CONFIDENCE)",
    "compiles": "engine jit compiles recorded by the deep-profiling "
                "layer (obs/profile.ProfiledJit) -- per-job attribution "
                "of the cold-jit tax",
    "serve_reaps": "spgemmd watchdog job reaps (deadline exceeded)",
    "serve_degrades": "spgemmd degrade transitions to the CPU path "
                      "(per-slice under the device pool)",
    "serve_steals": "spgemmd pool work steals: jobs taken by an idle "
                    "slice outside their preferred slice class (every "
                    "preferred slice was busy or degraded)",
    "serve_recoveries": "spgemmd self-healing slice reinstatements: a "
                        "degraded slice whose recovery re-probe "
                        "(SPGEMM_TPU_SERVE_RECOVER_S) came back live "
                        "rejoined placement behind the canary gate",
    "serve_batches": "spgemmd cross-job fused batches executed: a slice "
                     "executor drained >= 2 same-structure queued jobs "
                     "(SPGEMM_TPU_SERVE_BATCH_K / _BATCH_WINDOW_S) and "
                     "ran them as one fused dispatch per multiply",
    "serve_batched_jobs": "jobs that rode a cross-job fused batch "
                          "(the serve_batches counter's member total; "
                          "solo pickups never count)",
    "warm_hits": "warm-start store hits: a plan or delta entry a "
                 "previous process persisted was deserialized and "
                 "served (ops/warmstore)",
    "warm_misses": "warm-start store misses: no on-disk entry for the "
                   "fingerprint (first-ever contact, pruned entry, or "
                   "a different knob vector's fingerprint)",
    "warm_corrupt": "warm entries skipped as corrupt/version-skewed/"
                    "knob-vector-mismatched -- each a counted cold "
                    "fallback, never a crash or wrong bits",
    "tune_trials": "autotuner timed trial legs executed on idle slices "
                   "(one knob vector each; preempted or "
                   "generation-skewed legs count too -- they spent the "
                   "idle cycles even when the measurement was "
                   "discarded)",
    "tune_reverts": "autotuner override reverts: a canary failure or a "
                    "trial-time parity mismatch dropped the class's "
                    "tuned vector and backed off its re-trial",
}


@dataclass(frozen=True)
class Metric:
    """One declared metric family.

    kind: 'counter' | 'gauge' | 'histogram'.  Histogram samples are fed
    as {"buckets": {le: cumulative_count}, "sum": s, "count": n}.
    labels: the exact label names every sample of the family must carry.
    module: the producing module (repo-relative), for docs.
    """

    name: str
    kind: str
    doc: str
    module: str
    labels: tuple[str, ...] = ()


_METRICS = (
    Metric("spgemm_phase_seconds_total", "counter",
           "Wall seconds accumulated per engine phase (the ENGINE "
           "registry's totals; phase names are declared in "
           "obs/metrics.ENGINE_PHASES).",
           "utils/timers.py", labels=("phase",)),
    Metric("spgemm_phase_entries_total", "counter",
           "Times each engine phase was entered.",
           "utils/timers.py", labels=("phase",)),
    Metric("spgemm_engine_events_total", "counter",
           "Engine event counters (ENGINE.incr names, declared in "
           "obs/metrics.ENGINE_COUNTERS: dispatches, ring_steps, "
           "plan_cache_hits/misses, ...).",
           "utils/timers.py", labels=("event",)),
    Metric("spgemm_plan_cache_hits_total", "counter",
           "Structure-keyed plan cache hits since process start.",
           "ops/plancache.py"),
    Metric("spgemm_plan_cache_misses_total", "counter",
           "Structure-keyed plan cache misses since process start.",
           "ops/plancache.py"),
    Metric("spgemm_plan_cache_evictions_total", "counter",
           "Structure-keyed plan cache LRU evictions since process "
           "start.",
           "ops/plancache.py"),
    Metric("spgemm_plan_cache_entries", "gauge",
           "Plans currently retained in the LRU.",
           "ops/plancache.py"),
    Metric("spgemm_plan_cache_capacity", "gauge",
           "Configured plan-cache LRU capacity "
           "(SPGEMM_TPU_PLAN_CACHE_CAP).",
           "ops/plancache.py"),
    Metric("spgemm_warm_hits_total", "counter",
           "Warm-start store hits since process start (plan + delta "
           "entries served from disk).",
           "ops/warmstore.py"),
    Metric("spgemm_warm_misses_total", "counter",
           "Warm-start store misses since process start.",
           "ops/warmstore.py"),
    Metric("spgemm_warm_corrupt_total", "counter",
           "Warm entries skipped as corrupt/version-skewed/knob-vector-"
           "mismatched (counted cold fallbacks).",
           "ops/warmstore.py"),
    Metric("spgemm_warm_entries", "gauge",
           "Entries currently persisted in the warm dir, by kind "
           "(plan, delta).",
           "ops/warmstore.py", labels=("kind",)),
    Metric("spgemm_warm_bytes", "gauge",
           "On-disk bytes of warm plan/delta entries (the xla "
           "compilation-cache subdir is excluded).",
           "ops/warmstore.py"),
    Metric("spgemm_trace_spans", "gauge",
           "Spans currently retained in the flight-recorder ring.",
           "obs/trace.py"),
    Metric("spgemm_trace_spans_emitted_total", "counter",
           "Spans emitted into the flight recorder since process start.",
           "obs/trace.py"),
    Metric("spgemm_trace_spans_dropped_total", "counter",
           "Spans evicted from the ring (oldest-first past "
           "SPGEMM_TPU_OBS_RING_CAP).",
           "obs/trace.py"),
    Metric("spgemmd_uptime_seconds", "gauge",
           "Seconds since the serving daemon started.",
           "serve/daemon.py"),
    Metric("spgemmd_degraded", "gauge",
           "1 when the WHOLE pool is on the CPU failover path (every "
           "slice wedged/dead; with one slice, exactly the pre-pool "
           "daemon flag), else 0.  Per-slice degrade state is "
           "spgemm_slice_degraded.",
           "serve/daemon.py"),
    Metric("spgemm_slice_busy", "gauge",
           "1 while the slice's executor holds a job, else 0 -- the "
           "device-pool utilization signal, per slice.",
           "serve/daemon.py", labels=("slice",)),
    Metric("spgemm_slice_degraded", "gauge",
           "1 when this slice wedged/died and runs the CPU failover "
           "executor (excluded from placement while any healthy slice "
           "remains), else 0.",
           "serve/daemon.py", labels=("slice",)),
    Metric("spgemm_slice_jobs_total", "counter",
           "Jobs picked up by this slice's executor since daemon start "
           "(steals included).",
           "serve/daemon.py", labels=("slice",)),
    Metric("spgemm_slice_steals_total", "counter",
           "Jobs this slice STOLE (its class was not the job's preferred "
           "placement, but every preferred slice was busy/degraded).",
           "serve/daemon.py", labels=("slice",)),
    Metric("spgemm_slice_recoveries_total", "counter",
           "Times this degraded slice was reinstated into placement by "
           "the self-healing recovery loop (SPGEMM_TPU_SERVE_RECOVER_S "
           "re-probe came back live; the first job after each "
           "reinstatement runs under the canary gate).",
           "serve/daemon.py", labels=("slice",)),
    Metric("spgemmd_tenant_queue_depth", "gauge",
           "Jobs queued per fair-queuing tenant (tenants with no queued "
           "or in-flight jobs are retired from the series).  Label "
           "cardinality is bounded: the top-K tenants by recency keep "
           "their own label, the rest aggregate into one `other` row.",
           "serve/daemon.py", labels=("tenant",)),
    Metric("spgemmd_queue_depth", "gauge",
           "Jobs currently waiting in the admission FIFO.",
           "serve/daemon.py"),
    Metric("spgemmd_connections", "gauge",
           "Concurrent client connections held open.",
           "serve/daemon.py"),
    Metric("spgemmd_jobs", "gauge",
           "Jobs in the live index by state (terminal states bounded by "
           "JobQueue.RETAIN_TERMINAL).",
           "serve/daemon.py", labels=("state",)),
    Metric("spgemmd_jobs_terminal_total", "counter",
           "Daemon-lifetime terminal job outcomes: done, error (runner "
           "raised), timeout (watchdog reap -- a later wedge declaration "
           "does not re-count the job; alert on spgemmd_degraded / "
           "serve_degrades for wedges), abandoned (executor thread died "
           "mid-job), drained (reaped by a graceful shutdown past "
           "DRAIN_GRACE_S -- routine on rollouts, never an executor-"
           "death signal).",
           "serve/daemon.py", labels=("outcome",)),
    Metric("spgemmd_journal_bytes", "gauge",
           "On-disk size of the job journal next to the socket.",
           "serve/daemon.py"),
    Metric("spgemmd_journal_compactions_total", "counter",
           "Journal compactions since daemon start (startup replay "
           "included).",
           "serve/daemon.py"),
    Metric("spgemmd_journal_torn_total", "counter",
           "Journal tears detected during replay or compaction "
           "(CRC32/length frame mismatch -- the mid-write-kill "
           "signature): one count per truncation at the first bad "
           "record, never a crash.  Everything after the tear is "
           "unattributable and dropped with it, so this counts tears, "
           "not dropped records.",
           "serve/daemon.py"),
    # ---- fleet layer (fleet/router.py: the federation router's own
    # scrape; per-backend daemon series ride the aggregated passthrough
    # with an injected backend= label, not this registry) ----
    Metric("spgemm_router_backend_up", "gauge",
           "1 while the backend answers its stats poll healthy "
           "(undegraded), 0 while it is down or degraded -- the "
           "fleet-level analogue of spgemm_slice_degraded (a down "
           "backend is excluded from placement the same way).",
           "fleet/router.py", labels=("backend",)),
    Metric("spgemm_router_backend_queue_depth", "gauge",
           "Queued jobs last reported by each backend's stats poll "
           "(the router's load signal for least-loaded placement).",
           "fleet/router.py", labels=("backend",)),
    Metric("spgemm_router_jobs_total", "counter",
           "Submits the router placed per backend (failover re-submits "
           "count on the backend that finally accepted).",
           "fleet/router.py", labels=("backend",)),
    Metric("spgemm_router_failovers_total", "counter",
           "Jobs re-submitted once to a healthy peer after their "
           "backend died mid-job (the idempotent-by-fingerprint "
           "failover; a job that cannot fail over gets a structured "
           "backend-lost error instead).",
           "fleet/router.py"),
    Metric("spgemm_failpoints_triggered_total", "counter",
           "Chaos failpoint triggers per registered injection point "
           "(utils/failpoints.py registry, armed via "
           "SPGEMM_TPU_FAILPOINTS; zero series when unarmed).",
           "utils/failpoints.py", labels=("point",)),
    Metric("spgemmd_job_wall_seconds", "histogram",
           "Per-job wall time start-to-terminal (reaped jobs included).",
           "serve/daemon.py"),
    Metric("spgemm_serve_batch_size", "histogram",
           "Jobs per executor pickup while the cross-job batching window "
           "was armed (SPGEMM_TPU_SERVE_BATCH_WINDOW_S > 0): size 1 = a "
           "batchable head found no mates inside the window, >= 2 = one "
           "fused dispatch served the whole batch.  No samples while the "
           "window is 0 (the pre-batch scrape, byte-identical).",
           "serve/daemon.py"),
    # ---- deep profiling layer (obs/profile.py, obs/events.py) ----
    Metric("spgemm_compiles_total", "counter",
           "Engine jit compiles recorded per site (obs/profile.ProfiledJit "
           "wraps the XLA numeric round, assembly gather, delta splice, "
           "ring/rowshard entrypoints) -- the cold-jit tax the "
           "persistent-warm-start roadmap item targets.",
           "obs/profile.py", labels=("site",)),
    Metric("spgemm_compile_seconds", "histogram",
           "Compile wall per recorded engine jit compile (lower + "
           "backend compile, per site).",
           "obs/profile.py", labels=("site",)),
    Metric("spgemm_compile_flops_total", "counter",
           "Cumulative XLA cost_analysis FLOPs of the executables "
           "compiled per site.",
           "obs/profile.py", labels=("site",)),
    Metric("spgemm_compile_bytes_total", "counter",
           "Cumulative XLA cost_analysis bytes-accessed of the "
           "executables compiled per site.",
           "obs/profile.py", labels=("site",)),
    Metric("spgemm_compile_temp_bytes", "gauge",
           "Largest memory_analysis temp-buffer footprint among the "
           "executables compiled per site.",
           "obs/profile.py", labels=("site",)),
    Metric("spgemm_phase_seconds", "histogram",
           "Per-entry engine phase latency distribution, fed from "
           "completed flight-recorder spans (phase names declared in "
           "obs/metrics.ENGINE_PHASES) -- scrape-side phase latency "
           "without a trace dump.",
           "obs/profile.py", labels=("phase",)),
    Metric("spgemm_est_rel_error", "histogram",
           "Sampled-estimator relative error, scored when the deferred "
           "exact join lands (SpgemmPlan.ensure_exact): |predicted - "
           "exact| / exact per quantity (keys, pairs, fanout).  A "
           "drifting estimator is an alert here, not a silent mis-plan.",
           "obs/profile.py", labels=("quantity",)),
    Metric("spgemm_delta_dirty_fraction", "histogram",
           "Predicted-dirty fraction per delta-enabled multiply "
           "(dirty output rows / total rows; a counted full fallback "
           "observes 1.0) -- the per-multiply distribution behind the "
           "aggregate delta_rows_* counters: how incremental the "
           "submit stream actually is.",
           "obs/profile.py"),
    Metric("spgemm_delta_mispredictions_total", "counter",
           "Delta multiplies whose executed row count diverged from "
           "the predicted dirty set (the engine executes exactly what "
           "it predicts, so any nonzero here is an engine bug -- "
           "alert, don't graph).",
           "obs/profile.py"),
    Metric("spgemm_hbm_bytes_in_use", "gauge",
           "Device bytes in use at the newest engine memory_stats() "
           "sample (dispatch/assembly boundaries; omitted on backends "
           "without the API, e.g. CPU).",
           "obs/profile.py"),
    Metric("spgemm_hbm_peak_bytes", "gauge",
           "Peak device bytes in use over all engine memory_stats() "
           "samples since process start -- the observable form of "
           "SPGEMM_TPU_DELTA_RETAIN's entries-not-bytes retention bound.",
           "obs/profile.py"),
    Metric("spgemm_hbm_samples_total", "counter",
           "Engine memory_stats() samples recorded (0 and omitted "
           "gauges = backend never reported).",
           "obs/profile.py"),
    Metric("spgemm_events_emitted_total", "counter",
           "Structured events emitted into the event log "
           "(obs/events.py: job lifecycle, watchdog transitions, "
           "est/delta fallbacks, compile records).",
           "obs/events.py"),
    Metric("spgemm_events_dropped_total", "counter",
           "Events evicted from the bounded in-process event ring.",
           "obs/events.py"),
    Metric("spgemm_events_rotations_total", "counter",
           "On-disk event-log rotations (file grew past "
           "SPGEMM_TPU_OBS_EVENTS_MAX_KB and rolled to <path>.1).",
           "obs/events.py"),
    Metric("spgemm_events_bytes", "gauge",
           "Current on-disk size of the active event-log file (0 when "
           "no file sink is configured).",
           "obs/events.py"),
    # ---- autotuner (spgemm_tpu/tune) ----
    Metric("spgemm_tune_overrides", "gauge",
           "Structure classes currently holding a tuned knob override, "
           "by rollout state (canary = first post-promotion job still "
           "pending under the tightened deadline, live = canary passed, "
           "reverted = canary failed or parity mismatched -- held in "
           "backoff before re-trial).  No series while the tuner holds "
           "no class state (the SPGEMM_TPU_TUNE=0 scrape is "
           "byte-identical to the pre-tuner daemon).",
           "serve/daemon.py", labels=("state",)),
    Metric("spgemm_tune_win_ratio", "gauge",
           "Measured speedup (incumbent wall / winner wall) of each "
           "class's tuned override, labeled by the structure class key; "
           "only promoted overrides render (>= SPGEMM_TPU_TUNE_MIN_WIN "
           "by construction).",
           "serve/daemon.py", labels=("class",)),
    # ---- SLO engine (obs/slo.py) ----
    Metric("spgemm_slo_latency_seconds", "gauge",
           "Rolling-window per-tenant job latency quantile (p50/p95/p99 "
           "from the SLO engine's fixed-bucket digest, merged over the "
           "tenant's slices; window = SPGEMM_TPU_SLO_WINDOW_S).  Tenant "
           "label cardinality is bounded at the source (top-K by "
           "recency, evictions counted).",
           "obs/slo.py", labels=("tenant", "quantile")),
    Metric("spgemm_slo_error_ratio", "gauge",
           "Rolling-window per-tenant error ratio (failed jobs / total "
           "jobs in the SLO window).",
           "obs/slo.py", labels=("tenant",)),
    Metric("spgemm_slo_queue_wait_share", "gauge",
           "Rolling-window per-tenant queue-wait share: queued seconds "
           "/ (queued + execute) seconds -- whether a slow tenant is "
           "waiting on the pool or on its own jobs.",
           "obs/slo.py", labels=("tenant",)),
    Metric("spgemm_slo_burn_active", "gauge",
           "1 while the (tenant, slice) window is burning its error "
           "budget in BOTH burn windows (fast = window/12, slow = full "
           "window; objectives from SPGEMM_TPU_SLO_TARGET_S / "
           "SPGEMM_TPU_SLO_ERROR_PCT) -- the transition emitted a "
           "structured slo_burn event whose trace_id resolves via "
           "`cli trace-dump --merge` to the newest bad job's stitched "
           "trace; 0 (or the series absent) otherwise.",
           "obs/slo.py", labels=("slice", "tenant")),
    Metric("spgemm_slo_tenants_evicted_total", "counter",
           "Tenants evicted from the SLO engine's top-K-by-recency "
           "window set (their rolling windows dropped) -- the "
           "cardinality bound that keeps a tenant-id-per-request "
           "client from growing the engine or the scrape without "
           "bound.",
           "obs/slo.py"),
)

REGISTRY: dict[str, Metric] = {m.name: m for m in _METRICS}

# spgemmd_job_wall_seconds bucket upper bounds (seconds); +Inf implicit
JOB_WALL_BUCKETS = (0.1, 1.0, 10.0, 60.0, 600.0, 3600.0)

# spgemm_serve_batch_size bucket upper bounds (jobs per armed-window
# pickup); +Inf implicit -- covers every legal SPGEMM_TPU_SERVE_BATCH_K
# at power-of-two resolution
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16)


# ---------------------------------------------------------- text format --
def escape_help(text: str) -> str:
    """Prometheus 0.0.4 HELP escaping: backslash and newline."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label(value: str) -> str:
    """Prometheus 0.0.4 label-value escaping: backslash, quote, newline."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return f"{float(v):.10g}"


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def render(samples: list[tuple]) -> str:
    """Prometheus text-format 0.0.4 for `samples`: (family, labels, value)
    tuples, histogram values as {"buckets", "sum", "count"} dicts.

    Families render in REGISTRY order with one HELP/TYPE header each; an
    undeclared family name raises ValueError (declaring is the price of
    emitting -- the same contract as the knob registry), as does a sample
    whose label names differ from the declaration."""
    by_family: dict[str, list[tuple[dict, object]]] = {}
    for family, labels, value in samples:
        m = REGISTRY.get(family)
        if m is None:
            raise ValueError(
                f"undeclared metric {family!r}: register it in "
                "spgemm_tpu/obs/metrics.py (no ad-hoc series names)")
        if tuple(sorted(labels)) != tuple(sorted(m.labels)):
            raise ValueError(
                f"metric {family!r} declares labels {m.labels}, sample "
                f"carries {tuple(sorted(labels))}")
        by_family.setdefault(family, []).append((dict(labels), value))
    lines: list[str] = []
    for m in _METRICS:
        rows = by_family.get(m.name)
        if rows is None:
            continue
        lines.append(f"# HELP {m.name} {escape_help(m.doc)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for labels, value in sorted(rows,
                                    key=lambda r: sorted(r[0].items())):
            if m.kind == "histogram":
                buckets = value["buckets"]
                for le in sorted(buckets):
                    lab = _fmt_labels({**labels, "le": f"{le:g}"})
                    lines.append(f"{m.name}_bucket{lab} "
                                 f"{_fmt_value(buckets[le])}")
                inf_lab = _fmt_labels({**labels, "le": "+Inf"})
                lines.append(f"{m.name}_bucket{inf_lab} "
                             f"{_fmt_value(value['count'])}")
                lines.append(f"{m.name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(value['sum'])}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)} "
                             f"{_fmt_value(value['count'])}")
            else:
                lines.append(f"{m.name}{_fmt_labels(labels)} "
                             f"{_fmt_value(value)}")
    return "\n".join(lines) + "\n"


# ----------------------------------------------------- engine collection --
def collect_engine() -> list[tuple]:
    """Samples for the process-wide engine state: ENGINE phase totals and
    event counters, plan-cache stats, flight-recorder ring health.  The
    daemon layers its serving gauges on top; bench/CLI could render this
    alone.  jax-free (timers/plancache/trace all are)."""
    from spgemm_tpu.ops import plancache  # noqa: PLC0415
    from spgemm_tpu.obs import trace  # noqa: PLC0415
    from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415

    samples: list[tuple] = []
    totals = ENGINE.snapshot()
    counts = ENGINE.count_snapshot()
    for name in sorted(totals):
        samples.append(("spgemm_phase_seconds_total", {"phase": name},
                        totals[name]))
        samples.append(("spgemm_phase_entries_total", {"phase": name},
                        counts.get(name, 0)))
    for name, n in sorted(ENGINE.counter_snapshot().items()):
        samples.append(("spgemm_engine_events_total", {"event": name}, n))
    try:
        cache = plancache.stats()
    except ValueError:
        cache = None  # invalid cache knob: skip the rows, keep the scrape
    if cache is not None:
        samples += [
            ("spgemm_plan_cache_hits_total", {}, cache["hits"]),
            ("spgemm_plan_cache_misses_total", {}, cache["misses"]),
            ("spgemm_plan_cache_evictions_total", {},
             cache.get("evictions", 0)),
            ("spgemm_plan_cache_entries", {}, cache["entries"]),
            ("spgemm_plan_cache_capacity", {}, cache["capacity"]),
        ]
    from spgemm_tpu.ops import warmstore  # noqa: PLC0415
    try:
        warm = warmstore.stats()
    except ValueError:
        warm = None  # invalid warm knob: skip the rows, keep the scrape
    if warm is not None:
        samples += [
            ("spgemm_warm_hits_total", {},
             warm["plan_hits"] + warm["delta_hits"]),
            ("spgemm_warm_misses_total", {},
             warm["plan_misses"] + warm["delta_misses"]),
            ("spgemm_warm_corrupt_total", {}, warm["corrupt"]),
            ("spgemm_warm_entries", {"kind": "plan"}, warm["plans"]),
            ("spgemm_warm_entries", {"kind": "delta"}, warm["deltas"]),
            ("spgemm_warm_bytes", {}, warm["bytes"]),
        ]
        # count-0-gated: the tune tier's kind row only renders once a
        # tuned override persisted, so a TUNE=0 (or never-tuned) scrape
        # stays byte-identical to the pre-tuner daemon's
        if warm.get("tunes"):
            samples.append(("spgemm_warm_entries", {"kind": "tune"},
                            warm["tunes"]))
    ring = trace.RECORDER.stats()
    samples += [
        ("spgemm_trace_spans", {}, ring["spans"]),
        ("spgemm_trace_spans_emitted_total", {}, ring["emitted"]),
        ("spgemm_trace_spans_dropped_total", {}, ring["dropped"]),
    ]
    from spgemm_tpu.utils import failpoints  # noqa: PLC0415
    samples += [("spgemm_failpoints_triggered_total", {"point": point}, n)
                for point, n in sorted(failpoints.triggered().items())]
    samples += _collect_profile()
    return samples


def _collect_profile() -> list[tuple]:
    """Deep-profiling samples (obs/profile.py + obs/events.py): compile
    accounting per site, phase latency histograms, prediction
    accountability, memory watermarks (omitted when the backend never
    reported -- the CPU graceful-omission contract), event-log health.
    jax-free like the rest of the scrape path."""
    from spgemm_tpu.obs import events, profile  # noqa: PLC0415

    samples: list[tuple] = []
    for site, agg in profile.compile_stats().items():
        labels = {"site": site}
        samples += [
            ("spgemm_compiles_total", labels, agg["count"]),
            ("spgemm_compile_seconds", labels, agg["seconds"]),
            ("spgemm_compile_flops_total", labels, agg["flops_total"]),
            ("spgemm_compile_bytes_total", labels, agg["bytes_total"]),
            ("spgemm_compile_temp_bytes", labels, agg["temp_bytes_max"]),
        ]
    for phase, hist in profile.phase_stats().items():
        samples.append(("spgemm_phase_seconds", {"phase": phase}, hist))
    est = profile.est_stats()
    for quantity, hist in est["rel_error"].items():
        samples.append(("spgemm_est_rel_error", {"quantity": quantity},
                        hist))
    # rendered unconditionally (zero-count histogram / zero counter), so
    # an alert rule never has to distinguish "absent" from "zero" -- the
    # same contract spgemm_hbm_samples_total keeps
    dlt = profile.delta_stats()
    samples.append(("spgemm_delta_dirty_fraction", {},
                    dlt["dirty_fraction"]))
    samples.append(("spgemm_delta_mispredictions_total", {},
                    dlt["mispredictions"]))
    mem = profile.memory_stats()
    samples.append(("spgemm_hbm_samples_total", {}, mem["samples"]))
    if mem["available"]:
        samples += [
            ("spgemm_hbm_bytes_in_use", {}, mem["bytes_in_use"]),
            ("spgemm_hbm_peak_bytes", {}, mem["peak_bytes"]),
        ]
    ev = events.LOG.stats()
    samples += [
        ("spgemm_events_emitted_total", {}, ev["emitted"]),
        ("spgemm_events_dropped_total", {}, ev["dropped"]),
        ("spgemm_events_rotations_total", {}, ev["rotations"]),
        ("spgemm_events_bytes", {}, ev["bytes"]),
    ]
    return samples


# -------------------------------------------------------- generated docs --
def metrics_table_md() -> str:
    """The generated ARCHITECTURE.md metrics table (families + the
    declared ENGINE phase/counter name sets).  The DOC lint rule diffs
    this text against the committed block between the
    `<!-- metrics-table:begin -->` / `<!-- metrics-table:end -->` markers;
    regenerate with `python -m spgemm_tpu.analysis
    --write-metrics-table`."""
    lines = [
        "| metric | type | labels | produced in | what it measures |",
        "|---|---|---|---|---|",
    ]

    def md(cell: str) -> str:
        return cell.replace("|", "\\|")

    for m in _METRICS:
        labels = ", ".join(f"`{label}`" for label in m.labels) or "—"
        lines.append(f"| `{m.name}` | {m.kind} | {labels} | `{m.module}` "
                     f"| {md(m.doc)} |")
    lines.append("")
    lines.append("Declared `phase` label values (ENGINE phase names): "
                 + ", ".join(f"`{n}`" for n in ENGINE_PHASES) + ".")
    lines.append("")
    lines.append("Declared `event` label values (ENGINE counter names): "
                 + ", ".join(f"`{n}`" for n in ENGINE_COUNTERS) + ".")
    return "\n".join(lines)
