"""Deep profiling layer (L5): what the engine COST and whether it was RIGHT.

PR 7's flight recorder answers *when* each engine phase ran; this module
answers what it cost the machine and whether the engine's predictions
held, in four accounts:

  * **Compile & cost accounting** -- every engine jit compile (the XLA
    numeric round, the fused assembly gather, the delta splice, the
    ring/rowshard shard_map entrypoints) is recorded via `ProfiledJit`:
    compile wall, the jit-static knob vector it was compiled under, the
    compiled executable's `cost_analysis()` FLOPs / bytes-accessed and
    `memory_analysis()` argument/output/temp bytes.  This is the number
    the persistent-warm-start roadmap item will claim to remove (and the
    JITSPMM amortization argument, PAPERS.md, made measurable).
  * **Memory watermark telemetry** -- the engine samples
    `device.memory_stats()` at its dispatch/assembly boundaries and
    pushes the readings here (`observe_memory`); backends without the
    API (e.g. CPU) report nothing and every gauge is gracefully omitted.
    Finally makes SPGEMM_TPU_DELTA_RETAIN's entries-not-bytes bound
    observable on a serving device.
  * **Prediction accountability** -- when a deferred exact join lands
    (SpgemmPlan.ensure_exact), the sampled estimate's keys/pairs/fanout
    are scored against the exact join (`observe_estimate`, relative-error
    histograms); every delta-enabled multiply scores predicted-dirty vs
    actually-executed output rows (`observe_delta`).  A drifting
    estimator becomes an alertable series, not a silent mis-plan.
  * **Phase latency histograms** -- every completed flight-recorder span
    feeds a per-phase histogram (`observe_phase`), so scrape-side phase
    latency exists without pulling a trace dump.

The whole layer is keyed off `SPGEMM_TPU_OBS_TRACE` (the L5 master A/B
knob): at 0 nothing records, `ProfiledJit` degrades to the plain jit
call, and every series stays flat -- inert by construction, pinned in
tests/test_profile.py.

jax-free BY CONSTRUCTION like the rest of obs/ (the subprocess pin in
tests/test_obs.py covers it): `ProfiledJit` drives the AOT surface of
whatever jit-wrapped callable it is handed purely by duck typing
(`.lower(...).compile()`), and the memory/prediction accounts only
receive plain numbers the jax-side engine pushes in.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict

from spgemm_tpu.utils import knobs

log = logging.getLogger("spgemm_tpu.profile")


def enabled() -> bool:
    """The L5 master knob (SPGEMM_TPU_OBS_TRACE): the deep-profiling
    layer records only while span emission is on -- one A/B flag prices
    the whole observability stack."""
    return knobs.get("SPGEMM_TPU_OBS_TRACE")


def static_knob_vector() -> tuple:
    """Every jit-static knob's current value -- the compile record's
    provenance: two records for one site with different vectors are two
    different executables by the registry's own staticity contract.
    Delegates to the canonical registry definition (knobs.
    jit_static_vector), shared with the plan-cache fingerprint and the
    warm-start store's on-disk validation."""
    return knobs.jit_static_vector()


# ------------------------------------------------------------ histograms --
COMPILE_BUCKETS = (0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0)
REL_ERR_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0)
FRACTION_BUCKETS = (0.0, 0.01, 0.05, 0.1, 0.25, 0.5, 0.9)
PHASE_BUCKETS = (0.0001, 0.001, 0.01, 0.1, 1.0, 10.0, 60.0)


class Hist:
    """Fixed-bucket histogram in the Prometheus sample shape the metrics
    renderer consumes ({"buckets": {le: cumulative}, "sum", "count"}).
    NOT self-locked: every instance below is mutated under the module
    _LOCK (one lock, acquired once per observation batch)."""

    def __init__(self, buckets: tuple):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.sum = 0.0
        self.count = 0

    def observe_locked(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, le in enumerate(self.buckets):
            if value <= le:
                self.counts[i] += 1

    def snapshot_locked(self) -> dict:
        # counts[i] is ALREADY cumulative (observe bumps every bucket
        # whose bound admits the value -- the Prometheus bucket shape)
        return {"buckets": dict(zip(self.buckets, self.counts)),
                "sum": round(self.sum, 6), "count": self.count}


# --------------------------------------------------------------- the book --
# compile records retained for `cli profile` (aggregates are unbounded
# counters; the per-record list is ring-bounded like every other resident
# buffer in L5)
COMPILE_RETAIN = 256

_LOCK = threading.Lock()
_COMPILES: list[dict] = []          # spgemm-lint: guarded-by(_LOCK)
_COMPILE_DROPPED = 0                # spgemm-lint: guarded-by(_LOCK)
_SITES: dict[str, dict] = {}        # spgemm-lint: guarded-by(_LOCK)
_MEM = {"available": False, "samples": 0, "bytes_in_use": 0,
        "peak_bytes": 0}            # spgemm-lint: guarded-by(_LOCK)
# per-job HBM high-water marks, keyed by the emitting thread's span
# job_id tag (LRU-bounded).  Keyed -- NOT one global window -- so a
# wedged executor's late samples land in ITS job's window, never the
# replacement executor's (the same cross-job attribution contract
# PhaseScope enforces for phases).
_MEM_JOBS: "OrderedDict[str, int]" = OrderedDict()  # spgemm-lint: guarded-by(_LOCK)
MEM_JOB_RETAIN = 64
_EST: dict[str, Hist] = {}          # spgemm-lint: guarded-by(_LOCK)
_EST_COUNT = 0                      # spgemm-lint: guarded-by(_LOCK)
_DELTA = {"hist": Hist(FRACTION_BUCKETS), "predicted": 0,
          "executed": 0,
          "mispredictions": 0}      # spgemm-lint: guarded-by(_LOCK)
_PHASES: dict[str, Hist] = {}       # spgemm-lint: guarded-by(_LOCK)


def clear() -> None:
    """Zero every account (tests, A/B harnesses, bench iterations)."""
    global _COMPILE_DROPPED, _EST_COUNT
    with _LOCK:
        _COMPILES.clear()
        _COMPILE_DROPPED = 0
        _SITES.clear()
        _MEM.update(available=False, samples=0, bytes_in_use=0,
                    peak_bytes=0)
        _MEM_JOBS.clear()
        _EST.clear()
        _EST_COUNT = 0
        _DELTA["hist"] = Hist(FRACTION_BUCKETS)
        _DELTA["predicted"] = _DELTA["executed"] = 0
        _DELTA["mispredictions"] = 0
        _PHASES.clear()


# ----------------------------------------------------- compile accounting --
def record_compile(site: str, wall_s: float, signature,
                   cost: dict, memory: dict) -> None:
    """Land one compile record: per-record entry (bounded), per-site
    aggregates, the `compiles` engine counter (per-job attribution: a
    job's status detail shows which job paid the cold-jit tax), and a
    structured event."""
    global _COMPILE_DROPPED
    rec = {
        "site": site,
        "wall_s": round(wall_s, 6),
        "signature": repr(signature),
        "static_knobs": dict(static_knob_vector()),
        "flops": cost.get("flops", 0.0),
        "bytes_accessed": cost.get("bytes accessed", 0.0),
        **{k: memory.get(k, 0) for k in ("argument_bytes", "output_bytes",
                                         "temp_bytes", "code_bytes")},
        "ts": round(time.time(), 3),
    }
    with _LOCK:
        _COMPILES.append(rec)
        while len(_COMPILES) > COMPILE_RETAIN:
            _COMPILES.pop(0)
            _COMPILE_DROPPED += 1
        agg = _SITES.get(site)
        if agg is None:
            agg = _SITES[site] = {"count": 0, "seconds": Hist(COMPILE_BUCKETS),
                                  "flops_total": 0.0, "bytes_total": 0.0,
                                  "temp_bytes_max": 0}
        agg["count"] += 1
        agg["seconds"].observe_locked(wall_s)
        agg["flops_total"] += rec["flops"]
        agg["bytes_total"] += rec["bytes_accessed"]
        agg["temp_bytes_max"] = max(agg["temp_bytes_max"], rec["temp_bytes"])
    # per-job attribution + MET-declared counter (lazy import: timers ->
    # trace -> profile is the load chain, so importing timers at module
    # scope here would be a cycle)
    from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415
    ENGINE.incr("compiles")
    from spgemm_tpu.obs import events  # noqa: PLC0415
    events.emit("compile", site=site, wall_s=rec["wall_s"],
                flops=rec["flops"], temp_bytes=rec["temp_bytes"])


def _cost_dict(compiled) -> dict:
    """compiled.cost_analysis() across jax versions: a dict, a list of
    dicts, or unavailable -- always reduced to one plain dict."""
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 -- accounting must never break dispatch
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if isinstance(cost, dict) else {}


def _memory_dict(compiled) -> dict:
    """compiled.memory_analysis() reduced to plain bytes (0 when the
    backend does not implement it)."""
    try:
        mem = compiled.memory_analysis()
        return {
            "argument_bytes": int(getattr(mem, "argument_size_in_bytes", 0)),
            "output_bytes": int(getattr(mem, "output_size_in_bytes", 0)),
            "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0)),
            "code_bytes": int(getattr(mem, "generated_code_size_in_bytes",
                                      0)),
        }
    except Exception:  # noqa: BLE001 -- accounting must never break dispatch
        return {}


def _arg_sig(x):
    """One argument's abstract signature (shape/dtype/placement), pytree
    lists included -- the key under which one compiled executable is
    valid.  Placement rides along because an AOT executable is committed
    to its devices (parallel/chainpart runs one chain per device)."""
    if isinstance(x, (list, tuple)):
        return tuple(_arg_sig(e) for e in x)
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return repr(x)
    try:
        devs = tuple(sorted(d.id for d in x.devices()))
    except Exception:  # noqa: BLE001 -- placement is best-effort key salt
        devs = ()
    return (tuple(shape), str(dtype), devs)


class ProfiledJit:
    """Compile-accounting wrapper over one jit-wrapped callable.

    First contact per abstract signature goes through the AOT surface --
    `fn.lower(*args, **static_kwargs).compile()` -- timing the compile
    wall and reading the executable's cost/memory analyses into
    `record_compile`; the compiled executable is kept and every later
    same-signature call runs it directly (no double compile: the plain
    jit dispatch cache is never populated on this path).  Any AOT quirk
    (an exotic arg pytree, a backend without the surface) permanently
    degrades THIS wrapper to the uninstrumented jit call -- accounting
    must never break dispatch.  With the layer disabled
    (SPGEMM_TPU_OBS_TRACE=0) the wrapper is a plain pass-through.

    Duck-typed on `.lower` (no jax import -- this module stays in the
    obs jax-free contract); the jax-side modules construct instances.
    """

    def __init__(self, site: str, fn):
        self.site = site
        self._fn = fn
        self._lock = threading.Lock()
        self._compiled: dict = {}  # spgemm-lint: guarded-by(_lock)
        self._broken = not hasattr(fn, "lower")

    def __call__(self, *args, **kwargs):
        if self._broken or not enabled():
            return self._fn(*args, **kwargs)
        try:
            key = _arg_sig(args)
            if kwargs:
                key = (key, tuple(sorted((k, repr(v))
                                         for k, v in kwargs.items())))
        except Exception:  # noqa: BLE001 -- accounting must never break dispatch
            return self._fn(*args, **kwargs)
        with self._lock:
            compiled = self._compiled.get(key)
        if compiled is None:
            try:
                t0 = time.perf_counter()
                compiled = self._fn.lower(*args, **kwargs).compile()
                wall = time.perf_counter() - t0
            except Exception as e:  # noqa: BLE001 -- AOT quirk: degrade to plain jit for process lifetime
                self._broken = True
                log.warning("compile accounting for %s disabled: AOT "
                            "lower/compile failed (%r); dispatching the "
                            "plain jit from here on", self.site, e)
                return self._fn(*args, **kwargs)
            record_compile(self.site, wall, key, _cost_dict(compiled),
                           _memory_dict(compiled))
            with self._lock:
                self._compiled[key] = compiled
        try:
            # static kwargs are baked into the executable; only the
            # dynamic args ship
            return compiled(*args)
        except Exception as e:  # noqa: BLE001 -- an AOT call mismatch must fall back, not fail the multiply
            # degrade PERMANENTLY: a persistent call-path mismatch must
            # not pay a failed dispatch per multiply, and a genuine
            # runtime error (device OOM) must surface from the plain jit
            # retry below -- once, not masked forever
            self._broken = True
            log.warning("compile-accounted dispatch for %s failed (%r); "
                        "degrading to the plain jit call", self.site, e)
            return self._fn(*args, **kwargs)


# ------------------------------------------------------- memory watermark --
def _bump_job_peak_locked(job_id: str, in_use: int) -> None:
    _MEM_JOBS[job_id] = max(_MEM_JOBS.get(job_id, 0), in_use)
    _MEM_JOBS.move_to_end(job_id)
    while len(_MEM_JOBS) > MEM_JOB_RETAIN:
        _MEM_JOBS.popitem(last=False)


def observe_memory(stats: dict | None) -> None:
    """One device memory_stats() reading, pushed by the jax-side engine
    at its dispatch/assembly boundaries.  None (the CPU backend, or a
    raising plugin) leaves every gauge unavailable -- graceful omission,
    never a crash.  The reading also lands in the per-job window of the
    emitting thread's span job_id tag (if any) -- a wedged executor's
    late samples therefore stay attributed to ITS job, never the
    replacement's."""
    if not enabled():
        return
    if not isinstance(stats, dict) or "bytes_in_use" not in stats:
        return
    from spgemm_tpu.obs import trace  # noqa: PLC0415 -- trace lazily imports profile back
    job_id = trace.RECORDER.current_tags().get("job_id")
    in_use = int(stats["bytes_in_use"])
    peak = max(int(stats.get("peak_bytes_in_use", 0)), in_use)
    with _LOCK:
        _MEM["available"] = True
        _MEM["samples"] += 1
        _MEM["bytes_in_use"] = in_use
        _MEM["peak_bytes"] = max(_MEM["peak_bytes"], peak)
        if job_id is not None:
            _bump_job_peak_locked(str(job_id), in_use)


def memory_job_begin(job_id: str) -> None:
    """Open (or reset) `job_id`'s high-water window, seeded with the
    newest reading so retained results pinned BEFORE the job count
    toward its peak.  No-op while the backend has never reported."""
    with _LOCK:
        if _MEM["available"]:
            _MEM_JOBS.pop(str(job_id), None)
            _bump_job_peak_locked(str(job_id), _MEM["bytes_in_use"])


def memory_job_peak(job_id: str | None) -> int | None:
    """Peak bytes_in_use observed in `job_id`'s window, or None when the
    backend never reported for it (the detail key is then omitted, not
    zero).  Non-destructive: a reaped job's detail may be read again at
    its wedge declaration."""
    if job_id is None:
        return None
    with _LOCK:
        return _MEM_JOBS.get(str(job_id))


def memory_stats() -> dict:
    with _LOCK:
        return dict(_MEM)


# ------------------------------------------------ prediction accountability --
def _rel_err(predicted: float, actual: float) -> float:
    return abs(float(predicted) - float(actual)) / max(float(actual), 1.0)


def observe_estimate(est_keys: float, est_pairs: float, est_fanout: float,
                     actual_keys: float, actual_pairs: float,
                     actual_fanout: float) -> None:
    """Score one sampled structure estimate against the exact join it
    predicted (called when SpgemmPlan.ensure_exact lands the join)."""
    global _EST_COUNT
    if not enabled():
        return
    errors = {"keys": _rel_err(est_keys, actual_keys),
              "pairs": _rel_err(est_pairs, actual_pairs),
              "fanout": _rel_err(est_fanout, actual_fanout)}
    with _LOCK:
        _EST_COUNT += 1
        for quantity, err in errors.items():
            hist = _EST.get(quantity)
            if hist is None:
                hist = _EST[quantity] = Hist(REL_ERR_BUCKETS)
            hist.observe_locked(err)


def observe_delta(predicted_rows: int, executed_rows: int,
                  total_rows: int) -> None:
    """Account one delta-enabled multiply.  The histogram records the
    predicted-dirty FRACTION (predicted rows / total rows; a counted
    full fallback observes 1.0) -- the per-multiply distribution behind
    the aggregate delta_rows_* counters, i.e. how incremental the
    submit stream actually is.  Predicted-vs-executed rows are kept as
    totals plus a `mispredictions` count: today's engine executes
    exactly the rows it predicts (the diff's reachability IS the
    sub-plan), so any divergence is an engine bug worth an alert, not a
    distribution."""
    if not enabled():
        return
    frac = min(1.0, int(predicted_rows) / max(int(total_rows), 1))
    with _LOCK:
        _DELTA["hist"].observe_locked(frac)
        _DELTA["predicted"] += int(predicted_rows)
        _DELTA["executed"] += int(executed_rows)
        if int(executed_rows) != int(predicted_rows):
            _DELTA["mispredictions"] += 1


# -------------------------------------------------- phase latency histogram --
def observe_phase(name: str, dur_s: float) -> None:
    """One completed span's duration (fed by the flight recorder on
    commit -- already gated on the master knob upstream).  Only
    DECLARED engine phase names are admitted: the recorder also carries
    spans from ad-hoc PhaseTimers instances (the run-once CLI's local
    driver phases), which are deliberately outside the MET registry and
    must not mint undeclared label values on a declared-only family."""
    from spgemm_tpu.obs.metrics import ENGINE_PHASES  # noqa: PLC0415 -- metrics lazily imports profile back
    if name not in ENGINE_PHASES:
        return
    with _LOCK:
        hist = _PHASES.get(name)
        if hist is None:
            hist = _PHASES[name] = Hist(PHASE_BUCKETS)
        hist.observe_locked(dur_s)


# ------------------------------------------------------------- inspection --
def compile_stats() -> dict:
    """Per-site compile aggregates (Prometheus-shaped histograms)."""
    with _LOCK:
        return {site: {"count": agg["count"],
                       "seconds": agg["seconds"].snapshot_locked(),
                       "flops_total": agg["flops_total"],
                       "bytes_total": agg["bytes_total"],
                       "temp_bytes_max": agg["temp_bytes_max"]}
                for site, agg in sorted(_SITES.items())}


def est_stats() -> dict:
    with _LOCK:
        return {"count": _EST_COUNT,
                "rel_error": {q: h.snapshot_locked()
                              for q, h in sorted(_EST.items())}}


def delta_stats() -> dict:
    with _LOCK:
        return {"count": _DELTA["hist"].count,
                "predicted_rows": _DELTA["predicted"],
                "executed_rows": _DELTA["executed"],
                "mispredictions": _DELTA["mispredictions"],
                "dirty_fraction": _DELTA["hist"].snapshot_locked()}


def phase_stats() -> dict:
    with _LOCK:
        return {name: h.snapshot_locked()
                for name, h in sorted(_PHASES.items())}


def report() -> dict:
    """The `cli profile [--json]` payload: bounded per-record compile
    list + every aggregate account.  jax-free (daemon scrape-side)."""
    from spgemm_tpu.obs import events  # noqa: PLC0415
    with _LOCK:
        compiles = [dict(r) for r in _COMPILES]
        dropped = _COMPILE_DROPPED
    return {
        "enabled": enabled(),
        "compiles": compiles,
        "compiles_dropped": dropped,
        "compile_sites": compile_stats(),
        "memory": memory_stats(),
        "estimator": est_stats(),
        "delta": delta_stats(),
        "events": events.LOG.stats(),
    }


def summary() -> dict:
    """The one-line accountability digest (`cli knobs`, bench detail):
    compile count/wall, estimator mean relative errors, delta prediction
    mean error -- the numbers an operator eyeballs for drift."""
    with _LOCK:
        n_compiles = sum(agg["count"] for agg in _SITES.values())
        compile_s = sum(agg["seconds"].sum for agg in _SITES.values())
        est = {q: round(h.sum / h.count, 4)
               for q, h in sorted(_EST.items()) if h.count}
        est_n = _EST_COUNT
        d = _DELTA["hist"]
        delta_frac = round(d.sum / d.count, 4) if d.count else None
        delta_n = d.count
        mispredict = _DELTA["mispredictions"]
        mem = (_MEM["peak_bytes"] if _MEM["available"] else None)
    return {"compiles": n_compiles, "compile_s": round(compile_s, 4),
            "est_observations": est_n, "est_mean_rel_error": est,
            "delta_observations": delta_n,
            "delta_mean_dirty_fraction": delta_frac,
            "delta_mispredictions": mispredict,
            "hbm_peak_bytes": mem}
