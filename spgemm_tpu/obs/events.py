"""Structured event log (L5): bounded, rotating JSONL of engine/daemon
lifecycle transitions.

The flight recorder (obs/trace.py) answers "what was running when"; this
log answers "what HAPPENED": job lifecycle (submit/start/done/failed),
watchdog reap/wedge/degrade, estimator and delta fallbacks WITH their
reasons, chain failover, and jit compile records.  One JSON object per
line, each carrying a monotonically increasing `seq`, wall-clock `ts`,
a `mono_us` timestamp on the flight recorder's span origin (so an event
lines up against the Perfetto timeline), and the emitting thread's
active job/trace tags (auto-correlation: an event emitted inside a
tagged job span carries that job's id without the call site passing it).

Two sinks, both bounded:

  * an in-process ring (`RING_RETAIN` newest records) -- what the
    daemon's `events` op and `spgemm_tpu.cli events --tail N` read;
  * optionally a JSONL file (`configure()`; spgemmd points it next to
    the journal at `<socket>.events.jsonl`), rotated to `<path>.1` when
    it grows past SPGEMM_TPU_OBS_EVENTS_MAX_KB -- worst-case disk is
    ~2x the cap, never unbounded under a resident daemon.

`SPGEMM_TPU_OBS_EVENTS=0` disables emission entirely (both sinks).
Writes are best-effort AND asynchronous: emit() only appends to the
ring and a bounded pending queue; a single daemonized writer thread
does every file syscall, so a stalling filesystem (NFS hang, full
disk) can never block an emitting thread -- in particular never the
spgemmd watchdog, whose reap/degrade emits sit on the recovery path.
Write errors are counted, a pending queue past its bound drops the
OLDEST lines (counted) -- the ring keeps the newest records either
way.  `flush()` waits for the pending queue to drain (tests, daemon
shutdown).

jax-free by construction, like the rest of obs/ (subprocess-pinned in
tests/test_obs.py).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from spgemm_tpu.obs import trace
from spgemm_tpu.utils import knobs


# THE event-kind registry (the ENGINE_PHASES pattern from
# obs/metrics.py): kind -> doc.  The EVT lint rule holds every
# emit()/LOG.emit() call site to a string literal declared here, the
# DRF audit flags a declared kind no site emits, and the generated
# ARCHITECTURE.md event table renders this dict -- an ad-hoc kind at a
# call site is a lint finding, not a new unauditable stream.
# Enforcement is lint-time only (exactly like the ENGINE phase names):
# emit() never validates at runtime, so emitters stay syscall- and
# check-free on the hot path.
EVENT_KINDS: dict[str, str] = {
    "daemon_start": "spgemmd came up (socket, slice spec, pid)",
    "daemon_drain_reap": "SIGTERM/SIGINT drain reaped an in-flight job "
                         "that outlived the grace window",
    "daemon_degrade": "a slice (or the whole daemon) degraded to the "
                      "CPU oracle failover path, with the reason",
    "journal_torn": "journal replay truncated at the first torn "
                    "(CRC/length-framing) record",
    "job_submit": "job admitted: id, folder, queue depth, tenant, "
                  "trace context, placement class",
    "job_start": "an executor picked the job up (slice, stolen flag, "
                 "batch id when co-batched)",
    "job_done": "job finished bit-exact terminal",
    "job_failed": "job ended in a structured error (code rides along)",
    "watchdog_reap": "watchdog reaped a job past its deadline",
    "watchdog_wedge": "executor declared wedged after the reap grace "
                      "window passed without a heartbeat",
    "slice_canary": "reinstated slice's canary audition armed: first "
                    "job runs a tightened deadline",
    "slice_canary_passed": "canary job succeeded; the slice is fully "
                           "reinstated into placement",
    "slice_recover_probe": "off-thread backend re-probe of a degraded "
                           "slice (outcome rides along)",
    "slice_recovered": "re-probe came back live; slice reinstated "
                       "behind the canary gate",
    "accum_route_mismatch": "dense-route crossover gate disagreed with "
                            "the measured outcome (counted, bit-exact "
                            "either way)",
    "est_fallback": "sampled estimator fell back to the exact symbolic "
                    "join, with the reason",
    "delta_fallback": "delta recompute fell back to the full path, "
                      "with the reason",
    "plan_exact_landed": "an estimated plan's deferred exact join "
                         "landed off the critical path",
    "warm_disabled": "warm store ran cold (flock contention or knob), "
                     "with the reason",
    "warm_load": "warm-store entries loaded on fingerprint match after "
                 "a restart",
    "warm_corrupt_skipped": "corrupt/version-skewed warm entry skipped "
                            "as a counted cold fallback",
    "warm_flush": "warm store flushed to disk (entry counts ride "
                  "along)",
    "chain_failover": "chain engine failed over to the CPU oracle "
                      "path, with the triggering error",
    "compile": "jit compile record: site, wall, FLOPs/bytes, memory "
               "footprints (obs/profile.py)",
    "slo_burn": "SLO burn-rate breach transition for a (tenant, slice) "
                "window; carries the newest bad job's trace context",
    "slo_burn_clear": "the burn condition cleared for the window",
    "failpoint_trigger": "an armed chaos failpoint fired (point name "
                         "and action ride along)",
    "tune_trial": "autotuner ran one timed trial leg on an idle slice "
                  "(class, knob vector, measured wall ride along)",
    "tune_trial_preempted": "a trial leg aborted because a real job "
                            "arrived (or another slice swapped the "
                            "overlay mid-measurement); the measurement "
                            "was discarded",
    "tune_apply": "a class's tuned override was promoted (trial winner "
                  "persisted to the warm tune tier, canary armed) or "
                  "re-activated at job pickup",
    "tune_canary_passed": "the first job under a fresh tuned override "
                          "committed clean; the override is live",
    "tune_revert": "a tuned override was dropped (canary failure or "
                   "trial-time parity mismatch) and its class backed "
                   "off before re-trial",
    "router_start": "spgemm-router came up (listen address, backend "
                    "list, poll cadence)",
    "router_backend_down": "a backend failed its stats poll (or was "
                           "degraded) and left placement",
    "router_backend_up": "a backend answered its stats poll healthy "
                         "and (re)joined placement",
    "router_failover": "a job's backend died mid-flight; the job was "
                       "re-submitted once to a healthy peer (or "
                       "failed structured backend-lost -- outcome "
                       "rides along)",
}


def event_table_md() -> str:
    """The generated event-kind table for ARCHITECTURE.md (the DOC rule
    diffs the committed block against this; regenerate with
    `python -m spgemm_tpu.analysis --write-event-table`)."""
    lines = ["| event kind | when it fires |", "|---|---|"]
    for kind, doc in EVENT_KINDS.items():
        lines.append(f"| `{kind}` | {doc} |")
    return "\n".join(lines)


def enabled() -> bool:
    """SPGEMM_TPU_OBS_EVENTS=0|1 (default 1)."""
    return knobs.get("SPGEMM_TPU_OBS_EVENTS")


def cap_bytes() -> int:
    """SPGEMM_TPU_OBS_EVENTS_MAX_KB (default 256) in bytes."""
    return knobs.get("SPGEMM_TPU_OBS_EVENTS_MAX_KB") * 1024


class EventLog:
    """The process-wide event emitter: bounded ring + async rotating
    file sink (one daemonized writer thread owns every file syscall)."""

    # in-process records retained for tail()/the daemon `events` op
    RING_RETAIN = 512
    # encoded lines awaiting the writer thread: past this the OLDEST
    # pending lines drop (counted) -- a stalled disk bounds memory, and
    # the ring above still holds the newest records
    PENDING_RETAIN = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self._ring: deque = deque()   # spgemm-lint: guarded-by(_lock)
        self._pending: deque = deque()  # spgemm-lint: guarded-by(_lock)
        self._seq = 0                 # spgemm-lint: guarded-by(_lock)
        self._emitted = 0             # spgemm-lint: guarded-by(_lock)
        self._dropped = 0             # spgemm-lint: guarded-by(_lock)
        self._io_dropped = 0          # spgemm-lint: guarded-by(_lock)
        self._rotations = 0           # spgemm-lint: guarded-by(_lock)
        self._write_errors = 0        # spgemm-lint: guarded-by(_lock)
        self._path = None             # spgemm-lint: guarded-by(_lock)
        self._size = 0                # spgemm-lint: guarded-by(_lock)
        self._writer = None           # spgemm-lint: guarded-by(_lock)
        # lines POPPED from pending but not yet on disk: flush()'s drain
        # contract must cover them too, or a caller (test asserting file
        # bytes, daemon shutdown) can observe the rotation's mid-air
        # window -- old file replaced away, new one not yet created
        self._in_flight = 0           # spgemm-lint: guarded-by(_lock)
        self._wake = threading.Event()

    def configure(self, path: str | None) -> None:
        """Point the file sink at `path` (None detaches it) and start
        the writer thread on first attach.  An existing file is appended
        to -- its current size seeds the rotation budget, so a daemon
        restart cannot grow it past ~2x the cap."""
        with self._lock:
            self._path = path
            self._size = 0
            if path is not None:
                try:
                    self._size = os.path.getsize(path)
                except OSError:
                    self._size = 0
                if self._writer is None or not self._writer.is_alive():
                    self._writer = threading.Thread(
                        target=self._writer_loop, name="obs-events-writer",
                        daemon=True)
                    self._writer.start()
        self._wake.set()

    def emit(self, kind: str, **fields) -> None:
        """One event.  None-valued fields are dropped; the emitting
        thread's flight-recorder tags (job_id/trace_id) merge in under
        the explicit fields.  NO file I/O happens here -- the line is
        queued for the writer thread, so a stalling disk never blocks
        an emitter (the spgemmd watchdog emits on its recovery path)."""
        if not enabled():
            return
        rec = {"ts": round(time.time(), 6),
               "mono_us": round((time.perf_counter() - trace._BASE) * 1e6,
                                3),
               "kind": kind}
        rec.update(trace.RECORDER.current_tags())
        rec.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, **rec}
            self._ring.append(rec)
            self._emitted += 1
            while len(self._ring) > self.RING_RETAIN:
                self._ring.popleft()
                self._dropped += 1
            if self._path is None:
                return
            # encode here (cheap, no syscall): the rotation budget is in
            # BYTES, so the queued unit is the utf-8 line, not the str
            self._pending.append(
                (json.dumps(rec, separators=(",", ":"), default=str)
                 + "\n").encode("utf-8"))
            while len(self._pending) > self.PENDING_RETAIN:
                self._pending.popleft()
                self._io_dropped += 1
        self._wake.set()

    # ------------------------------------------------- the writer thread --
    def _writer_loop(self) -> None:
        while True:
            self._wake.wait(0.5)
            self._wake.clear()
            self._drain_once()

    def _drain_once(self) -> None:
        """Write queued lines until the pending queue is empty.  Every
        syscall happens here, on the writer thread, outside _lock --
        a blocked write stalls only this thread and the (bounded,
        oldest-dropped) pending queue."""
        while True:
            with self._lock:
                if self._path is None:
                    self._pending.clear()
                    return
                if not self._pending:
                    return
                data = self._pending.popleft()
                self._in_flight = 1
                path = self._path
                size = self._size
            cap = cap_bytes()
            rotated = False
            try:
                if size + len(data) > cap and size > 0:
                    # one rotation generation: the previous .1 is the
                    # price of boundedness
                    os.replace(path, path + ".1")
                    size = 0
                    rotated = True
                with open(path, "ab") as f:
                    f.write(data)
            except OSError:
                # best-effort sink: a full disk loses log lines, never
                # the device owner.  Re-stat the file so the tracked
                # size resyncs with reality -- a vanished file (an
                # operator logrotate/cleaner) must not leave a stale
                # over-cap _size that makes every later rotation attempt
                # fail forever; the next append simply recreates it.
                with self._lock:
                    self._in_flight = 0
                    self._write_errors += 1
                    if self._path == path:
                        if rotated:
                            self._rotations += 1
                        try:
                            self._size = os.path.getsize(path)
                        except OSError:
                            self._size = 0
                continue
            with self._lock:
                self._in_flight = 0
                if self._path == path:  # configure() may have moved it
                    self._size = size + len(data)
                    if rotated:
                        self._rotations += 1

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait for the pending queue -- AND the line the writer has
        already popped but not yet landed -- to drain (tests, daemon
        shutdown); True when both drained within `timeout`."""
        self._wake.set()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if ((not self._pending and not self._in_flight)
                        or self._path is None):
                    return True
                writer = self._writer
            if writer is None or not writer.is_alive():
                return False
            time.sleep(0.01)
        return False

    def tail(self, n: int = 50) -> list[dict]:
        """The newest n records, oldest first (copies)."""
        n = max(0, int(n))
        with self._lock:
            items = list(self._ring)
        return [dict(r) for r in items[len(items) - n:]]

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": enabled(), "ring": len(self._ring),
                    "emitted": self._emitted, "dropped": self._dropped,
                    "pending": len(self._pending),
                    "io_dropped": self._io_dropped,
                    "rotations": self._rotations,
                    "write_errors": self._write_errors,
                    "path": self._path, "bytes": self._size}

    def clear(self) -> None:
        """Drop the ring/pending lines and zero the counters; the file
        sink detaches (tests, harnesses).  The writer thread stays up
        for the next configure()."""
        with self._lock:
            self._ring.clear()
            self._pending.clear()
            self._seq = 0
            self._emitted = self._dropped = self._rotations = 0
            self._io_dropped = 0
            self._write_errors = 0
            self._path = None
            self._size = 0


# The process-wide log: the engine emits here, spgemmd configures the
# file sink and serves the `events` op from the ring.
LOG = EventLog()


def emit(kind: str, **fields) -> None:
    """Module-level convenience: LOG.emit."""
    LOG.emit(kind, **fields)


# ------------------------------------------------------------- follow --
def _read_records(path: str, offset: int) -> tuple[int, list[dict]]:
    """Complete JSONL records in `path` from byte `offset` on: returns
    (offset past the last complete line, parsed records).  A trailing
    half-written line is left for the next poll; a malformed line is
    skipped (its bytes are consumed -- the writer never rewrites)."""
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return offset, []
    end = data.rfind(b"\n")
    if end < 0:
        return offset, []
    records = []
    for line in data[: end + 1].splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            records.append(rec)
    return offset + end + 1, records


def follow_file(path: str, last_seq: int = 0, last_ts: float = 0.0,
                poll_s: float = 0.2, stop=None):
    """Yield records appended to the rotating event JSONL at `path`
    (the `cli events --follow` engine): polls the file, survives a
    rotation boundary (detected by INODE change, not just shrinkage --
    a burst can grow the fresh file past the old read offset within one
    poll; the tail of `<path>.1` beyond the old offset is drained
    first) without dropping or duplicating a line.  Dedup is on each
    record's (ts, seq) pair, not seq alone: a restarted daemon resets
    its seq counter while appending to the same file, and its records
    carry newer wall timestamps -- seq regression with a newer ts is a
    new generation, not a duplicate.  `stop` (optional callable) ends
    the generator when truthy (tests); the CLI ends it with Ctrl-C."""
    offset = 0
    last_ino: int | None = None
    # (wall ts, seq): generation-safe dedup -- pass the newest
    # already-printed record's ts alongside its seq, or file re-reads of
    # the same records (their real ts beats a zero) would duplicate
    last_key = (last_ts, last_seq)

    def _emit_new(records):
        nonlocal last_key
        for rec in records:
            key = (rec.get("ts", 0.0), rec.get("seq", 0))
            if key > last_key:
                last_key = key
                yield rec

    while True:
        if stop is not None and stop():
            return
        try:
            st = os.stat(path)
            size, ino = st.st_size, st.st_ino
        except OSError:
            size, ino = 0, None  # sink not created yet (or mid-rotation)
        rotated = (last_ino is not None and ino is not None
                   and ino != last_ino) or size < offset
        if rotated:
            # the bytes past our offset moved to <path>.1 -- drain them
            # before reading the fresh file from 0
            _, old_tail = _read_records(path + ".1", offset)
            yield from _emit_new(old_tail)
            offset = 0
        if ino is not None:
            last_ino = ino
        offset, records = _read_records(path, offset)
        yield from _emit_new(records)
        time.sleep(poll_s)
