"""L5 observability: span tracing, metrics, trace export.

Host-side and jax-free BY CONSTRUCTION (pinned by a subprocess test,
mirroring the linter's jax-free contract): the flight recorder and the
metrics registry are scraped/dumped from client processes and watchdog
threads that must never touch -- or hang on -- a backend.

  * obs/trace.py   -- the span flight recorder: every PhaseTimers phase
    enter/exit emits a span (monotonic ts, duration, parent, job/trace
    tags) into a bounded in-process ring, exportable as Perfetto/Chrome
    trace_event JSON.
  * obs/metrics.py -- the metrics registry (knobs.py-style single source
    of truth: name, type, help) + Prometheus text-format 0.0.4 renderer
    behind spgemmd's `metrics` op and `spgemm_tpu.cli metrics`.
"""
