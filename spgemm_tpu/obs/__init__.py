"""L5 observability: span tracing, metrics, deep profiling, event log.

Host-side and jax-free BY CONSTRUCTION (pinned by a subprocess test,
mirroring the linter's jax-free contract): the flight recorder, the
metrics registry, the profiling accounts, and the event log are
scraped/dumped from client processes and watchdog threads that must
never touch -- or hang on -- a backend.

  * obs/trace.py   -- the span flight recorder: every PhaseTimers phase
    enter/exit emits a span (monotonic ts, duration, parent, job/trace
    tags) into a bounded in-process ring, exportable as Perfetto/Chrome
    trace_event JSON.
  * obs/metrics.py -- the metrics registry (knobs.py-style single source
    of truth: name, type, help) + Prometheus text-format 0.0.4 renderer
    behind spgemmd's `metrics` op and `spgemm_tpu.cli metrics`.
  * obs/profile.py -- the deep-profiling layer: jit compile/cost/memory
    accounting (ProfiledJit over the engine's AOT surface), device
    memory watermarks (pushed by the jax-side engine; gracefully absent
    on backends without memory_stats), estimator and delta prediction
    accountability (predicted vs realized), and per-phase latency
    histograms fed from completed spans.  Inert under
    SPGEMM_TPU_OBS_TRACE=0 -- the same master A/B knob as the recorder.
  * obs/events.py  -- the structured event log: bounded in-process ring
    + rotating JSONL next to the spgemmd journal (job lifecycle,
    watchdog reap/wedge/degrade, est/delta fallbacks with reasons,
    compile records), auto-correlated with span job/trace tags;
    SPGEMM_TPU_OBS_EVENTS / SPGEMM_TPU_OBS_EVENTS_MAX_KB.
"""
