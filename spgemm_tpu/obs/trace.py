"""Span flight recorder: the engine's in-process black box.

Every `utils/timers.PhaseTimers` phase enter/exit emits a span here --
name, monotonic timestamp, duration, thread, parent span, and whatever
job/trace tags are active on the emitting thread -- into a bounded ring
(`SPGEMM_TPU_OBS_RING_CAP`, default 4096 spans; the oldest are evicted
and counted, never an unbounded buffer inside a resident daemon).  The
ring is what a wedge/degrade postmortem reads: spgemmd snapshots it next
to the job journal on every reap/degrade transition, the `trace` op and
`spgemm_tpu.cli trace-dump` serialize it as Perfetto/Chrome trace_event
JSON, and bench.py attaches a dump path to every run's detail.

`SPGEMM_TPU_OBS_TRACE=0` disables span emission entirely (timers still
accumulate totals) -- the whole-engine A/B knob that proves the
recorder's overhead, like every other engine knob.

jax-free and lock-disciplined by construction: the ring is guarded by a
lock the THR lint rule enforces; the per-thread open-span stack and tag
map live in a threading.local (thread-affine by definition, nothing to
guard).  Parenting is lexical per thread: the span open on a thread when
another begins is its parent, so a numeric_dispatch span nests under the
serve_execute span of the job that dispatched it.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque

from spgemm_tpu.utils import knobs

# one monotonic origin per process: every span timestamp is microseconds
# since this module loaded, so spans from any thread share one timeline
_BASE = time.perf_counter()


def enabled() -> bool:
    """SPGEMM_TPU_OBS_TRACE=0|1 (default 1): span emission on/off.  Read
    lazily per span, like every knob -- tests and A/B harnesses flip it
    mid-process."""
    return knobs.get("SPGEMM_TPU_OBS_TRACE")


def ring_cap() -> int:
    """SPGEMM_TPU_OBS_RING_CAP (default 4096): spans retained."""
    return knobs.get("SPGEMM_TPU_OBS_RING_CAP")


class FlightRecorder:
    """Bounded in-process span ring + per-thread span stacks and tags.

    begin()/end() bracket a phase (the PhaseTimers integration); point()
    records an externally-timed span ending now (timers.record); instant()
    records a zero-duration marker (degrade/reap transitions).  All are
    no-ops while the knob is off -- a disabled recorder costs one env read
    per phase."""

    def __init__(self):
        self._spans: deque = deque()  # spgemm-lint: guarded-by(_lock)
        self._dropped = 0             # spgemm-lint: guarded-by(_lock)
        self._emitted = 0             # spgemm-lint: guarded-by(_lock)
        self._next_id = 1             # spgemm-lint: guarded-by(_lock)
        self._lock = threading.Lock()
        self._tls = threading.local()  # open-span stack + tags, thread-affine

    # ------------------------------------------------------ thread state --
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_tags(self) -> dict:
        """The emitting thread's active job/trace tags (a copy)."""
        return dict(getattr(self._tls, "tags", ()) or {})

    @contextlib.contextmanager
    def tagged(self, **tags):
        """Attach tags (job_id/trace_id/...) to every span emitted by this
        thread inside the block; None values are dropped.  Nests: inner
        blocks layer over -- and on exit restore -- the outer map."""
        prev = getattr(self._tls, "tags", None)
        merged = dict(prev or {})
        merged.update({k: v for k, v in tags.items() if v is not None})
        self._tls.tags = merged
        try:
            yield
        finally:
            self._tls.tags = prev

    # --------------------------------------------------------- emission --
    def _new_id(self) -> int:
        """Span ids are assigned at OPEN time: a child span commits before
        its still-open parent, so the parent id the child records must be
        the id the parent will eventually commit under."""
        with self._lock:
            span_id = self._next_id
            self._next_id += 1
        return span_id

    def begin(self, name: str):
        """Open a span on this thread; returns the token end() consumes
        (None while disabled -- end(None) is a no-op)."""
        if not enabled():
            return None
        stack = self._stack()
        parent = stack[-1][0] if stack else None
        token = (self._new_id(), name, time.perf_counter(), parent)
        stack.append(token)
        return token

    def end(self, token) -> None:
        """Close the span `token` opened and ring-commit it."""
        if token is None:
            return
        now = time.perf_counter()
        stack = self._stack()
        span_id, name, t0, parent = token
        # unwind to our own entry: a knob flip mid-phase (or an abandoned
        # begin) can leave younger entries above it on this thread's stack
        while stack:
            if stack.pop()[0] == span_id:
                break
        self._commit(span_id, name, t0, now - t0, parent, "X")

    def point(self, name: str, seconds: float) -> None:
        """A span whose endpoints the caller timed itself (timers.record):
        ends now, lasted `seconds`, parented under this thread's open
        span."""
        if not enabled():
            return
        stack = self._stack()
        parent = stack[-1][0] if stack else None
        self._commit(self._new_id(), name, time.perf_counter() - seconds,
                     seconds, parent, "X")

    def instant(self, name: str, **tags) -> None:
        """Zero-duration marker (reap/wedge/degrade transitions)."""
        if not enabled():
            return
        stack = self._stack()
        parent = stack[-1][0] if stack else None
        with self.tagged(**tags):
            self._commit(self._new_id(), name, time.perf_counter(), 0.0,
                         parent, "i")

    def _commit(self, span_id: int, name: str, t0: float, dur_s: float,
                parent, ph: str) -> None:
        thread = threading.current_thread()
        span = {
            "id": span_id,
            "name": name,
            "ph": ph,
            "ts": round((t0 - _BASE) * 1e6, 3),     # us on the shared origin
            "dur": round(max(dur_s, 0.0) * 1e6, 3),  # us
            "tid": thread.ident,
            "thread": thread.name,
            "parent": parent,
        }
        tags = self.current_tags()
        if tags:
            span["tags"] = tags
        if ph == "X":
            # scrape-side phase latency: every completed span feeds the
            # per-phase histogram (obs/profile; already master-knob-gated
            # -- _commit is only reached while emission is enabled)
            from spgemm_tpu.obs import profile  # noqa: PLC0415
            profile.observe_phase(name, dur_s)
        cap = ring_cap()
        with self._lock:
            self._spans.append(span)
            self._emitted += 1
            while len(self._spans) > cap:
                self._spans.popleft()
                self._dropped += 1

    # -------------------------------------------------------- inspection --
    def snapshot(self) -> list[dict]:
        """Retained spans, oldest first (copies -- safe to serialize)."""
        with self._lock:
            return [dict(s) for s in self._spans]

    def stats(self) -> dict:
        """Ring health for metrics: retained/emitted/dropped + config."""
        with self._lock:
            retained = len(self._spans)
            emitted = self._emitted
            dropped = self._dropped
        return {"spans": retained, "emitted": emitted, "dropped": dropped,
                "capacity": ring_cap(), "enabled": enabled()}

    def clear(self) -> None:
        """Drop every span and zero the counters (tests, harnesses)."""
        with self._lock:
            self._spans.clear()
            self._dropped = 0
            self._emitted = 0


# The process-wide recorder: every PhaseTimers instance emits here, the
# daemon snapshots it, the CLI dumps it.
RECORDER = FlightRecorder()


# ------------------------------------------------------- Perfetto export --
# metadata event carrying the process's wall-clock origin: the span `ts`
# axis is microseconds since this module's monotonic _BASE, which differs
# per process -- the anchor lets the merge tool put per-process dumps on
# one shared timeline (clock skew across hosts notwithstanding)
CLOCK_ORIGIN_META = "spgemm_clock_origin"


def wall_origin_us() -> float:
    """The wall-clock time (epoch microseconds) corresponding to this
    process's span-timestamp origin (_BASE)."""
    return (time.time() - (time.perf_counter() - _BASE)) * 1e6


def to_trace_events(spans: list[dict] | None = None,
                    process_name: str | None = None) -> list[dict]:
    """Chrome/Perfetto trace_event JSON array for the given spans (default:
    the live ring).  Complete events ('X') carry ts+dur; instants stay
    'i'; metadata events name the process and every thread in the viewer
    and anchor the timeline to wall clock (CLOCK_ORIGIN_META) so
    `cli trace-dump --merge` can stitch per-process dumps."""
    import sys  # noqa: PLC0415 -- only for the default process label

    if spans is None:
        spans = RECORDER.snapshot()
    pid = os.getpid()
    events: list[dict] = []
    named_tids: dict[int, str] = {}
    for s in spans:
        tid = s.get("tid") or 0
        if tid not in named_tids:
            named_tids[tid] = s.get("thread", f"thread-{tid}")
        args = dict(s.get("tags") or {})
        args["span_id"] = s.get("id")
        if s.get("parent") is not None:
            args["parent"] = s["parent"]
        ev = {"name": s["name"], "cat": "spgemm", "ph": s.get("ph", "X"),
              "ts": s["ts"], "pid": pid, "tid": tid, "args": args}
        if ev["ph"] == "X":
            ev["dur"] = s.get("dur", 0.0)
        events.append(ev)
    if process_name is None:
        process_name = (os.path.basename(sys.argv[0] or "python")
                        + f":{pid}")
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": process_name}},
            {"name": CLOCK_ORIGIN_META, "ph": "M", "pid": pid, "tid": 0,
             "args": {"wall_origin_us": round(wall_origin_us(), 3)}}]
    meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
              "args": {"name": name}}
             for tid, name in sorted(named_tids.items())]
    return meta + events


def dump_json(path: str, spans: list[dict] | None = None,
              process_name: str | None = None) -> str:
    """Write the trace_event array to `path` (parent dirs created) and
    return the path -- the one serializer behind `cli trace-dump`, the
    daemon's postmortem auto-dump, and bench.py's detail.trace_path."""
    events = to_trace_events(spans, process_name=process_name)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(events, f, separators=(",", ":"))
    os.replace(tmp, path)  # a reader never sees a torn dump
    return path


# ------------------------------------------------------- trace stitching --
def filter_trace(events: list[dict], trace_id: str) -> list[dict]:
    """The events whose `trace_id` tag equals `trace_id`, plus the
    metadata tracks (process/thread names, clock anchors) still backing
    a surviving event -- an `slo_burn` trace id resolves to exactly one
    flame view, not a ring's worth of unrelated jobs."""
    keep = [ev for ev in events
            if ev.get("ph") == "M"
            or (ev.get("args") or {}).get("trace_id") == trace_id]
    live = {(ev.get("pid"), ev.get("tid")) for ev in keep
            if ev.get("ph") != "M"}
    live_pids = {pid for pid, _tid in live}
    out = []
    for ev in keep:
        if ev.get("ph") == "M":
            if ev.get("pid") not in live_pids:
                continue
            if ev.get("name") == "thread_name" \
                    and (ev.get("pid"), ev.get("tid")) not in live:
                continue
        out.append(ev)
    return out


def merge_trace_files(paths: list[str],
                      trace_id: str | None = None) -> list[dict]:
    """Stitch per-process/per-rank trace dumps into ONE Perfetto
    trace_event array (`cli trace-dump --merge <dir>`):

      * every file keeps its own process track -- colliding pids (two
        dumps of one restarted daemon) are remapped to fresh ids, and a
        file without a `process_name` metadata event gets one from its
        filename, so the viewer shows distinct labeled tracks;
      * timelines align on each dump's CLOCK_ORIGIN_META wall-clock
        anchor (span `ts` axes are per-process monotonic origins):
        every file's events shift onto the earliest anchor's axis; a
        legacy dump without an anchor merges unshifted;
      * `trace_id` filters to one trace's events (filter_trace), so an
        slo_burn event's trace context opens as a single flame view
        from client submit to slice fold.

    Raises ValueError on a file that is not a trace_event array."""
    loaded: list[tuple[str, list[dict], float | None]] = []
    for path in paths:
        with open(path, encoding="utf-8") as f:
            events = json.load(f)
        if not isinstance(events, list):
            raise ValueError(f"{path} is not a trace_event JSON array")
        origin = None
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == CLOCK_ORIGIN_META:
                anchor = (ev.get("args") or {}).get("wall_origin_us")
                if isinstance(anchor, (int, float)):
                    origin = float(anchor)
                break
        loaded.append((path, events, origin))
    anchors = [origin for _, _, origin in loaded if origin is not None]
    base = min(anchors) if anchors else 0.0
    claimed: dict[int, str] = {}  # merged pid -> owning file
    merged_meta: list[dict] = []
    merged_events: list[dict] = []
    for path, events, origin in loaded:
        shift = (origin - base) if origin is not None else 0.0
        remap: dict[int, int] = {}
        used: set[int] = set()  # merged pids this file already occupies
        named: set[int] = set()
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == CLOCK_ORIGIN_META:
                continue  # internal anchor: consumed by the shift above
            pid = ev.get("pid", 0)
            new = remap.get(pid)
            if new is None:
                new = pid
                while claimed.get(new, path) != path or new in used:
                    new += 1  # collision: walk to a fresh process id
                claimed[new] = path
                used.add(new)
                remap[pid] = new
            ev = dict(ev)
            ev["pid"] = new
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + shift, 3)
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    named.add(new)
                merged_meta.append(ev)
            else:
                merged_events.append(ev)
        label = os.path.basename(path)
        for suffix in (".trace.json", ".json"):
            if label.endswith(suffix):
                label = label[: -len(suffix)]
                break
        for pid in set(remap.values()) - named:
            merged_meta.append({"name": "process_name", "ph": "M",
                                "pid": pid, "tid": 0,
                                "args": {"name": label}})
    merged_events.sort(key=lambda ev: ev.get("ts", 0.0))
    merged = merged_meta + merged_events
    if trace_id is not None:
        merged = filter_trace(merged, trace_id)
    return merged
