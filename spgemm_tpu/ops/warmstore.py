"""Persistent warm start: the plan cache and delta store, on disk.

The engine's amortization story (JITSPMM / KokkosKernels symbolic reuse,
PAPERS.md) died at process death: a restarted spgemmd paid cold import +
cold jit + a full symbolic plan per structure + one full recompute per
delta structure.  This module is the disk tier under the two in-memory
stores:

  * an EXACT SpgemmPlan (ops/plancache entry) serializes to one npz next
    to the job journal -- plans are content-fingerprinted over operand
    coords + plan params + the jit-static knob vector, so the fingerprint
    IS the file key and a hit can never straddle a config change;
  * a delta-store entry (ops/delta: retained previous result + operand
    provenance) serializes with its result planes fetched to host, so an
    evolving-input client's first post-restart submit diffs against the
    retained result instead of paying a counted full fallback;
  * spgemmd additionally points JAX's persistent compilation cache at a
    subdir of the same store (configure_compilation_cache), so re-jit of
    unchanged executables is a disk hit.

Loading is LAZY: startup only counts files (the `warm_load` event); an
entry deserializes on its first fingerprint match, inside the engine's
`warm_load` phase.  Every write is atomic (tmp + os.replace), versioned
(symbolic.PLAN_CODEC_VERSION + the store schema below), and bounded
(SPGEMM_TPU_WARM_MAX_MB, oldest entries pruned after flush).

Failure policy -- the checkpoint.latest_pass contract, applied here: any
corrupt, truncated, version-skewed, or knob-vector-mismatched entry is a
loudly counted cold fallback (`warm_corrupt` counter + a
`warm_corrupt_skipped` event naming the file), NEVER a crash and never
wrong bits -- persistence only short-circuits planning and retention,
the fold order is baked into the persisted pa/pb gathers themselves.

Concurrency: one flock per warm dir.  A process that cannot take it
(a second daemon pointed at a live daemon's dir) runs COLD with a
`warm_disabled` event instead of corrupting the owner's entries.

jax-free by design: imported by the CLI (`warm` subcommand), the daemon
startup path, and the metrics scrape -- none may touch a backend.  The
delta result planes cross the device boundary only via the caller's
arrays (np.asarray on save forces the D2H; rehydration's H2D lives in
ops/spgemm, the module that owns device arrays).

Knobs (central registry, utils/knobs.py): SPGEMM_TPU_WARM (0|1, default
1), SPGEMM_TPU_WARM_DIR (unset: daemon uses <socket>.warm/),
SPGEMM_TPU_WARM_MAX_MB (default 256).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading

import numpy as np

from spgemm_tpu.utils import knobs

log = logging.getLogger("spgemm_tpu.warmstore")

# On-disk envelope schema.  Bump on any envelope change; entry payloads
# additionally carry their own codec version (symbolic.PLAN_CODEC_VERSION
# inside plan payloads) -- either mismatch is a counted cold fallback.
SCHEMA_VERSION = 1

_LOCK = threading.Lock()
_DIR: str | None = None          # spgemm-lint: guarded-by(_LOCK)
_DISABLED: str | None = None     # spgemm-lint: guarded-by(_LOCK)
_LOCK_FILE = None                # spgemm-lint: guarded-by(_LOCK)
# delta entries already persisted, key -> version (re-flushing an
# unchanged entry would re-pay its result's D2H every terminal event)
_SAVED_DELTA: dict = {}          # spgemm-lint: guarded-by(_LOCK)
_STATS = {"plan_hits": 0, "plan_misses": 0, "delta_hits": 0,
          "delta_misses": 0, "corrupt": 0, "saved_plans": 0,
          "saved_deltas": 0, "saved_tunes": 0,
          "pruned": 0}  # spgemm-lint: guarded-by(_LOCK)


def enabled() -> bool:
    """SPGEMM_TPU_WARM=0|1 (default 1) -- re-read per call, so the
    whole-engine A/B is one env flip even mid-process."""
    return knobs.get("SPGEMM_TPU_WARM")


def budget_bytes() -> int:
    """SPGEMM_TPU_WARM_MAX_MB (default 256) in bytes."""
    return knobs.get("SPGEMM_TPU_WARM_MAX_MB") * (1 << 20)


def _knob_sig() -> str:
    """The jit-static knob vector as one comparable string -- stored in
    every entry and validated on load (the fingerprint already bakes the
    vector in, so this only fires on a tampered/hand-copied file -- which
    is exactly when it must)."""
    return repr(knobs.jit_static_vector())


# ---------------------------------------------------------- configuration --
def configure(path: str | None = None) -> bool:
    """Bind the store to a directory and take its flock.

    Explicit SPGEMM_TPU_WARM_DIR wins over `path` (so a fleet deployment
    can share one dir across sockets); with neither, the store stays
    inactive.  Returns True when the store is usable.  Lock contention
    (another live process owns the dir) disables the store for this
    process -- a counted, evented cold start, never a corrupted peer."""
    global _DIR, _DISABLED, _LOCK_FILE
    if not enabled():
        return False
    directory = knobs.get("SPGEMM_TPU_WARM_DIR") or path
    if not directory:
        return False
    from spgemm_tpu.obs import events  # noqa: PLC0415
    with _LOCK:
        if _DIR == directory and _LOCK_FILE is not None:
            return True  # already configured on this dir
        _release_locked()
        try:
            os.makedirs(directory, exist_ok=True)
            lock_path = os.path.join(directory, "lock")
            fh = open(lock_path, "a+")
        except OSError as e:
            _DISABLED = f"warm dir unusable: {e!r}"
            log.warning("warm store disabled: %s", _DISABLED)
            return False
        import fcntl  # noqa: PLC0415 -- posix-only, like the daemon's unix socket
        # brief retry: `cli warm --stat/--clear` probes the lock for a
        # few microseconds, and losing THAT race must not cold-start a
        # daemon for its whole lifetime; a dir genuinely held by a live
        # process still fails fast (~a quarter second).  The scan probe
        # is the ONLY transient flock taker: the recovery re-probe path
        # (serve/daemon._recover_probe) never touches the warm dir --
        # the probe is a subprocess matmul and the replacement executor
        # reuses the already-bound store -- so this window covers every
        # race there is (tests/test_chaos.py pins both directions)
        locked = False
        for attempt in range(6):
            try:
                # spgemm-lint: blk-ok(LOCK_NB flock never blocks; bind-time only, before any serving thread contends for _LOCK)
                fcntl.flock(fh.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
                locked = True
                break
            except OSError:
                if attempt < 5:
                    import time  # noqa: PLC0415
                    # spgemm-lint: blk-ok(bounded 0.3s total bind-time retry; configure runs before the daemon serves, so no thread contends for _LOCK yet)
                    time.sleep(0.05)
        if not locked:
            fh.close()
            _DISABLED = (f"warm dir {directory} is locked by another "
                         "live process; running cold")
            log.warning("warm store disabled: %s", _DISABLED)
            events.emit("warm_disabled", dir=directory,
                        reason="lock_contention")
            return False
        _DIR, _DISABLED, _LOCK_FILE = directory, None, fh
        plans, deltas, tunes, size = _scan_locked()
    _fence_delta_versions(directory)
    log.info("warm store at %s: %d plans, %d delta entries, %d tuned "
             "overrides, %d bytes", directory, plans, deltas, tunes, size)
    events.emit("warm_load", dir=directory, plans=plans, deltas=deltas,
                tunes=tunes, bytes=size)
    return True


def _fence_delta_versions(directory: str) -> None:
    """Advance ops/delta's monotonic version source past every persisted
    entry's version, BEFORE any multiply can mint a fresh one.  Without
    this, a fresh process could re-issue a version number some surviving
    on-disk tag still references -- a rehydrated consumer would read the
    fresh producer's tag as already-consumed and splice stale rows
    (wrong bits).  A consumer's tag references are always OLDER than its
    own version (minted at commit, after the consumed tag existed), so
    the on-disk maximum bounds every reference; reading one int64 per
    entry keeps startup lazy (no payload deserializes).  An unreadable
    entry is skipped here -- its load will count it corrupt."""
    from spgemm_tpu.ops import delta  # noqa: PLC0415
    high = 0
    try:
        names = [n for n in os.listdir(directory)
                 if n.startswith("delta-") and n.endswith(".npz")]
    except OSError:
        return
    for name in names:
        try:
            with np.load(os.path.join(directory, name),
                         allow_pickle=False) as z:
                high = max(high, int(z["version"]))
        except Exception:  # noqa: BLE001 -- corrupt entry: counted at load, not here
            continue
    if high:
        delta.fence_version(high)


def _release_locked() -> None:
    global _DIR, _DISABLED, _LOCK_FILE
    if _LOCK_FILE is not None:
        try:
            _LOCK_FILE.close()  # closing drops the flock
        except OSError:
            pass
    _DIR = _DISABLED = _LOCK_FILE = None
    _SAVED_DELTA.clear()


def release() -> None:
    """Drop the flock and unbind (daemon stop, harness handoff to a
    child process).  On-disk entries stay."""
    with _LOCK:
        _release_locked()


def reset() -> None:
    """Tests/A-B harnesses: release + zero the counters."""
    with _LOCK:
        _release_locked()
        for k in _STATS:
            _STATS[k] = 0


def _ensure_configured() -> None:
    """Auto-bind from SPGEMM_TPU_WARM_DIR on first use (run-once CLI and
    bench children need no explicit configure call)."""
    with _LOCK:
        ready = _LOCK_FILE is not None or _DISABLED is not None
    if not ready and knobs.get("SPGEMM_TPU_WARM_DIR"):
        configure()


def active() -> bool:
    """True when persistence is on, a dir is bound, and this process
    holds its flock."""
    if not enabled():
        return False
    _ensure_configured()
    with _LOCK:
        return _LOCK_FILE is not None


def directory() -> str | None:
    with _LOCK:
        return _DIR


def disabled_reason() -> str | None:
    with _LOCK:
        return _DISABLED


# -------------------------------------------------------------- file layer --
def _plan_path(d: str, fingerprint: str) -> str:
    return os.path.join(d, f"plan-{fingerprint}.npz")


def _delta_path(d: str, key: str) -> str:
    # the delta key embeds device-placement brackets (ops/spgemm._delta_key)
    # -- hash it into a filename; the full key is stored inside and checked
    digest = hashlib.sha256(key.encode()).hexdigest()[:40]
    return os.path.join(d, f"delta-{digest}.npz")


def _tune_path(d: str, class_key: str) -> str:
    # the tune class key embeds the device kind (may carry spaces/slashes)
    # -- hash it; the full key is stored inside and checked like deltas
    digest = hashlib.sha256(class_key.encode()).hexdigest()[:40]
    return os.path.join(d, f"tune-{digest}.npz")


def _atomic_savez(path: str, payload: dict) -> None:
    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
    os.replace(tmp, path)


def _scan_locked() -> tuple[int, int, int, int]:
    """(plan files, delta files, tune files, total npz bytes) of the
    bound dir."""
    plans = deltas = tunes = size = 0
    if _DIR is None:
        return 0, 0, 0, 0
    try:
        names = os.listdir(_DIR)
    except OSError:
        return 0, 0, 0, 0
    for name in names:
        if not name.endswith(".npz"):
            continue
        try:
            size += os.path.getsize(os.path.join(_DIR, name))
        except OSError:
            continue  # pruned/replaced under us: not worth a stale count
        if name.startswith("plan-"):
            plans += 1
        elif name.startswith("delta-"):
            deltas += 1
        elif name.startswith("tune-"):
            tunes += 1
    return plans, deltas, tunes, size


def _note_corrupt(path: str, reason: str) -> None:
    """One corrupt/skewed/mismatched entry skipped: count it, event it,
    and UNLINK it so the slot self-heals -- the caller proceeds cold,
    re-derives the entry, and the next flush re-persists it (a corrupt
    file left in place would block save_plan's exists-check idempotency
    and make this fingerprint cold on every future restart)."""
    from spgemm_tpu.obs import events  # noqa: PLC0415
    from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415
    with _LOCK:
        _STATS["corrupt"] += 1
    ENGINE.incr("warm_corrupt")
    try:
        os.unlink(path)
    except OSError:
        pass  # already gone / unwritable dir: the count still stands
    log.warning("warm entry %s skipped (%s); removed, cold fallback",
                path, reason)
    events.emit("warm_corrupt_skipped", path=os.path.basename(path),
                reason=reason)


def _check_envelope(z, path: str, kind: str, ident: str,
                    sig: str | None = None) -> bool:
    """Validate one loaded npz's envelope: schema version, entry kind,
    identity (fingerprint/key) and the jit-static knob vector.  False =
    counted cold fallback.

    `sig` overrides the expected knob signature: the tune tier validates
    against the BASE vector (knobs.base_jit_static_vector -- env >
    default only), because loading a tuned override is itself what
    changes the overlaid vector; the plan/delta tiers use the live
    vector (their fingerprints bake it in)."""
    from spgemm_tpu.utils import failpoints  # noqa: PLC0415
    if failpoints.check("warm.load"):
        _note_corrupt(path, "failpoint warm.load")
        return False
    schema = int(z["schema"]) if "schema" in z.files else -1
    if schema != SCHEMA_VERSION:
        _note_corrupt(path, f"schema version {schema} != {SCHEMA_VERSION}")
        return False
    if str(z["kind"]) != kind or str(z["ident"]) != ident:
        _note_corrupt(path, "entry identity mismatch")
        return False
    if str(z["knobs"]) != (sig if sig is not None else _knob_sig()):
        _note_corrupt(path, "jit-static knob vector mismatch")
        return False
    return True


# ------------------------------------------------------------------ plans --
def save_plan(plan) -> bool:
    """Persist one EXACT fingerprinted plan (atomic, idempotent: an
    existing file for the fingerprint is left alone -- plans are immutable
    once their join landed).  False when skipped for any reason."""
    if not active() or getattr(plan, "fingerprint", None) is None:
        return False
    from spgemm_tpu.ops.symbolic import plan_to_arrays  # noqa: PLC0415
    with _LOCK:
        d = _DIR
    if d is None:
        return False
    path = _plan_path(d, plan.fingerprint)
    if os.path.exists(path):
        return False
    payload = plan_to_arrays(plan)
    if payload is None:
        return False  # deferred join: nothing worth persisting yet
    payload.update(schema=np.int64(SCHEMA_VERSION), kind=np.array("plan"),
                   ident=np.array(plan.fingerprint),
                   knobs=np.array(_knob_sig()))
    try:
        _atomic_savez(path, payload)
    except OSError as e:
        log.warning("warm plan save failed (%r); continuing", e)
        return False
    with _LOCK:
        _STATS["saved_plans"] += 1
    return True


def load_plan(fingerprint: str):
    """The persisted plan for a fingerprint, or None (miss or counted
    corrupt fallback).  Runs inside the engine's `warm_load` phase with
    the hit/miss counters bumped here, so per-job attribution rides the
    calling thread like every other engine phase."""
    if not active():
        return None
    from spgemm_tpu.ops.symbolic import plan_from_arrays  # noqa: PLC0415
    from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415
    with _LOCK:
        d = _DIR
    if d is None:
        return None
    path = _plan_path(d, fingerprint)
    with ENGINE.phase("warm_load"):
        if not os.path.exists(path):
            with _LOCK:
                _STATS["plan_misses"] += 1
            ENGINE.incr("warm_misses")
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                if not _check_envelope(z, path, "plan", fingerprint):
                    return None
                plan = plan_from_arrays(z, fingerprint=fingerprint)
        except Exception as e:  # noqa: BLE001 -- any unreadable entry is a counted cold fallback
            _note_corrupt(path, repr(e))
            return None
    with _LOCK:
        _STATS["plan_hits"] += 1
    ENGINE.incr("warm_hits")
    return plan


# ------------------------------------------------------------ delta entries --
def _encode_src(prefix: str, src: tuple, payload: dict) -> bool:
    """One operand provenance tuple into the payload; False = not
    persistable (opaque provenance cannot be diffed after restart)."""
    if src[0] == "digest":
        payload[f"{prefix}_kind"] = np.array("digest")
        payload[f"{prefix}_rows"] = np.asarray(src[1], np.int64)
        payload[f"{prefix}_digs"] = np.asarray(src[2], dtype="S32")
        return True
    if src[0] == "tag":
        payload[f"{prefix}_kind"] = np.array("tag")
        payload[f"{prefix}_tag_key"] = np.array(src[1])
        payload[f"{prefix}_tag_version"] = np.int64(src[2])
        return True
    return False


def _decode_src(prefix: str, z) -> tuple:
    kind = str(z[f"{prefix}_kind"])
    if kind == "digest":
        return ("digest", np.asarray(z[f"{prefix}_rows"], np.int64),
                np.asarray(z[f"{prefix}_digs"], dtype="S32"))
    if kind == "tag":
        return ("tag", str(z[f"{prefix}_tag_key"]),
                int(z[f"{prefix}_tag_version"]))
    raise ValueError(f"unknown provenance kind {kind!r}")


_VAL_BOUND_NONE = (1 << 64) - 1  # sentinel: result.val_bound was None


def save_delta(key: str, entry) -> bool:
    """Persist one delta-store entry: provenance + the retained result's
    planes fetched to host (np.asarray -- the one D2H of the flush; runs
    off the serving critical path, after the job's terminal event)."""
    if not active():
        return False
    res = entry.result
    try:
        hi = np.asarray(res.hi)
        lo = np.asarray(res.lo)
        meta = np.array([res.rows, res.cols, res.k], np.int64)
        coords = np.asarray(res.coords, np.int64)
        vb = res.val_bound
    except AttributeError:
        return False  # a result type without planes: nothing to retain
    payload = {
        "schema": np.int64(SCHEMA_VERSION), "kind": np.array("delta"),
        "ident": np.array(key), "knobs": np.array(_knob_sig()),
        "version": np.int64(entry.version),
        "out_rows": np.int64(entry.out_rows),
        "res_meta": meta, "res_coords": coords,
        "res_hi": hi, "res_lo": lo,
        "res_val_bound": np.uint64(_VAL_BOUND_NONE if vb is None
                                   else min(vb, _VAL_BOUND_NONE - 1)),
    }
    if not (_encode_src("a", entry.a_src, payload)
            and _encode_src("b", entry.b_src, payload)):
        return False
    with _LOCK:
        d = _DIR
    if d is None:
        return False
    path = _delta_path(d, key)
    try:
        _atomic_savez(path, payload)
    except OSError as e:
        log.warning("warm delta save failed (%r); continuing", e)
        return False
    with _LOCK:
        _STATS["saved_deltas"] += 1
        _SAVED_DELTA[key] = entry.version
    return True


def load_delta(key: str) -> dict | None:
    """The persisted delta entry for a key as HOST data, or None (miss or
    counted corrupt fallback): {"version", "out_rows", "a_src", "b_src",
    "result": {rows, cols, k, coords, hi, lo, val_bound}}.  The caller
    (ops/spgemm) re-uploads the planes and seeds ops/delta -- this module
    stays jax-free."""
    if not active():
        return None
    from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415
    with _LOCK:
        d = _DIR
    if d is None:
        return None
    path = _delta_path(d, key)
    with ENGINE.phase("warm_load"):
        if not os.path.exists(path):
            with _LOCK:
                _STATS["delta_misses"] += 1
            ENGINE.incr("warm_misses")
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                if not _check_envelope(z, path, "delta", key):
                    return None
                rows, cols, k = (int(v) for v in z["res_meta"])
                vb = int(z["res_val_bound"])
                out = {
                    "version": int(z["version"]),
                    "out_rows": int(z["out_rows"]),
                    "a_src": _decode_src("a", z),
                    "b_src": _decode_src("b", z),
                    "result": {
                        "rows": rows, "cols": cols, "k": k,
                        "coords": np.asarray(z["res_coords"], np.int64),
                        "hi": np.asarray(z["res_hi"], np.uint32),
                        "lo": np.asarray(z["res_lo"], np.uint32),
                        "val_bound": (None if vb == _VAL_BOUND_NONE
                                      else vb),
                    },
                }
        except Exception as e:  # noqa: BLE001 -- any unreadable entry is a counted cold fallback
            _note_corrupt(path, repr(e))
            return None
    with _LOCK:
        _STATS["delta_hits"] += 1
        _SAVED_DELTA[key] = out["version"]  # what disk holds = what we loaded
    ENGINE.incr("warm_hits")
    return out


# ------------------------------------------------------------------ flush --
# ---------------------------------------------------------------- tunes --
def save_tune(class_key: str, record: dict) -> bool:
    """Persist one structure class's tuned-override record (tune/tuner
    promotion, canary settle, revert, estimator adaptation -- the record
    is small JSON, so eager per-event persistence is cheap and flush()
    never needs to walk tuner state).  Atomic replace: unlike plans,
    tune records MUTATE (canary -> live -> reverted), so the newest
    write wins.  Validated on load against the BASE jit-static vector
    (env > default): an env-exported knob that changed across restarts
    invalidates every tuned decision made on top of the old base."""
    if not active():
        return False
    import json  # noqa: PLC0415
    with _LOCK:
        d = _DIR
    if d is None:
        return False
    payload = {
        "schema": np.int64(SCHEMA_VERSION),
        "kind": "tune",
        "ident": class_key,
        "knobs": repr(knobs.base_jit_static_vector()),
        "payload": json.dumps(record, sort_keys=True),
    }
    try:
        _atomic_savez(_tune_path(d, class_key), payload)
    except OSError as e:
        log.warning("tune record for %s not persisted (%r)", class_key, e)
        return False
    with _LOCK:
        _STATS["saved_tunes"] += 1
    return True


def load_tunes() -> dict[str, dict]:
    """Every persisted tuned-override record in the bound dir, keyed by
    class key (daemon start -> tune.TUNER.load).  A corrupt, schema-
    skewed, or base-knob-vector-mismatched entry is a counted cold
    fallback (_note_corrupt: the class simply re-trials)."""
    if not active():
        return {}
    import json  # noqa: PLC0415
    with _LOCK:
        d = _DIR
    if d is None:
        return {}
    sig = repr(knobs.base_jit_static_vector())
    out: dict[str, dict] = {}
    try:
        names = sorted(n for n in os.listdir(d)
                       if n.startswith("tune-") and n.endswith(".npz"))
    except OSError:
        return {}
    for name in names:
        path = os.path.join(d, name)
        try:
            with np.load(path, allow_pickle=False) as z:
                ident = str(z["ident"]) if "ident" in z.files else ""
                if not _check_envelope(z, path, "tune", ident, sig=sig):
                    continue
                record = json.loads(str(z["payload"]))
        except Exception as e:  # noqa: BLE001 -- any unreadable entry is the counted cold fallback, never a daemon-startup crash
            _note_corrupt(path, f"unreadable: {e!r}")
            continue
        if isinstance(record, dict):
            out[ident] = record
    return out


def scan_tunes(path: str) -> dict[str, dict]:
    """Read-only view of an ARBITRARY dir's tune tier (cli tune --status
    inspects a live daemon's dir): no binding, no flock, and -- unlike
    load_tunes -- no unlinking or corrupt-counting, because the dir may
    be owned by a running daemon.  Unreadable entries are skipped."""
    import json  # noqa: PLC0415
    out: dict[str, dict] = {}
    if not os.path.isdir(path):
        return out
    for name in sorted(os.listdir(path)):
        if not (name.startswith("tune-") and name.endswith(".npz")):
            continue
        try:
            with np.load(os.path.join(path, name),
                         allow_pickle=False) as z:
                if str(z["kind"]) != "tune":
                    continue
                record = json.loads(str(z["payload"]))
                ident = str(z["ident"])
        except Exception:  # noqa: BLE001 -- read-only probe of a possibly-live dir: skip, never touch
            continue
        if isinstance(record, dict):
            out[ident] = record
    return out


def flush() -> dict:
    """Persist every in-memory entry not yet on disk, then prune to the
    byte budget.  Called by spgemmd after each terminal job event and at
    shutdown; cheap when nothing changed (plan files are checked by
    existence, delta entries by version).  Never raises."""
    counts = {"plans": 0, "deltas": 0, "pruned": 0}
    try:
        if not active():
            return counts
        from spgemm_tpu.obs import events  # noqa: PLC0415
        from spgemm_tpu.ops import delta, plancache  # noqa: PLC0415
        from spgemm_tpu.utils import failpoints  # noqa: PLC0415
        from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415
        with ENGINE.phase("warm_flush"):
            failpoints.check("warm.flush")
            for _, plan in plancache.entries():
                if save_plan(plan):
                    counts["plans"] += 1
            for key, entry in delta.entries():
                with _LOCK:
                    unchanged = _SAVED_DELTA.get(key) == entry.version
                if not unchanged and save_delta(key, entry):
                    counts["deltas"] += 1
            counts["pruned"] = _prune_budget()
        if counts["plans"] or counts["deltas"] or counts["pruned"]:
            events.emit("warm_flush", **counts)
    except Exception as e:  # noqa: BLE001 -- persistence must never take down the serving path (the spgemmd executor calls this bare)
        log.warning("warm flush failed midway (%r); store left partial "
                    "but every entry is self-validating", e)
    return counts


def _prune_budget() -> int:
    """Drop oldest entries past SPGEMM_TPU_WARM_MAX_MB.  The xla/
    compilation-cache subdir manages its own size and is excluded."""
    with _LOCK:
        d = _DIR
    if d is None:
        return 0
    budget = budget_bytes()
    try:
        files = []
        for name in os.listdir(d):
            if not name.endswith(".npz"):
                continue
            path = os.path.join(d, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            files.append((st.st_mtime, st.st_size, path))
    except OSError:
        return 0
    total = sum(size for _, size, _ in files)
    pruned = 0
    for _, size, path in sorted(files):
        if total <= budget:
            break
        try:
            os.unlink(path)
        except OSError:
            continue
        total -= size
        pruned += 1
        with _LOCK:
            # a pruned delta file must be re-flushable later
            for key in list(_SAVED_DELTA):
                if _delta_path(d, key) == path:
                    del _SAVED_DELTA[key]
    if pruned:
        with _LOCK:
            _STATS["pruned"] += pruned
        log.info("warm store pruned %d entries to fit %d bytes",
                 pruned, budget)
    return pruned


# ---------------------------------------------------------- jax wiring ----
def configure_compilation_cache() -> bool:
    """Point JAX's persistent compilation cache at <dir>/xla (daemon
    startup, after the platform pin): re-jit of an executable an earlier
    daemon compiled on the same jit-static knob vector becomes a disk
    hit.  Lazy jax import -- this module stays importable jax-free; a
    jax too old for the config keys is a logged no-op."""
    with _LOCK:
        d = _DIR
    if d is None or not enabled():
        return False
    cache_dir = os.path.join(d, "xla")
    try:
        import jax  # noqa: PLC0415

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 -- cache wiring is an optimization, never a startup failure
        log.warning("persistent compilation cache not wired (%r)", e)
        return False
    log.info("jax persistent compilation cache at %s", cache_dir)
    return True


# ------------------------------------------------------------------ stats --
def stats() -> dict:
    """Live store state for `spgemm_tpu.cli warm --stat`, `cli knobs`,
    spgemmd stats, and the Prometheus scrape."""
    from spgemm_tpu.ops import delta  # noqa: PLC0415 -- shared bracket parser only
    with _LOCK:
        plans, deltas, tunes, size = _scan_locked()
        # DISTINCT delta keys this process persisted, split by the
        # device-placement bracket ops/spgemm._delta_key appends (parsed
        # by the one shared helper, delta.placement_histogram): under
        # the spgemmd device pool each slice's retained results persist
        # independently, and this is the per-slice view of that (derived
        # from the saved-key memo, so a re-flush of the same key never
        # inflates it; best-effort -- budget pruning is not subtracted)
        placements = delta.placement_histogram(_SAVED_DELTA)
        return {
            "dir": _DIR,
            "enabled": enabled(),
            "active": _LOCK_FILE is not None,
            "disabled_reason": _DISABLED,
            "plans": plans,
            "deltas": deltas,
            "tunes": tunes,
            "bytes": size,
            "budget_bytes": budget_bytes(),
            "delta_placements": placements,
            **dict(_STATS),
        }


def scan(path: str) -> dict:
    """Read-only file-level view of an ARBITRARY warm dir -- no binding,
    no persistent flock (`spgemm_tpu.cli warm --stat` inspects a live
    daemon's dir without stealing it): entry counts, bytes, and whether
    a live process currently holds the dir's lock."""
    out = {"dir": path, "exists": os.path.isdir(path), "plans": 0,
           "deltas": 0, "tunes": 0, "bytes": 0, "locked": False,
           "budget_bytes": budget_bytes()}
    if not out["exists"]:
        return out
    for name in os.listdir(path):
        if not name.endswith(".npz"):
            continue
        try:
            out["bytes"] += os.path.getsize(os.path.join(path, name))
        except OSError:
            continue
        if name.startswith("plan-"):
            out["plans"] += 1
        elif name.startswith("delta-"):
            out["deltas"] += 1
        elif name.startswith("tune-"):
            out["tunes"] += 1
    lock_path = os.path.join(path, "lock")
    if os.path.exists(lock_path):
        import fcntl  # noqa: PLC0415
        try:
            probe = open(lock_path, "a+")
        except OSError:
            return out
        try:
            fcntl.flock(probe.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            out["locked"] = True
        finally:
            probe.close()  # drops the probe lock if we took it
    return out


def clear(path: str | None = None) -> int:
    """Delete every warm entry (and the xla cache subdir) under `path`
    or the bound dir.  Refuses while another live process holds the
    dir's flock.  Returns the number of entries removed."""
    target = path
    if target is None:
        with _LOCK:
            target = _DIR
    if target is None or not os.path.isdir(target):
        return 0
    with _LOCK:
        own = _LOCK_FILE is not None and _DIR == target
    if not own:
        import fcntl  # noqa: PLC0415
        try:
            probe = open(os.path.join(target, "lock"), "a+")
        except OSError:
            probe = None
        if probe is not None:
            try:
                fcntl.flock(probe.fileno(),
                            fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                probe.close()
                raise RuntimeError(
                    f"warm dir {target} is in use by a live process; "
                    "stop it before clearing") from None
            probe.close()  # drops the probe lock
    removed = 0
    for name in os.listdir(target):
        if name.endswith(".npz"):
            try:
                os.unlink(os.path.join(target, name))
                removed += 1
            except OSError:
                pass
    xla_dir = os.path.join(target, "xla")
    if os.path.isdir(xla_dir):
        import shutil  # noqa: PLC0415
        shutil.rmtree(xla_dir, ignore_errors=True)
    with _LOCK:
        _SAVED_DELTA.clear()
    return removed


def clear_tunes(path: str) -> int:
    """Delete ONLY the tune tier's entries under `path` (`cli tune
    --clear`): the plan/delta tiers stay -- dropping a bad override must
    not also throw away the warm plans a restart depends on.  Same
    live-process refusal as clear().  Returns entries removed."""
    if not os.path.isdir(path):
        return 0
    with _LOCK:
        own = _LOCK_FILE is not None and _DIR == path
    if not own:
        import fcntl  # noqa: PLC0415
        try:
            probe = open(os.path.join(path, "lock"), "a+")
        except OSError:
            probe = None
        if probe is not None:
            try:
                fcntl.flock(probe.fileno(),
                            fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                probe.close()
                raise RuntimeError(
                    f"warm dir {path} is in use by a live process; "
                    "stop it before clearing tune overrides") from None
            probe.close()  # drops the probe lock
    removed = 0
    for name in os.listdir(path):
        if name.startswith("tune-") and name.endswith(".npz"):
            try:
                os.unlink(os.path.join(path, name))
                removed += 1
            except OSError:
                pass
    return removed


def clone(src: str, dst: str) -> dict:
    """Seed one warm dir from a peer's (`cli warm --clone SRC_DIR`;
    fleet bring-up: a new backend starts with a sibling's plans/deltas/
    tunes instead of a cold first contact for every structure the fleet
    already knows).  The SOURCE is read lock-free -- entries land via
    atomic rename, so a concurrent daemon's flush can never hand us a
    torn file, only a complete old or new one.  The DESTINATION gets
    the same live-process refusal as clear(): seeding under a running
    daemon would race its flush/prune cycle.

    Every entry is envelope-checked before it lands: unreadable npz,
    schema-version skew, or a kind/filename mismatch is a counted skip,
    never a crash -- and an entry already present at the destination is
    left alone (the local copy may be newer).  Knob-vector and identity
    checks stay with the loading daemon (_check_envelope): the cloner
    cannot know the destination's jit-static vector.  Returns
    {"copied", "skipped", "skip_reasons"}."""
    import shutil  # noqa: PLC0415
    import zipfile  # noqa: PLC0415

    if not os.path.isdir(src):
        raise RuntimeError(f"warm clone source {src} is not a directory")
    if os.path.abspath(src) == os.path.abspath(dst):
        raise RuntimeError("warm clone source and destination are the "
                           "same directory")
    if os.path.isdir(dst):
        with _LOCK:
            own = _LOCK_FILE is not None and _DIR == dst
        if not own:
            import fcntl  # noqa: PLC0415
            lock_path = os.path.join(dst, "lock")
            if os.path.exists(lock_path):
                try:
                    probe = open(lock_path, "a+")
                except OSError:
                    probe = None
                if probe is not None:
                    try:
                        fcntl.flock(probe.fileno(),
                                    fcntl.LOCK_EX | fcntl.LOCK_NB)
                    except OSError:
                        probe.close()
                        raise RuntimeError(
                            f"warm dir {dst} is in use by a live "
                            "process; stop it before seeding") from None
                    probe.close()  # drops the probe lock
    else:
        os.makedirs(dst, exist_ok=True)
    copied = skipped = 0
    reasons: dict[str, int] = {}

    def skip(reason: str) -> None:
        nonlocal skipped
        skipped += 1
        reasons[reason] = reasons.get(reason, 0) + 1

    for name in sorted(os.listdir(src)):
        if not name.endswith(".npz") or name.endswith(".tmp.npz"):
            continue
        prefix = name.split("-", 1)[0]
        if prefix not in ("plan", "delta", "tune"):
            skip("unknown-kind")
            continue
        dst_path = os.path.join(dst, name)
        if os.path.exists(dst_path):
            skip("exists")
            continue
        src_path = os.path.join(src, name)
        try:
            with np.load(src_path, allow_pickle=False) as z:
                schema = int(z["schema"]) if "schema" in z.files else -1
                if schema != SCHEMA_VERSION:
                    skip("schema-skew")
                    continue
                if str(z["kind"]) != prefix:
                    skip("kind-mismatch")
                    continue
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            skip("unreadable")
            continue
        tmp = dst_path + ".tmp.npz"
        try:
            shutil.copyfile(src_path, tmp)
            os.replace(tmp, dst_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            skip("copy-failed")
            continue
        copied += 1
    log.info("warm clone %s -> %s: %d copied, %d skipped %s",
             src, dst, copied, skipped, reasons or "")
    return {"copied": copied, "skipped": skipped,
            "skip_reasons": reasons}
