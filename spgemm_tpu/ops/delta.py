"""Delta SpGEMM: row-granular incremental recompute for evolving inputs.

The serving scenario that actually carries heavy traffic (ROADMAP north
star) is repeated chain submits where one operand changes a FEW tiles
between jobs -- graph updates.  The structure-keyed plan cache
(ops/plancache, KokkosKernels-style symbolic reuse) already skips the
planner on such repeats, but the NUMERIC phase still re-folded every
output row from scratch.  This module closes that gap: the plan-cache
content fingerprint, factored down to per-tile-row granularity
(`row_digests`, hashing through the same `plancache.hash_update` step the
whole-structure fingerprint uses), identifies WHICH input tile-rows
changed, the cached exact join's pair lists identify which output
tile-rows those can reach (`diff` -> reachability), and ops/spgemm then
re-executes only the dirty output-row subset (a row-sliced sub-plan
through the round-batched dispatch) and splices it into the retained
previous result.

Bit-exactness is by construction: the wrap-then-mod fold order
(SURVEY.md 2.9) is a per-output-row property -- an output key's bytes are
a pure function of the tiles its pair list touches, in j-ascending order.
Untouched rows therefore keep their exact bytes, and dirty rows re-fold
IN FULL with the exact same per-key pair lists the full plan would use
(ops/symbolic.slice_join copies them whole).  `SPGEMM_TPU_DELTA=0|1`
(default 1) is the whole-engine A/B: bit-identical either way, pinned by
tests/test_delta.py and the hypothesis property test.

Dirty-set provenance, per operand of a retained multiply:

  * host-reachable tiles ("digest" source): per-tile-row sha256 content
    digests, diffed against the previous submit's -- the LEAF operands of
    a chain (the files a job re-reads every submit);
  * a tagged partial ("tag" source): a multiply this module already
    serves tags its result with (entry key, version, dirty output rows),
    so the NEXT multiply in the chain consumes dirtiness analytically --
    no D2H, no hashing -- as long as the version lineage matches;
  * anything else ("opaque"): no way to prove what changed.

ANY ambiguity -- first contact, changed structure (a different
fingerprint never reaches the same entry), version lineage mismatch, an
evicted entry, an opaque operand -- falls back LOUDLY to the full path
(`delta_full_fallbacks` counter) and re-seeds the entry so the next
same-structure multiply can go incremental.

Host-only and jax-free: the retained result and the per-entry state are
opaque objects here (ops/spgemm owns the device arrays and the splice);
digesting runs on the chain plan-ahead worker when one exists
(`stash_digests` -- the diff's hash cost overlaps device execution), and
the module is in the numeric-lint FLD scope like the rest of the planner.

Knobs (central registry, utils/knobs.py):
  SPGEMM_TPU_DELTA        0|1 (default 1) -- 1 = same-structure repeats
    recompute only reached output rows; 0 = always full recompute.
  SPGEMM_TPU_DELTA_RETAIN int >= 1 (default 16) -- retained entries
    (LRU); each pins one previous result's device planes, so the cap
    bounds retention memory on the serving device.

Live stats (`stats()`) ride next to the plan-cache/estimator rows in
`spgemm_tpu.cli knobs [--json]` and in spgemmd `stats`; the engine
mirrors the per-multiply accounting into the ENGINE registry
(`delta_rows_recomputed`/`delta_rows_total`/`delta_full_fallbacks`
counters, `delta_diff`/`delta_splice` phases).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from spgemm_tpu.ops import plancache
from spgemm_tpu.utils import knobs

_LOCK = threading.Lock()
_STORE: "OrderedDict[str, DeltaEntry]" = OrderedDict()  # spgemm-lint: guarded-by(_LOCK)
_STATS = {"hits": 0, "full_fallbacks": 0, "evictions": 0,
          "rows_recomputed": 0, "rows_total": 0}  # spgemm-lint: guarded-by(_LOCK)
# per-reason fallback split (ops/spgemm passes the reason it diagnosed:
# "no_entry" = first contact or store eviction, "provenance_mismatch" =
# a lineage the store could not prove) -- the event log carries the same
# strings, so a drifting fallback mix is attributable from either surface
_FALLBACK_REASONS: dict = {}  # spgemm-lint: guarded-by(_LOCK)
# Monotonic tag-version source, process-wide and NEVER reset (clear()
# included): per-entry version counters would repeat after a store
# eviction re-seeded an entry at version 1, and a consumer still holding
# provenance for the OLD version 1 would then read an empty dirty set
# from a tag that actually describes different bytes.  Unique-forever
# versions make any lineage gap a (counted, correct) full fallback.
_VERSION = 0  # spgemm-lint: guarded-by(_LOCK)


def enabled() -> bool:
    """SPGEMM_TPU_DELTA=0|1 (default 1)."""
    return knobs.get("SPGEMM_TPU_DELTA")


def placement_of(key: str) -> str:
    """The device-placement bracket of a delta-store key, or "(none)".

    THE one parser for the `|dev[...]x[...]` qualifier
    ops/spgemm._delta_key appends (the builder): every stats surface that
    splits entries per placement (stats() below, ops/warmstore's
    persisted view) goes through here, so a format change cannot desync
    one view while the other is fixed."""
    bracket = key.split("|dev", 1)
    return "dev" + bracket[1] if len(bracket) == 2 else "(none)"


def placement_histogram(keys) -> dict:
    """Count keys per placement bracket (see placement_of)."""
    out: dict[str, int] = {}
    for key in keys:
        name = placement_of(key)
        out[name] = out.get(name, 0) + 1
    return out


def capacity() -> int:
    """SPGEMM_TPU_DELTA_RETAIN (default 16): retained entries (LRU).
    Each entry pins one multiply's previous result (device arrays, via
    the opaque `result` reference) plus the operand provenance, so the
    cap bounds retained-result memory on the serving device; an evicted
    entry just means the next same-structure multiply is a counted full
    fallback.  Re-read per store so harnesses may resize mid-process."""
    return knobs.get("SPGEMM_TPU_DELTA_RETAIN")


def _next_version() -> int:
    global _VERSION
    with _LOCK:
        _VERSION += 1
        return _VERSION


@dataclass
class DeltaTag:
    """Provenance a delta-served multiply attaches to its RESULT
    (`_delta_tag` attribute): "this matrix is version `version` of entry
    `key`, and differs from version `prev_version` exactly in the output
    tile-rows `dirty_rows`".  The next multiply in the chain consumes it
    as an analytic dirty set -- partials need no host tiles and no
    hashing -- provided its stored lineage matches `prev_version`."""

    key: str
    version: int
    prev_version: int
    dirty_rows: np.ndarray


@dataclass
class DeltaEntry:
    """Retained state of one multiply, keyed by its plan fingerprint
    (structure + plan params -- ops/plancache).  Mutated only by the
    executing thread (ops/spgemm.execute's single-thread contract), so
    fields carry no lock; the store map itself is _LOCK-guarded."""

    key: str
    version: int
    a_src: tuple   # ("digest", rows, digests) | ("tag", key, version) | ("opaque",)
    b_src: tuple
    result: object  # previous result (opaque: ops/spgemm owns its type)
    out_rows: int   # distinct output tile-rows of this multiply


@dataclass
class DeltaDiff:
    """One diff's verdict: which join keys must re-fold (`key_mask`, the
    dirty output tile-rows expanded back over the key list), the dirty
    output-row ids, and the refreshed operand provenance to store on
    commit."""

    key_mask: np.ndarray
    dirty_rows: np.ndarray
    new_a_src: tuple
    new_b_src: tuple


# -------------------------------------------------------- row digesting --
def row_digests(coords: np.ndarray,
                tiles: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-tile-row structure+content digests of one operand.

    (row_ids, digests): one sha256 per distinct tile-row over that row's
    coordinate slice and tile bytes, hashed through the SAME
    `plancache.hash_update` step as the whole-structure fingerprint --
    the two surfaces cannot drift on what "content" means.  Rows of equal
    digest are byte-identical rows; a digest mismatch is the dirty set.
    Coords must be lex-sorted by (row, col) -- the BlockSparseMatrix
    invariant -- so each row is one contiguous slice."""
    coords = np.ascontiguousarray(coords)
    n = len(coords)
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, dtype="S32")
    rows = coords[:, 0]
    row_ids, starts = np.unique(rows, return_index=True)
    ends = np.append(starts[1:], n)
    tiles = np.ascontiguousarray(tiles)
    # schema header through the shared hash_update step (array dtypes +
    # per-block shape, over zero-length prototypes); each row's digest is
    # then a COPY of that state updated with the row's raw byte slices --
    # one sha256 state copy + two buffer updates per row keeps the loop
    # at hashing speed (the naive per-row ascontiguousarray/repr/tobytes
    # round-trip was ~10x slower and showed up on the diff critical path)
    base = hashlib.sha256()
    plancache.hash_update(base, coords[:0])
    plancache.hash_update(base, tiles[:0])
    # zero-copy byte views (both arrays are contiguous by now): tobytes()
    # would duplicate multi-GB operands on the diff critical path
    cbuf = memoryview(coords).cast("B")
    tbuf = memoryview(tiles).cast("B")
    cs = len(cbuf) // n
    ts = len(tbuf) // n
    out = [b""] * len(row_ids)
    for i, (s, e) in enumerate(zip(starts.tolist(), ends.tolist())):
        h = base.copy()
        h.update(cbuf[s * cs:e * cs])
        h.update(tbuf[s * ts:e * ts])
        out[i] = h.digest()
    return row_ids, np.array(out, dtype="S32")


def _host_view(m):
    """(coords, tiles) of an operand's host-reachable content, or None.
    A BlockSparseMatrix carries tiles directly; a DeviceBlockMatrix only
    qualifies through its `_host` cache -- digesting must NEVER force a
    D2H (partials without host copies use the tag channel instead)."""
    tiles = getattr(m, "tiles", None)
    if tiles is not None:
        return m.coords, tiles
    host = getattr(m, "_host", None)
    if host is not None:
        return host.coords, host.tiles
    return None


def _memo_target(m):
    """The object the digest memo lives on: the HOST matrix when one is
    reachable (a DeviceBlockMatrix is a fresh wrapper per upload, so a
    memo on it would never be found again -- the chain plan-ahead worker
    stashes on the host operand and dispatch later sees the wrapper)."""
    if getattr(m, "tiles", None) is not None:
        return m
    return getattr(m, "_host", None) or m


def stash_digests(m) -> None:
    """Precompute an operand's row digests and stash them on the
    host-bearing object (`_delta_digests`).  Called by the chain
    plan-ahead worker so the diff's hash cost runs off the dispatch
    critical path; host-pure (the @host_only worker contract), a no-op
    for device-only partials.  The stash is SINGLE-USE: the multiply
    that consumes it pops it (current_digests), so a long-lived operand
    object whose tiles are later mutated IN PLACE can never be diffed
    against a stale cached digest -- absent a stash, digests are always
    computed fresh from the live tile bytes."""
    view = _host_view(m)
    if view is None:
        return
    try:
        _memo_target(m)._delta_digests = row_digests(*view)
    except AttributeError:
        pass  # exotic operand types without a __dict__: just don't stash


def current_digests(m):
    """The operand's (row_ids, digests): the plan-ahead worker's stash if
    one is pending (consumed -- see stash_digests), else computed fresh;
    None when the tiles are not host-reachable."""
    target = _memo_target(m)
    memo = getattr(target, "_delta_digests", None)
    if memo is not None:
        try:
            del target._delta_digests
        except AttributeError:
            pass
        return memo
    view = _host_view(m)
    if view is None:
        return None
    return row_digests(*view)


# ------------------------------------------------------------- the store --
def lookup(key: str):
    """Retained entry for a plan fingerprint, or None; a hit bumps MRU."""
    with _LOCK:
        entry = _STORE.get(key)
        if entry is not None:
            _STORE.move_to_end(key)
        return entry


def note_fallback_reason(reason: str) -> None:
    """Count one full fallback under its diagnosed reason (see
    _FALLBACK_REASONS; called by ops/spgemm next to the
    delta_full_fallbacks counter bump)."""
    with _LOCK:
        _FALLBACK_REASONS[reason] = _FALLBACK_REASONS.get(reason, 0) + 1


def clear() -> None:
    """Drop every entry and zero the stats (tests, A/B harnesses, bench
    iterations -- a retained result would otherwise answer a re-run)."""
    with _LOCK:
        _STORE.clear()
        for k in _STATS:
            _STATS[k] = 0
        _FALLBACK_REASONS.clear()


def stats() -> dict:
    """Live per-process delta state for `spgemm_tpu.cli knobs [--json]`
    and spgemmd stats: delta-served multiplies vs counted full fallbacks,
    the cumulative recomputed/total output-row split, and store health."""
    cap = capacity()
    with _LOCK:
        # per-placement entry split: keys are placement-qualified
        # (ops/spgemm._delta_key appends `|dev[...]x[...]`), so under the
        # spgemmd device pool each slice's retained results show as their
        # own bracket -- the stats view of "each slice keeps its delta
        # stream" (entries without a bracket are host/test-seeded)
        placements = placement_histogram(_STORE)
        return {
            "hits": _STATS["hits"],
            "full_fallbacks": _STATS["full_fallbacks"],
            "fallback_reasons": dict(_FALLBACK_REASONS),
            "evictions": _STATS["evictions"],
            "rows_recomputed": _STATS["rows_recomputed"],
            "rows_total": _STATS["rows_total"],
            "entries": len(_STORE),
            "placements": placements,
            "capacity": cap,
            "enabled": enabled(),
        }


def _store_entry(entry: DeltaEntry) -> None:
    cap = capacity()
    with _LOCK:
        _STORE[entry.key] = entry
        _STORE.move_to_end(entry.key)
        while len(_STORE) > cap:
            _STORE.popitem(last=False)
            _STATS["evictions"] += 1


def entries() -> list:
    """Snapshot of the live (key, DeltaEntry) pairs, LRU-first.  The
    warm-start flush (ops/warmstore) walks it to persist retained results
    whose version moved since the last flush; a copy, so serialization
    (one D2H per changed entry) holds no lock."""
    with _LOCK:
        return list(_STORE.items())


def fence_version(v: int) -> None:
    """Advance the global version source past `v`.  The monotonic-version
    contract (see _VERSION) must survive restart: a persisted entry (or a
    persisted tag REFERENCE to another entry) carries a version from a
    previous process, and a new process handing out versions from 1 again
    would let an old lineage alias a fresh one -- a rehydrated consumer
    would then read a fresh producer's tag as "the exact version I
    already consumed" and splice stale rows.  The warm store fences at
    BIND time over every on-disk entry's version; a consumer's tag
    references are always older than its own version (versions are
    minted at commit, after the consumed tag existed), so the on-disk
    maximum covers every reference too."""
    global _VERSION
    with _LOCK:
        _VERSION = max(_VERSION, int(v))


def seed_entry(entry: DeltaEntry) -> None:
    """Install a rehydrated (warm-start) entry AND fence the version
    source past it (defense in depth -- the bind-time fence above is the
    load-order-independent guarantee)."""
    fence_version(entry.version)
    _store_entry(entry)


# ---------------------------------------------------------------- diffing --
def _operand_dirty(src: tuple, m):
    """Dirty tile-row set of operand m against its stored provenance, or
    None when the lineage cannot be proven (-> full fallback).  Returns
    (dirty_row_ids, refreshed_src)."""
    if src[0] == "digest":
        cur = current_digests(m)
        if cur is None:
            return None
        row_ids, digs = cur
        if not np.array_equal(src[1], row_ids):
            return None  # defensive: same fingerprint implies same rows
        return row_ids[src[2] != digs], ("digest", row_ids, digs)
    if src[0] == "tag":
        tag = getattr(m, "_delta_tag", None)
        if tag is None or tag.key != src[1]:
            return None
        if tag.prev_version == src[2]:
            dirty = np.asarray(tag.dirty_rows, np.int64)
        elif tag.version == src[2]:
            # the exact version this entry already consumed (a repeated
            # call with the same partial object): nothing changed
            dirty = np.zeros(0, np.int64)
        else:
            return None  # lineage gap (e.g. a run this entry missed)
        return dirty, ("tag", tag.key, tag.version)
    return None  # opaque: stored with no provable provenance


def operand_src(m) -> tuple:
    """Fresh provenance for storing an operand on the full path: prefer
    the analytic tag (free), else content digests (host tiles), else
    opaque -- which forces (counted) full recompute until a tag shows
    up."""
    tag = getattr(m, "_delta_tag", None)
    if tag is not None:
        return ("tag", tag.key, tag.version)
    cur = current_digests(m)
    if cur is not None:
        return ("digest", *cur)
    return ("opaque",)


def reach(join_keys: np.ndarray, pair_ptr: np.ndarray, pair_a: np.ndarray,
          pair_b: np.ndarray, a_coords: np.ndarray, b_coords: np.ndarray,
          dirty_a_rows: np.ndarray,
          dirty_b_rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Propagate input-row dirtiness through the exact join: a pair is
    dirty iff its A tile or B tile lives in a dirty input tile-row; a key
    is dirty iff any of its pairs is; and the recompute set rounds up to
    whole OUTPUT tile-rows (the granularity the fold-order argument and
    the reporting both use).  Returns (key_mask, dirty_output_rows)."""
    num_keys = len(join_keys)
    if num_keys == 0:
        return np.zeros(0, bool), np.zeros(0, np.int64)
    dirty_blk_a = np.isin(a_coords[:, 0], dirty_a_rows)
    dirty_blk_b = np.isin(b_coords[:, 0], dirty_b_rows)
    pair_dirty = dirty_blk_a[pair_a] | dirty_blk_b[pair_b]
    hit = np.flatnonzero(pair_dirty)
    key_dirty = np.zeros(num_keys, bool)
    key_dirty[np.searchsorted(pair_ptr, hit, side="right") - 1] = True
    dirty_rows = np.unique(join_keys[key_dirty, 0])
    return np.isin(join_keys[:, 0], dirty_rows), dirty_rows


def diff(entry: DeltaEntry, a, b, join, a_coords: np.ndarray,
         b_coords: np.ndarray) -> DeltaDiff | None:
    """Diff both operands against the entry's provenance and propagate
    through the join; None on any lineage ambiguity (full fallback)."""
    from spgemm_tpu.utils import failpoints  # noqa: PLC0415
    if failpoints.check("delta.diff"):
        return None  # injected lineage ambiguity: counted full fallback
    got_a = _operand_dirty(entry.a_src, a)
    if got_a is None:
        return None
    got_b = _operand_dirty(entry.b_src, b)
    if got_b is None:
        return None
    dirty_a, new_a_src = got_a
    dirty_b, new_b_src = got_b
    key_mask, dirty_rows = reach(join.keys, join.pair_ptr, join.pair_a,
                                 join.pair_b, a_coords, b_coords,
                                 dirty_a, dirty_b)
    return DeltaDiff(key_mask=key_mask, dirty_rows=dirty_rows,
                     new_a_src=new_a_src, new_b_src=new_b_src)


# ---------------------------------------------------------------- commits --
def _tag(result, key: str, version: int, prev_version: int,
         dirty_rows: np.ndarray) -> None:
    try:
        result._delta_tag = DeltaTag(key=key, version=version,
                                     prev_version=prev_version,
                                     dirty_rows=dirty_rows)
    except AttributeError:
        pass  # a result type without a __dict__: downstream just falls back


def commit(entry: DeltaEntry, result, d: DeltaDiff, out_rows: int) -> None:
    """Land a delta-served multiply: refresh the entry in place (fresh
    global version, new provenance, retained result) and tag the result
    for the next multiply in the chain."""
    prev_version = entry.version
    entry.version = _next_version()
    entry.a_src, entry.b_src = d.new_a_src, d.new_b_src
    entry.result = result
    entry.out_rows = out_rows
    _store_entry(entry)
    _tag(result, entry.key, entry.version, prev_version,
         np.asarray(d.dirty_rows, np.int64))
    with _LOCK:
        _STATS["hits"] += 1
        _STATS["rows_recomputed"] += len(d.dirty_rows)
        _STATS["rows_total"] += out_rows


def store_full(key: str, a, b, result, out_rows: int,
               out_row_ids: np.ndarray) -> None:
    """Land a full-path multiply (first contact / fallback): (re)seed the
    entry so the NEXT same-structure multiply can go incremental, and tag
    the result all-dirty against the previous version (a consumer holding
    that version correctly re-folds everything this result may have
    changed; any other lineage is a full fallback).

    An operand with OPAQUE provenance (a device partial produced outside
    the delta layer -- no tag, no host tiles) makes the entry undiffable
    forever: nothing is stored and the result is NOT tagged, so the
    retention can never pin a result it cannot serve, and downstream
    multiplies honestly fall back instead of trusting a tag with no
    verifiable lineage."""
    with _LOCK:
        prev = _STORE.get(key)
        prev_version = prev.version if prev is not None else 0
        _STATS["full_fallbacks"] += 1
        _STATS["rows_recomputed"] += out_rows
        _STATS["rows_total"] += out_rows
    a_src = operand_src(a)
    if a_src[0] == "opaque":
        return
    b_src = operand_src(b)
    if b_src[0] == "opaque":
        return
    version = _next_version()
    entry = DeltaEntry(key=key, version=version, a_src=a_src, b_src=b_src,
                       result=result, out_rows=out_rows)
    _store_entry(entry)
    _tag(result, key, version, prev_version,
         np.asarray(out_row_ids, np.int64))
