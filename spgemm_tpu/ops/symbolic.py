"""Symbolic phase: output-structure join + round bucketing (C5, C6 -- host side).

The reference builds `m2_index: rowB -> [colsB]` then joins A's blocks against
it with hash maps (sparse_matrix_mult.cu:141-156), producing per-output-tile
lists of inner block coordinates; the round packer (:167-226) then memcpys
tile pairs into an 8 GB staging buffer in rounds of <= 500 output keys.

Here the join is a vectorized sorted merge-join over the (already sorted)
block-coordinate arrays -- O(nnzb + pairs) numpy, no hashing -- and "packing"
is just index arithmetic: the numeric phase gathers tiles in HBM by index, so
no staging copy exists.  Rounds become fixed-shape (num_keys, max_pairs)
buckets, padded with a sentinel index that points at an all-zero tile
(mulmod(0, x) == 0 and addmod(acc, 0) == acc, so padding is exact) -- this is
how dynamic sparsity meets XLA's static shapes (SURVEY.md section 7).

Ordering contract (parity-critical, SURVEY.md section 2.9): each output key's
pair list is ordered by ascending inner block-coordinate j, which is exactly
the order the reference's sorted-map traversal produces.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field

import numpy as np


def accept_round_stack(numeric_fn):
    """Wrap a numeric-round kernel so a stacked (R, K, P) pa/pb -- R
    same-shape rounds batched along a leading axis -- is accepted and
    returns (R, K, k, k).

    The stack flattens into the key axis: keys are disjoint across rounds
    and each key's fold order lives inside its own pair list, so batching
    is bit-exact by construction (round-batched dispatch).  One definition
    shared by all four numeric kernels; array-library agnostic (only
    ndim/shape/reshape)."""
    @functools.wraps(numeric_fn)
    def wrapped(a_hi, a_lo, b_hi, b_lo, pa, pb, **kw):
        if pa.ndim != 3:
            return numeric_fn(a_hi, a_lo, b_hi, b_lo, pa, pb, **kw)
        R, K, P = pa.shape
        k = a_hi.shape[-1]
        oh, ol = numeric_fn(a_hi, a_lo, b_hi, b_lo,
                            pa.reshape(R * K, P), pb.reshape(R * K, P), **kw)
        return oh.reshape(R, K, k, k), ol.reshape(R, K, k, k)
    return wrapped


def stack_round_indices(idx: np.ndarray, sentinel: int,
                        jobs: int) -> np.ndarray:
    """Stack one round's index array for a JOBS-wide cross-job fused
    dispatch (ops/spgemm.execute_batched): the J operand slabs
    concatenate tiles-only -- job j's tile t lands at j*sentinel + t --
    with ONE shared zero tile appended at jobs*sentinel, so job j's copy
    shifts every real index by j*sentinel and remaps the per-job
    sentinel to the shared one.  A naive uniform offset would alias job
    j's sentinel onto job j+1's tile 0 (wrong bits); the remap is the
    whole subtlety.  (K, P) stacks to (jobs, K, P) and an
    already-stacked (R, K, P) to (jobs*R, K, P) -- both the 3-D form
    accept_round_stack flattens into the key axis, which keeps every
    key's pair list and fold order untouched: bit-exact by construction,
    the same argument as round batching."""
    base = idx[None] if idx.ndim == 2 else idx
    copies = [np.where(base == sentinel, jobs * sentinel,
                       base + j * sentinel)
              for j in range(jobs)]
    return np.concatenate(copies, axis=0).astype(idx.dtype)


@dataclass
class JoinResult:
    """Output structure of A x B, in CSR-over-sorted-keys form.

    keys     : (num_keys, 2) int64, sorted lexicographically -- output tile coords.
    pair_ptr : (num_keys + 1,) int64 -- segment boundaries into pair_a/pair_b.
    pair_a   : (total_pairs,) int32 -- A tile slab indices, per key in j-ascending order.
    pair_b   : (total_pairs,) int32 -- B tile slab indices, aligned with pair_a.
    """

    keys: np.ndarray
    pair_ptr: np.ndarray
    pair_a: np.ndarray
    pair_b: np.ndarray

    @property
    def num_keys(self) -> int:
        return len(self.keys)

    @functools.cached_property
    def fanouts(self) -> np.ndarray:
        """Per-key pair counts, computed ONCE and memoized: plan_rounds,
        the ring mass balancer, and execute's proven-bound propagation all
        consume the same array (re-deriving the histogram per call was a
        measurable micro-cost on every cold or cache-missed plan)."""
        return np.diff(self.pair_ptr)


def _segment_expand(counts: np.ndarray):
    """Ragged expansion: for segments of the given lengths, return
    (segment_id, within_segment_offset) arrays of total length counts.sum()."""
    # spgemm-lint: fld-proof(integer segment-length total for sizing only; exact int64 addition is order-free, no wrap-then-mod values involved)
    total = int(counts.sum())
    seg_id = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    seg_start = np.concatenate(([0], np.cumsum(counts)[:-1]))
    offs = np.arange(total, dtype=np.int64) - np.repeat(seg_start, counts)
    return seg_id, offs


def symbolic_join(a_coords: np.ndarray, b_coords: np.ndarray) -> JoinResult:
    """Structure join: which (A-tile, B-tile) pairs feed which output tile.

    Both coord arrays must be lexicographically sorted by (row, col) --
    the BlockSparseMatrix invariant.

    Dispatches to the native C++ join (native/symbolic.cpp: searchsorted
    ranges + stable LSD radix sort) when the library is available -- the
    host runtime is native where the reference's is (its hash-join was "CPU
    hot loop #1", SURVEY.md section 3.2).  The numpy path below is the
    always-available fallback, kept bit-identical (tests cross-check).
    """
    from spgemm_tpu.utils import native  # noqa: PLC0415

    # The native join fuses keys as uint64 row*span + col; beyond uint64's
    # range that wraps, so dispatch to it only in the provably-safe regime
    # (the numpy fallback below switches to a stable lexsort there).
    native_safe = (
        len(a_coords) == 0 or len(b_coords) == 0
        or (int(a_coords[:, 0].max()) + 1) * (int(b_coords[:, 1].max()) + 1)
        <= 1 << 64)
    nat = native.symbolic_join_native(a_coords, b_coords) if native_safe else None
    if nat is not None:
        keys, pair_ptr, pair_a, pair_b = nat
        return JoinResult(keys=keys, pair_ptr=pair_ptr,
                          pair_a=pair_a, pair_b=pair_b)
    empty = JoinResult(
        keys=np.zeros((0, 2), np.int64),
        pair_ptr=np.zeros(1, np.int64),
        pair_a=np.zeros(0, np.int32),
        pair_b=np.zeros(0, np.int32),
    )
    if len(a_coords) == 0 or len(b_coords) == 0:
        return empty

    b_rows = b_coords[:, 0]  # sorted (lex order on (row, col))
    # For each A block (i, j): B blocks with row == j form the contiguous
    # range [lo, hi) in the sorted B slab.
    a_cols = a_coords[:, 1]
    lo = np.searchsorted(b_rows, a_cols, side="left")
    hi = np.searchsorted(b_rows, a_cols, side="right")
    counts = hi - lo
    # spgemm-lint: fld-proof(integer pair-count total for sizing only; exact int64 addition is order-free, no wrap-then-mod values involved)
    total = int(counts.sum())
    if total == 0:
        return empty

    # Segment-expand: pair stream in A-traversal order (sorted (i, j)), each A
    # block contributing its B row-range in ascending-c order.
    a_slot, offs = _segment_expand(counts)
    b_slot = np.repeat(lo, counts) + offs

    out_r = a_coords[a_slot, 0]
    out_c = b_coords[b_slot, 1]

    # Stable sort by output key: within a key, the stream order is ascending
    # inner-coordinate j (A sorted by (i, j)), which stability preserves.
    # A single fused uint64 key + stable argsort hits numpy's radix path --
    # several times faster than a two-pass lexsort on multi-million-pair
    # joins (the chain bench's symbolic phase was lexsort-dominated).  uint64
    # matches the native join (native/symbolic.cpp) bit-for-bit where int64
    # would silently wrap for max_row * span >= 2^63; beyond even uint64's
    # range, fall back to a stable lexsort on the coordinate pair.
    span = int(b_coords[:, 1].max()) + 1
    max_row = int(a_coords[:, 0].max())
    if (max_row + 1) * span <= 1 << 64:
        fused = out_r.astype(np.uint64) * np.uint64(span) + out_c.astype(np.uint64)
        order = np.argsort(fused, kind="stable")
        fused = fused[order]
        a_slot, b_slot = a_slot[order], b_slot[order]
        key_change = np.empty(total, dtype=bool)
        key_change[0] = True
        key_change[1:] = fused[1:] != fused[:-1]
        key_starts = np.flatnonzero(key_change)
        keys = np.stack(
            [(fused[key_starts] // np.uint64(span)).astype(np.int64),
             (fused[key_starts] % np.uint64(span)).astype(np.int64)], axis=1)
    else:
        order = np.lexsort((out_c, out_r))  # stable, last key primary
        r_s, c_s = out_r[order], out_c[order]
        a_slot, b_slot = a_slot[order], b_slot[order]
        key_change = np.empty(total, dtype=bool)
        key_change[0] = True
        key_change[1:] = (r_s[1:] != r_s[:-1]) | (c_s[1:] != c_s[:-1])
        key_starts = np.flatnonzero(key_change)
        keys = np.stack([r_s[key_starts], c_s[key_starts]], axis=1)
    pair_ptr = np.append(key_starts, total).astype(np.int64)

    return JoinResult(keys=keys, pair_ptr=pair_ptr,
                      pair_a=a_slot.astype(np.int32), pair_b=b_slot.astype(np.int32))


def slice_join(join: JoinResult,
               keep: np.ndarray) -> tuple[JoinResult, np.ndarray]:
    """Row-sliced sub-join: restrict to the keys selected by the boolean
    mask `keep`, copying each kept key's pair list WHOLE and in order.

    The delta-recompute path (ops/delta) re-executes only the dirty
    output rows; its bit-exactness rests on this function preserving the
    reference's per-key j-ascending pair order exactly -- a kept key folds
    identically under the sliced plan and the full plan, because its pair
    list is byte-identical.  Returns (sub_join, kept_key_indices), the
    indices mapping sub-join rows back into the full key list (the splice
    scatter)."""
    kept = np.flatnonzero(keep)
    lens = join.fanouts[kept]
    ptr = np.zeros(len(kept) + 1, np.int64)
    np.cumsum(lens, out=ptr[1:])
    _, offs = _segment_expand(lens)
    src = np.repeat(join.pair_ptr[kept], lens) + offs
    return JoinResult(keys=join.keys[kept], pair_ptr=ptr,
                      pair_a=join.pair_a[src],
                      pair_b=join.pair_b[src]), kept


@dataclass
class Round:
    """One fixed-shape numeric launch: <= round_size keys, all padded to the
    same fanout class.  The reference's 500-key round (sparse_matrix_mult.cu:181-185)
    generalized to (pow-4 key count) x (3/4-pow-2 fanout) shape classes so
    the jit cache stays small.

    Two array layouts share this container (SPGEMM_TPU_ACCUM_ROUTE):

      ladder (route='ladder'): pa/pb are (K_pad, P) -- each key's pair list
        sentinel-padded to the fanout class width P.  The pre-route layout.
      dense (route='dense'): pa/pb are (L,) -- the chunk's pair lists
        concatenated in key order (each list already j-ascending) into one
        contiguous stream, padded to the fine stream ladder (_stream_pad),
        with seg mapping every stream slot to its output row (pad slots to
        the scratch row n_rows).  No per-key padding: the padded-MAC tax
        collapses to the stream tail.

    Both layouts fold every output row's pairs in the identical
    left-to-right j-ascending order, so they are bit-exact by construction.
    An 'auto'-routed plan keeps the ladder layout here and its dense twin
    in dense_alt; dispatch picks via the measured crossover gate."""

    key_index: np.ndarray  # (n,) int64 -- positions into JoinResult.keys
    pa: np.ndarray         # ladder: (K_pad, P) int32 (sentinel-padded);
                           # dense: (L,) int32 pair stream
    pb: np.ndarray         # same shape as pa
    max_fanout: int = 0    # real (unpadded) max fanout among the round's keys
                           # -- the hybrid exactness proof uses this, not the
                           # padded class width (sentinel pairs contribute 0)
    route: str = "ladder"  # array layout: 'ladder' | 'dense'
    seg: np.ndarray | None = None  # dense only: (L,) int32 output row per
                                   # stream slot (pad slots -> n_rows)
    n_rows: int = 0        # dense only: padded output-row count (the ladder
                           # twin's K_pad, so assembly sees identical shapes)
    real_pairs: int = 0    # unpadded pair count (padded_mac_ratio numerator)
    dense_alt: "Round | None" = None  # auto route: the dense-stream twin

    @property
    def out_rows(self) -> int:
        """Output rows this round's kernel produces (padded key count) --
        the assembly-permutation row span, layout-independent."""
        return self.pa.shape[0] if self.pa.ndim == 2 else self.n_rows

    @property
    def shipped_macs(self) -> int:
        """Pair slots actually shipped to the kernel, padding included
        (each slot costs k j-MACs, so slot counts compare 1:1)."""
        return int(self.pa.size)

    def padded_mac_ratio(self) -> float:
        """Shipped / real pair slots (>= 1.0): the padded-MAC tax this
        round pays.  An auto round reports its ladder layout; the dense
        twin reports its own (stream-tail-only) ratio."""
        return self.shipped_macs / self.real_pairs if self.real_pairs else 1.0


def _ceil_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length() if x > 1 else 1


def _floor_pow2(x: int) -> int:
    return 1 << (max(int(x), 1).bit_length() - 1)


def _ladder_floor(x: int) -> int:
    """Largest pow2-or-3/4-pow2 ladder value <= x (floor twin of
    _shape_class's ceiling)."""
    p = _floor_pow2(x)
    c = 3 * p // 2  # = 3/4 of the next pow2 rung
    return c if p >= 2 and c <= x else p


def _shape_class_vec(f: np.ndarray) -> np.ndarray:
    """Round up to {1, 2, 3, 4, 6, 8, 12, 16, ...}: pow2 plus 3/4-pow2.

    Pure pow2 classes waste up to ~50% padded slots (a banded matrix with
    fanout 9 pads to 16); interleaving 3*2^(n-2) caps waste at 25% while the
    compiled-shape count stays logarithmic.  np.log2 of an exact power of
    two is exact in f64, so the ceil is safe."""
    p = 1 << np.ceil(np.log2(np.maximum(f, 1))).astype(np.int64)
    c34 = (3 * p) // 4
    return np.where((p >= 4) & (f <= c34), c34, p)


def _shape_class(x: int) -> int:
    return int(_shape_class_vec(np.array([x]))[0])


# Smallest fanout class the auto accumulator route considers dense-eligible:
# below it the ladder's padded-MAC tax is bounded (<= 1/3) and the per-key
# vectorized kernel wins on key count; at and above it hub-row classes burn
# enough sentinel MACs that the stream fold is worth carrying as a twin.
DENSE_MIN_CLASS = 256


def _stream_pad(n: int) -> int:
    """Smallest fine-ladder value m * 2^e (m in 8..15, e >= 3) >= n: the
    dense pair-stream pad target.  Eight rungs per octave keep the waste
    under 1/8 on any stream past 64 pairs (vs up to ~1/2 per key on the
    3/4-pow-2 ladder) while the compiled-shape count stays logarithmic;
    every rung is a multiple of 8, so the fold kernel may unroll pair
    blocks without a remainder loop."""
    n = max(int(n), 1)
    if n <= 8:
        return 8
    e = max((n - 1).bit_length() - 4, 3)
    return -(-n // (1 << e)) << e


def _dense_round(join: JoinResult, chunk: np.ndarray, lens: np.ndarray,
                 rows: np.ndarray, src: np.ndarray, n_rows: int,
                 a_sentinel: int, b_sentinel: int) -> Round:
    """Build the dense-stream layout for one class chunk: the chunk's pair
    lists concatenated in key order (rows/src from the caller's
    _segment_expand -- the exact per-key j-ascending order the ladder
    scatter uses), sentinel-padded to the fine stream ladder.  Pad slots
    fold zero tiles into the scratch row n_rows, so they cannot touch any
    real output row."""
    real = len(src)
    L = _stream_pad(real)
    spa = np.full(L, a_sentinel, np.int32)
    spb = np.full(L, b_sentinel, np.int32)
    seg = np.full(L, n_rows, np.int32)
    spa[:real] = join.pair_a[src]
    spb[:real] = join.pair_b[src]
    seg[:real] = rows
    return Round(key_index=chunk, pa=spa, pb=spb,
                 max_fanout=int(lens.max()), route="dense", seg=seg,
                 n_rows=n_rows, real_pairs=real)


def assembly_permutation(rounds: list["Round"], num_keys: int) -> np.ndarray:
    """Precomputed inverse permutation for the assembly gather.

    inv[key] = row of that key in the PADDED concatenation of the rounds'
    outputs (padded tail rows stay in place -- the numeric outputs are
    consumed whole, no per-round device slicing); the extra last entry maps
    the sentinel slot to a zero row appended after the concatenation.
    Host-side numpy, so the device assembly phase is exactly one gather."""
    total = sum(r.out_rows for r in rounds)
    inv = np.full(num_keys + 1, total, np.int64)
    off = 0
    for r in rounds:
        inv[r.key_index] = off + np.arange(len(r.key_index))
        off += r.out_rows
    return inv


@dataclass
class SpgemmPlan:
    """Everything the host decides about one C = A x B before any device
    work: the structure join, the round plan, the assembly permutation,
    and memoized schedule hooks for the sharded strategies.

    Built by ops/spgemm.plan() (host-only -- pure numpy, safe on planner
    worker threads when backend/platform are passed in resolved) and
    consumed by ops/spgemm.execute() (device-only).  A plan is valid for
    any operand pair with the same structure (coords/nnzb/k); sentinels
    are baked into the pa/pb index arrays, so check_operands() rejects a
    mismatched pair before a silent out-of-bounds gather can happen.

    fingerprint: the structure-cache key this plan was stored under
    (ops/plancache), or None when caching was off.
    plan_s: host wall the CALLER blocked on to get the plan (the critical-
        path cost).  A cache hit returns the memoized object unchanged, so
        this stays the cold figure; for an estimator-routed plan it is the
        fast-return wall -- the deferred exact join's cost lands in the
        `symbolic_join`/`plan_rounds` phases of whichever thread ran
        ensure_exact().
    estimate / plan_route: the sampled structure estimate that steered
        this plan (ops/estimate, None when the estimator did not run) and
        the route taken at plan time ('estimated' = fast return with the
        exact join deferred, 'exact' = join built inline).
    join/rounds/take are None on a DEFERRED plan until ensure_exact()
    lands the exact join; every consumer goes through ensure_exact() (or
    the ring_schedule/rowshard_rounds hooks, which call it).
    """

    backend: str           # resolved concrete backend the budgets assumed
    platform: str          # platform the budgets were derived for
    k: int
    a_nnzb: int            # A's sentinel index, baked into every pa
    b_nnzb: int
    join: JoinResult | None
    rounds: list | None    # list[Round]
    take: np.ndarray | None  # batch-mode assembly permutation (else None)
    batch: bool            # round-batched plan (SPGEMM_TPU_ROUND_BATCH)
    round_size: int | None
    split_fanout: int | None = None  # hybrid proof partition threshold
    fingerprint: str | None = None
    plan_s: float = 0.0
    estimate: object | None = None   # ops/estimate.StructureEstimate
    plan_route: str = "exact"        # 'estimated' | 'exact'
    # the exact block structures planned from (check_operands' real guard)
    _a_coords: np.ndarray | None = None
    _b_coords: np.ndarray | None = None
    _ring: dict = field(default_factory=dict, repr=False)
    _rowshard: dict = field(default_factory=dict, repr=False)
    # deferred-exact completion: a host-pure callable that fills
    # join/rounds/take in place (ops/spgemm builds it on the estimated
    # route), dropped once run; the lock makes ensure_exact() idempotent
    # across threads (the plan-ahead worker and the dispatch thread may
    # race to complete the same cached plan)
    _exact_builder: object | None = field(default=None, repr=False)
    _complete_lock: threading.Lock = field(default_factory=threading.Lock,
                                           repr=False)

    @property
    def is_deferred(self) -> bool:
        """True while the exact join has not landed yet (estimated route,
        before any consumer forced completion)."""
        with self._complete_lock:
            return self._exact_builder is not None

    def ensure_exact(self) -> "SpgemmPlan":
        """Materialize the deferred exact join/rounds/take in place and
        return self.  Idempotent and thread-safe; a no-op on plans built
        inline.  This is the in-place PROMOTION of an estimated plan-cache
        entry: the cached object is the same object, so every later cache
        hit serves the exact plan."""
        with self._complete_lock:
            builder = self._exact_builder
            if builder is not None:
                from spgemm_tpu.utils import failpoints  # noqa: PLC0415
                failpoints.check("plan.ensure_exact")
                builder(self)
                self._exact_builder = None
                # event-log breadcrumb: WHERE the deferred join landed
                # (the plan-ahead worker off the critical path, or a
                # consumer that had to block) -- the estimator's latency
                # win is only real when this mostly reads a worker thread
                from spgemm_tpu.obs import events  # noqa: PLC0415
                events.emit("plan_exact_landed",
                            thread=threading.current_thread().name,
                            fingerprint=(self.fingerprint or "")[:16]
                            or None)
        return self

    def check_operands(self, a, b) -> None:
        """Refuse to drive a mismatched operand pair.  The cheap k/nnzb
        gates catch gross misuse; the coords comparison is the real guard
        -- the pa/pb gathers were built from the operands' block
        structure, so a same-nnzb pair with different coords would gather
        in-bounds and produce a silently WRONG product.  O(nnzb) int
        compare, noise next to the dispatch it protects."""
        if (a.k, b.k) != (self.k, self.k):
            raise ValueError(
                f"plan built for k={self.k}, operands have k={a.k}/{b.k}")
        if (a.nnzb, b.nnzb) != (self.a_nnzb, self.b_nnzb):
            raise ValueError(
                f"plan built for nnzb=({self.a_nnzb}, {self.b_nnzb}), "
                f"operands have ({a.nnzb}, {b.nnzb})")
        if self._a_coords is None or self._b_coords is None:
            return  # hand-built plan without stored structure: k/nnzb only
        if not (np.array_equal(a.coords, self._a_coords)
                and np.array_equal(b.coords, self._b_coords)):
            raise ValueError(
                "plan built for a different block structure: operand "
                "coords do not match the coords this plan was planned "
                "from (same nnzb, different sparsity pattern)")

    def ring_schedule(self, nnzb_b: int, n_dev: int):
        """Memoized parallel/ring.plan_ring over this plan's join -- the
        ring strategy's prebuilt-schedule hook (pure numpy; a planner
        worker thread may warm it ahead of the fold)."""
        # the resolved mass-balance flag is part of the memo key: an
        # in-process A/B flipping SPGEMM_TPU_PLAN_ESTIMATE must never be
        # served the other leg's schedule
        from spgemm_tpu.parallel.ring import plan_ring  # noqa: PLC0415
        from spgemm_tpu.utils import knobs  # noqa: PLC0415
        mb = bool(knobs.get("SPGEMM_TPU_PLAN_ESTIMATE"))
        key = (nnzb_b, n_dev, mb)
        if key not in self._ring:
            self._ring[key] = plan_ring(self.ensure_exact().join,
                                        nnzb_b, n_dev, mass_balance=mb)
        return self._ring[key]

    def rowshard_rounds(self, round_size: int | None = None):
        """Memoized non-batch round plan for parallel/rowshard (one fixed
        512-key round plan per explicit round_size).  Always ladder: the
        shard_map'ed kernel consumes (K, P) index arrays directly."""
        rs = 512 if round_size is None else round_size
        if rs not in self._rowshard:
            self._rowshard[rs] = plan_rounds(
                self.ensure_exact().join, a_sentinel=self.a_nnzb,
                b_sentinel=self.b_nnzb, round_size=rs, route="ladder")
        return self._rowshard[rs]

    def padded_mac_ratio(self) -> float:
        """Shipped / real pair slots across this plan's rounds (>= 1.0):
        the padded-MAC tax the accumulator route is judged against.
        Counts each auto round's dense twin where one exists (that is the
        layout the route layer intends to dispatch); forces the exact
        plan."""
        rounds = self.ensure_exact().rounds or []
        shipped = real = 0
        for r in rounds:
            eff = r.dense_alt if r.dense_alt is not None else r
            shipped += eff.shipped_macs
            real += eff.real_pairs
        return shipped / real if real else 1.0


def _smem_key_cap(P: int, max_entries: int) -> int:
    """Key-chunk cap for fanout class P under a per-round index-array entry
    budget (the Pallas kernels' scalar-prefetch arrays live in SMEM).

    The kernel ships pa/pb with the LONGER axis in lanes (lane-padded to
    128, sublanes to 8), so the per-array footprint is
    pad8(short) * max(long, 128) entries; solve for the key-chunk size."""
    pad8_p = -(-P // 8) * 8
    if P <= 512:
        # (P, K): K rides the lanes, and Mosaic pads it to >= 128 no matter
        # how few keys ship -- below pad8(P) * 128 entries NO chunk size
        # meets the budget, so shrinking K would just overshoot silently
        # (the defect class the batch-mode pow2 clamp closes).  Unreachable
        # at the in-tree 64K budget (pad8(P) * 128 <= 65536 for P <= 512);
        # refuse loudly for external callers instead of under-budgeting.
        if max_entries < pad8_p * 128:
            raise ValueError(
                f"max_entries={max_entries} cannot fit fanout class P={P}: "
                f"the (P, K) index arrays lane-pad K to >= 128, so the "
                f"minimum SMEM footprint is pad8(P) * 128 = {pad8_p * 128} "
                "entries")
        return max_entries // pad8_p              # (P, K): P sublanes
    # (K, P): P rides the lanes and is padded to a 128 multiple by Mosaic --
    # budget against the padded footprint, not raw P, or the shipped arrays
    # overshoot SMEM for non-128-multiple fanout classes
    pad128_p = -(-P // 128) * 128
    return max(max_entries // pad128_p, 1)


def plan_rounds(join: JoinResult, a_sentinel: int, b_sentinel: int,
                round_size: int | None = 512,
                max_entries: int | None = None,
                batch: bool = False,
                batch_entries: int | None = None,
                split_fanout: int | None = None,
                route: str | None = None) -> list[Round]:
    """Bucket output keys by fanout class and chop into fixed-shape rounds.

    a_sentinel/b_sentinel: index of the appended all-zero tile in each slab.
    Padding both the pair axis (to the 3/4-pow-2 fanout class) and the key
    axis (to a pow-4 rung <= the chunk cap) keeps the set of compiled shapes
    logarithmic.

    max_entries: if set, the key-axis chunk for fanout class P grows to
    max_entries // P (pow-2, capped at 8192) instead of round_size -- fewer,
    bigger launches for a backend whose per-round index arrays are bounded by
    a memory budget (the Pallas kernel's scalar-prefetch arrays live in SMEM)
    rather than by gather-materialization size (the XLA backend's constraint).

    batch: round-batched ("mega-round") planning -- each fanout class's keys
    merge into ONE round (the (R, K, P) stack of the per-round plan,
    flattened into the key axis: keys are disjoint across rounds and the
    fold order lives entirely inside each key's pair list, so the merge is
    bit-exact by construction).  Dispatch count then scales with the number
    of shape classes, not the number of keys.  round_size becomes an
    OPTIONAL explicit cap (None = uncapped); batch_entries bounds the
    per-launch key*pair entry count (the XLA backend's gather
    materialization); the SMEM cap still applies when max_entries is set.
    The key axis pads to the finer 3/4-pow-2 ladder instead of pow4: a
    mega-round's tail padding is a fraction of the WHOLE class, so the 25%
    ladder matters where the pow4 ladder's 4x tail would not.

    split_fanout: if set (batch mode), each class's keys are partitioned
    into fanout <= split_fanout and > split_fanout before merging -- the
    hybrid dispatcher's exactness proof is a fanout threshold, so this
    keeps proof granularity at the key level while still dispatching one
    launch per (class, kernel-choice) partition.

    route: accumulator-route decision per class (SPGEMM_TPU_ACCUM_ROUTE;
    None reads the knob).  'ladder' plans exactly the pre-route layout --
    bytes identical, the whole-engine A/B.  'dense' forces the stream
    layout for every class.  'auto' keeps the ladder layout and attaches
    a dense-stream twin (Round.dense_alt) to classes >= DENSE_MIN_CLASS;
    dispatch picks per round via the measured crossover gate.  The
    decision keys off the REAL per-class fanouts of the exact join built
    here -- never an estimate -- so an estimator miss can shrink dense
    coverage but can never change fold semantics (every route is
    bit-exact by construction).
    """
    if route is None:
        from spgemm_tpu.utils import knobs  # noqa: PLC0415
        route = knobs.get("SPGEMM_TPU_ACCUM_ROUTE")
    if route not in ("auto", "ladder", "dense"):
        raise ValueError(f"unknown accumulator route {route!r}")
    if round_size is not None and round_size < 1:
        raise ValueError(f"round_size must be >= 1, got {round_size}")
    if round_size is None and not batch:
        round_size = 512
    rounds: list[Round] = []
    if join.num_keys == 0:
        return rounds
    fan = join.fanouts
    classes = _shape_class_vec(fan)
    for cls in np.unique(classes):
        members_all = np.flatnonzero(classes == cls)
        P = int(cls)
        if batch and split_fanout is not None:
            f = fan[members_all]
            parts = [members_all[f <= split_fanout],
                     members_all[f > split_fanout]]
            parts = [p for p in parts if len(p)]
        else:
            parts = [members_all]
        if batch:
            # one launch per class partition, bounded by every budget that
            # applies: the caller's explicit cap, the gather-materialization
            # entry budget, the SMEM index-array budget, and the 8192
            # compiled-shape ceiling.  The cap lands on the 3/4-pow-2 ladder
            # so tail rounds pad to <= 1/3 waste.
            caps = [8192]
            if round_size is not None:
                caps.append(round_size)
            if batch_entries is not None:
                caps.append(max(1, batch_entries // P))
            smem_cap = None
            if max_entries is not None:
                smem_cap = _smem_key_cap(P, max_entries)
                caps.append(smem_cap)
            chunk_cap = max(1, _ladder_floor(min(caps)))
            # SMEM-derived caps must clamp to the pow2 floor (ROADMAP
            # round-7 flag): at P <= 512 the kernel ships (P, K) with the
            # key axis in LANES, and Mosaic lane-pads K to the next 128
            # multiple -- a 3/4-ladder chunk like 192 would silently ship
            # a 256-wide array, overshooting the max_entries budget the
            # cap was solved from by up to 33%.  Pow2 rungs >= 128 are
            # their own lane padding, and ladder rungs >= 384 are already
            # 128-multiples, so only the small non-multiple rungs clamp.
            if (smem_cap is not None and P <= 512
                    and -(-chunk_cap // 128) * 128 > smem_cap):
                chunk_cap = max(1, _floor_pow2(min(caps)))
        elif max_entries is None:
            chunk_cap = round_size
        else:
            cap = _smem_key_cap(P, max_entries)
            chunk_cap = max(1, min(8192, _floor_pow2(cap)))
            chunk_cap = min(chunk_cap, max(round_size, 1))
        for members in parts:
            for start in range(0, len(members), chunk_cap):
                chunk = members[start : start + chunk_cap]
                K = len(chunk)
                if batch:
                    K_pad = min(_shape_class(K), chunk_cap)
                else:
                    # key-axis ladder is pow4 (4, 16, 64, 256, 1024, 4096):
                    # padded keys compute discarded zeros only on the one
                    # tail round per class, while the compiled-shape count --
                    # the expensive resource on the slow-AOT TPU toolchain --
                    # stays at <= 6 per fanout class.  The pair axis keeps
                    # the finer 3/4-pow2 ladder because its padding costs
                    # real work on every round.
                    K_pad = 4
                    while K_pad < K:
                        K_pad *= 4
                    K_pad = min(K_pad, chunk_cap)
                lens = fan[chunk]
                rows, cols = _segment_expand(lens)
                src = np.repeat(join.pair_ptr[chunk], lens) + cols
                if route == "dense":
                    rounds.append(_dense_round(join, chunk, lens, rows, src,
                                               K_pad, a_sentinel, b_sentinel))
                    continue
                pa = np.full((K_pad, P), a_sentinel, dtype=np.int32)
                pb = np.full((K_pad, P), b_sentinel, dtype=np.int32)
                # scatter each key's pair list into its row (vectorized)
                pa[rows, cols] = join.pair_a[src]
                pb[rows, cols] = join.pair_b[src]
                rnd = Round(key_index=chunk, pa=pa, pb=pb,
                            max_fanout=int(lens.max()), real_pairs=len(src))
                if route == "auto" and P >= DENSE_MIN_CLASS:
                    rnd.dense_alt = _dense_round(join, chunk, lens, rows, src,
                                                 K_pad, a_sentinel, b_sentinel)
                rounds.append(rnd)
    return rounds


# ------------------------------------------------- plan <-> arrays codec --
# Schema version of the flat-array plan encoding below.  Bump on ANY field
# or layout change: the warm-start store (ops/warmstore) refuses to decode
# a mismatched version -- a version-skewed on-disk entry must be a counted
# cold fallback, never a half-parsed plan.
# v2: accumulator-route fields (Round.route/seg/n_rows/real_pairs and the
# auto route's dense_alt twin).
PLAN_CODEC_VERSION = 2

# SpgemmPlan scalar fields packed into the "scalars" int64 array, in order
# (None encodes as -1 for the two optional ints; batch as 0/1).
_SCALAR_FIELDS = ("k", "a_nnzb", "b_nnzb", "batch", "round_size",
                  "split_fanout", "num_rounds", "has_take")


def plan_to_arrays(plan: SpgemmPlan) -> dict | None:
    """Flatten an EXACT plan into a dict of numpy arrays (npz-ready).

    The warm-start persistence codec: everything ops/spgemm.execute needs
    -- the exact join, the padded round index arrays, the assembly
    permutation, and the operand coords check_operands guards with --
    round-trips through plain arrays, so a persisted plan replays
    byte-identically (the pa/pb gathers ARE the fold order).  Returns
    None for a deferred (estimator-routed, join not yet landed) plan:
    persisting a plan without its exact join would save nothing worth the
    bytes.  Pure numpy, jax-free (host codec, any thread)."""
    if plan.is_deferred or plan.join is None or plan.rounds is None:
        return None
    scalars = np.array(
        [plan.k, plan.a_nnzb, plan.b_nnzb, int(plan.batch),
         -1 if plan.round_size is None else plan.round_size,
         -1 if plan.split_fanout is None else plan.split_fanout,
         len(plan.rounds), int(plan.take is not None)], np.int64)
    out = {
        "codec": np.int64(PLAN_CODEC_VERSION),
        "backend": np.array(plan.backend),
        "platform": np.array(plan.platform),
        "scalars": scalars,
        "join_keys": plan.join.keys,
        "join_pair_ptr": plan.join.pair_ptr,
        "join_pair_a": plan.join.pair_a,
        "join_pair_b": plan.join.pair_b,
        "round_max_fanout": np.array(
            [r.max_fanout for r in plan.rounds], np.int64),
        "a_coords": (plan._a_coords if plan._a_coords is not None
                     else np.zeros((0, 2), np.int64)),
        "b_coords": (plan._b_coords if plan._b_coords is not None
                     else np.zeros((0, 2), np.int64)),
    }
    if plan.take is not None:
        out["take"] = plan.take
    # per-round accumulator-route metadata (codec v2): layout flag, padded
    # row count, real pair count, dense-twin presence -- one int64 vector
    # per round, plus the stream arrays where a dense layout exists
    for i, r in enumerate(plan.rounds):
        out[f"r{i}_key_index"] = r.key_index
        out[f"r{i}_pa"] = r.pa
        out[f"r{i}_pb"] = r.pb
        out[f"r{i}_route"] = np.array(
            [int(r.route == "dense"), r.n_rows, r.real_pairs,
             int(r.dense_alt is not None)], np.int64)
        if r.seg is not None:
            out[f"r{i}_seg"] = r.seg
        if r.dense_alt is not None:
            alt = r.dense_alt
            out[f"r{i}_alt_pa"] = alt.pa
            out[f"r{i}_alt_pb"] = alt.pb
            out[f"r{i}_alt_seg"] = alt.seg
            out[f"r{i}_alt_meta"] = np.array(
                [alt.n_rows, alt.real_pairs], np.int64)
    return out


def plan_from_arrays(d, fingerprint: str | None = None) -> SpgemmPlan:
    """Rebuild a SpgemmPlan from plan_to_arrays output (or a loaded npz
    mapping).  Raises ValueError on codec-version skew and KeyError/
    ValueError on missing or malformed fields -- the caller (the
    warm-start store) catches and counts, never trusts."""
    version = int(d["codec"])
    if version != PLAN_CODEC_VERSION:
        raise ValueError(f"plan codec version {version} != "
                         f"{PLAN_CODEC_VERSION} (version skew)")
    s = {name: int(v) for name, v in zip(_SCALAR_FIELDS,
                                         np.asarray(d["scalars"]))}
    join = JoinResult(
        keys=np.asarray(d["join_keys"], np.int64),
        pair_ptr=np.asarray(d["join_pair_ptr"], np.int64),
        pair_a=np.asarray(d["join_pair_a"], np.int32),
        pair_b=np.asarray(d["join_pair_b"], np.int32))
    max_fan = np.asarray(d["round_max_fanout"], np.int64)
    if len(max_fan) != s["num_rounds"]:
        raise ValueError("round count does not match the scalars header")
    rounds = []
    for i in range(s["num_rounds"]):
        is_dense, n_rows, real_pairs, has_alt = (
            int(v) for v in np.asarray(d[f"r{i}_route"]))
        rnd = Round(key_index=np.asarray(d[f"r{i}_key_index"], np.int64),
                    pa=np.asarray(d[f"r{i}_pa"], np.int32),
                    pb=np.asarray(d[f"r{i}_pb"], np.int32),
                    max_fanout=int(max_fan[i]),
                    route="dense" if is_dense else "ladder",
                    n_rows=n_rows, real_pairs=real_pairs)
        if is_dense:
            rnd.seg = np.asarray(d[f"r{i}_seg"], np.int32)
            if rnd.pa.ndim != 1 or len(rnd.seg) != len(rnd.pa):
                raise ValueError("malformed dense-round stream arrays")
        if has_alt:
            alt_rows, alt_real = (int(v)
                                  for v in np.asarray(d[f"r{i}_alt_meta"]))
            rnd.dense_alt = Round(
                key_index=rnd.key_index,
                pa=np.asarray(d[f"r{i}_alt_pa"], np.int32),
                pb=np.asarray(d[f"r{i}_alt_pb"], np.int32),
                max_fanout=int(max_fan[i]), route="dense",
                seg=np.asarray(d[f"r{i}_alt_seg"], np.int32),
                n_rows=alt_rows, real_pairs=alt_real)
        rounds.append(rnd)
    take = np.asarray(d["take"], np.int64) if s["has_take"] else None
    a_coords = np.asarray(d["a_coords"], np.int64)
    b_coords = np.asarray(d["b_coords"], np.int64)
    return SpgemmPlan(
        backend=str(d["backend"]), platform=str(d["platform"]),
        k=s["k"], a_nnzb=s["a_nnzb"], b_nnzb=s["b_nnzb"], join=join,
        rounds=rounds, take=take, batch=bool(s["batch"]),
        round_size=None if s["round_size"] < 0 else s["round_size"],
        split_fanout=None if s["split_fanout"] < 0 else s["split_fanout"],
        fingerprint=fingerprint,
        _a_coords=a_coords if len(a_coords) else None,
        _b_coords=b_coords if len(b_coords) else None)
