"""Symbolic phase: output-structure join + round bucketing (C5, C6 -- host side).

The reference builds `m2_index: rowB -> [colsB]` then joins A's blocks against
it with hash maps (sparse_matrix_mult.cu:141-156), producing per-output-tile
lists of inner block coordinates; the round packer (:167-226) then memcpys
tile pairs into an 8 GB staging buffer in rounds of <= 500 output keys.

Here the join is a vectorized sorted merge-join over the (already sorted)
block-coordinate arrays -- O(nnzb + pairs) numpy, no hashing -- and "packing"
is just index arithmetic: the numeric phase gathers tiles in HBM by index, so
no staging copy exists.  Rounds become fixed-shape (num_keys, max_pairs)
buckets, padded with a sentinel index that points at an all-zero tile
(mulmod(0, x) == 0 and addmod(acc, 0) == acc, so padding is exact) -- this is
how dynamic sparsity meets XLA's static shapes (SURVEY.md section 7).

Ordering contract (parity-critical, SURVEY.md section 2.9): each output key's
pair list is ordered by ascending inner block-coordinate j, which is exactly
the order the reference's sorted-map traversal produces.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class JoinResult:
    """Output structure of A x B, in CSR-over-sorted-keys form.

    keys     : (num_keys, 2) int64, sorted lexicographically -- output tile coords.
    pair_ptr : (num_keys + 1,) int64 -- segment boundaries into pair_a/pair_b.
    pair_a   : (total_pairs,) int32 -- A tile slab indices, per key in j-ascending order.
    pair_b   : (total_pairs,) int32 -- B tile slab indices, aligned with pair_a.
    """

    keys: np.ndarray
    pair_ptr: np.ndarray
    pair_a: np.ndarray
    pair_b: np.ndarray

    @property
    def num_keys(self) -> int:
        return len(self.keys)

    @property
    def fanouts(self) -> np.ndarray:
        return np.diff(self.pair_ptr)


def symbolic_join(a_coords: np.ndarray, b_coords: np.ndarray) -> JoinResult:
    """Structure join: which (A-tile, B-tile) pairs feed which output tile.

    Both coord arrays must be lexicographically sorted by (row, col) --
    the BlockSparseMatrix invariant.
    """
    empty = JoinResult(
        keys=np.zeros((0, 2), np.int64),
        pair_ptr=np.zeros(1, np.int64),
        pair_a=np.zeros(0, np.int32),
        pair_b=np.zeros(0, np.int32),
    )
    if len(a_coords) == 0 or len(b_coords) == 0:
        return empty

    b_rows = b_coords[:, 0]  # sorted (lex order on (row, col))
    # For each A block (i, j): B blocks with row == j form the contiguous
    # range [lo, hi) in the sorted B slab.
    a_cols = a_coords[:, 1]
    lo = np.searchsorted(b_rows, a_cols, side="left")
    hi = np.searchsorted(b_rows, a_cols, side="right")
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return empty

    # Segment-expand: pair stream in A-traversal order (sorted (i, j)), each A
    # block contributing its B row-range in ascending-c order.
    a_slot = np.repeat(np.arange(len(a_coords), dtype=np.int64), counts)
    seg_start = np.concatenate(([0], np.cumsum(counts)[:-1]))
    offs = np.arange(total, dtype=np.int64) - np.repeat(seg_start, counts)
    b_slot = np.repeat(lo, counts) + offs

    out_r = a_coords[a_slot, 0]
    out_c = b_coords[b_slot, 1]

    # Stable sort by output key: within a key, the stream order is ascending
    # inner-coordinate j (A sorted by (i, j)), which stability preserves.
    order = np.lexsort((out_c, out_r))
    out_r, out_c = out_r[order], out_c[order]
    a_slot, b_slot = a_slot[order], b_slot[order]

    key_change = np.empty(total, dtype=bool)
    key_change[0] = True
    key_change[1:] = (out_r[1:] != out_r[:-1]) | (out_c[1:] != out_c[:-1])
    key_starts = np.flatnonzero(key_change)
    keys = np.stack([out_r[key_starts], out_c[key_starts]], axis=1)
    pair_ptr = np.append(key_starts, total).astype(np.int64)

    return JoinResult(keys=keys, pair_ptr=pair_ptr,
                      pair_a=a_slot.astype(np.int32), pair_b=b_slot.astype(np.int32))


@dataclass
class Round:
    """One fixed-shape numeric launch: <= round_size keys, all padded to the
    same fanout class.  The reference's 500-key round (sparse_matrix_mult.cu:181-185)
    generalized to (pow-2 key count) x (pow-2 fanout) shape classes so the jit
    cache stays small."""

    key_index: np.ndarray  # (n,) int64 -- positions into JoinResult.keys
    pa: np.ndarray         # (K_pad, P) int32 -- A slab indices (sentinel-padded)
    pb: np.ndarray         # (K_pad, P) int32


def _ceil_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length() if x > 1 else 1


def plan_rounds(join: JoinResult, a_sentinel: int, b_sentinel: int,
                round_size: int = 512) -> list[Round]:
    """Bucket output keys by fanout class and chop into fixed-shape rounds.

    a_sentinel/b_sentinel: index of the appended all-zero tile in each slab.
    Padding both the pair axis (to the fanout class) and the key axis (to a
    pow-2 <= round_size) keeps the set of compiled shapes logarithmic.
    """
    rounds: list[Round] = []
    if join.num_keys == 0:
        return rounds
    fan = join.fanouts
    classes = np.array([_ceil_pow2(int(f)) for f in fan])
    for cls in np.unique(classes):
        members = np.flatnonzero(classes == cls)
        P = int(cls)
        for start in range(0, len(members), round_size):
            chunk = members[start : start + round_size]
            K = len(chunk)
            K_pad = min(_ceil_pow2(K), round_size)
            pa = np.full((K_pad, P), a_sentinel, dtype=np.int32)
            pb = np.full((K_pad, P), b_sentinel, dtype=np.int32)
            for row, ki in enumerate(chunk):
                s, e = join.pair_ptr[ki], join.pair_ptr[ki + 1]
                pa[row, : e - s] = join.pair_a[s:e]
                pb[row, : e - s] = join.pair_b[s:e]
            rounds.append(Round(key_index=chunk, pa=pa, pb=pb))
    return rounds
