"""Pallas-grid MXU limb kernel: field-mode SpGEMM at systolic-array rates.

The XLA limb path (ops/mxu_spgemm.py) proves the method -- exact uint64
arithmetic mod (2^64-1) via 7-bit limb convolutions computed as one batched
int8 matmul -- but XLA lowers the per-key batched matmuls at ~250 us each,
~11x below the reference kernel's throughput (round-2 VERDICT #1).  This
kernel is the same arithmetic placed directly on the MXU by a Pallas grid:

  * grid = (keys, pair_blocks): scalar-prefetched pair indices pa/pb drive
    the BlockSpec index maps, exactly like the VPU exact kernel
    (ops/pallas_spgemm.py) -- tiles stream HBM -> VMEM per step with no
    host packing;
  * each step loads R tile pairs, splits them into N_LIMBS=10 planes of
    7 bits IN-KERNEL (VPU shifts/masks -- no 2.5x HBM blowup from
    precomputed limb slabs), lays them out as one (10k, R*k) x (R*k, 10k)
    bf16 matmul, and accumulates the f32 MXU product into an int32 VMEM
    scratch.  bf16 holds 0..127 exactly (8-bit mantissa) and each f32 dot
    entry is <= 127^2 * R*k < 2^24, so every step is exact; the int32
    scratch is exact for 127^2 * P*k < 2^31 (P*k <= 2^17, enforced by the
    caller -- same bound as the XLA path);
  * on the last pair block, a VPU epilogue splits every limb-product block
    into 16-bit pieces at its 2^(7d mod 64) weight (2^64 === 1 mod 2^64-1)
    and sums them into EIGHT carry-free uint32 limb planes (each sum stays
    < ~2^22: no wraps, no carry compares) written as the kernel output; the
    final normalize / pack / mod-(2^64-1) fold runs OUTSIDE the kernel as
    plain vectorized XLA over all keys.

The split point is deliberate: composing the carry-normalize + 32-bit pack
stages after the piece sums inside one Mosaic kernel miscompiles on this
toolchain (each stage is bit-exact in isolation and the composition is not
-- an empirically bisected Mosaic codegen instability; see
tests/test_pallas_mxu.py for the pinned regression).  The carry-free piece
sums are the verified-good graph, so the kernel ends there.

Semantics: clean mod-(2^64-1) "field mode" (associative); bit-exact vs the
reference's wrap-then-mod fold whenever the hybrid dispatcher's
safe_exact_bound proof holds (ops/mxu_spgemm.py docstring).

Reference equivalent: matrix_multiplyKernel (sparse_matrix_mult.cu:44-66),
the reference's perf-critical component.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spgemm_tpu.ops import u64
from spgemm_tpu.utils import jaxcompat
from spgemm_tpu.ops.mxu_spgemm import N_LIMBS
from spgemm_tpu.ops.symbolic import accept_round_stack


def _limb_planes_bf16(hi, lo, n_limbs: int = N_LIMBS):
    """n_limbs bf16 planes of 7 bits each -- mxu_spgemm.limbs7, bf16 cast."""
    from spgemm_tpu.ops.mxu_spgemm import limbs7  # noqa: PLC0415

    return limbs7(hi, lo, n_limbs, jnp.bfloat16)


def _piece_sums(S, k: int, la_limbs: int = N_LIMBS, lb_limbs: int = N_LIMBS):
    """(La*k, Lb*k) int32 limb products -> 8 carry-free uint32 limb planes.

    Every (la, lb) block carries weight 2^(7(la+lb) mod 64) (2^64 === 1 mod
    2^64-1).  Each block value s < 2^31 splits into 16-bit pieces at its
    weight's (q, r) = divmod(sh, 16) position; piece sums accumulate in
    uint32 with no possible wrap (300 pieces x 2^16 < 2^26), so the graph
    contains no carry compares -- the part of the fold Mosaic compiles
    correctly (see module docstring).
    """
    limbs = [jnp.zeros((k, k), jnp.uint32) for _ in range(8)]
    for la in range(la_limbs):
        for lb in range(lb_limbs):
            s = S[la * k:(la + 1) * k, lb * k:(lb + 1) * k]
            _accum_piece(limbs, s, la, lb)
    return limbs


def _accum_piece(limbs, s, la: int, lb: int) -> None:
    """Accumulate one (la, lb) limb-product block into the 8 carry-free
    16-bit-piece sums, at weight 2^(7(la+lb) mod 64).  Shape-agnostic (jnp
    broadcasting) -- the single definition shared by the in-kernel epilogue
    (_piece_sums) and the batched XLA one (piece_sums_batched)."""
    M16 = jnp.uint32(0xFFFF)
    sh = 7 * (la + lb)
    if sh >= 64:
        sh -= 64  # 2^64 === 1 (mod 2^64-1)
    q, r = divmod(sh, 16)
    s = s.astype(jnp.uint32)
    limbs[q] = limbs[q] + ((s << r) & M16)
    if r == 0:
        limbs[q + 1] = limbs[q + 1] + (s >> 16)
    else:
        limbs[q + 1] = limbs[q + 1] + ((s >> (16 - r)) & M16)
        limbs[q + 2] = limbs[q + 2] + (s >> (32 - r))


def piece_sums_batched(S, k: int, La: int, Lb: int):
    """(K, La*k, Lb*k) int32 raw limb products -> 8 (K, k, k) uint32 planes.

    The XLA-side twin of the in-kernel _piece_sums, for the raw_epilogue
    path: one reshape/transpose turns every (la, lb) block access into a
    leading-axis index (no per-key lane slicing -- the relayout is one
    batched transpose over all keys, XLA's scheduling instead of ~La*Lb
    in-kernel lane extracts per key).  Same weights via the shared
    _accum_piece, bit-identical by test."""
    K = S.shape[0]
    blocks = (S.reshape(K, La, k, Lb, k)
               .transpose(1, 3, 0, 2, 4))              # (La, Lb, K, k, k)
    limbs = [jnp.zeros((K, k, k), jnp.uint32) for _ in range(8)]
    for la in range(La):
        for lb in range(Lb):
            _accum_piece(limbs, blocks[la, lb], la, lb)
    return limbs


def fold_piece_sums(limbs):
    """8 carry-free uint32 16-bit-piece sums -> (hi, lo) mod (2^64-1).

    Vectorized XLA post-pass (any leading batch shape): one carry-normalize
    sweep, pack into four u32 words, fold hi64 + lo64 (2^64 === 1).
    """
    M16 = jnp.uint32(0xFFFF)
    limbs = list(limbs)
    for i in range(7):
        limbs[i + 1] = limbs[i + 1] + (limbs[i] >> 16)
        limbs[i] = limbs[i] & M16
    acc = [limbs[2 * j] | (limbs[2 * j + 1] << 16) for j in range(4)]
    return u64.addmod_field(acc[3], acc[2], acc[1], acc[0])


def _kernel(pa_ref, pb_ref, *refs, k: int, R: int, blocks: int,
            La: int, Lb: int, raw: bool, w_pad: int = 0):
    # refs layout: ah x R, al x R, bh x R, bl x R, out[, scratch]
    ahs = [r[0] for r in refs[0 * R:1 * R]]            # each (k, k) uint32
    als = [r[0] for r in refs[1 * R:2 * R]]
    bhs = [r[0] for r in refs[2 * R:3 * R]]
    bls = [r[0] for r in refs[3 * R:4 * R]]
    out_ref = refs[4 * R]   # raw: (1, La*k, Lb*k) int32; else (1, 8, k, k) u32
    acc_ref = None if raw else refs[4 * R + 1]           # (La*k, Lb*k) int32

    pb = pl.program_id(1)

    # A limbs: plane la is (i, j) -> rows (la, i); R pairs side by side in j.
    a_cat = jnp.concatenate(
        [jnp.concatenate(_limb_planes_bf16(h, l, La), axis=0)   # (La*k, k)
         for h, l in zip(ahs, als)], axis=1)                    # (La*k, R*k)
    # B limbs: plane lb is (j, n) -> cols (lb, n); R pairs stacked in j.
    b_cat = jnp.concatenate(
        [jnp.concatenate(_limb_planes_bf16(h, l, Lb), axis=1)   # (k, Lb*k)
         for h, l in zip(bhs, bls)], axis=0)                    # (R*k, Lb*k)
    if raw and w_pad > Lb * k:
        # pad the lane dim to a 128 multiple so the raw output block has a
        # Mosaic-legal minor dim on chip (zero columns add nothing to the
        # dot); sliced off again in the XLA epilogue
        b_cat = jnp.concatenate(
            [b_cat, jnp.zeros((R * k, w_pad - Lb * k), b_cat.dtype)], axis=1)

    # The MXU step: every one of the La*Lb limb-pair blocks in one dot.
    s = jax.lax.dot_general(a_cat, b_cat, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)

    if raw:
        # the output block IS the accumulator: no scratch, no in-kernel
        # epilogue -- the piece sums run batched in XLA outside
        @pl.when(pb == 0)
        def _init_raw():
            out_ref[0] = jnp.zeros_like(out_ref[0])

        out_ref[0] = out_ref[0] + s.astype(jnp.int32)
        return

    @pl.when(pb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += s.astype(jnp.int32)

    @pl.when(pb == blocks - 1)
    def _done():
        limbs = _piece_sums(acc_ref[...], k, La, Lb)
        for i in range(8):
            out_ref[0, i] = limbs[i]


def limbs_for_bound(val_bound: int | None) -> int:
    """Limbs needed to represent values <= val_bound (7 bits per limb)."""
    if val_bound is None:
        return N_LIMBS
    return min(N_LIMBS, max(1, -(-int(val_bound).bit_length() // 7)))


@accept_round_stack
@partial(jax.jit,
         static_argnames=("interpret", "a_limbs", "b_limbs", "pair_width",
                          "raw_epilogue"))
def numeric_round_mxu_pallas(a_hi, a_lo, b_hi, b_lo, pa, pb, interpret=None,
                             a_limbs: int = N_LIMBS, b_limbs: int = N_LIMBS,
                             pair_width: int | None = None,
                             raw_epilogue: bool = False):
    """Same contract as ops.spgemm.numeric_round_impl, field-mode semantics.

    a_*/b_* : (nnzb + 1, k, k) uint32 slabs (sentinel zero tile last).
    pa, pb  : (K, P) int32 slab indices, sentinel-padded (zero tiles
              contribute exactly 0 in field mode).
    a_limbs/b_limbs: per-operand limb counts (limbs_for_bound of the proven
              value bound) -- 32-bit-bounded operands need 5x5 limb blocks
              instead of 10x10, a 4x cut in dot flops and epilogue work.
    pair_width: requested pairs per grid step (R), clamped to the
              bf16-exactness cap 1024/k; None = the tuned default 8.
    raw_epilogue: skip the in-kernel piece-sum epilogue (the measured
              ~750 us/key lane-slicing cost, ROUND3_NOTES finding 2) and
              output the raw (La*k, Lb*k) int32 accumulator per key; the
              piece sums then run batched in XLA (piece_sums_batched).
              Trades La*Lb/8 x more output HBM traffic for zero in-kernel
              lane slicing -- at 3x3 limbs the output is ~= the same size,
              so this should win there; the sweep decides.
    Returns (out_hi, out_lo): (K, k, k) uint32, residues mod 2^64-1.

    A stacked (R, K, P) pa/pb is also accepted and returns (R, K, k, k)
    (symbolic.accept_round_stack -- round-batched dispatch).
    """
    K, P = pa.shape
    k = a_hi.shape[-1]
    La, Lb = a_limbs, b_limbs
    if P * k > 1 << 17:
        raise ValueError(f"P*k = {P * k} exceeds the int32-exact bound 2^17")
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"

    # pair-block width: R*k is the MXU contraction size; 127^2 * R*k < 2^24
    # keeps each f32 dot exact (R*k <= 1024, the hard cap).  The default 8
    # was tuned pre-outage; the round-3 sweep showed the epilogue amortizing
    # with MORE pairs per launch (7.0 GFLOP/s at (K=64, P=256) vs 1.4 at
    # (256, 16)), so pair_width (static; SPGEMM_TPU_MXU_R via the engine's
    # _select_numeric, swept by benchmarks/kernel_sweep.py) exposes the
    # exactness-capped range.
    R = max(1, min(pair_width or 8, P, 1024 // max(k, 1)))
    P_pad = -(-P // R) * R
    if P_pad != P:
        a_sent = jnp.int32(a_hi.shape[0] - 1)
        b_sent = jnp.int32(b_hi.shape[0] - 1)
        pa = jnp.concatenate(
            [pa, jnp.full((K, P_pad - P), a_sent, jnp.int32)], axis=1)
        pb = jnp.concatenate(
            [pb, jnp.full((K, P_pad - P), b_sent, jnp.int32)], axis=1)
    blocks = P_pad // R

    def a_map(r):
        return lambda kk, pblk, pa, pb: (pa[kk, pblk * R + r], 0, 0)

    def b_map(r):
        return lambda kk, pblk, pa, pb: (pb[kk, pblk * R + r], 0, 0)

    tile_spec_a = [pl.BlockSpec((1, k, k), a_map(r)) for r in range(R)]
    tile_spec_b = [pl.BlockSpec((1, k, k), b_map(r)) for r in range(R)]
    if raw_epilogue:
        # lane dim padded to a 128 multiple (Mosaic minor-dim tiling; the
        # ADVICE r4 on-chip concern) -- zero columns, sliced off post-kernel
        w_pad = -(-(Lb * k) // 128) * 128
        out_spec = pl.BlockSpec((1, La * k, w_pad),
                                lambda kk, pblk, pa, pb: (kk, 0, 0))
        out_shape = [jax.ShapeDtypeStruct((K, La * k, w_pad), jnp.int32)]
        scratch = []
    else:
        w_pad = 0
        out_spec = pl.BlockSpec((1, 8, k, k),
                                lambda kk, pblk, pa, pb: (kk, 0, 0, 0))
        out_shape = [jax.ShapeDtypeStruct((K, 8, k, k), jnp.uint32)]
        scratch = [pltpu.VMEM((La * k, Lb * k), jnp.int32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # pa, pb
        grid=(K, blocks),
        in_specs=tile_spec_a + tile_spec_a + tile_spec_b + tile_spec_b,
        out_specs=[out_spec],
        scratch_shapes=scratch,
    )
    (out,) = pl.pallas_call(
        partial(_kernel, k=k, R=R, blocks=blocks, La=La, Lb=Lb,
                raw=raw_epilogue, w_pad=w_pad),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=jaxcompat.CompilerParams(
            # pair axis must be sequential (scratch accumulation); the key
            # axis revisits the scratch too, so both stay "arbitrary"
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
    )(pa, pb,
      *([a_hi] * R), *([a_lo] * R), *([b_hi] * R), *([b_lo] * R))
    # final fold outside the kernel (see module docstring), batched over keys
    if raw_epilogue:
        out = out[:, :, :Lb * k]
        return fold_piece_sums(piece_sums_batched(out, k, La, Lb))
    return fold_piece_sums([out[:, i] for i in range(8)])
