"""Structure-keyed LRU cache for SpgemmPlan (ops/spgemm.plan).

The host-side symbolic planner (join + round bucketing + assembly
permutation) is deterministic in the operands' *structure* plus the plan
parameters -- identical sparsity patterns re-plan to identical rounds.
KokkosKernels-style SpGEMM (Deveci et al.) treats symbolic-structure reuse
across multiplies as a first-class optimization; here it is a content
fingerprint over the block-coordinate arrays, so repeated inputs (the
serving scenario, bench re-runs, failover retries) skip the planner
entirely.

Estimator-routed plans (ops/estimate) cache under the SAME structure
fingerprint while their exact symbolic join is still deferred: the cached
entry is the plan OBJECT, so when SpgemmPlan.ensure_exact() lands the join
the entry is promoted in place -- every later hit serves the exact plan
with no re-keying and no second planner run.

jax-free by design: this module is imported by the CLI `knobs` listing and
by planner WORKER threads (chain.py plan-ahead), neither of which may
touch a backend (the BKD contract -- plans are pure numpy).

Pool sharing: plans are host-side index arrays with no device placement,
so one cache serves every slice executor of the spgemmd device pool
concurrently (the lock below is the whole synchronization story) -- a
structure planned on one slice is a hit on every other, which is exactly
the amortization the pool wants.  Placement-dependent state lives in
ops/delta, whose keys are placement-qualified (ops/spgemm._delta_key).

Knobs (central registry, utils/knobs.py):
  SPGEMM_TPU_PLAN_CACHE     0|1 (default 1) -- memoization on/off.
  SPGEMM_TPU_PLAN_CACHE_CAP int >= 1 (default 32) -- LRU capacity; plans
    hold the padded pa/pb index arrays (~pair count x 8 bytes), so the cap
    bounds host RAM, not correctness.

Live stats (`stats()`) are surfaced by `spgemm_tpu.cli knobs [--json]`
next to the knob rows; the engine additionally mirrors hit/miss events
into the ENGINE timer registry (`plan_cache_hits`/`plan_cache_misses`
counters) so they flow into bench detail and suite rows per run.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

from spgemm_tpu.utils import knobs

_LOCK = threading.Lock()
_CACHE: "OrderedDict[str, object]" = OrderedDict()  # spgemm-lint: guarded-by(_LOCK)
_STATS = {"hits": 0, "misses": 0, "evictions": 0}  # spgemm-lint: guarded-by(_LOCK)

# admission-time structure book (the serve batching group key): input
# stat-signature -> chain structure fingerprint, recorded by the executor
# the first time a chain is actually read, looked up by the daemon's
# admission path so the queue can GROUP same-structure jobs without
# planning (or even reading) anything.  Bounded LRU like the placement
# price book -- an evicted entry just means the next submit of that
# folder admits ungrouped (first-contact behavior) until an executor
# re-records it.
STRUCT_CAP = 4096
_STRUCTS: "OrderedDict[str, str]" = OrderedDict()  # spgemm-lint: guarded-by(_LOCK)


def enabled() -> bool:
    """SPGEMM_TPU_PLAN_CACHE=0|1 (default 1)."""
    return knobs.get("SPGEMM_TPU_PLAN_CACHE")


def capacity() -> int:
    """SPGEMM_TPU_PLAN_CACHE_CAP (default 32): LRU entry cap, re-read per
    put so tests/harnesses may resize mid-process."""
    return knobs.get("SPGEMM_TPU_PLAN_CACHE_CAP")


def hash_update(h, arr: np.ndarray) -> None:
    """Feed one array (shape + dtype + raw bytes) into an open hashlib
    digest -- THE shared content-hashing step: the whole-structure
    fingerprint below and ops/delta's per-tile-row digests both hash
    through this function, so the two surfaces can never drift on what
    "content" means (shape + dtype ride along so two different-shape
    arrays never collide through tobytes())."""
    arr = np.ascontiguousarray(arr)
    h.update(repr((arr.shape, str(arr.dtype))).encode())
    h.update(arr.tobytes())
    h.update(b"|")


def fingerprint(a_coords: np.ndarray, b_coords: np.ndarray,
                meta: tuple) -> str:
    """Content fingerprint of (operand structures, plan parameters).

    Hashes the raw coordinate bytes (via hash_update) plus the repr of
    the caller's parameter tuple (k, sentinels, backend, platform,
    round_size, batch flag, hybrid split threshold, jit-static knob
    vector).  sha256 over a few MB of coords is ~ms -- orders of magnitude
    under the join it saves."""
    h = hashlib.sha256()
    for arr in (a_coords, b_coords):
        hash_update(h, arr)
    h.update(repr(meta).encode())
    return h.hexdigest()


def chain_fingerprint(coords_list) -> str:
    """Content fingerprint of a whole chain's operand structures (the
    coords of every matrix, in chain order) -- the serve batching group
    key's value: two jobs whose chains share this fingerprint walk
    identical plan sequences (planning is deterministic in structure), so
    their multiplies can share plans and co-batch dispatches.  Pure
    structure: values never feed the hash, matching what the plan cache
    itself keys on."""
    h = hashlib.sha256()
    h.update(b"chain|")
    for coords in coords_list:
        hash_update(h, np.asarray(coords))
    return h.hexdigest()


def note_chain_structure(sig: str | None, fp: str) -> None:
    """Record folder stat-signature -> chain structure fingerprint
    (executor side, right after the chain is read; the signature is
    serve/placement.signature's, None when the folder was unreadable)."""
    if sig is None:
        return
    with _LOCK:
        _STRUCTS[sig] = fp
        _STRUCTS.move_to_end(sig)
        while len(_STRUCTS) > STRUCT_CAP:
            _STRUCTS.popitem(last=False)


def chain_structure(sig: str | None) -> str | None:
    """The recorded chain structure fingerprint for a folder signature,
    or None on first contact / content change / eviction (an ungroupable
    job simply runs solo -- grouping is an optimization, never a
    correctness input)."""
    if sig is None:
        return None
    with _LOCK:
        fp = _STRUCTS.get(sig)
        if fp is not None:
            _STRUCTS.move_to_end(sig)
        return fp


def tune_class_key(fp: str | None, device_kind: str) -> str | None:
    """The autotuner's structure-class key: the chain structure
    fingerprint's class signature (a 12-hex prefix -- classes group
    structures, they need not distinguish every folder) joined with the
    device kind the class's jobs run on (a vector tuned on a TPU slice
    says nothing about a CPU failover path).  None passes through: a
    first-contact job (no recorded structure) is never tuned."""
    if not fp:
        return None
    return f"{fp[:12]}@{device_kind or 'unknown'}"


def lookup(key: str):
    """Cached plan for key, or None; a hit moves the entry to MRU."""
    with _LOCK:
        plan = _CACHE.get(key)
        if plan is None:
            _STATS["misses"] += 1
            return None
        _CACHE.move_to_end(key)
        _STATS["hits"] += 1
        return plan


def store(key: str, plan) -> int:
    """Insert (or refresh) a plan; evicts LRU entries past the cap.
    Returns the number of entries evicted -- the caller (ops/spgemm)
    mirrors it into the ENGINE `plan_cache_evictions` counter, the same
    split as the hit/miss pair (eviction pressure was invisible before
    delta fingerprint retention made it matter)."""
    cap = capacity()
    evicted = 0
    with _LOCK:
        _CACHE[key] = plan
        _CACHE.move_to_end(key)
        while len(_CACHE) > cap:
            _CACHE.popitem(last=False)
            evicted += 1
        _STATS["evictions"] += evicted
    return evicted


def entries() -> list:
    """Snapshot of the live (key, plan) pairs, LRU-first.  The warm-start
    flush (ops/warmstore) walks it to persist plans not yet on disk; the
    list is a copy, so the walker holds no lock while serializing."""
    with _LOCK:
        return list(_CACHE.items())


def baseline() -> dict:
    """Counter snapshot for scope-diffing (see stats(since=...)): a
    caller that wants per-job (not process-lifetime) hit/miss/eviction
    figures captures a baseline before the work and diffs after -- the
    PhaseScope discipline, applied to the cache counters.  spgemmd
    stashes one per job so a second job's detail never inherits the
    first's totals."""
    with _LOCK:
        return dict(_STATS)


def stats(since: dict | None = None) -> dict:
    """Live per-process cache state, for `spgemm_tpu.cli knobs` and bench
    detail: hits/misses since process start (or the last clear), current
    entry count, and the configured knob values.

    since: an earlier baseline() snapshot -- the hit/miss/eviction
    figures then report the DELTA since that scope opened (entry count
    and knob values stay live)."""
    base = since or {}
    with _LOCK:
        return {
            "hits": _STATS["hits"] - base.get("hits", 0),
            "misses": _STATS["misses"] - base.get("misses", 0),
            "evictions": _STATS["evictions"] - base.get("evictions", 0),
            "entries": len(_CACHE),
            "capacity": capacity(),
            "enabled": enabled(),
        }


def clear() -> None:
    """Drop every entry and zero the stats (tests, A/B harnesses)."""
    with _LOCK:
        _CACHE.clear()
        _STRUCTS.clear()
        _STATS["hits"] = _STATS["misses"] = _STATS["evictions"] = 0
