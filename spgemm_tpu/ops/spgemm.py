"""SpGEMM engine: the TPU-native equivalent of the reference's helper() (L2).

Two phases, mirroring the reference's design but not its data movement:

  1. symbolic (host, ops/symbolic.py): sorted merge-join -> output structure +
     fixed-shape index rounds.  The reference's equivalent is its hash-map join
     plus the 8 GB host staging copy (sparse_matrix_mult.cu:141-226); here no
     tile is ever copied on host -- tiles live in HBM and the numeric phase
     gathers them by index.
  2. numeric (device, this file): for each round, gather (A, B) tile pairs and
     fold them into output tiles with the exact wrap-then-mod u64 arithmetic
     of SURVEY.md section 2.9, sequential over (pair, j) to preserve the
     reference's accumulation order (matrix_multiplyKernel,
     sparse_matrix_mult.cu:44-66).

The XLA path below is the always-available implementation; ops/pallas_spgemm.py
provides the Pallas TPU kernel for the same contract (selected via backend=).
"""

from __future__ import annotations

import logging
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spgemm_tpu.obs import events as obs_events
from spgemm_tpu.obs import profile as obs_profile
from spgemm_tpu.ops import estimate, plancache, u64, warmstore
from spgemm_tpu.utils import failpoints, knobs
from spgemm_tpu.ops.symbolic import (SpgemmPlan, accept_round_stack,
                                     assembly_permutation, plan_rounds,
                                     slice_join, symbolic_join)
from spgemm_tpu.utils.backend_probe import host_only
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix

log = logging.getLogger("spgemm_tpu.spgemm")


def round_batch_enabled() -> bool:
    """SPGEMM_TPU_ROUND_BATCH=0|1 (default 1): whole-engine A/B of the
    round-batched dispatch path -- 1 = one mega-launch per (fanout class,
    kernel choice) with the fused single-gather assembly, 0 = the legacy
    one-launch-per-round loop with per-round output slicing.  Both produce
    identical bits; the knob exists so the dispatch/assembly overhead win
    is measurable in one flag flip (bench.py detail.phases_s/dispatches)."""
    return knobs.get("SPGEMM_TPU_ROUND_BATCH")


def _batch_entries(k: int) -> int:
    """Per-mega-launch key*pair entry budget: bounds the XLA backend's
    gather materialization (4 planes of entries * k * k uint32, ~1 GB at
    k=32) while leaving every fanout class at realistic scales in one
    launch.  Scales with 1/k^2 because the per-entry footprint scales with
    k^2; the SMEM budget (max_entries) still applies on top for Pallas."""
    return max(1024, (1 << 26) // (k * k))


def pack_tiles(m: BlockSparseMatrix, device=None):
    """Tile slab -> device (hi, lo) uint32 planes with an all-zero sentinel
    tile appended at index nnzb (padding target for the round planner).

    device: target placement -- a direct host->device transfer (the default
    placement otherwise; an explicit non-default device must NOT stage
    through device 0)."""
    k = m.k
    slab = np.concatenate([m.tiles, np.zeros((1, k, k), np.uint64)], axis=0)
    hi, lo = u64.u64_to_hilo(slab)
    if device is not None:
        return jax.device_put(hi, device), jax.device_put(lo, device)
    return jnp.asarray(hi), jnp.asarray(lo)


@accept_round_stack
def numeric_round_impl(a_hi, a_lo, b_hi, b_lo, pa, pb):
    """One fixed-shape numeric round (unjitted impl -- wrapped by _numeric_round
    and by parallel/rowshard's shard_map).

    a_*/b_* : (nnzb + 1, k, k) uint32 tile slabs (sentinel zero tile last).
    pa, pb  : (K, P) int32 slab indices; per-key pair lists in j-ascending
              order, padded with the sentinel.
    Returns (out_hi, out_lo): (K, k, k) uint32.

    A stacked (R, K, P) pa/pb is also accepted and returns (R, K, k, k)
    (symbolic.accept_round_stack -- round-batched dispatch).

    The fold runs sequentially over the flattened (pair, j) axis -- P*k steps
    of vectorized (K, k, k) limb arithmetic -- because addmod is not
    associative (SURVEY.md section 2.9).  Sentinel pairs contribute exactly 0.
    """
    K, P = pa.shape
    k = a_hi.shape[-1]

    ah, al = a_hi[pa], a_lo[pa]  # (K, P, k, k)
    bh, bl = b_hi[pb], b_lo[pb]

    # Walk order: for pair p, for j in 0..k-1.  The pair axis is a fori_loop
    # (dynamic-index slice per step); the j fold is unrolled at reference
    # scales (k <= 32 -- each loop body is ~k fused vector MACs instead of
    # one) and a fori_loop beyond them (a 128-wide unrolled MAC chain is a
    # compile bomb, and k > 32 is already outside the perf-critical regime
    # the reference can even reach).
    ath = jnp.transpose(ah, (1, 0, 2, 3))  # (P, K, ty, j)
    atl = jnp.transpose(al, (1, 0, 2, 3))
    bth = jnp.transpose(bh, (1, 0, 2, 3))  # (P, K, j, tx)
    btl = jnp.transpose(bl, (1, 0, 2, 3))

    def _mac_j(acc_h, acc_l, pah, pal, pbh, pbl, j):
        return u64.mac(
            acc_h, acc_l,
            jax.lax.dynamic_slice_in_dim(pah, j, 1, axis=2),
            jax.lax.dynamic_slice_in_dim(pal, j, 1, axis=2),
            jax.lax.dynamic_slice_in_dim(pbh, j, 1, axis=1),
            jax.lax.dynamic_slice_in_dim(pbl, j, 1, axis=1),
        )

    def body(p, acc):
        acc_h, acc_l = acc
        pah, pal = ath[p], atl[p]  # (K, k, k)
        pbh, pbl = bth[p], btl[p]
        if k <= 32:
            for j in range(k):
                acc_h, acc_l = u64.mac(
                    acc_h, acc_l,
                    pah[:, :, j : j + 1], pal[:, :, j : j + 1],
                    pbh[:, j : j + 1, :], pbl[:, j : j + 1, :],
                )
        else:
            acc_h, acc_l = jax.lax.fori_loop(
                0, k, lambda j, a: _mac_j(*a, pah, pal, pbh, pbl, j),
                (acc_h, acc_l))
        return acc_h, acc_l

    zero = jnp.zeros((K, k, k), jnp.uint32)
    out_h, out_l = jax.lax.fori_loop(0, P, body, (zero, zero))
    return out_h, out_l


# compile-accounted jit (obs/profile): first contact per shape signature
# goes through the AOT surface so compile wall + cost/memory analyses land
# in the profiling layer; bit-identical dispatch either way, and a plain
# jit call under SPGEMM_TPU_OBS_TRACE=0
_numeric_round = obs_profile.ProfiledJit("numeric_round",
                                         jax.jit(numeric_round_impl))


def numeric_round_dense_impl(a_hi, a_lo, b_hi, b_lo, pa, pb, seg,
                             acc_h, acc_l):
    """Dense-route numeric round: index-ordered segmented fold over one
    contiguous pair stream (SPGEMM_TPU_ACCUM_ROUTE, ops/symbolic dense
    Round layout).

    a_*/b_*    : (nnzb + 1, k, k) uint32 tile slabs (sentinel zero last).
    pa, pb     : (L,) int32 slab indices -- the class chunk's per-key pair
                 lists concatenated in key order (each list j-ascending),
                 sentinel-padded to the fine stream ladder (L % 8 == 0).
    seg        : (L,) int32 output row per stream slot; pad slots point at
                 the scratch row n_rows.
    acc_h/acc_l: (n_rows + 1, k, k) uint32 zeros -- the dense accumulator
                 planes; the last row is the pad-slot scratch, dropped on
                 return.
    Returns (out_hi, out_lo): (n_rows, k, k) uint32.

    The walk is strictly left-to-right along the stream, and within each
    pair strictly j-ascending -- every output row's segment is contiguous,
    so its MAC sequence is EXACTLY the ladder kernel's (pair, j) order for
    that key: no reduction is ever reordered (FLD-clean, no escape), and
    ladder/dense are bit-identical by construction.  Pad slots MAC the
    sentinel zero tiles into the scratch row (mulmod(0, x) == 0,
    addmod(acc, 0) == acc), so they cannot touch a real row.  Unlike the
    ladder kernel there is no per-key padding: the padded-MAC tax is the
    stream tail only (< 1/8).
    """
    k = a_hi.shape[-1]
    L = pa.shape[0]

    def _mac_j(ch, cl, th, tl, uh, ul, j):
        return u64.mac(
            ch, cl,
            jax.lax.dynamic_slice_in_dim(th, j, 1, axis=1),
            jax.lax.dynamic_slice_in_dim(tl, j, 1, axis=1),
            jax.lax.dynamic_slice_in_dim(uh, j, 1, axis=0),
            jax.lax.dynamic_slice_in_dim(ul, j, 1, axis=0),
        )

    def one_pair(i, acc_h, acc_l):
        ia, ib, row = pa[i], pb[i], seg[i]
        th, tl = a_hi[ia], a_lo[ia]  # (k, k)
        uh, ul = b_hi[ib], b_lo[ib]
        ch = jax.lax.dynamic_index_in_dim(acc_h, row, 0, keepdims=False)
        cl = jax.lax.dynamic_index_in_dim(acc_l, row, 0, keepdims=False)
        # same j-walk as the ladder kernel: unrolled at reference scales,
        # a fori_loop beyond them (identical rationale -- compile size)
        if k <= 32:
            for j in range(k):
                ch, cl = u64.mac(ch, cl,
                                 th[:, j : j + 1], tl[:, j : j + 1],
                                 uh[j : j + 1, :], ul[j : j + 1, :])
        else:
            ch, cl = jax.lax.fori_loop(
                0, k, lambda j, c: _mac_j(*c, th, tl, uh, ul, j), (ch, cl))
        return (jax.lax.dynamic_update_index_in_dim(acc_h, ch, row, 0),
                jax.lax.dynamic_update_index_in_dim(acc_l, cl, row, 0))

    # 4-pair blocks amortize the loop step overhead; the stream ladder
    # guarantees L % 8 == 0 (symbolic._stream_pad), so no remainder exists.
    # Pairs run sequentially inside the block -- the unroll changes loop
    # bookkeeping only, never the fold order.
    def body(s, acc):
        acc_h, acc_l = acc
        for u in range(4):
            acc_h, acc_l = one_pair(s * 4 + u, acc_h, acc_l)
        return acc_h, acc_l

    acc_h, acc_l = jax.lax.fori_loop(0, L // 4, body, (acc_h, acc_l))
    return acc_h[:-1], acc_l[:-1]


_numeric_dense = obs_profile.ProfiledJit("numeric_round_dense",
                                         jax.jit(numeric_round_dense_impl))


def _assemble_impl(outs_h, outs_l, take):
    """Round-batched assembly: pad-concat the (whole, padded) round outputs,
    append one zero row, and gather both planes through the precomputed
    inverse permutation (ops/symbolic.assembly_permutation) -- one executable
    for the entire epilogue, replacing the legacy per-round slice + concat
    chain.  Bit-identical: every real key reads its own output row; the
    sentinel slot reads the appended zero row."""
    k = outs_h[0].shape[-1]
    zero = jnp.zeros((1, k, k), jnp.uint32)
    cat_h = jnp.concatenate(list(outs_h) + [zero], axis=0)
    cat_l = jnp.concatenate(list(outs_l) + [zero], axis=0)
    return cat_h[take], cat_l[take]


_assemble = obs_profile.ProfiledJit("assembly_gather",
                                    jax.jit(_assemble_impl))


def _proof_fanout_cap(a_bound: int, b_bound: int, k: int) -> int | None:
    """Largest fanout for which mxu_spgemm.safe_exact_bound holds at these
    operand bounds (None = every fanout proves, no partition needed).  Used
    by round-batched hybrid planning to partition each fanout class at the
    proof threshold BEFORE merging, so kernel routing keeps the per-key
    granularity the per-round path had."""
    denom = a_bound * b_bound * k
    if denom == 0:
        return None  # zero operands: every product is 0, any fanout proves
    cap = ((1 << 64) - 2) // denom
    # safe_exact_bound treats fanout 0 as 1; a cap of 0 still partitions
    # correctly (everything lands in the unproven part)
    return cap if cap < (1 << 63) else None


def resolve_backend(backend: str | None, platform: str | None = None) -> str:
    """None -> 'pallas' on TPU, 'xla' elsewhere (the Pallas kernel runs in
    interpret mode on CPU, which is correct but slow -- tests opt in).

    platform None resolves from the live jax backend (a backend touch --
    main thread only); host-only callers pass the platform they resolved
    up front, same contract as crossover.gate_policy.

    Other values: 'mxu' = field-mode limb matmul on the systolic array
    (clean mod-(2^64-1) semantics, ops/pallas_mxu.py on TPU); 'hybrid' =
    per-ROUND choice within each multiply -- fanout classes whose
    bit-exactness proof holds run 'mxu', the rest run the exact kernel, and
    the mixed result is always reference-bit-exact."""
    if backend is not None:
        return backend
    if platform is None:
        platform = jax.devices()[0].platform
    return "pallas" if platform == "tpu" else "xla"


def _plan_budgets(backend: str, platform: str | None = None):
    """(max_entries, default_round_size) for a resolved backend -- THE
    single source of the per-backend round budgets, consumed by BOTH the
    plan side (ops/spgemm.plan, which must never touch a jax backend on
    planner worker threads) and the execute side (_select_numeric /
    _hybrid_setup), so the two can never drift.  Pure function of
    (backend, platform); platform matters only for mxu/hybrid (the Pallas
    MXU kernel exists on TPU only)."""
    if backend == "pallas":
        # Pallas rounds are bounded by SMEM-resident index arrays (SMEM is
        # ~1 MB and holds pa+pb, shipped (P, K) with P sublane-padded to
        # 8), not by gather materialization: merge key chunks into fewer,
        # bigger launches.  An explicit round_size still caps the key axis.
        return 64 * 1024, 8192
    if backend == "xla":
        return None, 512
    if backend == "mxu":
        return (64 * 1024, 8192) if platform == "tpu" else (None, 512)
    if backend == "hybrid":
        exact = "pallas" if platform == "tpu" else "xla"
        max_entries, default_rs = _plan_budgets(exact, platform)
        mxu_entries, _ = _plan_budgets("mxu", platform)
        # plan under the tighter budget so both kernels accept every round
        if mxu_entries is not None and (max_entries is None
                                        or mxu_entries < max_entries):
            max_entries = mxu_entries
        return max_entries, default_rs
    raise ValueError(f"unknown backend {backend!r}")


def _select_numeric(backend: str, a, b):
    """Resolve a concrete backend name to (numeric_fn, max_entries,
    default_round_size) for operands a, b (their val_bounds parameterize
    the MXU limb grids); budgets come from _plan_budgets."""
    if backend == "pallas":
        from spgemm_tpu.ops.pallas_spgemm import (  # noqa: PLC0415
            numeric_round_pallas, validate_vpu_config)

        # manual A/B hooks: SPGEMM_TPU_VPU_ALGO=vecj runs the whole engine
        # (CLI, bench) on the alternate kernel layout, SPGEMM_TPU_VPU_PB=N
        # on pair-axis blocking; defaults are the tuned values.  jit caches
        # per static value, so this costs nothing.  Validate at ENTRY: the
        # unsupported combinations die on TPU hardware with a bare
        # JaxRuntimeError deep inside Mosaic (round-5 VERDICT "What's weak"
        # #2), so the engine rejects them here with the knob named (the
        # registry validates value syntax, validate_vpu_config the
        # platform-legality of the combination).
        algo = knobs.get("SPGEMM_TPU_VPU_ALGO")
        pair_block = knobs.get("SPGEMM_TPU_VPU_PB")
        platform = jax.devices()[0].platform
        validate_vpu_config(algo, pair_block, platform=platform,
                            interpret=platform == "cpu")
        numeric = partial(numeric_round_pallas, algo=algo,
                          pair_block=pair_block)
        return (numeric, *_plan_budgets("pallas", platform))
    if backend == "xla":
        return (_numeric_round, *_plan_budgets("xla"))
    if backend == "mxu":
        # Pallas-grid MXU limb kernel on TPU (ops/pallas_mxu.py); the XLA
        # batched-matmul formulation elsewhere (it is the better CPU lowering
        # and the cross-check oracle for the kernel).
        platform = jax.devices()[0].platform
        if platform == "tpu":
            from spgemm_tpu.ops.pallas_mxu import (  # noqa: PLC0415
                limbs_for_bound, numeric_round_mxu_pallas)

            # proven value bounds shrink the limb grid (5x5 for 32-bit
            # values vs 10x10 unbounded): 4x less dot + epilogue work.
            # SPGEMM_TPU_MXU_R: whole-engine A/B of the pair width R, like
            # the VPU's ALGO/PB hooks above (static -> one jit cache entry
            # per value)
            numeric = partial(numeric_round_mxu_pallas,
                              a_limbs=limbs_for_bound(a.val_bound),
                              b_limbs=limbs_for_bound(b.val_bound),
                              pair_width=knobs.get("SPGEMM_TPU_MXU_R"))
            return (numeric, *_plan_budgets("mxu", platform))
        from spgemm_tpu.ops.mxu_spgemm import numeric_round_mxu  # noqa: PLC0415

        return (numeric_round_mxu, *_plan_budgets("mxu", platform))
    raise ValueError(f"unknown backend {backend!r}")


def _hybrid_setup(a, b, k):
    """Per-ROUND hybrid dispatch shared by the resident and out-of-core
    pipelines: rounds are bucketed by fanout class (plan_rounds) and the
    bit-exactness proof depends on the fanout, so each round independently
    runs MXU field mode when provably equal to the reference fold (no
    product or partial sum can reach 2^64-1 at that fanout) and the exact
    VPU/XLA kernel otherwise.  One huge-fanout key no longer forces the
    whole multiply off the MXU.  Every key is computed whole by one kernel,
    so the mixed result is bit-exact regardless of the split.

    a, b need only .val_bound.  Returns (numeric_exact, max_entries,
    default_rs, choose_numeric) where choose_numeric(rnd) ->
    (fn, used_mxu, proof_ok) -- see its docstring for the proof/routing
    distinction.

    A round goes MXU-ward only when BOTH gates pass: the bit-exactness
    proof (correctness) and -- under the 'auto' policy, the TPU default --
    a measured speed win at the round's shape (ops/crossover.py; round-3
    hardware data showed the proof-only gate routing provably-safe rounds
    to a kernel ~6x slower than the exact one).
    """
    from spgemm_tpu.ops import crossover  # noqa: PLC0415
    from spgemm_tpu.ops.mxu_spgemm import safe_exact_bound  # noqa: PLC0415
    from spgemm_tpu.ops.symbolic import _shape_class  # noqa: PLC0415

    platform = jax.devices()[0].platform
    exact_name = resolve_backend(None, platform)
    numeric_exact, _, _ = _select_numeric(exact_name, a, b)
    numeric_mxu, _, _ = _select_numeric("mxu", a, b)
    # proven-round exact kernel: under the same proof that licenses the MXU
    # route, both mod_max collapses are identity and the VPU kernel drops
    # them (u64.mac_nomod, 28 vs 36 ops/MAC) -- a strict op-subset of the
    # exact kernel, so no separate speed measurement is needed
    numeric_exact_proven = (partial(numeric_exact, no_mod=True)
                            if exact_name == "pallas" else numeric_exact)
    # the shared budget table already applies the tighter-of-both rule so
    # both kernels accept every round (and the plan side agrees)
    max_entries, default_rs = _plan_budgets("hybrid", platform)
    bounds_ok = a.val_bound is not None and b.val_bound is not None

    gate = crossover.gate_policy(platform)
    key_prefix = None
    if gate == "auto" and bounds_ok:
        dev = jax.devices()[0]
        algo = knobs.get("SPGEMM_TPU_VPU_ALGO")
        pb = knobs.get("SPGEMM_TPU_VPU_PB")
        if dev.platform == "tpu":
            from spgemm_tpu.ops.pallas_mxu import limbs_for_bound  # noqa: PLC0415

            limbs = f"l{limbs_for_bound(a.val_bound)}x{limbs_for_bound(b.val_bound)}"
        else:
            limbs = "xla"
        mxu_r = knobs.get("SPGEMM_TPU_MXU_R")
        # v2: the VPU side of the measurement is the proven-round (nomod)
        # kernel -- older entries timed the mod kernel and must not be reused
        key_prefix = (f"v2:{dev.platform}:{dev.device_kind}:"
                      f"{exact_name}-{algo}-pb{pb}:{limbs}-R{mxu_r}:k{k}")

    def choose_numeric(rnd):
        """-> (numeric_fn, used_mxu, proof_ok).  proof_ok reports whether
        the bit-exactness proof held at this round's fanout -- the proven
        output bound is valid whenever the proof holds, REGARDLESS of which
        kernel the speed gate then picks (all produce identical bits), so
        bound propagation keys off proof_ok, not used_mxu."""
        # proof at the round's REAL max fanout (padded sentinel pairs
        # contribute exactly 0)
        if (not bounds_ok
                or safe_exact_bound(a.val_bound, b.val_bound,
                                    rnd.max_fanout, k) is None):
            return numeric_exact, False, False
        # the padded width gates only the MXU kernel's own int32-accumulator
        # check (P*k <= 2^17) -- the proof itself (and so the nomod discount
        # and bound propagation) is unaffected
        if rnd.pa.shape[1] * k > 1 << 17:
            return numeric_exact_proven, False, True
        if key_prefix is not None:
            # measure at the round's padded key class so the cache stays
            # logarithmic in shapes; canonical 2048-tile slabs (wall time
            # is gather- and fold-shape-bound, not slab-size-bound).  The
            # VPU side of the measurement is the PROVEN-round kernel
            # (nomod where available) -- that is what an MXU loss would
            # actually run, so the routing is unbiased.  Kc is capped at
            # the measured ceiling (crossover measures at <= 4096 keys --
            # per-key cost is shape-stationary there), so mega-round
            # classes above it share one cache entry and one measurement.
            Kc = min(_shape_class(rnd.pa.shape[0]), 4096)
            P = rnd.pa.shape[1]
            if not crossover.mxu_wins(
                    numeric_exact_proven, numeric_mxu,
                    key=f"{key_prefix}:K{Kc}:P{P}", k=k, K=Kc, P=P,
                    nnzb=2048):
                return numeric_exact_proven, False, True
        return numeric_mxu, True, True

    return numeric_exact, max_entries, default_rs, choose_numeric


def _val_bound(m) -> int | None:
    """Inclusive element-value bound of an operand, matching what
    DeviceBlockMatrix.from_host would compute: the tracked val_bound for a
    device matrix, the exact slab maximum for a host matrix (so a plan
    built from the host operand is identical to one built after upload)."""
    vb = getattr(m, "val_bound", None)
    if vb is not None:
        return vb
    tiles = getattr(m, "tiles", None)
    if tiles is not None:
        return int(tiles.max()) if len(tiles) else 0
    return None


def _static_knob_vector() -> tuple:
    """Every jit-static knob's current value, for the plan-cache key: the
    registry guarantees these never vary inside a traced region, so they
    are exactly the knobs a cached plan may NOT straddle.  Delegates to
    the canonical registry definition -- the compile records and the
    warm-start store's on-disk validation key on the same vector."""
    return knobs.jit_static_vector()


def plan(a, b, *, round_size: int | None = None, backend: str | None = None,
         platform: str | None = None) -> SpgemmPlan:
    """Host-only planning half of spgemm_device: join + rounds + assembly
    permutation (+ lazily, ring/rowshard schedules via the SpgemmPlan
    hooks), memoized by operand-structure fingerprint (ops/plancache).

    backend/platform None resolve from the live jax backend -- a MAIN
    THREAD convenience.  Planner worker threads (chain.py plan-ahead) must
    pass both resolved so the body stays pure numpy: a dead TPU hangs
    inside backend init, and a hang on a worker thread wedges the pipeline
    with no exception to fail over on (the BKD contract, machine-checked
    for @host_only helpers by spgemm-lint)."""
    if platform is None:
        platform = jax.devices()[0].platform
    backend = resolve_backend(backend, platform)
    return _plan_host(a, b, round_size=round_size, backend=backend,
                      platform=platform)


@host_only
def _plan_host(a, b, *, round_size, backend, platform) -> SpgemmPlan:
    """The pure-numpy plan builder (see plan()).  Operands need only
    coords/nnzb/k and a value bound (val_bound attr or host tiles).

    First-contact route (ops/estimate): on a cache miss with the sampled
    estimator enabled and confident, the plan returns FAST -- budgets and
    the kernel-route partition come from the estimate, and the exact
    symbolic join is deferred into SpgemmPlan.ensure_exact(), which the
    chain plan-ahead worker runs off the dispatch critical path (execute
    forces it otherwise).  Low confidence takes the exact join inline (the
    `join_fallback` phase).  Either way the eventual rounds come from the
    exact join, so estimator on/off is bit-identical by construction."""
    from spgemm_tpu.utils.timers import ENGINE as timers  # noqa: PLC0415

    if a.k != b.k:
        raise ValueError(f"tile size mismatch: {a.k} vs {b.k}")
    k = a.k
    t0 = time.perf_counter()
    with timers.phase("plan"):
        failpoints.check("plan.build")
        batch = round_batch_enabled()
        split = None
        if backend == "hybrid" and batch:
            a_bound, b_bound = _val_bound(a), _val_bound(b)
            if a_bound is not None and b_bound is not None:
                split = _proof_fanout_cap(a_bound, b_bound, k)
        key = None
        if plancache.enabled():
            key = plancache.fingerprint(
                a.coords, b.coords,
                meta=(k, a.nnzb, b.nnzb, backend, platform, round_size,
                      batch, split, _static_knob_vector()))
            hit = plancache.lookup(key)
            if hit is not None:
                timers.incr("plan_cache_hits")
                return hit
            timers.incr("plan_cache_misses")
            # L2: the warm-start store (ops/warmstore) -- a plan a
            # PREVIOUS process persisted under this fingerprint replays
            # byte-identically (the pa/pb gathers are the fold order), so
            # a restarted daemon's first contact skips the symbolic
            # planner entirely.  load_plan validates schema/identity/knob
            # vector and counts warm_hits/warm_misses/warm_corrupt; any
            # doubt returns None and the cold path below runs.
            warm = warmstore.load_plan(key)
            if warm is not None:
                evicted = plancache.store(key, warm)
                if evicted:  # mirrored like the cold path's store below
                    timers.incr("plan_cache_evictions", evicted)
                return warm
        max_entries, default_rs = _plan_budgets(backend, platform)
        a_coords = np.asarray(a.coords)
        b_coords = np.asarray(b.coords)
        a_nnzb, b_nnzb = a.nnzb, b.nnzb

        est = None
        if estimate.enabled():
            with timers.phase("estimate"):
                est = estimate.maybe_estimate(a_coords, b_coords)

        # estimate-steered kernel-route partition (ESTIMATED route only:
        # the fallback path just declared the sample untrustworthy, and
        # the inline exact join has the real fanouts for free): when every
        # sampled fanout sits under the hybrid proof threshold, skip
        # materializing the split partition (the > split part would be
        # empty).  Safe on an estimation miss: choose_numeric re-proves
        # every round's REAL max fanout at dispatch, so a deep key the
        # sample missed just routes its whole class to the exact kernel --
        # identical bits either way.
        est_split = split
        if (est is not None and split is not None
                and est.est_max_fanout <= split):
            est_split = None

        # the pure MXU backend is field-mode semantics end to end: never
        # mix the (reference-mode) dense stream kernel into its rounds --
        # every other backend lets plan_rounds read SPGEMM_TPU_ACCUM_ROUTE
        route = "ladder" if backend == "mxu" else None
        # pre-dispatch route prediction from the sampled fanout histogram
        # (advisory only -- plan_rounds re-decides from the REAL per-class
        # fanouts once the exact join lands, so a misprediction is drift
        # telemetry, never a semantics change)
        route_pred = estimate.predicted_route(est) if route is None else None

        def build_exact(p: SpgemmPlan, build_split,
                        score_est: bool = False) -> None:
            """Fill join/rounds/take in place from the exact symbolic
            join.  Host-pure (runs on plan-ahead worker threads); phase
            accumulation attributes to whichever thread forced it."""
            with timers.phase("symbolic_join"):
                join = symbolic_join(a_coords, b_coords)
            if score_est and est is not None:
                # prediction accountability (obs/profile): the moment the
                # exact join exists, the estimate that STEERED this plan
                # is scored against it -- estimator drift becomes an
                # alertable series, not a silent mis-plan.  Scored only
                # on the estimated route: a low-confidence estimate the
                # engine already rejected (join_fallback) must not bias
                # the drift alert with errors that never steered anything
                obs_profile.observe_estimate(
                    est.est_keys, est.est_pairs, est.est_max_fanout,
                    join.num_keys, int(join.pair_ptr[-1]),
                    int(join.fanouts.max()) if join.num_keys else 0)
            with timers.phase("plan_rounds"):
                if batch:
                    # round-batched dispatch: one mega-round per fanout
                    # class (partitioned at the hybrid proof threshold so
                    # kernel routing stays key-exact), bounded by the
                    # gather/SMEM budgets.  An explicit round_size still
                    # caps the key axis.
                    rounds = plan_rounds(join, a_sentinel=a_nnzb,
                                         b_sentinel=b_nnzb,
                                         round_size=round_size,
                                         max_entries=max_entries,
                                         batch=True,
                                         batch_entries=_batch_entries(k),
                                         split_fanout=build_split,
                                         route=route)
                else:
                    rs = default_rs if round_size is None else round_size
                    rounds = plan_rounds(join, a_sentinel=a_nnzb,
                                         b_sentinel=b_nnzb, round_size=rs,
                                         max_entries=max_entries,
                                         route=route)
                # the assembly gather's inverse permutation is precomputed
                # on host here, off the dispatch/assembly spans
                take = assembly_permutation(rounds, join.num_keys) \
                    if batch else None
            if route_pred is not None:
                # re-proof accountability: compare the estimator's
                # pre-dispatch route prediction against what the REAL
                # fanouts planned -- a mismatch is an event, never a
                # routing input (the rounds above already hold the truth)
                real = ("dense" if any(r.route == "dense"
                                       or r.dense_alt is not None
                                       for r in rounds) else "ladder")
                if real != route_pred:
                    obs_events.emit("accum_route_mismatch",
                                    predicted=route_pred, real=real)
            p.join, p.rounds, p.take = join, rounds, take

        p = SpgemmPlan(backend=backend, platform=platform, k=k,
                       a_nnzb=a_nnzb, b_nnzb=b_nnzb, join=None,
                       rounds=None, take=None, batch=batch,
                       round_size=round_size, split_fanout=split,
                       fingerprint=key, estimate=est,
                       _a_coords=a_coords, _b_coords=b_coords)
        if (est is not None
                and est.confidence >= estimate.confidence_threshold()):
            # confident estimate: fast return, exact join deferred off
            # the critical path (the plan-ahead worker or execute() runs
            # ensure_exact; the cached entry is promoted in place)
            estimate.note_hit()
            timers.incr("est_hits")
            p.plan_route = "estimated"
            p._exact_builder = partial(build_exact, build_split=est_split,
                                       score_est=True)
        elif est is not None:
            # estimator ran but the sample is not trustworthy (skewed
            # mass): take the exact join inline, visibly, with the FULL
            # proof-threshold partition (never the distrusted estimate's)
            estimate.note_fallback()
            timers.incr("est_fallbacks")
            obs_events.emit("est_fallback", reason="low_confidence",
                            confidence=round(est.confidence, 4),
                            sampled_rows=est.sampled_rows,
                            total_rows=est.total_rows)
            with timers.phase("join_fallback"):
                build_exact(p, build_split=split)
        else:
            build_exact(p, build_split=split)
        p.plan_s = time.perf_counter() - t0
        if key is not None:
            evicted = plancache.store(key, p)
            if evicted:
                # LRU pops were invisible before delta fingerprint
                # retention made eviction pressure matter: mirror them
                # into the engine registry like the hit/miss pair
                timers.incr("plan_cache_evictions", evicted)
            # write-through to the warm store: an exact plan persists the
            # moment it exists (an estimator-routed plan's join is still
            # deferred here -- the daemon's terminal-event flush catches
            # it once ensure_exact lands).  No-op unless a warm dir is
            # bound; save_plan never raises into the planner.
            if not p.is_deferred:
                warmstore.save_plan(p)
        return p


def _observe_memory() -> None:
    """Sample device memory_stats() into the profiling layer's watermark
    account (obs/profile.observe_memory).  Backends without the API (the
    CPU backend returns None; an exotic plugin may raise) leave every
    HBM gauge gracefully absent -- telemetry must never break dispatch.
    Main-thread only, like every other backend touch in this module."""
    if not obs_profile.enabled():
        return
    try:
        stats = jax.devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 -- telemetry must never break dispatch
        stats = None
    obs_profile.observe_memory(stats)


def _dense_dispatch(rnd, a, b, k, timers):
    """One dense-route launch: zero accumulator planes + the segmented
    stream fold (numeric_round_dense_impl).  The dense_fold sub-span and
    route_dense counter make the route observable per dispatch."""
    with timers.phase("dense_fold"):
        zeros = jnp.zeros((rnd.out_rows + 1, k, k), jnp.uint32)
        oh, ol = _numeric_dense(a.hi, a.lo, b.hi, b.lo,
                                jnp.asarray(rnd.pa), jnp.asarray(rnd.pb),
                                jnp.asarray(rnd.seg), zeros, zeros)
    timers.incr("route_dense")
    return oh, ol


def _dense_gate(plan: SpgemmPlan, rnd, numeric_ladder) -> bool:
    """Auto accumulator route, dispatch side: should this round run its
    dense-stream twin?  The exact analog of the hybrid MXU gate --
    measured per (key class, fanout class, k) under the 'auto' crossover
    policy, structural (the round's padded-MAC ratio) under 'proof'.

    This is the re-proof at dispatch: the decision keys off the round's
    REAL ladder layout and REAL stream (both built from the exact join),
    never off the estimate that steered planning -- an estimator miss can
    shrink dense coverage (a deep class the sample missed planned without
    a twin) but can never change semantics, because every route is
    bit-exact and the gate only ranks wall clock."""
    from spgemm_tpu.ops import crossover  # noqa: PLC0415
    from spgemm_tpu.ops.symbolic import _shape_class  # noqa: PLC0415

    policy = crossover.gate_policy(plan.platform)
    Kc = min(_shape_class(rnd.pa.shape[0]), 4096)
    P = rnd.pa.shape[1]
    key = ""
    if policy == "auto":
        dev = jax.devices()[0]
        key = (f"dense-v1:{dev.platform}:{dev.device_kind}:"
               f"k{plan.k}:K{Kc}:P{P}")
    return crossover.dense_wins(
        numeric_ladder, _numeric_dense, key=key, k=plan.k, K=Kc, P=P,
        stream_len=len(rnd.dense_alt.pa), policy=policy,
        padded_ratio=rnd.padded_mac_ratio())


def _dense_proof_ok(a, b, rnd, k: int) -> bool:
    """Exactness-proof check for a forced-dense round under the hybrid
    backend: the proof is a property of the fanout and operand bounds,
    not of the kernel (all routes produce identical bits), so bound
    propagation must keep counting rounds the stream fold ran."""
    from spgemm_tpu.ops.mxu_spgemm import safe_exact_bound  # noqa: PLC0415

    return (a.val_bound is not None and b.val_bound is not None
            and safe_exact_bound(a.val_bound, b.val_bound,
                                 rnd.max_fanout, k) is not None)


def execute(plan: SpgemmPlan, a, b):
    """Device-only execution half of spgemm_device: kernel selection,
    numeric dispatch, on-device assembly.  Everything host-decidable lives
    in the SpgemmPlan; this function owns every backend touch (crossover
    measurement included), so it must run on the main thread."""
    from spgemm_tpu.ops.device import DeviceBlockMatrix, ensure_device  # noqa: PLC0415

    from spgemm_tpu.utils.timers import ENGINE as timers  # noqa: PLC0415

    a = ensure_device(a)
    b = ensure_device(b)
    plan.check_operands(a, b)
    # an estimator-routed plan may still carry a deferred exact join
    # (direct plan() callers without a plan-ahead worker): land it now --
    # in-place, so the plan-cache entry is promoted for every later hit
    plan.ensure_exact()
    k = plan.k
    join, rounds, batch = plan.join, plan.rounds, plan.batch
    if join.num_keys == 0:
        return DeviceBlockMatrix.empty(a.rows, b.cols, k)

    backend = plan.backend
    out_bound = (1 << 64) - 2  # any backend's outputs are mod-collapsed
    choose_numeric = None  # per-round dispatcher (hybrid only)
    if backend == "hybrid":
        numeric, _, _, choose_numeric = _hybrid_setup(a, b, k)
    else:
        numeric, _, _ = _select_numeric(backend, a, b)

    # All rounds dispatch asynchronously; outputs are assembled into one
    # key-ordered slab on device, never touching host.  Timed phases are
    # host-side spans (dispatch, not device completion -- the device tail is
    # the caller's block_until_ready); the reference's Table-2 analog phases
    # are plan (symbolic_join + plan_rounds) / numeric_dispatch / assembly.
    mxu_rounds = proof_rounds = 0
    with timers.phase("numeric_dispatch"):
        failpoints.check("kernel.dispatch")
        outs_h, outs_l, order = [], [], []
        for rnd in rounds:
            fn = numeric
            used_mxu = False
            dense = rnd if rnd.route == "dense" else None
            if choose_numeric is not None and dense is not None:
                # forced dense stream (SPGEMM_TPU_ACCUM_ROUTE=dense): the
                # MXU speed gate never sees the round, but the exactness
                # proof is kernel-independent -- keep bound propagation
                proof_rounds += _dense_proof_ok(a, b, rnd, k)
            elif choose_numeric is not None:
                fn, used_mxu, proof_ok = choose_numeric(rnd)
                mxu_rounds += used_mxu
                proof_rounds += proof_ok
            if dense is None and rnd.dense_alt is not None and not used_mxu:
                # auto route: this round carries a dense twin and the
                # exact (non-MXU) kernel would run -- let the measured
                # crossover gate pick the layout (bit-exact either way)
                if _dense_gate(plan, rnd, fn):
                    dense = rnd.dense_alt
            if dense is not None:
                oh, ol = _dense_dispatch(dense, a, b, k, timers)
            else:
                oh, ol = fn(a.hi, a.lo, b.hi, b.lo,
                            jnp.asarray(rnd.pa), jnp.asarray(rnd.pb))
            timers.incr("dispatches")
            if batch:
                # outputs are consumed whole (padded tails included): the
                # precomputed permutation skips the pad rows, so no per-round
                # slice op is ever enqueued
                outs_h.append(oh)
                outs_l.append(ol)
            else:
                n_valid = len(rnd.key_index)
                outs_h.append(oh[:n_valid])
                outs_l.append(ol[:n_valid])
                order.append(rnd.key_index)

    with timers.phase("assembly"):
        if batch:
            # one fused jit call: pad-concat + single gather through the
            # precomputed inverse permutation into the output slab (the
            # legacy path's per-round slice + unjitted concat chain enqueued
            # 2-3 executables PER ROUND -- enough to stall the host on the
            # backend's in-flight dispatch throttle at chain scales)
            out_hi, out_lo = _assemble(outs_h, outs_l, jnp.asarray(plan.take))
        else:
            # inv[key] = position of that key in the concatenated round
            # outputs; the extra last entry maps the sentinel slot to the
            # appended zero tile.
            cat_idx = np.concatenate(order)
            inv = np.empty(join.num_keys + 1, np.int64)
            inv[cat_idx] = np.arange(len(cat_idx))
            inv[-1] = len(cat_idx)
            take = jnp.asarray(inv)
            zero = jnp.zeros((1, k, k), jnp.uint32)
            out_hi = jnp.concatenate(outs_h + [zero], axis=0)[take]
            out_lo = jnp.concatenate(outs_l + [zero], axis=0)[take]
    # HBM watermark sample at the multiply boundary: dispatch + assembly
    # are enqueued, so bytes_in_use covers this multiply's working set
    _observe_memory()

    # structured observability (SURVEY.md section 5.5): size, fill-in, work
    total_pairs = int(join.pair_ptr[-1])
    tag = backend
    if choose_numeric is not None:
        tag = f"hybrid mxu={mxu_rounds}/{len(rounds)}"
        if proof_rounds == len(rounds):
            # every round's exactness proof held: the tighter propagated
            # bound feeds the NEXT multiply's proof, keeping chain products
            # provable as long as the bounds hold -- even when the speed
            # gate routed the rounds to the exact kernel (identical bits,
            # so the proven bound applies either way)
            from spgemm_tpu.ops.mxu_spgemm import safe_exact_bound  # noqa: PLC0415

            proven = safe_exact_bound(a.val_bound, b.val_bound,
                                      int(join.fanouts.max()), k)
            if proven is not None:
                out_bound = proven
    log.info("spgemm[%s]: nnzb %d x %d -> keys=%d pairs=%d dispatches=%d "
             "batch=%d work=%.3f GFLOP",
             tag, a.nnzb, b.nnzb, join.num_keys, total_pairs, len(rounds),
             batch, 2.0 * total_pairs * k ** 3 / 1e9)

    return DeviceBlockMatrix(rows=a.rows, cols=b.cols, k=k,
                             coords=join.keys, hi=out_hi, lo=out_lo,
                             val_bound=min(out_bound, (1 << 64) - 2))


def _stack_width(rnd, plan: SpgemmPlan, jobs: int) -> int:
    """How many jobs' copies of one round may ride a single fused launch
    without busting the budgets the plan was built under: the SMEM
    index-array budget (Pallas backends -- the stacked key axis ships in
    the same arrays the solo round did) and the gather-materialization
    entry budget (every backend).  Small rounds -- the cross-job batching
    workload -- fit the whole batch; a round already near budget degrades
    to narrower chunks (worst case per-job launches), never a silently
    over-budget dispatch."""
    from spgemm_tpu.ops.symbolic import _smem_key_cap  # noqa: PLC0415

    K, P = rnd.pa.shape
    width = jobs
    max_entries, _ = _plan_budgets(plan.backend, plan.platform)
    if max_entries is not None:
        width = min(width, max(1, _smem_key_cap(P, max_entries) // max(K, 1)))
    width = min(width, max(1, _batch_entries(plan.k) // max(K * P, 1)))
    return max(1, width)


def execute_batched(plan: SpgemmPlan, pairs: list) -> list:
    """One fused dispatch for J same-structure multiplies (cross-job
    batching, serve/daemon batch pickup): every (a, b) in `pairs` must
    match `plan` (check_operands guards each), the J operand slabs
    concatenate tiles-only with ONE shared sentinel zero tile, each round
    dispatches once with the jobs stacked along the round axis every
    numeric kernel already accepts (symbolic.accept_round_stack), and
    per-job results de-interleave at assembly through the SAME take
    permutation the solo path uses.  Each output row's pair list and fold
    order are untouched, so every job's result is byte-identical to its
    solo execute(plan, a, b) -- bit-exact by construction.

    Kernel routing: the hybrid backend's per-round speed gate is skipped
    -- every round runs the exact kernel (proof-gated routes are
    bit-identical by contract, so only wall clock differs); the proven
    val_bound still propagates per job when the proof holds.  Returns the
    J results in submission order."""
    from spgemm_tpu.ops.device import DeviceBlockMatrix, ensure_device  # noqa: PLC0415
    from spgemm_tpu.ops.symbolic import stack_round_indices  # noqa: PLC0415
    from spgemm_tpu.utils.timers import ENGINE as timers  # noqa: PLC0415

    if len(pairs) == 1:
        return [execute(plan, *pairs[0])]
    pairs = [(ensure_device(a), ensure_device(b)) for a, b in pairs]
    for a, b in pairs:
        plan.check_operands(a, b)
    plan.ensure_exact()
    k, J = plan.k, len(pairs)
    join, rounds = plan.join, plan.rounds
    if join.num_keys == 0:
        return [DeviceBlockMatrix.empty(a.rows, b.cols, k)
                for a, b in pairs]
    nnzb_a, nnzb_b = plan.a_nnzb, plan.b_nnzb
    if max(nnzb_a, nnzb_b) * J + 1 >= 1 << 31 \
            or any(rnd.pa.ndim != 2 for rnd in rounds):
        # the stacked slab indices must stay int32 (kernel contract), and
        # only the planner's 2-D rounds stack along the job axis; either
        # way the fused path cannot exist -- run solo, same bits
        return [execute(plan, a, b) for a, b in pairs]

    backend = plan.backend
    cap = (1 << 64) - 2
    if backend == "hybrid":
        # exact kernel for every round (see docstring); parameterize the
        # selection off the widest bounds so an mxu-limb choice -- were
        # the exact backend ever bound-sensitive -- covers every job
        exact_name = resolve_backend(None, plan.platform)
        numeric, _, _ = _select_numeric(exact_name, *pairs[0])
    else:
        from types import SimpleNamespace  # noqa: PLC0415

        def _widest(bounds):
            vals = [vb for vb in bounds]
            return None if any(v is None for v in vals) else max(vals)
        a_w = SimpleNamespace(val_bound=_widest([a.val_bound
                                                 for a, _ in pairs]))
        b_w = SimpleNamespace(val_bound=_widest([b.val_bound
                                                 for _, b in pairs]))
        numeric, _, _ = _select_numeric(backend, a_w, b_w)

    # ONE shared sentinel zero tile: every job's slab carries its own as
    # the last row -- reuse job 0's instead of appending a fresh device
    # zero (stack_round_indices remaps every job's sentinel onto it)
    with timers.phase("numeric_dispatch"):
        failpoints.check("kernel.dispatch")
        a_hi = jnp.concatenate([a.hi[:nnzb_a] for a, _ in pairs]
                               + [pairs[0][0].hi[nnzb_a:nnzb_a + 1]], axis=0)
        a_lo = jnp.concatenate([a.lo[:nnzb_a] for a, _ in pairs]
                               + [pairs[0][0].lo[nnzb_a:nnzb_a + 1]], axis=0)
        b_hi = jnp.concatenate([b.hi[:nnzb_b] for _, b in pairs]
                               + [pairs[0][1].hi[nnzb_b:nnzb_b + 1]], axis=0)
        b_lo = jnp.concatenate([b.lo[:nnzb_b] for _, b in pairs]
                               + [pairs[0][1].lo[nnzb_b:nnzb_b + 1]], axis=0)
        # per round, per job: the (chunk, K, k, k) output stack sliced
        # back out -- de-interleaving is row arithmetic, never a re-fold
        outs_h: list[list] = [[] for _ in range(J)]
        outs_l: list[list] = [[] for _ in range(J)]
        fused = 0
        for rnd in rounds:
            width = _stack_width(rnd, plan, J)
            spa_all = stack_round_indices(rnd.pa, nnzb_a, J)  # (J, K, P)
            spb_all = stack_round_indices(rnd.pb, nnzb_b, J)
            for lo in range(0, J, width):
                chunk = min(width, J - lo)
                oh, ol = numeric(a_hi, a_lo, b_hi, b_lo,
                                 jnp.asarray(spa_all[lo:lo + chunk]),
                                 jnp.asarray(spb_all[lo:lo + chunk]))
                timers.incr("dispatches")
                fused += chunk > 1
                for idx in range(chunk):
                    outs_h[lo + idx].append(oh[idx])
                    outs_l[lo + idx].append(ol[idx])

    with timers.phase("assembly"):
        results = []
        if plan.take is not None:
            take = jnp.asarray(plan.take)
            planes = [_assemble(outs_h[j], outs_l[j], take)
                      for j in range(J)]
        else:
            # legacy (non-round-batched) plan: the solo path's inverse
            # permutation over valid round rows, built once, gathered per
            # job -- still one fused epilogue call per job
            order = [rnd.key_index for rnd in rounds]
            cat_idx = np.concatenate(order)
            inv = np.empty(join.num_keys + 1, np.int64)
            inv[cat_idx] = np.arange(len(cat_idx))
            inv[-1] = len(cat_idx)
            take = jnp.asarray(inv)
            zero = jnp.zeros((1, k, k), jnp.uint32)
            planes = []
            for j in range(J):
                valid_h = [oh[:len(rnd.key_index)]
                           for oh, rnd in zip(outs_h[j], rounds)]
                valid_l = [ol[:len(rnd.key_index)]
                           for ol, rnd in zip(outs_l[j], rounds)]
                planes.append(
                    (jnp.concatenate(valid_h + [zero], axis=0)[take],
                     jnp.concatenate(valid_l + [zero], axis=0)[take]))
        for (a, b), (out_hi, out_lo) in zip(pairs, planes):
            out_bound = cap
            if backend == "hybrid" and a.val_bound is not None \
                    and b.val_bound is not None:
                from spgemm_tpu.ops.mxu_spgemm import safe_exact_bound  # noqa: PLC0415

                proven = safe_exact_bound(a.val_bound, b.val_bound,
                                          int(join.fanouts.max()), k)
                if proven is not None:
                    out_bound = proven
            results.append(DeviceBlockMatrix(
                rows=a.rows, cols=b.cols, k=k, coords=join.keys,
                hi=out_hi, lo=out_lo, val_bound=min(out_bound, cap)))
    _observe_memory()
    total_pairs = int(join.pair_ptr[-1])
    log.info("spgemm[%s,x%d-job-batch]: nnzb %d x %d -> keys=%d pairs=%d "
             "rounds=%d fused_launches=%d work=%.3f GFLOP/job",
             backend, J, nnzb_a, nnzb_b, join.num_keys, total_pairs,
             len(rounds), fused, 2.0 * total_pairs * k ** 3 / 1e9)
    return results


def subplan(parent: SpgemmPlan,
            keep: np.ndarray) -> tuple[SpgemmPlan, np.ndarray]:
    """Row-sliced sub-plan: the delta path's restriction of a cached plan
    to the dirty output-key subset (boolean mask over the join's keys).

    The sub-join copies each kept key's pair list whole and in order
    (ops/symbolic.slice_join), and the rounds rebuild under the parent's
    EXACT budgets and hybrid proof partition -- so a kept key folds
    byte-identically to the full plan through the same round-batched
    dispatch, only over fewer keys.  Host-pure; never cached (the dirty
    subset changes per submit).  Returns (sub_plan, kept_key_indices) --
    the indices are the splice scatter back into the full key list."""
    from spgemm_tpu.ops.symbolic import _shape_class  # noqa: PLC0415

    parent.ensure_exact()
    sub_join, kept = slice_join(parent.join, keep)
    max_entries, default_rs = _plan_budgets(parent.backend, parent.platform)
    # same accumulator-route rule as _plan_host: the knob is jit-static
    # (stable per process), so the sub-plan re-derives the parent's route
    sub_route = "ladder" if parent.backend == "mxu" else None
    if parent.batch:
        rounds = plan_rounds(sub_join, a_sentinel=parent.a_nnzb,
                             b_sentinel=parent.b_nnzb,
                             round_size=parent.round_size,
                             max_entries=max_entries, batch=True,
                             batch_entries=_batch_entries(parent.k),
                             split_fanout=parent.split_fanout,
                             route=sub_route)
        take = assembly_permutation(rounds, sub_join.num_keys)
        # pad the assembly permutation to a 3/4-pow-2 rung: the dirty-key
        # count drifts per submit, and an exact-length take would compile
        # a fresh _assemble gather every time (the padding rows read the
        # appended zero row -- take's sentinel slot -- and only the first
        # num_keys rows of the output planes are ever consumed)
        pad = _shape_class(len(take)) - len(take)
        if pad:
            take = np.concatenate([take, np.full(pad, take[-1], take.dtype)])
    else:
        rs = default_rs if parent.round_size is None else parent.round_size
        rounds = plan_rounds(sub_join, a_sentinel=parent.a_nnzb,
                             b_sentinel=parent.b_nnzb, round_size=rs,
                             max_entries=max_entries, route=sub_route)
        take = None
    sub = SpgemmPlan(backend=parent.backend, platform=parent.platform,
                     k=parent.k, a_nnzb=parent.a_nnzb,
                     b_nnzb=parent.b_nnzb, join=sub_join, rounds=rounds,
                     take=take, batch=parent.batch,
                     round_size=parent.round_size,
                     split_fanout=parent.split_fanout,
                     _a_coords=parent._a_coords,
                     _b_coords=parent._b_coords)
    return sub, kept


def _splice_impl(prev_hi, prev_lo, idx, take, sub_hi, sub_lo):
    """Delta splice: scatter the recomputed rows (gathered through
    `take`) into the retained previous planes at `idx`.  One fused
    executable; idx/take are ladder-padded by the caller (pad slots
    scatter the sub result's zero row onto the retained sentinel row --
    zeros onto zeros), so the compiled-shape count stays logarithmic as
    the dirty-key count drifts across submits."""
    return prev_hi.at[idx].set(sub_hi[take]), prev_lo.at[idx].set(sub_lo[take])


_splice = obs_profile.ProfiledJit("delta_splice", jax.jit(_splice_impl))


def _delta_key(plan: SpgemmPlan, a, b) -> str:
    """The delta store key: the plan's structure fingerprint QUALIFIED by
    both operands' device placements.  The fingerprint alone is placement
    blind, and an in-process multi-device scheduler (parallel/chainpart
    runs one same-structure chain per rank) would otherwise be served a
    retained result living on ANOTHER rank's device -- the next multiply
    then dies on a mixed-device dispatch.  Per-placement keys keep each
    rank's delta stream independent (and each rank gets the win)."""
    ids_a = sorted(d.id for d in a.hi.devices())
    ids_b = sorted(d.id for d in b.hi.devices())
    return f"{plan.fingerprint}|dev{ids_a}x{ids_b}"


def _rehydrate_delta_entry(key: str, raw: dict):
    """A warm-store delta record (host arrays -- warmstore stays
    jax-free) back into a live DeltaEntry: one H2D of the retained result
    planes onto the default device (the single-device daemon's placement,
    which is also what the placement-qualified key just matched)."""
    from spgemm_tpu.ops import delta  # noqa: PLC0415
    from spgemm_tpu.ops.device import DeviceBlockMatrix  # noqa: PLC0415

    res = raw["result"]
    result = DeviceBlockMatrix(
        rows=res["rows"], cols=res["cols"], k=res["k"],
        coords=res["coords"], hi=jnp.asarray(res["hi"]),
        lo=jnp.asarray(res["lo"]), val_bound=res["val_bound"])
    return delta.DeltaEntry(key=key, version=raw["version"],
                            a_src=raw["a_src"], b_src=raw["b_src"],
                            result=result, out_rows=raw["out_rows"])


def _delta_execute(plan: SpgemmPlan, a, b):
    """Delta SpGEMM (ops/delta): incremental execute for a plan whose
    structure fingerprint has been seen before.

    diff -> reach -> slice -> splice: per-tile-row content digests (or
    the producer's analytic dirty tag) identify the changed input rows,
    the cached exact join propagates them to the reachable OUTPUT
    tile-rows, a row-sliced sub-plan re-executes exactly those through
    the normal dispatch, and the recomputed rows splice into the retained
    previous result on device.  Untouched rows keep their previous bytes
    -- bit-exact because an output key's fold is a pure function of its
    pair list's tiles in j-ascending order, which slice_join preserves.

    Every ambiguity (first contact, provenance mismatch, store eviction)
    is a counted full fallback that re-seeds the retained entry."""
    from spgemm_tpu.ops import delta  # noqa: PLC0415
    from spgemm_tpu.ops.device import DeviceBlockMatrix  # noqa: PLC0415
    from spgemm_tpu.utils.timers import ENGINE as timers  # noqa: PLC0415

    plan.ensure_exact()
    join = plan.join
    key = _delta_key(plan, a, b)
    entry = delta.lookup(key)
    if entry is None:
        # warm start (ops/warmstore): a previous process's retained
        # result + provenance for this key may be on disk -- rehydrate
        # (one H2D of the result planes) and seed the store, so the first
        # post-restart submit diffs instead of paying a full fallback.
        # load_delta validates and counts; any doubt leaves entry None
        # and the normal first-contact path runs.
        raw = warmstore.load_delta(key)
        if raw is not None:
            entry = _rehydrate_delta_entry(key, raw)
            delta.seed_entry(entry)
    d = None
    # fallback provenance for the event log / per-reason stats: an absent
    # entry is first contact OR a store eviction (indistinguishable by
    # design -- eviction forgets), a failed diff is a lineage the store
    # could not prove
    reason = "no_entry" if entry is None else None
    if entry is not None:
        with timers.phase("delta_diff"):
            d = delta.diff(entry, a, b, join, plan._a_coords,
                           plan._b_coords)
        if d is None:
            reason = "provenance_mismatch"
    if d is None:
        # first contact / provenance mismatch / store eviction: the full
        # path, loudly counted, and the entry (re)seeded so the next
        # same-structure multiply can go incremental
        out_row_ids = np.unique(join.keys[:, 0]) if join.num_keys \
            else np.zeros(0, np.int64)
        total_rows = len(out_row_ids)
        timers.incr("delta_full_fallbacks")
        timers.incr("delta_rows_recomputed", total_rows)
        timers.incr("delta_rows_total", total_rows)
        delta.note_fallback_reason(reason)
        obs_events.emit("delta_fallback", reason=reason,
                        total_rows=total_rows)
        # accountability: a full fallback predicted -- and executed --
        # everything (error 0 by definition, but the observation count
        # keeps the series honest about how often delta even applies)
        obs_profile.observe_delta(total_rows, total_rows, total_rows)
        result = execute(plan, a, b)
        with timers.phase("delta_diff"):
            delta.store_full(key, a, b, result, total_rows, out_row_ids)
        return result
    # diffed against a live entry: its out_rows IS this join's distinct
    # output-row count (same fingerprint, same structure) -- no per-call
    # np.unique on the hot path
    total_rows = entry.out_rows
    n_dirty = len(d.dirty_rows)
    timers.incr("delta_rows_recomputed", n_dirty)
    timers.incr("delta_rows_total", total_rows)
    # accountability: predicted dirty rows vs what actually re-executes
    # (an all-dirty diff degenerates to the full path and executes every
    # row; an empty diff executes none)
    executed = total_rows if n_dirty >= total_rows else n_dirty
    obs_profile.observe_delta(n_dirty, executed, total_rows)
    if n_dirty == 0:
        # empty diff: the retained result IS this multiply's result (the
        # digests/tags prove both operands byte-identical to last time)
        result = entry.result
    elif n_dirty >= total_rows:
        # all-dirty degenerates to the full path (no slicing overhead)
        result = execute(plan, a, b)
    else:
        from spgemm_tpu.ops.symbolic import _shape_class  # noqa: PLC0415

        sub_plan, kept = subplan(plan, d.key_mask)
        sub = execute(sub_plan, a, b)
        with timers.phase("delta_splice"):
            failpoints.check("delta.splice")
            prev = entry.result
            n_sub = len(kept)
            # ladder-pad the scatter like the sub-plan's assembly: pad
            # slots write the sub result's zero row (index n_sub) onto
            # the retained sentinel row (index num_keys) -- zeros onto
            # zeros -- so the jitted splice compiles per rung, not per
            # dirty-key count
            rung = _shape_class(n_sub)
            idx = np.full(rung, join.num_keys, np.int64)
            idx[:n_sub] = kept
            gather = np.full(rung, n_sub, np.int64)
            gather[:n_sub] = np.arange(n_sub)
            out_hi, out_lo = _splice(prev.hi, prev.lo, jnp.asarray(idx),
                                     jnp.asarray(gather), sub.hi, sub.lo)
            cap = (1 << 64) - 2
            vb = max(prev.val_bound if prev.val_bound is not None else cap,
                     sub.val_bound if sub.val_bound is not None else cap)
            result = DeviceBlockMatrix(rows=a.rows, cols=b.cols, k=plan.k,
                                       coords=join.keys, hi=out_hi,
                                       lo=out_lo, val_bound=min(vb, cap))
        _observe_memory()  # splice retains prev + sub planes: the delta
        # path's HBM watermark is exactly what DELTA_RETAIN sizing needs
        log.info("spgemm[delta]: recomputed %d/%d output rows "
                 "(%d/%d keys)", n_dirty, total_rows, n_sub,
                 join.num_keys)
    delta.commit(entry, result, d, total_rows)
    return result


_plan = plan  # module-level alias: spgemm_device's `plan` kwarg shadows it


def spgemm_device(a, b, *, round_size: int | None = None,
                  backend: str | None = None,
                  plan: SpgemmPlan | None = None):
    """C = A x B with reference-exact semantics, tiles staying in HBM.

    a, b: DeviceBlockMatrix (or host BlockSparseMatrix -- uploaded on entry).
    Returns a DeviceBlockMatrix; no tile data crosses the device boundary,
    which inverts the reference's pack/H2D/D2H round-trip per multiply
    (sparse_matrix_mult.cu:189-269, 27% of its report's total time).

    plan: a prebuilt SpgemmPlan (chain.py's plan-ahead worker, or a caller
    reusing a plan across same-structure multiplies).  None plans inline --
    the legacy serial path, bit-identical since planning is deterministic
    and dispatch order is unchanged.  `plan_wait` times how long dispatch
    actually blocked on planning: the full plan cost here, near-zero when
    a prebuilt plan (or a plan-cache hit) arrives ready.
    """
    from spgemm_tpu.ops.device import ensure_device  # noqa: PLC0415

    from spgemm_tpu.utils.timers import ENGINE as timers  # noqa: PLC0415

    a = ensure_device(a)
    b = ensure_device(b)
    if plan is None:
        with timers.phase("plan_wait"):
            plan = _plan(a, b, round_size=round_size, backend=backend)
    # delta recompute (ops/delta): a fingerprinted plan whose structure
    # was multiplied before re-executes only the output rows the changed
    # input rows can reach, splicing into the retained previous result --
    # bit-identical to the full path (SPGEMM_TPU_DELTA=0 is the A/B)
    from spgemm_tpu.ops import delta  # noqa: PLC0415
    if delta.enabled() and plan.fingerprint is not None:
        return _delta_execute(plan, a, b)
    return execute(plan, a, b)


def spgemm_outofcore(a: BlockSparseMatrix, b: BlockSparseMatrix, *,
                     round_size: int | None = None,
                     backend: str | None = None) -> BlockSparseMatrix:
    """C = A x B without ever materializing either operand slab in HBM.

    The device-resident pipeline (spgemm_device) requires both operand slabs
    plus the result to fit in HBM at once.  The reference has no such limit:
    its matrices live in host RAM and the GPU only ever holds one <= 500-key
    round's staged pairs (the 8 GB large_arr, sparse_matrix_mult.cu:167-257).
    This is the same staging model as a *capacity* mode: operands stay host-
    resident, and each round uploads only the tiles it references --

      peak HBM = TWO rounds' sub-slabs + output tiles (depth-2 pipeline;
      SPGEMM_TPU_OOC_DEPTH=N deepens the pipeline to N rounds of overlap
      at N rounds of peak HBM when landing D2H is the bottleneck),

    bounded by round_size regardless of operand size, at the cost of one
    upload per referenced tile per round (banded/clustered structures re-use
    tiles within a round, so uploads are deduplicated per round).

    Sub-slab sizes are padded to the 3/4-pow-2 ladder so the jit cache sees
    a logarithmic set of shapes, and rounds are pipelined depth-deep
    (default two) through a 3-stage worker pipeline -- staging thread (host
    gather/pack) -> main thread (upload + launch) -> landing thread (D2H +
    host scatter) -- so round i+1's host gather, round i's device execution,
    and round i-1's result landing all overlap.

    Semantics, ordering, and output structure are identical to spgemm
    (reference wrap-then-mod, SURVEY.md section 2.9), including per-round
    'hybrid' dispatch (exact host-side value bounds feed the same proof as
    the resident pipeline's).
    """
    from types import SimpleNamespace  # noqa: PLC0415

    from spgemm_tpu.ops.symbolic import _shape_class  # noqa: PLC0415
    from spgemm_tpu.utils.timers import ENGINE as timers  # noqa: PLC0415

    a = a.to_host() if hasattr(a, "to_host") else a
    b = b.to_host() if hasattr(b, "to_host") else b
    if a.k != b.k:
        raise ValueError(f"tile size mismatch: {a.k} vs {b.k}")
    backend = resolve_backend(backend)
    k = a.k
    with timers.phase("symbolic_join"):
        join = symbolic_join(a.coords, b.coords)
    if join.num_keys == 0:
        return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k)

    # val_bound for the MXU limb-grid selection and the hybrid proof (host
    # matrices don't track bounds the way DeviceBlockMatrix does -- compute
    # the EXACT slab maxima here; one pass each, and only the backends that
    # read them pay for it)
    if backend in ("mxu", "hybrid"):
        bound = SimpleNamespace(val_bound=int(a.tiles.max()) if a.nnzb else 0), \
                SimpleNamespace(val_bound=int(b.tiles.max()) if b.nnzb else 0)
    else:
        bound = SimpleNamespace(val_bound=None), SimpleNamespace(val_bound=None)
    # keep the backend's max_entries (the Pallas kernels' SMEM index-array
    # budget -- huge-fanout classes must still shrink their key chunks), but
    # bound every round by round_size keys (the reference's small_size):
    # capacity, not launch width, is the point here
    choose_numeric = None
    if backend == "hybrid":
        numeric, max_entries, _, choose_numeric = _hybrid_setup(*bound, k)
    else:
        numeric, max_entries, _ = _select_numeric(backend, *bound)
    round_size = 512 if round_size is None else round_size

    with timers.phase("plan_rounds"):
        rounds = plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=b.nnzb,
                             round_size=round_size, max_entries=max_entries,
                             route="ladder")

    def host_prep(rnd):
        """Stage 1 (host-only): gather + pad one round's referenced tiles
        into upload-ready (hi, lo) planes.  Pure numpy -- under depth >= 2
        this runs on the staging worker thread, so the unique/searchsorted/
        pack cost overlaps the device compute and D2H of earlier rounds
        instead of sitting on the dispatch critical path."""
        ua = np.unique(rnd.pa)
        ua = ua[ua < a.nnzb]          # drop the global sentinel
        ub = np.unique(rnd.pb)
        ub = ub[ub < b.nnzb]
        # global index -> sub-slab index; the global sentinel (> every real
        # index) lands at len(ua), exactly where the zero tile sits
        sub_pa = np.searchsorted(ua, rnd.pa).astype(np.int32)
        sub_pb = np.searchsorted(ub, rnd.pb).astype(np.int32)
        # pad the sub-slab length to a shape class so jit compiles a
        # logarithmic set of slab shapes, not one per round
        na = _shape_class(len(ua) + 1)
        nb = _shape_class(len(ub) + 1)
        a_sub = np.zeros((na, k, k), np.uint64)
        a_sub[: len(ua)] = a.tiles[ua]
        b_sub = np.zeros((nb, k, k), np.uint64)
        b_sub[: len(ub)] = b.tiles[ub]
        ah, al = u64.u64_to_hilo(a_sub)
        bh, bl = u64.u64_to_hilo(b_sub)
        return ah, al, bh, bl, sub_pa, sub_pb

    def dispatch(rnd, prep):
        """Stage 2 (main thread): upload the prepped planes + one numeric
        launch.  Kernel choice stays on the main thread because the hybrid
        gate may run a one-time crossover measurement on the device."""
        ah, al, bh, bl, sub_pa, sub_pb = prep
        fn, used_mxu = (numeric, False) if choose_numeric is None \
            else choose_numeric(rnd)[:2]
        out = fn(jnp.asarray(ah), jnp.asarray(al),
                 jnp.asarray(bh), jnp.asarray(bl),
                 jnp.asarray(sub_pa), jnp.asarray(sub_pb))
        timers.incr("dispatches")
        return out, used_mxu

    out_tiles = np.zeros((join.num_keys, k, k), np.uint64)

    def land(oh, ol, key_index):
        """Fetch one round's outputs (blocks on that round only) and place
        them into the host result slab."""
        n = len(key_index)
        out_tiles[key_index] = u64.hilo_to_u64(np.asarray(oh[:n]),
                                               np.asarray(ol[:n]))

    # pipeline depth: how many un-landed rounds may be in flight.  Depth 1
    # is the synchronous minimal-HBM mode (land each round before staging
    # the next, zero overlap).  Depth >= 2 runs the full 3-stage pipeline:
    #
    #   staging worker (host gather/pack)  ->  main thread (upload +
    #   launch)  ->  landing worker (D2H fetch + host scatter)
    #
    # The landing worker blocks on each round's D2H fetch (np.asarray
    # releases the GIL during the device wait), so landing never absorbs
    # compute wait in the main loop -- the round-4 Large profile showed 86%
    # of wall in that blocking fetch (ROUND4_NOTES).  The staging worker
    # runs host_prep (np.unique/searchsorted/pack -- numpy releases the GIL
    # for the bulk of it) ahead of the main loop, so the next round's host
    # gather overlaps the current round's device execution instead of
    # sitting on the producer's critical path.  `slots` is the peak-HBM
    # bound: a round's output slot is taken before its sub-slabs are
    # UPLOADED and released only once it has LANDED, so at most `depth`
    # rounds' sub-slabs + outputs are alive on device; staged-but-not-
    # dispatched preps are host RAM, bounded by the stage queue's depth.
    # Landing order across rounds is irrelevant to bit-exactness: each
    # round writes a disjoint key_index slice of out_tiles, and the fold
    # order lives inside the kernels (test_outofcore pins depths 1/4
    # bit-identical).
    depth = knobs.get("SPGEMM_TPU_OOC_DEPTH")
    mxu_rounds = 0
    if depth == 1:
        for rnd in rounds:
            with timers.phase("numeric_dispatch"):
                (oh, ol), used_mxu = dispatch(rnd, host_prep(rnd))
                mxu_rounds += used_mxu
            with timers.phase("assembly"):
                land(oh, ol, rnd.key_index)
    else:
        import queue as queue_mod  # noqa: PLC0415
        import threading  # noqa: PLC0415

        landq: queue_mod.Queue = queue_mod.Queue()
        stageq: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
        stop = threading.Event()
        land_err: list = []
        prep_err: list = []
        slots = threading.Semaphore(depth)
        # the workers' stage_prep/assembly phases and spans belong to the
        # multiply that spawned them (per-job PhaseScope + trace tags)
        attr = timers.attribution()

        def _put(q, item):
            """Bounded put that can never deadlock a dying pipeline: bail
            out once the main thread has signalled shutdown."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue_mod.Full:
                    continue
            return False

        def _stager():
            try:
                with timers.attributed(attr):
                    for rnd in rounds:
                        if stop.is_set() or land_err:
                            return
                        with timers.phase("stage_prep"):
                            prep = host_prep(rnd)
                        if not _put(stageq, (rnd, prep)):
                            return
            except Exception as e:  # noqa: BLE001 -- re-raised below
                prep_err.append(e)
            finally:
                _put(stageq, None)

        def _lander():
            with timers.attributed(attr):
                while True:
                    item = landq.get()
                    if item is None:
                        return
                    if not land_err:  # keep draining after a failure so the
                        try:          # producer can never deadlock
                            with timers.phase("assembly"):
                                land(*item)
                        except Exception as e:  # noqa: BLE001 -- re-raised below
                            land_err.append(e)
                    slots.release()

        lander = threading.Thread(target=_lander, name="ooc-landing",
                                  daemon=True)
        stager = threading.Thread(target=_stager, name="ooc-staging",
                                  daemon=True)
        lander.start()
        stager.start()
        try:
            while True:
                item = stageq.get()
                if item is None or land_err:
                    break
                rnd, prep = item
                slots.acquire()
                with timers.phase("numeric_dispatch"):
                    (oh, ol), used_mxu = dispatch(rnd, prep)
                    mxu_rounds += used_mxu
                landq.put((oh, ol, rnd.key_index))
        finally:
            # always shut both workers down, also when dispatch raises --
            # a leaked worker would pin out_tiles for process lifetime
            stop.set()
            landq.put(None)
            lander.join()
            stager.join()
        if prep_err:
            raise prep_err[0]
        if land_err:
            raise land_err[0]
    _observe_memory()

    total_pairs = int(join.pair_ptr[-1])
    tag = backend if choose_numeric is None \
        else f"hybrid mxu={mxu_rounds}/{len(rounds)}"
    log.info("spgemm[%s,out-of-core]: nnzb %d x %d -> keys=%d pairs=%d "
             "rounds=%d work=%.3f GFLOP", tag, a.nnzb, b.nnzb,
             join.num_keys, total_pairs, len(rounds),
             2.0 * total_pairs * k ** 3 / 1e9)
    return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k,
                             coords=join.keys, tiles=out_tiles)


def spgemm(a: BlockSparseMatrix, b: BlockSparseMatrix, *,
           round_size: int | None = None,
           backend: str | None = None) -> BlockSparseMatrix:
    """C = A x B with reference-exact semantics, host-to-host.  Result keeps
    all-zero output tiles (pruning happens only at final output,
    sparse_matrix_mult.cu:577-592) and carries rows=a.rows, cols=b.cols
    (:281-282).  One fused D2H at the end; use spgemm_device to chain
    multiplies without leaving HBM."""
    return spgemm_device(a, b, round_size=round_size, backend=backend).to_host()
