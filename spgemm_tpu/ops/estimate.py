"""Sampled structure estimator: first-contact planning without the full join.

PR 4's structure-keyed plan cache (ops/plancache) made repeated multiplies
~145x cheaper, but a *first-contact* structure still paid the full exact
symbolic join on the caller's critical path -- 16.6 ms per cold plan at 20k
keys, and far worse at webbase scale where first-touch planning dominates
job wall.  Ocean-style sampling (PAPERS.md) recovers near-exact SpGEMM
decisions from a bounded row sample at a fraction of that cost: this module
joins an evenly-spaced sample of A's distinct tile-rows against B's (sorted)
row index EXACTLY -- the sampled rows' output keys, fanouts, and pair masses
are true values, not sketches -- and scales them to the population.

What the estimate steers (ops/spgemm.plan):
  * the kernel-route partition point (whether the hybrid `_proof_fanout_cap`
    split is worth materializing -- guarded downstream by the per-round
    exactness proof, so an estimation error can never change bits);
  * whether the exact symbolic join runs INLINE (low confidence -- the
    `join_fallback` path) or DEFERRED off the critical path into the
    plan-ahead worker (SpgemmPlan.ensure_exact);
  * ring load balancing: `parallel/ring.plan_ring` assigns key slabs by
    cumulative pair mass -- the quantity `row_mass` predicts -- instead of
    raw key count.

What it can never steer: fold order.  Estimation picks budgets and routing
only; every kernel produces identical bits and each key's pair list keeps
the reference's j-ascending order (SURVEY.md section 2.9), so estimator
on/off is a bit-identical whole-engine A/B (pinned in tests/test_estimate).

Host-only and jax-free like the rest of the planner (safe on plan-ahead
worker threads -- the BKD contract), and in the numeric-lint FLD scope:
the integer sizing sums below are order-free by proof, anything else would
be a finding.

Knobs (central registry, utils/knobs.py):
  SPGEMM_TPU_PLAN_ESTIMATE  0|1 (default 1) -- estimator on/off.
  SPGEMM_TPU_EST_SAMPLE_ROWS int >= 1 (default 48) -- row sample budget;
    structures with this many distinct A tile-rows or fewer skip
    estimation (the sample would be the population -- exact is free).
  SPGEMM_TPU_EST_CONFIDENCE  float >= 0 (default 0.5) -- estimates whose
    confidence falls below this take the exact-join fallback inline.

Live stats (`stats()`) ride next to the plan-cache row in
`spgemm_tpu.cli knobs [--json]`; the engine mirrors hit/fallback events
into the ENGINE registry (`est_hits`/`est_fallbacks` counters) so they
flow into bench detail and the Prometheus surface per run.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from spgemm_tpu.utils import knobs

_LOCK = threading.Lock()
_STATS = {"hits": 0, "fallbacks": 0}  # spgemm-lint: guarded-by(_LOCK)


def enabled() -> bool:
    """SPGEMM_TPU_PLAN_ESTIMATE=0|1 (default 1)."""
    return knobs.get("SPGEMM_TPU_PLAN_ESTIMATE")


def sample_budget() -> int:
    """SPGEMM_TPU_EST_SAMPLE_ROWS (default 48): distinct A tile-rows
    sampled, evenly spaced over the sorted row set (deterministic -- the
    same structure always produces the same estimate)."""
    return knobs.get("SPGEMM_TPU_EST_SAMPLE_ROWS")


def confidence_threshold() -> float:
    """SPGEMM_TPU_EST_CONFIDENCE (default 0.5): below it, plan() takes the
    exact-join fallback inline; above 1 forces the fallback everywhere
    (a zero-variance sample earns exactly 1.0)."""
    return knobs.get("SPGEMM_TPU_EST_CONFIDENCE")


def note_hit() -> None:
    with _LOCK:
        _STATS["hits"] += 1


def note_fallback() -> None:
    with _LOCK:
        _STATS["fallbacks"] += 1


def stats() -> dict:
    """Live per-process estimator routing state, for `spgemm_tpu.cli
    knobs` next to the plan-cache row: estimator-routed plans vs inline
    exact-join fallbacks since process start, plus the knob values."""
    with _LOCK:
        return {
            "hits": _STATS["hits"],
            "fallbacks": _STATS["fallbacks"],
            "enabled": enabled(),
            "sample_rows": sample_budget(),
            "confidence_threshold": confidence_threshold(),
        }


def clear() -> None:
    """Zero the routing stats (tests, A/B harnesses)."""
    with _LOCK:
        _STATS["hits"] = _STATS["fallbacks"] = 0


def pair_mass(a_coords: np.ndarray, b_coords: np.ndarray) -> float:
    """Predicted tile-pair count (MAC mass / k^3) of one A x B multiply:
    the sampled estimate where the structure is big enough to sample, the
    EXACT searchsorted pair count otherwise (small structures join in
    microseconds -- exact is free).  The device-pool scheduler prices a
    job with this before routing it (serve/placement): pricing steers
    placement only, never fold order, so it stays correct -- and cheap --
    to call on structures the plan estimator would skip."""
    est = maybe_estimate(a_coords, b_coords)
    if est is not None:
        return float(est.est_pairs)
    if len(a_coords) == 0 or len(b_coords) == 0:
        return 0.0
    b_rows = b_coords[:, 0]
    lo = np.searchsorted(b_rows, a_coords[:, 1], side="left")
    hi = np.searchsorted(b_rows, a_coords[:, 1], side="right")
    cnt = hi - lo
    # spgemm-lint: fld-proof(integer pair-count total for placement pricing only; exact int64 addition is order-free, no wrap-then-mod values involved)
    return float(cnt.sum())


def chain_mass(coords_list: list[np.ndarray]) -> float:
    """Predicted tile-pair mass of one chain job's FIRST reduction pass
    (helper2 pairing: (0,1), (2,3), ...; the odd trailing operand carries
    for free).  The first pass is where a chain's MAC mass concentrates --
    later passes fold at most half as many operands -- so this is the
    scheduler's per-job price signal, not a wall-time model."""
    total = 0.0
    for i in range(0, len(coords_list) - 1, 2):
        total += pair_mass(coords_list[i], coords_list[i + 1])
    return total


def predicted_route(est: "StructureEstimate | None") -> str | None:
    """Accumulator route the fanout histogram predicts for an estimated
    plan ("dense" when any sampled shape class reaches the dense-eligible
    floor, else "ladder"), or None when there is no estimate to read.

    ADVISORY ONLY: plan_rounds re-proves the decision against the real
    per-class fanouts once the exact join lands, so a misprediction can
    never change routing semantics -- it only shows up as drift telemetry
    (the `accum_route_mismatch` event in ops/spgemm._plan_host)."""
    if est is None:
        return None
    from spgemm_tpu.ops.symbolic import DENSE_MIN_CLASS  # noqa: PLC0415

    if any(cls >= DENSE_MIN_CLASS for cls in est.class_hist):
        return "dense"
    return "ladder"


@dataclass
class StructureEstimate:
    """Scaled prediction of one A x B output structure from a row sample.

    The sampled rows' figures are EXACT (a real mini-join ran over them);
    population figures are the sampled totals scaled by
    total_rows / sampled_rows.  `confidence` is 1 minus the relative
    standard error of the sampled per-row pair mass -- near 1 on uniform
    structures (banded chains), collapsing toward 0 under power-law skew,
    which is exactly when scaled totals stop being trustworthy and the
    exact join should run inline instead.
    """

    total_rows: int            # distinct A tile-rows in the population
    sampled_rows: int
    scale: float               # total_rows / sampled_rows
    est_keys: float            # predicted output-key count
    est_pairs: float           # predicted total tile pairs (MAC mass)
    est_max_fanout: int        # max per-key fanout SEEN in the sample
    class_hist: dict = field(default_factory=dict)  # shape class -> est keys
    row_mass: np.ndarray | None = None  # per-sampled-row pair counts
    skew: float = 0.0          # coefficient of variation of row_mass
    confidence: float = 0.0


def maybe_estimate(a_coords: np.ndarray, b_coords: np.ndarray,
                   sample_rows: int | None = None) -> StructureEstimate | None:
    """Estimate the A x B output structure from a bounded row sample, or
    None when estimation does not apply: an empty operand (the exact join
    is O(1) there), or a population no bigger than the sample budget (the
    sample would be the population -- run the exact join, it costs the
    same and is exact).

    Both coord arrays must be lexicographically sorted by (row, col) --
    the BlockSparseMatrix invariant the exact join also relies on.
    Deterministic: evenly spaced sample positions, no RNG.
    """
    from spgemm_tpu.ops.symbolic import (_segment_expand,  # noqa: PLC0415
                                         _shape_class_vec)

    if sample_rows is None:
        sample_rows = sample_budget()
    if len(a_coords) == 0 or len(b_coords) == 0:
        return None
    a_rows = a_coords[:, 0]
    row_vals, row_starts = np.unique(a_rows, return_index=True)
    n_rows = len(row_vals)
    if n_rows <= sample_rows:
        return None
    row_ends = np.append(row_starts[1:], len(a_rows))

    # evenly spaced distinct sample over the sorted row set
    take = np.unique(np.linspace(0, n_rows - 1, num=sample_rows)
                     .astype(np.int64))
    n_take = len(take)
    lens = row_ends[take] - row_starts[take]
    blk_seg, blk_off = _segment_expand(lens)  # sample-local row per block
    blk_idx = np.repeat(row_starts[take], lens) + blk_off

    # exact mini-join of the sampled rows against B's sorted row index
    cols = a_coords[blk_idx, 1]
    b_rows = b_coords[:, 0]
    b_cols = b_coords[:, 1]
    lo = np.searchsorted(b_rows, cols, side="left")
    hi = np.searchsorted(b_rows, cols, side="right")
    cnt = hi - lo
    # spgemm-lint: fld-proof(integer pair-count total for sizing only; exact int64 addition is order-free, no wrap-then-mod values involved)
    total_pairs = int(cnt.sum())
    row_mass = np.bincount(blk_seg, weights=cnt,
                           minlength=n_take).astype(np.int64)
    scale = n_rows / n_take

    if total_pairs == 0:
        # sampled rows produce nothing: predict an empty-ish output with
        # full-sample confidence semantics (uniformly zero mass has zero
        # variance, so the formula below would also say 1.0)
        return StructureEstimate(
            total_rows=n_rows, sampled_rows=n_take, scale=scale,
            est_keys=0.0, est_pairs=0.0, est_max_fanout=0,
            class_hist={}, row_mass=row_mass, skew=0.0, confidence=1.0)

    # output keys + per-key fanout for the sampled rows, exactly
    pair_seg, pair_off = _segment_expand(cnt)
    b_slot = np.repeat(lo, cnt) + pair_off
    out_r = blk_seg[pair_seg].astype(np.uint64)      # sample-local row id
    out_c = b_cols[b_slot].astype(np.uint64)
    span = np.uint64(int(b_cols.max()) + 1)
    fused = out_r * span + out_c                     # < n_take * span, safe
    uniq, fan = np.unique(fused, return_counts=True)
    keys_per_row = np.bincount((uniq // span).astype(np.int64),
                               minlength=n_take)

    classes, cls_counts = np.unique(_shape_class_vec(fan),
                                    return_counts=True)
    class_hist = {int(c): float(n * scale)
                  for c, n in zip(classes, cls_counts)}

    mean = float(row_mass.mean())
    std = float(row_mass.std())
    skew = std / mean if mean > 0 else 0.0
    # relative standard error of the scaled total: sigma / (mu * sqrt(n))
    rse = skew / float(np.sqrt(n_take))
    return StructureEstimate(
        total_rows=n_rows, sampled_rows=n_take, scale=scale,
        # spgemm-lint: fld-proof(integer key/pair totals for prediction scaling only; exact int64 addition is order-free, no wrap-then-mod values involved)
        est_keys=float(keys_per_row.sum()) * scale,
        est_pairs=float(total_pairs) * scale,
        est_max_fanout=int(fan.max()),
        class_hist=class_hist, row_mass=row_mass, skew=skew,
        confidence=max(0.0, 1.0 - rse))
